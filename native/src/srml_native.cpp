//
// Native compute kernels for spark_rapids_ml_tpu — the in-tree C++ equivalent
// of the reference's JNI CUDA library (reference jvm/native/src/
// rapidsml_jni.cu:35-269: dgemmCov covariance gemm, calSVD = eigDC + reverse +
// signFlip). CPU/C++ here (the TPU compute path is JAX/XLA; this component
// exists for the reference's native-stack parity: host-side covariance
// accumulation, a dependency-free symmetric eigensolver, and eigenvector sign
// canonicalization), surfaced to Python over a plain C ABI via ctypes.
//
// Exported C ABI:
//   srml_cov_accumulate : C += X^T X  (blocked, cache-friendly)
//   srml_weighted_mean  : m = sum_i w_i x_i / sum_i w_i
//   srml_eigh_jacobi    : cyclic Jacobi symmetric eigendecomposition
//                         (ascending eigenvalues, column eigenvectors)
//   srml_signflip       : per-row max-|.| element made positive
//                         (rapidsml_jni.cu:35-61 semantics)
//
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// C += X^T X for row-major X [n, d]; C row-major [d, d].
// Blocked over rows for cache locality; mirrors dgemmCov accumulation
// (rapidsml_jni.cu:109-127).
void srml_cov_accumulate(const double* x, int64_t n, int64_t d, double* c) {
  const int64_t RB = 256;  // row block
  for (int64_t r0 = 0; r0 < n; r0 += RB) {
    const int64_t r1 = (r0 + RB < n) ? r0 + RB : n;
    for (int64_t i = 0; i < d; ++i) {
      const double* xi = x + i;
      for (int64_t j = i; j < d; ++j) {
        const double* xj = x + j;
        double acc = 0.0;
        for (int64_t r = r0; r < r1; ++r) {
          acc += xi[r * d] * xj[r * d];
        }
        c[i * d + j] += acc;
      }
    }
  }
  // mirror the upper triangle
  for (int64_t i = 0; i < d; ++i)
    for (int64_t j = 0; j < i; ++j) c[i * d + j] = c[j * d + i];
}

void srml_weighted_mean(const double* x, const double* w, int64_t n, int64_t d,
                        double* mean) {
  std::vector<double> acc(d, 0.0);
  double sw = 0.0;
  for (int64_t r = 0; r < n; ++r) {
    const double wr = w ? w[r] : 1.0;
    sw += wr;
    const double* row = x + r * d;
    for (int64_t j = 0; j < d; ++j) acc[j] += wr * row[j];
  }
  const double inv = sw > 0 ? 1.0 / sw : 0.0;
  for (int64_t j = 0; j < d; ++j) mean[j] = acc[j] * inv;
}

// Cyclic Jacobi eigensolver for a symmetric row-major A [d, d].
// Outputs: eigenvalues ascending in `evals` [d]; eigenvectors as COLUMNS of
// row-major `evecs` [d, d] (evecs[:, k] pairs with evals[k]).
// Returns the number of sweeps used, or -1 if not converged.
int srml_eigh_jacobi(const double* a_in, int64_t d, double* evals,
                     double* evecs, int max_sweeps, double tol) {
  std::vector<double> A(a_in, a_in + d * d);
  // V = I
  for (int64_t i = 0; i < d; ++i)
    for (int64_t j = 0; j < d; ++j) evecs[i * d + j] = (i == j) ? 1.0 : 0.0;

  auto off = [&]() {
    double s = 0.0;
    for (int64_t i = 0; i < d; ++i)
      for (int64_t j = i + 1; j < d; ++j) s += A[i * d + j] * A[i * d + j];
    return std::sqrt(2.0 * s);
  };

  int sweep = 0;
  const double scale = off();
  const double stop = tol * (scale > 0 ? scale : 1.0);
  for (; sweep < max_sweeps; ++sweep) {
    if (off() <= stop) break;
    for (int64_t p = 0; p < d - 1; ++p) {
      for (int64_t q = p + 1; q < d; ++q) {
        const double apq = A[p * d + q];
        if (std::fabs(apq) < 1e-300) continue;
        const double app = A[p * d + p], aqq = A[q * d + q];
        const double theta = (aqq - app) / (2.0 * apq);
        const double t = (theta >= 0 ? 1.0 : -1.0) /
                         (std::fabs(theta) + std::sqrt(theta * theta + 1.0));
        const double c = 1.0 / std::sqrt(t * t + 1.0);
        const double s = t * c;
        for (int64_t k = 0; k < d; ++k) {
          const double akp = A[k * d + p], akq = A[k * d + q];
          A[k * d + p] = c * akp - s * akq;
          A[k * d + q] = s * akp + c * akq;
        }
        for (int64_t k = 0; k < d; ++k) {
          const double apk = A[p * d + k], aqk = A[q * d + k];
          A[p * d + k] = c * apk - s * aqk;
          A[q * d + k] = s * apk + c * aqk;
        }
        for (int64_t k = 0; k < d; ++k) {
          const double vkp = evecs[k * d + p], vkq = evecs[k * d + q];
          evecs[k * d + p] = c * vkp - s * vkq;
          evecs[k * d + q] = s * vkp + c * vkq;
        }
      }
    }
  }
  const bool converged = off() <= stop;
  // extract + sort ascending (insertion order map)
  std::vector<int64_t> order(d);
  for (int64_t i = 0; i < d; ++i) order[i] = i;
  std::vector<double> diag(d);
  for (int64_t i = 0; i < d; ++i) diag[i] = A[i * d + i];
  for (int64_t i = 1; i < d; ++i) {  // insertion sort: d is small here
    int64_t oi = order[i];
    double key = diag[oi];
    int64_t j = i - 1;
    while (j >= 0 && diag[order[j]] > key) {
      order[j + 1] = order[j];
      --j;
    }
    order[j + 1] = oi;
  }
  std::vector<double> vtmp(d * d);
  for (int64_t kcol = 0; kcol < d; ++kcol) {
    evals[kcol] = diag[order[kcol]];
    for (int64_t i = 0; i < d; ++i) vtmp[i * d + kcol] = evecs[i * d + order[kcol]];
  }
  std::memcpy(evecs, vtmp.data(), sizeof(double) * d * d);
  return converged ? sweep : -1;
}

// For each ROW of row-major comps [k, d]: if the max-|.| element is negative,
// negate the whole row (rapidsml_jni.cu:35-61 signFlip semantics — makes
// eigenvector signs deterministic).
void srml_signflip(double* comps, int64_t k, int64_t d) {
  for (int64_t r = 0; r < k; ++r) {
    double* row = comps + r * d;
    double best = 0.0;
    double val = 0.0;
    for (int64_t j = 0; j < d; ++j) {
      const double a = std::fabs(row[j]);
      if (a > best) {
        best = a;
        val = row[j];
      }
    }
    if (val < 0.0)
      for (int64_t j = 0; j < d; ++j) row[j] = -row[j];
  }
}

}  // extern "C"
