//
// JNI bridge over the srml_native C kernels — the counterpart of the
// reference's JNI surface (reference jvm/src/main/java/.../JniRAPIDSML.java:
// 64-77 declares native dgemm/calSVD entry points implemented by
// rapidsml_jni.cu). Here the same pattern binds the in-tree C++ kernels
// (srml_native.cpp) to the Scala/Java API in /jvm.
//
// Build: only compiled when CMake finds a JNI installation (see
// native/CMakeLists.txt) — the CI image ships no JVM, so this file is
// exercised by the Maven build documented in jvm/README.md.
//
#include <jni.h>

#include <cstdint>
#include <vector>

extern "C" {
void srml_cov_accumulate(const double* x, int64_t n, int64_t d, double* c);
void srml_weighted_mean(const double* x, const double* w, int64_t n, int64_t d,
                        double* mean);
int srml_eigh_jacobi(const double* a_in, int64_t d, double* evals,
                     double* evecs, int max_sweeps, double tol);
void srml_signflip(double* comps, int64_t k, int64_t d);
}

extern "C" {

// class com.srmltpu.linalg.SrmlNative — names must match the Java decls.

JNIEXPORT void JNICALL Java_com_srmltpu_linalg_SrmlNative_covAccumulate(
    JNIEnv* env, jclass, jdoubleArray jx, jlong n, jlong d, jdoubleArray jc) {
  // Called once per multi-row BLOCK (TpuPCA.scala buffers ~1400 rows per
  // call), so the array copies here are ~2% of the block's gram compute.
  // Deliberately NOT GetPrimitiveArrayCritical: the block update runs
  // seconds of native code at d=3000, and a critical region that long pins
  // GC for every other task thread in a shared Spark executor JVM.
  jdouble* x = env->GetDoubleArrayElements(jx, nullptr);
  jdouble* c = env->GetDoubleArrayElements(jc, nullptr);
  srml_cov_accumulate(x, n, d, c);
  env->ReleaseDoubleArrayElements(jx, x, JNI_ABORT);  // input: no copy-back
  env->ReleaseDoubleArrayElements(jc, c, 0);
}

JNIEXPORT void JNICALL Java_com_srmltpu_linalg_SrmlNative_weightedMean(
    JNIEnv* env, jclass, jdoubleArray jx, jdoubleArray jw, jlong n, jlong d,
    jdoubleArray jmean) {
  jdouble* x = env->GetDoubleArrayElements(jx, nullptr);
  jdouble* w = jw ? env->GetDoubleArrayElements(jw, nullptr) : nullptr;
  jdouble* m = env->GetDoubleArrayElements(jmean, nullptr);
  srml_weighted_mean(x, w, n, d, m);
  env->ReleaseDoubleArrayElements(jx, x, JNI_ABORT);
  if (jw) env->ReleaseDoubleArrayElements(jw, w, JNI_ABORT);
  env->ReleaseDoubleArrayElements(jmean, m, 0);
}

JNIEXPORT jint JNICALL Java_com_srmltpu_linalg_SrmlNative_eighJacobi(
    JNIEnv* env, jclass, jdoubleArray ja, jlong d, jdoubleArray jevals,
    jdoubleArray jevecs, jint maxSweeps, jdouble tol) {
  jdouble* a = env->GetDoubleArrayElements(ja, nullptr);
  jdouble* evals = env->GetDoubleArrayElements(jevals, nullptr);
  jdouble* evecs = env->GetDoubleArrayElements(jevecs, nullptr);
  const int sweeps = srml_eigh_jacobi(a, d, evals, evecs, maxSweeps, tol);
  env->ReleaseDoubleArrayElements(ja, a, JNI_ABORT);
  env->ReleaseDoubleArrayElements(jevals, evals, 0);
  env->ReleaseDoubleArrayElements(jevecs, evecs, 0);
  return sweeps;
}

JNIEXPORT void JNICALL Java_com_srmltpu_linalg_SrmlNative_signFlip(
    JNIEnv* env, jclass, jdoubleArray jcomps, jlong k, jlong d) {
  jdouble* comps = env->GetDoubleArrayElements(jcomps, nullptr);
  srml_signflip(comps, k, d);
  env->ReleaseDoubleArrayElements(jcomps, comps, 0);
}

}  // extern "C"
