# Empty dependencies file for srml_native.
# This may be replaced when dependencies are built.
