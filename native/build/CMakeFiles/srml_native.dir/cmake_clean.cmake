file(REMOVE_RECURSE
  "CMakeFiles/srml_native.dir/src/srml_native.cpp.o"
  "CMakeFiles/srml_native.dir/src/srml_native.cpp.o.d"
  "libsrml_native.pdb"
  "libsrml_native.so"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/srml_native.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
