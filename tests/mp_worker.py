#
# Worker script for the multi-process SPMD test (launched as a subprocess by
# tests/test_multiprocess.py; the `mp_` prefix keeps pytest from collecting it).
#
# Each process holds a RAGGED local row block and fits PCA + LinearRegression +
# LogisticRegression cooperatively through TpuContext(require_distributed=True)
# — the analog of the reference's one-Spark-task-per-GPU barrier fit
# (reference core.py:698-791). Results must match a single-process fit on the
# concatenated data (asserted by the parent test).
#
import os
import sys


def main() -> None:
    rank = int(sys.argv[1])
    nranks = int(sys.argv[2])
    rdv_dir = sys.argv[3]
    out_dir = sys.argv[4]
    run_id = sys.argv[5] if len(sys.argv) > 5 else None

    import numpy as np
    import pandas as pd

    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.models.knn import NearestNeighbors
    from spark_rapids_ml_tpu.models.regression import LinearRegression, RandomForestRegressor
    from spark_rapids_ml_tpu.parallel import FileRendezvous, TpuContext

    X, y_log, y_lin = make_dataset()
    bounds = split_bounds(len(X), nranks)
    lo, hi = bounds[rank], bounds[rank + 1]
    df = pd.DataFrame(
        {"features": list(X[lo:hi]), "label": y_log[lo:hi], "target": y_lin[lo:hi],
         "id": np.arange(lo, hi, dtype=np.int64)}
    )

    rdv = FileRendezvous(rank, nranks, rdv_dir, timeout_s=120.0, run_id=run_id)
    with TpuContext(rank, nranks, rdv, require_distributed=True):
        pca = PCA(k=3, inputCol="features", float32_inputs=False).fit(df)
        lin = (
            LinearRegression(regParam=0.0, float32_inputs=False, labelCol="target")
            .setFeaturesCol("features")
            .fit(df)
        )
        lr = (
            LogisticRegression(maxIter=100, regParam=0.1, tol=1e-10, float32_inputs=False)
            .setFeaturesCol("features")
            .fit(df)
        )
        km = (
            KMeans(k=4, maxIter=15, seed=3, float32_inputs=False)
            .setFeaturesCol("features")
            .fit(df)
        )
        rf = (
            RandomForestRegressor(
                numTrees=8, maxDepth=4, seed=1, labelCol="target", float32_inputs=False
            )
            .setFeaturesCol("features")
            .fit(df)
        )
        rf_pred = rf.transform(df)["prediction"].to_numpy()
        # kNN: items AND queries are rank-local; ids are global user ids
        gnn = (
            NearestNeighbors(k=3, float32_inputs=False)
            .setInputCol("features")
            .setIdCol("id")
            .fit(df)
        )
        query_df = df.iloc[:5]
        _, _, knn_df = gnn.kneighbors(query_df)
        # sparse kNN SPMD: same rows as CSR — local exact search + merged
        # top-k must equal the dense global result
        import scipy.sparse as sp

        from spark_rapids_ml_tpu.linalg import Vectors

        xs = sp.csr_matrix(X[lo:hi])
        df_sp = df.copy()
        df_sp["sfeat"] = [
            Vectors.sparse(X.shape[1], xs[i].indices.tolist(), xs[i].data.tolist())
            for i in range(hi - lo)
        ]
        gnn_sp = (
            NearestNeighbors(k=3, float32_inputs=False)
            .setInputCol("sfeat")
            .setIdCol("id")
            .fit(df_sp)
        )
        _, _, knn_sp_df = gnn_sp.kneighbors(df_sp.iloc[:5])
        # DBSCAN: replicated-data SPMD — every rank gathers the full set and
        # the N² passes run cooperatively over the global mesh
        from spark_rapids_ml_tpu.models.clustering import DBSCAN

        db_model = DBSCAN(eps=1.5, min_samples=3).setFeaturesCol("features").fit(df)
        db_labels = db_model.transform(df)["prediction"].to_numpy()
        # UMAP: gathered-data deterministic per-rank fit on local devices
        from spark_rapids_ml_tpu.models.umap import UMAP

        um = (
            UMAP(n_components=2, n_neighbors=5.0, n_epochs=30, random_state=3, init="random")
            .setFeaturesCol("features")
            .fit(df)
        )
        um_emb = np.asarray(um.embedding_)
        # ANN: per-rank local index, broadcast queries, global top-k merge;
        # nprobe == nlist makes each local search exhaustive, so the merged
        # result is exact
        from spark_rapids_ml_tpu.models.knn import ApproximateNearestNeighbors

        ann = (
            ApproximateNearestNeighbors(
                k=3, algorithm="ivfflat", algoParams={"nlist": 4, "nprobe": 4}
            )
            .setInputCol("features")
            .setIdCol("id")
            .fit(df)
        )
        _, _, ann_df = ann.kneighbors(query_df)
    np.savez(
        os.path.join(out_dir, f"rank{rank}.npz"),
        pca_components=pca.components_,
        pca_mean=pca.mean_,
        pca_var_ratio=pca.explained_variance_ratio_,
        lin_coef=lin.coef_,
        lin_intercept=np.asarray(lin.intercept_),
        lr_coef=lr.coef_,
        lr_intercept=lr.intercept_,
        lr_classes=lr.classes_,
        km_centers=km.cluster_centers_,
        km_inertia=np.asarray(km.inertia_),
        rf_pred=rf_pred,
        rf_target=y_lin[lo:hi],
        knn_query_ids=knn_df["query_id"].to_numpy(),
        knn_indices=np.stack(knn_df["indices"].to_numpy()),
        knn_distances=np.stack(knn_df["distances"].to_numpy()),
        knn_sp_indices=np.stack(knn_sp_df["indices"].to_numpy()),
        knn_sp_distances=np.stack(knn_sp_df["distances"].to_numpy()),
        db_labels=db_labels,
        um_emb=um_emb,
        ann_indices=np.stack(ann_df["indices"].to_numpy()),
        ann_distances=np.stack(ann_df["distances"].to_numpy()),
    )


def make_dataset():
    """Deterministic dataset; rows SORTED by label so later ranks see only one
    class — exercising the rendezvous class-set merge."""
    import numpy as np

    rng = np.random.default_rng(7)
    n, d = 120, 6
    X = rng.normal(size=(n, d))
    coef = rng.normal(size=d)
    y_lin = X @ coef + 0.5
    y_log = (X @ coef + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    order = np.argsort(y_log, kind="stable")
    return X[order], y_log[order], y_lin[order]


def split_bounds(n, nranks):
    """Deliberately ragged split: rank 0 gets ~60% of the rows."""
    bounds = [0]
    big = int(n * 0.6)
    rest = n - big
    per = rest // max(1, nranks - 1) if nranks > 1 else 0
    bounds.append(big if nranks > 1 else n)
    for r in range(1, nranks):
        bounds.append(bounds[-1] + (per if r < nranks - 1 else n - bounds[-1]))
    return bounds


if __name__ == "__main__":
    main()
