#
# Metrics / evaluators / CrossValidator tests (reference tests/test_tuning.py +
# metrics assertions inside test_logistic_regression.py pattern).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.metrics import MulticlassMetrics, RegressionMetrics, _SummarizerBuffer
from spark_rapids_ml_tpu.models.regression import LinearRegression
from spark_rapids_ml_tpu.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)


def test_regression_metrics_vs_sklearn(rng):
    from sklearn.metrics import mean_absolute_error, mean_squared_error, r2_score

    y = rng.normal(size=200)
    p = y + 0.3 * rng.normal(size=200)
    m = RegressionMetrics.from_values(y, p)
    np.testing.assert_allclose(m.mean_squared_error(), mean_squared_error(y, p), rtol=1e-10)
    np.testing.assert_allclose(m.mean_absolute_error(), mean_absolute_error(y, p), rtol=1e-10)
    np.testing.assert_allclose(m.r2(), r2_score(y, p), rtol=1e-10)


def test_summarizer_buffer_merge_equals_whole(rng):
    y = rng.normal(size=300)
    p = y + 0.1 * rng.normal(size=300)
    whole = RegressionMetrics.from_values(y, p)
    parts = [
        RegressionMetrics.from_values(y[i : i + 100], p[i : i + 100]) for i in (0, 100, 200)
    ]
    merged = RegressionMetrics.merge_all(parts)
    np.testing.assert_allclose(merged.mean_squared_error(), whole.mean_squared_error(), rtol=1e-12)
    np.testing.assert_allclose(merged.r2(), whole.r2(), rtol=1e-12)
    np.testing.assert_allclose(merged.mean_absolute_error(), whole.mean_absolute_error(), rtol=1e-12)


def test_multiclass_metrics_vs_sklearn(rng):
    from sklearn.metrics import accuracy_score, f1_score, precision_score, recall_score

    y = rng.integers(0, 3, size=500).astype(float)
    p = np.where(rng.uniform(size=500) < 0.8, y, rng.integers(0, 3, size=500)).astype(float)
    confusion = {}
    for a, b in zip(y, p):
        confusion[(a, b)] = confusion.get((a, b), 0.0) + 1.0
    m = MulticlassMetrics.from_confusion(confusion)
    ev = MulticlassClassificationEvaluator(metricName="accuracy")
    np.testing.assert_allclose(m.evaluate(ev), accuracy_score(y, p), rtol=1e-12)
    ev.setMetricName("f1")
    np.testing.assert_allclose(m.evaluate(ev), f1_score(y, p, average="weighted"), rtol=1e-10)
    ev.setMetricName("weightedPrecision")
    np.testing.assert_allclose(m.evaluate(ev), precision_score(y, p, average="weighted"), rtol=1e-10)
    ev.setMetricName("weightedRecall")
    np.testing.assert_allclose(m.evaluate(ev), recall_score(y, p, average="weighted"), rtol=1e-10)


def test_binary_evaluator_auc_vs_sklearn(rng):
    from sklearn.metrics import average_precision_score, roc_auc_score

    y = rng.integers(0, 2, size=400).astype(float)
    score = y + rng.normal(scale=0.8, size=400)
    df = pd.DataFrame({"label": y, "rawPrediction": score})
    ev = BinaryClassificationEvaluator()
    np.testing.assert_allclose(ev.evaluate(df), roc_auc_score(y, score), atol=1e-9)
    ev.setMetricName("areaUnderPR")
    np.testing.assert_allclose(ev.evaluate(df), average_precision_score(y, score), atol=5e-3)


def test_param_grid_builder():
    lr = LinearRegression()
    grid = (
        ParamGridBuilder()
        .addGrid(lr.getParam("regParam"), [0.0, 0.1])
        .addGrid(lr.getParam("elasticNetParam"), [0.0, 0.5, 1.0])
        .build()
    )
    assert len(grid) == 6
    assert all(len(g) == 2 for g in grid)


def _cv_data(rng, n=400, d=5):
    x = rng.normal(size=(n, d))
    coef = np.array([1.0, -2.0, 0.0, 0.0, 3.0])
    y = x @ coef + 0.5 + 0.2 * rng.normal(size=n)
    return pd.DataFrame({"features": list(x), "label": y})


def test_cross_validator_fused_path(rng):
    df = _cv_data(rng)
    lr = LinearRegression(standardization=False, float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.5, 10.0]).build()
    ev = RegressionEvaluator(metricName="rmse")
    assert lr._supportsTransformEvaluate(ev)
    cv = CrossValidator(estimator=lr, estimatorParamMaps=grid, evaluator=ev, numFolds=3, seed=42)
    cv_model = cv.fit(df)
    assert isinstance(cv_model, CrossValidatorModel)
    assert len(cv_model.avgMetrics) == 3
    # tiny regularization best for well-conditioned data
    assert int(np.argmin(cv_model.avgMetrics)) == 0
    out = cv_model.transform(df)
    assert "prediction" in out.columns


def test_cross_validator_matches_manual_scores(rng):
    # fused path must equal the naive per-model loop
    df = _cv_data(rng, n=200)
    lr = LinearRegression(standardization=False, float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 1.0]).build()
    ev = RegressionEvaluator(metricName="r2")
    cv = CrossValidator(estimator=lr, estimatorParamMaps=grid, evaluator=ev, numFolds=2, seed=7)
    fused = cv.fit(df).avgMetrics

    # manual loop with identical folds
    folds = cv._kfold_indices(len(df), df)
    manual = np.zeros(2)
    for train_idx, valid_idx in folds:
        train, valid = df.iloc[train_idx], df.iloc[valid_idx]
        for j, pm in enumerate(grid):
            model = lr.copy(pm).fit(train)
            manual[j] += ev.evaluate(model.transform(valid))
    manual /= len(folds)
    np.testing.assert_allclose(fused, manual, rtol=1e-8)


def test_cross_validator_parallel(rng):
    df = _cv_data(rng, n=150)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.1]).build()
    ev = RegressionEvaluator()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid, evaluator=ev, numFolds=3, parallelism=3
    )
    assert len(cv.fit(df).avgMetrics) == 2


def test_cross_validator_fold_col(rng):
    df = _cv_data(rng, n=90)
    df["my_fold"] = np.arange(90) % 3
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid, evaluator=RegressionEvaluator(), numFolds=3,
        foldCol="my_fold",
    )
    assert len(cv.fit(df).avgMetrics) == 1


def test_binary_auc_ties_and_order_invariance(rng):
    # constant scores must give AUC 0.5 regardless of row order
    y = np.array([1.0, 1, 1, 0, 0, 0])
    df = pd.DataFrame({"label": y, "rawPrediction": np.zeros(6)})
    ev = BinaryClassificationEvaluator(numBins=0)
    np.testing.assert_allclose(ev.evaluate(df), 0.5, atol=1e-12)
    df2 = df.iloc[::-1].reset_index(drop=True)
    np.testing.assert_allclose(ev.evaluate(df2), 0.5, atol=1e-12)
    # tied groups vs sklearn
    from sklearn.metrics import roc_auc_score
    yy = rng.integers(0, 2, size=200).astype(float)
    ss = np.round(yy + rng.normal(scale=0.8, size=200), 1)  # heavy ties
    d3 = pd.DataFrame({"label": yy, "rawPrediction": ss})
    np.testing.assert_allclose(ev.evaluate(d3), roc_auc_score(yy, ss), atol=1e-10)


def test_cv_small_dataset_folds_nonempty(rng):
    df = _cv_data(rng, n=7)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0]).build()
    cv = CrossValidator(estimator=lr, estimatorParamMaps=grid, evaluator=RegressionEvaluator(), numFolds=3, seed=0)
    m = cv.fit(df)  # must not crash on any seed: folds are balanced
    assert np.isfinite(m.avgMetrics[0])


def test_cv_collect_sub_models(rng):
    df = _cv_data(rng, n=60)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.1]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid, evaluator=RegressionEvaluator(),
        numFolds=2, collectSubModels=True,
    )
    m = cv.fit(df)
    assert m.subModels is not None and len(m.subModels) == 2
    assert all(len(fold_models) == 2 for fold_models in m.subModels)


def test_train_validation_split_fused_and_fallback(rng):
    df = _cv_data(rng)
    lr = LinearRegression(standardization=False, float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.5, 10.0]).build()
    ev = RegressionEvaluator(metricName="rmse")
    tvs = TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid, evaluator=ev, trainRatio=0.75, seed=4
    )
    m = tvs.fit(df)
    assert isinstance(m, TrainValidationSplitModel)
    assert len(m.validationMetrics) == 3
    assert int(np.argmin(m.validationMetrics)) == 0  # tiny reg wins
    assert "prediction" in m.transform(df).columns

    # fused path must equal the manual per-model loop on the SAME split
    rng2 = np.random.default_rng(4)
    perm = rng2.permutation(len(df))
    n_train = int(round(0.75 * len(df)))
    train, valid = df.iloc[perm[:n_train]], df.iloc[perm[n_train:]]
    manual = [
        ev.evaluate(lr.copy(pm).fit(train).transform(valid)) for pm in grid
    ]
    np.testing.assert_allclose(m.validationMetrics, manual, rtol=1e-8)


def test_train_validation_split_persistence(rng, tmp_path):
    df = _cv_data(rng, n=150)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.1]).build()
    m = TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid, evaluator=RegressionEvaluator(),
        collectSubModels=True,
    ).fit(df)
    assert m.subModels is not None and len(m.subModels) == 2
    path = str(tmp_path / "tvs")
    m.save(path)
    loaded = TrainValidationSplitModel.load(path)
    np.testing.assert_allclose(loaded.validationMetrics, m.validationMetrics, rtol=1e-12)
    assert len(loaded.subModels) == 2
    np.testing.assert_allclose(
        loaded.transform(df)["prediction"].to_numpy(),
        m.transform(df)["prediction"].to_numpy(),
        rtol=1e-10,
    )

    with pytest.raises(ValueError, match="trainRatio"):
        TrainValidationSplit(
            estimator=lr, estimatorParamMaps=grid, evaluator=RegressionEvaluator(),
            trainRatio=1.5,
        ).fit(df)


def test_cv_model_persistence_roundtrip(rng, tmp_path):
    # reference parity: CV models save/load like every other model
    # (reference tuning.py:139-177 round-trips through pyspark writers)
    df = _cv_data(rng, n=80)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.1]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid, evaluator=RegressionEvaluator(),
        numFolds=2, collectSubModels=True, seed=3,
    )
    m = cv.fit(df)
    path = str(tmp_path / "cv_model")
    m.save(path)
    with pytest.raises(FileExistsError):
        m.save(path)
    m.write().overwrite().save(path)  # overwrite lane

    loaded = CrossValidatorModel.load(path)
    np.testing.assert_allclose(loaded.avgMetrics, m.avgMetrics, rtol=1e-12)
    np.testing.assert_allclose(loaded.stdMetrics, m.stdMetrics, rtol=1e-12)
    np.testing.assert_allclose(
        loaded.bestModel.coefficients, m.bestModel.coefficients, rtol=1e-12
    )
    assert loaded.subModels is not None and len(loaded.subModels) == 2
    assert all(len(fold_models) == 2 for fold_models in loaded.subModels)
    np.testing.assert_allclose(
        loaded.subModels[1][1].coefficients, m.subModels[1][1].coefficients, rtol=1e-12
    )
    # loaded best model transforms identically
    np.testing.assert_allclose(
        loaded.transform(df)["prediction"].to_numpy(),
        m.transform(df)["prediction"].to_numpy(),
        rtol=1e-10,
    )


def test_cv_model_persistence_no_submodels(rng, tmp_path):
    df = _cv_data(rng, n=60)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0]).build()
    m = CrossValidator(
        estimator=lr, estimatorParamMaps=grid, evaluator=RegressionEvaluator(), numFolds=2
    ).fit(df)
    assert m.subModels is None
    path = str(tmp_path / "cv2")
    m.save(path)
    loaded = CrossValidatorModel.load(path)
    assert loaded.subModels is None
    np.testing.assert_allclose(loaded.avgMetrics, m.avgMetrics, rtol=1e-12)


def test_fused_path_respects_evaluator_label_col(rng):
    df = _cv_data(rng, n=100).rename(columns={"label": "target"})
    lr = LinearRegression(float32_inputs=False, labelCol="target").setFeaturesCol("features")
    ev = RegressionEvaluator(metricName="rmse").setLabelCol("target")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0]).build()
    cv = CrossValidator(estimator=lr, estimatorParamMaps=grid, evaluator=ev, numFolds=2)
    assert np.isfinite(cv.fit(df).avgMetrics[0])


def test_weighted_evaluator_takes_fallback(rng):
    lr = LinearRegression()
    ev = RegressionEvaluator(metricName="rmse")
    assert lr._supportsTransformEvaluate(ev)
    ev2 = RegressionEvaluator(metricName="rmse", weightCol="w")
    assert not lr._supportsTransformEvaluate(ev2)


def test_logloss_non_contiguous_labels(rng):
    from sklearn.metrics import log_loss as sk_log_loss

    # labels {1., 3., 5.} with a 3-column probability vector ordered by sorted
    # class value — logLoss must index via the class ordering, not label value
    classes = np.array([1.0, 3.0, 5.0])
    y = classes[rng.integers(0, 3, size=120)]
    probs = rng.dirichlet(np.ones(3), size=120)
    pred = classes[np.argmax(probs, axis=1)]
    df = pd.DataFrame({"label": y, "prediction": pred, "probability": list(probs)})
    ev = MulticlassClassificationEvaluator(metricName="logLoss")
    np.testing.assert_allclose(
        ev.evaluate(df), sk_log_loss(y, probs, labels=classes), rtol=1e-10
    )


def test_logloss_contiguous_labels_vs_sklearn(rng):
    from sklearn.metrics import log_loss as sk_log_loss

    y = rng.integers(0, 3, size=150).astype(float)
    probs = rng.dirichlet(np.ones(3), size=150)
    pred = np.argmax(probs, axis=1).astype(float)
    df = pd.DataFrame({"label": y, "prediction": pred, "probability": list(probs)})
    ev = MulticlassClassificationEvaluator(metricName="logLoss")
    np.testing.assert_allclose(ev.evaluate(df), sk_log_loss(y, probs, labels=[0, 1, 2]), rtol=1e-10)


# ----------------------------------------------- SPMD sweep engine gating ---
#
# The multi-fit engine no longer falls back under multi-process SPMD
# (docs/performance.md): eligibility extends to SPMD-capable dense
# estimators, and held-out scoring allgathers every rank's validation slice
# so all ranks pick the same winner. Gating and gather are unit-tested here
# with stub contexts + thread ranks; tests/sweep_worker.py drives the real
# cross-process path where the backend supports it.


def test_engine_eligibility_under_spmd(monkeypatch):
    from types import SimpleNamespace

    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.parallel import TpuContext
    from spark_rapids_ml_tpu.tuning import _engine_eligible

    lr = LinearRegression()
    assert _engine_eligible(lr)  # single-controller: any _TpuEstimator
    assert not _engine_eligible(object())  # foreign estimators never engine

    spmd = SimpleNamespace(is_spmd=True)
    monkeypatch.setattr(TpuContext, "current", classmethod(lambda cls: spmd))
    assert _engine_eligible(lr)  # dense + SPMD-capable: engine runs
    sparse = LogisticRegression(enable_sparse_data_optim=True)
    assert not _engine_eligible(sparse)  # scoring gather is dense-only
    no_mp = LinearRegression()
    no_mp._supports_multiprocess = False
    assert not _engine_eligible(no_mp)  # estimator cannot fit under SPMD

    single = SimpleNamespace(is_spmd=False)
    monkeypatch.setattr(TpuContext, "current", classmethod(lambda cls: single))
    assert _engine_eligible(sparse)  # sparse is fine off SPMD


def test_gather_validation_concatenates_in_rank_order(monkeypatch):
    import threading
    from types import SimpleNamespace

    from spark_rapids_ml_tpu.parallel import LocalRendezvous, TpuContext
    from spark_rapids_ml_tpu.tuning import _gather_validation

    rvs = LocalRendezvous.create(2, timeout_s=20.0)
    by_thread = {}
    monkeypatch.setattr(
        TpuContext,
        "current",
        classmethod(lambda cls: by_thread.get(threading.get_ident())),
    )
    feats = [
        np.arange(6, dtype=np.float64).reshape(3, 2),
        10.0 + np.arange(4, dtype=np.float64).reshape(2, 2),
    ]
    labels = [np.array([0.0, 1.0, 2.0]), np.array([3.0, 4.0])]
    out = [None, None]
    errors = [None, None]

    def worker(r):
        try:
            by_thread[threading.get_ident()] = SimpleNamespace(
                is_spmd=True, rendezvous=rvs[r]
            )
            out[r] = _gather_validation(feats[r], labels[r])
        except BaseException as e:
            errors[r] = e

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads)
    assert errors == [None, None]
    want_f = np.concatenate(feats, axis=0)
    want_y = np.concatenate(labels, axis=0)
    for r in range(2):  # every rank scores the SAME globalized rows
        np.testing.assert_array_equal(out[r][0], want_f)
        np.testing.assert_array_equal(out[r][1], want_y)

    # identity off SPMD: no copy, no control-plane round
    f0, y0 = _gather_validation(feats[0], labels[0])
    assert f0 is feats[0] and y0 is labels[0]
