#
# Efficiency attribution plane tests (docs/observability.md "Efficiency
# plane"): the zero-cost disabled path (shared no-op identity + the <1%
# overhead micro-bench, mirroring PR 2's pin), the attribution acceptance
# (execute/compile/host/idle sum ≈ scope wall, ≥95% of fit wall attributed
# to named kinds — on a real CV sweep over the virtual 8-device mesh), the
# compile ledger (miss on first sighting, hit on the second, per-fit
# `_fit_metrics["compile"]` stamp), the peak-spec grammar and
# omitted-unless-configured MFU gauges, the per-tenant `device_time` merge
# into `HbmLedger.tenant_usage()` and the ops-plane report/exporters, the
# per-model serving tenant default, and exporter rendering of
# `efficiency.*`/`compile.*` under concurrent scrape. All without a TPU.
#
import threading
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import core, ops_plane, telemetry
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.regression import LinearRegression
from spark_rapids_ml_tpu.ops_plane import efficiency, export

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture
def tele():
    """Fresh enabled registry + fresh efficiency state; restore after."""
    telemetry.registry().reset()
    efficiency.reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.registry().reset()
    efficiency.reset()


@pytest.fixture
def peak_1g():
    saved = core.config.get("device_peak_flops")
    core.config["device_peak_flops"] = "1G"
    yield
    core.config["device_peak_flops"] = saved


def _binary_df(rng, n=256, d=6):
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return pd.DataFrame({"features": list(x), "label": y})


# ------------------------------------------------------------- peak spec ----


def test_parse_peak_spec_grammar():
    assert efficiency.parse_peak_spec("1G") == 1e9
    assert efficiency.parse_peak_spec("275T") == 275e12
    assert efficiency.parse_peak_spec("1.5k") == 1.5e3
    assert efficiency.parse_peak_spec("2.75e14") == 2.75e14
    assert efficiency.parse_peak_spec(9e12) == 9e12
    # unset/empty/garbage/non-positive = no peak — gauges omitted, never
    # guessed (the documented contract)
    for bad in (None, "", "   ", "fast", "-3T", 0, -1.0):
        assert efficiency.parse_peak_spec(bad) is None


# ------------------------------------------------------- zero-cost pins -----


def test_disabled_hooks_are_shared_noops():
    telemetry.disable()
    efficiency.reset()  # process-wide state — earlier test files attribute
    # identity, not just behavior: the disabled path allocates NOTHING per
    # call (the PR-2 `_NOOP_SPAN` contract extended to the new hooks)
    assert telemetry.device_wait("a") is telemetry._NOOP_SPAN
    assert telemetry.device_wait("b") is telemetry.host_section("c")
    assert telemetry.compile_event("p", "s") is telemetry._NOOP_COMPILE_EVENT
    assert telemetry.attribution("l") is telemetry._NOOP_SPAN
    assert telemetry.note_flops(1e9) is None
    # usable as context managers, recording nothing
    with telemetry.device_wait("x"), telemetry.compile_event("p", "s") as ce:
        assert ce.cache_hit is False
    assert efficiency.tenant_time_splits() == {}
    assert efficiency.compile_stats()["programs"] == 0


def test_disabled_overhead_micro_bench(rng):
    """The <1% pin: per-boundary hook cost on the disabled path, scaled to
    a generous per-fit boundary count, must stay under 1% of a real
    logistic fit's wall (mirrors PR 2's zero-cost acceptance)."""
    telemetry.disable()
    t0 = time.monotonic()
    LogisticRegression(maxIter=10).setFeaturesCol("features").fit(_binary_df(rng))
    fit_wall = time.monotonic() - t0

    n = 20_000
    t0 = time.monotonic()
    for _ in range(n):
        with telemetry.device_wait("s"):
            pass
        with telemetry.compile_event("p", "k"):
            pass
        telemetry.note_flops(1.0)
    hook_wall = time.monotonic() - t0
    # a fit crosses a few hundred instrumented boundaries at most; charge
    # 1000 of each hook against the measured fit wall
    per_fit_cost = hook_wall / n * 1000
    assert per_fit_cost < 0.01 * fit_wall, (
        f"disabled hook path costs {per_fit_cost:.6f}s per 1000 boundaries "
        f"vs fit wall {fit_wall:.3f}s"
    )


# ------------------------------------------------- attribution acceptance ---


def test_fit_stamp_attribution_sums_to_wall(tele, rng):
    model = (
        LogisticRegression(maxIter=10).setFeaturesCol("features").fit(_binary_df(rng))
    )
    eff = model._fit_metrics.get("efficiency")
    assert eff, "fit must stamp _fit_metrics['efficiency']"
    wall = eff["wall_s"]
    accounted = eff["execute_s"] + eff["compile_s"] + eff["host_s"] + eff["idle_s"]
    assert wall > 0
    # the acceptance: >=95% of fit wall attributed to named kinds (by
    # construction idle is the residual, so this is ~exact)
    assert accounted >= 0.95 * wall
    assert accounted <= wall * 1.001 + 1e-6
    # the compile stamp rides next to it
    assert model._fit_metrics["compile"]["misses"] >= 1
    # the registry saw the kind histograms
    snap = tele.snapshot()
    for name in (
        "efficiency.execute_s",
        "efficiency.compile_s",
        "efficiency.host_s",
        "efficiency.idle_s",
    ):
        assert name in snap["histograms"]


def test_cv_sweep_attribution_acceptance(tele, rng):
    """The ISSUE acceptance scenario: an instrumented CV sweep on the
    virtual 8-device mesh attributes >=95% of its wall to named kinds, and
    the nested fold fits fold into ONE outer scope (scopes never nest)."""
    from spark_rapids_ml_tpu.evaluation import RegressionEvaluator
    from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

    x = rng.normal(size=(300, 5))
    coef = np.array([1.0, -2.0, 0.0, 0.5, 3.0])
    y = x @ coef + 0.1 * rng.normal(size=300)
    df = pd.DataFrame({"features": list(x), "label": y})
    lr = LinearRegression(standardization=False, float32_inputs=False)
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 1.0]).build()
    ev = RegressionEvaluator(metricName="rmse")
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid, evaluator=ev, numFolds=2, seed=3
    )
    t0 = time.monotonic()
    cv.fit(df)
    sweep_wall = time.monotonic() - t0

    splits = efficiency.tenant_time_splits()
    assert splits, "the sweep must attribute under some tenant"
    total_wall = sum(s["wall_s"] for s in splits.values())
    total_accounted = sum(
        s["execute_s"] + s["compile_s"] + s["host_s"] + s["idle_s"]
        for s in splits.values()
    )
    assert total_accounted >= 0.95 * total_wall
    # scope walls never exceed the sweep's own wall: the inner fold fits
    # attributed into outer windows instead of stacking their own
    assert total_wall <= sweep_wall * 1.05 + 0.1
    # the report names a top idle stage per tenant once stages were seen
    rep = ops_plane.report()["efficiency"]
    assert set(rep["tenants"]) == set(splits)


# --------------------------------------------------------- compile ledger ---


def test_compile_ledger_miss_then_hit_across_identical_fits(tele, rng):
    df = _binary_df(rng)
    est = LogisticRegression(maxIter=5).setFeaturesCol("features")
    m1 = est.fit(df)
    stamp1 = m1._fit_metrics["compile"]
    assert stamp1["misses"] >= 1
    m2 = est.fit(df)
    stamp2 = m2._fit_metrics["compile"]
    # identical (program, shape-class): the second fit is all hits
    assert stamp2["misses"] == 0
    assert stamp2["hits"] >= 1
    stats = efficiency.compile_stats()
    assert stats["misses"] >= 1 and stats["hits"] >= 1
    assert stats["wall_s"] > 0
    assert any(e["program"].startswith("fit.") for e in stats["entries"])
    snap = tele.snapshot()
    assert snap["counters"]["compile.misses"] >= 1
    assert snap["counters"]["compile.hits"] >= 1
    assert "compile.wall_s" in snap["histograms"]


def test_compile_event_scope_less_and_shape_keyed(tele):
    # prewarm/autotune record with NO scope active — ledger is process-wide
    with telemetry.compile_event("prewarm.M", "128x4") as ce:
        assert ce.cache_hit is False
        time.sleep(0.01)
    with telemetry.compile_event("prewarm.M", "128x4") as ce:
        assert ce.cache_hit is True
    # a different shape class is its own entry (a new compile)
    with telemetry.compile_event("prewarm.M", "256x4") as ce:
        assert ce.cache_hit is False
    stats = efficiency.compile_stats()
    assert stats["programs"] == 2
    assert stats["misses"] == 2 and stats["hits"] == 1
    assert stats["wall_s"] >= 0.01


# ------------------------------------------------------------ MFU gauges ----


def test_mfu_gauge_present_only_with_peak_spec(tele, peak_1g, rng):
    model = (
        LogisticRegression(maxIter=5).setFeaturesCol("features").fit(_binary_df(rng))
    )
    eff = model._fit_metrics["efficiency"]
    assert "mfu" in eff and 0 < eff["mfu"] < 1
    assert tele.snapshot()["gauges"].get("efficiency.mfu") == pytest.approx(
        eff["mfu"]
    )


def test_mfu_gauge_omitted_without_peak_spec(tele, rng):
    saved = core.config.get("device_peak_flops")
    core.config["device_peak_flops"] = None
    try:
        model = (
            LogisticRegression(maxIter=5)
            .setFeaturesCol("features")
            .fit(_binary_df(rng))
        )
        assert "mfu" not in model._fit_metrics["efficiency"]
        assert "efficiency.mfu" not in tele.snapshot()["gauges"]
    finally:
        core.config["device_peak_flops"] = saved


def test_solver_flop_estimates_exist():
    # every headline solver publishes a roofline numerator (the
    # _solver_workspace_terms sibling); serving models the per-bucket hook
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.models.feature import PCA

    assert LogisticRegression(maxIter=3)._solver_flop_estimate(100, 10) > 0
    assert LinearRegression()._solver_flop_estimate(100, 10) > 0
    assert KMeans(n_clusters=4)._solver_flop_estimate(100, 10) > 0
    assert PCA(k=2)._solver_flop_estimate(100, 10) > 0


# --------------------------------------------------- tenant_usage / report --


def test_tenant_usage_merges_device_time(tele, rng):
    from spark_rapids_ml_tpu.scheduler.ledger import global_ledger

    LogisticRegression(maxIter=5).setFeaturesCol("features").fit(_binary_df(rng))
    usage = global_ledger().tenant_usage()
    assert "default" in usage
    dt = usage["default"].get("device_time")
    assert dt is not None
    assert set(dt) >= {"execute_s", "compile_s", "host_s", "idle_s", "wall_s"}
    # the same split flows through the scheduler's stats surface
    from spark_rapids_ml_tpu.scheduler import FitScheduler

    sched = FitScheduler(max_concurrent=1)
    try:
        assert "device_time" in sched.stats()["tenant_usage"]["default"]
    finally:
        sched.shutdown()


def test_report_and_snapshot_carry_efficiency_and_autotune(tele, tmp_path, rng):
    import json

    LogisticRegression(maxIter=5).setFeaturesCol("features").fit(_binary_df(rng))
    rep = ops_plane.report()
    assert "default" in rep["efficiency"]["tenants"]
    assert rep["efficiency"]["compile"]["misses"] >= 1
    # satellite: PR 16's autotune stats surface here too
    assert set(rep["autotune"]) >= {
        "hits", "misses", "measurements", "table_errors", "entries", "table_path",
    }
    # the archived snapshot (what /snapshot serves) carries both sections
    path = str(tmp_path / "snap.json")
    export.write_snapshot(path)
    with open(path) as f:
        snap = json.load(f)
    assert "efficiency" in snap and "autotune" in snap
    assert "default" in snap["efficiency"]["tenants"]
    # opsreport renders the efficiency section + the standalone archive
    from benchmark.opsreport import main, render

    out = render(snap)
    assert "efficiency (attributed device time)" in out
    assert "compile ledger:" in out
    eff_path = str(tmp_path / "efficiency_report.json")
    assert main(["--write-efficiency", eff_path, "--json"]) in (0, 1)
    with open(eff_path) as f:
        eff_doc = json.load(f)
    assert "efficiency" in eff_doc and "autotune" in eff_doc


def test_admit_model_load_defaults_per_model_serving_tenant(tele, rng):
    from spark_rapids_ml_tpu import memory
    from spark_rapids_ml_tpu.scheduler.ledger import global_ledger

    model = (
        LogisticRegression(maxIter=3).setFeaturesCol("features").fit(_binary_df(rng))
    )
    adm = memory.admit_model_load(model)  # ledger-ok: exercising the admission entry itself
    try:
        tenants = {r.tenant for r in global_ledger().reservations()}
        # keyed by model identity, not the old literal "serving" bucket
        assert "serving:LogisticRegressionModel" in tenants
        assert "serving" not in tenants
    finally:
        memory.release_admission(adm)


# ---------------------------------------------------- concurrent scrape -----


def test_exporter_renders_efficiency_under_concurrent_scrape(tele):
    """Writers run attribution scopes + compile events while readers render
    Prometheus text and report() — no exceptions, and the new metric
    families appear in the exposition."""
    errors = []
    stop = threading.Event()

    def writer(tid):
        try:
            for i in range(40):
                with telemetry.attribution(f"fit_{tid}", tenant=f"t{tid}"):
                    with telemetry.device_wait("solve"):
                        time.sleep(0.0005)
                    with telemetry.compile_event(f"p{tid}", str(i % 4)):
                        pass
                    telemetry.note_flops(1e6)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                export.render_prometheus()
                ops_plane.report()
                efficiency.summary()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    writers = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not errors
    text = export.render_prometheus()
    assert "efficiency_execute_s" in text or "efficiency.execute_s" in text
    assert "compile_misses" in text or "compile.misses" in text
    splits = efficiency.tenant_time_splits()
    assert {f"t{t}" for t in range(4)} <= set(splits)
    for s in splits.values():
        accounted = s["execute_s"] + s["compile_s"] + s["host_s"] + s["idle_s"]
        assert accounted >= 0.95 * s["wall_s"]
