#
# KMeans compat tests vs sklearn (reference tests/test_kmeans.py pattern).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.linalg import Vectors
from spark_rapids_ml_tpu.models.clustering import KMeans, KMeansModel


def _blobs(rng, n=400, d=6, k=4, dtype=np.float32):
    from sklearn.datasets import make_blobs

    x, y = make_blobs(n_samples=n, n_features=d, centers=k, cluster_std=0.4, random_state=7)
    return x.astype(dtype), y


@pytest.mark.parametrize("feature_type", ["array", "vector"])
def test_kmeans_recovers_blobs(rng, feature_type):
    x, y = _blobs(rng)
    col = list(x) if feature_type == "array" else [Vectors.dense(v) for v in x]
    df = pd.DataFrame({"features": col})
    km = KMeans(k=4, maxIter=50, seed=5, num_workers=4).setFeaturesCol("features")
    model = km.fit(df)
    assert model.cluster_centers_.shape == (4, 6)

    out = model.transform(df)
    labels = np.asarray(out["prediction"], dtype=int)
    # clustering must match blob structure up to label permutation
    from sklearn.metrics import adjusted_rand_score

    assert adjusted_rand_score(y, labels) > 0.99


def test_kmeans_vs_sklearn_inertia(rng):
    from sklearn.cluster import KMeans as SkKMeans

    x, _ = _blobs(rng, n=300, d=5, k=3)
    df = pd.DataFrame({"features": list(x)})
    model = KMeans(k=3, maxIter=100, tol=1e-8, seed=3).setFeaturesCol("features").fit(df)
    sk = SkKMeans(n_clusters=3, n_init=10, random_state=0).fit(x)
    assert model.inertia_ <= sk.inertia_ * 1.05


def test_kmeans_random_init_and_params(rng):
    x, _ = _blobs(rng, n=100, d=4, k=2)
    df = pd.DataFrame({"features": list(x)})
    km = (
        KMeans()
        .setK(2)
        .setMaxIter(30)
        .setInitMode("random")
        .setSeed(11)
        .setFeaturesCol("features")
        .setPredictionCol("cluster")
    )
    assert km.solver_params["n_clusters"] == 2
    assert km.solver_params["init"] == "random"
    model = km.fit(df)
    out = model.transform(df)
    assert set(np.asarray(out["cluster"], dtype=int)) == {0, 1}
    # single-vector predict agrees with transform
    assert model.predict(x[0]) == int(out["cluster"].iloc[0])


def test_kmeans_tol_zero_remap():
    km = KMeans(k=2).setTol(0.0)
    assert km.solver_params["tol"] == 1e-16


def test_kmeans_distance_measure_validation():
    with pytest.raises(ValueError, match="euclidean"):
        KMeans(k=2, distanceMeasure="cosine")
    KMeans(k=2, distanceMeasure="euclidean")  # accepted


def test_kmeans_weighted(rng):
    # weight w==duplication equivalence for centers
    x = np.array([[0.0, 0], [0, 0.1], [10, 10], [10, 10.1], [10, 9.9]], dtype=np.float64)
    w = np.array([3.0, 3.0, 1.0, 1.0, 1.0])
    df_w = pd.DataFrame({"features": list(x), "w": w})
    model = (
        KMeans(k=2, seed=2, maxIter=50, float32_inputs=False)
        .setFeaturesCol("features")
        .setWeightCol("w")
        .fit(df_w)
    )
    centers = sorted([tuple(np.round(c, 3)) for c in model.cluster_centers_])
    assert centers[0] == (0.0, 0.05)
    np.testing.assert_allclose(centers[1], (10, 10), atol=0.1)


def test_kmeans_persistence(tmp_path, rng):
    x, _ = _blobs(rng, n=80, d=3, k=2)
    df = pd.DataFrame({"features": list(x)})
    model = KMeans(k=2, seed=1).setFeaturesCol("features").fit(df)
    p = str(tmp_path / "km")
    model.write().overwrite().save(p)
    loaded = KMeansModel.load(p)
    np.testing.assert_array_equal(loaded.cluster_centers_, model.cluster_centers_)
    out1 = model.transform(df)["prediction"]
    out2 = loaded.transform(df)["prediction"]
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_kmeans_k_exceeds_rows(rng):
    df = pd.DataFrame({"features": list(rng.normal(size=(3, 2)))})
    with pytest.raises(ValueError, match="exceeds"):
        KMeans(k=5).setFeaturesCol("features").fit(df)


def test_kmeans_batching_equivalence(rng):
    # tiny max_samples_per_batch must not change results
    x, _ = _blobs(rng, n=200, d=4, k=3)
    df = pd.DataFrame({"features": list(x)})
    m1 = KMeans(k=3, seed=9, maxIter=40).setFeaturesCol("features").fit(df)
    m2 = KMeans(k=3, seed=9, maxIter=40, max_samples_per_batch=17).setFeaturesCol("features").fit(df)
    np.testing.assert_allclose(m1.cluster_centers_, m2.cluster_centers_, atol=1e-4)
