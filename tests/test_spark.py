#
# Spark barrier-stage integration lane (reference core.py:698-797 runs every
# fit inside `mapInPandas(...).rdd.barrier()` tasks; its communicator is built
# from `BarrierTaskContext` — cuml_context.py:80-103, conftest.py:44-70).
#
# Two lanes:
#   * test_simulated_barrier_stage_fit — ALWAYS runs: N real OS processes,
#     each wrapping a `BarrierTaskContext`-shaped object (cross-process
#     file-backed allGather) in BarrierRendezvous + TpuContext — the exact
#     production wiring for a Spark task body, minus the JVM.
#   * test_pyspark_barrier_stage_fit — runs when pyspark is importable
#     (`ci/test.sh --spark`); skipped otherwise since this image ships no
#     pyspark. Drives the same fit from inside a REAL local[N] barrier stage.
#
import os
import subprocess
import sys
import uuid

import numpy as np
import pandas as pd
import pytest

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)
NRANKS = 3


def _reference_models():
    from tests.mp_worker import make_dataset

    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.models.feature import PCA

    X, y_log, _ = make_dataset()
    df = pd.DataFrame({"features": list(X), "label": y_log})
    pca = PCA(k=3, inputCol="features", float32_inputs=False).fit(df)
    lr = (
        LogisticRegression(maxIter=100, regParam=0.1, tol=1e-10, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(df)
    )
    return pca, lr


def test_simulated_barrier_stage_fit(tmp_path):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_ENABLE_X64"] = "1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rdv_dir = str(tmp_path / "rdv")
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir, exist_ok=True)
    run_id = uuid.uuid4().hex
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "spark_barrier_worker.py"),
             str(r), str(NRANKS), rdv_dir, out_dir, run_id],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(NRANKS)
    ]
    outputs = [p.communicate(timeout=300)[0].decode() for p in procs]
    if any(
        "Multiprocess computations aren't implemented on the CPU backend" in out
        for out in outputs
    ):
        # older jax/XLA CPU backends cannot execute cross-process SPMD at all
        # (same capability gate as tests/test_multiprocess.py)
        pytest.skip("CPU backend lacks multi-process SPMD execution (jax/XLA too old)")
    for r, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"

    pca_ref, lr_ref = _reference_models()
    results = [
        np.load(os.path.join(out_dir, f"rank_{r}.npz")) for r in range(NRANKS)
    ]
    for r, res in enumerate(results):
        # every rank must hold the SAME global model, equal to the
        # single-process fit on the concatenated data
        np.testing.assert_allclose(res["pc"], np.asarray(pca_ref.pc), rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(res["mean"], np.asarray(pca_ref.mean), rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(
            res["coef"], np.asarray(lr_ref.coefficients), rtol=1e-6, atol=1e-8
        )
        np.testing.assert_allclose(
            res["intercept"], [lr_ref.intercept], rtol=1e-6, atol=1e-8
        )


def _spark_train_body(it):
    """Barrier-task body: the reference's train UDF shape (core.py:698-797) —
    get the BarrierTaskContext, wrap it, build the communicator, fit, emit
    rank 0's model."""
    from pyspark import BarrierTaskContext

    rows = list(it)
    import numpy as np
    import pandas as pd

    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.parallel import BarrierRendezvous, TpuContext

    ctx = BarrierTaskContext.get()
    rdv = BarrierRendezvous(ctx)
    feats = np.asarray([r["features"] for r in rows], dtype=np.float64)
    df = pd.DataFrame({"features": list(feats)})
    with TpuContext(rdv.rank, rdv.nranks, rdv, require_distributed=True):
        pca = PCA(k=3, inputCol="features", float32_inputs=False).fit(df)
    if rdv.rank == 0:
        yield {
            "pc": np.asarray(pca.pc).ravel().tolist(),
            "mean": np.asarray(pca.mean).tolist(),
        }


# -- Spark JVM model interop (`.cpu()`): reference utils.py:311-481 /
# -- tree.py:524-569 / feature.py:365-379 parity -----------------------------


def _rf_training_data(seed=0, n=300, d=6, classification=True):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    if classification:
        y = ((x[:, 0] + 0.5 * x[:, 1] > 0).astype(int) + (x[:, 2] > 1.0)).astype(float)
    else:
        y = x[:, 0] * 2.0 - x[:, 3] + 0.1 * rng.normal(size=n)
    return pd.DataFrame({"features": list(x), "label": y}), x


def test_tree_spec_pure_layer():
    """The py4j-free node-spec layer: structure and stats must be consistent
    with the model's own predictions — runs WITHOUT pyspark."""
    from spark_rapids_ml_tpu.models.classification import RandomForestClassifier
    from spark_rapids_ml_tpu.models.regression import RandomForestRegressor
    from spark_rapids_ml_tpu.spark_interop import forest_specs

    df, x = _rf_training_data(classification=True)
    clf = RandomForestClassifier(
        numTrees=3, maxDepth=4, seed=7, float32_inputs=False
    ).setFeaturesCol("features").fit(df)
    specs = forest_specs(clf)
    assert len(specs) == clf.num_trees

    def walk(node, depth=0):
        assert depth <= clf.max_depth
        assert node["impurity"] >= 0 and node["instance_count"] > 0
        assert len(node["stats"]) == clf.numClasses
        assert node["prediction"] == float(np.argmax(node["stats"]))
        if "split_feature" in node:
            assert 0 <= node["split_feature"] < clf.n_cols
            assert np.isfinite(node["threshold"])
            # children partition the parent's instances
            assert (
                node["left"]["instance_count"] + node["right"]["instance_count"]
                == node["instance_count"]
            )
            walk(node["left"], depth + 1)
            walk(node["right"], depth + 1)

    for spec in specs:
        walk(spec)

    # single-tree spec traversal must reproduce the model's own prediction
    def spec_predict(node, row):
        while "split_feature" in node:
            node = node["left"] if row[node["split_feature"]] <= node["threshold"] else node["right"]
        return node["prediction"]

    votes = np.zeros((len(x), clf.numClasses))
    for spec in specs:
        for i, row in enumerate(x):
            node = spec
            while "split_feature" in node:
                node = node["left"] if row[node["split_feature"]] <= node["threshold"] else node["right"]
            s = np.asarray(node["stats"])
            votes[i] += s / s.sum()
    got = clf.classes_[np.argmax(votes, axis=1)]
    want = clf.transform(df)["prediction"].to_numpy()
    np.testing.assert_array_equal(got.astype(float), want)

    # regression: leaf prediction = node mean; forest mean matches transform
    dfr, xr = _rf_training_data(classification=False)
    reg = RandomForestRegressor(
        numTrees=3, maxDepth=4, seed=7, float32_inputs=False
    ).setFeaturesCol("features").fit(dfr)
    preds = np.zeros(len(xr))
    for spec in forest_specs(reg):
        preds += np.asarray([spec_predict(spec, row) for row in xr])
    preds /= reg.num_trees
    np.testing.assert_allclose(
        preds, reg.transform(dfr)["prediction"].to_numpy(), rtol=1e-8, atol=1e-10
    )


def test_tree_spec_root_leaf():
    # a forest whose gain bar blocks every split must convert to single
    # LeafNode trees (the degenerate case the py4j builder must survive)
    from spark_rapids_ml_tpu.models.classification import RandomForestClassifier
    from spark_rapids_ml_tpu.spark_interop import forest_specs

    df, _ = _rf_training_data(n=120)
    clf = RandomForestClassifier(
        numTrees=2, maxDepth=3, minInfoGain=1e9, seed=1, float32_inputs=False
    ).setFeaturesCol("features").fit(df)
    for spec in forest_specs(clf):
        assert "split_feature" not in spec  # root is a leaf
        assert spec["instance_count"] > 0
        assert spec["prediction"] == float(np.argmax(spec["stats"]))


def test_cpu_requires_pyspark_message():
    """Without pyspark, .cpu() must raise a clear ImportError (not crash deep
    in py4j)."""
    try:
        import pyspark  # noqa: F401

        pytest.skip("pyspark installed; the gated parity tests cover .cpu()")
    except ImportError:
        pass
    from spark_rapids_ml_tpu.models.feature import PCA

    df, _ = _rf_training_data()
    model = PCA(k=2, inputCol="features", float32_inputs=False).fit(df)
    with pytest.raises(ImportError, match="pyspark"):
        model.cpu()


@pytest.fixture(scope="module")
def spark_session():
    pytest.importorskip("pyspark")
    from pyspark.sql import SparkSession

    spark = (
        SparkSession.builder.master("local[2]")
        .appName("srml-tpu-cpu-interop")
        .getOrCreate()
    )
    yield spark
    spark.stop()


def _spark_predictions(spark, spark_model, x, cols):
    from pyspark.ml.linalg import Vectors as SparkVectors

    sdf = spark.createDataFrame(
        [(SparkVectors.dense([float(v) for v in row]),) for row in x], ["features"]
    )
    rows = spark_model.transform(sdf).collect()
    return {c: np.asarray([_to_np(r[c]) for r in rows]) for c in cols}


def _to_np(v):
    return v.toArray() if hasattr(v, "toArray") else v


def test_rf_to_spark_model(spark_session):
    """Fitted TPU RF -> genuine JVM RandomForestClassificationModel with
    matching predictions (VERDICT round-4 item 4; reference tree.py:524-569)."""
    from spark_rapids_ml_tpu.models.classification import RandomForestClassifier

    df, x = _rf_training_data(classification=True)
    model = RandomForestClassifier(
        numTrees=5, maxDepth=5, seed=3, float32_inputs=False
    ).setFeaturesCol("features").fit(df)
    spark_model = model.cpu()
    assert spark_model.getNumTrees == model.num_trees
    assert spark_model.numFeatures == model.n_cols
    assert spark_model.numClasses == model.numClasses

    ours = model.transform(df)
    got = _spark_predictions(
        spark_session, spark_model, x, ["prediction", "probability"]
    )
    np.testing.assert_allclose(
        got["prediction"], ours["prediction"].to_numpy(), atol=1e-12
    )
    np.testing.assert_allclose(
        got["probability"], np.stack(ours["probability"].to_list()), atol=1e-6
    )
    # predictLeaf delegates through the JVM model (reference tree.py:513-518)
    leaves = model.predictLeaf(x[0])
    assert np.asarray(leaves.toArray() if hasattr(leaves, "toArray") else leaves).shape[-1] == model.num_trees


def test_rf_regression_to_spark_model(spark_session):
    from spark_rapids_ml_tpu.models.regression import RandomForestRegressor

    df, x = _rf_training_data(classification=False)
    model = RandomForestRegressor(
        numTrees=5, maxDepth=5, seed=3, float32_inputs=False
    ).setFeaturesCol("features").fit(df)
    spark_model = model.cpu()
    got = _spark_predictions(spark_session, spark_model, x, ["prediction"])
    np.testing.assert_allclose(
        got["prediction"], model.transform(df)["prediction"].to_numpy(), rtol=1e-6
    )


def test_pca_to_spark_model(spark_session):
    """PCA -> JVM PCAModel: pc/explainedVariance carried exactly; projections
    agree on centered inputs (Spark PCAModel does not mean-center)."""
    from spark_rapids_ml_tpu.models.feature import PCA

    df, x = _rf_training_data()
    model = PCA(k=3, inputCol="features", outputCol="pca_out", float32_inputs=False).fit(df)
    spark_model = model.cpu()
    np.testing.assert_allclose(
        np.asarray(spark_model.pc.toArray()), np.asarray(model.pc), rtol=1e-10
    )
    np.testing.assert_allclose(
        np.asarray(spark_model.explainedVariance.toArray()),
        np.asarray(model.explainedVariance),
        rtol=1e-10,
    )
    xc = x - np.asarray(model.mean)[None, :]
    got = _spark_predictions(spark_session, spark_model, xc, ["pca_out"])
    np.testing.assert_allclose(got["pca_out"], xc @ np.asarray(model.pc), atol=1e-8)


def test_kmeans_to_spark_model(spark_session):
    from spark_rapids_ml_tpu.models.clustering import KMeans

    df, x = _rf_training_data(classification=False)
    model = KMeans(k=4, seed=2, maxIter=20, float32_inputs=False).setFeaturesCol("features").fit(df)
    spark_model = model.cpu()
    got_centers = np.stack([np.asarray(c) for c in spark_model.clusterCenters()])
    np.testing.assert_allclose(got_centers, np.asarray(model.cluster_centers_), rtol=1e-10)
    got = _spark_predictions(spark_session, spark_model, x, ["prediction"])
    np.testing.assert_array_equal(
        got["prediction"], model.transform(df)["prediction"].to_numpy()
    )


def test_linear_models_to_spark(spark_session):
    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.models.regression import LinearRegression

    df, x = _rf_training_data(classification=False)
    lin = LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    got = _spark_predictions(spark_session, lin.cpu(), x, ["prediction"])
    np.testing.assert_allclose(
        got["prediction"], lin.transform(df)["prediction"].to_numpy(), rtol=1e-6
    )

    dfc, xc = _rf_training_data(classification=True)
    dfc["label"] = (dfc["label"] > 0).astype(float)  # binary 0/1
    log = (
        LogisticRegression(maxIter=200, tol=1e-12, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(dfc)
    )
    got = _spark_predictions(spark_session, log.cpu(), xc, ["prediction", "probability"])
    ours = log.transform(dfc)
    np.testing.assert_allclose(got["prediction"], ours["prediction"].to_numpy(), atol=1e-12)
    np.testing.assert_allclose(
        got["probability"], np.stack(ours["probability"].to_list()), atol=1e-6
    )


def test_pyspark_barrier_stage_fit(tmp_path):
    pyspark = pytest.importorskip("pyspark")
    from pyspark.sql import SparkSession

    from tests.mp_worker import make_dataset, split_bounds

    spark = (
        SparkSession.builder.master(f"local[{NRANKS}]")
        .appName("srml-tpu-barrier-it")
        .config("spark.default.parallelism", str(NRANKS))
        .config("spark.python.worker.reuse", "false")
        .getOrCreate()
    )
    try:
        X, _, _ = make_dataset()
        bounds = split_bounds(len(X), NRANKS)
        rows = [
            {"part": r, "features": X[i].tolist()}
            for r in range(NRANKS)
            for i in range(bounds[r], bounds[r + 1])
        ]
        rdd = (
            spark.sparkContext.parallelize(rows, NRANKS)
            .barrier()
            .mapPartitions(_spark_train_body)
        )
        out = rdd.collect()
        assert len(out) == 1  # one model row, from rank 0
        pca_ref, _ = _reference_models()
        got_pc = np.asarray(out[0]["pc"]).reshape(np.asarray(pca_ref.pc).shape)
        np.testing.assert_allclose(got_pc, np.asarray(pca_ref.pc), rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(
            np.asarray(out[0]["mean"]), np.asarray(pca_ref.mean), rtol=1e-6, atol=1e-8
        )
    finally:
        spark.stop()


def test_as_spark_df_probes_first_non_null():
    # the column-kind probe must skip leading None/NaN cells (ADVICE round 5):
    # a vector column whose row 0 is null is still a vector column — runs
    # WITHOUT pyspark (pure pandas helper layer)
    from spark_rapids_ml_tpu.spark_interop import _first_non_null

    pdf = pd.DataFrame(
        {
            "vec_leading_none": [None, np.array([1.0, 2.0]), np.array([3.0, 4.0])],
            "vec_leading_nan": [np.nan, [1.0, 2.0], [3.0, 4.0]],
            "scalar_leading_nan": [np.nan, 1.5, 2.5],
            "all_null": [None, None, None],
        }
    )
    probed = _first_non_null(pdf["vec_leading_none"])
    assert isinstance(probed, np.ndarray)
    np.testing.assert_array_equal(probed, [1.0, 2.0])
    assert _first_non_null(pdf["vec_leading_nan"]) == [1.0, 2.0]
    assert _first_non_null(pdf["scalar_leading_nan"]) == 1.5
    assert _first_non_null(pdf["all_null"]) is None
    assert _first_non_null(pd.Series([], dtype=object)) is None

    # null cells of a vector column map to None (a bare NaN in a VectorUDT
    # column breaks Spark's serializer); non-null branches need pyspark and
    # are covered by the --spark lane
    from spark_rapids_ml_tpu.spark_interop import _vector_cell_or_none

    assert _vector_cell_or_none(None) is None
    assert _vector_cell_or_none(float("nan")) is None
    assert _vector_cell_or_none(np.float64("nan")) is None
