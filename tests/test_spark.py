#
# Spark barrier-stage integration lane (reference core.py:698-797 runs every
# fit inside `mapInPandas(...).rdd.barrier()` tasks; its communicator is built
# from `BarrierTaskContext` — cuml_context.py:80-103, conftest.py:44-70).
#
# Two lanes:
#   * test_simulated_barrier_stage_fit — ALWAYS runs: N real OS processes,
#     each wrapping a `BarrierTaskContext`-shaped object (cross-process
#     file-backed allGather) in BarrierRendezvous + TpuContext — the exact
#     production wiring for a Spark task body, minus the JVM.
#   * test_pyspark_barrier_stage_fit — runs when pyspark is importable
#     (`ci/test.sh --spark`); skipped otherwise since this image ships no
#     pyspark. Drives the same fit from inside a REAL local[N] barrier stage.
#
import os
import subprocess
import sys
import uuid

import numpy as np
import pandas as pd
import pytest

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)
NRANKS = 3


def _reference_models():
    from tests.mp_worker import make_dataset

    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.models.feature import PCA

    X, y_log, _ = make_dataset()
    df = pd.DataFrame({"features": list(X), "label": y_log})
    pca = PCA(k=3, inputCol="features", float32_inputs=False).fit(df)
    lr = (
        LogisticRegression(maxIter=100, regParam=0.1, tol=1e-10, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(df)
    )
    return pca, lr


def test_simulated_barrier_stage_fit(tmp_path):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_ENABLE_X64"] = "1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rdv_dir = str(tmp_path / "rdv")
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir, exist_ok=True)
    run_id = uuid.uuid4().hex
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, "spark_barrier_worker.py"),
             str(r), str(NRANKS), rdv_dir, out_dir, run_id],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(NRANKS)
    ]
    outputs = [p.communicate(timeout=300)[0].decode() for p in procs]
    for r, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"

    pca_ref, lr_ref = _reference_models()
    results = [
        np.load(os.path.join(out_dir, f"rank_{r}.npz")) for r in range(NRANKS)
    ]
    for r, res in enumerate(results):
        # every rank must hold the SAME global model, equal to the
        # single-process fit on the concatenated data
        np.testing.assert_allclose(res["pc"], np.asarray(pca_ref.pc), rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(res["mean"], np.asarray(pca_ref.mean), rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(
            res["coef"], np.asarray(lr_ref.coefficients), rtol=1e-6, atol=1e-8
        )
        np.testing.assert_allclose(
            res["intercept"], [lr_ref.intercept], rtol=1e-6, atol=1e-8
        )


def _spark_train_body(it):
    """Barrier-task body: the reference's train UDF shape (core.py:698-797) —
    get the BarrierTaskContext, wrap it, build the communicator, fit, emit
    rank 0's model."""
    from pyspark import BarrierTaskContext

    rows = list(it)
    import numpy as np
    import pandas as pd

    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.parallel import BarrierRendezvous, TpuContext

    ctx = BarrierTaskContext.get()
    rdv = BarrierRendezvous(ctx)
    feats = np.asarray([r["features"] for r in rows], dtype=np.float64)
    df = pd.DataFrame({"features": list(feats)})
    with TpuContext(rdv.rank, rdv.nranks, rdv, require_distributed=True):
        pca = PCA(k=3, inputCol="features", float32_inputs=False).fit(df)
    if rdv.rank == 0:
        yield {
            "pc": np.asarray(pca.pc).ravel().tolist(),
            "mean": np.asarray(pca.mean).tolist(),
        }


def test_pyspark_barrier_stage_fit(tmp_path):
    pyspark = pytest.importorskip("pyspark")
    from pyspark.sql import SparkSession

    from tests.mp_worker import make_dataset, split_bounds

    spark = (
        SparkSession.builder.master(f"local[{NRANKS}]")
        .appName("srml-tpu-barrier-it")
        .config("spark.default.parallelism", str(NRANKS))
        .config("spark.python.worker.reuse", "false")
        .getOrCreate()
    )
    try:
        X, _, _ = make_dataset()
        bounds = split_bounds(len(X), NRANKS)
        rows = [
            {"part": r, "features": X[i].tolist()}
            for r in range(NRANKS)
            for i in range(bounds[r], bounds[r + 1])
        ]
        rdd = (
            spark.sparkContext.parallelize(rows, NRANKS)
            .barrier()
            .mapPartitions(_spark_train_body)
        )
        out = rdd.collect()
        assert len(out) == 1  # one model row, from rank 0
        pca_ref, _ = _reference_models()
        got_pc = np.asarray(out[0]["pc"]).reshape(np.asarray(pca_ref.pc).shape)
        np.testing.assert_allclose(got_pc, np.asarray(pca_ref.pc), rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(
            np.asarray(out[0]["mean"]), np.asarray(pca_ref.mean), rtol=1e-6, atol=1e-8
        )
    finally:
        spark.stop()
