#
# Parity suite for the shared tiled distance/top-k core (ops/distance.py):
# the Pallas kernels (run through the interpreter — CPU CI's way of
# executing real kernel code) against the bit-compatible pure-jnp fallback,
# swept across tile boundaries (rows/k/d = block±1), f32/f64,
# weighted/zero-weight padding rows, the `fast` bf16 precision mode, and
# top-k tie ordering against a full-matrix `jax.lax.top_k` reference.
# Plus the compile-count invariant: a KMeans fit compiles ONE distance
# program across all its Lloyd iterations (the distance.* counters tick at
# TRACE time by design).
#
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu import telemetry
from spark_rapids_ml_tpu.core import config
from spark_rapids_ml_tpu.ops import distance


@pytest.fixture
def interpret_mode():
    """Force the REAL kernels through the Pallas interpreter for this test;
    restore the probed mode after."""
    saved = distance._MODE
    distance._MODE = "interpret"
    yield
    distance._MODE = saved


@pytest.fixture
def jnp_mode():
    saved = distance._MODE
    distance._MODE = "jnp"
    yield
    distance._MODE = saved


def _data(n, k, d, dtype, seed=0, dup_rows=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(dtype)
    if dup_rows:  # deliberate exact ties for the tie-ordering tests
        X[-dup_rows:] = X[:dup_rows]
    C = rng.normal(size=(k, d)).astype(dtype)
    w = rng.uniform(0.5, 2.0, size=n).astype(dtype)
    return jnp.asarray(X), jnp.asarray(C), jnp.asarray(w)


def _fallback_assign_accumulate(X, w, C):
    d2 = jnp.sum(C * C, 1)[None, :] - 2.0 * (X @ C.T)
    assign = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1) + jnp.sum(X * X, axis=1)
    oh = jax.nn.one_hot(assign, C.shape[0], dtype=X.dtype) * w[:, None]
    return oh.T @ X, jnp.sum(oh, axis=0), jnp.sum(jnp.maximum(min_d2, 0.0) * w)


# ------------------------------------------------- assign/accumulate parity --


@pytest.mark.parametrize("n", [7, 8, 9])
@pytest.mark.parametrize("k", [3, 4, 5])
@pytest.mark.parametrize("d", [5, 8])
def test_assign_accumulate_kernel_parity_f64(interpret_mode, n, k, d):
    # blocks of (8, 4): every (n, k) combination crosses a boundary or a
    # ragged tail on at least one axis
    X, C, w = _data(n, k, d, np.float64, seed=n * 100 + k * 10 + d)
    s, c, i = distance.assign_accumulate(X, w, C, block_rows=8, block_k=4)
    sr, cr, ir = _fallback_assign_accumulate(X, w, C)
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(c), np.asarray(cr), rtol=1e-9)
    np.testing.assert_allclose(float(i), float(ir), rtol=1e-9)


@pytest.mark.parametrize("n,k", [(9, 5), (16, 4), (33, 7)])
def test_assignments_exact_f32(interpret_mode, n, k):
    X, C, _ = _data(n, k, 6, np.float32, seed=n)
    _, a = distance.assign_argmin(X, C, block_rows=8, block_k=4)
    ref = jnp.argmin(jnp.sum(C * C, 1)[None, :] - 2.0 * (X @ C.T), axis=1)
    assert (np.asarray(a) == np.asarray(ref)).all()


def test_assignments_exact_f32_fast_mode(interpret_mode):
    # `fast` (one-pass bf16, f32 accumulation) must round IDENTICALLY on the
    # kernel and fallback paths — assignments are compared exactly
    X, C, w = _data(33, 5, 8, np.float32, seed=3)
    s, c, i = distance.assign_accumulate(X, w, C, fast=True, block_rows=8, block_k=4)
    distance._MODE = "jnp"
    sr, cr, ir = distance.assign_accumulate(X, w, C, fast=True)
    distance._MODE = "interpret"
    np.testing.assert_allclose(np.asarray(c), np.asarray(cr), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(i), float(ir), rtol=1e-5)


def test_zero_weight_padding_rows_contribute_nothing(interpret_mode):
    # the resident pad contract: rows with w == 0 change NOTHING, on both
    # paths, including when they land in a ragged kernel block
    X, C, w = _data(11, 4, 5, np.float64, seed=7)
    Xp = jnp.concatenate([X, jnp.ones((5, 5), X.dtype) * 1e6])
    wp = jnp.concatenate([w, jnp.zeros((5,), X.dtype)])
    s, c, i = distance.assign_accumulate(Xp, wp, C, block_rows=8, block_k=4)
    sr, cr, ir = _fallback_assign_accumulate(X, w, C)
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(c), np.asarray(cr), rtol=1e-9)
    np.testing.assert_allclose(float(i), float(ir), rtol=1e-9)


def test_argmin_assign_ragged_tiles_match_bruteforce(jnp_mode):
    # row-tiled predict path: clamp-back tiles recompute overlap rows
    # idempotently — assignments equal the untiled argmin
    X, C, _ = _data(37, 6, 5, np.float64, seed=11)
    a = distance.argmin_assign(X, C, batch_rows=8)
    ref = jnp.argmin(jnp.sum(C * C, 1)[None, :] - 2.0 * (X @ C.T), axis=1)
    assert (np.asarray(a) == np.asarray(ref)).all()
    assert a.dtype == jnp.int32


# ------------------------------------------------------------ top-k parity --


def _topk_reference(q, items, valid, kk):
    d2 = jnp.sum(items * items, 1)[None, :] - 2.0 * (q @ items.T)
    if valid is not None:
        d2 = jnp.where(valid[None, :], d2, jnp.inf)
    neg_d, idx = jax.lax.top_k(-d2, kk)
    return -neg_d, idx


@pytest.mark.parametrize("mode_fixture", ["interpret_mode", "jnp_mode"])
@pytest.mark.parametrize("n", [7, 8, 9, 20])
def test_topk_tile_boundary_parity(request, mode_fixture, n):
    request.getfixturevalue(mode_fixture)
    rng = np.random.default_rng(n)
    q = jnp.asarray(rng.normal(size=(5, 6)))
    items = jnp.asarray(rng.normal(size=(n, 6)))
    kk = min(4, n)
    d2, idx = distance.topk_tile(q, items, None, kk, k_tile=4, block_rows=8)
    d2r, idxr = _topk_reference(q, items, None, kk)
    np.testing.assert_allclose(np.asarray(d2), np.asarray(d2r), rtol=1e-9)
    assert (np.asarray(idx) == np.asarray(idxr)).all()


def test_topk_tie_ordering_matches_lax_top_k(jnp_mode):
    # duplicated item rows produce EXACTLY tied distances; the k-tiled
    # running merge must resolve them like one full-matrix lax.top_k
    # (lower index first) even when the tie straddles a tile boundary
    rng = np.random.default_rng(0)
    base = rng.integers(-3, 4, size=(6, 5)).astype(np.float64)
    items = jnp.asarray(np.concatenate([base, base[:3]]))  # ids 6,7,8 == 0,1,2
    q = jnp.asarray(rng.integers(-3, 4, size=(4, 5)).astype(np.float64))
    d2, idx = distance.topk_tile(q, items, None, 6, k_tile=4)
    d2r, idxr = _topk_reference(q, items, None, 6)
    np.testing.assert_array_equal(np.asarray(d2), np.asarray(d2r))
    assert (np.asarray(idx) == np.asarray(idxr)).all()


def test_topk_tie_ordering_kernel_path(interpret_mode):
    # INTEGER-valued rows: every dot product is exact in f64 regardless of
    # tiling/summation order, so duplicated rows are bitwise ties on both
    # paths — the only fair way to compare tie ordering across matmul
    # shapes (float matmuls of different shapes are not bitwise
    # reproducible even within one backend)
    rng = np.random.default_rng(1)
    base = rng.integers(-3, 4, size=(6, 5)).astype(np.float64)
    items = jnp.asarray(np.concatenate([base, base[:3]]))  # ids 6,7,8 == 0,1,2
    q = jnp.asarray(rng.integers(-3, 4, size=(4, 5)).astype(np.float64))
    d2, idx = distance.topk_tile(q, items, None, 6, k_tile=4, block_rows=4)
    d2r, idxr = _topk_reference(q, items, None, 6)
    np.testing.assert_array_equal(np.asarray(d2), np.asarray(d2r))
    assert (np.asarray(idx) == np.asarray(idxr)).all()


def test_topk_invalid_items_masked(interpret_mode):
    # padding items (valid=False) must never appear among finite neighbors
    rng = np.random.default_rng(2)
    items = jnp.asarray(rng.normal(size=(9, 4)))
    valid = jnp.asarray(np.array([True] * 6 + [False] * 3))
    q = jnp.asarray(rng.normal(size=(3, 4)))
    d2, idx = distance.topk_tile(q, items, valid, 6, k_tile=4, block_rows=4)
    finite = np.isfinite(np.asarray(d2))
    assert finite[:, :6].sum() == 3 * 6  # all six real items found
    assert (np.asarray(idx)[finite] < 6).all()


def test_tile_topk_routes_batch_queries_through_config():
    # satellite: the query scan's hardcoded 4096 became
    # config["distance_tile_rows"] — a small knob value must still produce
    # exact results (more, smaller tiles), proving the knob is live
    saved = config["distance_tile_rows"]
    config["distance_tile_rows"] = 8
    try:
        assert distance.tile_rows() == 8
        rng = np.random.default_rng(5)
        items = jnp.asarray(rng.normal(size=(30, 4)))
        valid = jnp.asarray(np.ones(30, dtype=bool))
        q = jnp.asarray(rng.normal(size=(21, 4)))  # 3 tiles of 8 (ragged)
        dist, idx = distance.tile_topk(items, q, valid, 5)
        d2r, idxr = _topk_reference(q, items, valid, 5)
        ref = np.asarray(d2r) + np.sum(np.asarray(q) ** 2, axis=1)[:, None]
        np.testing.assert_allclose(np.asarray(dist), ref, rtol=1e-9)
        assert (np.asarray(idx) == np.asarray(idxr)).all()
    finally:
        config["distance_tile_rows"] = saved


# ------------------------------------------------------- compile invariant --


def test_kmeans_fit_compiles_one_distance_program():
    # the distance.* counters tick once per TRACE: across 3 and then 8 Lloyd
    # iterations of identical shape, the assign program is traced for the
    # first fit only — no per-iteration (or per-fit) recompile
    from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit
    from spark_rapids_ml_tpu.parallel import get_mesh

    rng = np.random.default_rng(9)
    # unique shape so no other test's cached program hides the first trace
    X = jnp.asarray(rng.normal(size=(257, 13)))
    w = jnp.ones((257,), X.dtype)
    c0 = jnp.asarray(rng.normal(size=(6, 13)))
    telemetry.enable()
    try:
        telemetry.registry().reset()
        kmeans_fit(X, w, c0, mesh=get_mesh(1), max_iter=3, tol=0.0)
        first = telemetry.snapshot()["counters"].get("distance.assign_programs", 0)
        assert first > 0  # the fit really went through the shared core
        kmeans_fit(X, w, c0, mesh=get_mesh(1), max_iter=8, tol=0.0)
        second = telemetry.snapshot()["counters"].get("distance.assign_programs", 0)
        assert second == first  # 8 iterations + a second fit: zero retraces
    finally:
        telemetry.registry().reset()
        telemetry.disable()


def test_kernel_mode_probe_is_jnp_on_cpu(monkeypatch):
    monkeypatch.delenv("SRML_DISTANCE_KERNEL", raising=False)
    saved = distance._MODE
    distance._MODE = None
    try:
        assert distance.kernel_mode() == "jnp"  # CPU backend -> fallback
    finally:
        distance._MODE = saved


def test_kernel_mode_env_override(monkeypatch):
    saved = distance._MODE
    try:
        monkeypatch.setenv("SRML_DISTANCE_KERNEL", "interpret")
        distance._MODE = None
        assert distance.kernel_mode() == "interpret"
        # explicit `pallas` really FORCES the kernel path (no silent
        # self-test fallback — docs/configuration.md contract)
        monkeypatch.setenv("SRML_DISTANCE_KERNEL", "pallas")
        distance._MODE = None
        assert distance.kernel_mode() == "pallas"
    finally:
        distance._MODE = saved


def test_plan_blocks_fits_budget_and_floors():
    br, bk = distance.plan_blocks(4096, 1000, 3000, 4)
    assert (br * 3000 + bk * 3000 + br * bk) * 4 <= distance._VMEM_BUDGET_BYTES
    assert br >= 8 and bk >= 128
    # absurd depth: nothing fits -> None (callers fall back to jnp)
    assert distance.plan_blocks(4096, 1000, 50_000_000, 4) is None
