#
# Notebook smoke lane (reference ships notebooks/ and CI-checks them):
# execute every notebook top-to-bottom on the CPU mesh. Slow (kernel startup
# + full workflow), so nightly-gated like tests_large.
#
import os

import pytest

nbformat = pytest.importorskip("nbformat")
pytest.importorskip("nbclient")

HERE = os.path.dirname(__file__)
NB_DIR = os.path.join(os.path.dirname(HERE), "notebooks")
NOTEBOOKS = sorted(f for f in os.listdir(NB_DIR) if f.endswith(".ipynb"))


@pytest.mark.slow
@pytest.mark.parametrize("name", NOTEBOOKS)
def test_notebook_executes(name, monkeypatch):
    from nbclient import NotebookClient

    # the kernel is a fresh process: give it the repo import path (scoped to
    # this test — the kernel inherits the env; monkeypatch restores it)
    monkeypatch.setenv(
        "PYTHONPATH", os.path.dirname(HERE) + os.pathsep + os.environ.get("PYTHONPATH", "")
    )
    nb = nbformat.read(os.path.join(NB_DIR, name), as_version=4)
    NotebookClient(nb, timeout=300, kernel_name="python3").execute()
