#
# Test harness: run every test on a virtual 8-device CPU mesh so the real
# multi-chip SPMD code paths (sharding, psum, ppermute) execute on one machine —
# the analog of the reference's Spark local[N]-with-real-GPUs harness
# (reference tests/conftest.py:44-70): multi-"node" behavior without a cluster.
#
# The env vars MUST be set before jax is imported anywhere in the process.
#
import os

# Belt-and-braces for a clean interpreter; in this image a sitecustomize
# force-registers the TPU PJRT plugin before conftest runs, so the decisive
# override is the framework's device hook below, not these env vars.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")  # f64 parity tests (float32_inputs=False path)

import jax  # noqa: E402

try:
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass
jax.config.update("jax_enable_x64", True)

from spark_rapids_ml_tpu.parallel import set_devices  # noqa: E402

set_devices("cpu")  # all framework work on the virtual 8-device CPU mesh

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False, help="run slow tests")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: mark test as slow (nightly only)")
    config.addinivalue_line("markers", "compat: Spark-ML output-parity test")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="need --runslow option to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def mesh8():
    from spark_rapids_ml_tpu.parallel import default_devices, get_mesh

    assert len(default_devices()) >= 8, "conftest must provide 8 CPU devices"
    return get_mesh(8)
