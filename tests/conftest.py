#
# Test harness: run every test on a virtual 8-device CPU mesh so the real
# multi-chip SPMD code paths (sharding, psum, ppermute) execute on one machine —
# the analog of the reference's Spark local[N]-with-real-GPUs harness
# (reference tests/conftest.py:44-70): multi-"node" behavior without a cluster.
#
# The env vars MUST be set before jax is imported anywhere in the process.
#
import os
import sys

# The whole suite runs on the CPU mesh, so never let jax touch the TPU tunnel:
# with the tunnel down, ANY backend init in a process whose env names the
# tunnel (PALLAS_AXON_POOL_IPS) hangs for minutes (measured rounds 4-5) even
# when the framework pins its work to CPU — the tunnel plugin is activated by
# sitecustomize AT INTERPRETER STARTUP, before conftest can scrub os.environ.
# The only reliable fix is to re-exec pytest once with a clean env (the same
# scrub mp_worker / test_spark already apply to their children). The re-exec
# must happen from pytest_configure with global capture STOPPED: at conftest
# import time pytest has already pointed fds 1/2 at capture temp files, and an
# exec'd child inheriting those writes its whole report into a file nobody
# reads. Chip-only runs use bench.py / benchmark_runner, not pytest.
_ENV_POISONED = os.environ.get("SRML_TEST_REEXEC") != "1" and (
    "PALLAS_AXON_POOL_IPS" in os.environ
    or os.environ.get("JAX_PLATFORMS", "cpu") not in ("cpu", "")
)

if not _ENV_POISONED:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_ENABLE_X64", "1")  # f64 parity (float32_inputs=False path)

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        pass
    jax.config.update("jax_enable_x64", True)

    from spark_rapids_ml_tpu.parallel import set_devices

    set_devices("cpu")  # all framework work on the virtual 8-device CPU mesh

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False, help="run slow tests")


def pytest_configure(config):
    if _ENV_POISONED:
        capman = config.pluginmanager.getplugin("capturemanager")
        if capman is not None:
            capman.stop_global_capturing()  # restore real fds 1/2 for the exec'd child
        env = dict(os.environ, SRML_TEST_REEXEC="1", JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        sys.stderr.write("[conftest] re-exec with TPU tunnel env scrubbed (CPU-mesh suite)\n")
        sys.stderr.flush()
        os.execve(
            sys.executable,
            [sys.executable, "-m", "pytest", *config.invocation_params.args],
            env,
        )
    config.addinivalue_line("markers", "slow: mark test as slow (nightly only)")
    config.addinivalue_line("markers", "compat: Spark-ML output-parity test")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="need --runslow option to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)


@pytest.fixture(autouse=True)
def _fresh_hbm_ledger():
    # every HBM admission (fit, serving load, scheduler job) reserves in the
    # process-global shared ledger (docs/scheduling.md); a test that admits
    # without releasing (direct admit_* calls, un-evicted registries) must
    # not shrink every later test's budget
    from spark_rapids_ml_tpu.scheduler.ledger import reset_global_ledger

    reset_global_ledger()
    yield
    reset_global_ledger()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def mesh8():
    from spark_rapids_ml_tpu.parallel import default_devices, get_mesh

    assert len(default_devices()) >= 8, "conftest must provide 8 CPU devices"
    return get_mesh(8)
