#
# RandomForest classifier/regressor tests vs sklearn
# (reference tests/test_random_forest.py pattern, 945 LoC there).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.models.classification import (
    RandomForestClassificationModel,
    RandomForestClassifier,
)
from spark_rapids_ml_tpu.models.regression import (
    RandomForestRegressionModel,
    RandomForestRegressor,
)


def _clf_data(rng, n=500, d=8, k=3):
    from sklearn.datasets import make_classification

    x, y = make_classification(
        n_samples=n, n_features=d, n_informative=d - 2, n_redundant=0,
        n_classes=k, n_clusters_per_class=1, class_sep=2.0, random_state=9,
    )
    return pd.DataFrame({"features": list(x.astype(np.float64)), "label": y.astype(np.float64)}), x, y


def _reg_data(rng, n=500, d=6):
    x = rng.uniform(-2, 2, size=(n, d))
    y = np.sin(x[:, 0]) * 3 + x[:, 1] ** 2 + 0.5 * x[:, 2] + 0.1 * rng.normal(size=n)
    return pd.DataFrame({"features": list(x), "label": y}), x, y


def test_rf_classifier_accuracy(rng):
    df, x, y = _clf_data(rng)
    rf = (
        RandomForestClassifier(numTrees=20, maxDepth=6, maxBins=64, seed=7, num_workers=4)
        .setFeaturesCol("features")
    )
    assert rf.solver_params["n_estimators"] == 20
    model = rf.fit(df)
    assert model.numClasses == 3
    assert model.getNumTrees == 20
    out = model.transform(df)
    acc = (np.asarray(out["prediction"]) == y).mean()
    assert acc > 0.93
    # probability columns sane
    probs = np.stack([np.asarray(p) for p in out["probability"]])
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-6)
    raws = np.stack([np.asarray(p) for p in out["rawPrediction"]])
    np.testing.assert_allclose(raws.sum(axis=1), model.num_trees, rtol=1e-5)


def test_rf_classifier_vs_sklearn_holdout(rng):
    from sklearn.ensemble import RandomForestClassifier as SkRF

    df, x, y = _clf_data(rng, n=800, d=10)
    train, test = df.iloc[:600], df.iloc[600:].reset_index(drop=True)
    model = (
        RandomForestClassifier(numTrees=30, maxDepth=8, maxBins=64, seed=3)
        .setFeaturesCol("features")
        .fit(train)
    )
    ours = (np.asarray(model.transform(test)["prediction"]) == y[600:]).mean()
    sk = SkRF(n_estimators=30, max_depth=8, random_state=3).fit(x[:600], y[:600])
    theirs = (sk.predict(x[600:]) == y[600:]).mean()
    assert ours >= theirs - 0.07  # within striking distance of sklearn


def test_rf_regressor_quality(rng):
    from sklearn.ensemble import RandomForestRegressor as SkRF

    df, x, y = _reg_data(rng, n=800)
    train, test = df.iloc[:600], df.iloc[600:].reset_index(drop=True)
    # featureSubsetStrategy='all' to match sklearn's regression default
    # (Spark's 'auto' means onethird for regression); num_workers=2 so each
    # tree sees 300 rows like a reasonable shard
    model = (
        RandomForestRegressor(
            numTrees=30, maxDepth=8, maxBins=64, seed=1,
            featureSubsetStrategy="all", num_workers=2,
        )
        .setFeaturesCol("features")
        .fit(train)
    )
    pred = np.asarray(model.transform(test)["prediction"])
    sk = SkRF(n_estimators=30, max_depth=8, random_state=1).fit(x[:600], y[:600])
    sk_mse = np.mean((sk.predict(x[600:]) - y[600:]) ** 2)
    our_mse = np.mean((pred - y[600:]) ** 2)
    var = np.var(y[600:])
    assert our_mse < var * 0.1  # explains >90% of variance
    assert our_mse < sk_mse * 2.5


def test_rf_feature_subset_strategies():
    from spark_rapids_ml_tpu.models.tree import resolve_max_features

    assert resolve_max_features("auto", 100, True) == 10
    assert resolve_max_features("auto", 99, False) == 33
    assert resolve_max_features("all", 7, True) == 7
    assert resolve_max_features("sqrt", 64, False) == 8
    assert resolve_max_features("log2", 64, True) == 6
    assert resolve_max_features("onethird", 9, True) == 3
    assert resolve_max_features("5", 100, True) == 5
    assert resolve_max_features("0.5", 10, True) == 5
    with pytest.raises(ValueError):
        resolve_max_features("bogus", 10, True)


def test_rf_impurity_validation():
    with pytest.raises(ValueError, match="gini"):
        RandomForestClassifier(impurity="variance")
    with pytest.raises(ValueError, match="variance"):
        RandomForestRegressor(impurity="gini")
    RandomForestClassifier(impurity="entropy")  # ok


def test_rf_persistence(tmp_path, rng):
    df, x, y = _clf_data(rng, n=200)
    model = RandomForestClassifier(numTrees=5, maxDepth=4, seed=2).setFeaturesCol("features").fit(df)
    p = str(tmp_path / "rf")
    model.write().overwrite().save(p)
    loaded = RandomForestClassificationModel.load(p)
    np.testing.assert_array_equal(loaded.feature, model.feature)
    np.testing.assert_array_equal(loaded.threshold, model.threshold)
    np.testing.assert_array_equal(
        np.asarray(loaded.transform(df)["prediction"]),
        np.asarray(model.transform(df)["prediction"]),
    )


def test_rf_single_vector_predict(rng):
    df, x, y = _clf_data(rng, n=150)
    model = RandomForestClassifier(numTrees=10, maxDepth=5, seed=5).setFeaturesCol("features").fit(df)
    out = model.transform(df)
    assert model.predict(x[0]) == float(np.asarray(out["prediction"])[0])
    # native raw/probability single-vector surface (reference delegates to cpu())
    raw = model.predictRaw(x[0]).toArray()
    np.testing.assert_allclose(raw, np.stack(out["rawPrediction"].to_list())[0], rtol=1e-6)
    prob = model.predictProbability(x[0]).toArray()
    np.testing.assert_allclose(prob.sum(), 1.0, atol=1e-9)
    np.testing.assert_allclose(prob, np.stack(out["probability"].to_list())[0], rtol=1e-6)

    dfr, xr, yr = _reg_data(rng, n=150)
    mr = RandomForestRegressor(numTrees=10, maxDepth=5, seed=5).setFeaturesCol("features").fit(dfr)
    outr = mr.transform(dfr)
    np.testing.assert_allclose(mr.predict(xr[0]), np.asarray(outr["prediction"])[0], rtol=1e-6)


def test_rf_deterministic_with_seed(rng):
    df, _, _ = _clf_data(rng, n=150)
    m1 = RandomForestClassifier(numTrees=8, maxDepth=4, seed=11).setFeaturesCol("features").fit(df)
    m2 = RandomForestClassifier(numTrees=8, maxDepth=4, seed=11).setFeaturesCol("features").fit(df)
    np.testing.assert_array_equal(m1.feature, m2.feature)
    np.testing.assert_array_equal(m1.threshold, m2.threshold)


def test_rf_min_instances_and_gain(rng):
    df, _, _ = _clf_data(rng, n=150)
    # huge minInstancesPerNode forces shallow trees
    m = (
        RandomForestClassifier(numTrees=4, maxDepth=6, minInstancesPerNode=100, seed=1)
        .setFeaturesCol("features")
        .fit(df)
    )
    n_splits = int(np.sum(m.feature >= 0))
    m2 = RandomForestClassifier(numTrees=4, maxDepth=6, seed=1).setFeaturesCol("features").fit(df)
    assert n_splits < int(np.sum(m2.feature >= 0))


def test_rf_feature_subset_fraction_one():
    from spark_rapids_ml_tpu.models.tree import resolve_max_features

    # Spark grammar: "1.0" is a FRACTION (all features), "1" is a count
    assert resolve_max_features("1.0", 100, True) == 100
    assert resolve_max_features("1", 100, True) == 1


def test_rf_weight_col_changes_model(rng):
    df, x, y = _clf_data(rng, n=200, d=6, k=2)
    w = np.where(y == 0, 10.0, 0.1)  # heavily favor class 0
    dfw = df.copy()
    dfw["w"] = w
    m_plain = RandomForestClassifier(numTrees=6, maxDepth=4, seed=4).setFeaturesCol("features").fit(df)
    m_w = (
        RandomForestClassifier(numTrees=6, maxDepth=4, seed=4, weightCol="w")
        .setFeaturesCol("features")
        .fit(dfw)
    )
    # weighting must change the learned trees
    assert not np.array_equal(m_plain.node_stats, m_w.node_stats)
    # and not bias predictions AWAY from the upweighted class (the data is
    # near-separable, so the shift can be small — compare mean probability
    # with slack rather than flaky per-row prediction counts)
    prob_plain = np.stack(m_plain.transform(df)["probability"].to_numpy())[:, 0]
    prob_w = np.stack(m_w.transform(df)["probability"].to_numpy())[:, 0]
    assert prob_w.mean() >= prob_plain.mean() - 0.02


def test_rf_bootstrap_weight_applied_once(rng):
    # ADVICE r1 (high): with bootstrap=True the draw was proportional to w AND
    # the histogram stats were w-scaled -> w² weighting. Weighted mean of
    # {y=0,w=1; y=1,w=3} must be ~0.75 either way.
    n = 400
    y = (np.arange(n) % 2).astype(np.float64)
    w = np.where(y == 0, 1.0, 3.0)
    x = rng.normal(size=(n, 3))  # uninformative features -> root-level mean
    df = pd.DataFrame({"features": list(x), "label": y, "w": w})
    for bootstrap in (True, False):
        m = (
            RandomForestRegressor(
                numTrees=8, maxDepth=1, seed=3, weightCol="w", bootstrap=bootstrap,
                minInfoGain=1e9,  # forbid splits: every tree is a root stump
            )
            .setFeaturesCol("features")
            .fit(df)
        )
        pred = float(np.asarray(m.transform(df)["prediction"])[0])
        assert abs(pred - 0.75) < 0.05, f"bootstrap={bootstrap}: {pred}"


def test_rf_no_bootstrap_subsampling_diversifies(rng):
    df, _, _ = _clf_data(rng, n=300, d=6, k=2)
    m = (
        RandomForestClassifier(
            numTrees=6, maxDepth=4, seed=2, bootstrap=False, subsamplingRate=0.5, num_workers=1
        )
        .setFeaturesCol("features")
        .fit(df)
    )
    # trees trained on different half-samples must differ
    assert not np.array_equal(m.node_stats[0], m.node_stats[1])


def test_feature_importances(rng):
    # informative features dominate pure-noise ones; importances sum to 1
    import pandas as pd

    n, d = 600, 8
    x = rng.normal(size=(n, d))
    y = (x[:, 0] + 2 * x[:, 1] > 0).astype(np.float64)  # only features 0/1 matter
    df = pd.DataFrame({"features": list(x), "label": y})
    # featureSubsetStrategy="all": every node sees every feature, so the
    # importance concentration is deterministic in intent (with "auto"'s
    # sqrt(d)=3-of-8 subsets, noise features NECESSARILY win splits in the
    # ~36% of nodes whose subset misses both informative features, capping
    # the informative mass near 0.64 — correct behavior, weak test signal)
    m = (
        RandomForestClassifier(
            numTrees=10, maxDepth=5, seed=7, float32_inputs=False,
            featureSubsetStrategy="all",
        )
        .setFeaturesCol("features")
        .fit(df)
    )
    fi = np.asarray(m.featureImportances.toArray())
    assert fi.shape == (d,)
    np.testing.assert_allclose(fi.sum(), 1.0, rtol=1e-9)
    assert fi[[0, 1]].sum() > 0.8, f"informative mass too low: {fi}"
    assert fi[[0, 1]].min() > fi[2:].max()


def test_tree_json_reproduces_predictions(rng):
    # the portable per-tree JSON must reproduce the model's predictions exactly
    import json

    import pandas as pd

    n, d = 300, 5
    x = rng.normal(size=(n, d))
    y = x[:, 0] * 3 + x[:, 2] + 0.05 * rng.normal(size=n)
    df = pd.DataFrame({"features": list(x), "label": y})
    m = (
        RandomForestRegressor(numTrees=5, maxDepth=4, seed=3, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(df)
    )
    trees = [json.loads(s) for s in m.treesToJson()]
    assert len(trees) == 5

    def eval_tree(node, row):
        while "split_feature" in node:
            if row[node["split_feature"]] <= node["threshold"]:
                node = node["yes"]
            else:
                node = node["no"]
        return node["leaf_value"][0]

    preds_json = np.array(
        [np.mean([eval_tree(t, x[i]) for t in trees]) for i in range(50)]
    )
    preds_model = m.transform(df.iloc[:50])["prediction"].to_numpy()
    np.testing.assert_allclose(preds_json, preds_model, rtol=1e-6)


def test_to_debug_string(rng):
    import pandas as pd

    x = rng.normal(size=(100, 3))
    y = (x[:, 0] > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(x), "label": y})
    m = (
        RandomForestClassifier(numTrees=2, maxDepth=3, seed=1)
        .setFeaturesCol("features")
        .fit(df)
    )
    s = m.toDebugString()
    assert "numTrees=2" in s
    assert "Tree 0" in s and "Tree 1" in s
    assert "If (feature" in s and "Predict:" in s
