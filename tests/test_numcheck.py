#
# Unit family for the runtime numerics sanitizer
# (spark_rapids_ml_tpu/utils/numcheck.py): trip shape (typed NumericsError +
# flight-recorder event + recorded violation), allow_inf sentinels, dtype
# watermarks, disabled = zero-cost (None hook, nothing recorded), the report
# artifact ci/test.sh archives and gates on zero trips, snapshot/restore
# isolation (deliberate test trips never poison the CI gate), and the
# end-to-end boundaries: a k-means fit and a segmented GLM-style loop sweep
# clean under SRML_NUMCHECK=1, and a NaN injected into a segmented state is
# caught AT the boundary with solver/iteration attribution.
#
import json
import pathlib
import sys

import numpy as np
import pytest

ROOT = pathlib.Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from spark_rapids_ml_tpu import diagnostics  # noqa: E402
from spark_rapids_ml_tpu.errors import NumericsError, SrmlError  # noqa: E402
from spark_rapids_ml_tpu.utils import numcheck  # noqa: E402


@pytest.fixture()
def sanitizer(monkeypatch):
    """Isolated sanitizer state (the lockcheck fixture discipline): snapshot
    the process-global state, run against a clean slate, restore EXACTLY —
    the deliberate trips these tests seed must not poison the CI lane's
    numcheck report, and the lane's real observations must survive this
    file (the zero-trip gate would otherwise check a reset report)."""
    monkeypatch.setenv("SRML_NUMCHECK", "1")
    state = numcheck.snapshot()
    numcheck.reset()
    diagnostics.flight_recorder().reset()
    yield numcheck
    numcheck.restore(state)


# ------------------------------------------------------------- disabled ----


def test_disabled_hook_is_none_and_records_nothing(monkeypatch):
    monkeypatch.setenv("SRML_NUMCHECK", "0")
    # the zero-cost contract: no hook object at all — boundary sites hold a
    # None local and pay one `is not None` test per boundary
    assert numcheck.hook() is None
    assert numcheck.enabled() is False
    state = numcheck.snapshot()  # same isolation discipline as the fixture
    numcheck.reset()
    try:
        from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit  # noqa: F401

        assert numcheck.report()["enabled"] is False
        assert numcheck.checks() == 0 and numcheck.trips() == []
    finally:
        numcheck.restore(state)


# ----------------------------------------------------------------- trips ----


def test_trip_shape_typed_error_and_flight_recorder(sanitizer):
    with pytest.raises(NumericsError) as ei:
        numcheck.check(
            "t.stage", solver="glm", iteration=7, coef=np.array([1.0, np.nan, np.inf])
        )
    e = ei.value
    assert isinstance(e, SrmlError) and isinstance(e, ArithmeticError)
    assert e.stage == "t.stage" and e.solver == "glm" and e.iteration == 7
    assert e.value_name == "coef"
    assert "1 NaN / 1 Inf" in str(e)
    trips = numcheck.trips()
    assert len(trips) == 1
    t = trips[0]
    assert t["stage"] == "t.stage" and t["value"] == "coef"
    assert t["nan"] == 1 and t["inf"] == 1 and t["shape"] == [3]
    evs = [
        ev for ev in diagnostics.flight_recorder().events()
        if ev["kind"] == "numcheck.trip"
    ]
    assert len(evs) >= 1
    assert evs[-1]["stage"] == "t.stage" and evs[-1]["solver"] == "glm"


def test_allow_inf_sentinels_pass_but_nan_still_trips(sanitizer):
    numcheck.check("t.inf", allow_inf=True, d=np.array([np.inf, 1.0]))
    assert numcheck.trips() == []
    with pytest.raises(NumericsError):
        numcheck.check("t.inf", allow_inf=True, d=np.array([np.nan]))


def test_non_float_values_and_scalars(sanitizer):
    numcheck.check("t.int", ids=np.arange(5), n=3)
    with pytest.raises(NumericsError):
        numcheck.check("t.scalar", shift=float("nan"))
    assert numcheck.checks() == 2


def test_watermarks_record_every_dtype_seen(sanitizer):
    numcheck.check(
        "t.wm", watermark=np.dtype(np.float32),
        a=np.zeros(2, np.float64), b=np.zeros(2, np.int32),
    )
    wm = numcheck.watermarks()["t.wm"]
    assert wm == {"float32": 1, "float64": 1, "int32": 1}


# ---------------------------------------------------------------- report ----


def test_report_artifact_roundtrip(sanitizer, tmp_path):
    numcheck.check("t.ok", v=np.ones(3))
    path = tmp_path / "numcheck_report.json"
    assert numcheck.write_report(str(path)) == str(path)
    rep = json.loads(path.read_text())
    assert rep["enabled"] is True and rep["checks"] == 1
    assert rep["trips"] == [] and "t.ok" in rep["watermarks"]


def test_snapshot_restore_discards_fixture_trips(sanitizer):
    numcheck.check("t.before", v=np.ones(1))
    outer = numcheck.snapshot()
    with pytest.raises(NumericsError):
        numcheck.check("t.poison", v=np.array([np.nan]))
    assert len(numcheck.trips()) == 1
    numcheck.restore(outer)
    # the deliberate trip is gone; the prior observation survives
    assert numcheck.trips() == [] and numcheck.checks() == 1
    assert "t.before" in numcheck.watermarks()


# ------------------------------------------------------------ boundaries ----


def test_kmeans_fit_sweeps_clean_under_numcheck(sanitizer):
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit
    from spark_rapids_ml_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 6)).astype(np.float32)
    out = kmeans_fit(
        jnp.asarray(X), jnp.ones((256,), jnp.float32), jnp.asarray(X[:3]),
        mesh=get_mesh(), max_iter=8, tol=1e-7,
    )
    assert np.isfinite(float(out["inertia_"]))
    rep = numcheck.report()
    assert rep["trips"] == [] and rep["checks"] > 0
    assert "float32" in rep["watermarks"]["kmeans.iterate"]


def test_segmented_while_boundary_catches_injected_nan(sanitizer):
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import checkpoint as ckpt

    # state = (x, it): x goes NaN at inner iteration 3; the segment
    # boundary (every=2) must catch it AT the it=4 checkpoint with solver
    # attribution — not let it poison the store
    def cond(s):
        return s[1] < 8

    def body(s):
        x, it = s
        x = jnp.where(it == 3, jnp.nan, x * 1.5)
        return (x, it + 1)

    store = ckpt.CheckpointStore()
    with pytest.raises(NumericsError) as ei:
        ckpt.run_segmented_while(
            cond, body, (jnp.ones((4,), jnp.float32), jnp.asarray(0, jnp.int32)),
            it_of=lambda s: s[1], every=2, store=store, key="t",
            solver="toy", max_iter=8,
        )
    e = ei.value
    assert e.solver == "toy" and e.stage == "segment.toy"
    assert e.iteration == 4 and e.value_name.startswith("leaf")
    assert len(numcheck.trips()) == 1


def test_segmented_while_inf_sentinel_does_not_trip(sanitizer):
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import checkpoint as ckpt

    # GLM-style state carries a deliberate jnp.inf best-loss sentinel: the
    # boundary sweep is allow_inf and must stay quiet
    def cond(s):
        return s[1] < 4

    def body(s):
        return (s[0], s[1] + 1)

    store = ckpt.CheckpointStore()
    out = ckpt.run_segmented_while(
        cond, body, (jnp.asarray(jnp.inf, jnp.float32), jnp.asarray(0, jnp.int32)),
        it_of=lambda s: s[1], every=2, store=store, key="t2",
        solver="toy", max_iter=4,
    )
    assert not np.isfinite(float(out[0]))
    assert numcheck.trips() == [] and numcheck.checks() > 0


def test_streaming_kmeans_sweeps_clean_under_numcheck(sanitizer):
    # the streaming chunk + iterate boundaries fire and stay quiet on a
    # healthy out-of-core fit (stage names pinned for the report reader)
    pd = pytest.importorskip("pandas")
    from spark_rapids_ml_tpu import core as core_mod
    from spark_rapids_ml_tpu.models.clustering import KMeans

    rng = np.random.default_rng(3)
    df = pd.DataFrame({"features": list(rng.normal(size=(1500, 6)))})
    saved = {
        k: core_mod.config[k] for k in ("hbm_budget_bytes", "stream_chunk_rows")
    }
    try:
        core_mod.config["hbm_budget_bytes"] = 16_000  # forces the STREAM verdict
        core_mod.config["stream_chunk_rows"] = 512
        model = (
            KMeans(k=4, seed=7, maxIter=6, float32_inputs=False)
            .setFeaturesCol("features")
            .fit(df)
        )
    finally:
        core_mod.config.update(saved)
    assert np.all(np.isfinite(np.asarray(model.cluster_centers_)))
    rep = numcheck.report()
    assert rep["trips"] == []
    assert "kmeans_stream.chunk" in rep["watermarks"]
    assert "kmeans_stream.iterate" in rep["watermarks"]
