#
# Runtime/communicator layer tests — the analog of the reference's transport
# test (reference tests/test_ucx.py:36-99: build the communicator clique for
# 1..N ranks and assert a live allGather). Here: mesh construction, pad-and-mask
# global array assembly, PartitionDescriptor allgather through the rendezvous,
# and a live psum over the 8-device mesh via shard_map.
#
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_rapids_ml_tpu.parallel import (
    ROWS_AXIS,
    LocalRendezvous,
    PartitionDescriptor,
    TpuContext,
    get_mesh,
    make_global_rows,
    pad_rows,
)


def test_pad_rows():
    x = np.arange(10, dtype=np.float32).reshape(5, 2)
    xp, n = pad_rows(x, 4)
    assert n == 5
    assert xp.shape == (8, 2)
    np.testing.assert_array_equal(xp[5:], 0)
    xp2, n2 = pad_rows(x, 5)
    assert xp2.shape == (5, 2) and n2 == 5


def test_make_global_rows_weights_mask_padding(mesh8):
    x = np.ones((13, 3), dtype=np.float32)
    X, w, n_valid = make_global_rows(mesh8, x)
    assert n_valid == 13
    assert X.shape[0] % 8 == 0
    # weighted row count sees only valid rows
    assert float(jnp.sum(w)) == 13.0
    # weighted column sums ignore padding
    np.testing.assert_allclose(np.asarray(jnp.sum(X * w[:, None], axis=0)), [13, 13, 13])


def test_live_psum_over_mesh(mesh8):
    from spark_rapids_ml_tpu.parallel.mesh import shard_map

    x = np.arange(16, dtype=np.float32).reshape(16, 1)
    X, w, _ = make_global_rows(mesh8, x)

    @jax.jit
    def global_sum(X, w):
        def body(xb, wb):
            local = jnp.sum(xb * wb[:, None])
            return jnp.reshape(jax.lax.psum(local, ROWS_AXIS), (1,))

        return shard_map(
            body, mesh=mesh8, in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS)),
            out_specs=P(ROWS_AXIS),
        )(X, w)

    out = np.asarray(global_sum(X, w))
    np.testing.assert_allclose(out, np.full(8, x.sum()))


@pytest.mark.parametrize("nranks", [1, 2, 4])
def test_local_rendezvous_allgather(nranks):
    rvs = LocalRendezvous.create(nranks)
    results = [None] * nranks

    def work(r):
        results[r] = rvs[r].allgather(f"rank{r}")

    threads = [threading.Thread(target=work, args=(r,)) for r in range(nranks)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    for r in range(nranks):
        assert results[r] == [f"rank{i}" for i in range(nranks)]


def test_partition_descriptor_via_rendezvous():
    rvs = LocalRendezvous.create(2)
    out = [None, None]

    def work(r):
        out[r] = PartitionDescriptor.build([10 + r], total_cols=5, rank=r, rendezvous=rvs[r])

    threads = [threading.Thread(target=work, args=(r,)) for r in range(2)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    for r in range(2):
        assert out[r].m == 21
        assert out[r].n == 5
        assert out[r].parts_rank_size == [(0, 10), (1, 11)]
    assert out[0].rows_of(1) == 11
    assert out[1].row_offset_of(1) == 10


def test_partition_descriptor_single_controller():
    d = PartitionDescriptor.build([4, 4, 5], total_cols=3)
    assert d.m == 13 and d.n == 3
    assert d.rows_of(2) == 5 and d.row_offset_of(2) == 8


def test_tpu_context_single_process():
    with TpuContext(0, 1) as ctx:
        assert ctx.mesh is not None
        assert ctx.mesh.devices.size >= 1


def test_distributed_transform_matches_single_device(rng):
    # >= distributed_transform_min_rows rows: the batch is row-sharded over the
    # 8-device mesh with replicated model state; result must equal the
    # single-device path bit-for-bit (row-parallel programs, no reductions)
    import pandas as pd

    from spark_rapids_ml_tpu import core as core_mod
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    n, d = 40000, 8
    x = rng.normal(size=(n, d)).astype(np.float64)
    y = (x[:, 0] > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(x), "label": y})
    m = LogisticRegression(maxIter=30, float32_inputs=False).setFeaturesCol("features").fit(df)

    assert n >= core_mod.config["distributed_transform_min_rows"]
    out_mesh = m.transform(df)
    saved = core_mod.config["distributed_transform_min_rows"]
    try:
        core_mod.config["distributed_transform_min_rows"] = 1 << 60  # force single-device
        out_single = m.transform(df)
    finally:
        core_mod.config["distributed_transform_min_rows"] = saved
    np.testing.assert_array_equal(
        np.asarray(out_mesh["prediction"]), np.asarray(out_single["prediction"])
    )
    def _mat(col):
        return np.stack([v.toArray() if hasattr(v, "toArray") else np.asarray(v) for v in col])

    pm = _mat(out_mesh["probability"])
    ps = _mat(out_single["probability"])
    np.testing.assert_allclose(pm, ps, rtol=1e-12, atol=1e-15)


def test_distributed_transform_rf_and_kmeans(rng):
    import pandas as pd

    from spark_rapids_ml_tpu import core as core_mod
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.models.regression import RandomForestRegressor

    n, d = 33000, 6
    x = rng.normal(size=(n, d)).astype(np.float64)
    y = x[:, 0] * 2 + rng.normal(size=n) * 0.1
    df = pd.DataFrame({"features": list(x), "label": y})

    km = KMeans(k=5, maxIter=5, seed=1).setFeaturesCol("features").fit(df)
    rf = (
        RandomForestRegressor(numTrees=4, maxDepth=4, seed=1)
        .setFeaturesCol("features")
        .fit(df)
    )
    saved = core_mod.config["distributed_transform_min_rows"]
    out_km_mesh = km.transform(df)
    out_rf_mesh = rf.transform(df)
    try:
        core_mod.config["distributed_transform_min_rows"] = 1 << 60
        out_km_single = km.transform(df)
        out_rf_single = rf.transform(df)
    finally:
        core_mod.config["distributed_transform_min_rows"] = saved
    np.testing.assert_array_equal(
        np.asarray(out_km_mesh["prediction"]), np.asarray(out_km_single["prediction"])
    )
    np.testing.assert_allclose(
        np.asarray(out_rf_mesh["prediction"]),
        np.asarray(out_rf_single["prediction"]),
        rtol=1e-12,
    )


def test_barrier_rendezvous_adapter():
    # duck-typed BarrierTaskContext: the adapter exposes the framework's
    # allgather contract over Spark's allGather (reference cuml_context.py:80-103)
    from spark_rapids_ml_tpu.parallel import BarrierRendezvous

    class FakeBarrierCtx:
        def __init__(self):
            self.sent = []

        def partitionId(self):
            return 2

        def getTaskInfos(self):
            return [object()] * 4

        def allGather(self, payload):
            self.sent.append(payload)
            return [f"r{i}:{payload}" for i in range(4)]

    ctx = FakeBarrierCtx()
    rdv = BarrierRendezvous(ctx)
    assert rdv.rank == 2 and rdv.nranks == 4
    out = rdv.allgather("hello")
    assert out == ["r0:hello", "r1:hello", "r2:hello", "r3:hello"]
    rdv.barrier()
    assert ctx.sent == ["hello", ""]


@pytest.mark.parametrize("rows", [[5, 17, 2], [0, 3], [4, 0, 0]])
def test_allgather_ndarray_ragged_row_counts(rows):
    # ragged per-rank row counts force the chunk-count AGREEMENT round to do
    # real work (every rank must adopt the max), and zero-row ranks must
    # still complete every round — all under the new per-round deadline
    # (timeout_s set, so a desynced rank would fail typed, not hang)
    from spark_rapids_ml_tpu.parallel.context import allgather_ndarray

    nranks = len(rows)
    rvs = LocalRendezvous.create(nranks, timeout_s=30.0)
    arrs = [
        (np.arange(r * 4, dtype=np.float64).reshape(r, 4) + 1000.0 * i)
        for i, r in enumerate(rows)
    ]
    results = [None] * nranks

    def work(r):
        # chunk_bytes=64 -> 2 rows per chunk: the 17-row rank needs 9 rounds
        results[r] = allgather_ndarray(rvs[r], arrs[r], chunk_bytes=64)

    threads = [threading.Thread(target=work, args=(r,)) for r in range(nranks)]
    [t.start() for t in threads]
    [t.join(timeout=60) for t in threads]
    assert not any(t.is_alive() for t in threads)
    for r in range(nranks):
        assert results[r] is not None, f"rank {r} did not finish"
        assert len(results[r]) == nranks
        for i in range(nranks):
            assert results[r][i].shape == (rows[i], 4)
            np.testing.assert_array_equal(results[r][i], arrs[i])


def test_allgather_ndarray_zero_row_rank_chunk_agreement():
    # the zero-row rank's local chunk count is 1; it must still participate
    # in all 5 of the big rank's chunk rounds or every peer would hang —
    # regression pin for the chunk-count agreement round
    from spark_rapids_ml_tpu.parallel.context import allgather_ndarray

    rvs = LocalRendezvous.create(2, timeout_s=20.0)
    arrs = [np.zeros((0, 8)), np.arange(80, dtype=np.float64).reshape(10, 8)]
    results = [None, None]

    def work(r):
        results[r] = allgather_ndarray(rvs[r], arrs[r], chunk_bytes=128)  # 2 rows/chunk

    threads = [threading.Thread(target=work, args=(r,)) for r in range(2)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert not any(t.is_alive() for t in threads)
    for r in range(2):
        assert results[r][0].shape == (0, 8)
        np.testing.assert_array_equal(results[r][1], arrs[1])
    # both ranks ran the same number of rounds (agreement + 5 chunk rounds each)
    assert rvs[0]._round == rvs[1]._round


def test_allgather_ndarray_chunked(tmp_path):
    # broadcast_chunk_bytes bounds each control-plane round's payload; the
    # reassembled arrays must be identical to the unchunked gather
    import uuid

    from spark_rapids_ml_tpu.parallel import FileRendezvous
    from spark_rapids_ml_tpu.parallel.context import allgather_ndarray

    # single-rank rendezvous keeps this a unit test (chunk logic is rank-local)
    rdv = FileRendezvous(0, 1, str(tmp_path), run_id=uuid.uuid4().hex)
    arr = np.arange(1000, dtype=np.float64).reshape(100, 10)
    out = allgather_ndarray(rdv, arr, chunk_bytes=800)  # ~10 rows per chunk
    assert len(out) == 1
    np.testing.assert_array_equal(out[0], arr)
    # round counter advanced by more than one round (it actually chunked)
    assert rdv._round > 3
