#
# Runtime/communicator layer tests — the analog of the reference's transport
# test (reference tests/test_ucx.py:36-99: build the communicator clique for
# 1..N ranks and assert a live allGather). Here: mesh construction, pad-and-mask
# global array assembly, PartitionDescriptor allgather through the rendezvous,
# and a live psum over the 8-device mesh via shard_map.
#
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_rapids_ml_tpu.parallel import (
    ROWS_AXIS,
    LocalRendezvous,
    PartitionDescriptor,
    TpuContext,
    get_mesh,
    make_global_rows,
    pad_rows,
)


def test_pad_rows():
    x = np.arange(10, dtype=np.float32).reshape(5, 2)
    xp, n = pad_rows(x, 4)
    assert n == 5
    assert xp.shape == (8, 2)
    np.testing.assert_array_equal(xp[5:], 0)
    xp2, n2 = pad_rows(x, 5)
    assert xp2.shape == (5, 2) and n2 == 5


def test_make_global_rows_weights_mask_padding(mesh8):
    x = np.ones((13, 3), dtype=np.float32)
    X, w, n_valid = make_global_rows(mesh8, x)
    assert n_valid == 13
    assert X.shape[0] % 8 == 0
    # weighted row count sees only valid rows
    assert float(jnp.sum(w)) == 13.0
    # weighted column sums ignore padding
    np.testing.assert_allclose(np.asarray(jnp.sum(X * w[:, None], axis=0)), [13, 13, 13])


def test_live_psum_over_mesh(mesh8):
    from spark_rapids_ml_tpu.parallel.mesh import shard_map

    x = np.arange(16, dtype=np.float32).reshape(16, 1)
    X, w, _ = make_global_rows(mesh8, x)

    @jax.jit
    def global_sum(X, w):
        def body(xb, wb):
            local = jnp.sum(xb * wb[:, None])
            return jnp.reshape(jax.lax.psum(local, ROWS_AXIS), (1,))

        return shard_map(
            body, mesh=mesh8, in_specs=(P(ROWS_AXIS, None), P(ROWS_AXIS)),
            out_specs=P(ROWS_AXIS),
        )(X, w)

    out = np.asarray(global_sum(X, w))
    np.testing.assert_allclose(out, np.full(8, x.sum()))


@pytest.mark.parametrize("nranks", [1, 2, 4])
def test_local_rendezvous_allgather(nranks):
    rvs = LocalRendezvous.create(nranks)
    results = [None] * nranks

    def work(r):
        results[r] = rvs[r].allgather(f"rank{r}")

    threads = [threading.Thread(target=work, args=(r,)) for r in range(nranks)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    for r in range(nranks):
        assert results[r] == [f"rank{i}" for i in range(nranks)]


def test_partition_descriptor_via_rendezvous():
    rvs = LocalRendezvous.create(2)
    out = [None, None]

    def work(r):
        out[r] = PartitionDescriptor.build([10 + r], total_cols=5, rank=r, rendezvous=rvs[r])

    threads = [threading.Thread(target=work, args=(r,)) for r in range(2)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    for r in range(2):
        assert out[r].m == 21
        assert out[r].n == 5
        assert out[r].parts_rank_size == [(0, 10), (1, 11)]
    assert out[0].rows_of(1) == 11
    assert out[1].row_offset_of(1) == 10


def test_partition_descriptor_single_controller():
    d = PartitionDescriptor.build([4, 4, 5], total_cols=3)
    assert d.m == 13 and d.n == 3
    assert d.rows_of(2) == 5 and d.row_offset_of(2) == 8


def test_tpu_context_single_process():
    with TpuContext(0, 1) as ctx:
        assert ctx.mesh is not None
        assert ctx.mesh.devices.size >= 1


def test_distributed_transform_matches_single_device(rng):
    # >= distributed_transform_min_rows rows: the batch is row-sharded over the
    # 8-device mesh with replicated model state; result must equal the
    # single-device path bit-for-bit (row-parallel programs, no reductions)
    import pandas as pd

    from spark_rapids_ml_tpu import core as core_mod
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    n, d = 40000, 8
    x = rng.normal(size=(n, d)).astype(np.float64)
    y = (x[:, 0] > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(x), "label": y})
    m = LogisticRegression(maxIter=30, float32_inputs=False).setFeaturesCol("features").fit(df)

    assert n >= core_mod.config["distributed_transform_min_rows"]
    out_mesh = m.transform(df)
    saved = core_mod.config["distributed_transform_min_rows"]
    try:
        core_mod.config["distributed_transform_min_rows"] = 1 << 60  # force single-device
        out_single = m.transform(df)
    finally:
        core_mod.config["distributed_transform_min_rows"] = saved
    np.testing.assert_array_equal(
        np.asarray(out_mesh["prediction"]), np.asarray(out_single["prediction"])
    )
    def _mat(col):
        return np.stack([v.toArray() if hasattr(v, "toArray") else np.asarray(v) for v in col])

    pm = _mat(out_mesh["probability"])
    ps = _mat(out_single["probability"])
    np.testing.assert_allclose(pm, ps, rtol=1e-12, atol=1e-15)


def test_distributed_transform_rf_and_kmeans(rng):
    import pandas as pd

    from spark_rapids_ml_tpu import core as core_mod
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.models.regression import RandomForestRegressor

    n, d = 33000, 6
    x = rng.normal(size=(n, d)).astype(np.float64)
    y = x[:, 0] * 2 + rng.normal(size=n) * 0.1
    df = pd.DataFrame({"features": list(x), "label": y})

    km = KMeans(k=5, maxIter=5, seed=1).setFeaturesCol("features").fit(df)
    rf = (
        RandomForestRegressor(numTrees=4, maxDepth=4, seed=1)
        .setFeaturesCol("features")
        .fit(df)
    )
    saved = core_mod.config["distributed_transform_min_rows"]
    out_km_mesh = km.transform(df)
    out_rf_mesh = rf.transform(df)
    try:
        core_mod.config["distributed_transform_min_rows"] = 1 << 60
        out_km_single = km.transform(df)
        out_rf_single = rf.transform(df)
    finally:
        core_mod.config["distributed_transform_min_rows"] = saved
    np.testing.assert_array_equal(
        np.asarray(out_km_mesh["prediction"]), np.asarray(out_km_single["prediction"])
    )
    np.testing.assert_allclose(
        np.asarray(out_rf_mesh["prediction"]),
        np.asarray(out_rf_single["prediction"]),
        rtol=1e-12,
    )


def test_barrier_rendezvous_adapter():
    # duck-typed BarrierTaskContext: the adapter exposes the framework's
    # allgather contract over Spark's allGather (reference cuml_context.py:80-103)
    from spark_rapids_ml_tpu.parallel import BarrierRendezvous

    class FakeBarrierCtx:
        def __init__(self):
            self.sent = []

        def partitionId(self):
            return 2

        def getTaskInfos(self):
            return [object()] * 4

        def allGather(self, payload):
            self.sent.append(payload)
            return [f"r{i}:{payload}" for i in range(4)]

    ctx = FakeBarrierCtx()
    rdv = BarrierRendezvous(ctx)
    assert rdv.rank == 2 and rdv.nranks == 4
    out = rdv.allgather("hello")
    assert out == ["r0:hello", "r1:hello", "r2:hello", "r3:hello"]
    rdv.barrier()
    assert ctx.sent == ["hello", ""]


@pytest.mark.parametrize("rows", [[5, 17, 2], [0, 3], [4, 0, 0]])
def test_allgather_ndarray_ragged_row_counts(rows):
    # ragged per-rank row counts force the chunk-count AGREEMENT round to do
    # real work (every rank must adopt the max), and zero-row ranks must
    # still complete every round — all under the new per-round deadline
    # (timeout_s set, so a desynced rank would fail typed, not hang)
    from spark_rapids_ml_tpu.parallel.context import allgather_ndarray

    nranks = len(rows)
    rvs = LocalRendezvous.create(nranks, timeout_s=30.0)
    arrs = [
        (np.arange(r * 4, dtype=np.float64).reshape(r, 4) + 1000.0 * i)
        for i, r in enumerate(rows)
    ]
    results = [None] * nranks

    def work(r):
        # chunk_bytes=64 -> 2 rows per chunk: the 17-row rank needs 9 rounds
        results[r] = allgather_ndarray(rvs[r], arrs[r], chunk_bytes=64)

    threads = [threading.Thread(target=work, args=(r,)) for r in range(nranks)]
    [t.start() for t in threads]
    [t.join(timeout=60) for t in threads]
    assert not any(t.is_alive() for t in threads)
    for r in range(nranks):
        assert results[r] is not None, f"rank {r} did not finish"
        assert len(results[r]) == nranks
        for i in range(nranks):
            assert results[r][i].shape == (rows[i], 4)
            np.testing.assert_array_equal(results[r][i], arrs[i])


def test_allgather_ndarray_zero_row_rank_chunk_agreement():
    # the zero-row rank's local chunk count is 1; it must still participate
    # in all 5 of the big rank's chunk rounds or every peer would hang —
    # regression pin for the chunk-count agreement round
    from spark_rapids_ml_tpu.parallel.context import allgather_ndarray

    rvs = LocalRendezvous.create(2, timeout_s=20.0)
    arrs = [np.zeros((0, 8)), np.arange(80, dtype=np.float64).reshape(10, 8)]
    results = [None, None]

    def work(r):
        results[r] = allgather_ndarray(rvs[r], arrs[r], chunk_bytes=128)  # 2 rows/chunk

    threads = [threading.Thread(target=work, args=(r,)) for r in range(2)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert not any(t.is_alive() for t in threads)
    for r in range(2):
        assert results[r][0].shape == (0, 8)
        np.testing.assert_array_equal(results[r][1], arrs[1])
    # both ranks ran the same number of rounds (agreement + 5 chunk rounds each)
    assert rvs[0]._round == rvs[1]._round


def test_allgather_ndarray_chunked(tmp_path):
    # broadcast_chunk_bytes bounds each control-plane round's payload; the
    # reassembled arrays must be identical to the unchunked gather
    import uuid

    from spark_rapids_ml_tpu.parallel import FileRendezvous
    from spark_rapids_ml_tpu.parallel.context import allgather_ndarray

    # single-rank rendezvous keeps this a unit test (chunk logic is rank-local)
    rdv = FileRendezvous(0, 1, str(tmp_path), run_id=uuid.uuid4().hex)
    arr = np.arange(1000, dtype=np.float64).reshape(100, 10)
    out = allgather_ndarray(rdv, arr, chunk_bytes=800)  # ~10 rows per chunk
    assert len(out) == 1
    np.testing.assert_array_equal(out[0], arr)
    # round counter advanced by more than one round (it actually chunked)
    assert rdv._round > 3


# ------------------------------------------------ hierarchical / sub-mesh ---
#
# The sub-mesh placement substrate (docs/scheduling.md "2-D placement"):
# build_mesh composes an ICI `rows` axis with a DCN axis across process
# groups; submesh carves contiguous chip runs; survivor_mesh composes with
# both so a sweep shard that loses a host re-meshes its OWN carve.


class _FakeDev:
    """Stand-in device for topology-only mesh math (jax.sharding.Mesh takes
    any object; no program ever runs on these)."""

    def __init__(self, did, process_index):
        self.id = did
        self.process_index = process_index

    def __repr__(self):  # pragma: no cover - debug aid
        return f"fake(d{self.id}@p{self.process_index})"


def _fake_pool(n_procs, per_proc):
    return [
        _FakeDev(p * per_proc + i, p) for p in range(n_procs) for i in range(per_proc)
    ]


def test_get_mesh_divisibility_is_typed_and_names_both_sides():
    from spark_rapids_ml_tpu.errors import MeshTopologyError, SrmlError

    with pytest.raises(MeshTopologyError) as ei:
        get_mesh(3)  # 8-device pool: 3 does not divide it
    assert isinstance(ei.value, SrmlError)
    assert ei.value.requested == 3
    assert ei.value.available == 8
    assert "num_workers=3" in str(ei.value) and "8-device" in str(ei.value)
    with pytest.raises(MeshTopologyError):
        get_mesh(0)
    with pytest.raises(MeshTopologyError):
        get_mesh(16)
    assert get_mesh(4).devices.size == 4  # divisors still build


def test_build_mesh_flat_default_and_2d_topology():
    from spark_rapids_ml_tpu.parallel import DCN_AXIS, build_mesh

    flat = build_mesh()
    assert flat.axis_names == (ROWS_AXIS,)
    assert flat.devices.size == 8

    pool = _fake_pool(n_procs=2, per_proc=4)
    m = build_mesh({"dcn": 2, "rows": 4}, devices=pool)
    assert m.axis_names == (DCN_AXIS, ROWS_AXIS)
    assert m.devices.shape == (2, 4)
    # each DCN row is ONE process group's ICI-connected chips
    for row in m.devices:
        assert len({d.process_index for d in row}) == 1

    # "auto" axes: dcn defaults to the process-group count
    auto = build_mesh({"dcn": 0}, devices=pool)
    assert auto.devices.shape == (2, 4)
    rows_only = build_mesh({"rows": 2}, devices=pool)
    assert rows_only.devices.shape == (4, 2)


def test_build_mesh_rejects_bad_topologies():
    from spark_rapids_ml_tpu.errors import MeshTopologyError
    from spark_rapids_ml_tpu.parallel import build_mesh

    pool = _fake_pool(n_procs=2, per_proc=4)
    with pytest.raises(MeshTopologyError) as ei:
        build_mesh({"dcn": 3, "rows": 4}, devices=pool)  # 12 != 8
    assert ei.value.available == 8
    assert ei.value.topology == {"dcn": 3, "rows": 4}
    with pytest.raises(MeshTopologyError):
        build_mesh({"ici": 8}, devices=pool)  # unknown axis name


def test_build_mesh_reads_config_topology_knob():
    from spark_rapids_ml_tpu import core as core_mod
    from spark_rapids_ml_tpu.parallel import DCN_AXIS, build_mesh

    saved = core_mod.config["mesh_topology"]
    core_mod.config["mesh_topology"] = {"dcn": 2, "rows": 4}
    try:
        m = build_mesh()  # deployment-wide default from config
        assert m.axis_names == (DCN_AXIS, ROWS_AXIS)
        assert m.devices.shape == (2, 4)
        flat = build_mesh({})  # an explicit empty topology wins over config
        assert flat.axis_names == (ROWS_AXIS,)
    finally:
        core_mod.config["mesh_topology"] = saved


def test_submesh_carves_contiguous_runs_only():
    from spark_rapids_ml_tpu.errors import MeshTopologyError
    from spark_rapids_ml_tpu.parallel import submesh

    mesh = get_mesh(8)
    flat = list(mesh.devices.flatten())

    first4 = submesh(mesh, 4)
    assert first4.axis_names == (ROWS_AXIS,)
    assert list(first4.devices.flatten()) == flat[:4]

    right = submesh(mesh, [4, 5, 6, 7])
    assert list(right.devices.flatten()) == flat[4:]
    by_dev = submesh(mesh, flat[2:5])  # device objects work too
    assert list(by_dev.devices.flatten()) == flat[2:5]

    with pytest.raises(MeshTopologyError):
        submesh(mesh, [0, 2])  # gapped: ICI run broken
    with pytest.raises(MeshTopologyError):
        submesh(mesh, [6, 7, 8])  # out of range
    with pytest.raises(MeshTopologyError):
        submesh(mesh, 9)  # wider than the pool
    with pytest.raises(MeshTopologyError):
        submesh(mesh, [])  # empty carve


def test_submesh_of_hierarchical_mesh_and_survivor_composition():
    from spark_rapids_ml_tpu.parallel import DCN_AXIS, build_mesh, submesh

    pool = _fake_pool(n_procs=2, per_proc=4)
    m2d = build_mesh({"dcn": 2, "rows": 4}, devices=pool)

    # carve one DCN row (one host's chips) as a 1-D rows sub-mesh
    row0 = submesh(m2d, 4)
    assert row0.axis_names == (ROWS_AXIS,)
    assert [d.process_index for d in row0.devices.flatten()] == [0] * 4

    # PR-6 recovery composes with the carve: losing a fictional process
    # keeps the carve; losing the carve's own host raises (nothing left)
    from spark_rapids_ml_tpu.errors import MeshTopologyError
    from spark_rapids_ml_tpu.parallel import survivor_mesh

    same = survivor_mesh(row0, {9})
    assert list(same.devices.flatten()) == list(row0.devices.flatten())
    with pytest.raises(MeshTopologyError):
        survivor_mesh(row0, {0})

    # 2-D mesh, whole DCN row dies: hierarchy survives intact
    kept = survivor_mesh(m2d, {1})
    assert kept.axis_names == (DCN_AXIS, ROWS_AXIS)
    assert kept.devices.shape == (1, 4)
    assert all(d.process_index == 0 for d in kept.devices.flatten())

    # partial row death degrades to the flat 1-D survivors (a ragged 2-D
    # grid is not a mesh): each DCN row here spans TWO processes, so losing
    # one process leaves its row half-alive
    ragged_pool = _fake_pool(n_procs=4, per_proc=2)
    m24 = build_mesh({"dcn": 2, "rows": 4}, devices=ragged_pool)
    flatd = survivor_mesh(m24, {3})
    assert flatd.axis_names == (ROWS_AXIS,)
    assert flatd.devices.size == 6


def test_chip_scope_pins_default_devices_context_locally():
    from spark_rapids_ml_tpu.parallel import (
        chip_scope,
        current_chip_scope,
        default_devices,
    )

    pool = default_devices()
    seen = {}

    def worker():
        # a sibling thread must NOT see the main thread's pin
        seen["other"] = list(default_devices())

    with chip_scope(pool[4:]):
        assert current_chip_scope() == tuple(pool[4:])
        assert default_devices() == pool[4:]
        assert get_mesh().devices.size == 4  # downstream mesh calls follow
        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=10)
    assert seen["other"] == pool
    assert current_chip_scope() is None
    assert default_devices() == pool


def test_shard_map_fold_grid_on_carved_submesh(mesh8):
    # the SPMD-batched sweep substrate: a vmapped fold grid under shard_map
    # over a CARVED sub-mesh computes exactly what plain numpy does on the
    # same rows — folds batch INSIDE the shard body, collectives stay on the
    # sub-mesh's own `rows` axis
    from jax.sharding import NamedSharding

    from spark_rapids_ml_tpu.parallel import submesh
    from spark_rapids_ml_tpu.parallel.mesh import row_sharding, shard_map

    sub = submesh(mesh8, 4)
    n_rows = sub.devices.size * 2
    x = np.arange(n_rows * 3, dtype=np.float32).reshape(n_rows, 3)
    masks = np.stack([
        np.tile(np.array([1.0, 0.0], np.float32), n_rows // 2),
        np.tile(np.array([0.0, 1.0], np.float32), n_rows // 2),
    ])  # (2 folds, n_rows)

    X = jax.device_put(x, row_sharding(sub, 2))
    M = jax.device_put(masks, NamedSharding(sub, P(None, ROWS_AXIS)))

    def body(xs, ms):
        def one_fold(m):  # xs: (local_rows, 3), m: (local_rows,)
            return jax.lax.psum(jnp.sum(xs * m[:, None]), ROWS_AXIS)

        return jax.vmap(one_fold)(ms)

    got = np.asarray(
        shard_map(
            body, mesh=sub,
            in_specs=(P(ROWS_AXIS, None), P(None, ROWS_AXIS)),
            out_specs=P(),
        )(X, M)
    )
    want = (x[None, :, :] * masks[:, :, None]).sum(axis=(1, 2))
    np.testing.assert_allclose(got, want, rtol=1e-6)
