#
# Unit family for the runtime lock-order sanitizer
# (spark_rapids_ml_tpu/utils/lockcheck.py): inversion detected, same-order
# clean, disabled = zero-cost no-op (plain threading primitives), re-entrant
# RLock clean, condition wait-time excluded from holds, long-hold watermark,
# flight-recorder event shape, and the report artifact ci/test.sh archives.
#
import json
import pathlib
import sys
import threading
import time

import pytest

ROOT = pathlib.Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from spark_rapids_ml_tpu import diagnostics  # noqa: E402
from spark_rapids_ml_tpu.utils import lockcheck  # noqa: E402


@pytest.fixture()
def sanitizer(monkeypatch):
    """Isolated sanitizer state: snapshot the process-global graph, run the
    test against a clean slate, then restore the snapshot EXACTLY — the
    deliberate inversions these tests seed must not poison the CI lane's
    lockcheck report, and the lane's real observations must survive this
    file (the zero-inversion gate would otherwise check an empty report)."""
    monkeypatch.setenv("SRML_LOCKCHECK", "1")
    state = lockcheck.snapshot()
    lockcheck.reset()
    diagnostics.flight_recorder().reset()
    yield lockcheck
    lockcheck.restore(state)


# ------------------------------------------------------------- disabled ----


def test_disabled_returns_plain_threading_primitives(monkeypatch):
    monkeypatch.setenv("SRML_LOCKCHECK", "0")
    lock = lockcheck.make_lock("t.disabled")
    rlock = lockcheck.make_lock("t.disabled_r", "rlock")
    cond = lockcheck.make_condition("t.disabled_c")
    # the zero-cost contract: no wrapper object at all
    assert type(lock) is type(threading.Lock())
    assert type(rlock) is type(threading.RLock())
    assert isinstance(cond, threading.Condition)
    assert not isinstance(lock, lockcheck.CheckedLock)
    state = lockcheck.snapshot()  # same isolation discipline as the fixture
    lockcheck.reset()
    try:
        with lock:
            pass
        assert lockcheck.violations() == []
        assert lockcheck.report()["enabled"] is False
    finally:
        lockcheck.restore(state)


# ------------------------------------------------------------ inversions ---


def test_inversion_detected_single_thread(sanitizer):
    a = lockcheck.make_lock("t.A")
    b = lockcheck.make_lock("t.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    vs = lockcheck.violations()
    assert [v["kind"] for v in vs] == ["inversion"]
    assert vs[0]["lock"] == "t.A" and vs[0]["held"] == "t.B"
    assert lockcheck.report()["inversions"][0]["lock"] == "t.A"


def test_inversion_detected_across_threads(sanitizer):
    a = lockcheck.make_lock("t.A")
    b = lockcheck.make_lock("t.B")
    with a:
        with b:
            pass

    def reverse():
        with b:
            with a:
                pass

    t = threading.Thread(target=reverse, daemon=True)
    t.start()
    t.join(10.0)
    assert [v["kind"] for v in lockcheck.violations()] == ["inversion"]


def test_inversion_does_not_eat_forward_edges(sanitizer):
    # regression: one inversion used to stop the scan of the remaining held
    # locks, so the B->C nesting observed in the same acquisition was never
    # recorded and a later genuine C->B inversion passed clean
    a = lockcheck.make_lock("t.A")
    b = lockcheck.make_lock("t.B")
    c = lockcheck.make_lock("t.C")
    with c:
        with a:
            pass
    with a:
        with b:
            with c:  # inversion vs A — must STILL record the B->C edge
                pass
    with c:
        with b:  # genuine ABBA against the observed B->C order
            pass
    vs = [(v["lock"], v["held"]) for v in lockcheck.violations()]
    assert ("t.C", "t.A") in vs and ("t.B", "t.C") in vs


def test_same_order_is_clean(sanitizer):
    a = lockcheck.make_lock("t.A")
    b = lockcheck.make_lock("t.B")
    for _ in range(5):
        with a:
            with b:
                pass
    assert lockcheck.violations() == []
    assert lockcheck.report()["edges"] == ["t.A -> t.B"]


def test_reentrant_rlock_is_clean(sanitizer):
    r = lockcheck.make_lock("t.R", "rlock")
    with r:
        with r:
            pass
    assert lockcheck.violations() == []
    # re-entry is not an edge either
    assert lockcheck.report()["edges"] == []


# ------------------------------------------------------------- condition ---


def test_condition_wait_time_is_not_hold_time(sanitizer, monkeypatch):
    import spark_rapids_ml_tpu.core as core

    monkeypatch.setitem(core.config, "lockcheck_long_hold_ms", 20.0)
    cond = lockcheck.make_condition("t.C")
    with cond:
        cond.wait(0.1)  # wait releases through _release_save: clock pauses
    assert lockcheck.violations() == []


def test_condition_notify_roundtrip(sanitizer):
    cond = lockcheck.make_condition("t.C")
    got = []

    def consumer():
        with cond:
            while not got:
                cond.wait(1.0)

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    time.sleep(0.02)
    with cond:
        got.append(1)
        cond.notify_all()
    t.join(10.0)
    assert not t.is_alive()
    assert lockcheck.violations() == []


# -------------------------------------------------------------- long hold --


def test_long_hold_watermark(sanitizer, monkeypatch):
    import spark_rapids_ml_tpu.core as core

    monkeypatch.setitem(core.config, "lockcheck_long_hold_ms", 10.0)
    lock = lockcheck.make_lock("t.slow")
    with lock:
        time.sleep(0.05)
    vs = lockcheck.violations()
    assert [v["kind"] for v in vs] == ["long_hold"]
    assert vs[0]["lock"] == "t.slow" and vs[0]["hold_s"] >= 0.04
    assert lockcheck.report()["max_hold_s"]["t.slow"] >= 0.04


# ------------------------------------------------- flight-recorder events --


def test_inversion_is_flight_recorder_visible(sanitizer):
    """Acceptance: a deliberately-inverted fixture produces a
    flight-recorder-visible violation with the pinned event shape."""
    a = lockcheck.make_lock("t.A")
    b = lockcheck.make_lock("t.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    evs = [
        e for e in diagnostics.flight_recorder().events()
        if e["kind"] == "lockcheck.inversion"
    ]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["lock"] == "t.A" and ev["held"] == "t.B"
    assert ev["thread"] and "t" in ev and "rank" in ev
    assert isinstance(ev["first_site"], list) and ev["first_site"]


# ----------------------------------------------------------------- report --


def test_write_report_artifact(sanitizer, tmp_path):
    a = lockcheck.make_lock("t.A")
    with a:
        pass
    path = tmp_path / "lockcheck_report.json"
    assert lockcheck.write_report(str(path)) == str(path)
    rep = json.loads(path.read_text())
    assert rep["enabled"] is True
    assert "t.A" in rep["locks"]
    assert rep["inversions"] == [] and rep["long_holds"] == []


def test_framework_locks_are_checked_when_enabled(sanitizer):
    # construction through the factory inside framework modules picks the
    # sanitizer up: a fresh ledger's locks are CheckedLocks with static ids
    from spark_rapids_ml_tpu.scheduler.ledger import HbmLedger

    ledger = HbmLedger()
    assert isinstance(ledger._lock, lockcheck.CheckedLock)
    assert ledger._lock.name == "scheduler.ledger.HbmLedger._lock"
    r = ledger.reserve("fixture", "fit", 1024)
    ledger.release(r)
    assert all(v["kind"] != "inversion" for v in lockcheck.violations())
