#
# Fixture corpus for the AST analysis gate (ci/analysis): per rule, at least
# one true-positive snippet and one false-positive guard — including the
# regex-era false-positive class, pinned as a regression: trigger text
# inside comments, docstrings, and string literals must NOT fire under the
# AST ports. Plus baseline ratchet behavior (new finding fails, baselined
# finding passes, fixed finding shrinks the baseline) and JSON verdict
# schema validation.
#
import json
import pathlib
import sys
import textwrap

import pytest

ROOT = pathlib.Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from ci.analysis import RegistrySources, analyze_source  # noqa: E402
from ci.analysis import baseline as baseline_mod  # noqa: E402
from ci.analysis.cli import main as cli_main  # noqa: E402
from ci.analysis.rules import (  # noqa: E402
    BlockingRule,
    ConfigKeyRule,
    ExporterScopeRule,
    HostSyncRule,
    HygieneRule,
    JsonlRule,
    MemStatsRule,
    MetricNameRule,
    PadRowsRule,
    PerfCounterRule,
    ProfilerScopeRule,
    RawDistanceRule,
    LedgerBypassRule,
    ServeDispatchRule,
    SleepRule,
    SpmdDivergenceRule,
    TracedImpurityRule,
    WallclockDeadlineRule,
)


def run(src, rule_factory, relpath="spark_rapids_ml_tpu/snippet.py", sources=None):
    return analyze_source(
        textwrap.dedent(src), relpath=relpath, rules=[rule_factory()], sources=sources
    )


def rule_ids(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------------------
# legacy rule ports: true positives
# --------------------------------------------------------------------------


def test_perf_counter_true_positive():
    fs = run("import time\nt0 = time.perf_counter()\n", PerfCounterRule)
    assert rule_ids(fs) == ["bare-perf-counter"]
    assert fs[0].line == 2


def test_perf_counter_alias_still_caught():
    fs = run("from time import perf_counter as pc\nt = pc()\n", PerfCounterRule)
    assert rule_ids(fs) == ["bare-perf-counter"]


def test_profiler_scope_jax_profiler_true_positive():
    fs = run(
        """
        import jax
        def f(d):
            with jax.profiler.trace(d):
                pass
        """,
        ProfilerScopeRule,
    )
    assert rule_ids(fs) == ["profiler-scope"]


def test_profiler_scope_sync_then_clock_true_positive():
    fs = run(
        """
        import time
        def f(x):
            t0 = time.perf_counter()
            x.block_until_ready()
            return time.perf_counter() - t0
        """,
        ProfilerScopeRule,
    )
    assert rule_ids(fs) == ["profiler-scope"] * 2


def test_profiler_scope_waiver_and_exempt_files():
    src = """
    import jax
    def f(d):
        with jax.profiler.trace(d):  # profiler-ok: the sanctioned hook
            pass
    """
    assert run(src, ProfilerScopeRule) == []
    # the attribution owners are exempt wholesale
    bare = """
    import time
    def f(x):
        t0 = time.perf_counter()
        x.block_until_ready()
        return time.perf_counter() - t0
    """
    for owner in (
        "spark_rapids_ml_tpu/telemetry.py",
        "spark_rapids_ml_tpu/ops_plane/efficiency.py",
    ):
        assert run(bare, ProfilerScopeRule, relpath=owner) == []


def test_profiler_scope_false_positive_guards():
    # perf_counter WITHOUT a sync in the same immediate body: not this
    # rule's finding (PerfCounterRule owns plain perf_counter use)
    fs = run(
        "import time\ndef f():\n    return time.perf_counter()\n",
        ProfilerScopeRule,
    )
    assert fs == []
    # a sync inside a NESTED function doesn't mark the enclosing timer as
    # device-timing (the autotuner's measurement-closure shape)
    fs = run(
        """
        import time
        def timer(run):
            def run_once():
                run().block_until_ready()
            t0 = time.perf_counter()
            run_once()
            return time.perf_counter() - t0
        """,
        ProfilerScopeRule,
    )
    assert fs == []
    # trigger text in comments/docstrings never fires the AST rule
    fs = run(
        '"""uses jax.profiler.trace and time.perf_counter"""\n'
        "# jax.profiler.start_trace idiom\n",
        ProfilerScopeRule,
    )
    assert fs == []


def test_blocking_while_true_and_bare_wait():
    fs = run(
        """
        def f(ev):
            while True:
                ev.wait()
        """,
        BlockingRule,
    )
    assert rule_ids(fs) == ["unbounded-blocking"] * 2


def test_blocking_bounded_wait_passes():
    fs = run("def f(ev):\n    ev.wait(5.0)\n    ev.wait(timeout=5.0)\n", BlockingRule)
    assert fs == []


def test_blocking_explicit_none_timeout_is_still_unbounded():
    fs = run("def f(ev):\n    ev.wait(None)\n    ev.wait(timeout=None)\n", BlockingRule)
    assert rule_ids(fs) == ["unbounded-blocking"] * 2


def test_jsonl_bypass_true_positive():
    fs = run(
        """
        import json
        def f(fh, rec):
            fh.write(json.dumps(rec) + "\\n")
        """,
        JsonlRule,
    )
    # ONE violation = ONE finding (the .write and the `+ "\n"` concat are
    # the same line; double-reporting would corrupt the baseline ratchet)
    assert rule_ids(fs) == ["jsonl-bypass"]


def test_jsonl_plain_dump_passes():
    fs = run(
        "import json\ndef f(fh, rec):\n    json.dump(rec, fh)\n    s = json.dumps(rec)\n",
        JsonlRule,
    )
    assert fs == []


def test_sleep_true_positive_including_alias():
    fs = run("import time as _t\n_t.sleep(2)\n", SleepRule)
    assert rule_ids(fs) == ["bare-sleep"]


def test_memstats_true_positive_and_owner_exempt():
    src = "def f(d):\n    return d.memory_stats()\n"
    assert rule_ids(run(src, MemStatsRule)) == ["direct-memstats"]
    assert run(src, MemStatsRule, relpath="spark_rapids_ml_tpu/memory.py") == []


def test_pad_rows_true_positive_and_bucket_passes():
    assert rule_ids(run("y = pad_rows(x, 8)\n", PadRowsRule)) == ["raw-pad-rows"]
    assert run("y = bucket_rows(x)\n", PadRowsRule) == []
    assert run("y = pad_rows(x, 8)\n", PadRowsRule, relpath="spark_rapids_ml_tpu/parallel/mesh.py") == []


# --------------------------------------------------------------------------
# raw-distance: hand-rolled x·cᵀ → argmin/top-k outside ops/distance.py
# --------------------------------------------------------------------------


def test_raw_distance_inline_matmul_argmin_fires():
    src = """
    import jax.numpy as jnp
    def assign(x, c):
        return jnp.argmin(jnp.sum(c * c, 1)[None, :] - 2.0 * x @ c.T, axis=1)
    """
    assert rule_ids(run(src, RawDistanceRule)) == ["raw-distance"]


def test_raw_distance_tainted_local_through_where_and_concat_fires():
    src = """
    import jax
    import jax.numpy as jnp
    def tile(q, items, valid, best):
        d2 = jnp.sum(items * items, 1)[None, :] - 2.0 * (q @ items.T)
        d2 = jnp.where(valid[None, :], d2, jnp.inf)
        cat = jnp.concatenate([best, d2], axis=1)
        return jax.lax.top_k(-cat, 4)
    """
    assert rule_ids(run(src, RawDistanceRule)) == ["raw-distance"]


def test_raw_distance_einsum_taint_and_method_argmin_fire():
    src = """
    import jax.numpy as jnp
    def f(q, bucket):
        d2 = -2.0 * jnp.einsum("bld,bd->bl", bucket, q)
        return d2.argmin(axis=1)
    """
    assert rule_ids(run(src, RawDistanceRule)) == ["raw-distance"]


def test_raw_distance_binding_inside_if_block_fires():
    # regression: a binding and its reduction inside ONE compound statement
    src = """
    import jax.numpy as jnp
    def f(x, c, small):
        if small:
            d2 = c_sq[None] - 2.0 * jnp.einsum("nd,kd->nk", x, c)
            return jnp.argmin(d2, axis=1)
        return None
    """
    assert rule_ids(run(src, RawDistanceRule)) == ["raw-distance"]


def test_raw_distance_core_call_results_are_clean():
    # the intended ported shape: distances from the shared core, reduction
    # on the call RESULT — calls launder taint
    src = """
    import jax
    import jax.numpy as jnp
    from .distance import pairwise_d2
    def f(q, items):
        d2 = pairwise_d2(q, items)
        return jax.lax.top_k(-d2, 4)
    """
    assert run(src, RawDistanceRule) == []


def test_raw_distance_non_matmul_reductions_pass():
    src = """
    import jax
    import jax.numpy as jnp
    def g(scores, probs, gumbel):
        a = jnp.argmin(scores, axis=1)            # no matmul anywhere
        keys = jnp.where(probs > 0, jnp.log(probs) + gumbel, -jnp.inf)
        _, idx = jax.lax.top_k(keys, 8)           # laundered through log()
        return a, idx
    """
    assert run(src, RawDistanceRule) == []


def test_raw_distance_exempt_in_core_and_waiver():
    src = """
    import jax.numpy as jnp
    def assign(x, c):
        return jnp.argmin(c_sq[None, :] - 2.0 * x @ c.T, axis=1)
    """
    assert run(src, RawDistanceRule, relpath="spark_rapids_ml_tpu/ops/distance.py") == []
    waived = """
    import jax.numpy as jnp
    def assign(x, c):
        return jnp.argmin(c_sq[None, :] - 2.0 * x @ c.T, axis=1)  # distance-ok: fixture rationale
    """
    assert run(waived, RawDistanceRule) == []
    bare = """
    import jax.numpy as jnp
    def assign(x, c):
        return jnp.argmin(c_sq[None, :] - 2.0 * x @ c.T, axis=1)  # distance-ok
    """
    assert rule_ids(run(bare, RawDistanceRule)) == ["raw-distance"]


def test_raw_distance_clean_rebinding_clears_taint():
    src = """
    import jax.numpy as jnp
    def f(x, c, scores):
        d2 = x @ c.T
        d2 = jnp.asarray(scores)   # rebinding from a laundering call cleans
        return jnp.argmin(d2, axis=1)
    """
    assert run(src, RawDistanceRule) == []


# --------------------------------------------------------------------------
# serve-dispatch: the serving plane's async contract (docs/serving.md)
# --------------------------------------------------------------------------

_SERVING_PATH = "spark_rapids_ml_tpu/serving/snippet.py"


def test_serve_dispatch_direct_jit_fires():
    src = """
    import jax
    def load(predict):
        return jax.jit(predict)
    """
    fs = run(src, ServeDispatchRule, relpath=_SERVING_PATH)
    assert rule_ids(fs) == ["serve-dispatch"]


def test_serve_dispatch_block_until_ready_both_forms_fire():
    src = """
    import jax
    def assemble(result):
        jax.block_until_ready(result)
        result.block_until_ready()
        return jax.device_get(result)
    """
    fs = run(src, ServeDispatchRule, relpath=_SERVING_PATH)
    assert rule_ids(fs) == ["serve-dispatch"] * 3


def test_serve_dispatch_waiver_and_import_alias():
    waived = """
    import jax
    def assemble(results):
        jax.block_until_ready(results)  # serve-ok: the one response-assembly sync point
        return results
    """
    assert run(waived, ServeDispatchRule, relpath=_SERVING_PATH) == []
    aliased = """
    from jax import jit as J
    def load(predict):
        return J(predict)
    """
    assert rule_ids(run(aliased, ServeDispatchRule, relpath=_SERVING_PATH)) == [
        "serve-dispatch"
    ]


def test_serve_dispatch_scoped_to_serving_only():
    # the same constructs are legal everywhere else in the framework (the
    # fit side jits freely) — and prose mentions never fire under AST rules
    src = """
    import jax
    def f(predict, result):
        g = jax.jit(predict)
        return g(result).block_until_ready()
    """
    assert run(src, ServeDispatchRule) == []  # default core-tree relpath
    prose = '''
    def doc():
        """Engines must not call jax.jit or block_until_ready directly."""
        s = "jax.jit(predict).block_until_ready()"
        return s
    '''
    assert run(prose, ServeDispatchRule, relpath=_SERVING_PATH) == []


def test_serve_dispatch_program_calls_pass():
    # the sanctioned surface: PredictProgram dispatch/fetch and plain numpy
    src = """
    import numpy as np
    def group(program, block):
        result, n = program.dispatch(block)
        return np.concatenate([program.fetch(result, n)])
    """
    assert run(src, ServeDispatchRule, relpath=_SERVING_PATH) == []


# --------------------------------------------------------------------------
# pinned regression: the regex-era false-positive class — trigger text in
# comments, docstrings, and string literals must not fire under AST ports
# --------------------------------------------------------------------------

_LEGACY_FP_SNIPPETS = [
    (PerfCounterRule, '# uses time.perf_counter() internally\ns = "time.perf_counter()"\n'),
    (
        BlockingRule,
        '''
        def f():
            """Spins in `while True` and calls `.wait()` — as PROSE."""
            msg = "while True: ev.wait()"
            return msg
        ''',
    ),
    (JsonlRule, 's = \'fh.write(json.dumps(rec) + "\\\\n")\'  # fh.write(json.dumps(rec))\n'),
    (SleepRule, '# time.sleep(5) would be wrong here\ndoc = "time.sleep(5)"\n'),
    (MemStatsRule, '"""Never call d.memory_stats() directly."""\ns = "d.memory_stats()"\n'),
    (PadRowsRule, '# pad_rows(x, 8) is forbidden\ns = "pad_rows(x, 8)"\n'),
    (
        RawDistanceRule,
        '"""Never write jnp.argmin(x @ c.T) by hand."""\ns = "jax.lax.top_k(-(x @ c.T), k)"\n',
    ),
]


@pytest.mark.parametrize(
    "rule_cls,src", _LEGACY_FP_SNIPPETS, ids=lambda p: getattr(p, "id", None) or "src"
)
def test_comment_and_string_mentions_do_not_fire(rule_cls, src):
    assert run(src, rule_cls) == []


def test_perf_counter_ns_kept_from_regex_era():
    fs = run("import time\nt0 = time.perf_counter_ns()\n", PerfCounterRule)
    assert rule_ids(fs) == ["bare-perf-counter"]


def test_waiver_inside_loop_body_does_not_waive_the_loop_finding():
    # a `.wait()` waiver deep in the body must not become an invisible
    # escape hatch for the enclosing while-True finding (header lines only)
    fs = run(
        """
        def f(ev):
            while True:
                ev.wait(5.0)
                ev.wait()  # blocking-ok: fixture reason for THIS call only
        """,
        BlockingRule,
    )
    assert rule_ids(fs) == ["unbounded-blocking"]
    assert fs[0].line == 3  # the while, not the waived call


def test_waiver_with_reason_suppresses_but_bare_waiver_does_not():
    waived = "import time\ntime.sleep(1)  # sleep-ok: fixture-bounded delay\n"
    assert run(waived, SleepRule) == []
    bare = "import time\ntime.sleep(1)  # sleep-ok\n"
    fs = analyze_source(bare, rules=[SleepRule(), HygieneRule()])
    assert sorted(rule_ids(fs)) == ["bare-sleep", "waiver-missing-reason"]


def test_hygiene_tabs_and_trailing_whitespace():
    fs = run("x =\t1\ny = 2  \n", HygieneRule)
    assert sorted(rule_ids(fs)) == ["tab", "trailing-whitespace"]


def test_waiver_mention_in_prose_is_not_a_waiver_attempt():
    fs = run("# the framework (`# hbm-ok` waiver) covers this\nx = 1\n", HygieneRule)
    assert fs == []


# --------------------------------------------------------------------------
# framework-aware detectors
# --------------------------------------------------------------------------


def test_spmd_divergence_rank_conditional():
    fs = run(
        """
        def f(ctx, rdv):
            if ctx.rank == 0:
                rdv.allgather("x")
        """,
        SpmdDivergenceRule,
    )
    assert rule_ids(fs) == ["spmd-divergence"]
    assert "rank" in fs[0].message


def test_spmd_divergence_except_handler():
    fs = run(
        """
        def f(rdv, work):
            try:
                work()
            except Exception:
                rdv.barrier()
        """,
        SpmdDivergenceRule,
    )
    assert rule_ids(fs) == ["spmd-divergence"]
    assert "except handler" in fs[0].message


def test_spmd_divergence_rank_guarded_early_exit():
    # the other spelling of the same hang: only rank 0 survives the guard,
    # so the straight-line collective below it is rank-dependent too
    fs = run(
        """
        def f(rank, rdv):
            if rank != 0:
                return
            rdv.barrier()
        """,
        SpmdDivergenceRule,
    )
    assert rule_ids(fs) == ["spmd-divergence"]
    assert "early exit" in fs[0].message


def test_spmd_early_exit_is_block_local():
    # a rank-guarded `continue` diverges the rest of the LOOP BODY, not the
    # code after the loop
    fs = run(
        """
        def f(rank, rdv, items):
            for it in items:
                if rank != 0:
                    continue
                prep(it)
            rdv.barrier()
        """,
        SpmdDivergenceRule,
    )
    assert fs == []


def test_spmd_nested_loop_continue_is_not_an_early_exit():
    # the continue exits the INNER for-loop only; every rank reaches the
    # collective below the guard
    fs = run(
        """
        def f(rank, rdv, items):
            if rank == 0:
                for x in items:
                    if not x:
                        continue
                    handle(x)
            rdv.allgather("payload")
        """,
        SpmdDivergenceRule,
    )
    assert fs == []


def test_spmd_return_inside_nested_loop_is_an_early_exit():
    fs = run(
        """
        def f(rank, rdv, items):
            if rank != 0:
                for x in items:
                    return x
            rdv.allgather("payload")
        """,
        SpmdDivergenceRule,
    )
    assert rule_ids(fs) == ["spmd-divergence"]


def test_spmd_symmetric_collective_in_both_arms_passes():
    # every rank enters the round — only the payload differs per arm
    fs = run(
        """
        def f(rank, ctx):
            if rank == 0:
                out = ctx.allgather(header)
            else:
                out = ctx.allgather("")
            return out
        """,
        SpmdDivergenceRule,
    )
    assert fs == []


def test_spmd_asymmetric_arms_still_flagged():
    fs = run(
        """
        def f(rank, ctx):
            if rank == 0:
                ctx.allgather(header)
                ctx.barrier()
            else:
                ctx.allgather("")
        """,
        SpmdDivergenceRule,
    )
    assert rule_ids(fs) == ["spmd-divergence"] * 3


def test_spmd_rank_dependent_payload_passes():
    fs = run(
        """
        def f(ctx, rdv):
            payload = "coord" if ctx.rank == 0 else ""
            rdv.allgather(payload)
        """,
        SpmdDivergenceRule,
    )
    assert fs == []


def test_spmd_submesh_scoped_full_mesh_collective_is_flagged():
    # PR 19: a full-clique control-plane round reachable only from sub-mesh
    # scoped code strands the ranks outside the carve — placement-induced
    # divergence, same hang as a rank conditional
    fs = run(
        """
        from spark_rapids_ml_tpu.parallel.mesh import chip_scope

        def f(devs, rdv):
            with chip_scope(devs):
                rdv.allgather("x")
        """,
        SpmdDivergenceRule,
    )
    assert rule_ids(fs) == ["spmd-divergence"]
    assert "sub-mesh scope `chip_scope(...)`" in fs[0].message
    assert "# submesh-ok" in fs[0].message


def test_spmd_submesh_carve_with_as_binding_is_flagged():
    fs = run(
        """
        from spark_rapids_ml_tpu.parallel import submesh

        def f(mesh, ctx):
            with submesh(mesh, 4) as sub:
                ctx.barrier()
        """,
        SpmdDivergenceRule,
    )
    assert rule_ids(fs) == ["spmd-divergence"]
    assert "submesh(...)" in fs[0].message


def test_spmd_submesh_waiver_suppresses_and_scope_exit_clears():
    # FP guards: a reasoned `# submesh-ok` waives the deliberate full-group
    # round, and collectives AFTER the carve (full mesh restored) are clean
    fs = run(
        """
        from spark_rapids_ml_tpu.parallel.mesh import chip_scope

        def f(devs, rdv):
            with chip_scope(devs):
                rdv.allgather("done")  # submesh-ok: whole clique joins the report round
            rdv.barrier()
        """,
        SpmdDivergenceRule,
    )
    assert fs == []


def test_spmd_submesh_waiver_is_tag_specific_and_needs_a_reason():
    # a `# spmd-ok` reason does NOT waive the sub-mesh finding (different
    # failure, different tag), and a bare `# submesh-ok` suppresses nothing
    wrong_tag = """
        from spark_rapids_ml_tpu.parallel.mesh import chip_scope

        def f(devs, rdv):
            with chip_scope(devs):
                rdv.allgather("x")  # spmd-ok: wrong tag for this finding
        """
    fs = run(wrong_tag, SpmdDivergenceRule)
    assert rule_ids(fs) == ["spmd-divergence"]
    bare = wrong_tag.replace(
        "# spmd-ok: wrong tag for this finding", "# submesh-ok"
    )
    fs = analyze_source(
        textwrap.dedent(bare),
        relpath="spark_rapids_ml_tpu/snippet.py",
        rules=[SpmdDivergenceRule(), HygieneRule()],
    )
    assert sorted(rule_ids(fs)) == ["spmd-divergence", "waiver-missing-reason"]


def test_spmd_non_carving_with_block_is_not_a_submesh_scope():
    # FP guard: ordinary context managers (locks, dataset scopes) around a
    # collective do not make it sub-mesh-scoped
    fs = run(
        """
        def f(lock, rdv):
            with lock:
                rdv.allgather("x")
        """,
        SpmdDivergenceRule,
    )
    assert fs == []


def test_spmd_rank_conditional_inside_submesh_scope_keeps_rank_message():
    # the innermost divergence frame wins: a rank conditional INSIDE the
    # carve is the rank-reachability bug, reported (and waived) as such
    fs = run(
        """
        from spark_rapids_ml_tpu.parallel.mesh import chip_scope

        def f(devs, rank, rdv):
            with chip_scope(devs):
                if rank == 0:
                    rdv.allgather("x")
        """,
        SpmdDivergenceRule,
    )
    assert rule_ids(fs) == ["spmd-divergence"]
    assert "rank-identity conditional" in fs[0].message


def test_spmd_nested_function_resets_conditional_context():
    fs = run(
        """
        def f(ctx):
            if ctx.rank == 0:
                def g(rdv):
                    rdv.allgather("")
                return g
        """,
        SpmdDivergenceRule,
    )
    assert fs == []


def test_host_sync_fetch_in_loop():
    fs = run(
        """
        import jax.numpy as jnp

        def solve(x0, n):
            x = jnp.asarray(x0)
            v = 0.0
            for _ in range(n):
                x = x * 2
                v = float(x.sum())
            return v
        """,
        HostSyncRule,
        relpath="spark_rapids_ml_tpu/ops/snippet.py",
    )
    assert rule_ids(fs) == ["host-sync"]


def test_host_sync_host_numpy_loop_passes():
    fs = run(
        """
        import numpy as np

        def host(n):
            a = np.zeros(n)
            s = 0.0
            for _ in range(n):
                s += float(np.dot(a, a))
            return s
        """,
        HostSyncRule,
        relpath="spark_rapids_ml_tpu/ops/snippet.py",
    )
    assert fs == []


def test_host_sync_metadata_and_final_fetch_pass():
    fs = run(
        """
        import numpy as np
        import jax.numpy as jnp

        def solve(x0, n):
            x = jnp.asarray(x0)
            for _ in range(n):
                k = int(x.shape[0])
                x = x * k
            return np.asarray(x)
        """,
        HostSyncRule,
        relpath="spark_rapids_ml_tpu/ops/snippet.py",
    )
    assert fs == []


def test_host_sync_only_in_hot_path_files():
    src = """
    import jax.numpy as jnp

    def solve(x0, n):
        x = jnp.asarray(x0)
        for _ in range(n):
            x = float(x) * x
        return x
    """
    assert run(src, HostSyncRule, relpath="spark_rapids_ml_tpu/tuning.py") == []


def test_traced_impurity_print_in_jitted():
    fs = run(
        """
        import jax

        @jax.jit
        def step(x):
            print("tracing", x)
            return x
        """,
        TracedImpurityRule,
    )
    assert rule_ids(fs) == ["traced-impurity"]


def test_traced_impurity_closure_append_in_loop_body():
    fs = run(
        """
        from jax import lax

        def solve(x):
            log = []
            def body(c):
                log.append(1)
                return c
            def cond(c):
                return c.sum() > 0
            return lax.while_loop(cond, body, x)
        """,
        TracedImpurityRule,
    )
    assert rule_ids(fs) == ["traced-impurity"]
    assert "log" in fs[0].message


def test_traced_impurity_debug_callback_is_sanctioned():
    fs = run(
        """
        import jax
        from functools import partial
        from spark_rapids_ml_tpu import telemetry

        @jax.jit
        def step(x):
            jax.debug.callback(partial(telemetry.record_convergence_point, "s"), x)
            return x
        """,
        TracedImpurityRule,
    )
    assert fs == []


def test_traced_impurity_untraced_function_passes():
    fs = run("def host():\n    print('fine on the host')\n", TracedImpurityRule)
    assert fs == []


def test_config_key_unknown_and_known():
    sources = RegistrySources(
        config_schema_keys={"alpha": 3},
        config_docs_text="| `alpha` | 1 | the knob |\n",
    )
    bad = run(
        "from spark_rapids_ml_tpu.core import config\nv = config['aplha']\n",
        ConfigKeyRule,
        sources=sources,
    )
    assert rule_ids(bad) == ["config-key"] and "aplha" in bad[0].message
    ok = run(
        "from spark_rapids_ml_tpu.core import config\nv = config['alpha']\nconfig.get('alpha', 1)\n",
        ConfigKeyRule,
        sources=sources,
    )
    assert ok == []


def test_config_key_ignores_other_config_objects():
    sources = RegistrySources(config_schema_keys={"alpha": 3})
    fs = run(
        "import jax\njax.config.update('jax_enable_x64', True)\nmycfg = {}\nmycfg['whatever'] = 1\n",
        ConfigKeyRule,
        sources=sources,
    )
    assert fs == []


def test_config_key_ignores_unrelated_locals_named_config():
    # a parameter/local named `config` outside core.py is NOT the schema dict
    sources = RegistrySources(config_schema_keys={"alpha": 3})
    fs = run(
        "def bench(config):\n    return config['batch_size']\n",
        ConfigKeyRule,
        relpath="benchmark/bench_x.py",
        sources=sources,
    )
    assert fs == []


def test_config_key_schema_docs_drift_both_directions():
    sources = RegistrySources(
        config_schema_keys={"alpha": 3, "beta": 4},
        config_docs_text="| `alpha` | 1 | doc |\n| `gamma` | 2 | ghost |\n",
    )
    fs = run("x = 1\n", ConfigKeyRule, sources=sources)
    msgs = " || ".join(f.message for f in fs)
    assert "`beta`" in msgs and "undocumented" in msgs
    assert "`gamma`" in msgs and "does not exist" in msgs


def test_metric_name_near_miss_and_documented():
    sources = RegistrySources(metric_docs_text="counters: `ingest.rows` and `fit.retries`.\n")
    bad = run(
        "from spark_rapids_ml_tpu import telemetry\ntelemetry.registry().inc('ingest.row')\n",
        MetricNameRule,
        sources=sources,
    )
    assert rule_ids(bad) == ["metric-name"]
    assert "near-miss" in bad[0].message and "ingest.rows" in bad[0].message
    ok = run(
        "from spark_rapids_ml_tpu import telemetry\ntelemetry.registry().inc('ingest.rows')\n",
        MetricNameRule,
        sources=sources,
    )
    assert ok == []


def test_metric_name_dynamic_names_are_skipped_not_flagged():
    sources = RegistrySources(metric_docs_text="`ingest.rows`\n")
    fs = run(
        "def f(reg, solver):\n    reg.inc(f'{solver}.fits')\n",
        MetricNameRule,
        sources=sources,
    )
    assert fs == []


def test_metric_name_convergence_partial_form_is_checked():
    sources = RegistrySources(metric_docs_text="`kmeans.shift`\n")
    fs = run(
        """
        from functools import partial
        from spark_rapids_ml_tpu import telemetry
        cb = partial(telemetry.record_convergence_point, "kmaens.shift")
        """,
        MetricNameRule,
        sources=sources,
    )
    assert rule_ids(fs) == ["metric-name"]


# --------------------------------------------------------------------------
# baseline ratchet + CLI verdict
# --------------------------------------------------------------------------


def _mini_repo(tmp_path, body):
    root = tmp_path / "repo"
    (root / "spark_rapids_ml_tpu").mkdir(parents=True)
    (root / "spark_rapids_ml_tpu" / "mod.py").write_text(body, encoding="utf-8")
    return root


def test_baseline_ratchet_new_fails_then_freezes_then_shrinks(tmp_path, capsys):
    root = _mini_repo(tmp_path, "import time\ntime.sleep(1)\n")
    bl = str(tmp_path / "baseline.json")
    args = ["spark_rapids_ml_tpu", "--root", str(root), "--baseline", bl, "--no-imports"]

    # 1. a new finding fails the gate
    assert cli_main(args) == 1
    # 2. plain --write-baseline refuses to GROW the ratchet...
    assert cli_main(args + ["--write-baseline"]) == 1
    assert baseline_mod.load(bl) == {}
    # ...freezing requires the explicit rule-landing flag
    assert cli_main(args + ["--write-baseline", "--allow-baseline-growth"]) == 0
    assert cli_main(args) == 0
    frozen = baseline_mod.load(bl)
    assert frozen == {"spark_rapids_ml_tpu/mod.py:bare-sleep": 1}
    # 3. a SECOND finding on top of the frozen one fails again
    (root / "spark_rapids_ml_tpu" / "mod.py").write_text(
        "import time\ntime.sleep(1)\ntime.sleep(2)\n", encoding="utf-8"
    )
    assert cli_main(args) == 1
    # 4. fixing everything passes, reports the stale entry, and
    #    --write-baseline shrinks the file to empty
    (root / "spark_rapids_ml_tpu" / "mod.py").write_text("x = 1\n", encoding="utf-8")
    assert cli_main(args) == 0
    assert "stale" in capsys.readouterr().out
    assert cli_main(args + ["--write-baseline"]) == 0
    assert baseline_mod.load(bl) == {}


def test_json_verdict_schema(tmp_path, capsys):
    root = _mini_repo(tmp_path, "import time\ntime.sleep(1)\n")
    bl = str(tmp_path / "baseline.json")
    rc = cli_main(
        ["spark_rapids_ml_tpu", "--root", str(root), "--baseline", bl,
         "--no-imports", "--json"]
    )
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == 1
    assert payload["verdict"] == "fail"
    assert payload["files_scanned"] == 1
    assert {r["id"] for r in payload["rules"]} >= {"bare-sleep", "spmd-divergence", "host-sync"}
    (finding,) = [f for f in payload["findings"] if f["rule"] == "bare-sleep"]
    assert set(finding) == {"path", "line", "col", "rule", "message", "status"}
    assert finding["status"] == "new" and finding["line"] == 2
    assert set(payload["baseline"]) == {"path", "stale", "counts"}
    assert payload["baseline"]["counts"] == {"spark_rapids_ml_tpu/mod.py:bare-sleep": 1}
    assert isinstance(payload["dynamic_metric_names"], list)


def test_subpath_target_still_applies_rules(tmp_path):
    # scanning a SUB-path must run the same rules as the full tree — never
    # a silently rule-less green pass
    root = tmp_path / "repo"
    (root / "spark_rapids_ml_tpu" / "sub").mkdir(parents=True)
    (root / "spark_rapids_ml_tpu" / "sub" / "mod.py").write_text(
        "import time\ntime.sleep(1)\n", encoding="utf-8"
    )
    rc = cli_main(
        ["spark_rapids_ml_tpu/sub", "--root", str(root),
         "--baseline", str(tmp_path / "b.json"), "--no-imports"]
    )
    assert rc == 1


def test_subset_write_baseline_preserves_unscanned_trees(tmp_path):
    # ratcheting one tree must not erase another tree's frozen entries
    root = tmp_path / "repo"
    for tree in ("spark_rapids_ml_tpu", "benchmark"):
        (root / tree).mkdir(parents=True)
        (root / tree / "mod.py").write_text("x =\t1\n", encoding="utf-8")
    bl = str(tmp_path / "baseline.json")
    base = ["--root", str(root), "--baseline", bl, "--no-imports"]
    assert cli_main(["spark_rapids_ml_tpu", "benchmark", *base,
                     "--write-baseline", "--allow-baseline-growth"]) == 0
    assert len(baseline_mod.load(bl)) == 2
    # fix only the framework tree, then ratchet ONLY that tree
    (root / "spark_rapids_ml_tpu" / "mod.py").write_text("x = 1\n", encoding="utf-8")
    # the './'-prefixed spelling must ratchet the same tree, not preserve it
    assert cli_main(["./spark_rapids_ml_tpu", *base, "--write-baseline"]) == 0
    assert baseline_mod.load(bl) == {"benchmark/mod.py:tab": 1}
    # and the full run still passes against the merged baseline
    assert cli_main(["spark_rapids_ml_tpu", "benchmark", *base]) == 0


def test_missing_registry_source_fails_instead_of_silently_disabling(tmp_path):
    # a repo whose docs/observability.md was moved must NOT get a green
    # metric-name pass with usages unchecked
    root = _mini_repo(
        tmp_path,
        "from spark_rapids_ml_tpu import telemetry\n"
        "telemetry.registry().inc('totally.bogus_metric')\n",
    )
    rc = cli_main(
        ["spark_rapids_ml_tpu", "--root", str(root),
         "--baseline", str(tmp_path / "b.json"), "--no-imports"]
    )
    assert rc == 1


def test_missing_target_fails_instead_of_green_zero_file_pass(tmp_path):
    root = _mini_repo(tmp_path, "x = 1\n")
    rc = cli_main(
        ["no_such_tree", "--root", str(root),
         "--baseline", str(tmp_path / "b.json"), "--no-imports"]
    )
    assert rc == 1


def test_utf8_bom_file_is_not_a_syntax_error(tmp_path):
    root = tmp_path / "repo"
    (root / "spark_rapids_ml_tpu").mkdir(parents=True)
    (root / "spark_rapids_ml_tpu" / "mod.py").write_bytes(b"\xef\xbb\xbfx = 1\n")
    rc = cli_main(
        ["spark_rapids_ml_tpu", "--root", str(root),
         "--baseline", str(tmp_path / "b.json"), "--no-imports"]
    )
    assert rc == 0


def test_verdict_catalog_covers_every_emitted_rule_id(tmp_path, capsys):
    root = _mini_repo(tmp_path, "import time\ntime.sleep(1)  # sleep-ok\nx =\t1  \n")
    cli_main(
        ["spark_rapids_ml_tpu", "--root", str(root),
         "--baseline", str(tmp_path / "b.json"), "--no-imports", "--json"]
    )
    payload = json.loads(capsys.readouterr().out)
    catalog_ids = {r["id"] for r in payload["rules"]}
    emitted_ids = {f["rule"] for f in payload["findings"]}
    assert emitted_ids  # tab, trailing-whitespace, waiver-missing-reason, bare-sleep
    assert emitted_ids <= catalog_ids
    assert {"syntax-error", "encoding"} <= catalog_ids


def test_syntax_error_is_a_structured_finding(tmp_path):
    root = _mini_repo(tmp_path, "def broken(:\n")
    rc = cli_main(
        ["spark_rapids_ml_tpu", "--root", str(root),
         "--baseline", str(tmp_path / "b.json"), "--no-imports"]
    )
    assert rc == 1


def test_nul_byte_is_a_structured_finding_not_a_crash(tmp_path):
    root = tmp_path / "repo"
    (root / "spark_rapids_ml_tpu").mkdir(parents=True)
    (root / "spark_rapids_ml_tpu" / "mod.py").write_bytes(b"x = 1\x00\n")
    rc = cli_main(
        ["spark_rapids_ml_tpu", "--root", str(root),
         "--baseline", str(tmp_path / "b.json"), "--no-imports"]
    )
    assert rc == 1


def test_write_baseline_ratchets_finalize_emitted_doc_paths(tmp_path):
    # a fixed docs-drift entry (emitted by the registry finalize pass at a
    # docs/ path outside the scanned code trees) must ratchet OUT, not be
    # preserved forever by the subset-protection
    root = _mini_repo(tmp_path, "x = 1\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(
        json.dumps({"version": 1, "counts": {"docs/observability.md:metric-name": 1}}),
        encoding="utf-8",
    )
    args = ["spark_rapids_ml_tpu", "--root", str(root),
            "--baseline", str(bl), "--no-imports"]
    assert cli_main(args + ["--write-baseline"]) == 0
    assert baseline_mod.load(str(bl)) == {}


def test_repo_gate_is_clean_with_empty_baseline():
    # the acceptance contract: the real tree passes with the checked-in
    # (empty) baseline — every finding is fixed or carries a reasoned waiver
    assert cli_main(["--no-imports"]) == 0
    assert baseline_mod.load(str(ROOT / "ci" / "analysis" / "baseline.json")) == {}


# --------------------------------------------------------------------------
# ledger-bypass: capacity math stays behind the shared HBM ledger
# (docs/scheduling.md "The shared ledger")
# --------------------------------------------------------------------------


def test_ledger_bypass_direct_admit_fit_fires():
    src = """
    from spark_rapids_ml_tpu import memory
    def place(est, ex, ctx):
        return memory.admit_fit(est, ex, ctx)
    """
    fs = run(src, LedgerBypassRule)
    assert rule_ids(fs) == ["ledger-bypass"]
    assert "admit_fit" in fs[0].message


def test_ledger_bypass_admit_model_load_and_memstats_fire():
    src = """
    def load(memory, model, dev):
        adm = memory.admit_model_load(model)
        cap = dev.memory_stats()
        return adm, cap
    """
    fs = run(src, LedgerBypassRule)
    assert rule_ids(fs) == ["ledger-bypass"] * 2


def test_ledger_bypass_from_import_alias_fires():
    src = """
    from ..memory import admit_fit as place
    def f(est, ex, ctx):
        return place(est, ex, ctx)
    """
    fs = run(src, LedgerBypassRule)
    assert rule_ids(fs) == ["ledger-bypass"]


def test_ledger_bypass_waiver_suppresses():
    src = """
    from spark_rapids_ml_tpu import memory
    def place(est, ex, ctx):
        return memory.admit_fit(est, ex, ctx)  # ledger-ok: the fit-entry admission — reserves through the shared ledger
    """
    assert run(src, LedgerBypassRule) == []


def test_ledger_bypass_exempt_in_owner_trees():
    src = """
    from spark_rapids_ml_tpu import memory
    def place(est, ex, ctx):
        return memory.admit_fit(est, ex, ctx)
    """
    # memory.py owns admission; scheduler/ owns the ledger; telemetry.py is
    # the sanctioned watermark sampler
    assert run(src, LedgerBypassRule, relpath="spark_rapids_ml_tpu/memory.py") == []
    assert (
        run(src, LedgerBypassRule, relpath="spark_rapids_ml_tpu/scheduler/queue.py")
        == []
    )
    assert run(src, LedgerBypassRule, relpath="spark_rapids_ml_tpu/telemetry.py") == []


def test_ledger_bypass_fp_guards():
    # prose/docstring mentions never fire under AST rules, and a LOCAL
    # function that shares the name is not the budgeter's admission
    prose = '''
    def doc():
        """Admissions go through memory.admit_fit and admit_model_load."""
        s = "memory.admit_fit(est, ex, ctx); d.memory_stats()"
        return s
    '''
    assert run(prose, LedgerBypassRule) == []
    local = """
    def admit_fit(a, b):
        return a + b
    def f():
        return admit_fit(1, 2)
    """
    assert run(local, LedgerBypassRule) == []


# --------------------------------------------------------------------------
# exporter-scope (the ops plane's export surface)
# --------------------------------------------------------------------------


def test_exporter_scope_http_server_import_fires():
    fs = run("import http.server\n", ExporterScopeRule)
    assert rule_ids(fs) == ["exporter-scope"]
    fs = run("from http.server import ThreadingHTTPServer\n", ExporterScopeRule)
    assert rule_ids(fs) == ["exporter-scope"]
    fs = run("import socketserver\n", ExporterScopeRule)
    assert rule_ids(fs) == ["exporter-scope"]


def test_exporter_scope_raw_socket_call_fires():
    src = """
    import socket
    def probe():
        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]
    """
    fs = run(src, ExporterScopeRule)
    assert rule_ids(fs) == ["exporter-scope"]


def test_exporter_scope_prometheus_assembly_fires():
    src = """
    def render(counters):
        lines = []
        for name, v in counters.items():
            lines.append("# TYPE " + name + " counter")
        return lines
    """
    fs = run(src, ExporterScopeRule)
    assert rule_ids(fs) == ["exporter-scope"]


def test_exporter_scope_waiver_suppresses():
    src = """
    import socket
    def probe():
        with socket.socket() as s:  # exporter-ok: coordinator port probe, not a metrics endpoint
            return s.getsockname()[1]
    """
    assert run(src, ExporterScopeRule) == []


def test_exporter_scope_exempt_inside_ops_plane():
    src = """
    from http.server import ThreadingHTTPServer
    def render(counters):
        return ["# TYPE srml_x counter"]
    """
    assert (
        run(src, ExporterScopeRule, relpath="spark_rapids_ml_tpu/ops_plane/export.py")
        == []
    )


def test_exporter_scope_fp_guards():
    # non-server socket attribute use, urllib clients, and prose mentioning
    # the modules (no marker strings) must not fire
    clean = '''
    import socket
    import urllib.request
    def f():
        """Scrapes http.server-style endpoints via urllib, no server here."""
        host = socket.gethostname()
        return urllib.request.urlopen(f"http://{host}/metrics")
    '''
    assert run(clean, ExporterScopeRule) == []
    # "TYPE" without the exposition marker form is not Prometheus assembly
    assert run('KIND = "TYPE: counter"\n', ExporterScopeRule) == []


# --------------------------------------------------------------------------
# wallclock-deadline: time.time() feeding deadline/timeout arithmetic
# --------------------------------------------------------------------------


def test_wallclock_deadline_direct_compare_true_positive():
    fs = run(
        """
        import time
        def wait(deadline):
            if time.time() > deadline:
                raise TimeoutError
        """,
        WallclockDeadlineRule,
    )
    assert rule_ids(fs) == ["wallclock-deadline"]


def test_wallclock_deadline_tainted_name_compare_true_positive():
    # name assigned from time.time() carries the taint into the compare,
    # including through +/- arithmetic
    fs = run(
        """
        import time
        def wait(t0, timeout_s):
            now = time.time()
            while now - t0 < timeout_s:
                now = time.time()
        """,
        WallclockDeadlineRule,
    )
    assert rule_ids(fs) == ["wallclock-deadline"]


def test_wallclock_deadline_bound_assign_true_positive():
    fs = run(
        "import time\ndeadline = time.time() + 5.0\n",
        WallclockDeadlineRule,
    )
    assert rule_ids(fs) == ["wallclock-deadline"]
    assert fs[0].line == 2


def test_wallclock_deadline_keyword_true_positive():
    fs = run(
        """
        import time
        def f(fut):
            fut.result(timeout=time.time() + 1.0)
        """,
        WallclockDeadlineRule,
    )
    assert rule_ids(fs) == ["wallclock-deadline"]


def test_wallclock_deadline_alias_still_caught():
    fs = run(
        "from time import time as now\nexpires = now() + 3\n",
        WallclockDeadlineRule,
    )
    assert rule_ids(fs) == ["wallclock-deadline"]


def test_wallclock_deadline_fp_guards():
    # the timestamping idiom stays legal: record fields, bare stamps,
    # attribute stamps, and ALL monotonic-clock arithmetic
    clean = """
    import time
    class T:
        def stamp(self):
            self._w0 = time.time()
            return {"t": time.time(), "host": "x"}
    def wait(t0, timeout_s):
        while time.monotonic() - t0 < timeout_s:
            pass
    def unrelated():
        n = len("abc")
        return n > 2
    """
    assert run(clean, WallclockDeadlineRule) == []


def test_wallclock_deadline_taint_is_scope_local():
    # a tainted name in one function must not poison a same-named
    # monotonic reading in another
    clean = """
    import time
    def a():
        now = time.time()
        return {"t": now}
    def b(deadline):
        now = time.monotonic()
        return now > deadline
    """
    assert run(clean, WallclockDeadlineRule) == []


def test_wallclock_deadline_waiver():
    waived = (
        "import time\n"
        "now = time.time()\n"
        "if now - mtime > 60:  # wallclock-ok: compared against file mtimes\n"
        "    pass\n"
    )
    assert run(waived, WallclockDeadlineRule) == []
