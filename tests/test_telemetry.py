#
# Telemetry subsystem tests: spans/counters/sinks (telemetry.py), the
# instrumented fit path (core.py ingest/layout/solve spans, model._fit_metrics),
# rendezvous round-trip metrics, solver convergence traces, the
# SRML_PROFILE_DIR trace artifact, and the get_logger satellite contracts
# (SRML_LOG_LEVEL, no duplicate handlers).
#
import json
import logging
import os

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import telemetry
from spark_rapids_ml_tpu.models.classification import LogisticRegression


@pytest.fixture
def tele(tmp_path):
    """Enable telemetry with a fresh registry + JSONL sink; restore after."""
    path = str(tmp_path / "metrics.jsonl")
    telemetry.registry().reset()
    telemetry.enable(path)
    yield path
    telemetry.disable()
    telemetry._STATE.sink_path = None
    telemetry.registry().reset()


def _binary_df(rng, n=200, d=4):
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return pd.DataFrame({"features": list(x), "label": y})


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_fit_writes_spans_counters_and_fit_metrics(tele, rng):
    model = (
        LogisticRegression(maxIter=25, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(_binary_df(rng))
    )
    records = _read_jsonl(tele)
    span_names = {r["name"] for r in records if r["kind"] == "span"}
    # the acceptance-contract stage spans
    assert {"ingest", "layout", "solve", "fit"} <= span_names
    # nesting paths are recorded
    paths = {r["path"] for r in records if r["kind"] == "span"}
    assert {"fit/ingest", "fit/layout", "fit/solve"} <= paths
    # one fit snapshot with bytes-ingested counters and a solver iteration count
    fit_recs = [r for r in records if r["kind"] == "fit"]
    assert len(fit_recs) == 1
    counters = fit_recs[0]["counters"]
    assert counters["ingest.bytes"] > 0
    assert counters["ingest.rows"] == 200
    assert counters["logistic.iterations"] >= 1
    assert counters["placement.device_put_calls"] >= 1
    # the same delta is surfaced on the model
    assert model._fit_metrics["counters"]["logistic.iterations"] >= 1
    assert any(s["name"] == "solve" for s in model._fit_metrics["spans"])
    # all records are rank-tagged
    assert all("rank" in r for r in records)


def test_disabled_is_noop_and_fit_metrics_empty(rng):
    telemetry.disable()
    telemetry.registry().reset()
    # no-op span is a shared singleton: no allocation per disabled span
    assert telemetry.span("a") is telemetry.span("b")
    model = (
        LogisticRegression(maxIter=5).setFeaturesCol("features").fit(_binary_df(rng))
    )
    assert model._fit_metrics == {}
    snap = telemetry.snapshot()
    assert snap["counters"] == {} and snap["spans"] == {}


def test_nested_spans_and_summary(tele):
    with telemetry.span("outer"):
        with telemetry.span("inner"):
            pass
    snap = telemetry.snapshot()
    assert "outer" in snap["spans"] and "outer/inner" in snap["spans"]
    telemetry.registry().inc("some.counter", 3)
    s = telemetry.summary()
    assert "outer/inner" in s and "some.counter" in s


def test_registry_counters_gauges_histograms(tele):
    reg = telemetry.registry()
    reg.inc("c", 2)
    reg.inc("c", 3)
    reg.gauge("g", 7.5)
    reg.gauge_max("w", 10)
    reg.gauge_max("w", 4)  # watermark keeps the max
    reg.observe("h", 1.0)
    reg.observe("h", 3.0)
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == 7.5
    assert snap["gauges"]["w"] == 10
    assert snap["histograms"]["h"] == {"count": 2.0, "sum": 4.0, "min": 1.0, "max": 3.0}


def test_fit_scope_delta_isolated(tele):
    telemetry.registry().inc("pre.existing", 100)
    with telemetry.fit_scope("X") as scope:
        telemetry.registry().inc("during", 1)
    # the scope delta carries only what accumulated inside
    assert scope["metrics"]["counters"] == {"during": 1}


def test_rendezvous_roundtrip_metrics(tele):
    import threading

    from spark_rapids_ml_tpu.parallel.context import LocalRendezvous

    rvs = LocalRendezvous.create(2)
    out = [None, None]

    def run(r):
        out[r] = rvs[r].allgather(f"payload-{r}")

    threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert out[0] == ["payload-0", "payload-1"] == out[1]
    snap = telemetry.snapshot()
    assert snap["counters"]["rendezvous.rounds"] == 2  # one per rank
    assert snap["counters"]["rendezvous.payload_bytes"] == len("payload-0") * 2
    assert snap["spans"]["rendezvous.allgather"]["count"] == 2


def test_convergence_trace_solver_iterations(tele, rng):
    # per-iteration objective points from inside the jitted L-BFGS loop.
    # NOTE: the gate is read at trace time, so this uses a distinctive
    # problem shape that no other test fits (fresh trace, callbacks baked in).
    telemetry.enable(convergence=True)
    try:
        df = _binary_df(rng, n=230, d=7)
        model = (
            LogisticRegression(maxIter=30, float32_inputs=False)
            .setFeaturesCol("features")
            .fit(df)
        )
        pts = telemetry.registry().convergence_trace("glm_qn")
        assert len(pts) >= int(model.n_iter_) >= 2
        objs = [v for _, v in pts]
        assert objs[-1] <= objs[0]  # the objective decreased
    finally:
        telemetry.enable(convergence=False)


def test_kmeans_convergence_trace(tele, rng):
    from spark_rapids_ml_tpu.models.clustering import KMeans

    x = np.concatenate([rng.normal(size=(60, 3)) + 4, rng.normal(size=(60, 3)) - 4])
    df = pd.DataFrame({"features": list(x)})
    KMeans(k=2, maxIter=10, seed=1).setFeaturesCol("features").fit(df)
    snap = telemetry.snapshot()
    assert snap["counters"]["kmeans.fits"] == 1
    assert snap["counters"]["kmeans.iterations"] >= 1
    assert len(telemetry.registry().convergence_trace("kmeans.shift")) >= 1


def test_pca_fit_recorded(tele, rng):
    from spark_rapids_ml_tpu.models.feature import PCA

    x = rng.normal(size=(120, 6))
    df = pd.DataFrame({"features": list(x)})
    PCA(k=2, inputCol="features").fit(df)
    snap = telemetry.snapshot()
    assert snap["counters"]["pca.fits"] == 1
    assert 0.0 < snap["gauges"]["pca.explained_variance_ratio_sum"] <= 1.0 + 1e-9


def test_sparse_ell_counters(tele):
    import scipy.sparse as sp

    from spark_rapids_ml_tpu.ops.sparse import csr_to_ell

    x = sp.random(50, 20, density=0.1, random_state=np.random.RandomState(0), format="csr")
    idx, val, k_max = csr_to_ell(x)
    snap = telemetry.snapshot()
    assert snap["counters"]["sparse.csr_to_ell_calls"] == 1
    assert snap["counters"]["sparse.ell_rows"] == 50
    assert snap["counters"]["sparse.ell_pad_cells"] == 50 * k_max - x.nnz
    assert snap["gauges"]["sparse.k_max"] == k_max


def test_convergence_trace_ring_buffer(tele, monkeypatch):
    # at the cap, the OLDEST point is dropped (so `last` stays current) and
    # the truncation is surfaced as a counter instead of silent staleness
    monkeypatch.setattr(telemetry, "_MAX_CONVERGENCE_POINTS", 5)
    reg = telemetry.registry()
    for i in range(8):
        reg.record_convergence("ringtest", i, float(100 - i))
    pts = reg.convergence_trace("ringtest")
    assert len(pts) == 5
    assert pts[0][0] == 3 and pts[-1][0] == 7  # oldest dropped, newest kept
    assert reg.snapshot()["counters"]["ringtest.convergence_points_dropped"] == 3


def test_record_device_memory_never_breaks(tele):
    # CPU devices expose no memory_stats — the watermark sampler must be a
    # silent no-op there and a gauge writer where stats exist
    telemetry.record_device_memory()
    snap = telemetry.snapshot()
    peak = snap["gauges"].get("device.peak_bytes_in_use")
    assert peak is None or peak >= 0


def test_profile_dir_trace_artifact(tmp_path, monkeypatch, rng):
    # SRML_PROFILE_DIR: the fit runs under jax.profiler.trace and an xprof
    # artifact lands in the directory; telemetry spans (TraceAnnotation
    # emitters) must work both under the trace and with the profiler inactive.
    prof = tmp_path / "prof"
    monkeypatch.setenv("SRML_PROFILE_DIR", str(prof))
    model = (
        LogisticRegression(maxIter=5).setFeaturesCol("features").fit(_binary_df(rng))
    )
    assert model.n_iter_ >= 1
    artifacts = [
        os.path.join(dp, f) for dp, _, fs in os.walk(prof) for f in fs
    ]
    assert artifacts, "no profiler artifact written under SRML_PROFILE_DIR"
    # nested spans with the profiler INACTIVE (env cleared) keep working
    monkeypatch.delenv("SRML_PROFILE_DIR")
    with telemetry.span("post-profile"):
        with telemetry.span("nested"):
            pass


def test_get_logger_no_duplicate_handlers():
    from spark_rapids_ml_tpu.utils import _LOGGERS, get_logger

    logger = get_logger("TelemetryHandlerTest")
    n0 = len(logger.handlers)
    assert n0 == 1
    # repeated calls through the cache
    for _ in range(3):
        assert len(get_logger("TelemetryHandlerTest").handlers) == n0
    # even with the cache cleared (fresh-module simulation), the underlying
    # logging.Logger is process-global and must not gain a second handler
    _LOGGERS.pop("spark_rapids_ml_tpu.TelemetryHandlerTest", None)
    for _ in range(3):
        assert len(get_logger("TelemetryHandlerTest").handlers) == n0


def test_get_logger_honors_env_level(monkeypatch):
    from spark_rapids_ml_tpu.utils import get_logger

    monkeypatch.setenv("SRML_LOG_LEVEL", "DEBUG")
    logger = get_logger("TelemetryEnvLevelTest")
    assert logger.level == logging.DEBUG
    # level resolved ONCE at creation: later env changes don't rewrite it
    monkeypatch.setenv("SRML_LOG_LEVEL", "ERROR")
    assert get_logger("TelemetryEnvLevelTest").level == logging.DEBUG
    # explicit argument beats the env var for a fresh logger
    monkeypatch.setenv("SRML_LOG_LEVEL", "WARNING")
    assert get_logger("TelemetryEnvArgTest", level="CRITICAL").level == logging.CRITICAL


def test_verbose_stage_logging_via_spans(rng):
    # the old `verbose` wall-clock lines now come from spans: capture the
    # estimator logger and check the stage lines fire WITHOUT telemetry on
    telemetry.disable()
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("spark_rapids_ml_tpu.LogisticRegression")
    handler = _Capture(level=logging.INFO)
    logger.addHandler(handler)
    try:
        LogisticRegression(maxIter=5, verbose=True).setFeaturesCol("features").fit(
            _binary_df(rng)
        )
    finally:
        logger.removeHandler(handler)
    stage_lines = [r for r in records if r.startswith("stage ")]
    assert any("fit/ingest" in r for r in stage_lines)
    assert any("fit/layout" in r for r in stage_lines)
    assert any("fit/solve" in r for r in stage_lines)
