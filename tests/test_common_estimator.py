#
# Framework unit test with a zero-math dummy backend — proves the entire
# estimator/model plumbing (param mapping incl. None/""-mapped params, fit-side
# runtime asserts, persistence round-trip, fitMultiple overrides, num_workers
# validation) with no real algorithm, exactly the reference's
# tests/test_common_estimator.py `CumlDummy`/`SparkRapidsMLDummy` pattern
# (reference test_common_estimator.py:46-113, 185-227, 462-512, 528-558).
#
from typing import Any, Dict

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.core import (
    FitInputs,
    _TpuEstimator,
    _TpuModelWithColumns,
)
from spark_rapids_ml_tpu.params import HasFeaturesCol, HasFeaturesCols, Param, TypeConverters


class TpuDummy:
    """Stand-in solver: records what it was called with (reference CumlDummy)."""

    def __init__(self, a=10.0, b=20, k=30, x=40):
        self.a, self.b, self.k, self.x = a, b, k, x


class DummyEstimator(_TpuEstimator, HasFeaturesCol, HasFeaturesCols):
    def __init__(self, **kwargs):
        super().__init__()
        self._set_params(**kwargs)

    # Spark param "fake_alpha" maps to solver "a"; "fake_beta" is unsupported
    # (None); "fake_drop" accepted but dropped ("").
    fake_alpha = Param("fake_alpha", "maps to solver param a", TypeConverters.toFloat)
    fake_beta = Param("fake_beta", "unsupported on TPU", TypeConverters.toInt)
    fake_drop = Param("fake_drop", "accepted and ignored", TypeConverters.toString)

    @classmethod
    def _param_mapping(cls):
        return {"fake_alpha": "a", "fake_beta": None, "fake_drop": ""}

    def _get_solver_params_default(self) -> Dict[str, Any]:
        return {"a": 10.0, "b": 20, "k": 30, "x": 40}

    def setFeaturesCol(self, value):
        return self._set_params(featuresCol=value)

    def _get_tpu_fit_func(self, extracted):
        n_cols = extracted.n_cols

        def _fit(inputs: FitInputs, params: Dict[str, Any]) -> Dict[str, Any]:
            # runtime asserts inside the "barrier" body (reference :185-227)
            assert inputs.desc.n == n_cols
            assert inputs.desc.m == inputs.n_valid
            assert inputs.mesh is not None
            assert set(params.keys()) == {"a", "b", "k", "x"}
            dummy = TpuDummy(**params)
            return {
                "model_attr": float(dummy.a) * 100,
                "n_cols": n_cols,
                "coefs": np.arange(n_cols, dtype=np.float64),
            }

        return _fit

    def _create_model(self, attrs):
        return DummyModel(**attrs)


class DummyModel(_TpuModelWithColumns, HasFeaturesCol, HasFeaturesCols):
    def __init__(self, model_attr=None, n_cols=None, coefs=None, **kwargs):
        super().__init__(model_attr=model_attr, n_cols=n_cols, coefs=coefs)
        self.model_attr = model_attr
        self.n_cols = n_cols
        self.coefs = np.asarray(coefs) if coefs is not None else None

    @classmethod
    def _param_mapping(cls):
        return DummyEstimator._param_mapping()

    def _get_solver_params_default(self):
        return {"a": 10.0, "b": 20, "k": 30, "x": 40}

    def _out_column_names(self):
        return ["dummy_pred"]

    def _get_transform_func(self):
        coefs = self.coefs

        def construct():
            return np.asarray(coefs)

        def predict(state, xb):
            return xb @ state

        return construct, predict, None


def _df(n=16, d=4):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d))
    return pd.DataFrame({"features": list(x)}), x


def test_params_mapping_and_defaults():
    est = DummyEstimator(featuresCol="features")
    assert est.solver_params == {"a": 10.0, "b": 20, "k": 30, "x": 40}
    assert est.cuml_params == est.solver_params  # drop-in alias
    est._set_params(fake_alpha=2.5)
    assert est.solver_params["a"] == 2.5
    assert est.getOrDefault("fake_alpha") == 2.5
    # unsupported param raises
    with pytest.raises(ValueError, match="not supported"):
        est._set_params(fake_beta=1)
    # dropped param accepted, not forwarded
    est._set_params(fake_drop="anything")
    assert "fake_drop" not in est.solver_params
    # direct solver param
    est._set_params(k=7)
    assert est.solver_params["k"] == 7
    # unknown raises
    with pytest.raises(ValueError, match="Unknown parameter"):
        est._set_params(nope=1)


def test_fit_and_transform_end_to_end():
    df, x = _df()
    est = DummyEstimator(featuresCol="features", num_workers=4)
    model = est.fit(df)
    assert model.model_attr == 1000.0
    assert model.n_cols == 4
    out = model.transform(df)
    assert "dummy_pred" in out.columns
    np.testing.assert_allclose(np.asarray(out["dummy_pred"]), x @ model.coefs, rtol=1e-6)


def test_fit_multiple_single_pass_and_overrides():
    df, _ = _df()
    est = DummyEstimator(featuresCol="features")
    pm = [{est.getParam("fake_alpha"): 5.0}, {est.getParam("fake_alpha"): 7.0}]
    it = est.fitMultiple(df, pm)
    models = dict(it)
    assert models[0].model_attr == 500.0
    assert models[1].model_attr == 700.0
    # original estimator untouched
    assert est.solver_params["a"] == 10.0


def test_persistence_round_trip(tmp_path):
    df, x = _df()
    est = DummyEstimator(featuresCol="features", fake_alpha=3.0, k=9)
    est_path = str(tmp_path / "est")
    est.save(est_path)
    est2 = DummyEstimator.load(est_path)
    assert est2.solver_params["a"] == 3.0
    assert est2.solver_params["k"] == 9
    assert est2.getOrDefault("featuresCol") == "features"

    model = est.fit(df)
    m_path = str(tmp_path / "model")
    model.write().overwrite().save(m_path)
    model2 = DummyModel.load(m_path)
    assert model2.model_attr == model.model_attr
    np.testing.assert_array_equal(model2.coefs, model.coefs)
    out = model2.transform(df)
    np.testing.assert_allclose(np.asarray(out["dummy_pred"]), x @ model.coefs, rtol=1e-6)


def test_num_workers_validation():
    with pytest.raises(ValueError):
        DummyEstimator(num_workers=0)
    est = DummyEstimator(featuresCol="features", num_workers=3)
    assert est.num_workers == 3
    est2 = DummyEstimator(featuresCol="features")
    from spark_rapids_ml_tpu.parallel import default_devices

    assert est2.num_workers == len(default_devices())


def test_copy_semantics():
    est = DummyEstimator(featuresCol="features", fake_alpha=1.5)
    c = est.copy({est.getParam("fake_alpha"): 9.0})
    assert c.getOrDefault("fake_alpha") == 9.0
    assert est.getOrDefault("fake_alpha") == 1.5
    # solver params are NOT shared dicts
    c._set_params(k=1)
    assert est.solver_params["k"] == 30


def test_empty_dataset_raises():
    df = pd.DataFrame({"features": []})
    est = DummyEstimator(featuresCol="features")
    with pytest.raises((RuntimeError, ValueError)):
        est.fit(df)


def test_verbose_stage_timing_logs(rng, caplog):
    # verbose solver param produces per-stage timing lines (reference cuML
    # verbosity plumbing, core.py:394-417 analog), emitted by telemetry spans
    # with their nesting path (fit/ingest, fit/layout, fit/solve, fit) — see
    # docs/observability.md. The framework logger writes to its own stderr
    # handler (propagate=False), so hook caplog's handler in.
    import logging

    import pandas as pd

    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.utils import get_logger

    x = rng.normal(size=(200, 6))
    df = pd.DataFrame({"features": list(x)})
    est = PCA(k=2, inputCol="features")
    est._solver_params["verbose"] = True
    logger = get_logger(PCA)
    logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.INFO):
            est.fit(df)
    finally:
        logger.removeHandler(caplog.handler)
    text = caplog.text
    assert "stage fit/ingest" in text
    assert "stage fit/layout" in text
    assert "stage fit/solve" in text
    assert "stage fit:" in text  # the enclosing whole-fit span


def test_profile_trace_dir(rng, tmp_path, monkeypatch):
    # SRML_PROFILE_DIR produces a jax.profiler trace directory
    import pandas as pd

    from spark_rapids_ml_tpu.models.feature import PCA

    prof = str(tmp_path / "trace")
    monkeypatch.setenv("SRML_PROFILE_DIR", prof)
    x = rng.normal(size=(100, 4))
    df = pd.DataFrame({"features": list(x)})
    PCA(k=2, inputCol="features").fit(df)
    import os

    assert os.path.isdir(prof)
    found = []
    for root, _, files in os.walk(prof):
        found.extend(files)
    assert found, "profiler trace produced no files"
