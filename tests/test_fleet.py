#
# Fleet plane tests (docs/observability.md "Fleet plane"): the one set of
# merge definitions (counters sum; gauges keep per-rank values + min/max/sum;
# age-aligned window merges preserve exact counts/sums and are associative
# and rank-order independent), the live ops round over LocalRendezvous
# (3-rank aggregation, lockstep piggyback on trace_scope, two-layer
# non-fatality, zero cost while telemetry is off), cluster SLO evaluation
# where a `min_count` floor lets the MERGED window trip while every thin
# per-rank slice stays vacuously healthy (rank-0 /healthz flips 503),
# straggler attribution naming the laggard rank in the flight recorder AND
# the audit trail, the per-rank snapshot meta header + rank-aware naming +
# exporter port policy, and `opsreport --cluster`'s partial-fleet exit code.
# All without a TPU.
#
import json
import os
import socket
import threading
import time
import types
import urllib.error
import urllib.request

import pytest

from benchmark import opsreport
from spark_rapids_ml_tpu import core, diagnostics, ops_plane, telemetry
from spark_rapids_ml_tpu.ops_plane import audit, export, fleet, slo
from spark_rapids_ml_tpu.parallel import LocalRendezvous
from spark_rapids_ml_tpu.scheduler.ledger import merge_tenant_usage

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(autouse=True)
def _fresh_fleet():
    """Fleet module state is process-global; this file runs BEFORE
    test_ops_plane.py alphabetically, and a leftover merged cluster view
    would flip its /healthz assertions."""
    fleet.reset()
    audit.clear()
    diagnostics.flight_recorder().reset()
    yield
    fleet.reset()
    audit.clear()


@pytest.fixture
def tele():
    """Fresh enabled registry with FAST window buckets; restore after."""
    saved = {
        k: core.config[k] for k in ("metrics_bucket_seconds", "metrics_bucket_count")
    }
    core.config["metrics_bucket_seconds"] = 0.05
    core.config["metrics_bucket_count"] = 20  # 1s horizon
    telemetry.registry().reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.registry().reset()
    core.config.update(saved)


@pytest.fixture
def slo_cfg():
    saved = core.config["slo"]
    slo.reset()
    yield
    core.config["slo"] = saved
    slo.reset()


def _run_ranks(nranks, fn, timeout_s=60.0):
    """Run fn(rank, rendezvous) on one thread per rank; re-raise the first
    thread error in the caller (a hung lockstep bug must fail, not wedge)."""
    rdvs = LocalRendezvous.create(nranks, timeout_s=30.0)
    results = [None] * nranks
    errors = []

    def work(rank):
        try:
            results[rank] = fn(rank, rdvs[rank])
        except BaseException as e:
            errors.append(e)
            rdvs[rank].abort(f"test rank {rank}: {type(e).__name__}")

    threads = [
        threading.Thread(target=work, args=(r,), daemon=True) for r in range(nranks)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout_s)
    assert not any(t.is_alive() for t in threads), "rank thread hung"
    if errors:
        raise errors[0]
    return results


def _mk_export(samples_newest_first, bucket_seconds=0.05, bucket_count=20,
               name="fleet_test.lat_s", counters=None):
    """Craft one rank's age-indexed window export from per-bucket sample
    lists (newest first), the shape `windows_export()` emits."""
    buckets = [sorted(float(v) for v in b) for b in samples_newest_first]
    buckets += [[] for _ in range(bucket_count - len(buckets))]
    return {
        "bucket_seconds": bucket_seconds,
        "bucket_count": bucket_count,
        "counters": {
            k: list(v) + [0.0] * (bucket_count - len(v))
            for k, v in (counters or {}).items()
        },
        "hists": {
            name: {
                "counts": [float(len(b)) for b in buckets],
                "sums": [float(sum(b)) for b in buckets],
                "samples": buckets,
            }
        },
    }


# ----------------------------------------------------- merge semantics ------


def test_merge_counters_sum():
    m = telemetry.merge_counters(
        [{"a": 1.0, "b": 2.0}, {"a": 10.0}, {"b": 0.5, "c": 4.0}]
    )
    assert m == {"a": 11.0, "b": 2.5, "c": 4.0}


def test_merge_gauges_keep_per_rank_and_min_max_sum():
    m = telemetry.merge_gauges({0: {"g": 2.0}, 2: {"g": 8.0}, 1: {"g": 5.0}})
    assert m["g"]["by_rank"] == {0: 2.0, 1: 5.0, 2: 8.0}
    assert (m["g"]["min"], m["g"]["max"], m["g"]["sum"]) == (2.0, 8.0, 15.0)


def test_merge_histograms_exact_counts_sums():
    m = telemetry.merge_histograms(
        [
            {"h": {"count": 3.0, "sum": 6.0, "min": 1.0, "max": 3.0}},
            {"h": {"count": 2.0, "sum": 9.0, "min": 4.0, "max": 5.0}},
        ]
    )
    assert m["h"] == {"count": 5.0, "sum": 15.0, "min": 1.0, "max": 5.0}


def test_merge_windows_exact_associative_order_independent():
    a = _mk_export([[0.01, 0.02], [0.03]])
    b = _mk_export([[1.0], []])
    c = _mk_export([[], [0.5, 0.6]])
    merged = telemetry.merge_windows([a, b, c])
    h = merged["hists"]["fleet_test.lat_s"]
    # exact counts/sums per age bucket, never approximated
    assert h["counts"][0] == 3.0 and h["counts"][1] == 3.0
    assert h["sums"][0] == pytest.approx(0.01 + 0.02 + 1.0)
    assert h["sums"][1] == pytest.approx(0.03 + 0.5 + 0.6)
    # rank-order independence + associativity (canonical sorted-sample form)
    assert telemetry.merge_windows([c, a, b]) == merged
    left = telemetry.merge_windows([telemetry.merge_windows([a, b]), c])
    right = telemetry.merge_windows([a, telemetry.merge_windows([b, c])])
    for view in (left, right):
        assert view["hists"] == merged["hists"]
        assert view["counters"] == merged["counters"]


def test_merge_single_rank_identity(tele):
    tele.inc("fleet_test.work", 3.0)
    for v in (0.3, 0.1, 0.2):
        tele.observe("fleet_test.lat_s", v)
    e = tele.windows_export()
    m = telemetry.merge_windows([e])
    assert m["counters"] == e["counters"]
    assert m["hists"] == e["hists"]
    assert m["ranks"] == 1


def test_merged_p99_brackets_per_rank_p99s():
    fast = _mk_export([[0.01] * 20])
    slow = _mk_export([[0.9] * 20])
    q = lambda e: telemetry.MergedWindows(  # noqa: E731
        telemetry.merge_windows([e])
    ).window_quantile("fleet_test.lat_s", 0.99)
    merged_q = telemetry.MergedWindows(
        telemetry.merge_windows([fast, slow])
    ).window_quantile("fleet_test.lat_s", 0.99)
    assert q(fast) <= merged_q <= q(slow)


def test_merge_windows_bucket_mismatch_raises():
    with pytest.raises(ValueError):
        telemetry.merge_windows(
            [_mk_export([[]], bucket_seconds=0.05), _mk_export([[]], bucket_seconds=0.1)]
        )


def test_merge_tenant_usage_sums_device_time():
    merged = merge_tenant_usage(
        [
            {"t1": {"byte_seconds": 1.0, "chips_busy": 2.0,
                    "device_time": {"execute_s": 1.0, "idle_s": 0.5}}},
            {"t1": {"byte_seconds": 3.0, "device_time": {"execute_s": 2.0}},
             "_pool": {"chips_busy": 4.0, "chips_idle": 4.0}},
        ]
    )
    assert merged["t1"]["byte_seconds"] == 4.0
    assert merged["t1"]["chips_busy"] == 2.0
    assert merged["t1"]["device_time"] == {"execute_s": 3.0, "idle_s": 0.5}
    assert merged["_pool"]["chips_busy"] == 4.0


# ----------------------------------------------------------- live round -----


def _rank_payload(rank, **over):
    p = fleet.local_payload(rank)
    p.update(rank=rank, **over)
    return p


def test_three_rank_round_merges_counters(tele):
    views = _run_ranks(
        3,
        lambda r, rdv: fleet.ops_round(
            rdv, force=True,
            payload=_rank_payload(r, counters={"fleet_test.work": float(r + 1)}),
        ),
    )
    view = next(v for v in views if v is not None)
    # merged counters equal the per-rank sum — the acceptance identity
    assert view["counters"]["fleet_test.work"] == 6.0
    assert view["ranks_reporting"] == 3 and view["missing"] == []
    assert set(view["ranks"]) == {0, 1, 2}
    assert view["ranks"][1]["pid"] == os.getpid()
    # the merged view is the process's cluster view now
    assert fleet.cluster_view()["counters"]["fleet_test.work"] == 6.0
    rep = ops_plane.report(cluster=True)
    assert rep["cluster"]["available"] is True
    assert rep["cluster"]["ranks_reporting"] == 3
    assert telemetry.registry().snapshot()["counters"]["fleet.ops_rounds"] == 1.0


def test_ops_due_throttles_to_interval(tele):
    assert fleet.ops_due(now=100.0) is True
    assert fleet.ops_due(now=100.01) is False  # within one bucket width
    assert fleet.ops_due(now=100.06) is True  # past it
    telemetry.disable()
    assert fleet.ops_due(now=200.0) is False  # disabled: never due


def test_trace_scope_piggybacks_ops_round(tele):
    def fit(rank, rdv):
        ctx = types.SimpleNamespace(rank=rank, is_spmd=True, rendezvous=rdv)
        with diagnostics.trace_scope("fleet-fit", ctx):
            pass
        return rdv._round

    rounds = _run_ranks(2, fit)
    # exactly the trace round + the piggybacked ops round, on every rank
    assert rounds == [2, 2]
    assert fleet.cluster_view() is not None
    assert telemetry.registry().snapshot()["counters"]["fleet.ops_rounds"] == 1.0


def test_disabled_telemetry_adds_no_rounds_and_records_nothing(tele):
    telemetry.disable()

    def fit(rank, rdv):
        ctx = types.SimpleNamespace(rank=rank, is_spmd=True, rendezvous=rdv)
        with diagnostics.trace_scope("fleet-fit", ctx):
            pass
        return rdv._round

    rounds = _run_ranks(2, fit)
    assert rounds == [1, 1]  # ONLY the trace round: zero extra rounds
    assert fleet.cluster_view() is None
    snap = telemetry.registry().snapshot()
    assert "fleet.ops_rounds" not in snap["counters"]


def test_ops_round_payload_failure_degrades_to_bare_marker(tele, monkeypatch):
    monkeypatch.setattr(
        fleet, "local_payload",
        lambda rank=None: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    views = _run_ranks(2, lambda r, rdv: fleet.ops_round(rdv, force=True))
    # the round still completed lockstep; every rank is NAMED missing, the
    # fit is untouched
    view = next(v for v in views if v is not None)
    assert view["ranks_reporting"] == 0
    assert view["missing"] == [0, 1]


def test_ops_round_dead_peer_degrades_survivors_nonfatally(tele):
    def fit(rank, rdv):
        if rank == 1:
            rdv.abort("chaos: rank 1 died mid-round")
            return "aborted"
        return fleet.ops_round(
            rdv, force=True, payload=_rank_payload(rank)
        )

    views = _run_ranks(2, fit)
    assert views[0] is None  # survivor degraded to local-only, no raise
    assert views[1] == "aborted"
    kinds = [e["kind"] for e in diagnostics.flight_recorder().events()]
    assert "ops_round_failed" in kinds
    counters = telemetry.registry().snapshot()["counters"]
    assert counters["fleet.ops_rounds_failed"] == 1.0
    assert "fleet.ops_rounds" not in counters  # nothing merged


# ------------------------------------------------------- cluster health -----


def _min_count_spec(min_count=10):
    return {
        "name": "fleet_lat", "kind": "latency", "histogram": "fleet_test.lat_s",
        "threshold_s": 0.1, "objective": 0.9, "min_count": min_count,
        "fast_burn": 1.0,
    }


def _skewed_rank_windows():
    """3 ranks x 4 samples: each rank's slice is under the min_count floor
    (vacuously healthy alone), but the merged 12-sample window burns —
    rank 2's chaos-delayed serves are 4/12 = 33% over a 10% budget."""
    return [
        _mk_export([[0.01] * 4]),
        _mk_export([[0.01] * 4]),
        _mk_export([[1.0] * 4]),
    ]


def test_min_count_floor_trips_cluster_not_ranks(tele, slo_cfg):
    core.config["slo"] = [_min_count_spec()]
    exports = _skewed_rank_windows()
    for e in exports:  # each rank alone: below the floor, no verdict fires
        reader = telemetry.MergedWindows(telemetry.merge_windows([e]))
        health = slo.cluster_health(reader)
        assert health["healthy"], "a thin per-rank slice must stay healthy"
    merged = telemetry.MergedWindows(telemetry.merge_windows(exports))
    health = slo.cluster_health(merged)
    assert not health["healthy"]
    assert health["failing"] == ["fleet_lat"]


def test_cluster_failure_flips_rank0_healthz(tele, slo_cfg):
    core.config["slo"] = [_min_count_spec()]
    host, port = export.start_server(0)
    try:
        # no cluster view yet + empty local windows: healthy
        resp = urllib.request.urlopen(f"http://{host}:{port}/healthz", timeout=5)
        assert resp.status == 200
        exports = _skewed_rank_windows()
        _run_ranks(
            3,
            lambda r, rdv: fleet.ops_round(
                rdv, force=True, payload=_rank_payload(r, windows=exports[r])
            ),
        )
        # local verdict alone is still healthy (this rank's windows are
        # empty); the merged cluster view flips the probe to 503
        assert slo.health(fresh=True)["healthy"]
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(f"http://{host}:{port}/healthz", timeout=5)
        assert exc_info.value.code == 503
        verdict = json.loads(exc_info.value.read())
        assert verdict["cluster"]["healthy"] is False
        assert verdict["cluster"]["failing"] == ["fleet_lat"]
        # the /metrics surface carries the rank="cluster" rollup
        text = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5
        ).read().decode()
        assert 'srml_cluster_healthy{rank="cluster"} 0' in text
        assert 'srml_cluster_ranks_reporting{rank="cluster"} 3' in text
    finally:
        export.stop_server()


# ----------------------------------------------------------- stragglers -----


def test_straggler_named_in_flight_recorder_and_audit(tele):
    saved = {
        k: core.config[k]
        for k in ("fleet_straggler_windows", "fleet_straggler_min_lag_s")
    }
    core.config["fleet_straggler_windows"] = 3
    core.config["fleet_straggler_min_lag_s"] = 0.05
    try:
        def fit(rank, rdv):
            base = 1000.0
            for i in range(3):  # 3 consecutive ops rounds, rank 2 lagging
                lag = 0.2 if rank == 2 else 0.0
                fleet.ops_round(
                    rdv, force=True,
                    payload=_rank_payload(
                        rank,
                        round_exits=[[0, i, base + i + lag, base + i + 0.3]],
                    ),
                )

        _run_ranks(3, fit)
        view = fleet.cluster_view()
        assert view["straggler"]["lags_s"][2] == pytest.approx(0.2)
        events = [
            e for e in diagnostics.flight_recorder().events()
            if e["kind"] == "straggler_detected"
        ]
        assert len(events) == 1 and events[0]["rank"] == 2
        assert events[0]["rounds"] == 3
        flagged = [d for d in audit.decisions() if d["kind"] == "straggler"]
        assert len(flagged) == 1
        assert flagged[0]["subject"] == "rank:2"
        assert flagged[0]["verdict"] == "flagged"
        counters = telemetry.registry().snapshot()["counters"]
        assert counters["fleet.stragglers_flagged"] == 1.0
        assert (
            telemetry.registry().snapshot()["gauges"]["rendezvous.straggler_lag_s"]
            == pytest.approx(0.2)
        )
    finally:
        core.config.update(saved)


def test_straggler_below_min_lag_never_fires(tele):
    def fit(rank, rdv):
        base = 1000.0
        for i in range(4):
            lag = 0.001 if rank == 1 else 0.0  # below the 50ms floor
            fleet.ops_round(
                rdv, force=True,
                payload=_rank_payload(
                    rank, round_exits=[[0, i, base + i + lag, base + i + 0.3]]
                ),
            )

    _run_ranks(2, fit)
    assert [d for d in audit.decisions() if d["kind"] == "straggler"] == []


# ------------------------------------------------- snapshots + exporters ----


def test_report_meta_header(tele):
    rep = ops_plane.report()
    meta = rep["meta"]
    assert meta["rank"] == 0
    assert meta["hostname"] == socket.gethostname()
    assert meta["pid"] == os.getpid()
    assert meta["t"] == pytest.approx(time.time(), abs=60.0)
    assert "trace_id" in meta  # None outside a trace, the id inside one
    assert "windows_detail" in rep  # what the offline merger keys on


def test_write_snapshot_rank_aware_naming(tele, tmp_path):
    saved = core.config["ops_snapshot_dir"]
    core.config["ops_snapshot_dir"] = str(tmp_path)
    try:
        diagnostics.set_process_rank(2)
        path = export.write_snapshot()
        assert os.path.basename(path) == "ops_snapshot_rank_2.json"
        diagnostics.set_process_rank(0)
        path = export.write_snapshot()
        assert os.path.basename(path) == "ops_snapshot.json"
        with open(path) as f:
            assert json.load(f)["meta"]["rank"] == 0
    finally:
        diagnostics._PROCESS_RANK = None
        core.config["ops_snapshot_dir"] = saved


def test_ensure_server_rank0_only_by_default(tele, monkeypatch):
    monkeypatch.setenv("SRML_METRICS_PORT", "12345")
    monkeypatch.delenv("SRML_METRICS_ALL_RANKS", raising=False)
    diagnostics.set_process_rank(1)
    try:
        # rank 1 without the opt-in binds NOTHING (no port collision)
        assert export.ensure_server() is None
        assert export.server_address() is None
    finally:
        diagnostics._PROCESS_RANK = None


def test_ensure_server_all_ranks_offsets_port(tele, monkeypatch):
    with socket.socket() as s:  # a known-free port for rank 1 to land on
        s.bind(("127.0.0.1", 0))
        free = s.getsockname()[1]
    monkeypatch.setenv("SRML_METRICS_PORT", str(free - 1))
    monkeypatch.setenv("SRML_METRICS_ALL_RANKS", "1")
    diagnostics.set_process_rank(1)
    try:
        addr = export.ensure_server()
        assert addr is not None and addr[1] == free  # base port + rank
    finally:
        export.stop_server()
        diagnostics._PROCESS_RANK = None


# ---------------------------------------------------- offline + opsreport ---


def _write_rank_snapshot(directory, rank, t=None):
    rep = ops_plane.report()
    rep["meta"] = dict(rep["meta"], rank=rank, t=t or time.time())
    name = "ops_snapshot.json" if rank == 0 else f"ops_snapshot_rank_{rank}.json"
    with open(os.path.join(directory, name), "w") as f:
        json.dump(rep, f, default=str)


def test_read_rank_snapshots_names_missing_and_stale(tele, tmp_path):
    _write_rank_snapshot(tmp_path, 0)
    _write_rank_snapshot(tmp_path, 1, t=time.time() - 10_000)  # stale
    reports, issues = fleet.read_rank_snapshots(str(tmp_path), nranks=3)
    assert [r["meta"]["rank"] for r in reports] == [0]
    assert issues["stale"] == [1]
    assert issues["missing"] == [2]
    view = fleet.merge_reports(reports, expected=3)
    assert view["missing"] == [1, 2]  # named, never silently averaged in


def test_opsreport_cluster_partial_exit_code(tele, tmp_path, capsys):
    _write_rank_snapshot(tmp_path, 0)
    _write_rank_snapshot(tmp_path, 1)
    rc = opsreport.main(["--cluster", str(tmp_path), "--nranks", "3"])
    out = capsys.readouterr().out
    assert rc == opsreport.EXIT_PARTIAL  # half-dead fleet: distinct verdict
    assert "2/3 rank(s) reporting" in out
    assert "missing rank(s): 2" in out
    _write_rank_snapshot(tmp_path, 2)
    rc = opsreport.main(["--cluster", str(tmp_path), "--nranks", "3"])
    assert rc == opsreport.EXIT_HEALTHY
    assert "3/3 rank(s) reporting" in capsys.readouterr().out


def test_opsreport_cluster_no_snapshots_unreadable(tele, tmp_path, capsys):
    rc = opsreport.main(["--cluster", str(tmp_path)])
    capsys.readouterr()
    assert rc == opsreport.EXIT_UNREADABLE


def test_opsreport_cluster_live_view(tele, capsys):
    _run_ranks(
        3,
        lambda r, rdv: fleet.ops_round(
            rdv, force=True,
            payload=_rank_payload(r, counters={"fleet_test.work": 1.0}),
        ),
    )
    rc = opsreport.main(["--cluster"])
    out = capsys.readouterr().out
    assert rc == opsreport.EXIT_HEALTHY
    assert "3/3 rank(s) reporting" in out
