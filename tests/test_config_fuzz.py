#
# Seeded config fuzz: random VALID param combinations across the estimator
# surface, each driven fit -> transform -> save/load -> transform-parity on
# tiny data. Catches param-plumbing, solver-edge and persistence crashes
# that targeted tests don't enumerate. Deterministic per seed.
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.linalg import Vectors


def _df(rng, n=80, d=6):
    x = rng.normal(size=(n, d))
    y_bin = (x[:, 0] > 0).astype(float)
    y_reg = x @ rng.normal(size=d) + 0.1 * rng.normal(size=n)
    return pd.DataFrame(
        {"features": [Vectors.dense(r) for r in x], "label": y_bin, "target": y_reg}
    )


def _roundtrip(model, df, tmp_path, tag):
    out1 = model.transform(df)
    pred_col = [c for c in out1.columns if c not in ("features", "label", "target")][0]
    path = str(tmp_path / tag)
    model.write().overwrite().save(path)
    from spark_rapids_ml_tpu.core import load_instance

    loaded = load_instance(path)
    out2 = loaded.transform(df)
    a = np.asarray([np.asarray(v).ravel() for v in out1[pred_col]], dtype=np.float64)
    b = np.asarray([np.asarray(v).ravel() for v in out2[pred_col]], dtype=np.float64)
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-8)


@pytest.mark.parametrize("seed", range(4))
def test_estimator_config_fuzz(seed, tmp_path):
    from spark_rapids_ml_tpu.models.classification import (
        LogisticRegression,
        RandomForestClassifier,
    )
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.models.regression import (
        LinearRegression,
        RandomForestRegressor,
    )

    rng = np.random.default_rng(seed)
    df = _df(rng)
    pick = lambda *opts: opts[int(rng.integers(len(opts)))]  # noqa: E731

    cases = [
        (
            "pca",
            PCA(
                k=int(rng.integers(1, 6)),
                inputCol="features",
                outputCol="o",
                float32_inputs=pick(True, False),
            ),
        ),
        (
            "kmeans",
            KMeans(
                k=int(rng.integers(2, 8)),
                maxIter=int(rng.integers(2, 15)),
                initMode=pick("k-means||", "random"),
                seed=int(rng.integers(100)),
                tol=float(pick(0.0, 1e-6, 1e-2)),
            ).setFeaturesCol("features"),
        ),
        (
            "linreg",
            LinearRegression(
                regParam=float(pick(0.0, 1e-3, 0.5)),
                elasticNetParam=float(pick(0.0, 0.3, 1.0)),
                fitIntercept=pick(True, False),
                standardization=pick(True, False),
                labelCol="target",
                float32_inputs=pick(True, False),
            ).setFeaturesCol("features"),
        ),
        (
            "logreg",
            LogisticRegression(
                regParam=float(pick(0.0, 1e-3, 0.1)),
                elasticNetParam=float(pick(0.0, 0.5)),
                maxIter=int(rng.integers(5, 40)),
                fitIntercept=pick(True, False),
                standardization=pick(True, False),
            ).setFeaturesCol("features"),
        ),
        (
            "rfc",
            RandomForestClassifier(
                numTrees=int(rng.integers(1, 6)),
                maxDepth=int(rng.integers(1, 6)),
                maxBins=int(pick(4, 16, 32)),
                impurity=pick("gini", "entropy"),
                featureSubsetStrategy=pick("auto", "all", "sqrt"),
                bootstrap=pick(True, False),
                seed=int(rng.integers(100)),
            ).setFeaturesCol("features"),
        ),
        (
            "rfr",
            RandomForestRegressor(
                numTrees=int(rng.integers(1, 5)),
                maxDepth=int(rng.integers(1, 5)),
                maxBins=int(pick(4, 16)),
                subsamplingRate=float(pick(0.5, 1.0)),
                labelCol="target",
                seed=int(rng.integers(100)),
            ).setFeaturesCol("features"),
        ),
    ]
    for tag, est in cases:
        model = est.fit(df)
        _roundtrip(model, df, tmp_path, f"{tag}_{seed}")


@pytest.mark.parametrize("seed", range(2))
def test_clustering_manifold_config_fuzz(seed):
    # DBSCAN (fit-is-noop, transform clusters) and UMAP (graph + SGD layout)
    # under randomized valid configs — no persistence round-trip for DBSCAN
    # labels (transform is the work), UMAP checked for finite embeddings
    from spark_rapids_ml_tpu.models.clustering import DBSCAN
    from spark_rapids_ml_tpu.models.umap import UMAP

    rng = np.random.default_rng(100 + seed)
    df = _df(rng, n=120, d=5)
    pick = lambda *opts: opts[int(rng.integers(len(opts)))]  # noqa: E731

    db = DBSCAN(
        eps=float(pick(0.3, 1.0, 3.0)),
        min_samples=int(rng.integers(2, 8)),
        metric=pick("euclidean", "cosine"),
        calc_core_sample_indices=pick(True, False),
    ).setFeaturesCol("features")
    out = db.fit(df).transform(df)
    labels = out["prediction"].to_numpy()
    assert len(labels) == len(df) and (labels >= -1).all()

    um = UMAP(
        n_neighbors=int(rng.integers(4, 12)),
        n_components=int(pick(2, 3)),
        n_epochs=int(pick(30, 80)),
        init=pick("spectral", "random"),
        metric=pick("euclidean", "cosine"),
        min_dist=float(pick(0.05, 0.5)),
        negative_sample_rate=int(pick(2, 5)),
        random_state=seed,
    ).setFeaturesCol("features")
    m = um.fit(df)
    emb = np.asarray(m.embedding_)
    assert np.isfinite(emb).all() and emb.shape[0] == len(df)
    t = m.transform(df.head(20))
    assert np.isfinite(np.stack(t[m.getOutputCol()].to_list())).all()
