#
# Feature-type x dtype sweep (the reference's per-algo parametrization:
# vector / array / multi-col inputs x float32 / float64 — e.g.
# test_pca.py/test_linear_regression.py run every combination). One sweep here
# covers the shared ingest/transform plumbing for four algorithms.
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.linalg import Vectors
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.clustering import KMeans
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.models.regression import LinearRegression


def _make(rng, n=200, d=5):
    x = rng.normal(size=(n, d))
    y = x @ rng.normal(size=d) + 0.3
    return x, y


def _dataset(x, feature_type, extra=None):
    if feature_type == "vector":
        df = pd.DataFrame({"features": [Vectors.dense(row) for row in x]})
    elif feature_type == "array":
        df = pd.DataFrame({"features": list(x)})
    else:  # multi_cols
        df = pd.DataFrame({f"c{j}": x[:, j] for j in range(x.shape[1])})
    if extra:
        for k, v in extra.items():
            df[k] = v
    return df


def _feature_setter(est, feature_type, d):
    # Spark parity: feature.PCA uses inputCol; the predictors use featuresCol
    setter = est.setInputCol if hasattr(est, "setInputCol") else est.setFeaturesCol
    if feature_type == "multi_cols":
        return setter([f"c{j}" for j in range(d)])
    return setter("features")


FEATURE_TYPES = ["vector", "array", "multi_cols"]
DTYPES = [True, False]  # float32_inputs


@pytest.mark.parametrize("feature_type", FEATURE_TYPES)
@pytest.mark.parametrize("f32", DTYPES)
def test_pca_feature_type_dtype(rng, feature_type, f32):
    x, _ = _make(rng)
    df = _dataset(x, feature_type)
    est = _feature_setter(PCA(k=2, float32_inputs=f32), feature_type, x.shape[1])
    model = est.fit(df)
    comps = np.asarray(model.components_)
    assert comps.shape == (2, 5)
    # same subspace regardless of ingest path
    ref = PCA(k=2, float32_inputs=False).setInputCol("features").fit(
        _dataset(x, "array")
    )
    np.testing.assert_allclose(
        np.abs(comps), np.abs(np.asarray(ref.components_)),
        atol=1e-3 if f32 else 1e-8,
    )
    out = model.transform(df)
    assert len(out) == len(df)


@pytest.mark.parametrize("feature_type", FEATURE_TYPES)
@pytest.mark.parametrize("f32", DTYPES)
def test_linear_feature_type_dtype(rng, feature_type, f32):
    x, y = _make(rng)
    df = _dataset(x, feature_type, {"label": y})
    est = _feature_setter(
        LinearRegression(regParam=0.0, float32_inputs=f32), feature_type, x.shape[1]
    )
    model = est.fit(df)
    ref = (
        LinearRegression(regParam=0.0, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(_dataset(x, "array", {"label": y}))
    )
    np.testing.assert_allclose(
        np.asarray(model.coef_), np.asarray(ref.coef_), atol=1e-3 if f32 else 1e-9
    )
    pred = model.transform(df)["prediction"].to_numpy()
    assert np.corrcoef(pred, y)[0, 1] > 0.99


@pytest.mark.parametrize("feature_type", FEATURE_TYPES)
@pytest.mark.parametrize("f32", DTYPES)
def test_logistic_feature_type_dtype(rng, feature_type, f32):
    x, y = _make(rng)
    lab = (y > y.mean()).astype(np.float64)
    df = _dataset(x, feature_type, {"label": lab})
    est = _feature_setter(
        LogisticRegression(maxIter=50, float32_inputs=f32), feature_type, x.shape[1]
    )
    model = est.fit(df)
    out = model.transform(df)
    acc = (np.asarray(out["prediction"]) == lab).mean()
    assert acc > 0.9
    # output column types: vector input -> vector probability column
    p0 = out["probability"].iloc[0]
    if feature_type == "vector":
        assert hasattr(p0, "toArray")
    else:
        assert isinstance(np.asarray(p0), np.ndarray)


@pytest.mark.parametrize("feature_type", FEATURE_TYPES)
@pytest.mark.parametrize("f32", DTYPES)
def test_kmeans_feature_type_dtype(rng, feature_type, f32):
    from sklearn.datasets import make_blobs

    x, true = make_blobs(n_samples=300, n_features=4, centers=3, random_state=2)
    df = _dataset(x, feature_type)
    est = _feature_setter(
        KMeans(k=3, seed=1, maxIter=20, float32_inputs=f32), feature_type, x.shape[1]
    )
    model = est.fit(df)
    labels = model.transform(df)["prediction"].to_numpy()
    from sklearn.metrics import adjusted_rand_score

    assert adjusted_rand_score(true, labels) > 0.95
