#
# Elastic recovery tests (docs/robustness.md "Elastic recovery"): solver
# checkpoints that make a resumed fit bit-identical to an uninterrupted one,
# survivor re-meshing through membership reform, host-retained re-placement,
# and the sweep completion ledger. The subprocess SIGKILL-mid-solve harness
# lives in tests/test_chaos.py (it shares the chaos_worker launcher).
#
import threading
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import checkpoint as ckpt
from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu import telemetry
from spark_rapids_ml_tpu.errors import RankFailedError, RendezvousTimeoutError
from spark_rapids_ml_tpu.parallel import FileRendezvous, LocalRendezvous, chaos


@pytest.fixture(autouse=True)
def _clean_state():
    chaos.clear_fault_plan()
    saved = {
        k: core_mod.config[k]
        for k in (
            "checkpoint_every_iters", "recovery_max_rank_losses",
            "fit_retry_backoff_s", "sweep_max_resumes",
        )
    }
    core_mod.config["fit_retry_backoff_s"] = 0.01
    telemetry.enable()
    telemetry.registry().reset()
    yield
    chaos.clear_fault_plan()
    core_mod.config.update(saved)
    telemetry.disable()


def _counters():
    return telemetry.registry().snapshot()["counters"]


# ------------------------------------------------------------ store basics --


def test_checkpoint_scope_isolation_and_adoption():
    assert ckpt.active_store() is None
    with ckpt.checkpoint_scope() as outer:
        assert ckpt.active_store() is outer
        outer.save("k", ckpt.SolverCheckpoint(solver="s", iteration=1, state={}))
        with ckpt.ensure_scope() as inner:  # adopts, does not shadow
            assert inner is outer
            assert len(inner) == 1
        assert len(outer) == 1  # the nested exit did NOT clear the store
    assert ckpt.active_store() is None


def test_checkpoint_scope_clears_on_exit():
    with ckpt.checkpoint_scope() as store:
        store.save("k", ckpt.SolverCheckpoint(solver="s", iteration=3, state={}))
    assert len(store) == 0  # per-stage: checkpoints never leak across fits


def test_get_or_compute_is_placement_keyed():
    calls = []

    def compute():
        calls.append(1)
        return {"G": np.eye(2)}

    with ckpt.checkpoint_scope() as store:
        a = store.get_or_compute("stats", compute, solver="linear", placement_key=("m1",))
        b = store.get_or_compute("stats", compute, solver="linear", placement_key=("m1",))
        assert a is b and len(calls) == 1
        assert _counters()["checkpoint.stats_reuses"] == 1
        # a DIFFERENT placement (survivor mesh) must recompute, not reuse
        store.get_or_compute("stats", compute, solver="linear", placement_key=("m2",))
        assert len(calls) == 2


def test_solver_checkpoints_active_requires_cadence_and_store():
    core_mod.config["checkpoint_every_iters"] = 0
    with ckpt.checkpoint_scope():
        assert not ckpt.solver_checkpoints_active()
    core_mod.config["checkpoint_every_iters"] = 2
    assert not ckpt.solver_checkpoints_active()  # no store installed
    with ckpt.checkpoint_scope():
        assert ckpt.solver_checkpoints_active()


# --------------------------------------------- solver-level resume pinning --


def _blob_df(rng, n=600, d=5):
    x = rng.normal(size=(n, d)).astype(np.float32)
    return pd.DataFrame({"features": list(x)}), x


def test_kmeans_interrupted_fit_resumes_bit_identical(rng):
    # THE acceptance pin: a fit interrupted mid-solve (transient fault at a
    # checkpoint boundary) retries, RESUMES from the checkpoint — counted —
    # and its model is bit-identical to an uninterrupted checkpointed fit.
    from spark_rapids_ml_tpu.models.clustering import KMeans

    df, _ = _blob_df(rng)
    core_mod.config["checkpoint_every_iters"] = 3

    clean = KMeans(k=8, maxIter=10, tol=0.0, seed=7).fit(df)
    assert _counters()["checkpoint.saves"] >= 3

    chaos.set_fault_plan("fail:stage=solve:times=1")
    telemetry.registry().reset()
    resumed = KMeans(k=8, maxIter=10, tol=0.0, seed=7).fit(df)
    snap = _counters()
    np.testing.assert_array_equal(
        resumed.cluster_centers_, clean.cluster_centers_
    )
    assert resumed.n_iter_ == clean.n_iter_
    assert snap["fit.retries"] == 1
    assert snap["checkpoint.restores"] >= 1  # resumed, not restarted


def test_kmeans_checkpointing_does_not_change_the_fit(rng):
    # cadence on vs off: the checkpoint fetches add host syncs, never math
    from spark_rapids_ml_tpu.models.clustering import KMeans

    df, _ = _blob_df(rng)
    plain = KMeans(k=6, maxIter=8, tol=0.0, seed=3).fit(df)
    core_mod.config["checkpoint_every_iters"] = 2
    ckpted = KMeans(k=6, maxIter=8, tol=0.0, seed=3).fit(df)
    np.testing.assert_array_equal(plain.cluster_centers_, ckpted.cluster_centers_)


def test_logistic_interrupted_fit_resumes_bit_identical(rng):
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    df, x = _blob_df(rng)
    y = (x @ rng.normal(size=x.shape[1]) > 0).astype(float)
    df = df.assign(label=y)
    core_mod.config["checkpoint_every_iters"] = 4

    clean = LogisticRegression(maxIter=20).fit(df)
    chaos.set_fault_plan("fail:stage=solve:times=1")
    telemetry.registry().reset()
    resumed = LogisticRegression(maxIter=20).fit(df)
    snap = _counters()
    np.testing.assert_array_equal(resumed.coef_, clean.coef_)
    np.testing.assert_array_equal(resumed.intercept_, clean.intercept_)
    assert resumed.n_iter_ == clean.n_iter_
    assert snap["checkpoint.restores"] >= 1


def test_elasticnet_interrupted_fit_resumes_bit_identical(rng):
    # the OWL-QN (L1) segmented loop shares the driver; pin it separately
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    df, x = _blob_df(rng)
    y = (x @ rng.normal(size=x.shape[1]) > 0).astype(float)
    df = df.assign(label=y)
    core_mod.config["checkpoint_every_iters"] = 4

    def make():
        return LogisticRegression(maxIter=20, regParam=0.05, elasticNetParam=0.5)

    clean = make().fit(df)
    chaos.set_fault_plan("fail:stage=solve:times=1")
    telemetry.registry().reset()
    resumed = make().fit(df)
    np.testing.assert_array_equal(resumed.coef_, clean.coef_)
    assert _counters()["checkpoint.restores"] >= 1


def test_glm_segment_boundaries_are_lossless(rng):
    # THE segmentation contract: boundary host round-trips never change the
    # math. A 5-iteration cadence (5 boundaries) must be BIT-identical to a
    # cadence larger than maxIter (one segment, zero mid-solve boundaries) —
    # same traced body, same compiled segment program, lossless fetches.
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    df, x = _blob_df(rng)
    y = (x @ rng.normal(size=x.shape[1]) > 0).astype(float)
    df = df.assign(label=y)
    core_mod.config["checkpoint_every_iters"] = 100  # > maxIter: one segment
    one_seg = LogisticRegression(maxIter=25).fit(df)
    core_mod.config["checkpoint_every_iters"] = 5
    many_seg = LogisticRegression(maxIter=25).fit(df)
    assert many_seg.n_iter_ == one_seg.n_iter_
    np.testing.assert_array_equal(many_seg.coef_, one_seg.coef_)
    np.testing.assert_array_equal(many_seg.intercept_, one_seg.intercept_)


def test_glm_segmented_matches_monolithic(rng):
    # checkpointed (segmented) vs one-program solver: identical closures and
    # iteration count, but DIFFERENT compiled programs (the monolithic loop
    # wraps the body in freeze_when_done inside one lax.while_loop; the
    # segmented driver jits the body with a seg_end bound), so XLA may
    # reassociate f32 reductions differently and the batched Armijo line
    # search can pick a different step when candidates differ by an ulp.
    # The documented contract (docs/robustness.md "Elastic recovery") is
    # numerical equivalence on a well-conditioned problem — bit-identity is
    # only promised segmented-vs-segmented (pinned above and by the
    # interrupted-resume tests).
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    df, x = _blob_df(rng)
    # noisy labels + ridge keep the minimizer finite and the comparison
    # well-conditioned (a separable unregularized fit amplifies ulp noise
    # exponentially — coefficients diverge, only their direction converges)
    y = ((x @ rng.normal(size=x.shape[1]) + rng.normal(size=len(x))) > 0).astype(float)
    df = df.assign(label=y)

    def make():
        return LogisticRegression(maxIter=25, regParam=0.01)

    plain = make().fit(df)
    core_mod.config["checkpoint_every_iters"] = 5
    seg = make().fit(df)
    assert seg.n_iter_ == plain.n_iter_
    np.testing.assert_allclose(seg.coef_, plain.coef_, rtol=0, atol=5e-3)


def test_linear_retry_reuses_retained_stats(rng):
    # linear-family checkpoint = the sufficient statistics: an interrupted
    # fit's retry must SKIP the data pass (stats_reuses) and produce a
    # bit-identical model
    from spark_rapids_ml_tpu.models.regression import LinearRegression

    df, x = _blob_df(rng)
    df = df.assign(label=(x @ rng.normal(size=x.shape[1])).astype(np.float32))
    core_mod.config["checkpoint_every_iters"] = 1

    clean = LinearRegression().fit(df)
    chaos.set_fault_plan("fail:stage=solve:times=1")
    telemetry.registry().reset()
    resumed = LinearRegression().fit(df)
    snap = _counters()
    np.testing.assert_array_equal(
        np.asarray(resumed.coef_), np.asarray(clean.coef_)
    )
    assert snap["checkpoint.stats_reuses"] >= 1
    assert snap["fit.retries"] == 1


def test_pca_retry_reuses_retained_stats(rng):
    from spark_rapids_ml_tpu.models.feature import PCA

    df, _ = _blob_df(rng)
    core_mod.config["checkpoint_every_iters"] = 1
    clean = PCA(k=3).fit(df)
    chaos.set_fault_plan("fail:stage=solve:times=1")
    telemetry.registry().reset()
    resumed = PCA(k=3).fit(df)
    np.testing.assert_array_equal(resumed.components_, clean.components_)
    assert _counters()["checkpoint.stats_reuses"] >= 1


def test_checkpoint_disabled_by_default_costs_nothing(rng):
    # cadence 0 (the default): no store interaction, no counters, identical fit
    from spark_rapids_ml_tpu.models.clustering import KMeans

    df, _ = _blob_df(rng)
    assert core_mod.config["checkpoint_every_iters"] == 0
    KMeans(k=4, maxIter=5, seed=1).fit(df)
    snap = _counters()
    assert "checkpoint.saves" not in snap
    assert "checkpoint.restores" not in snap


# ------------------------------------------------- recoverable_stage (unit) --


def test_recoverable_stage_reforms_and_resumes_local():
    # 3 thread-ranks; rank 2 dies at round 1 of "iteration" traffic. The
    # survivors must reform to a 2-rank group, re-enter the stage, and
    # complete — with the recovery counters advancing and the checkpoint
    # store surviving the epoch.
    nranks = 3
    rvs = LocalRendezvous.create(nranks, timeout_s=15.0)
    results = [None] * nranks
    core_mod.config["recovery_max_rank_losses"] = 1

    def work(r):
        holder = {"rdv": rvs[r]}

        def fit(attempt):
            rdv = holder["rdv"]
            store = ckpt.active_store()
            saved = store.load("it") if store is not None else None
            start = 0 if saved is None else int(saved.iteration)
            for it in range(start, 4):
                if r == 2 and it == 1:
                    # rank 2 "dies": publish and unwind (the graceful-death
                    # shape; SIGKILL needs processes — tests/test_chaos.py)
                    rdv.abort("rank 2 died")
                    raise RuntimeError("rank 2 died")
                rdv.allgather(f"{rdv.rank}:{it}")
                store.save("it", ckpt.SolverCheckpoint(
                    solver="unit", iteration=it + 1, state={}
                ))
            return ("done", rdv.nranks, list(rdv.live_ranks), start)

        try:
            results[r] = core_mod.recoverable_stage(
                fit, stage="fit", rendezvous=rvs[r],
                on_recover=lambda new, gen, dead: holder.update(rdv=new),
            )
        except Exception as e:  # noqa: BLE001 - asserted below
            results[r] = e

    threads = [threading.Thread(target=work, args=(r,)) for r in range(nranks)]
    [t.start() for t in threads]
    [t.join(timeout=60) for t in threads]
    assert not any(t.is_alive() for t in threads)

    # the dead rank raised its own error; survivors completed on the
    # reformed 2-rank group, RESUMING from their checkpoints (start > 0)
    assert isinstance(results[2], RuntimeError)
    for r in (0, 1):
        status, n, live, start = results[r]
        assert status == "done"
        assert n == 2 and live == [0, 1]
        assert start >= 1, "survivor restarted from scratch instead of resuming"
    snap = _counters()
    assert snap["fit.recoveries"] == 2  # one per survivor
    assert snap["recovery.epochs"] == 2
    assert snap["rendezvous.reforms"] == 2


def test_recoverable_stage_exhaustion_degrades_to_typed_failure():
    # recovery budget 0: the RankFailedError propagates exactly as before,
    # stamped with how far recovery got (never opened here)
    core_mod.config["recovery_max_rank_losses"] = 0
    rdv = LocalRendezvous.create(1, timeout_s=5.0)[0]

    def fit(attempt):
        raise RankFailedError(0, "peer gone")

    with pytest.raises(RankFailedError) as ei:
        core_mod.recoverable_stage(fit, stage="fit", rendezvous=rdv)
    assert ei.value.recovery_exhausted is False
    assert ei.value.recovery_generations == 0


def test_recoverable_stage_unreformable_substrate_degrades():
    class _NoReform:
        rank, nranks = 0, 2
        can_reform = False

        def begin_epoch(self, e):
            pass

    def fit(attempt):
        raise RankFailedError(1, "dead")

    with pytest.raises(RankFailedError):
        core_mod.recoverable_stage(fit, stage="fit", rendezvous=_NoReform())


def test_recoverable_stage_bounded_losses():
    # every epoch loses another rank; the budget must bound the loop and the
    # final error must carry the exhaustion stamp
    core_mod.config["recovery_max_rank_losses"] = 2
    nranks = 4
    rvs = LocalRendezvous.create(nranks, timeout_s=10.0)
    attempts = []

    def work(r):
        holder = {"rdv": rvs[r]}

        def fit(attempt):
            rdv = holder["rdv"]
            attempts.append(rdv.nranks)
            # the highest-numbered CURRENT rank always dies
            if rdv.rank == rdv.nranks - 1:
                rdv.abort("serial failure")
                raise RuntimeError("died")
            rdv.allgather(f"{rdv.rank}")
            rdv.allgather(f"{rdv.rank}")
            raise RankFailedError(rdv.nranks - 1, "peer still dying")

        try:
            return core_mod.recoverable_stage(
                fit, stage="fit", rendezvous=rvs[r],
                on_recover=lambda new, gen, dead: holder.update(rdv=new),
            )
        except Exception as e:  # noqa: BLE001
            return e

    out = [None] * nranks
    threads = [
        threading.Thread(target=lambda rr=r: out.__setitem__(rr, work(rr)))
        for r in range(nranks)
    ]
    [t.start() for t in threads]
    [t.join(timeout=60) for t in threads]
    assert not any(t.is_alive() for t in threads)
    # rank 0 survived every epoch; after 2 losses the budget is exhausted
    assert isinstance(out[0], RankFailedError)
    assert out[0].recovery_exhausted is True
    assert out[0].recovery_generations == 2


# ------------------------------------------------- FileRendezvous reform ----


def test_file_reform_survivors_agree(tmp_path):
    nranks = 3
    rvs = [
        FileRendezvous(r, nranks, str(tmp_path), timeout_s=10.0, run_id="t",
                       heartbeat_interval_s=0.2)
        for r in range(nranks)
    ]
    out = [None, None]

    def work(r):
        out[r] = rvs[r].reform(dead_ranks={2}, generation=1)

    threads = [threading.Thread(target=work, args=(r,)) for r in (0, 1)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert not any(t.is_alive() for t in threads)
    for r in (0, 1):
        assert out[r].nranks == 2
        assert out[r].live_ranks == [0, 1]
        assert out[r].orig_rank == r
        assert out[r].reform_generation == 1
    # the reformed plane works end to end
    res = [None, None]

    def gather(r):
        res[r] = out[r].allgather(f"hello{r}")

    threads = [threading.Thread(target=gather, args=(r,)) for r in (0, 1)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert res[0] == res[1] == ["hello0", "hello1"]
    for r in rvs + out:
        r.close()


def test_file_reform_admits_respawned_rank(tmp_path):
    # survivors hold the window open (rejoin grace); a respawned incarnation
    # of the dead rank votes inside it and joins at the epoch boundary
    saved = core_mod.config["recovery_rejoin_grace_s"]
    core_mod.config["recovery_rejoin_grace_s"] = 1.5
    nranks = 3
    rvs = [
        FileRendezvous(r, nranks, str(tmp_path), timeout_s=15.0, run_id="t",
                       heartbeat_interval_s=0.2)
        for r in range(nranks)
    ]
    out = {}

    def survivor(r):
        out[r] = rvs[r].reform(dead_ranks={2}, generation=1)

    def respawn():
        time.sleep(0.3)  # arrives after the window opened
        fresh = FileRendezvous(2, nranks, str(tmp_path), timeout_s=15.0,
                               run_id="t", heartbeat_interval_s=0.2)
        out[2] = fresh.rejoin()

    threads = [threading.Thread(target=survivor, args=(r,)) for r in (0, 1)]
    threads.append(threading.Thread(target=respawn))
    try:
        [t.start() for t in threads]
        [t.join(timeout=60) for t in threads]
        assert not any(t.is_alive() for t in threads)
        for r in range(3):
            assert out[r].nranks == 3, f"rank {r} saw {out[r].nranks} members"
            assert out[r].live_ranks == [0, 1, 2]
            assert out[r].orig_rank == r
    finally:
        core_mod.config["recovery_rejoin_grace_s"] = saved
        for r in list(out.values()) + rvs:
            r.close()


def test_file_reform_declares_silent_rank_dead(tmp_path):
    # a peer that neither votes nor heartbeats within the staleness window is
    # declared dead by the reform round; the lone survivor gets a 1-rank group
    r0 = FileRendezvous(0, 2, str(tmp_path), timeout_s=2.0, run_id="t",
                        heartbeat_interval_s=0.2)
    try:
        new = r0.reform(dead_ranks={1}, generation=1)
        assert new.live_ranks == [0]
        assert new.nranks == 1
    finally:
        r0.close()


def test_survivor_mesh_drops_dead_process_devices():
    import jax

    from spark_rapids_ml_tpu.parallel.mesh import get_mesh, survivor_mesh

    mesh = get_mesh(4)
    # CPU test topology: every device is process 0 — excluding a fictional
    # dead process keeps everything; excluding process 0 must raise
    same = survivor_mesh(mesh, {7})
    assert same.devices.size == mesh.devices.size
    with pytest.raises(ValueError):
        survivor_mesh(mesh, {0})


# --------------------------------------- host-retained re-placement (core) --


def test_replacement_reuses_host_blocks_after_mesh_change(rng):
    # one fit on an 8-device mesh, then the "survivor mesh" shape: the same
    # data on a 4-device mesh inside one scope. The second fit must skip
    # ingest entirely (host blocks retained) and only re-run layout.
    from spark_rapids_ml_tpu.models.clustering import KMeans

    df, _ = _blob_df(rng)
    with core_mod.device_dataset_scope():
        KMeans(k=4, maxIter=3, seed=1, num_workers=8).fit(df)
        snap1 = _counters()
        KMeans(k=4, maxIter=3, seed=1, num_workers=4).fit(df)
        snap2 = _counters()
    assert snap1.get("fit.device_dataset_builds") == 1
    assert snap2.get("recovery.replacements") == 1
    assert snap2.get("recovery.rows_replaced") == 600
    # ingest ran ONCE: the dataset counter did not advance on the re-placement
    assert snap2.get("ingest.datasets") == snap1.get("ingest.datasets")


# ------------------------------------------------------ sweep ledger (CV) ---


class _Evaluator:
    def getMetricName(self):
        return "accuracy"

    def isLargerBetter(self):
        return True


def _cv_setup(rng, fail_at_fit=None):
    from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

    x = rng.normal(size=(300, 5)).astype(np.float32)
    y = (x @ rng.normal(size=5) > 0).astype(float)
    pdf = pd.DataFrame({"features": list(x), "label": y})

    state = {"n": 0}

    class FlakyLR(LogisticRegression):
        def _fit_internal(self, *a, **kw):
            state["n"] += 1
            if fail_at_fit is not None and state["n"] == fail_at_fit:
                raise RankFailedError(1, "injected rank loss mid-sweep")
            return super()._fit_internal(*a, **kw)

    lr = FlakyLR(maxIter=10)
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.1]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=3, seed=1,
    )
    return cv, pdf, state


def test_cv_sweep_resumes_at_first_incomplete_fit(rng):
    # acceptance: a CV sweep losing a rank mid-flight resumes at the first
    # incomplete fit and redoes ZERO completed (fold, paramMap) fits —
    # asserted from the ledger telemetry counters alone
    cv, pdf, state = _cv_setup(rng, fail_at_fit=3)  # dies entering fold 2
    model = cv.fit(pdf)
    snap = _counters()
    assert snap["sweep.resumes"] == 1
    assert snap["sweep.fits_completed"] == 6  # 3 folds x 2 maps, each ONCE
    assert snap["sweep.fits_skipped"] == 4  # folds 0-1 ledger-served on resume
    # fold fits actually performed: 2 clean + 1 failed + 1 resumed + 1 refit
    assert state["n"] == 5
    assert model.bestModel is not None
    assert len(model.avgMetrics) == 2


def test_cv_sweep_clean_run_has_no_resumes(rng):
    cv, pdf, state = _cv_setup(rng)
    cv.fit(pdf)
    snap = _counters()
    assert snap["sweep.fits_completed"] == 6
    assert "sweep.resumes" not in snap
    assert "sweep.fits_skipped" not in snap


def test_cv_sweep_resume_metrics_match_clean_run():
    rng_a = np.random.default_rng(5)
    cv, pdf, _ = _cv_setup(rng_a, fail_at_fit=2)
    resumed = cv.fit(pdf)
    rng_b = np.random.default_rng(5)
    cv2, pdf2, _ = _cv_setup(rng_b)
    clean = cv2.fit(pdf2)
    np.testing.assert_allclose(resumed.avgMetrics, clean.avgMetrics)


def test_cv_sweep_resumes_inside_carved_chip_scope():
    # ISSUE 19 composition: the sweep ledger's resume works unchanged when the
    # WHOLE sweep runs on a carved sub-mesh (the scheduler's chip_scope pin).
    # The injected rank loss re-meshes within the pinned half-pool, resume
    # redoes zero completed fits, and the metric grid is bit-identical to a
    # clean sweep on the same sub-mesh.
    from spark_rapids_ml_tpu.parallel import chip_scope, default_devices, get_mesh

    pool = default_devices()
    assert len(pool) == 8
    half = pool[4:]
    rng_a = np.random.default_rng(5)
    cv, pdf, state = _cv_setup(rng_a, fail_at_fit=3)
    with chip_scope(half):
        assert get_mesh().devices.size == 4
        resumed = cv.fit(pdf)
    snap = _counters()
    assert snap["sweep.resumes"] == 1
    assert snap["sweep.fits_completed"] == 6
    assert snap["sweep.fits_skipped"] == 4
    # 2 clean + 1 failed + 1 resumed + 1 refit, all on the half-pool
    assert state["n"] == 5
    rng_b = np.random.default_rng(5)
    cv2, pdf2, _ = _cv_setup(rng_b)
    with chip_scope(half):
        clean = cv2.fit(pdf2)
    np.testing.assert_array_equal(resumed.avgMetrics, clean.avgMetrics)


def test_cv_sweep_resume_budget_exhaustion():
    rng = np.random.default_rng(6)
    core_mod.config["sweep_max_resumes"] = 0
    cv, pdf, _ = _cv_setup(rng, fail_at_fit=2)
    with pytest.raises(RankFailedError):
        cv.fit(pdf)


def test_sweep_ledger_registry_lookup():
    from spark_rapids_ml_tpu import tuning

    ledger = tuning._register_ledger(tuning.SweepLedger("trace-xyz", 2, 2))
    ledger.complete(0, 0, 0.5)
    ledger.complete(0, 1, 0.7)
    got = tuning.sweep_ledger("trace-xyz")
    assert got is ledger
    assert got.fold_done(0) and not got.fold_done(1)
    np.testing.assert_allclose(got.fold_metrics(0), [0.5, 0.7])
    assert len(got) == 2


def test_cv_ledger_drops_models_without_collect_sub(rng):
    # the ledger only ever reads models back for subModels restoration —
    # without collectSubModels it must not pin a sweep's worth of them in
    # the retained registry entry
    from spark_rapids_ml_tpu import tuning

    cv, pdf, _ = _cv_setup(rng)
    cv.fit(pdf)
    ledgers = list(tuning._LEDGERS.values())
    assert ledgers, "sweep did not register a ledger"
    assert all(not led._models for led in ledgers)


def _tvs_setup(rng, fail_at_fit=None, evaluator=None):
    from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.tuning import ParamGridBuilder, TrainValidationSplit

    x = rng.normal(size=(300, 5)).astype(np.float32)
    y = (x @ rng.normal(size=5) > 0).astype(float)
    pdf = pd.DataFrame({"features": list(x), "label": y})
    state = {"n": 0}

    class FlakyLR(LogisticRegression):
        def _fit_internal(self, *a, **kw):
            state["n"] += 1
            if fail_at_fit is not None and state["n"] == fail_at_fit:
                raise RankFailedError(1, "injected rank loss mid-sweep")
            return super()._fit_internal(*a, **kw)

    lr = FlakyLR(maxIter=10)
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.1]).build()
    tvs = TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=evaluator or MulticlassClassificationEvaluator(metricName="accuracy"),
        trainRatio=0.75, seed=1,
    )
    return tvs, pdf, state


def test_tvs_engine_sweep_resumes_mid_grid(rng):
    # same elastic contract as CV (docs claim CV AND TVS): a mid-flight
    # control-plane failure resumes the sweep instead of failing it
    tvs, pdf, state = _tvs_setup(rng, fail_at_fit=1)
    model = tvs.fit(pdf)
    snap = _counters()
    assert snap["sweep.resumes"] == 1
    assert snap["sweep.fits_completed"] == 2
    assert "sweep.fits_skipped" not in snap  # died before any fit finished
    assert state["n"] == 3  # failed grid + resumed grid + best refit
    assert model.bestModel is not None
    assert len(model.validationMetrics) == 2


class _PandasAccuracyEvaluator:
    # deliberately NOT a framework evaluator (unsupported metric name):
    # forces the fallback per-model TVS path, where the ledger works at
    # (paramMap) granularity
    def getMetricName(self):
        return "pandas_accuracy"

    def isLargerBetter(self):
        return True

    def evaluate(self, df):
        return float((df["prediction"] == df["label"]).mean())


def test_tvs_fallback_resumes_at_first_incomplete_map(rng):
    tvs, pdf, state = _tvs_setup(
        rng, fail_at_fit=2, evaluator=_PandasAccuracyEvaluator()
    )
    model = tvs.fit(pdf)
    snap = _counters()
    assert snap["sweep.resumes"] == 1
    assert snap["sweep.fits_completed"] == 2
    assert snap["sweep.fits_skipped"] == 1  # map 0 ledger-served on resume
    assert state["n"] == 4  # map 0 + failed map 1 + resumed map 1 + refit
    assert model.bestModel is not None
    assert len(model.validationMetrics) == 2


# ------------------------------------------- multi-generation file reform ---


def test_file_reform_dirs_anchor_at_original_root(tmp_path):
    # generation N+1's window must open under the ORIGINAL run root — never
    # nested under the g<N> plane — or a respawned rank constructing over
    # the original root can only ever discover generation 1
    import os

    r = FileRendezvous(0, 1, str(tmp_path), timeout_s=10.0, run_id="t",
                       heartbeat_interval_s=0.2)
    anchor = r.root
    g1 = r.reform(dead_ranks=(), generation=1)
    assert g1.root == os.path.join(anchor, "reform_g1", "plane")
    g2 = g1.reform(dead_ranks=(), generation=2)
    assert g2.root == os.path.join(anchor, "reform_g2", "plane")
    # the respawn's view: a fresh instance over the original root sees the
    # latest window, and the rejoin marker lands where g2 survivors scan it
    respawn = FileRendezvous(0, 1, str(tmp_path), timeout_s=5.0, run_id="t",
                             heartbeat_interval_s=0)
    assert respawn.latest_generation() == 2
    assert respawn._rejoin_wait_path(0) == g2._rejoin_wait_path(0)
    for rv in (r, g1, g2, respawn):
        rv.close()


def test_rejoin_marker_raises_current_index(tmp_path):
    # the rejoin-marker failure path must raise the CURRENT rank index like
    # the abort/heartbeat paths do — recoverable_stage maps failed_rank
    # through live_ranks exactly once, so an original id here would be
    # double-mapped after a prior reform and blame an innocent survivor
    rv = FileRendezvous(0, 2, str(tmp_path), timeout_s=5.0,
                        heartbeat_interval_s=0, live_ranks=[0, 2])
    with open(rv._rejoin_wait_path(2), "w") as f:
        f.write("{}")
    with pytest.raises(RankFailedError) as ei:
        rv._check_failures({1}, round_index=0)
    assert ei.value.failed_rank == 1  # current index of original rank 2
    assert "original rank 2" in ei.value.reason
    rv.close()


def test_stale_reform_dirs_cleaned_on_run_id_less_reuse(tmp_path):
    # a crashed previous run's reform tree in a reused run_id-less root
    # would close this run's first window instantly with the wrong live set;
    # construction removes trees with no recent file activity and keeps
    # fresh ones (a live window another rank just opened)
    import os

    stale = tmp_path / "reform_g1"
    (stale / "plane" / "round_0").mkdir(parents=True)
    (stale / "member_rank_0").write_text("{}")
    (stale / "plane" / "round_0" / "rank_0").write_text("old")
    old = time.time() - 7200
    for dirpath, dirnames, filenames in os.walk(stale, topdown=False):
        for name in filenames:
            os.utime(os.path.join(dirpath, name), (old, old))
        os.utime(dirpath, (old, old))
    fresh = tmp_path / "reform_g2"
    fresh.mkdir()
    (fresh / "member_rank_1").write_text("{}")
    rv = FileRendezvous(0, 2, str(tmp_path), timeout_s=5.0,
                        heartbeat_interval_s=0)
    assert not stale.exists()  # stale tree removed
    assert fresh.exists()  # live window untouched
    rv.close()


# -------------------------------------------------------- postmortem epoch --


def test_postmortem_names_recovery_epochs(tmp_path):
    from spark_rapids_ml_tpu import diagnostics

    # simulate what recoverable_stage + reform record on a survivor
    events = [
        dict(kind="rdv_enter", round=4),
        dict(kind="error", error="RankFailedError", failed_rank=2, round_index=4),
        dict(kind="recovery_epoch_begin", generation=1, failed_rank=2,
             dead_ranks=[2]),
        dict(kind="recovery_reform", generation=1, survivors=[0, 1], dead=[2]),
    ]
    import json
    import os

    dump = tmp_path / "flightrec_rank_0.jsonl"
    with open(dump, "w") as f:
        for i, ev in enumerate(events):
            f.write(json.dumps(dict(ev, t=float(i), rank=0)) + "\n")
    pm = diagnostics.assemble_postmortem(str(tmp_path), nranks=3)
    assert pm["failed_rank"] == 2
    assert pm["recovery_epochs"] == [
        {"generation": 1, "survivors": [0, 1], "dead": [2]}
    ]
    rendered = diagnostics.render_postmortem(pm)
    assert "recovery epoch g1" in rendered
    assert "survivors [0, 1]" in rendered
