#
# Persistent serving plane tests (docs/serving.md): registry admission +
# LRU eviction, load-time ladder prewarm (compile-count pins via
# transform.bucket_programs), micro-batch coalescing bit-identity vs solo
# predicts, zero-row requests through the bucket ladder, the bf16 query path
# on the distance-core models, and the knn serve program's tiled-core route.
#
import threading

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import HbmBudgetError, core, telemetry
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.clustering import KMeansModel
from spark_rapids_ml_tpu.models.knn import NearestNeighbors
from spark_rapids_ml_tpu.serving import ModelRegistry, ScoringEngine


@pytest.fixture
def tele():
    """Enable telemetry with a fresh registry; restore after."""
    telemetry.registry().reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.registry().reset()


@pytest.fixture
def serve_cfg():
    """Small bucket ladder + prewarm so compile-count pins are cheap."""
    saved = {
        k: core.config[k]
        for k in (
            "transform_bucket_min_rows",
            "serve_prewarm_rows",
            "serve_max_batch_rows",
            "serve_coalesce_window_ms",
            "hbm_budget_bytes",
        )
    }
    core.config["transform_bucket_min_rows"] = 8
    core.config["serve_prewarm_rows"] = 64
    core.config["serve_max_batch_rows"] = 256
    core.config["serve_coalesce_window_ms"] = 25.0
    yield
    core.config.update(saved)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _kmeans_model(rng, k=6, d=10, scale=10.0):
    centers = (rng.standard_normal((k, d)) * scale).astype(np.float32)
    return KMeansModel(cluster_centers_=centers, n_cols=d, dtype="float32")


def _logistic_model(rng, n=160, d=6):
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(x), "label": y})
    return LogisticRegression(maxIter=30, regParam=0.01).setFeaturesCol("features").fit(df)


def _knn_model(rng, n=150, d=5, k=4):
    items = rng.normal(size=(n, d))
    df = pd.DataFrame({"features": list(items), "id": np.arange(1000, 1000 + n)})
    model = NearestNeighbors(k=k).setInputCol("features").setIdCol("id").fit(df)
    return model, items


# ------------------------------------------------------------ registry -----


def test_load_stamps_resident_admission(tele, serve_cfg, rng):
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    entry = registry.load("km", model)
    stamp = model._serve_metrics["admission"]
    assert stamp["verdict"] == "resident"
    assert stamp["largest_term"]  # names its dominant byte line item
    assert entry.resident_bytes > 0
    assert registry.resident_bytes() == entry.resident_bytes
    snap = tele.snapshot()
    assert snap["counters"]["serve.models_loaded"] == 1
    assert snap["gauges"]["serve.resident_models"] == 1


def test_prewarm_compiles_exactly_the_ladder(tele, serve_cfg, rng):
    # d=11 is unique to this test: the process-wide bucket-shape set
    # deliberately survives registry resets (it mirrors the jit cache), so
    # the compile-count pin needs shapes no other test dispatches
    model = _kmeans_model(rng, d=11)
    registry = ModelRegistry()
    before = tele.snapshot()["counters"].get("transform.bucket_programs", 0)
    entry = registry.load("km", model)
    after = tele.snapshot()["counters"].get("transform.bucket_programs", 0)
    ladder = entry.program.ladder(core.config["serve_prewarm_rows"])
    assert entry.prewarmed_rungs == len(ladder) == 4  # 8,16,32,64
    # compile-count pin: prewarm minted exactly one program per rung
    assert after - before == len(ladder)
    # ...and ragged post-load traffic mints NOTHING new inside the prewarmed
    # range: every dispatch is a bucket hit
    with ScoringEngine(registry) as engine:
        for n in (1, 5, 8, 13, 31, 64, 40):
            engine.score("km", rng.standard_normal((n, 11)).astype(np.float32))
    final = tele.snapshot()["counters"]
    assert final.get("transform.bucket_programs", 0) == after
    assert final["serve.bucket_hits"] > 0


def test_eviction_under_pressure_stamps_and_frees(tele, serve_cfg, rng):
    from spark_rapids_ml_tpu import memory

    m_a, m_b = _kmeans_model(rng), _kmeans_model(rng, scale=3.0)
    one = memory.model_serve_estimate(m_a, core.config["serve_max_batch_rows"]).total()
    # budget fits ONE model (plus headroom), not two
    core.config["hbm_budget_bytes"] = int(one * 1.5 / 0.9)
    registry = ModelRegistry()
    registry.load("A", m_a)
    registry.load("B", m_b)
    assert "A" not in registry and "B" in registry
    stamp = m_a._serve_metrics["admission"]
    assert stamp["verdict"] == "evicted"
    assert "pressure" in stamp["reason"]
    assert stamp["largest_term"]  # an evicted load names its largest term
    with pytest.raises(KeyError):
        registry.get("A")
    assert tele.snapshot()["counters"]["serve.model_evictions"] == 1


def test_refused_load_is_typed_and_stamped(tele, serve_cfg, rng):
    core.config["hbm_budget_bytes"] = 2048  # below any model's working set
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    with pytest.raises(HbmBudgetError) as ei:
        registry.load("km", model)
    assert ei.value.largest_term  # the typed refusal names what doesn't fit
    stamp = model._serve_metrics["admission"]
    assert stamp["verdict"] == "refused"
    assert stamp["largest_term"] == ei.value.largest_term
    assert "km" not in registry


def test_lru_eviction_respects_serving_touch(tele, serve_cfg, rng):
    from spark_rapids_ml_tpu import memory

    m_a, m_b, m_c = (_kmeans_model(rng) for _ in range(3))
    one = memory.model_serve_estimate(m_a, core.config["serve_max_batch_rows"]).total()
    core.config["hbm_budget_bytes"] = int(one * 2.5 / 0.9)  # fits two, not three
    registry = ModelRegistry()
    registry.load("A", m_a)
    registry.load("B", m_b)
    registry.get("A")  # touch: A becomes MRU, B is now the LRU victim
    registry.load("C", m_c)
    assert "A" in registry and "C" in registry and "B" not in registry


def test_reload_replaces_entry(tele, serve_cfg, rng):
    registry = ModelRegistry()
    m1, m2 = _kmeans_model(rng), _kmeans_model(rng, k=4)
    registry.load("km", m1)
    registry.load("km", m2)
    assert registry.get("km").model is m2
    assert m1._serve_metrics["admission"]["verdict"] == "evicted"
    assert len(registry.names()) == 1


# -------------------------------------------------------------- engine -----


def test_coalesced_responses_bit_identical_to_solo(tele, serve_cfg, rng):
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    sizes = (1, 3, 17, 40, 2, 9, 64, 5)
    requests = [rng.standard_normal((n, 10)).astype(np.float32) for n in sizes]
    solo = [np.asarray(model._transform_arrays(q)) for q in requests]
    with ScoringEngine(registry) as engine:
        # submit from threads so requests genuinely interleave in the window
        futs = [None] * len(requests)

        def submit(i):
            futs[i] = engine.submit("km", requests[i])

        threads = [threading.Thread(target=submit, args=(i,)) for i in range(len(requests))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for fut, ref in zip(futs, solo):
            got = fut.result(timeout=60)
            assert np.array_equal(np.asarray(got), ref)  # BIT-identical
    counters = tele.snapshot()["counters"]
    assert counters["serve.requests"] == len(requests)
    assert counters["serve.coalesced_batches"] >= 1  # micro-batching happened
    assert counters["serve.batches"] < len(requests)


def test_zero_row_request_through_the_ladder(tele, serve_cfg, rng):
    km = _kmeans_model(rng)
    lr = _logistic_model(rng)
    registry = ModelRegistry()
    registry.load("km", km)
    registry.load("lr", lr)
    with ScoringEngine(registry) as engine:
        z = engine.score("km", np.zeros((0, 10), np.float32))
        assert z.shape == (0,)
        # multi-output model: one correctly-shaped empty array PER output
        raw, prob = engine.score("lr", np.zeros((0, 6)))
        assert raw.shape == (0, 2) and prob.shape == (0, 2)


def test_multi_output_and_oversized_requests(tele, serve_cfg, rng):
    lr = _logistic_model(rng)
    registry = ModelRegistry()
    registry.load("lr", lr)
    # rows > serve_max_batch_rows: the engine splits across dispatches
    big = rng.normal(size=(2 * core.config["serve_max_batch_rows"] + 37, 6))
    ref_raw, ref_prob = lr._transform_arrays(big)
    with ScoringEngine(registry) as engine:
        raw, prob = engine.score("lr", big, timeout=120)
    assert np.array_equal(raw, ref_raw) and np.array_equal(prob, ref_prob)


def test_mixed_model_routing(tele, serve_cfg, rng):
    km, lr = _kmeans_model(rng, d=6), _logistic_model(rng)
    registry = ModelRegistry()
    registry.load("km", km)
    registry.load("lr", lr)
    with ScoringEngine(registry) as engine:
        q_km = rng.standard_normal((11, 6)).astype(np.float32)
        q_lr = rng.normal(size=(13, 6))
        f1 = engine.submit("km", q_km)
        f2 = engine.submit("lr", q_lr)
        assert np.array_equal(f1.result(), np.asarray(km._transform_arrays(q_km)))
        raw, _ = f2.result()
        assert np.array_equal(raw, lr._transform_arrays(q_lr)[0])


def test_submit_validates_synchronously(tele, serve_cfg, rng):
    registry = ModelRegistry()
    registry.load("km", _kmeans_model(rng))
    with ScoringEngine(registry) as engine:
        with pytest.raises(KeyError):
            engine.submit("nope", np.zeros((1, 10), np.float32))
        with pytest.raises(ValueError):
            engine.submit("km", np.zeros((3, 4), np.float32))  # wrong width
        with pytest.raises(ValueError):
            engine.submit("km", np.zeros(10, np.float32))  # not 2-D
    with pytest.raises(RuntimeError):
        engine.submit("km", np.zeros((1, 10), np.float32))  # stopped engine


def test_latency_histograms_and_stats(tele, serve_cfg, rng):
    registry = ModelRegistry()
    registry.load("km", _kmeans_model(rng))
    with ScoringEngine(registry) as engine:
        for _ in range(5):
            engine.score("km", rng.standard_normal((4, 10)).astype(np.float32))
        stats = engine.stats()
    hists = tele.snapshot()["histograms"]
    assert hists["serve.queue_wait_s"]["count"] == 5
    assert hists["serve.e2e_s"]["count"] == 5
    assert stats["e2e_p99_s"] >= stats["e2e_p50_s"] > 0
    assert tele.quantile("serve.e2e_s", 0.5) is not None
    assert tele.quantile("no.such.histogram", 0.5) is None


def test_evicted_mid_flight_fails_typed(tele, serve_cfg, rng):
    registry = ModelRegistry()
    registry.load("km", _kmeans_model(rng))
    engine = ScoringEngine(registry).start()
    try:
        fut = engine.submit("km", rng.standard_normal((4, 10)).astype(np.float32))
        fut.result(timeout=30)  # drain so the evict below is unambiguous
        registry.evict("km")
        with pytest.raises(KeyError):
            engine.submit("km", np.zeros((1, 10), np.float32))
    finally:
        engine.stop()


# ---------------------------------------------------------- bf16 + knn -----


def test_bf16_kmeans_assignments_match_f32(tele, serve_cfg, rng):
    # well-separated centers: the ~1e-3 bf16 rounding cannot flip assignments
    model = _kmeans_model(rng, scale=50.0)
    registry = ModelRegistry()
    registry.load("km16", model, serve_dtype="bf16")
    q = rng.standard_normal((37, 10)).astype(np.float32)
    with ScoringEngine(registry) as engine:
        a16 = engine.score("km16", q)
    assert np.array_equal(a16, np.asarray(model._transform_arrays(q)))


def test_bf16_rejected_off_the_distance_core(tele, serve_cfg, rng):
    lr = _logistic_model(rng)
    registry = ModelRegistry()
    with pytest.raises(ValueError, match="distance-core"):
        registry.load("lr", lr, serve_dtype="bf16")


def test_knn_serving_matches_kneighbors(tele, serve_cfg, rng):
    model, items = _knn_model(rng)
    _, _, knn_df = model.kneighbors(
        pd.DataFrame({"features": list(items[:9]), "id": np.arange(9)})
    )
    ref_idx = np.stack(knn_df["indices"].to_numpy())
    ref_d = np.stack(knn_df["distances"].to_numpy())
    before = tele.snapshot()["counters"].get("distance.topk_programs", 0)
    registry = ModelRegistry()
    registry.load("knn", model)
    with ScoringEngine(registry) as engine:
        d, idx = engine.score("knn", items[:9])
    # the serve program routes through the tiled distance core
    assert tele.snapshot()["counters"].get("distance.topk_programs", 0) > before
    assert np.array_equal(idx, ref_idx)
    np.testing.assert_allclose(d, ref_d, atol=2e-3)  # f32 expansion rounding


def test_knn_bf16_neighbor_sets_on_separated_items(tele, serve_cfg, rng):
    # items on a coarse lattice: neighbor gaps far above bf16 rounding
    items = (rng.integers(-4, 5, size=(80, 5)) * 10.0).astype(np.float64)
    items += rng.normal(scale=0.01, size=items.shape)
    df = pd.DataFrame({"features": list(items), "id": np.arange(80)})
    model = NearestNeighbors(k=3).setInputCol("features").setIdCol("id").fit(df)
    registry = ModelRegistry()
    registry.load("knn16", model, serve_dtype="bf16")
    registry2 = ModelRegistry()
    registry2.load("knn32", model)
    q = items[:7] + 0.05
    with ScoringEngine(registry) as engine:
        _, idx16 = engine.score("knn16", q)
    with ScoringEngine(registry2) as engine:
        _, idx32 = engine.score("knn32", q)
    assert np.array_equal(idx16, idx32)


def test_knn_admission_prices_the_item_block(tele, serve_cfg, rng):
    from spark_rapids_ml_tpu import memory

    model, items = _knn_model(rng, n=150, d=5)
    est = memory.model_serve_estimate(
        model, core.config["serve_max_batch_rows"]
    )
    # the resident item block is a named placement term, and the top-k tile
    # workspace is bounded (never a [bucket, n_items] block on the kernel path)
    assert est.terms["placement.items"] == items.size * 4  # f32
    assert "workspace.topk_block" in est.terms


def test_doomed_load_does_not_evict_residents(tele, serve_cfg, rng):
    # a load that can never succeed (no serving hook / bad serve_dtype) must
    # preflight-fail BEFORE the admission/eviction loop — previously-serving
    # models stay resident
    from spark_rapids_ml_tpu import memory
    from spark_rapids_ml_tpu.models.clustering import DBSCAN

    m_a = _kmeans_model(rng)
    one = memory.model_serve_estimate(m_a, core.config["serve_max_batch_rows"]).total()
    core.config["hbm_budget_bytes"] = int(one * 1.5 / 0.9)  # tight: fits one
    registry = ModelRegistry()
    registry.load("A", m_a)
    x = rng.normal(size=(20, 3))
    dbm = DBSCAN(eps=2.0, min_samples=3).setFeaturesCol("features").fit(
        pd.DataFrame({"features": list(x)})
    )
    with pytest.raises(NotImplementedError):
        registry.load("dbscan", dbm)
    lr = _logistic_model(rng)
    with pytest.raises(ValueError, match="distance-core"):
        registry.load("lr16", lr, serve_dtype="bf16")
    assert "A" in registry  # survived both doomed loads
    assert tele.snapshot()["counters"].get("serve.model_evictions", 0) == 0


def test_zero_window_disables_coalescing(tele, serve_cfg, rng):
    registry = ModelRegistry()
    registry.load("km", _kmeans_model(rng))
    requests = [rng.standard_normal((n, 10)).astype(np.float32) for n in (3, 5, 7, 9)]
    with ScoringEngine(registry, coalesce_window_s=0.0) as engine:
        futs = [engine.submit("km", q) for q in requests]  # backlog builds
        outs = [f.result(30) for f in futs]
    for out, q in zip(outs, requests):
        assert out.shape == (q.shape[0],)
    counters = tele.snapshot()["counters"]
    # 0 disables coalescing even with a queued same-model backlog: one
    # dispatched batch per request, nothing coalesced
    assert counters["serve.batches"] == len(requests)
    assert counters.get("serve.coalesced_batches", 0) == 0


def test_unserveable_model_raises(tele, serve_cfg, rng):
    from spark_rapids_ml_tpu.models.clustering import DBSCAN

    registry = ModelRegistry()
    x = rng.normal(size=(20, 3))
    dbs = DBSCAN(eps=2.0, min_samples=3).setFeaturesCol("features")
    dbm = dbs.fit(pd.DataFrame({"features": list(x)}))
    with pytest.raises(NotImplementedError, match="serving hook"):
        registry.load("dbscan", dbm)


def test_predict_program_shared_with_transform(tele, serve_cfg, rng):
    """The serving handle and _transform_arrays share one implementation:
    a program built directly gives the same outputs as the transform path."""
    from spark_rapids_ml_tpu.core import PredictProgram

    model = _logistic_model(rng)
    q = rng.normal(size=(23, 6))
    program = PredictProgram(model, cap=core.config["serve_max_batch_rows"])
    result, n_valid = program.dispatch(q)
    raw, prob = program.fetch(result, n_valid)
    ref_raw, ref_prob = model._transform_arrays(q)
    assert np.array_equal(raw, ref_raw) and np.array_equal(prob, ref_prob)
