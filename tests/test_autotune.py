#
# Measured block autotuner (spark_rapids_ml_tpu/ops/autotune.py,
# docs/performance.md "Kernel autotuner") and the planner it overrides
# (distance.effective_itemsize / _plan). The acceptance contract:
#
#   - the fast path budgets VMEM at the EFFECTIVE on-chip itemsize (bf16
#     blocks = 2 bytes), never the input dtype's;
#   - a measured winner persists as JSON beside the compile cache and is
#     reused ACROSS PROCESSES (simulated here by dropping the in-memory
#     cache), hit/miss counters pinned;
#   - every degradation path — disabled, off-TPU, malformed table, stale
#     version, bad entries, raising timer, unset cache dir — falls back to
#     the heuristic without raising; a fit never fails in the tuner.
#
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu import telemetry
from spark_rapids_ml_tpu.ops import autotune
from spark_rapids_ml_tpu.ops.distance import (
    _plan,
    effective_itemsize,
    plan_blocks,
)

_KEYS = ("compilation_cache_dir", "autotune_enabled", "autotune_repeats")


@pytest.fixture
def tuner(tmp_path):
    """Isolated tuner: private table directory, clean in-memory cache and
    counters, config restored exactly (other files' fits must keep seeing
    the real settings)."""
    saved = {k: core_mod.config[k] for k in _KEYS}
    core_mod.config["compilation_cache_dir"] = str(tmp_path)
    core_mod.config["autotune_enabled"] = True
    autotune.reset()
    telemetry.enable()
    telemetry.registry().reset()
    yield tmp_path
    core_mod.config.update(saved)
    autotune.reset()
    telemetry.disable()
    telemetry.registry().reset()


def _fake_timer(best=(256, 256)):
    """Deterministic stand-in for the on-device timer: the chosen winner
    times fastest, everything else slower by its distance from it."""
    calls = []

    def timer(br, bk):
        calls.append((br, bk))
        return 1.0 + abs(br - best[0]) + abs(bk - best[1])

    timer.calls = calls
    return timer


# ------------------------------------------------------ planner itemsize ----


def test_effective_itemsize_pins():
    assert effective_itemsize(jnp.float32, fast=False) == 4
    assert effective_itemsize(jnp.float32, fast=True) == 2
    assert effective_itemsize(jnp.float64, fast=False) == 8
    # the fast path stages bf16 blocks regardless of the ambient dtype
    assert effective_itemsize(jnp.float64, fast=True) == 2
    assert effective_itemsize(jnp.bfloat16, fast=False) == 2


def test_fast_plan_budgets_double_elements(tuner):
    # a VMEM-tight depth: at 4-byte f32 the heuristic must shrink blocks,
    # at the 2-byte effective itemsize the same shape fits bigger tiles
    d = 3000
    full = plan_blocks(4096, 4096, d, effective_itemsize(jnp.float32, False))
    fast = plan_blocks(4096, 4096, d, effective_itemsize(jnp.float32, True))
    assert full is not None and fast is not None
    assert fast[0] * fast[1] > full[0] * full[1]
    # _plan threads the same effective itemsize (no table entry here)
    assert _plan(4096, 4096, d, jnp.float32, False) == full
    assert _plan(4096, 4096, d, jnp.float32, True) == fast


def test_shape_class_buckets():
    # rows/k round UP to powers of two; depth exact; mode spelled out
    assert autotune.shape_class(1000, 5, 64, jnp.float32, True) == "r1024:k8:d64:float32:fast"
    assert autotune.shape_class(1024, 8, 64, jnp.float32, True) == "r1024:k8:d64:float32:fast"
    assert autotune.shape_class(1025, 9, 64, jnp.float64, False) == "r2048:k16:d64:float64:full"
    # same bucket => same key (one measurement covers the bucket)
    assert autotune.shape_class(513, 5, 32, jnp.float32, False) == autotune.shape_class(
        1024, 8, 32, jnp.float32, False
    )


# ------------------------------------------------- measure and persist ------


def test_ensure_measures_persists_and_reuses(tuner):
    timer = _fake_timer(best=(256, 256))
    won = autotune.ensure(4096, 512, 64, jnp.float32, True, timer=timer)
    assert won == (256, 256)
    assert len(timer.calls) >= 2  # a real grid was raced, not a single point
    # persisted beside the compile cache, schema-versioned
    path = os.path.join(str(tuner), "srml_autotune.json")
    with open(path) as f:
        raw = json.load(f)
    assert raw["version"] == 1
    key = autotune.shape_class(4096, 512, 64, jnp.float32, True)
    assert raw["entries"][key] == [256, 256]

    # "another process": drop the in-memory cache, the file alone must serve
    autotune.reset()
    assert autotune.lookup(4096, 512, 64, jnp.float32, True) == (256, 256)
    stats = autotune.stats()
    assert stats["hits"] == 1 and stats["misses"] == 0 and stats["entries"] == 1
    # the planner consumes the tuned winner over its heuristic
    assert _plan(4096, 512, 64, jnp.float32, True) == (256, 256)
    # second ensure is a pure table read — no re-measurement
    n_calls = len(timer.calls)
    assert autotune.ensure(4096, 512, 64, jnp.float32, True, timer=timer) == (256, 256)
    assert len(timer.calls) == n_calls
    assert autotune.stats()["measurements"] == 0  # this process never measured


def test_lookup_miss_counts_and_falls_back(tuner):
    assert autotune.lookup(4096, 512, 64, jnp.float32, False) is None
    stats = autotune.stats()
    assert stats["misses"] == 1 and stats["hits"] == 0
    assert telemetry.registry().snapshot()["counters"]["autotune.misses"] == 1
    # the planner still plans (heuristic)
    assert _plan(4096, 512, 64, jnp.float32, False) == plan_blocks(4096, 512, 64, 4)


def test_candidates_respect_vmem_and_include_heuristic(tuner):
    cands = autotune._candidates(4096, 4096, 3000, jnp.float32, False)
    heuristic = plan_blocks(4096, 4096, 3000, 4)
    assert cands[0] == heuristic
    budget = 8 * 1024 * 1024 // 4
    for br, bk in cands:
        assert br * 3000 + bk * 3000 + br * bk <= budget


# ------------------------------------------------------ degradation ---------


def test_malformed_table_degrades_to_heuristic(tuner):
    path = os.path.join(str(tuner), "srml_autotune.json")
    with open(path, "w") as f:
        f.write("{ not json")
    assert autotune.lookup(4096, 512, 64, jnp.float32, True) is None
    assert autotune.stats()["table_errors"] == 1
    assert _plan(4096, 512, 64, jnp.float32, True) is not None  # heuristic lives


def test_stale_version_discarded_wholesale(tuner):
    key = autotune.shape_class(4096, 512, 64, jnp.float32, True)
    path = os.path.join(str(tuner), "srml_autotune.json")
    with open(path, "w") as f:
        json.dump({"version": 0, "entries": {key: [256, 256]}}, f)
    assert autotune.lookup(4096, 512, 64, jnp.float32, True) is None
    assert autotune.stats()["table_errors"] == 1


def test_bad_entry_shapes_filtered(tuner):
    good = autotune.shape_class(4096, 512, 64, jnp.float32, True)
    path = os.path.join(str(tuner), "srml_autotune.json")
    with open(path, "w") as f:
        json.dump(
            {
                "version": 1,
                "entries": {
                    good: [256, 256],
                    "bad1": [256],          # wrong arity
                    "bad2": [0, 256],       # non-positive
                    "bad3": "256x256",      # wrong type
                },
            },
            f,
        )
    assert autotune.lookup(4096, 512, 64, jnp.float32, True) == (256, 256)
    stats = autotune.stats()
    assert stats["table_errors"] == 3 and stats["entries"] == 1


def test_raising_timer_never_fails_the_fit(tuner):
    def timer(br, bk):
        raise RuntimeError("exotic part says no")

    assert autotune.ensure(4096, 512, 64, jnp.float32, True, timer=timer) is None
    assert autotune.stats()["table_errors"] == 1
    assert not os.path.exists(os.path.join(str(tuner), "srml_autotune.json"))


def test_disabled_is_a_noop(tuner):
    core_mod.config["autotune_enabled"] = False
    assert autotune.lookup(4096, 512, 64, jnp.float32, True) is None
    assert autotune.ensure(
        4096, 512, 64, jnp.float32, True, timer=_fake_timer()
    ) is None
    stats = autotune.stats()
    assert stats == {"hits": 0, "misses": 0, "measurements": 0,
                     "table_errors": 0, "entries": 0}


def test_off_tpu_without_timer_measures_nothing(tuner):
    # CPU/CI contract: kernel_mode() != "pallas" here, so ensure() without
    # an injected timer must return None and write nothing
    assert autotune.ensure(4096, 512, 64, jnp.float32, True) is None
    assert not os.path.exists(os.path.join(str(tuner), "srml_autotune.json"))
    assert autotune.stats()["measurements"] == 0


def test_no_cache_dir_stays_in_memory(tuner):
    core_mod.config["compilation_cache_dir"] = None
    assert autotune.table_path() is None
    won = autotune.ensure(4096, 512, 64, jnp.float32, True, timer=_fake_timer())
    assert won == (256, 256)
    # in-memory table serves this process...
    assert autotune.lookup(4096, 512, 64, jnp.float32, True) == (256, 256)
    # ...but a "new process" starts cold (nothing was persisted anywhere)
    autotune.reset()
    assert autotune.lookup(4096, 512, 64, jnp.float32, True) is None
    assert not os.path.exists(os.path.join(str(tuner), "srml_autotune.json"))


def test_env_seed_of_autotune_enabled(monkeypatch):
    # SRML_AUTOTUNE=0 seeds config["autotune_enabled"] False at load; the
    # seeding helper is pinned directly (config itself loaded long ago)
    import subprocess
    import sys

    code = (
        "from spark_rapids_ml_tpu.core import config; "
        "print(config['autotune_enabled'])"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, "SRML_AUTOTUNE": "0", "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=120,
    )
    assert out.stdout.strip() == "False", out.stderr
