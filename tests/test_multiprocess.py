#
# Multi-process SPMD fit tests: N real OS processes, each holding a ragged
# local row block, fit cooperatively through TpuContext(require_distributed=
# True) over a FileRendezvous — the runtime analog of the reference's barrier
# stage of one-task-per-GPU NCCL ranks (reference core.py:698-791 +
# cuml_context.py:36-148). Results must match a single-process fit on the
# concatenated dataset.
#
import os
import subprocess
import sys
import uuid

import numpy as np
import pandas as pd
import pytest

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)


def _launch_workers(nranks, tmp_path, local_devices=2, script="mp_worker.py"):
    env = dict(os.environ)
    # subprocesses must NOT grab the real TPU chip nor inherit the parent's
    # 8-device CPU forcing: plain CPU backend with `local_devices` devices each
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={local_devices}"
    env["JAX_ENABLE_X64"] = "1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rdv_dir = str(tmp_path / "rdv")
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir, exist_ok=True)
    run_id = uuid.uuid4().hex  # launcher-minted nonce guards against stale rounds
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(HERE, script),
             str(r), str(nranks), rdv_dir, out_dir, run_id],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(nranks)
    ]
    outputs = [p.communicate(timeout=300)[0].decode() for p in procs]
    if any(
        "Multiprocess computations aren't implemented on the CPU backend" in out
        for out in outputs
    ):
        # older jax/XLA CPU backends cannot execute cross-process SPMD
        # programs at all — the capability this harness exists to test is
        # absent from the environment, not broken in the framework
        pytest.skip("CPU backend lacks multi-process SPMD execution (jax/XLA too old)")
    for r, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
    return out_dir


def _single_process_reference():
    from tests.mp_worker import make_dataset

    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.models.knn import NearestNeighbors
    from spark_rapids_ml_tpu.models.regression import LinearRegression

    X, y_log, y_lin = make_dataset()
    df = pd.DataFrame(
        {"features": list(X), "label": y_log, "target": y_lin,
         "id": np.arange(len(X), dtype=np.int64)}
    )
    pca = PCA(k=3, inputCol="features", float32_inputs=False).fit(df)
    lin = (
        LinearRegression(regParam=0.0, float32_inputs=False, labelCol="target")
        .setFeaturesCol("features")
        .fit(df)
    )
    lr = (
        LogisticRegression(maxIter=100, regParam=0.1, tol=1e-10, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(df)
    )
    km = KMeans(k=4, maxIter=15, seed=3, float32_inputs=False).setFeaturesCol("features").fit(df)
    gnn = (
        NearestNeighbors(k=3, float32_inputs=False).setInputCol("features").setIdCol("id").fit(df)
    )
    return pca, lin, lr, km, gnn, df


@pytest.mark.parametrize("nranks", [2, 3])
def test_multiprocess_fit_matches_single_process(nranks, tmp_path):
    out_dir = _launch_workers(nranks, tmp_path)
    pca, lin, lr, km, gnn, full_df = _single_process_reference()
    from tests.mp_worker import make_dataset, split_bounds

    X, _, _ = make_dataset()
    bounds = split_bounds(len(X), nranks)

    for r in range(nranks):
        got = np.load(os.path.join(out_dir, f"rank{r}.npz"))
        np.testing.assert_allclose(got["pca_components"], pca.components_, rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(got["pca_mean"], pca.mean_, rtol=1e-6, atol=1e-10)
        np.testing.assert_allclose(
            got["pca_var_ratio"], pca.explained_variance_ratio_, rtol=1e-6
        )
        np.testing.assert_allclose(got["lin_coef"], lin.coef_, rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(got["lin_intercept"], lin.intercept_, rtol=1e-6, atol=1e-8)
        # the SORTED labels mean later ranks hold a single class locally — the
        # rendezvous class-merge must still find both classes globally
        np.testing.assert_array_equal(got["lr_classes"], lr.classes_)
        np.testing.assert_allclose(got["lr_coef"], lr.coef_, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(got["lr_intercept"], lr.intercept_, rtol=1e-4, atol=1e-6)
        # KMeans: identical rendezvous-gathered init -> same Lloyd trajectory
        np.testing.assert_allclose(got["km_centers"], km.cluster_centers_, rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(
            float(got["km_inertia"]), km.inertia_, rtol=1e-6
        )
        # RF: tree growth is partition-layout-dependent (like cuRF) — require
        # the distributed forest to actually FIT its local slice
        # each device grows trees on its own small row shard here (~36 rows),
        # so the bar is "clearly fitted" (far above the ~0 of noise), not
        # "strongly converged" — realizations across RNG-stream changes have
        # landed between 0.52 and 0.75
        corr = np.corrcoef(got["rf_pred"], got["rf_target"])[0, 1]
        assert corr > 0.5, f"rank {r} RF pred/target correlation {corr}"
        # kNN: each rank queried its first 5 local rows against the GLOBAL
        # items; must match the single-process result for those query rows
        lo = bounds[r]
        q_rows = full_df.iloc[lo : lo + 5]
        _, _, knn_ref = gnn.kneighbors(q_rows)
        np.testing.assert_array_equal(got["knn_query_ids"], knn_ref["query_id"].to_numpy())
        np.testing.assert_array_equal(
            got["knn_indices"], np.stack(knn_ref["indices"].to_numpy())
        )
        np.testing.assert_allclose(
            got["knn_distances"], np.stack(knn_ref["distances"].to_numpy()),
            rtol=1e-7, atol=1e-6,  # self-distances are 0 ± sqrt-expansion noise
        )
        # sparse SPMD kNN (local exact + merged top-k) equals the dense result
        np.testing.assert_array_equal(
            got["knn_sp_indices"], np.stack(knn_ref["indices"].to_numpy())
        )
        np.testing.assert_allclose(
            got["knn_sp_distances"], np.stack(knn_ref["distances"].to_numpy()),
            rtol=1e-7, atol=1e-6,
        )
        # DBSCAN: replicated-data SPMD labels equal the single-process labels
        # for this rank's rows (deterministic: same full data, same program)
        from spark_rapids_ml_tpu.models.clustering import DBSCAN

        db_ref = (
            DBSCAN(eps=1.5, min_samples=3).setFeaturesCol("features").fit(full_df)
            .transform(full_df)["prediction"].to_numpy()
        )
        np.testing.assert_array_equal(got["db_labels"], db_ref[bounds[r] : bounds[r + 1]])
        # UMAP: every rank fit the same gathered data with the same seed ->
        # identical embeddings across ranks; finite and right-shaped
        emb = got["um_emb"]
        assert emb.shape == (len(X), 2) and np.isfinite(emb).all()
        if r > 0:
            ref0 = np.load(os.path.join(out_dir, "rank0.npz"))["um_emb"]
            np.testing.assert_allclose(emb, ref0, rtol=1e-6, atol=1e-7)
        # ANN with nprobe == nlist: local searches are exhaustive, so the
        # merged global top-k equals brute force (compare neighbor id sets —
        # equidistant neighbors may order differently)
        q = X[bounds[r] : bounds[r] + 5]
        d2 = ((q[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        brute = np.argsort(d2, axis=1, kind="stable")[:, :3]
        for qi in range(5):
            assert set(got["ann_indices"][qi]) == set(brute[qi]), (
                f"rank {r} q{qi}: {got['ann_indices'][qi]} vs {brute[qi]}"
            )


def test_multiprocess_default_is_opt_in(tmp_path):
    # estimators without rendezvous-merged host stats must refuse SPMD fits
    from spark_rapids_ml_tpu.core import _TpuCaller
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.models.tree import _RandomForestEstimator

    assert KMeans._supports_multiprocess  # rendezvous-merged init centers
    assert _RandomForestEstimator._supports_multiprocess  # merged classes/bins
    assert not _TpuCaller._supports_multiprocess  # default is opt-in


def test_multirank_context_requires_rendezvous():
    from spark_rapids_ml_tpu.parallel import TpuContext

    with pytest.raises(RuntimeError, match="rendezvous"):
        with TpuContext(0, 2):
            pass


def test_spmd_sweep_single_ingest_and_agreed_winner(tmp_path):
    # ISSUE 19 acceptance: a CrossValidator sweep under multi-process SPMD
    # runs through the multi-fit engine (no per-fold fallback) — each rank
    # asserts ONE ingest + ONE layout for the whole sweep in-process
    # (tests/sweep_worker.py), and the gathered held-out scoring makes the
    # metric grid and the winning param map IDENTICAL across ranks
    out_dir = _launch_workers(2, tmp_path, script="sweep_worker.py")
    got = [
        np.load(os.path.join(out_dir, f"rank{r}.npz")) for r in range(2)
    ]
    assert got[0]["avg_metrics"].shape == (3,)
    assert np.isfinite(got[0]["avg_metrics"]).all()
    # bit-identical agreement: every rank scored the SAME globalized
    # validation rows, so metrics, winner, and refit coefficients all match
    np.testing.assert_array_equal(got[0]["avg_metrics"], got[1]["avg_metrics"])
    np.testing.assert_array_equal(got[0]["best_reg"], got[1]["best_reg"])
    np.testing.assert_array_equal(got[0]["best_coef"], got[1]["best_coef"])
    assert int(got[0]["spmd_rounds"]) >= 4  # one agreement round per fit
