#
# Fixture corpus for the numerics gate (ci/analysis/rules/numerics.py +
# rules/histogram.py): TP + FP-guard per invariant, the prose/docstring FP
# class, import-alias resolution, waiver handling, the interprocedural
# param-dtype / entry-x64 / collective-reachability compositions, and the
# result-cache engine-hash pin that keeps a new rule module from being
# masked by stale cached verdicts.
#
import pathlib
import sys
import textwrap

ROOT = pathlib.Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from ci.analysis import analyze_source  # noqa: E402
from ci.analysis.engine import analyze_sources  # noqa: E402
from ci.analysis import cache as cache_mod  # noqa: E402
from ci.analysis.rules import (  # noqa: E402
    HistogramLoopRule,
    HygieneRule,
    PrecisionFlowRule,
    PrngDisciplineRule,
    default_rules,
)


def run(src, rule_factory, relpath="spark_rapids_ml_tpu/snippet.py"):
    return analyze_source(textwrap.dedent(src), relpath=relpath, rules=[rule_factory()])


def run_files(files, rule_factory):
    return analyze_sources(
        {rel: textwrap.dedent(src) for rel, src in files.items()},
        rules=[rule_factory()],
    )


def rule_ids(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------------------
# precision-flow: accumulator narrowing
# --------------------------------------------------------------------------


def test_precision_narrow_reassign_fires():
    src = """
    import jax.numpy as jnp
    def solve(x):
        acc = jnp.zeros((4,), dtype=jnp.float64)
        acc = acc.astype(jnp.float32)
        return acc
    """
    fs = run(src, PrecisionFlowRule)
    # the astype itself types the RHS; exactly one narrow finding
    narrows = [f for f in fs if "accumulator" in f.message]
    assert len(narrows) == 1 and narrows[0].line == 5
    assert "`acc`" in narrows[0].message


def test_precision_narrow_augassign_fires():
    src = """
    import jax.numpy as jnp
    def solve(x):
        acc = jnp.zeros((4,), dtype=jnp.float64)
        acc += x.astype(jnp.bfloat16)
        return acc
    """
    fs = run(src, PrecisionFlowRule)
    narrows = [f for f in fs if "accumulator" in f.message]
    assert len(narrows) == 1 and "augmented" in narrows[0].message


def test_precision_narrow_fp_guards():
    # f32 -> f32 rebind, f64 -> f64 promote-preserving update, and an
    # UNKNOWN-dtype reassign must all stay clean (unknown never guesses);
    # f64 established via the HOST spelling so no x64 finding mixes in
    src = """
    import numpy as np
    import jax.numpy as jnp
    def solve(x, other):
        a = jnp.zeros((4,), dtype=jnp.float32)
        a = a.astype(jnp.float32)
        b = x.astype(np.float64)
        b = b + x
        b = other(b)
        return a, b
    """
    assert run(src, PrecisionFlowRule) == []


# --------------------------------------------------------------------------
# precision-flow: low-precision dots
# --------------------------------------------------------------------------


def test_precision_lowdot_inline_bf16_fires_and_pref_passes():
    src = """
    import jax.numpy as jnp
    def score(x, c):
        bad = jnp.dot(x.astype(jnp.bfloat16), c.astype(jnp.bfloat16).T)
        good = jnp.dot(
            x.astype(jnp.bfloat16), c.astype(jnp.bfloat16).T,
            preferred_element_type=jnp.float32,
        )
        return bad, good
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"] and fs[0].line == 4
    assert "preferred_element_type" in fs[0].message


def test_precision_lowdot_matmul_operator_fires():
    src = """
    import jax.numpy as jnp
    def score(x, c):
        a = x.astype(jnp.bfloat16)
        return a @ c
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"]
    assert "`@`" in fs[0].message


def test_precision_lowdot_interprocedural_param_meet_fires():
    # bf16 flows through a call: the dot is on a bare parameter whose ONE
    # resolved call site passes bf16 — the param-dtype fixpoint proves it
    files = {
        "spark_rapids_ml_tpu/a.py": """
        import jax.numpy as jnp
        def caller(x):
            b = x.astype(jnp.bfloat16)
            return helper(b)
        def helper(v):
            return jnp.matmul(v, v)
        """,
    }
    fs = run_files(files, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"]
    assert "matmul" in fs[0].message


def test_precision_lowdot_conflicting_callers_stay_clean():
    # two call sites disagree (bf16 vs f32): the meet poisons to unknown —
    # findings are proven, never guessed
    files = {
        "spark_rapids_ml_tpu/a.py": """
        import jax.numpy as jnp
        def c1(x):
            return helper(x.astype(jnp.bfloat16))
        def c2(x):
            return helper(x.astype(jnp.float32))
        def helper(v):
            return jnp.matmul(v, v)
        """,
    }
    assert run_files(files, PrecisionFlowRule) == []


def test_precision_lowdot_einsum_skips_equation_string():
    src = """
    import jax.numpy as jnp
    def score(x):
        a = x.astype(jnp.bfloat16)
        return jnp.einsum("td,tcd->tc", a, a)
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"]


def test_precision_lowdot_lax_dot_solver_idiom_pair():
    # the sanctioned mixed-precision solver cast (docs/performance.md
    # "Mixed-precision solvers", ops/logistic._dense_ops / streaming._fdot):
    # bf16 operands + f32 accumulator passes; dropping the accumulator
    # annotation from the SAME dot is exactly what the rule must catch
    src = """
    import jax
    import jax.numpy as jnp
    def matvec(x, beta):
        bad = jax.lax.dot(
            x.astype(jnp.bfloat16), beta.astype(jnp.bfloat16),
            precision=jax.lax.Precision.DEFAULT,
        )
        good = jax.lax.dot(
            x.astype(jnp.bfloat16), beta.astype(jnp.bfloat16),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32,
        )
        return bad, good
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"] and fs[0].line == 5
    assert "preferred_element_type" in fs[0].message


def test_precision_lowdot_einsum_solver_idiom_pair():
    # the sufficient-stat einsum variant (ops/linalg.weighted_cov fast path):
    # two bf16 operands with an f32 accumulator pass; without it, fires
    src = """
    import jax.numpy as jnp
    def gram(xw, x):
        bad = jnp.einsum(
            "nd,ne->de", xw.astype(jnp.bfloat16), x.astype(jnp.bfloat16)
        )
        good = jnp.einsum(
            "nd,ne->de", xw.astype(jnp.bfloat16), x.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return bad, good
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"] and fs[0].line == 4
    assert "preferred_element_type" in fs[0].message


# --------------------------------------------------------------------------
# precision-flow: unguarded jnp f64
# --------------------------------------------------------------------------


def test_precision_f64_unguarded_fires_and_np_host_passes():
    src = """
    import numpy as np
    import jax.numpy as jnp
    def place(x):
        dev = jnp.asarray(x, dtype=jnp.float64)
        host = np.asarray(x, dtype=np.float64)
        return dev, host
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"] and fs[0].line == 5
    assert "x64 guard" in fs[0].message


def test_precision_f64_under_with_guard_passes():
    src = """
    import jax.numpy as jnp
    from jax.experimental import enable_x64
    def place(x):
        with enable_x64(True):
            return jnp.asarray(x, dtype=jnp.float64)
    """
    assert run(src, PrecisionFlowRule) == []


def test_precision_f64_negated_guard_polarity():
    # `if not jax_enable_x64:` guards the ELSE arm — f64 in the TRUE arm
    # runs exactly when x64 is OFF and must still be a finding
    # (review-caught polarity blindness)
    src = """
    import jax
    import jax.numpy as jnp
    def place(x):
        if not jax.config.jax_enable_x64:
            bad = jnp.asarray(x, dtype=jnp.float64)
        else:
            good = jnp.asarray(x, dtype=jnp.float64)
        return bad, good
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"] and fs[0].line == 6


def test_precision_f64_not_equal_false_guard_is_positive_polarity():
    # `!= False` is truthy exactly when x64 is ON: the true arm IS guarded
    # (review-caught operator blindness in the negation check)
    src = """
    import jax
    import jax.numpy as jnp
    def place(x):
        if jax.config.jax_enable_x64 != False:
            good = jnp.asarray(x, dtype=jnp.float64)
        else:
            bad = jnp.asarray(x, dtype=jnp.float64)
        return good, bad
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"] and fs[0].line == 8


def test_precision_f64_nested_def_escapes_with_guard():
    # a closure defined inside `with enable_x64():` runs when CALLED —
    # after the scoped guard exited — so its f64 is NOT guarded
    # (review-caught: _x64_depth must reset per nested def, like `held`)
    src = """
    import jax.numpy as jnp
    from jax.experimental import enable_x64
    def factory(n):
        with enable_x64(True):
            def later():
                return jnp.zeros((n,), dtype=jnp.float64)
        return later
    """
    fs = run(src, PrecisionFlowRule)
    assert rule_ids(fs) == ["precision-flow"]
    assert "x64 guard" in fs[0].message


def test_precision_starred_args_do_not_shift_param_dtypes():
    # `callee(*xs, key)`: past the splat, positional alignment is unknown —
    # the bf16 must NOT be met into param `b` (review-caught misattribution)
    files = {
        "spark_rapids_ml_tpu/a.py": """
        import jax.numpy as jnp
        def caller(xs, x):
            key = x.astype(jnp.bfloat16)
            return callee(*xs, key)
        def callee(a, b, c):
            return jnp.dot(a, b)
        """,
    }
    assert run_files(files, PrecisionFlowRule) == []


def test_precision_f64_entry_guard_fixpoint_passes():
    # the f64 helper is ONLY called from inside the x64 guard: the
    # entry-x64 fixpoint proves it guarded across the call
    files = {
        "spark_rapids_ml_tpu/a.py": """
        import jax.numpy as jnp
        from jax.experimental import enable_x64
        def outer(x):
            with enable_x64(True):
                return widen(x)
        def widen(x):
            return jnp.asarray(x, dtype=jnp.float64)
        """,
    }
    assert run_files(files, PrecisionFlowRule) == []


def test_precision_docstring_mention_does_not_fire():
    src = '''
    import jax.numpy as jnp
    def doc(x):
        """Uses jnp.dot(a.astype(jnp.bfloat16), b) and jnp.float64 in prose."""
        return x
    '''
    assert run(src, PrecisionFlowRule) == []


def test_precision_waiver_suppresses_and_bare_waiver_is_finding():
    waived = """
    import jax.numpy as jnp
    def score(x, c):
        a = x.astype(jnp.bfloat16)
        return a @ c  # precision-ok: documented fast path, parity-tested
    """
    assert run(waived, PrecisionFlowRule) == []
    bare = """
    import jax.numpy as jnp
    def score(x, c):
        a = x.astype(jnp.bfloat16)
        return a @ c  # precision-ok
    """
    fs = analyze_source(
        textwrap.dedent(bare), rules=[PrecisionFlowRule(), HygieneRule()]
    )
    assert sorted(rule_ids(fs)) == ["precision-flow", "waiver-missing-reason"]


# --------------------------------------------------------------------------
# prng-discipline: key linearity
# --------------------------------------------------------------------------


def test_prng_reuse_two_samplers_fires():
    src = """
    import jax
    def draw(n):
        key = jax.random.PRNGKey(0)
        a = jax.random.normal(key, (n,))
        b = jax.random.uniform(key, (n,))
        return a, b
    """
    fs = run(src, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"] and fs[0].line == 6
    assert "already consumed" in fs[0].message


def test_prng_sample_after_split_fires():
    src = """
    import jax
    def draw(n):
        key = jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(key)
        noise = jax.random.normal(key, (n,))
        return k1, k2, noise
    """
    fs = run(src, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"]
    assert "`split`" in fs[0].message


def test_prng_split_rebind_chain_is_clean():
    src = """
    import jax
    def draw(seed, n):
        key = jax.random.PRNGKey(seed)
        key, k0 = jax.random.split(key)
        a = jax.random.normal(k0, (n,))
        key, k1 = jax.random.split(key)
        b = jax.random.uniform(k1, (n,))
        return a, b
    """
    assert run(src, PrngDisciplineRule) == []


def test_prng_loop_reuse_of_outer_key_fires():
    src = """
    import jax
    def draw(n):
        key = jax.random.PRNGKey(0)
        out = []
        for i in range(4):
            out.append(jax.random.normal(key, (n,)))
        return out
    """
    fs = run(src, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"]


def test_prng_fold_in_per_index_stream_is_clean():
    # the sanctioned many-streams pattern: fold_in derives without consuming
    src = """
    import jax
    def draw(seed, n):
        key = jax.random.PRNGKey(seed)
        out = []
        for e in range(4):
            ke = jax.random.fold_in(key, e)
            out.append(jax.random.normal(ke, (n,)))
        return out
    """
    assert run(src, PrngDisciplineRule) == []


def test_prng_loop_remint_inside_body_is_clean():
    src = """
    import jax
    def draw(n):
        key = jax.random.PRNGKey(0)
        out = []
        for i in range(4):
            key, sub = jax.random.split(key)
            out.append(jax.random.normal(sub, (n,)))
        return out
    """
    assert run(src, PrngDisciplineRule) == []


def test_prng_for_target_subkeys_are_fresh_per_iteration():
    # the canonical batch-split idiom: the loop TARGET is a fresh binding
    # each iteration, never a reuse (review-caught FP)
    src = """
    import jax
    def draw(key, n):
        out = []
        for sub in jax.random.split(key, n):
            out.append(jax.random.normal(sub, (3,)))
        return out
    """
    assert run(src, PrngDisciplineRule) == []


def test_prng_nested_def_in_loop_reports_once():
    # the double loop-body scan re-enters nested scopes: a violation inside
    # a closure defined in a loop must still report exactly ONCE
    # (review-caught double-report)
    src = """
    import numpy as np
    def outer(n):
        fns = []
        for i in range(n):
            def make():
                return np.random.rand(3)
            fns.append(make)
        return fns
    """
    fs = run(src, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"]


def test_prng_branch_arms_each_consume_once_is_clean():
    src = """
    import jax
    def draw(flag, key, n):
        if flag:
            out = jax.random.normal(key, (n,))
        else:
            out = jax.random.uniform(key, (n,))
        return out
    """
    assert run(src, PrngDisciplineRule) == []


def test_prng_consumed_in_branch_then_after_fires():
    src = """
    import jax
    def draw(flag, key, n):
        if flag:
            out = jax.random.normal(key, (n,))
        else:
            out = None
        tail = jax.random.uniform(key, (n,))
        return out, tail
    """
    fs = run(src, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"]


def test_prng_dropped_split_fires_and_underscore_bind_is_clean():
    src = """
    import jax
    def derive(key):
        jax.random.split(key)
        k1, _ = jax.random.split(key)
        return k1
    """
    fs = run(src, PrngDisciplineRule)
    # one drop finding; the second split of the same key is also reuse
    kinds = [("never bound" in f.message, "already consumed" in f.message) for f in fs]
    assert (True, False) in kinds and (False, True) in kinds and len(fs) == 2


def test_prng_nested_function_param_shadows_outer_key():
    # the gen_data shape: the inner fn's `key` PARAM is a fresh binding —
    # outer split + inner sample is NOT reuse
    src = """
    import jax
    def gen(seed, n):
        key = jax.random.PRNGKey(seed)
        k1, k2 = jax.random.split(key)
        def label_fn(X, key):
            return jax.random.normal(key, (n,))
        return label_fn(None, k2), jax.random.normal(k1, (n,))
    """
    assert run(src, PrngDisciplineRule) == []


# --------------------------------------------------------------------------
# prng-discipline: seeding
# --------------------------------------------------------------------------


def test_prng_wallclock_seed_fires():
    src = """
    import time
    import jax
    def mint():
        return jax.random.PRNGKey(int(time.time()))
    """
    fs = run(src, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"]
    assert "time.time" in fs[0].message


def test_prng_unseeded_default_rng_and_global_np_random_fire():
    src = """
    import numpy as np
    def mint(n):
        rng = np.random.default_rng()
        x = np.random.normal(size=n)
        return rng, x
    """
    fs = run(src, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"] * 2


def test_prng_seeded_default_rng_is_clean():
    src = """
    import numpy as np
    def mint(seed, part):
        return np.random.default_rng(seed * 7919 + part)
    """
    assert run(src, PrngDisciplineRule) == []


def test_prng_alias_import_still_caught():
    src = """
    import jax.random as jr
    def draw(n):
        key = jr.PRNGKey(0)
        a = jr.normal(key, (n,))
        b = jr.normal(key, (n,))
        return a, b
    """
    fs = run(src, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"]


def test_prng_scope_gen_data_yes_other_benchmark_no():
    src = """
    import numpy as np
    def mint(n):
        return np.random.normal(size=n)
    """
    assert rule_ids(run(src, PrngDisciplineRule, relpath="benchmark/gen_data.py")) == [
        "prng-discipline"
    ]
    assert run(src, PrngDisciplineRule, relpath="benchmark/bench_foo.py") == []


def test_prng_docstring_mention_does_not_fire():
    src = '''
    def doc():
        """Call jax.random.normal(key, ...) twice and np.random.seed(0)."""
        return None
    '''
    assert run(src, PrngDisciplineRule) == []


# --------------------------------------------------------------------------
# prng-discipline: rank-dependent keys x collective reachability
# --------------------------------------------------------------------------

_RANKDEP_TMPL = """
import jax

def fit(rank, rdv, seed, n):
    key = jax.random.fold_in(jax.random.PRNGKey(seed), rank){waiver}
    x = jax.random.normal(key, (n,))
    {collective}
    return x
"""


def test_prng_rank_dep_with_collective_fires():
    files = {
        "spark_rapids_ml_tpu/a.py": _RANKDEP_TMPL.format(
            waiver="", collective="rdv.allgather(x)"
        )
    }
    fs = run_files(files, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"]
    assert "lockstep" in fs[0].message and "rank" in fs[0].message


def test_prng_rank_dep_without_collective_is_clean():
    files = {
        "spark_rapids_ml_tpu/a.py": _RANKDEP_TMPL.format(waiver="", collective="pass")
    }
    assert run_files(files, PrngDisciplineRule) == []


def test_prng_rank_dep_waiver_suppresses():
    files = {
        "spark_rapids_ml_tpu/a.py": _RANKDEP_TMPL.format(
            waiver="  # prng-ok: per-rank sample, allgathered below",
            collective="rdv.allgather(x)",
        )
    }
    assert run_files(files, PrngDisciplineRule) == []


def test_prng_rank_dep_reaches_collective_through_call_chain():
    # the collective sits one resolved call away: may_block's fixpoint
    # carries it back to the minting function
    files = {
        "spark_rapids_ml_tpu/a.py": """
        import jax
        def exchange(rdv, x):
            return rdv.allgather(x)
        def fit(rank, rdv, seed, n):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), rank)
            x = jax.random.normal(key, (n,))
            return exchange(rdv, x)
        """,
    }
    fs = run_files(files, PrngDisciplineRule)
    assert rule_ids(fs) == ["prng-discipline"]


# --------------------------------------------------------------------------
# histogram-loop
# --------------------------------------------------------------------------


def test_histogram_segment_sum_over_digitize_fires():
    src = """
    import jax
    import jax.numpy as jnp
    def hist(x, edges, vals, n):
        bins = jnp.digitize(x, edges)
        return jax.ops.segment_sum(vals, bins, num_segments=n)
    """
    fs = run(src, HistogramLoopRule)
    assert rule_ids(fs) == ["histogram-loop"]
    assert "segment_sum" in fs[0].message


def test_histogram_at_add_and_one_hot_matmul_fire():
    src = """
    import jax
    import jax.numpy as jnp
    def hist(x, edges, vals, n):
        bins = jnp.searchsorted(edges, x).astype(jnp.int32)
        h1 = jnp.zeros((n,), vals.dtype).at[bins].add(vals)
        oh = jax.nn.one_hot(bins, n)
        h2 = oh.T @ vals
        return h1, h2
    """
    fs = run(src, HistogramLoopRule)
    assert rule_ids(fs) == ["histogram-loop"] * 2


def test_histogram_cross_function_binning_is_clean():
    # bins produced by ANOTHER function launder: that factored boundary is
    # exactly what the future core provides
    src = """
    import jax
    import jax.numpy as jnp
    def bin_features(x, edges):
        return jnp.searchsorted(edges, x)
    def accumulate(bins, vals, n):
        return jax.ops.segment_sum(vals, bins, num_segments=n)
    """
    assert run(src, HistogramLoopRule) == []


def test_histogram_non_binned_scatter_is_clean():
    # argmin-derived ids (the distance core's one-hot accumulate shape) and
    # plain index scatters are NOT histogram loops
    src = """
    import jax
    import jax.numpy as jnp
    def assign(x, c, w, k):
        ids = jnp.argmin(x, axis=1)
        oh = jax.nn.one_hot(ids, k)
        return oh.T @ w
    def scatter(idx, vals, n):
        return jnp.zeros((n,), vals.dtype).at[idx].add(vals)
    """
    assert run(src, HistogramLoopRule) == []


def test_histogram_waiver_and_exempt_core_file():
    src = """
    import jax
    import jax.numpy as jnp
    def hist(x, edges, vals, n):
        bins = jnp.digitize(x, edges)
        return jax.ops.segment_sum(vals, bins, num_segments=n)  # histogram-ok: genuinely different shape
    """
    assert run(src, HistogramLoopRule) == []
    unwaived = src.replace("  # histogram-ok: genuinely different shape", "")
    assert (
        run(unwaived, HistogramLoopRule, relpath="spark_rapids_ml_tpu/ops/histogram.py")
        == []
    )


def test_histogram_docstring_mention_does_not_fire():
    src = '''
    def doc():
        """segment_sum over jnp.digitize(x, edges) ids is the banned shape."""
        return None
    '''
    assert run(src, HistogramLoopRule) == []


# --------------------------------------------------------------------------
# catalog + cache integration
# --------------------------------------------------------------------------


def test_rules_registered_in_default_catalog():
    ids = {r.id for r in default_rules()}
    assert {"precision-flow", "prng-discipline", "histogram-loop"} <= ids


def test_engine_hash_covers_rule_modules(tmp_path):
    # the result cache's invalidation key must change when ANY rule module
    # changes — a stale cached verdict cannot mask a new/edited rule
    d = tmp_path / "analysis"
    (d / "rules").mkdir(parents=True)
    (d / "engine.py").write_text("ENGINE = 1\n")
    (d / "rules" / "numerics.py").write_text("RULE = 1\n")
    h1 = cache_mod.engine_hash(str(d))
    (d / "rules" / "numerics.py").write_text("RULE = 2\n")
    h2 = cache_mod.engine_hash(str(d))
    (d / "rules" / "brand_new_rule.py").write_text("RULE = 3\n")
    h3 = cache_mod.engine_hash(str(d))
    assert len({h1, h2, h3}) == 3


def test_prng_deferred_state_replays_from_cache(tmp_path, capsys):
    # cache-hit path: the rank-dep candidates are collector state — a
    # cached file must still produce the finding through restore_state
    root = tmp_path / "repo"
    pkg = root / "spark_rapids_ml_tpu"
    pkg.mkdir(parents=True)
    (root / "ci" / "analysis").mkdir(parents=True)
    (pkg / "mod.py").write_text(
        textwrap.dedent(_RANKDEP_TMPL.format(waiver="", collective="rdv.allgather(x)"))
    )
    from ci.analysis.cli import main as cli_main

    args = ["spark_rapids_ml_tpu", "--root", str(root), "--no-imports",
            "--baseline", str(root / "bl.json")]
    assert cli_main(args) == 1
    out1 = capsys.readouterr().out
    assert "prng-discipline" in out1
    # freeze the finding, then re-run: the file is served from the cache and
    # the deferred rank-dep candidate must replay through restore_state —
    # the finding shows up as baselined, not as silently absent
    assert cli_main(args + ["--write-baseline", "--allow-baseline-growth"]) == 0
    capsys.readouterr()
    assert cli_main(args) == 0
    out2 = capsys.readouterr().out
    assert "1 cached" in out2 and "1 baselined" in out2
