#
# Worker for the OOM-chaos subprocess harness (launched by
# tests/test_oocore.py; the non-test prefix keeps pytest from collecting it).
#
# The memory-safety acceptance scenarios need a REAL fit driver consuming a
# REAL `SRML_FAULT_PLAN` from the environment — exactly how an operator
# would chaos-test a deployment — so they run in a clean subprocess: the
# fault plan is process-global state, and the parity reference fit must see
# the plan SPENT, not absent.
#
# Modes (argv[1]; argv[2] = output JSON path):
#
#   demote       `oom:budget=<bytes>` plan: fit 1 enters admission against the
#                injected shrunken budget and must DEMOTE to streaming
#                (fit.demotions == 1); fit 2 (plan spent) runs resident. The
#                worker reports both verdicts, the counters, and the relative
#                coefficient difference — parity is judged here, in-process,
#                where both models share one backend.
#
#   midrecovery  `fail:stage=solve;oom:stage=placement:round=1` plan with
#                solver checkpoints on: attempt 0 runs RESIDENT, checkpoints
#                at the cadence boundary, and dies there on the injected
#                transient; the retry's RE-placement OOMs (round=1 = the
#                recovery attempt), converts to the typed budget error, and
#                the fit must complete on the STREAMING path RESUMED from the
#                attempt-0 checkpoint (checkpoint.restores >= 1) — the
#                "OOM mid-recovery" acceptance ladder end to end.
#
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel


def _dataset():
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(7)
    k, d = 3, 5
    offsets = rng.normal(scale=8.0, size=(k, d))
    x = np.concatenate(
        [rng.normal(size=(600, d)) + offsets[c] for c in range(k)]
    )
    return pd.DataFrame({"features": list(x)})


def main() -> None:
    mode = sys.argv[1]
    out_path = sys.argv[2]

    import numpy as np

    from spark_rapids_ml_tpu import core, telemetry
    from spark_rapids_ml_tpu.models.clustering import KMeans

    telemetry.enable()
    df = _dataset()
    core.config["stream_chunk_rows"] = 256  # multi-chunk: overlap measurable
    if mode == "midrecovery":
        core.config["checkpoint_every_iters"] = 2

    def fit():
        return KMeans(k=3, seed=11, maxIter=12, float32_inputs=False).setFeaturesCol(
            "features"
        ).fit(df)

    result = {"mode": mode, "error": None}
    try:
        faulted = fit()
        snap = telemetry.snapshot()
        result["counters"] = snap.get("counters", {})
        result["gauges"] = snap.get("gauges", {})
        result["admission_faulted"] = faulted._fit_metrics.get("admission")
        # reference fit: the plan is SPENT, so this runs clean + resident
        telemetry.registry().reset()
        clean = fit()
        result["admission_clean"] = clean._fit_metrics.get("admission")
        denom = np.maximum(np.abs(clean.cluster_centers_), 1e-30)
        result["max_rel_center_diff"] = float(
            np.max(np.abs(faulted.cluster_centers_ - clean.cluster_centers_) / denom)
        )
        result["n_iter_faulted"] = int(faulted._fit_metrics.get("n_iter", -1)) if isinstance(
            faulted._fit_metrics.get("n_iter"), (int, float)
        ) else None
    except Exception as e:  # noqa: BLE001 - the typed class IS the result
        result["error"] = type(e).__name__
        result["detail"] = str(e)
    with open(out_path + ".tmp", "w") as f:
        json.dump(result, f)
    os.replace(out_path + ".tmp", out_path)


if __name__ == "__main__":
    main()
