#
# Worker script for the SPMD-batched sweep test (launched as a subprocess by
# tests/test_multiprocess.py; the `sweep_` prefix keeps pytest from collecting
# it as a test module).
#
# Each process holds a RAGGED local row block and runs ONE CrossValidator
# sweep through the device-resident multi-fit engine under
# TpuContext(require_distributed=True): fold masks are local row masks,
# held-out scoring allgathers every rank's validation slice, and DeviceDataset
# placement fingerprints are agreed over one rendezvous round per fit. The
# worker asserts the sweep's data-plane telemetry IN-PROCESS (exactly one
# ingest and one layout for the whole sweep, per rank) and saves the metric
# grid + winner so the parent can assert cross-rank agreement.
#
import os
import sys


def main() -> None:
    rank = int(sys.argv[1])
    nranks = int(sys.argv[2])
    rdv_dir = sys.argv[3]
    out_dir = sys.argv[4]
    run_id = sys.argv[5] if len(sys.argv) > 5 else None

    import numpy as np
    import pandas as pd

    from spark_rapids_ml_tpu import telemetry
    from spark_rapids_ml_tpu.evaluation import RegressionEvaluator
    from spark_rapids_ml_tpu.models.regression import LinearRegression
    from spark_rapids_ml_tpu.parallel import FileRendezvous, TpuContext
    from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

    X, y = make_dataset()
    bounds = split_bounds(len(X), nranks)
    lo, hi = bounds[rank], bounds[rank + 1]
    df = pd.DataFrame({"features": list(X[lo:hi]), "label": y[lo:hi]})

    telemetry.enable()
    telemetry.registry().reset()
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(
        lr.getParam("regParam"), [0.0, 0.1, 1.0]
    ).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(metricName="rmse"), numFolds=3, seed=1,
    )
    rdv = FileRendezvous(rank, nranks, rdv_dir, timeout_s=120.0, run_id=run_id)
    with TpuContext(rank, nranks, rdv, require_distributed=True):
        model = cv.fit(df)

    # the acceptance pin, asserted per rank from this rank's own registry:
    # the WHOLE numFolds x paramMaps sweep performed exactly ONE ingest and
    # ONE layout — the engine did not fall back to per-fold fits under SPMD
    snap = telemetry.registry().snapshot()
    c, s = snap["counters"], snap["spans"]
    assert c["ingest.datasets"] == 1, c
    assert s["fit/ingest"]["count"] == 1, s
    assert s["fit/layout"]["count"] == 1, s
    assert c["fit.device_dataset_builds"] == 1, c
    assert c["fit.device_dataset_reuses"] == 3, c  # folds 1-2 + best refit
    # placement-fingerprint agreement ran one rendezvous round per fit
    assert c["fit.device_dataset_spmd_rounds"] >= 4, c

    best_reg = float(model.bestModel.getOrDefault("regParam"))
    np.savez(
        os.path.join(out_dir, f"rank{rank}.npz"),
        avg_metrics=np.asarray(model.avgMetrics, dtype=np.float64),
        best_reg=np.asarray(best_reg),
        best_coef=np.asarray(model.bestModel.coef_),
        spmd_rounds=np.asarray(int(c["fit.device_dataset_spmd_rounds"])),
    )


def make_dataset():
    """Deterministic regression data with a real ridge-path optimum."""
    import numpy as np

    rng = np.random.default_rng(11)
    n, d = 150, 5
    X = rng.normal(size=(n, d))
    coef = np.array([1.0, -2.0, 0.0, 0.5, 3.0])
    y = X @ coef + 0.3 * rng.normal(size=n)
    return X, y


def split_bounds(n, nranks):
    """Deliberately ragged split: rank 0 gets ~60% of the rows."""
    bounds = [0]
    big = int(n * 0.6)
    rest = n - big
    per = rest // max(1, nranks - 1) if nranks > 1 else 0
    bounds.append(big if nranks > 1 else n)
    for r in range(1, nranks):
        bounds.append(bounds[-1] + (per if r < nranks - 1 else n - bounds[-1]))
    return bounds


if __name__ == "__main__":
    main()
