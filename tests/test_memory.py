#
# HBM admission-budgeter unit tests (spark_rapids_ml_tpu/memory.py): every
# estimate formula pinned against an ANALYTICALLY computed byte count — the
# budgeter's contract is exact, simple arithmetic, so the tests do the same
# arithmetic independently and demand equality, not tolerance. CPU backend
# throughout (no capacity information -> the verdict ladder is driven by the
# `hbm_budget_bytes` override / chaos-injected budgets, exactly as documented).
#
import numpy as np
import pytest
import scipy.sparse as sp

from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu import memory
from spark_rapids_ml_tpu.data import ExtractedData
from spark_rapids_ml_tpu.errors import HbmBudgetError
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.clustering import KMeans
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.models.regression import LinearRegression


@pytest.fixture
def clean_config():
    keys = ("hbm_budget_bytes", "hbm_headroom_fraction", "stream_chunk_rows")
    saved = {k: core_mod.config[k] for k in keys}
    yield core_mod.config
    core_mod.config.update(saved)


def _dense_extracted(n=1000, d=12, label=True, dtype=np.float64):
    rng = np.random.default_rng(0)
    return ExtractedData(
        features=rng.normal(size=(n, d)).astype(dtype),
        label=rng.normal(size=n).astype(dtype) if label else None,
        feature_names=["features"],
    )


def _sparse_extracted(n=600, d=40, label=True, dtype=np.float64):
    rng = np.random.default_rng(1)
    csr = sp.random(n, d, density=0.1, format="csr", random_state=2, dtype=dtype)
    return ExtractedData(
        features=csr,
        label=rng.normal(size=n).astype(dtype) if label else None,
        feature_names=["features"],
    )


# ------------------------------------------------------------- formulas -----


def test_rows_per_device_pads_to_multiple():
    assert memory.rows_per_device(1000, 8) == 125
    assert memory.rows_per_device(1001, 8) == 126  # 1001 -> 1008 pad
    assert memory.rows_per_device(7, 8) == 1
    assert memory.rows_per_device(0, 8) == 0
    assert memory.rows_per_device(5, 1) == 5


def test_dense_placement_terms_analytic():
    ex = _dense_extracted(n=1000, d=12)
    terms = memory.placement_terms(ex, np.float64, 8)
    rows_dev = 125
    assert terms["placement.X"] == rows_dev * 12 * 8
    assert terms["placement.y"] == rows_dev * 8
    assert terms["placement.w"] == rows_dev * 8
    assert set(terms) == {"placement.X", "placement.y", "placement.w"}


def test_dense_placement_terms_unsupervised_no_label():
    ex = _dense_extracted(n=1000, d=12, label=False)
    terms = memory.placement_terms(ex, np.float64, 8)
    assert "placement.y" not in terms


def test_ell_placement_terms_include_padding():
    ex = _sparse_extracted(n=600, d=40)
    csr = ex.features
    k_max = int(np.diff(csr.indptr).max())
    assert k_max >= 2  # the padded-ELL point of the test
    terms = memory.placement_terms(ex, np.float64, 8)
    rows_dev = memory.rows_per_device(600, 8)
    # the padding cells are REAL placed bytes: rows_dev * k_max, not nnz
    assert terms["placement.ell_values"] == rows_dev * k_max * 8
    assert terms["placement.ell_indices"] == rows_dev * k_max * 4
    assert terms["placement.y"] == rows_dev * 8
    assert terms["placement.w"] == rows_dev * 8


def test_row_bytes_dense_and_ell():
    ex = _dense_extracted(n=100, d=12)
    # d feature doubles + label + weight
    assert memory.row_bytes(ex, np.float64) == 12 * 8 + 8 + 8
    exs = _sparse_extracted()
    k_max = int(np.diff(exs.features.indptr).max())
    assert memory.row_bytes(exs, np.float64) == k_max * (4 + 8) + 8 + 8


def test_memory_estimate_largest_names_dominant_term():
    est = memory.MemoryEstimate({"a": 10, "b": 300, "c": 2})
    assert est.total() == 312
    assert est.largest() == ("b", 300)
    assert memory.MemoryEstimate({}).largest() == ("", 0)


# ---------------------------------------------------- workspace hooks -------


def test_linear_workspace_terms_analytic():
    est = LinearRegression(float32_inputs=False)
    terms = est._solver_workspace_terms(125, 12, dict(est._solver_params), 8)
    assert terms == {"gram": 12 * 12 * 8, "vectors": 4 * 12 * 8}


def test_pca_workspace_terms_analytic():
    est = PCA(k=3, float32_inputs=False)
    terms = est._solver_workspace_terms(125, 12, dict(est._solver_params), 8)
    assert terms == {"covariance": 2 * 12 * 12 * 8, "vectors": 2 * 12 * 8}


def test_kmeans_workspace_terms_analytic():
    est = KMeans(k=5, float32_inputs=False)
    terms = est._solver_workspace_terms(125, 12, dict(est._solver_params), 8)
    # b = min(max_samples_per_batch, rows_dev) = 125; the predict-side
    # assignment tile is min(distance_tile_rows, rows_dev) = 125 rows
    assert terms == {
        "tile_buffers": 2 * 125 * 5 * 8,
        "centers": 2 * 5 * 12 * 8,
        "predict_tile": 125 * 5 * 8,
    }
    # huge shard: the fit tile caps at max_samples_per_batch, the predict
    # tile at config["distance_tile_rows"] (default 4096)
    terms = est._solver_workspace_terms(10**6, 12, dict(est._solver_params), 8)
    assert terms["tile_buffers"] == 2 * 32768 * 5 * 8
    assert terms["predict_tile"] == 4096 * 5 * 8


def test_kmeans_predict_tile_term_tracks_config():
    # the predict-side term follows the distance_tile_rows knob — the
    # admission estimate and the transform-path tiling cannot drift apart
    saved = core_mod.config["distance_tile_rows"]
    core_mod.config["distance_tile_rows"] = 512
    try:
        est = KMeans(k=5, float32_inputs=False)
        terms = est._solver_workspace_terms(10**6, 12, dict(est._solver_params), 8)
        assert terms["predict_tile"] == 512 * 5 * 8
    finally:
        core_mod.config["distance_tile_rows"] = saved


def test_logistic_workspace_terms_analytic():
    est = LogisticRegression(float32_inputs=False)
    terms = est._solver_workspace_terms(125, 12, dict(est._solver_params), 8)
    n_flat = 12 * 1 + 1
    assert terms == {
        "glm_logits": 2 * 125 * 1 * 8,
        "lbfgs_history": 2 * 10 * n_flat * 8,
    }
    # explicit multinomial family: documented k_out floor of 2
    est_m = LogisticRegression(family="multinomial", float32_inputs=False)
    terms_m = est_m._solver_workspace_terms(125, 12, dict(est_m._solver_params), 8)
    assert terms_m["glm_logits"] == 2 * 125 * 2 * 8
    assert terms_m["lbfgs_history"] == 2 * 10 * (12 * 2 + 2) * 8


def test_workspace_estimate_prefixes_and_streaming_rows():
    ex = _dense_extracted(n=1000, d=12)
    est = LogisticRegression(float32_inputs=False)
    ws = memory.workspace_estimate(est, ex, 8)
    assert set(ws.terms) == {"workspace.glm_logits", "workspace.lbfgs_history"}
    assert ws.terms["workspace.glm_logits"] == 2 * 125 * 8
    # streaming evaluates row-scaling terms at the CHUNK shard
    stream = memory.streaming_estimate(est, ex, 8, chunk_rows=256)
    chunk_dev = memory.rows_per_device(256, 8)
    rb = memory.row_bytes(ex, np.float64)
    assert stream.terms["stream.chunk_buffers"] == 2 * chunk_dev * rb
    assert stream.terms["workspace.glm_logits"] == 2 * chunk_dev * 8
    # ...while the history term is row-count independent
    assert (
        stream.terms["workspace.lbfgs_history"]
        == ws.terms["workspace.lbfgs_history"]
    )


def test_resident_estimate_is_placement_plus_workspace():
    ex = _dense_extracted(n=1000, d=12)
    est = LinearRegression(float32_inputs=False)
    res = memory.resident_estimate(est, ex, 8)
    placement = memory.placement_terms(ex, np.float64, 8)
    ws = memory.workspace_estimate(est, ex, 8)
    assert res.total() == sum(placement.values()) + ws.total()


# ------------------------------------------------------------ admission -----


class _FakeDevice:
    def __init__(self, ids):
        import numpy as _np

        self.devices = _np.array(ids)


class _FakeCtx:
    def __init__(self, n_dev=8, is_spmd=False):
        self.mesh = _FakeDevice(list(range(n_dev)))
        self.is_spmd = is_spmd


def test_admit_resident_when_no_capacity_information(clean_config):
    ex = _dense_extracted()
    dec = memory.admit_fit(LinearRegression(float32_inputs=False), ex, _FakeCtx())
    assert dec.verdict == memory.RESIDENT
    assert dec.budget_bytes is None
    assert dec.reason == "no capacity information"


def test_admit_applies_headroom_fraction(clean_config):
    ex = _dense_extracted(n=1000, d=12)
    est = LinearRegression(float32_inputs=False)
    need = memory.resident_estimate(est, ex, 8).total()
    clean_config["hbm_headroom_fraction"] = 0.25
    # budget = cap * 0.75: a capacity of need/0.75 + eps admits, below demotes
    clean_config["hbm_budget_bytes"] = int(need / 0.75) + 8
    dec = memory.admit_fit(est, ex, _FakeCtx())
    assert dec.verdict == memory.RESIDENT
    # hand the first admission's shared-ledger claim back (core's fit driver
    # does this in its finally) so the second admission sees a clean book
    memory.release_admission(dec)
    clean_config["hbm_budget_bytes"] = int(need / 0.75) - 8
    assert memory.admit_fit(est, ex, _FakeCtx()).verdict == memory.STREAM


def test_admit_demotes_and_sizes_chunks(clean_config):
    ex = _dense_extracted(n=1000, d=12)
    est = LinearRegression(float32_inputs=False)
    need = memory.resident_estimate(est, ex, 8).total()
    clean_config["hbm_budget_bytes"] = need  # headroom 0.1 -> budget < need
    dec = memory.admit_fit(est, ex, _FakeCtx())
    assert dec.verdict == memory.STREAM and dec.demoted
    assert dec.chunk_rows >= 1
    assert dec.estimate.total() <= dec.budget_bytes
    stamp = dec.stamp()
    assert stamp["verdict"] == "stream" and stamp["chunk_rows"] == dec.chunk_rows


def test_admit_honors_configured_chunk_rows(clean_config):
    ex = _dense_extracted(n=1000, d=12)
    est = LinearRegression(float32_inputs=False)
    clean_config["hbm_budget_bytes"] = memory.resident_estimate(est, ex, 8).total()
    clean_config["stream_chunk_rows"] = 300
    assert memory.admit_fit(est, ex, _FakeCtx()).chunk_rows == 300


def test_admit_raises_typed_when_even_streaming_cannot_fit(clean_config):
    ex = _dense_extracted(n=1000, d=12)
    est = LinearRegression(float32_inputs=False)
    clean_config["hbm_budget_bytes"] = 1000
    with pytest.raises(HbmBudgetError) as ei:
        memory.admit_fit(est, ex, _FakeCtx())
    e = ei.value
    assert e.largest_term == "stream.chunk_buffers"
    assert e.largest_term in str(e) and "streaming" in str(e)
    assert e.estimate_bytes and e.terms


def test_admit_refuses_streaming_without_estimator_support(clean_config):
    ex = _dense_extracted(n=1000, d=12)
    est = LinearRegression(float32_inputs=False)
    est._supports_streaming_fit = False
    clean_config["hbm_budget_bytes"] = 10_000
    with pytest.raises(HbmBudgetError, match="no out-of-core streaming path"):
        memory.admit_fit(est, ex, _FakeCtx())


def test_admit_refuses_streaming_under_spmd(clean_config):
    ex = _dense_extracted(n=1000, d=12)
    clean_config["hbm_budget_bytes"] = 10_000
    with pytest.raises(HbmBudgetError, match="single-controller"):
        memory.admit_fit(
            LinearRegression(float32_inputs=False), ex, _FakeCtx(is_spmd=True)
        )


def test_force_stream_skips_resident_check(clean_config):
    # the OOM-retry entry: no capacity information at all, still streams
    ex = _dense_extracted(n=1000, d=12)
    dec = memory.admit_fit(
        LinearRegression(float32_inputs=False), ex, _FakeCtx(), force_stream=True
    )
    assert dec.verdict == memory.STREAM and dec.demoted
    assert dec.chunk_rows == min(memory.DEFAULT_STREAM_CHUNK_ROWS, 1000)


# ------------------------------------------------------------ OOM match -----


def test_is_oom_error_matches_backend_shapes():
    assert memory.is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert memory.is_oom_error(RuntimeError("Out of memory allocating 1234 bytes"))
    assert memory.is_oom_error(MemoryError("boom"))
    assert not memory.is_oom_error(RuntimeError("some other failure"))
    assert not memory.is_oom_error(ValueError("RESOURCE_EXHAUSTED"))
    # an already-typed budget error must PROPAGATE, never re-enter conversion
    assert not memory.is_oom_error(HbmBudgetError("x"))


def test_as_hbm_budget_error_wraps_message():
    e = memory.as_hbm_budget_error(RuntimeError("RESOURCE_EXHAUSTED: 42"))
    assert isinstance(e, HbmBudgetError)
    assert "RESOURCE_EXHAUSTED: 42" in str(e)


def test_hbm_budget_error_is_permanent_memoryerror():
    from spark_rapids_ml_tpu.errors import is_transient

    e = HbmBudgetError("x", estimate_bytes=10, capacity_bytes=5,
                       largest_term="placement.X", largest_term_bytes=9)
    assert isinstance(e, MemoryError)
    assert not is_transient(e)
    assert "placement.X" in str(e) and "9" in str(e)


# ------------------------------------------- estimate vs memory_stats -------


@pytest.mark.slow
def test_estimate_vs_memory_stats_watermark(rng):
    """Where the backend DOES expose memory_stats (TPU/GPU), the resident
    estimate must bound the post-layout watermark growth within tolerance.
    On CPU jax exposes no stats — the test then only asserts the sampler's
    no-op contract (no gauges, no crash), keeping the lane green everywhere
    while pinning real numbers on chip runs."""
    import pandas as pd

    import jax

    from spark_rapids_ml_tpu import telemetry

    stats_available = any(
        (lambda d: (lambda s: bool(s))(d.memory_stats() if hasattr(d, "memory_stats") else None))(d)
        for d in jax.local_devices()
        if hasattr(d, "memory_stats")
    )
    telemetry.enable()
    telemetry.registry().reset()
    try:
        n, d = 4096, 16
        x = rng.normal(size=(n, d))
        y = x @ rng.normal(size=d)
        df = pd.DataFrame({"features": list(x), "label": y})
        est = LinearRegression(float32_inputs=False).setFeaturesCol("features")
        model = est.fit(df)
        gauges = telemetry.registry().snapshot().get("gauges", {})
        if not stats_available:
            assert "device.peak_bytes_in_use" not in gauges
            return
        ex = _dense_extracted(n=n, d=d)
        estimate = memory.resident_estimate(est, ex, jax.local_device_count())
        peak = gauges["device.peak_bytes_in_use"]
        # the estimate models the placement exactly; allocator rounding and
        # compiled-program scratch may add real bytes on top — the headroom
        # fraction exists for those. 2x is the documented tolerance.
        assert peak >= estimate.total() * 0.1
        assert estimate.total() <= peak * 2.0
        assert model.coef_ is not None
    finally:
        telemetry.disable()
        telemetry.registry().reset()


# ------------------------------------------------- shared HBM ledger --------
# The split-brain bugfix (docs/scheduling.md "The shared ledger"): fits and
# serving loads used to budget independently against FULL capacity, so a
# concurrent fit plus resident served models could jointly overshoot HBM.
# Both admission controllers now charge against capacity minus what the
# process-global scheduler.HbmLedger already holds.


class _FakeServeModel:
    """Minimal serving-hook surface for admit_model_load."""

    _float32_inputs = True

    def __init__(self, nbytes):
        self._nbytes = int(nbytes)

    def _serve_placement_terms(self):
        return {"params": self._nbytes}


def test_fit_admission_subtracts_resident_serving_bytes(clean_config):
    # THE satellite pin: a large model resident in the serving plane, then a
    # fit that would fit an EMPTY budget must demote to STREAM because the
    # model's bytes are already spoken for in the shared ledger.
    from spark_rapids_ml_tpu.scheduler.ledger import global_ledger

    ex = _dense_extracted(n=1000, d=12)
    est = LinearRegression(float32_inputs=False)
    need = memory.resident_estimate(est, ex, 8).total()
    # budget comfortably fits the fit alone (2x) — no model, RESIDENT
    clean_config["hbm_budget_bytes"] = int(2 * need / 0.9)
    dec = memory.admit_fit(est, ex, _FakeCtx())
    assert dec.verdict == memory.RESIDENT
    memory.release_admission(dec)

    # a "large model" load takes 1.25x the fit's bytes out of the budget:
    # what remains (~0.75x) no longer fits the fit resident, but DOES fit
    # its streaming working set — the demotion, not a refusal
    load = memory.admit_model_load(
        _FakeServeModel(int(1.25 * need)), bucket_rows_count=0
    )
    assert load.verdict == memory.RESIDENT
    assert global_ledger().reserved_bytes(kind="serve") >= 1.25 * need

    dec2 = memory.admit_fit(est, ex, _FakeCtx())
    assert dec2.verdict == memory.STREAM and dec2.demoted
    assert "already reserved" in dec2.reason  # the reason NAMES the ledger
    memory.release_admission(dec2)
    # evicting the model (releasing its claim) restores residency
    memory.release_admission(load)
    dec3 = memory.admit_fit(est, ex, _FakeCtx())
    assert dec3.verdict == memory.RESIDENT
    memory.release_admission(dec3)


def test_model_load_admission_subtracts_fit_reservations(clean_config):
    # ...and vice versa: a running fit's reservation counts against a model
    # load, which refuses typed instead of jointly overshooting
    ex = _dense_extracted(n=1000, d=12)
    est = LinearRegression(float32_inputs=False)
    need = memory.resident_estimate(est, ex, 8).total()
    clean_config["hbm_budget_bytes"] = int(2 * need / 0.9)
    fit_dec = memory.admit_fit(est, ex, _FakeCtx())  # holds `need` bytes
    assert fit_dec.verdict == memory.RESIDENT
    with pytest.raises(HbmBudgetError, match="held in the shared ledger"):
        memory.admit_model_load(_FakeServeModel(int(1.5 * need)), bucket_rows_count=0)
    # the fit completing frees the budget; the same load then admits
    memory.release_admission(fit_dec)
    load = memory.admit_model_load(_FakeServeModel(int(1.5 * need)), bucket_rows_count=0)
    assert load.verdict == memory.RESIDENT
    memory.release_admission(load)


def test_release_admission_is_idempotent_and_none_safe(clean_config):
    from spark_rapids_ml_tpu.scheduler.ledger import global_ledger

    ex = _dense_extracted(n=200, d=4)
    dec = memory.admit_fit(LinearRegression(float32_inputs=False), ex, _FakeCtx())
    assert global_ledger().reserved_bytes() > 0
    memory.release_admission(dec)
    assert global_ledger().reserved_bytes() == 0
    memory.release_admission(dec)  # double release: no-op, never a credit
    memory.release_admission(None)
    assert global_ledger().reserved_bytes() == 0
