#
# Shared O(nnz)-memory CSR generator for the sparse test lanes.
# `scipy.sparse.random` is unusable at large shapes: sampling its n*d cell
# space without replacement materializes index arrays orders of magnitude
# larger than the matrix (observed host MemoryError at 1e7 x 2200 on a
# 125 GB box). Per-row Binomial(d, density) nnz with with-replacement column
# draws matches the density; rare in-row duplicate columns sum — harmless
# for every consumer here.
#
import numpy as np
import scipy.sparse as sp


def random_csr(rng, n, d, density, dtype=np.float32, values="uniform"):
    """[n, d] CSR with ~`density` fill; `values` = "uniform" [0,1) or
    "normal"."""
    nnz_row = rng.binomial(d, density, size=n).astype(np.int64)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(nnz_row, out=indptr[1:])
    total = int(indptr[-1])
    indices = rng.integers(0, d, size=total).astype(np.int32)
    if values == "normal":
        data = rng.normal(size=total).astype(dtype)
    else:
        data = rng.random(total, dtype=np.float32).astype(dtype)
    return sp.csr_matrix((data, indices, indptr), shape=(n, d))
