#
# Shared CSR generator for the sparse test lanes — delegates to the
# benchmark's O(nnz) generator (benchmark/gen_data.py random_csr; see there
# for why scipy.sparse.random cannot be used at scale).
#
from benchmark.gen_data import random_csr  # noqa: F401
