#
# Worker for the simulated Spark barrier-stage test (spawned by
# tests/test_spark.py; no `test_` prefix so pytest doesn't collect it).
#
# Fidelity target: the reference runs its fit inside barrier-stage tasks and
# builds its communicator from `BarrierTaskContext` (reference
# core.py:698-797, cuml_context.py:80-103). This worker reproduces that wiring
# exactly — the framework sees ONLY a `BarrierTaskContext`-shaped object
# (partitionId / getTaskInfos / allGather) wrapped in `BarrierRendezvous`; the
# allGather itself is genuinely cross-process and blocking (file-backed), so
# rank skew, ordering and payload-size behavior match a real barrier stage,
# unlike an in-process stub.
#
import os
import sys


class _TaskInfo:
    def __init__(self, address: str) -> None:
        self.address = address


class FileBackedBarrierTaskContext:
    """`pyspark.BarrierTaskContext` duck-type whose allGather really blocks
    across OS processes. Only the surface the framework consumes exists."""

    def __init__(self, rank: int, nranks: int, root: str, run_id: str) -> None:
        from spark_rapids_ml_tpu.parallel import FileRendezvous

        self._rank = rank
        self._nranks = nranks
        self._rdv = FileRendezvous(
            rank, nranks, root, timeout_s=120.0, run_id=run_id
        )

    def partitionId(self) -> int:
        return self._rank

    def getTaskInfos(self):
        return [_TaskInfo(f"127.0.0.1:{5000 + i}") for i in range(self._nranks)]

    def allGather(self, message: str = ""):
        return self._rdv.allgather(message)

    def barrier(self) -> None:
        self._rdv.allgather("")


def main() -> None:
    rank = int(sys.argv[1])
    nranks = int(sys.argv[2])
    rdv_dir = sys.argv[3]
    out_dir = sys.argv[4]
    run_id = sys.argv[5]

    import numpy as np
    import pandas as pd

    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.models.feature import PCA
    from spark_rapids_ml_tpu.parallel import BarrierRendezvous, TpuContext

    from tests.mp_worker import make_dataset, split_bounds

    X, y_log, _ = make_dataset()
    bounds = split_bounds(len(X), nranks)
    lo, hi = bounds[rank], bounds[rank + 1]
    df = pd.DataFrame({"features": list(X[lo:hi]), "label": y_log[lo:hi]})

    # the task body the reference runs inside each barrier task: wrap the
    # context, build the communicator, fit
    ctx = FileBackedBarrierTaskContext(rank, nranks, rdv_dir, run_id)
    rdv = BarrierRendezvous(ctx)
    assert rdv.rank == rank and rdv.nranks == nranks
    with TpuContext(rdv.rank, rdv.nranks, rdv, require_distributed=True):
        pca = PCA(k=3, inputCol="features", float32_inputs=False).fit(df)
        lr = (
            LogisticRegression(maxIter=100, regParam=0.1, tol=1e-10, float32_inputs=False)
            .setFeaturesCol("features")
            .fit(df)
        )

    np.savez(
        os.path.join(out_dir, f"rank_{rank}.npz"),
        pc=np.asarray(pca.pc),
        mean=np.asarray(pca.mean),
        coef=np.asarray(lr.coefficients),
        intercept=np.asarray([lr.intercept]),
    )


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
