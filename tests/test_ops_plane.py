#
# Ops plane tests (docs/observability.md "Ops plane"): rolling windows
# (rates, windowed quantiles, clamp-to-horizon, concurrent writers), SLO
# burn-rate monitors (fast-window trip within one bucket width, error-rate
# and gauge-ceiling kinds, trip/clear events), exporters (Prometheus text,
# the /metrics + /healthz + /snapshot HTTP surface, rotating on-disk
# snapshots), the decision audit trail (per-tenant/trace queries, fed by
# fit admission + scheduler + serving verdicts), per-tenant ledger
# accounting (byte-seconds/chip-seconds integration), the drift seedling
# (per-column stats off the validation scan, PSI vs a registered baseline),
# and the opsreport CLI — including the chaos-injected latency-spike
# acceptance scenario: a `delay:stage=serve` plan flips /healthz to failing
# via the fast burn window, and opsreport names the tenant, the violated
# SLO, and the decision-log entries. All without a TPU.
#
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from spark_rapids_ml_tpu import core, ops_plane, telemetry
from spark_rapids_ml_tpu.ops_plane import audit, drift, export, slo

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture
def tele():
    """Fresh enabled registry with FAST window buckets; restore after."""
    saved = {
        k: core.config[k] for k in ("metrics_bucket_seconds", "metrics_bucket_count")
    }
    core.config["metrics_bucket_seconds"] = 0.05
    core.config["metrics_bucket_count"] = 20  # 1s horizon
    telemetry.registry().reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.registry().reset()
    core.config.update(saved)


@pytest.fixture
def slo_cfg():
    saved = core.config["slo"]
    slo.reset()
    yield
    core.config["slo"] = saved
    slo.reset()


@pytest.fixture(autouse=True)
def _fresh_audit():
    audit.clear()
    yield
    audit.clear()


# ------------------------------------------------------------- windows ------


def test_counter_rate_over_window(tele):
    for _ in range(10):
        tele.inc("ops_test.requests")
    r = tele.rate("ops_test.requests")  # full 1s horizon
    assert r is not None and r == pytest.approx(10.0, rel=0.01)
    # a narrower window clamps to >= one bucket and still sees the burst
    assert tele.rate("ops_test.requests", 0.05) > 0
    # never-incremented counters have no rate (not a zero one)
    assert tele.rate("ops_test.never") is None


def test_window_ages_out_but_cumulative_persists(tele):
    tele.observe("ops_test.lat_s", 5.0)
    assert tele.window_quantile("ops_test.lat_s", 0.99) == 5.0
    time.sleep(1.1)  # > the 1s horizon
    assert tele.window_quantile("ops_test.lat_s", 0.99) is None
    assert tele.window_count("ops_test.lat_s") == 0.0
    # the cumulative views never forget
    assert tele.quantile("ops_test.lat_s", 0.99) == 5.0
    s = telemetry.summarize_histogram("ops_test.lat_s")
    assert s["count"] == 1.0 and s["p99"] == 5.0
    w = telemetry.summarize_histogram("ops_test.lat_s", window_s=1.0)
    assert w["p99"] is None and w["window_count"] == 0.0


def test_window_fraction_over(tele):
    for v in (0.01, 0.01, 0.01, 1.0):
        tele.observe("ops_test.lat_s", v)
    frac, count = tele.window_fraction_over("ops_test.lat_s", 0.5)
    assert count == 4 and frac == pytest.approx(0.25)
    assert tele.window_fraction_over("ops_test.empty", 0.5) is None


def test_windows_zero_cost_when_disabled(tele):
    telemetry.disable()
    tele.inc("ops_test.off")
    tele.observe("ops_test.off_h", 1.0)
    assert tele.rate("ops_test.off") is None
    assert tele.window_quantile("ops_test.off_h", 0.5) is None


def test_window_params_resolved_from_config(tele):
    snap = tele.windows_snapshot()
    assert snap["bucket_seconds"] == 0.05
    assert snap["bucket_count"] == 20
    assert snap["horizon_s"] == pytest.approx(1.0)


def test_quantile_of_is_the_one_extraction():
    assert telemetry.quantile_of([], 0.5) is None
    assert telemetry.quantile_of([3.0, 1.0, 2.0], 0.5) == 2.0
    assert telemetry.quantile_of([1.0], 0.99) == 1.0
    # the registry's cumulative quantile delegates (same nearest-rank rule)
    telemetry.registry().reset()
    telemetry.enable()
    try:
        for v in (1.0, 2.0, 3.0):
            telemetry.registry().observe("ops_test.q", v)
        assert telemetry.registry().quantile("ops_test.q", 0.5) == 2.0
    finally:
        telemetry.disable()
        telemetry.registry().reset()


def test_windows_under_concurrent_writers(tele):
    """The satellite pin: threaded serving + scheduler hammer the registry;
    window reads must stay consistent (counts exact, quantiles inside the
    observed range, no exceptions) under concurrent inc/observe."""
    n_threads, per_thread = 8, 300
    stop = threading.Event()
    errors = []

    def writer(tid):
        try:
            for i in range(per_thread):
                tele.inc("ops_test.conc")
                tele.observe("ops_test.conc_h", float(tid * per_thread + i))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                tele.rate("ops_test.conc", 0.2)
                for q in (
                    tele.window_quantile("ops_test.conc_h", 0.99),
                    tele.quantile("ops_test.conc_h", 0.5),  # cumulative view too
                ):
                    if q is not None:
                        assert 0.0 <= q < n_threads * per_thread
                tele.windows_snapshot()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers + threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not errors
    # cumulative counter is exact under concurrency
    snap = tele.snapshot()
    assert snap["counters"]["ops_test.conc"] == n_threads * per_thread
    assert snap["histograms"]["ops_test.conc_h"]["count"] == n_threads * per_thread
    # the whole burst happened inside the horizon: the ring saw every inc
    r = tele.rate("ops_test.conc")
    assert r is not None and r > 0


# ----------------------------------------------------------------- SLO ------


def _latency_spec(threshold_s=0.1, objective=0.9, **over):
    spec = {
        "name": "test_lat", "kind": "latency", "histogram": "ops_test.lat_s",
        "threshold_s": threshold_s, "objective": objective,
    }
    spec.update(over)
    return spec


def test_latency_slo_trips_on_fast_window(tele, slo_cfg):
    core.config["slo"] = [_latency_spec(fast_burn=1.0)]
    assert slo.health()["healthy"]  # empty window: healthy
    t0 = time.monotonic()
    for _ in range(10):
        tele.observe("ops_test.lat_s", 1.0)  # every request violates
    h = slo.health()
    elapsed = time.monotonic() - t0
    assert not h["healthy"] and h["failing"] == ["test_lat"]
    # the fast window saw the spike within ONE bucket width of it landing
    assert elapsed < 2 * core.config["metrics_bucket_seconds"] + 0.5
    v = h["verdicts"][0]
    assert v["fast_burn"] is not None and v["fast_burn"] >= 1.0
    snap = tele.snapshot()
    assert snap["counters"]["slo.trips"] == 1.0
    assert snap["gauges"]["slo.failing"] == 1.0
    # the structured slo.* event landed in the flight recorder
    from spark_rapids_ml_tpu import diagnostics

    kinds = [e["kind"] for e in diagnostics.flight_recorder().events()]
    assert "slo.trip" in kinds


def test_latency_slo_clears_when_spike_ages_out(tele, slo_cfg):
    core.config["slo"] = [_latency_spec(fast_burn=1.0)]
    tele.observe("ops_test.lat_s", 1.0)
    assert not slo.health()["healthy"]
    time.sleep(1.1)  # horizon
    assert slo.health()["healthy"]
    assert tele.snapshot()["counters"]["slo.clears"] == 1.0


def test_error_rate_slo(tele, slo_cfg):
    core.config["slo"] = [{
        "name": "errs", "kind": "error_rate", "errors": "ops_test.errors",
        "total": "ops_test.total", "threshold": 0.01, "fast_burn": 1.0,
    }]
    for _ in range(20):
        tele.inc("ops_test.total")
    assert slo.health()["healthy"]  # zero errors
    tele.inc("ops_test.errors", 5)
    h = slo.health()
    assert not h["healthy"] and h["failing"] == ["errs"]


def test_gauge_ceiling_slo(tele, slo_cfg):
    core.config["slo"] = [{
        "name": "util", "kind": "gauge_ceiling",
        "gauge": "ops_test.util", "ceiling": 0.9,
    }]
    tele.gauge("ops_test.util", 0.5)
    assert slo.health()["healthy"]
    tele.gauge("ops_test.util", 0.95)
    h = slo.health()
    assert not h["healthy"]
    assert h["verdicts"][0]["value"] == pytest.approx(0.95)


def test_malformed_spec_degrades_to_error_verdict(tele, slo_cfg):
    core.config["slo"] = [
        {"name": "bad", "kind": "latency", "histogram": "h",
         "threshold_s": "not-a-number"},
        {"name": "unknown", "kind": "nope"},
    ]
    h = slo.health()  # must not raise
    assert h["healthy"]
    assert all("error" in v for v in h["verdicts"])


def test_no_specs_is_vacuously_healthy(tele, slo_cfg):
    core.config["slo"] = None
    h = slo.health()
    assert h["healthy"] and h["specs"] == 0


# ----------------------------------------------------------- exporters ------


def test_prometheus_render_names_and_rank_labels(tele):
    tele.inc("ops_test.requests", 3)
    tele.gauge("ops_test.util", 0.5)
    tele.observe("ops_test.lat_s", 0.25)
    text = export.render_prometheus()
    assert "# TYPE srml_ops_test_requests counter" in text
    assert 'srml_ops_test_requests{rank="0"} 3' in text
    assert "# TYPE srml_ops_test_util gauge" in text
    assert "# TYPE srml_ops_test_lat_s summary" in text
    assert 'srml_ops_test_lat_s{rank="0",quantile="0.99"} 0.25' in text
    assert 'srml_ops_test_lat_s_count{rank="0"} 1' in text


def test_http_surface_and_healthz_flip(tele, slo_cfg):
    host, port = export.start_server(0)
    try:
        body = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5
        ).read().decode()
        assert body.startswith("# TYPE") or body == "\n"
        # healthy: 200
        core.config["slo"] = [_latency_spec(fast_burn=1.0)]
        resp = urllib.request.urlopen(f"http://{host}:{port}/healthz", timeout=5)
        assert resp.status == 200
        # violate the SLO: the NEXT scrape must be 503 (fresh evaluation)
        tele.observe("ops_test.lat_s", 1.0)
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(f"http://{host}:{port}/healthz", timeout=5)
        assert exc_info.value.code == 503
        verdict = json.loads(exc_info.value.read())
        assert verdict["failing"] == ["test_lat"]
        snap = json.loads(
            urllib.request.urlopen(
                f"http://{host}:{port}/snapshot", timeout=5
            ).read()
        )
        assert set(snap) >= {"health", "slo", "windows", "decisions", "tenants"}
        with pytest.raises(urllib.error.HTTPError) as nf:
            urllib.request.urlopen(f"http://{host}:{port}/nope", timeout=5)
        assert nf.value.code == 404
    finally:
        export.stop_server()


def test_snapshot_rotation(tele, tmp_path):
    path = str(tmp_path / "ops_snapshot.json")
    for _ in range(4):
        assert export.write_snapshot(path, keep=2) == path
    assert (tmp_path / "ops_snapshot.json").exists()
    assert (tmp_path / "ops_snapshot.1.json").exists()
    assert (tmp_path / "ops_snapshot.2.json").exists()
    assert not (tmp_path / "ops_snapshot.3.json").exists()  # bounded
    with open(path) as f:
        rep = json.load(f)
    assert "health" in rep and "tenants" in rep


def test_snapshot_skipped_without_dir(tele, monkeypatch):
    monkeypatch.delenv("SRML_OPS_SNAPSHOT_DIR", raising=False)
    saved = core.config["ops_snapshot_dir"]
    core.config["ops_snapshot_dir"] = None
    try:
        assert export.write_snapshot() is None
    finally:
        core.config["ops_snapshot_dir"] = saved


# ------------------------------------------------------------- audit --------


def test_audit_record_and_query(tele):
    audit.record_decision("admission", "fit", "resident", subject="KMeans",
                          tenant="t1", reason="fits")
    audit.record_decision("demotion", "scheduler", "stream", subject="job:1",
                          tenant="t2", reason="preempted twice")
    assert len(audit.decisions()) == 2
    assert [d["tenant"] for d in audit.decisions(tenant="t2")] == ["t2"]
    assert audit.decisions(kind="demotion")[0]["verdict"] == "stream"
    assert audit.decisions(subsystem="fit")[0]["subject"] == "KMeans"
    assert audit.decisions(limit=1)[0]["kind"] == "demotion"  # newest kept
    st = audit.stats()
    assert st["recorded"] == 2 and st["retained"] == 2 and st["dropped"] == 0
    snap = tele.snapshot()
    assert snap["counters"]["ops.decisions_recorded"] == 2.0


def test_audit_records_regardless_of_telemetry():
    telemetry.disable()
    audit.record_decision("admission", "fit", "resident", subject="X")
    assert len(audit.decisions()) == 1  # decisions are robustness state


def test_audit_carries_trace_id(tele):
    from spark_rapids_ml_tpu import diagnostics

    with diagnostics.trace_scope("ops-test"):
        rec = audit.record_decision("admission", "fit", "resident", subject="X")
        tid = rec["trace_id"]
    audit.record_decision("admission", "fit", "resident", subject="Y")
    assert [d["subject"] for d in audit.decisions(trace_id=tid)] == ["X"]


def test_fit_admission_lands_in_audit_trail(tele):
    """E2E: a real fit's admission verdict is queryable from the trail."""
    from spark_rapids_ml_tpu.models.clustering import KMeans

    rng = np.random.default_rng(0)
    df = {"features": rng.standard_normal((200, 4)).astype(np.float32)}
    est = KMeans(k=2, maxIter=2, seed=1)
    est.num_workers = 1
    est.fit(df)
    recs = audit.decisions(kind="admission", subsystem="fit")
    assert recs and recs[-1]["verdict"] == "resident"
    assert recs[-1]["tenant"] == "default"
    assert recs[-1]["trace_id"]  # fits run inside trace_scope


# -------------------------------------------------- tenant accounting -------


def test_ledger_tenant_byte_seconds_integration(tele):
    from spark_rapids_ml_tpu.scheduler.ledger import HbmLedger

    led = HbmLedger()
    t0 = time.monotonic()
    r = led.reserve("fit:X", "fit", 1000, tenant="t1", chips=4)
    time.sleep(0.05)
    led.resize(r, 2000)
    time.sleep(0.05)
    led.release(r)
    elapsed = time.monotonic() - t0
    led.release(r)  # idempotent: no double accounting
    u = led.tenant_usage()["t1"]
    # interval 1 charged at 1000B, interval 2 at the resized 2000B
    assert 0.05 * (1000 + 2000) * 0.8 < u["byte_seconds"] <= elapsed * 2000
    assert 4 * 0.1 * 0.8 < u["chip_seconds"] <= 4 * elapsed
    assert u["reservations"] == 1
    assert "live_bytes" not in u  # released
    # a second tenant_usage() call does not re-accrue the released claim
    assert led.tenant_usage()["t1"]["byte_seconds"] == u["byte_seconds"]


def test_scheduler_job_and_fit_admission_charge_same_chips(tele):
    """The chip-seconds multiplier must agree across admission paths: a
    scheduler job's ledger claim carries the mesh width its preflight
    estimated (not the default 1), and a fit admission stamps its device
    count on the AdmissionDecision so cache-hit re-reserves charge alike."""
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.scheduler import FitScheduler
    from spark_rapids_ml_tpu.scheduler.ledger import global_ledger

    rng = np.random.default_rng(1)
    df = {"features": rng.standard_normal((400, 4)).astype(np.float32)}
    est = KMeans(k=2, maxIter=2, seed=1)
    est.num_workers = 8
    sched = FitScheduler(max_concurrent=1)
    try:
        job = sched.submit(est, df, tenant="t8")
        model = job.result(timeout=120)
    finally:
        sched.shutdown(wait=True, timeout=30)
    assert job.chips == 8
    # the standalone fit path stamps the same multiplier on its decision
    est2 = KMeans(k=2, maxIter=2, seed=1)
    est2.num_workers = 8
    est2.fit(df)
    assert est2._last_admission.chips == 8
    usage = global_ledger().tenant_usage()
    assert usage["t8"]["chip_seconds"] > 0


def test_ledger_live_claims_integrate_to_now(tele):
    from spark_rapids_ml_tpu.scheduler.ledger import HbmLedger

    led = HbmLedger()
    led.reserve("serve:M", "serve", 500, tenant="serving")
    time.sleep(0.03)
    u1 = led.tenant_usage()["serving"]
    assert u1["live_bytes"] == 500 and u1["live_reservations"] == 1
    assert u1["byte_seconds"] > 0
    time.sleep(0.03)
    u2 = led.tenant_usage()["serving"]
    assert u2["byte_seconds"] > u1["byte_seconds"]  # still accruing


# --------------------------------------------------------------- drift ------


def _extract(x, validate=True):
    from spark_rapids_ml_tpu.data import extract_dataset

    return extract_dataset({"features": x}, input_col="features", validate=validate)


def test_drift_stats_published_from_validation_scan(tele):
    from spark_rapids_ml_tpu.data import validate_extracted

    rng = np.random.default_rng(3)
    x = (rng.standard_normal((500, 3)) * np.array([1.0, 2.0, 3.0])).astype(np.float32)
    ex = _extract(x)
    validate_extracted(ex)
    gauges = tele.snapshot()["gauges"]
    # a single vector-block column publishes per-column-INDEX gauges
    for i in range(3):
        assert gauges[f"ingest.feature.{i}.mean"] == pytest.approx(
            float(x[:, i].mean()), abs=1e-5
        )
        assert gauges[f"ingest.feature.{i}.std"] == pytest.approx(
            float(x[:, i].std()), rel=1e-4
        )
        assert gauges[f"ingest.feature.{i}.null_fraction"] == 0.0


def test_drift_stats_exact_per_column_and_chunked(tele):
    from spark_rapids_ml_tpu.data import validate_extracted

    saved = core.config["ingest_chunk_bytes"]
    core.config["ingest_chunk_bytes"] = 64 * 4  # force many chunks
    try:
        rng = np.random.default_rng(4)
        x = rng.standard_normal((333, 2)).astype(np.float64)
        import pandas as pd

        from spark_rapids_ml_tpu.data import extract_dataset

        df = pd.DataFrame({"a": x[:, 0], "b": x[:, 1]})
        ex = extract_dataset(df, input_cols=["a", "b"], float32_inputs=False)
        validate_extracted(ex)
        stats = drift.last_stats()
        assert stats["rows"] == 333 and stats["columns"] == ["a", "b"]
        np.testing.assert_allclose(stats["mean"], x.mean(axis=0), rtol=1e-9)
        np.testing.assert_allclose(stats["std"], x.std(axis=0), rtol=1e-6)
        assert stats["null_fraction"] == [0.0, 0.0]
        gauges = tele.snapshot()["gauges"]
        assert gauges["ingest.feature.a.mean"] == pytest.approx(x[:, 0].mean())
        assert gauges["ingest.feature.b.std"] == pytest.approx(
            x[:, 1].std(), rel=1e-6
        )
    finally:
        core.config["ingest_chunk_bytes"] = saved


def test_drift_psi_against_registered_baseline(tele):
    from spark_rapids_ml_tpu.data import validate_extracted

    rng = np.random.default_rng(5)
    ref = rng.standard_normal((2000, 2))
    base = drift.build_baseline(_extract(ref))
    drift.register_baseline(base)
    try:
        # same distribution: PSI ~ 0
        same = _extract(rng.standard_normal((2000, 2)))
        validate_extracted(same)
        psi_same = tele.snapshot()["gauges"]["ingest.feature.psi_max"]
        assert psi_same < 0.05
        # shifted distribution: PSI large
        shifted = _extract(rng.standard_normal((2000, 2)) + 3.0)
        validate_extracted(shifted)
        psi_shift = tele.snapshot()["gauges"]["ingest.feature.psi_max"]
        assert psi_shift > 0.5
        assert drift.last_stats()["psi_max"] == pytest.approx(psi_shift)
    finally:
        drift.clear_baseline()


def test_drift_skips_sparse_and_disabled(tele):
    import scipy.sparse as sp

    from spark_rapids_ml_tpu.ops_plane.drift import accumulator_for

    ex = _extract(np.ones((4, 2)))
    ex.features = sp.csr_matrix(ex.features)
    assert accumulator_for(ex) is None
    telemetry.disable()
    ex2 = _extract(np.ones((4, 2)))
    assert accumulator_for(ex2) is None


# ----------------------------------------------- report() + opsreport -------


def test_report_shape_and_filters(tele):
    audit.record_decision("admission", "fit", "resident", subject="A", tenant="t1")
    audit.record_decision("eviction", "serving", "evicted", subject="B",
                          tenant="serving")
    rep = ops_plane.report(tenant="t1")
    assert set(rep) >= {
        "health", "slo", "windows", "decisions", "decision_log", "tenants",
        "drift", "telemetry",
    }
    assert [d["tenant"] for d in rep["decisions"]] == ["t1"]
    json.dumps(rep, default=str)  # JSON-able end to end


def test_opsreport_cli_unreadable_snapshot(tmp_path, capsys):
    from benchmark.opsreport import main

    bad = tmp_path / "nope.json"
    assert main([str(bad)]) == 2
    bad.write_text("{not json")
    assert main([str(bad)]) == 2


# ------------------------------------------- the acceptance scenario --------


def test_chaos_latency_spike_flips_healthz_and_opsreport_names_it(
    tele, slo_cfg, tmp_path, capsys
):
    """The ISSUE acceptance pin: a chaos-injected serving latency spike
    (`delay:stage=serve` plan) flips /healthz to failing via the fast
    burn-rate window within one bucket width, and opsreport — fed the
    on-disk snapshot — names the tenant, the violated SLO, and the
    decision-log entries for that trace. No TPU involved."""
    from spark_rapids_ml_tpu.models.clustering import KMeansModel
    from spark_rapids_ml_tpu.parallel import chaos
    from spark_rapids_ml_tpu.serving import ModelRegistry, ScoringEngine

    rng = np.random.default_rng(7)
    centers = (rng.standard_normal((4, 6)) * 5.0).astype(np.float32)
    model = KMeansModel(cluster_centers_=centers, n_cols=6, dtype="float32")

    saved = {k: core.config[k] for k in ("serve_prewarm_rows", "slo")}
    core.config["serve_prewarm_rows"] = 16
    core.config["slo"] = [{
        "name": "serve_p99", "kind": "latency", "histogram": "serve.e2e_s",
        "threshold_s": 0.05, "objective": 0.9, "fast_burn": 1.0,
    }]
    host, port = export.start_server(0)
    try:
        registry = ModelRegistry()
        registry.load("m", model)
        with ScoringEngine(registry) as engine:
            q = rng.standard_normal((8, 6)).astype(np.float32)
            engine.score("m", q)  # warm, fast request: healthy baseline
            assert urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=5
            ).status == 200
            # inject the spike: every dispatch sleeps 0.2s (>> threshold)
            chaos.set_fault_plan("delay:stage=serve:seconds=0.2:times=4")
            t_spike = time.monotonic()
            for _ in range(4):
                engine.score("m", q, timeout=30)
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(f"http://{host}:{port}/healthz", timeout=5)
            detect_s = time.monotonic() - t_spike - 4 * 0.2
            assert exc_info.value.code == 503
            verdict = json.loads(exc_info.value.read())
            assert verdict["failing"] == ["serve_p99"]
            # detection is scrape-fresh: within ~one bucket width of the
            # spike landing (generous slack for CI scheduling)
            assert detect_s < 2 * core.config["metrics_bucket_seconds"] + 1.0
        # the load's admission decision is in the trail, under the
        # per-model serving tenant "serving:m"
        recs = audit.decisions(tenant="serving:m", subsystem="serving")
        assert recs and recs[0]["verdict"] == "resident"
        trace = recs[0].get("trace_id")
        # archive + render: opsreport names the SLO, the tenant, the entries
        snap_path = str(tmp_path / "ops_snapshot.json")
        assert export.write_snapshot(snap_path) == snap_path
        from benchmark.opsreport import main

        args = [snap_path, "--tenant", "serving:m"]
        if trace:
            args += ["--trace-id", trace]
        rc = main(args)
        out = capsys.readouterr().out
        assert rc == 1  # an SLO is failing
        assert "FAILING" in out and "serve_p99" in out
        assert "tenant=serving:m" in out and "resident" in out
    finally:
        chaos.clear_fault_plan()
        export.stop_server()
        core.config.update(saved)
        registry.clear()


# ------------------------------------------------ stats delegation ----------


def test_engine_and_scheduler_stats_share_the_extraction(tele):
    """The satellite pin: both stats() surfaces read p50/p99 through
    telemetry.summarize_histogram, so seeding the histograms directly is
    visible through BOTH with identical nearest-rank semantics."""
    from spark_rapids_ml_tpu.scheduler import FitScheduler
    from spark_rapids_ml_tpu.serving import ModelRegistry, ScoringEngine

    for v in (0.1, 0.2, 0.3):
        tele.observe("serve.e2e_s", v)
        tele.observe("serve.queue_wait_s", v)
        tele.observe("scheduler.queue_wait_s", v)
    engine = ScoringEngine(ModelRegistry())
    es = engine.stats()
    assert es["e2e_p50_s"] == telemetry.quantile_of([0.1, 0.2, 0.3], 0.5)
    assert es["e2e_p99_s"] == 0.3
    sched = FitScheduler(max_concurrent=1)
    try:
        ss = sched.stats()
        assert ss["queue_wait_p50_s"] == 0.2
        assert ss["queue_wait_p99_s"] == 0.3
        assert ss["tenant_usage"] == {} or isinstance(ss["tenant_usage"], dict)
    finally:
        sched.shutdown(wait=False)
