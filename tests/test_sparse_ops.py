#
# Fast unit parity for the padded-ELL sparse layer (ops/sparse.py) vs scipy —
# the nightly 1e7-scale lane (test_large_sparse.py) certifies scale; this file
# certifies the math across shapes, densities and edge cases.
#
import numpy as np
import pytest
import scipy.sparse as sp

import jax

from spark_rapids_ml_tpu.ops.sparse import (
    csr_to_ell,
    ell_col_moments,
    ell_matmul,
    ell_matvec,
    ell_rmatvec,
)


def _random_csr(rng, n, d, density, dtype=np.float32):
    from tests.sparse_gen import random_csr

    x = random_csr(rng, n, d, density, dtype=dtype, values="normal")
    x.sum_duplicates()
    return x


@pytest.mark.parametrize("n,d,density", [(200, 50, 0.1), (64, 8, 0.5), (500, 300, 0.01)])
def test_ell_roundtrip_and_matmul_parity(rng, n, d, density):
    x = _random_csr(rng, n, d, density)
    indices, values, k_max = csr_to_ell(x)
    assert indices.shape == values.shape == (n, k_max)
    # densified ELL == densified CSR
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (np.arange(n)[:, None].repeat(k_max, 1), indices), values)
    np.testing.assert_allclose(dense, x.toarray(), atol=1e-7)

    B = rng.normal(size=(d, 3)).astype(np.float32)
    got = np.asarray(ell_matmul(jax.device_put(values), jax.device_put(indices), jax.device_put(B)))
    np.testing.assert_allclose(got, x.toarray() @ B, rtol=1e-4, atol=1e-4)

    b = B[:, 0]
    got_v = np.asarray(ell_matvec(jax.device_put(values), jax.device_put(indices), jax.device_put(b)))
    np.testing.assert_allclose(got_v, x.toarray() @ b, rtol=1e-4, atol=1e-4)

    r = rng.normal(size=n).astype(np.float32)
    got_r = np.asarray(ell_rmatvec(jax.device_put(values), jax.device_put(indices), jax.device_put(r), d))
    np.testing.assert_allclose(got_r, x.toarray().T @ r, rtol=1e-4, atol=1e-4)


def test_ell_col_moments_match_dense(rng):
    x = _random_csr(rng, 300, 40, 0.15, dtype=np.float64)
    w = rng.random(300)
    indices, values, _ = csr_to_ell(x, dtype=np.float64)
    tw, mean, var = ell_col_moments(
        jax.device_put(values), jax.device_put(indices), jax.device_put(w), 40
    )
    dense = x.toarray()
    np.testing.assert_allclose(float(tw), w.sum(), rtol=1e-12)
    want_mean = (dense * w[:, None]).sum(0) / w.sum()
    want_var = (dense**2 * w[:, None]).sum(0) / w.sum() - want_mean**2
    np.testing.assert_allclose(np.asarray(mean), want_mean, rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(np.asarray(var), want_var, rtol=1e-9, atol=1e-12)


def test_ell_edge_cases(rng):
    # all-empty rows
    x = sp.csr_matrix((5, 7), dtype=np.float32)
    indices, values, k_max = csr_to_ell(x)
    assert k_max == 1 and not values.any()
    got = np.asarray(ell_matmul(values, indices, np.ones((7, 2), np.float32)))
    np.testing.assert_array_equal(got, np.zeros((5, 2)))

    # explicit k_max padding (the SPMD rendezvous-agreed width)
    x2 = _random_csr(rng, 30, 10, 0.3)
    i2, v2, km = csr_to_ell(x2, k_max=9)
    assert km == 9 and i2.shape == (30, 9)
    B = rng.normal(size=(10, 2)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ell_matmul(v2, i2, B)), x2.toarray() @ B, rtol=1e-4, atol=1e-5
    )

    # k_max smaller than the widest row must raise
    wide = sp.csr_matrix(np.ones((2, 6), np.float32))
    with pytest.raises(ValueError, match="k_max"):
        csr_to_ell(wide, k_max=3)

    # zero-row matrix
    empty = sp.csr_matrix((0, 4), dtype=np.float32)
    ie, ve, ke = csr_to_ell(empty)
    assert ie.shape == (0, max(ke, 1))
