#
# Exact + approximate kNN tests (reference tests/test_nearest_neighbors.py and
# test_approximate_nearest_neighbors.py pattern).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.models.knn import (
    ApproximateNearestNeighbors,
    NearestNeighbors,
)


def _item_query(rng, n_items=64, n_queries=10, d=4):
    items = rng.normal(size=(n_items, d))
    queries = rng.normal(size=(n_queries, d))
    item_df = pd.DataFrame({"features": list(items), "id": np.arange(n_items, dtype=np.int64)})
    query_df = pd.DataFrame({"features": list(queries), "id": np.arange(n_queries, dtype=np.int64) + 1000})
    return item_df, query_df, items, queries


def _sk_knn(items, queries, k):
    from sklearn.neighbors import NearestNeighbors as SkNN

    nn = SkNN(n_neighbors=k).fit(items)
    dist, idx = nn.kneighbors(queries)
    return dist, idx


def test_exact_knn_matches_sklearn(rng):
    item_df, query_df, items, queries = _item_query(rng)
    model = NearestNeighbors(k=4).setInputCol("features").setIdCol("id").fit(item_df)
    _, _, knn_df = model.kneighbors(query_df)
    sk_dist, sk_idx = _sk_knn(items, queries, 4)
    ours_idx = np.stack(knn_df["indices"].to_list())
    ours_dist = np.stack(knn_df["distances"].to_list())
    np.testing.assert_allclose(ours_dist, sk_dist, rtol=1e-5, atol=1e-8)
    np.testing.assert_array_equal(ours_idx, sk_idx)


def test_exact_knn_k_exceeds_per_shard_rows(rng):
    # 16 items spread over the 8-device mesh = 2 rows per shard; k=5 is valid
    # (k <= total rows) and must not crash the per-shard top-k
    item_df, query_df, items, queries = _item_query(rng, n_items=16, n_queries=4)
    model = NearestNeighbors(k=5).setInputCol("features").setIdCol("id").fit(item_df)
    _, _, knn_df = model.kneighbors(query_df)
    sk_dist, sk_idx = _sk_knn(items, queries, 5)
    np.testing.assert_allclose(np.stack(knn_df["distances"].to_list()), sk_dist, rtol=1e-5, atol=1e-8)
    np.testing.assert_array_equal(np.stack(knn_df["indices"].to_list()), sk_idx)


def test_exact_knn_k_exceeds_total_rows_raises(rng):
    item_df, query_df, *_ = _item_query(rng, n_items=8)
    model = NearestNeighbors(k=9).setInputCol("features").setIdCol("id").fit(item_df)
    with pytest.raises(ValueError, match="exceeds"):
        model.kneighbors(query_df)


def test_exact_join_row_count(rng):
    item_df, query_df, *_ = _item_query(rng, n_items=32, n_queries=6)
    model = NearestNeighbors(k=3).setInputCol("features").setIdCol("id").fit(item_df)
    out = model.exactNearestNeighborsJoin(query_df)
    assert len(out) == 6 * 3
    assert "distCol" in out.columns
    assert "item_id" in out.columns and "query_id" in out.columns


def test_ann_ivfflat_recall(rng):
    item_df, query_df, items, queries = _item_query(rng, n_items=512, n_queries=32, d=8)
    ann = (
        ApproximateNearestNeighbors(k=8, algoParams={"nlist": 16, "nprobe": 16})
        .setInputCol("features")
        .setIdCol("id")
    )
    model = ann.fit(item_df)
    _, _, knn_df = model.kneighbors(query_df)
    _, sk_idx = _sk_knn(items, queries, 8)
    ours = np.stack(knn_df["indices"].to_list())
    # probing ALL lists -> exact search: recall must be 1
    recall = np.mean([len(set(a) & set(b)) / 8.0 for a, b in zip(ours, sk_idx)])
    assert recall == 1.0


def test_ann_join_skips_padded_ids(rng):
    # tiny buckets + 1 probe: some queries see < k candidates, producing -1
    # padded ids that the join must silently drop (not KeyError)
    item_df, query_df, *_ = _item_query(rng, n_items=20, n_queries=5, d=3)
    ann = (
        ApproximateNearestNeighbors(k=10, algoParams={"nlist": 10, "nprobe": 1})
        .setInputCol("features")
        .setIdCol("id")
    )
    model = ann.fit(item_df)
    _, _, knn_df = model.kneighbors(query_df)
    indices = np.stack(knn_df["indices"].to_list())
    assert (indices == -1).any(), "test setup should produce under-filled results"
    out = model.approxSimilarityJoin(query_df)
    assert (out["item_id"] != -1).all()
    assert np.isfinite(out["distCol"]).all()


def test_ivfpq_recall_and_estimator(rng):
    # IVFPQ with generous probes on clustered data: decent recall, and the
    # estimator surface maps cuML algoParams keys {M, n_bits}
    import pandas as pd

    from sklearn.datasets import make_blobs

    from spark_rapids_ml_tpu.models.knn import ApproximateNearestNeighbors

    x, _ = make_blobs(n_samples=2000, n_features=32, centers=20, random_state=4)
    x = x.astype(np.float64)
    df = pd.DataFrame({"features": list(x)})
    ann = (
        ApproximateNearestNeighbors(
            k=8, algorithm="ivfpq",
            algoParams={"nlist": 32, "nprobe": 8, "M": 8, "n_bits": 6},
        )
        .setInputCol("features")
        .fit(df)
    )
    assert ann._solver_params["pq_m"] == 8 and ann._solver_params["pq_n_bits"] == 6
    _, _, knn_df = ann.kneighbors(df.iloc[:200])
    got = np.stack(knn_df["indices"].to_numpy())

    from spark_rapids_ml_tpu.models.knn import NearestNeighbors

    exact = NearestNeighbors(k=8).setInputCol("features").fit(df)
    _, _, exact_df = exact.kneighbors(df.iloc[:200])
    ref = np.stack(exact_df["indices"].to_numpy())
    recall = np.mean([len(set(got[i]) & set(ref[i])) / 8 for i in range(200)])
    assert recall > 0.6, f"ivfpq recall {recall}"


def test_ivfpq_rejects_bad_m(rng):
    import pandas as pd

    from spark_rapids_ml_tpu.models.knn import ApproximateNearestNeighbors

    x = rng.normal(size=(100, 10))
    df = pd.DataFrame({"features": list(x)})
    with pytest.raises(ValueError, match="M"):
        ApproximateNearestNeighbors(
            k=3, algorithm="ivfpq", algoParams={"M": 3}
        ).setInputCol("features").fit(df)


def test_exact_knn_1dev_matches_sharded(rng):
    # the single-device host-tiled path must equal the sharded path exactly
    import jax

    from spark_rapids_ml_tpu.ops.knn import exact_knn
    from spark_rapids_ml_tpu.parallel import get_mesh, make_global_rows

    items = rng.normal(size=(500, 16)).astype(np.float32)
    queries = rng.normal(size=(73, 16)).astype(np.float32)
    mesh8 = get_mesh(8)
    X8, w8, _ = make_global_rows(mesh8, items)
    d8, i8 = exact_knn(X8, w8 > 0, jax.device_put(queries), mesh=mesh8, k=7, batch_queries=32)
    mesh1 = get_mesh(1)
    X1, w1, _ = make_global_rows(mesh1, items)
    d1, i1 = exact_knn(X1, w1 > 0, jax.device_put(queries), mesh=mesh1, k=7, batch_queries=32)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i8))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d8), rtol=1e-6, atol=1e-6)


def test_sparse_knn_matches_dense(rng):
    # CSR item set searched via tile-densify must equal the dense result
    import scipy.sparse as sp

    from spark_rapids_ml_tpu.models.knn import NearestNeighbors

    x = sp.random(400, 24, density=0.2, random_state=np.random.RandomState(5), format="csr")
    # sp.random leaves ~0.8^24 of rows all-zero -> exactly equidistant ties
    # with order ambiguity; a distinct last column makes every distance unique
    x = sp.hstack([x[:, :-1], sp.csr_matrix(np.arange(400)[:, None] * 1e-3)]).tocsr()
    xd = np.asarray(x.todense())
    rows = [
        __import__("spark_rapids_ml_tpu.linalg", fromlist=["Vectors"]).Vectors.sparse(
            24, x[i].indices.tolist(), x[i].data.tolist()
        )
        for i in range(400)
    ]
    import pandas as pd

    df_sp = pd.DataFrame({"features": rows})
    df_dn = pd.DataFrame({"features": list(xd)})
    q = df_dn.iloc[:37]

    m_sp = NearestNeighbors(k=5, float32_inputs=False).setInputCol("features").fit(df_sp)
    m_dn = NearestNeighbors(k=5, float32_inputs=False).setInputCol("features").fit(df_dn)
    _, _, knn_sp = m_sp.kneighbors(q)
    _, _, knn_dn = m_dn.kneighbors(q)
    np.testing.assert_array_equal(
        np.stack(knn_sp["indices"].to_numpy()), np.stack(knn_dn["indices"].to_numpy())
    )
    np.testing.assert_allclose(
        np.stack(knn_sp["distances"].to_numpy()),
        np.stack(knn_dn["distances"].to_numpy()),
        rtol=1e-5, atol=1e-6,
    )
    # tiling invariance: tiny tiles give the same answer (f64 like the models
    # above — f32 rounding can flip near-tie orderings)
    from spark_rapids_ml_tpu.ops.knn import exact_knn_sparse

    d_t, i_t = exact_knn_sparse(x.astype(np.float64), xd[:37].astype(np.float64), 5, batch_items=64)
    np.testing.assert_array_equal(i_t, np.stack(knn_dn["indices"].to_numpy()))


def test_knn_empty_query_frames(rng):
    # 0-row query frames return empty results on both backends (the 1-device
    # host-tiled path used to raise range(..., 0))
    import jax

    from spark_rapids_ml_tpu.ops.knn import exact_knn, exact_knn_sparse
    from spark_rapids_ml_tpu.parallel import get_mesh, make_global_rows

    items = rng.normal(size=(100, 8)).astype(np.float32)
    empty_q = np.zeros((0, 8), np.float32)
    mesh1 = get_mesh(1)
    X, w, _ = make_global_rows(mesh1, items)
    d, i = exact_knn(X, w > 0, jax.device_put(empty_q), mesh=mesh1, k=3)
    assert np.asarray(d).shape == (0, 3) and np.asarray(i).shape == (0, 3)

    import scipy.sparse as sp

    xs = sp.csr_matrix(items)
    d, i = exact_knn_sparse(xs, empty_q, 3)
    assert d.shape == (0, 3) and i.shape == (0, 3)


def test_knn_empty_query_model_join(rng):
    # model-level: a 0-row query frame flows through kneighbors AND the
    # exploded join with the same schema as the non-empty path
    import pandas as pd

    from spark_rapids_ml_tpu.models.knn import NearestNeighbors

    items = rng.normal(size=(60, 8)).astype(np.float32)
    df = pd.DataFrame({"features": list(items)})
    nn = NearestNeighbors(k=3).setInputCol("features").fit(df)
    empty_q = pd.DataFrame({"features": list(items[:0])})

    _, query_out, knn_df = nn.kneighbors(empty_q)
    assert len(query_out) == 0 and len(knn_df) == 0
    assert list(knn_df.columns) == ["query_id", "indices", "distances"]

    joined = nn.exactNearestNeighborsJoin(pd.DataFrame({"features": list(items[:5])}))
    joined0 = nn.exactNearestNeighborsJoin(empty_q)
    assert len(joined0) == 0
    assert list(joined0.columns) == list(joined.columns)


def test_cagra_early_exit_triggers(rng, monkeypatch):
    # with an absurd threshold every round is "converged": the update-rate
    # early exit must cut the descent far short of the 14-round random-init max
    import spark_rapids_ml_tpu.ops.cagra as cg

    calls = []
    orig = cg._descent_round

    def spy(*a, **k):
        out = orig(*a, **k)
        calls.append(int(out[2]))
        return out

    monkeypatch.setattr(cg, "_descent_round", spy)
    x = rng.normal(size=(600, 8)).astype(np.float32)
    idx = cg.build_cagra(x, build_algo="nn_descent", termination_threshold=1.0, seed=0)
    assert len(calls) < 14, calls
    assert np.asarray(idx["graph"]).shape[0] == 600

    # threshold 0 (never converged by the bar): runs the full schedule
    calls.clear()
    cg.build_cagra(x, build_algo="nn_descent", termination_threshold=0.0, seed=0,
                   nn_descent_niter=5)
    assert len(calls) == 5


def test_ann_set_algo_params_replace_semantics():
    # reference setAlgoParams REPLACES the param dict: keys a previous call
    # set must revert to defaults, not linger across config sweeps
    est = ApproximateNearestNeighbors(algoParams={"nlist": 32, "nprobe": 16})
    assert est.solver_params["n_lists"] == 32 and est.solver_params["n_probes"] == 16
    est.setAlgoParams({"nprobe": 4})
    assert est.solver_params["n_probes"] == 4
    assert est.solver_params["n_lists"] == 64  # back to the default
    est.setAlgoParams({})
    assert est.solver_params["n_probes"] == 8  # all defaults restored


def test_ann_metric_sqeuclidean_and_cosine(rng):
    # reference ANN metric surface (knn.py:845-888): sqeuclidean = squared
    # euclidean outputs; cosine = unit-normalized index/query with cosine
    # distances, recall checked against sklearn's cosine kNN
    from sklearn.neighbors import NearestNeighbors as SkNN

    item_df, query_df, items, queries = _item_query(rng, n_items=500, n_queries=30, d=12)
    base = (
        ApproximateNearestNeighbors(k=6, algoParams={"nlist": 8, "nprobe": 8})
        .setInputCol("features").setIdCol("id")
    )
    _, _, knn_eu = base.fit(item_df).kneighbors(query_df)

    sq = base.copy().setMetric("sqeuclidean")
    assert sq.getMetric() == "sqeuclidean"
    _, _, knn_sq = sq.fit(item_df).kneighbors(query_df)
    d_eu = np.stack(knn_eu["distances"].to_list())
    d_sq = np.stack(knn_sq["distances"].to_list())
    np.testing.assert_allclose(d_sq, d_eu**2, rtol=1e-5)
    np.testing.assert_array_equal(
        np.stack(knn_eu["indices"].to_list()), np.stack(knn_sq["indices"].to_list())
    )

    cos = (
        ApproximateNearestNeighbors(k=6, metric="cosine", algoParams={"nlist": 8, "nprobe": 8})
        .setInputCol("features").setIdCol("id")
    )
    _, _, knn_cos = cos.fit(item_df).kneighbors(query_df)
    ours = np.stack(knn_cos["indices"].to_list())
    d_cos = np.stack(knn_cos["distances"].to_list())
    sk = SkNN(n_neighbors=6, metric="cosine").fit(items)
    sk_dist, sk_idx = sk.kneighbors(queries)
    recall = np.mean([len(set(a) & set(b)) / 6.0 for a, b in zip(ours, sk_idx)])
    assert recall > 0.95, recall  # nprobe == nlist: exhaustive search
    # cosine distances in the metric's own scale (1 - cos)
    np.testing.assert_allclose(np.sort(d_cos[:, 0]), np.sort(sk_dist[:, 0]), atol=1e-5)

    # the reference's cagra path REQUIRES metric="sqeuclidean"
    # (knn.py:1267) — that exact configuration must work here too
    cg = (
        ApproximateNearestNeighbors(
            k=6, algorithm="cagra", metric="sqeuclidean",
            algoParams={"build_algo": "nn_descent", "itopk_size": 64},
        )
        .setInputCol("features").setIdCol("id")
    )
    _, _, knn_cg = cg.fit(item_df).kneighbors(query_df)
    d_cg = np.stack(knn_cg["distances"].to_list())
    i_cg = np.stack(knn_cg["indices"].to_list())
    # squared-euclidean outputs: nearest distances match sklearn's squared
    sk_eu = SkNN(n_neighbors=6).fit(items)
    skd, _ = sk_eu.kneighbors(queries)
    np.testing.assert_allclose(d_cg[:, 0], skd[:, 0] ** 2, rtol=1e-3, atol=1e-4)
    assert (np.diff(d_cg, axis=1) >= -1e-5).all()

    with pytest.raises(ValueError, match="metric"):
        ApproximateNearestNeighbors(metric="manhattan")


def test_cagra_recall_and_estimator(rng):
    # CAGRA graph ANN (reference knn.py:902-935, 1452-1481): NN-descent build
    # + greedy graph search must recover most true neighbors
    item_df, query_df, items, queries = _item_query(rng, n_items=800, n_queries=40, d=16)
    ann = (
        ApproximateNearestNeighbors(
            k=8,
            algorithm="cagra",
            algoParams={
                "build_algo": "nn_descent",
                "graph_degree": 32,
                "intermediate_graph_degree": 48,
                "itopk_size": 64,
            },
        )
        .setInputCol("features")
        .setIdCol("id")
    )
    model = ann.fit(item_df)
    _, _, knn_df = model.kneighbors(query_df)
    _, sk_idx = _sk_knn(items, queries, 8)
    ours = np.stack(knn_df["indices"].to_list())
    dist = np.stack(knn_df["distances"].to_list())
    recall = np.mean([len(set(a) & set(b)) / 8.0 for a, b in zip(ours, sk_idx)])
    assert recall >= 0.85, recall
    # euclidean distances, ascending per row
    assert (np.diff(dist, axis=1) >= -1e-6).all()
    sk_dist, _ = _sk_knn(items, queries, 8)
    # the nearest neighbor found must score its TRUE euclidean distance
    assert np.all(dist[:, 0] >= sk_dist[:, 0] - 1e-5)


def test_cagra_ivfpq_seeded_build(rng):
    # default build_algo="ivf_pq" seeds NN-descent from coarse-quantizer lists
    item_df, query_df, items, queries = _item_query(rng, n_items=600, n_queries=25, d=8)
    ann = (
        ApproximateNearestNeighbors(k=5, algorithm="cagra")
        .setInputCol("features")
        .setIdCol("id")
    )
    model = ann.fit(item_df)
    _, _, knn_df = model.kneighbors(query_df)
    _, sk_idx = _sk_knn(items, queries, 5)
    ours = np.stack(knn_df["indices"].to_list())
    recall = np.mean([len(set(a) & set(b)) / 5.0 for a, b in zip(ours, sk_idx)])
    assert recall >= 0.85, recall


def test_cagra_param_validation(rng):
    # itopk_size is rounded up to a multiple of 32 and must cover k
    # (reference knn.py:1286-1297)
    item_df, *_ = _item_query(rng, n_items=100, n_queries=4, d=4)
    ann = (
        ApproximateNearestNeighbors(
            k=40, algorithm="cagra", algoParams={"itopk_size": 1}
        )
        .setInputCol("features")
        .setIdCol("id")
    )
    with pytest.raises(ValueError, match="itopk_size"):
        ann.fit(item_df)
    # itopk 33 -> internal 64 >= k=40: accepted
    ApproximateNearestNeighbors(
        k=40, algorithm="cagra", algoParams={"itopk_size": 33}
    ).setInputCol("features").setIdCol("id").fit(item_df)
    with pytest.raises(ValueError, match="compression"):
        ApproximateNearestNeighbors(
            k=4, algorithm="cagra", algoParams={"compression": {}}
        )
    with pytest.raises(ValueError, match="not supported"):
        ApproximateNearestNeighbors(k=4, algorithm="hnsw")
    with pytest.raises(ValueError, match="build_algo"):
        ApproximateNearestNeighbors(
            k=4, algorithm="cagra", algoParams={"build_algo": "bogus"}
        ).setInputCol("features").setIdCol("id").fit(item_df)
