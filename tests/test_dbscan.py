#
# DBSCAN tests vs sklearn (reference tests/test_dbscan.py pattern).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.models.clustering import DBSCAN, DBSCANModel


def _df(x):
    return pd.DataFrame({"features": list(x.astype(np.float64))})


def _sk_labels(x, eps, min_samples, metric="euclidean"):
    from sklearn.cluster import DBSCAN as SkDBSCAN

    return SkDBSCAN(eps=eps, min_samples=min_samples, metric=metric).fit(x)


def _assert_equivalent(got, sk_labels):
    """Clustering equality up to the only legitimate freedom DBSCAN has: an
    ambiguous border point (within eps of cores from 2+ clusters) may go to
    either cluster — sklearn/cuML assign by scan/BFS order, this implementation
    by minimum core label. Noise mask and partition structure must still match
    exactly (ARI == 1 requires every point, border included, to agree modulo
    label permutation; ambiguous borders are the only allowed disagreement)."""
    from sklearn.metrics import adjusted_rand_score

    got = np.asarray(got)
    sk_labels = np.asarray(sk_labels)
    np.testing.assert_array_equal(got == -1, sk_labels == -1)
    assert adjusted_rand_score(got, sk_labels) == pytest.approx(1.0)


def test_dbscan_blobs_exact_sklearn(rng):
    from sklearn.datasets import make_blobs

    x, _ = make_blobs(n_samples=500, centers=4, cluster_std=0.5, random_state=3)
    model = DBSCAN(eps=0.8, min_samples=5).setFeaturesCol("features").fit(_df(x))
    out = model.transform(_df(x))
    sk = _sk_labels(x, 0.8, 5)
    _assert_equivalent(out["prediction"].to_numpy(), sk.labels_)
    np.testing.assert_array_equal(
        np.sort(model.core_sample_indices_), np.sort(sk.core_sample_indices_)
    )


def test_dbscan_precomputed_metric(rng):
    # metric="precomputed" (reference parity: cuML supports it): the features
    # rows are the [n, n] distance matrix; must equal both the sklearn
    # precomputed run and this implementation's own euclidean run
    from scipy.spatial.distance import cdist
    from sklearn.datasets import make_blobs

    x, _ = make_blobs(n_samples=300, centers=3, cluster_std=0.6, random_state=5)
    D = cdist(x, x)
    model = (
        DBSCAN(eps=0.8, min_samples=5, metric="precomputed")
        .setFeaturesCol("features")
        .fit(_df(D))
    )
    got = model.transform(_df(D))["prediction"].to_numpy()
    _assert_equivalent(got, _sk_labels(D, 0.8, 5, metric="precomputed").labels_)

    own = (
        DBSCAN(eps=0.8, min_samples=5).setFeaturesCol("features").fit(_df(x))
        .transform(_df(x))["prediction"].to_numpy()
    )
    np.testing.assert_array_equal(got, own)

    # non-square matrix must raise
    with pytest.raises(ValueError, match="square"):
        DBSCAN(eps=0.5, min_samples=3, metric="precomputed").setFeaturesCol(
            "features"
        ).fit(_df(D[:, :10])).transform(_df(D[:, :10]))


def test_dbscan_moons_and_noise(rng):
    from sklearn.datasets import make_moons

    x, _ = make_moons(n_samples=400, noise=0.05, random_state=1)
    model = DBSCAN(eps=0.15, min_samples=5).setFeaturesCol("features").fit(_df(x))
    out = model.transform(_df(x))
    sk = _sk_labels(x, 0.15, 5)
    _assert_equivalent(out["prediction"].to_numpy(), sk.labels_)

    # uniform noise: mostly -1 labels, still exact
    xn = rng.uniform(-5, 5, size=(300, 2))
    m2 = DBSCAN(eps=0.3, min_samples=4).setFeaturesCol("features").fit(_df(xn))
    sk2 = _sk_labels(xn, 0.3, 4)
    _assert_equivalent(m2.transform(_df(xn))["prediction"].to_numpy(), sk2.labels_)
    assert (sk2.labels_ == -1).any()  # the scenario actually has noise points


def test_dbscan_border_points():
    # handmade chain: two dense cores + one border point reachable from a core,
    # one point out of reach (noise)
    x = np.array(
        [[0.0, 0], [0.1, 0], [0.2, 0], [0.3, 0],   # cluster 0 (core at 0.1/0.2)
         [0.95, 0],                                  # border of cluster 0? no: out of eps
         [5.0, 0], [5.1, 0], [5.2, 0], [5.3, 0],   # cluster 1
         [5.75, 0],                                  # border: within eps of 5.3
         [9.0, 0]]                                   # noise
    )
    model = DBSCAN(eps=0.5, min_samples=3).setFeaturesCol("features").fit(_df(x))
    out = model.transform(_df(x))["prediction"].to_numpy()
    sk = _sk_labels(x, 0.5, 3)
    np.testing.assert_array_equal(out, sk.labels_)
    assert out[-1] == -1


def test_dbscan_cosine_metric(rng):
    # rays from origin: cosine clusters by direction regardless of magnitude
    angles = np.concatenate([rng.normal(0.0, 0.05, 40), rng.normal(1.5, 0.05, 40)])
    r = rng.uniform(0.5, 3.0, 80)
    x = np.stack([r * np.cos(angles), r * np.sin(angles)], axis=1)
    model = DBSCAN(eps=0.02, min_samples=4, metric="cosine").setFeaturesCol("features").fit(_df(x))
    out = model.transform(_df(x))["prediction"].to_numpy()
    sk = _sk_labels(x, 0.02, 4, metric="cosine")
    _assert_equivalent(out, sk.labels_)
    assert out.max() == 1  # two directional clusters


def test_dbscan_max_mbytes_tiling_invariance(rng):
    from sklearn.datasets import make_blobs

    x, _ = make_blobs(n_samples=300, centers=3, cluster_std=0.6, random_state=7)
    base = DBSCAN(eps=0.9, min_samples=5).setFeaturesCol("features").fit(_df(x)).transform(_df(x))
    tiny = (
        DBSCAN(eps=0.9, min_samples=5, max_mbytes_per_batch=1)
        .setFeaturesCol("features")
        .fit(_df(x))
        .transform(_df(x))
    )
    np.testing.assert_array_equal(base["prediction"].to_numpy(), tiny["prediction"].to_numpy())


def test_dbscan_all_noise_and_single_cluster(rng):
    x = rng.uniform(-100, 100, size=(50, 3))  # far apart: all noise
    out = DBSCAN(eps=0.1, min_samples=3).setFeaturesCol("features").fit(_df(x)).transform(_df(x))
    assert (out["prediction"].to_numpy() == -1).all()

    x2 = rng.normal(size=(60, 3)) * 0.01  # one tight ball
    out2 = DBSCAN(eps=0.5, min_samples=3).setFeaturesCol("features").fit(_df(x2)).transform(_df(x2))
    assert (out2["prediction"].to_numpy() == 0).all()


def test_dbscan_param_validation():
    DBSCAN(metric="precomputed")  # supported (see test_dbscan_precomputed_metric)
    with pytest.raises(ValueError, match="metric"):
        DBSCAN(metric="manhattan")
    with pytest.raises(ValueError, match="algorithm"):
        DBSCAN(algorithm="kdtree")
    d = DBSCAN(eps=0.25, min_samples=7)
    assert d.getEps() == 0.25
    assert d.getMinSamples() == 7
    assert d.solver_params["eps"] == 0.25
    assert d.setAlgorithm("rbc").getAlgorithm() == "rbc"
    assert d.setCalcCoreSampleIndices(False).getCalcCoreSampleIndices() is False


def test_dbscan_fit_is_noop_and_persistence(tmp_path, rng):
    x = rng.normal(size=(40, 2))
    est = DBSCAN(eps=0.7, min_samples=4).setFeaturesCol("features")
    model = est.fit(_df(x))  # must not touch the data distribution-wise
    p = str(tmp_path / "dbscan")
    model.write().overwrite().save(p)
    loaded = DBSCANModel.load(p)
    assert loaded.getEps() == 0.7
    assert loaded.getMinSamples() == 4
    np.testing.assert_array_equal(
        loaded.transform(_df(x))["prediction"].to_numpy(),
        model.transform(_df(x))["prediction"].to_numpy(),
    )


def test_dbscan_prediction_col_name(rng):
    x = rng.normal(size=(30, 2))
    model = (
        DBSCAN(eps=0.5, min_samples=3)
        .setFeaturesCol("features")
        .setPredictionCol("cluster")
        .fit(_df(x))
    )
    out = model.transform(_df(x))
    assert "cluster" in out.columns


def test_dbscan_fit_multiple_param_maps(rng):
    x = rng.normal(size=(40, 2))
    est = DBSCAN(eps=0.5, min_samples=3).setFeaturesCol("features")
    grid = [{est.getParam("eps"): 0.3}, {est.getParam("eps"): 0.8}]
    models = est.fit(_df(x), grid)
    assert len(models) == 2
    assert models[0].getEps() == 0.3 and models[1].getEps() == 0.8
    assert models[0].solver_params["eps"] == 0.3


def test_dbscan_ambiguous_border_tiebreak():
    # a border point exactly within eps of core points from TWO clusters: the
    # sklearn-exact contract does not cover it (assignment is scan-order there);
    # this implementation deterministically adopts the minimum core label
    x = np.array(
        [[0.0, 0.0], [0.1, 0.0], [0.2, 0.0],   # cluster A (tight: all cores)
         [1.0, 0.0],                             # border: d=0.8 to A's 0.2 and to B's 1.8
         [1.8, 0.0], [1.9, 0.0], [2.0, 0.0]]    # cluster B (tight: all cores)
    )
    # min_samples=4: each tight triple + the border point = 4 neighbors, so the
    # triples are cores; the border point itself has only 3 (itself + one core
    # from each side) -> genuinely a non-core, ambiguously-reachable border
    model = DBSCAN(eps=0.85, min_samples=4).setFeaturesCol("features").fit(_df(x))
    out = model.transform(_df(x))["prediction"].to_numpy()
    sk = _sk_labels(x, 0.85, 4)
    # confirm the geometry really is ambiguous: point 3 is a border (non-core)
    # point and the two sides are distinct clusters
    assert 3 not in set(sk.core_sample_indices_.tolist())
    assert sk.labels_[0] != sk.labels_[4]
    _assert_equivalent(np.delete(out, 3), np.delete(sk.labels_, 3))
    assert out[3] in (0, 1) and sk.labels_[3] in (0, 1)
    assert out[3] == 0  # min-core-label tie-break is deterministic
