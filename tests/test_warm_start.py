#
# Public warm-start API (`estimator.fit(..., warm_start_from=)`,
# docs/scheduling.md "Warm starts"): the PR-6 portable checkpoint subset —
# what preempted/recovered fits resume from — exposed as a fit seed. Pins:
# iterate ADOPTION (the donor's iterate demonstrably enters the solver: a
# warm fit converges in strictly fewer iterations than a cold one, and a
# near-converged donor leaves almost nothing to do), the iterations-saved
# counter, SolverCheckpoint donors, and the typed mismatch/unsupported
# refusals.
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import checkpoint as ckpt
from spark_rapids_ml_tpu import telemetry
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.clustering import KMeans
from spark_rapids_ml_tpu.models.regression import LinearRegression


@pytest.fixture(autouse=True)
def _tele():
    telemetry.enable()
    telemetry.registry().reset()
    yield
    telemetry.disable()


def _counters():
    return telemetry.registry().snapshot()["counters"]


def _blob_df(rng, n=600, d=5):
    x = rng.normal(size=(n, d)).astype(np.float32)
    return pd.DataFrame({"features": list(x)}), x


def _cls_df(rng, n=800, d=6):
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return pd.DataFrame({"features": list(x), "label": y}), x, y


# ------------------------------------------------------------ kmeans ---------


def test_kmeans_warm_start_from_model_adopts_iterate(rng):
    df, _ = _blob_df(rng)

    def make():
        return KMeans(k=6, maxIter=30, tol=1e-6, seed=3)

    cold = make().fit(df)
    assert cold.n_iter_ > 2  # the cold fit actually iterated
    warm = make().fit(df, warm_start_from=cold)
    # seeding from the converged iterate restarts AT the fixpoint: Lloyd
    # re-confirms convergence in a couple of iterations, not a re-run
    assert warm.n_iter_ < cold.n_iter_
    assert warm.n_iter_ <= 3
    np.testing.assert_allclose(
        np.asarray(warm.cluster_centers_), np.asarray(cold.cluster_centers_),
        rtol=1e-5,
    )
    snap = _counters()
    assert snap["fit.warm_starts"] == 1
    # the donor's already-paid iterations land in the saved counter
    assert snap["fit.warm_start_iterations_saved"] == cold.n_iter_


def test_kmeans_warm_start_from_solver_checkpoint(rng):
    df, x = _blob_df(rng)
    donor = KMeans(k=6, maxIter=25, tol=1e-7, seed=3).fit(df)
    # the PR-6 portable subset: a SolverCheckpoint carrying centers
    snap = ckpt.SolverCheckpoint(
        solver="kmeans",
        iteration=int(donor.n_iter_),
        state={"centers": np.asarray(donor.cluster_centers_)},
    )
    warm = KMeans(k=6, maxIter=25, tol=1e-7, seed=3).fit(df, warm_start_from=snap)
    assert warm.n_iter_ < donor.n_iter_
    assert _counters()["fit.warm_start_iterations_saved"] == donor.n_iter_


def test_kmeans_warm_start_shape_mismatch_raises(rng):
    df, _ = _blob_df(rng)
    donor = KMeans(k=6, maxIter=5, seed=3).fit(df)
    with pytest.raises(ValueError, match="warm-start centers shape"):
        KMeans(k=8, maxIter=5, seed=3).fit(df, warm_start_from=donor)


def test_kmeans_warm_start_wrong_donor_type_raises(rng):
    df, _ = _blob_df(rng)
    with pytest.raises(TypeError, match="cannot warm-start KMeans"):
        KMeans(k=4).fit(df, warm_start_from=object())


# ---------------------------------------------------------- logistic ---------


def test_logistic_warm_start_from_model_adopts_iterate(rng):
    df, x, y = _cls_df(rng)

    def make():
        est = LogisticRegression(maxIter=50, regParam=1e-3)
        est.num_workers = 1
        return est

    cold = make().fit(df)
    assert cold.n_iter_ > 3
    warm = make().fit(df, warm_start_from=cold)
    # the solver restarts AT the converged standardized iterate (the exact
    # inverse of its own fold-out) — convergence re-confirms immediately
    assert warm.n_iter_ < cold.n_iter_
    assert warm.n_iter_ <= 3
    # the warm fit may take 1-2 polishing steps past the donor's stop point
    # (the donor stopped at rel-tol, not at a true stationary point) — same
    # model to ~1e-2, not bitwise
    np.testing.assert_allclose(
        np.asarray(warm.coef_), np.asarray(cold.coef_), rtol=2e-2, atol=1e-4
    )
    snap = _counters()
    assert snap["fit.warm_starts"] == 1
    assert snap["fit.warm_start_iterations_saved"] == cold.n_iter_


def test_logistic_elasticnet_warm_start_owlqn_path(rng):
    # the OWL-QN (L1) solver takes the same seed through its own x0
    df, _, _ = _cls_df(rng)

    def make():
        est = LogisticRegression(maxIter=40, regParam=0.05, elasticNetParam=0.5)
        est.num_workers = 1
        return est

    cold = make().fit(df)
    warm = make().fit(df, warm_start_from=cold)
    assert warm.n_iter_ <= cold.n_iter_
    assert _counters()["fit.warm_starts"] == 1


def test_logistic_warm_start_shape_mismatch_raises(rng):
    df, _, _ = _cls_df(rng, d=6)
    cold = LogisticRegression(maxIter=10).fit(df)
    df2, _, _ = _cls_df(rng, d=4)
    with pytest.raises(ValueError, match="warm-start coef shape"):
        LogisticRegression(maxIter=10).fit(df2, warm_start_from=cold)


def test_logistic_rejects_standardized_checkpoint_with_pointer(rng):
    # GLM segment checkpoints carry the dataset-specific STANDARDIZED
    # iterate: not portable across fits, so the refusal names the model route
    snap = ckpt.SolverCheckpoint(
        solver="glm_qn", iteration=7, state={}, portable={"x": np.zeros(7)}
    )
    df, _, _ = _cls_df(rng)
    with pytest.raises(ValueError, match="warm-start from the fitted model"):
        LogisticRegression(maxIter=10).fit(df, warm_start_from=snap)


# ------------------------------------------------------------ surface --------


def test_closed_form_estimator_refuses_warm_start(rng):
    df, _, _ = _cls_df(rng)
    with pytest.raises(NotImplementedError, match="does not support warm_start_from"):
        LinearRegression().fit(df, warm_start_from=object())


def test_warm_start_with_param_map_list_refuses(rng):
    df, _ = _blob_df(rng)
    donor = KMeans(k=4, maxIter=5, seed=3).fit(df)
    with pytest.raises(ValueError, match="single-fit seed"):
        KMeans(k=4).fit(df, [{}, {}], warm_start_from=donor)


def test_warm_start_state_cleared_after_fit(rng):
    # the seed applies to ONE fit call — the next fit cold-starts
    df, _ = _blob_df(rng)
    est = KMeans(k=6, maxIter=30, tol=1e-6, seed=3)
    donor = est.fit(df)
    est2 = KMeans(k=6, maxIter=30, tol=1e-6, seed=3)
    warm = est2.fit(df, warm_start_from=donor)
    assert est2._warm_start is None
    again = est2.fit(df)  # no seed: the full init + Lloyd run repeats
    assert again.n_iter_ == donor.n_iter_
    assert warm.n_iter_ < again.n_iter_


def test_warm_start_through_scheduler_submit(rng):
    # the scheduler's submit(..., warm_start_from=) hands the seed to the
    # job's fit — continuous retrains ride the queue warm
    from spark_rapids_ml_tpu.scheduler import FitScheduler

    df, _ = _blob_df(rng)
    donor = KMeans(k=6, maxIter=30, tol=1e-6, seed=3).fit(df)
    sched = FitScheduler()
    try:
        est = KMeans(k=6, maxIter=30, tol=1e-6, seed=3)
        est.num_workers = 1
        job = sched.submit(est, df, tenant="retrain", warm_start_from=donor)
        model = job.result(timeout=120)
    finally:
        sched.shutdown()
    assert model.n_iter_ < donor.n_iter_
    assert _counters()["fit.warm_starts"] == 1
