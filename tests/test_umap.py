#
# UMAP tests (reference tests/test_umap.py pattern): embedding quality via
# trustworthiness, supervised fit, transform consistency, persistence.
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.models.umap import UMAP, UMAPModel


def _blobs(n=600, d=10, k=5, seed=0):
    from sklearn.datasets import make_blobs

    x, y = make_blobs(n_samples=n, centers=k, n_features=d, cluster_std=1.0, random_state=seed)
    return x.astype(np.float64), y


def _df(x, y=None):
    d = {"features": list(x)}
    if y is not None:
        d["label"] = y.astype(np.float64)
    return pd.DataFrame(d)


def test_umap_fit_quality_trustworthiness():
    from sklearn.manifold import trustworthiness

    x, y = _blobs()
    model = UMAP(n_components=2, random_state=42).setFeaturesCol("features").fit(_df(x))
    emb = np.asarray(model.embedding_)
    assert emb.shape == (600, 2)
    tw = trustworthiness(x, emb, n_neighbors=15)
    assert tw > 0.90, tw


def test_umap_precomputed_knn_matches_builtin():
    # the reference's precomputed_knn param (umap.py -> cuML). Handing the fit
    # the IDENTICAL graph it would have built must reproduce the embedding
    # exactly (the kNN stage is skipped, everything downstream is seeded);
    # an sklearn-built exact graph (f64 vs f32 distance ties) must still give
    # an embedding of the same quality.
    from sklearn.manifold import trustworthiness
    from sklearn.neighbors import NearestNeighbors as SkNN

    from spark_rapids_ml_tpu.ops.umap import build_knn_graph
    from spark_rapids_ml_tpu.parallel import get_mesh
    from spark_rapids_ml_tpu.parallel.mesh import dtype_scope

    x, _ = _blobs(n=300)
    base = UMAP(n_components=2, random_state=7).setFeaturesCol("features").fit(_df(x))

    # same precision scope AND mesh as the fit (tie order is mesh-dependent)
    with dtype_scope(np.float32):
        idx, dist = build_knn_graph(x.astype(np.float32), 15, get_mesh())
    pre = (
        UMAP(n_components=2, random_state=7, precomputed_knn=(idx, dist))
        .setFeaturesCol("features")
        .fit(_df(x))
    )
    np.testing.assert_allclose(
        np.asarray(pre.embedding_), np.asarray(base.embedding_), rtol=1e-5, atol=1e-5
    )

    sk_dist, sk_idx = SkNN(n_neighbors=15).fit(x).kneighbors(x)  # self in col 0
    pre_sk = (
        UMAP(n_components=2, random_state=7, precomputed_knn=(sk_idx, sk_dist))
        .setFeaturesCol("features")
        .fit(_df(x))
    )
    assert trustworthiness(x, np.asarray(pre_sk.embedding_), n_neighbors=15) > 0.90

    # WIDE + SELF-EXCLUDED pair (the advertised [n, >=k] contract): the k-1
    # nearest non-self entries must survive normalization — regression for a
    # swap-then-truncate bug that dropped every row's nearest neighbor
    n = len(x)
    rng2 = np.random.default_rng(0)
    far_idx = rng2.integers(0, n, size=(n, 10))
    wide_idx = np.concatenate([idx[:, 1:], far_idx], axis=1)  # no self column
    wide_dist = np.concatenate([dist[:, 1:], np.full((n, 10), 1e6, np.float32)], axis=1)
    pre_wide = (
        UMAP(n_components=2, random_state=7, precomputed_knn=(wide_idx, wide_dist))
        .setFeaturesCol("features")
        .fit(_df(x))
    )
    np.testing.assert_allclose(
        np.asarray(pre_wide.embedding_), np.asarray(base.embedding_), rtol=1e-5, atol=1e-5
    )


def test_umap_cosine_metric():
    # angular clusters with wildly varying radii: cosine separates them,
    # euclidean mixes them (radius dominates) — the metric must reach the
    # graph stage, and transform must follow the same convention
    from sklearn.manifold import trustworthiness
    from sklearn.metrics import silhouette_score

    rng = np.random.default_rng(3)
    k_dirs = 4
    dirs = rng.normal(size=(k_dirs, 16))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    lab = rng.integers(0, k_dirs, size=400)
    radii = rng.uniform(0.1, 100.0, size=400)[:, None]
    x = dirs[lab] * radii + 0.01 * rng.normal(size=(400, 16))

    model = (
        UMAP(n_components=2, metric="cosine", random_state=4)
        .setFeaturesCol("features")
        .fit(_df(x))
    )
    emb = np.asarray(model.embedding_)
    assert silhouette_score(emb, lab) > 0.5
    assert trustworthiness(x, emb, n_neighbors=15, metric="cosine") > 0.9

    out = model.transform(_df(x[:50]))
    emb_new = np.stack(out[model.getOutputCol()].to_list())
    assert emb_new.shape == (50, 2) and np.isfinite(emb_new).all()

    # persistence must carry the metric: a reloaded model transforms with the
    # same cosine convention (bit-equal to the in-memory transform)
    import tempfile

    p = tempfile.mkdtemp() + "/umap_cos"
    model.write().overwrite().save(p)
    loaded = UMAPModel.load(p)
    assert str(loaded._solver_params["metric"]) == "cosine"
    out2 = loaded.transform(_df(x[:50]))
    np.testing.assert_allclose(
        np.stack(out2[loaded.getOutputCol()].to_list()), emb_new, rtol=1e-6, atol=1e-7
    )

    with pytest.raises(ValueError, match="metric"):
        UMAP(metric="manhattan")


def test_umap_precomputed_knn_validation():
    x, _ = _blobs(n=100)
    with pytest.raises(ValueError, match="pair"):
        UMAP(precomputed_knn=np.zeros((100, 15)))
    bad = (np.zeros((50, 15), np.int64), np.zeros((50, 15)))
    with pytest.raises(ValueError, match="precomputed_knn"):
        UMAP(precomputed_knn=bad).setFeaturesCol("features").fit(_df(x))
    good = (np.zeros((100, 15), np.int64), np.zeros((100, 15)))
    with pytest.raises(ValueError, match="sample_fraction"):
        UMAP(precomputed_knn=good, sample_fraction=0.5).setFeaturesCol("features").fit(_df(x))


def test_umap_separates_blobs():
    from sklearn.metrics import silhouette_score

    x, y = _blobs()
    model = UMAP(n_components=2, random_state=1).setFeaturesCol("features").fit(_df(x))
    score = silhouette_score(model.embedding_, y)
    assert score > 0.7, score  # well-separated blobs stay separated


def test_umap_transform_matches_fit_points():
    x, y = _blobs(n=400)
    model = UMAP(n_components=2, random_state=7).setFeaturesCol("features").fit(_df(x))
    out = model.transform(_df(x[:80] + 0.01))
    assert model.getOutputCol() in out.columns and "features" in out.columns
    emb_new = np.stack(out[model.getOutputCol()].to_list())
    # near-duplicates of training points must land near their trained embedding
    d = np.linalg.norm(emb_new - model.embedding_[:80], axis=1)
    scale = np.abs(model.embedding_).max()
    assert np.median(d) < 0.15 * scale, (np.median(d), scale)


def test_umap_supervised_improves_separation():
    from sklearn.metrics import silhouette_score

    # genuinely overlapping clusters (std comparable to center spread, so the
    # kNN graph has cross-label edges): labels must pull classes apart
    from sklearn.datasets import make_blobs

    x, y = make_blobs(
        n_samples=500, centers=3, n_features=8, cluster_std=6.0, random_state=5
    )
    x = x.astype(np.float64)
    un = UMAP(n_components=2, random_state=3).setFeaturesCol("features").fit(_df(x))
    sup = (
        UMAP(n_components=2, random_state=3)
        .setFeaturesCol("features")
        .setLabelCol("label")
        .fit(_df(x, y))
    )
    s_un = silhouette_score(un.embedding_, y)
    s_sup = silhouette_score(sup.embedding_, y)
    assert s_sup > s_un, (s_sup, s_un)


def test_umap_random_init_and_epochs():
    x, _ = _blobs(n=200)
    m = (
        UMAP(n_components=2, init="random", n_epochs=50, random_state=0)
        .setFeaturesCol("features")
        .fit(_df(x))
    )
    assert np.isfinite(m.embedding_).all()


def test_umap_sample_fraction():
    x, _ = _blobs(n=400)
    m = (
        UMAP(n_components=2, sample_fraction=0.5, random_state=0)
        .setFeaturesCol("features")
        .fit(_df(x))
    )
    assert 100 < m.embedding_.shape[0] < 300  # ~200 rows kept
    assert m.raw_data_.shape[0] == m.embedding_.shape[0]


def test_umap_persistence_npy_sidecar(tmp_path):
    x, _ = _blobs(n=150)
    model = UMAP(n_components=2, random_state=11).setFeaturesCol("features").fit(_df(x))
    p = str(tmp_path / "umap")
    model.write().overwrite().save(p)
    import os

    assert os.path.exists(os.path.join(p, "data", "embedding_.npy"))
    assert os.path.exists(os.path.join(p, "data", "raw_data_.npy"))
    loaded = UMAPModel.load(p)
    np.testing.assert_array_equal(loaded.embedding_, model.embedding_)
    np.testing.assert_array_equal(loaded.raw_data_, model.raw_data_)
    assert loaded.a_ == model.a_ and loaded.b_ == model.b_
    out1 = model.transform(_df(x[:20]))
    out2 = loaded.transform(_df(x[:20]))
    np.testing.assert_allclose(
        np.stack(out1[model.getOutputCol()].to_list()),
        np.stack(out2[model.getOutputCol()].to_list()),
        rtol=1e-6,
    )


def test_umap_param_surface_and_validation():
    u = UMAP(n_neighbors=10, min_dist=0.25, spread=2.0)
    assert u.getNNeighbors() == 10
    assert u.getMinDist() == 0.25
    assert u.solver_params["min_dist"] == 0.25
    u.setNComponents(3)
    assert u.getNComponents() == 3
    with pytest.raises(ValueError, match="metric"):
        UMAP(metric="manhattan")
    with pytest.raises(ValueError, match="init"):
        UMAP(init="pca")
    with pytest.raises(ValueError, match="precomputed_knn"):
        UMAP(precomputed_knn=[[0, 1]])


def test_umap_smooth_knn_hits_target():
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.umap import smooth_knn

    rng = np.random.default_rng(0)
    d = np.sort(rng.uniform(0.1, 2.0, size=(50, 15)), axis=1)
    d[:, 0] = 0.0  # self
    rho, sigma = smooth_knn(jnp.asarray(d.astype(np.float32)))
    psum = np.sum(np.exp(-np.maximum(d - np.asarray(rho)[:, None], 0) / np.asarray(sigma)[:, None]), axis=1)
    np.testing.assert_allclose(psum, np.log2(15), rtol=1e-3)


def test_umap_find_ab_params():
    from spark_rapids_ml_tpu.ops.umap import find_ab_params

    a, b = find_ab_params(1.0, 0.1)
    # umap-learn's canonical values for spread=1, min_dist=0.1
    assert abs(a - 1.577) < 0.05 and abs(b - 0.895) < 0.02
