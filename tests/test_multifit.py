#
# Multi-fit execution engine tests (docs/performance.md "Multi-fit engine"):
# DeviceDataset reuse across fits, CrossValidator weight-masked folds
# (one ingest + one layout per CV fit, fold metrics bit-identical to a
# physical split), batched hyperparameter sweeps vs sequential solves, the
# transform bucket ladder (one predict program per bucket, never per tail
# shape), and the zero-row multi-output transform fix.
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import core, telemetry
from spark_rapids_ml_tpu.evaluation import (
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.linalg import SparseVector
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.regression import LinearRegression
from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder


@pytest.fixture
def tele():
    """Enable telemetry with a fresh registry; restore after."""
    telemetry.registry().reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.registry().reset()


def _reg_df(rng, n=200, d=5):
    x = rng.normal(size=(n, d))
    coef = np.array([1.0, -2.0, 0.0, 0.0, 3.0])
    y = x @ coef + 0.5 + 0.2 * rng.normal(size=n)
    return pd.DataFrame({"features": list(x), "label": y})


def _cls_df(rng, n=200, d=4, sparse=False):
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    if sparse:
        x = np.where(np.abs(x) > 0.8, x, 0.0)  # sparsify but keep signal
        rows = [
            SparseVector(d, np.nonzero(r)[0].astype(np.int32), r[np.nonzero(r)[0]])
            for r in x
        ]
        return pd.DataFrame({"features": rows, "label": y})
    return pd.DataFrame({"features": list(x), "label": y})


# ------------------------------------------------------------ DeviceDataset --


def test_device_dataset_scope_single_ingest(tele, rng):
    df = _reg_df(rng)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    with core.device_dataset_scope():
        m1 = lr.fit(df)
        m2 = lr.copy({lr.getParam("regParam"): 0.5}).fit(df)
    snap = telemetry.snapshot()
    assert snap["counters"]["ingest.datasets"] == 1
    assert snap["counters"]["fit.device_dataset_builds"] == 1
    assert snap["counters"]["fit.device_dataset_reuses"] == 1
    assert snap["spans"]["fit/ingest"]["count"] == 1
    assert snap["spans"]["fit/layout"]["count"] == 1
    # the reused placement still produces the right models
    assert not np.allclose(m1.coef_, m2.coef_)  # different regParam really fit
    # outside a scope, every fit ingests
    lr.fit(df)
    assert telemetry.snapshot()["counters"]["ingest.datasets"] == 2


def test_device_dataset_no_stale_reuse_after_gc(tele, rng):
    # the cache key is id()-based: every entry must PIN its source object,
    # or a gc'd dataset's recycled id on a new same-shaped object would be a
    # silent false hit (model trained on the WRONG data)
    import gc

    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    with core.device_dataset_scope():
        m1 = lr.fit(_reg_df(rng))  # temporary df: unreferenced after the call
        gc.collect()
        m2 = lr.fit(_reg_df(rng))  # same shape/columns, DIFFERENT data
    snap = telemetry.snapshot()
    assert snap["counters"]["fit.device_dataset_builds"] == 2
    assert "fit.device_dataset_reuses" not in snap["counters"]
    assert not np.allclose(m1.coef_, m2.coef_)  # really fit on the new draw


def test_device_dataset_scope_distinct_datasets(tele, rng):
    df1, df2 = _reg_df(rng), _reg_df(rng, n=100)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    with core.device_dataset_scope():
        lr.fit(df1)
        lr.fit(df2)  # different object/shape: its own placement
    snap = telemetry.snapshot()
    assert snap["counters"]["ingest.datasets"] == 2
    assert snap["counters"]["fit.device_dataset_builds"] == 2
    assert "fit.device_dataset_reuses" not in snap["counters"]


def test_device_dataset_scope_bounded_lru(tele, rng):
    # a scope around a loop over FRESH dataset objects must not stack HBM
    # placements: retention is bounded by config["device_dataset_cache_entries"]
    dfs = [_reg_df(rng, n=60 + i) for i in range(3)]
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    old = core.config["device_dataset_cache_entries"]
    core.config["device_dataset_cache_entries"] = 2
    try:
        with core.device_dataset_scope() as scope:
            for df in dfs:
                lr.fit(df)
            assert len(scope.cache) == 2  # oldest evicted
            lr.fit(dfs[2])  # newest still cached
            snap = telemetry.snapshot()
            assert snap["counters"]["fit.device_dataset_builds"] == 3
            assert snap["counters"]["fit.device_dataset_evictions"] == 1
            assert snap["counters"]["fit.device_dataset_reuses"] == 1
            lr.fit(dfs[0])  # evicted: must re-ingest, never stale-hit
            assert telemetry.snapshot()["counters"]["fit.device_dataset_builds"] == 4
    finally:
        core.config["device_dataset_cache_entries"] = old


# ------------------------------------------- CV: one placement, every fit --


def test_cv_telemetry_one_ingest_one_layout(tele, rng):
    # ISSUE acceptance: a numFolds=3 x 4-param-map CrossValidator fit
    # performs exactly 1 ingest and 1 layout (vs numFolds before), with the
    # whole grid dispatched as batched solves per fold + 1 sequential refit
    df = _reg_df(rng, n=240)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(
        lr.getParam("regParam"), [0.0, 0.01, 0.1, 1.0]
    ).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(metricName="rmse"), numFolds=3, seed=1,
    )
    cv.fit(df)
    snap = telemetry.snapshot()
    assert snap["counters"]["ingest.datasets"] == 1
    assert snap["spans"]["fit/ingest"]["count"] == 1
    assert snap["spans"]["fit/layout"]["count"] == 1
    assert snap["counters"]["fit.device_dataset_builds"] == 1
    assert snap["counters"]["fit.device_dataset_reuses"] == 3  # 2 folds + refit
    assert snap["counters"]["fit.solves_batched"] == 12  # 3 folds x 4 maps
    assert snap["counters"]["fit.solves_sequential"] == 1  # best-model refit


@pytest.mark.parametrize("sparse", [False, True], ids=["dense", "csr"])
def test_cv_fold_metrics_bit_identical_logistic(rng, sparse):
    _fold_bit_identity_check(
        _cls_df(rng, n=180, sparse=sparse),
        LogisticRegression(
            maxIter=40, float32_inputs=False,
            **({"enable_sparse_data_optim": True} if sparse else {}),
        ).setFeaturesCol("features"),
        MulticlassClassificationEvaluator(metricName="accuracy"),
        [0.01, 0.1],
    )


@pytest.mark.parametrize("sparse", [False, True], ids=["dense", "csr"])
def test_cv_fold_metrics_bit_identical_linear(rng, sparse):
    df = _reg_df(rng, n=180)
    if sparse:
        x = np.stack(df["features"].to_numpy())
        x = np.where(np.abs(x) > 0.5, x, 0.0)
        d = x.shape[1]
        df = pd.DataFrame({
            "features": [
                SparseVector(d, np.nonzero(r)[0].astype(np.int32), r[np.nonzero(r)[0]])
                for r in x
            ],
            "label": df["label"],
        })
    _fold_bit_identity_check(
        df,
        LinearRegression(
            float32_inputs=False,
            **({"enable_sparse_data_optim": True} if sparse else {}),
        ).setFeaturesCol("features"),
        RegressionEvaluator(metricName="rmse"),
        [0.0, 0.1],
    )


def _fold_bit_identity_check(df, est, eva, reg_grid):
    """The engine's weight-masked fold fits vs a PHYSICAL representation of
    the same split: the fold mask written into the dataset as an explicit
    weight column (the framework's documented padding semantics — w == 0
    rows are absent from the objective) and fitted through the ordinary
    per-fold fitMultiple path with its own ingest. Same rows, same layout,
    same programs => fold metrics must be BIT-identical. A second check
    compares against the literal row-subset fit (different reduction
    groupings, so exact-arithmetic equality only): tight allclose."""
    grid = ParamGridBuilder().addGrid(est.getParam("regParam"), reg_grid).build()
    num_folds = 2
    cv = CrossValidator(
        estimator=est, estimatorParamMaps=grid, evaluator=eva,
        numFolds=num_folds, seed=5,
    )
    engine_avg = np.asarray(cv.fit(df).avgMetrics)

    n = len(df)
    folds = cv._kfold_indices(n, df)
    feats_full = est._pre_process_data(df, for_fit=False).features
    labels = df["label"].to_numpy(dtype=np.float64)

    baseline = np.zeros((num_folds, len(grid)))
    subset = np.zeros_like(baseline)
    for f, (train_idx, valid_idx) in enumerate(folds):
        mask = np.zeros(n)
        mask[train_idx] = 1.0
        df_w = df.copy()
        df_w["w_"] = mask
        est_w = est.copy()._set_params(weightCol="w_")
        models = [m for _, m in sorted(est_w.fitMultiple(df_w, grid))]
        combined = models[0]._combine(models)
        baseline[f] = combined._transform_evaluate_arrays(
            feats_full[valid_idx], labels[valid_idx], eva
        )
        # literal physical split (row subset, its own layout): exact math,
        # different float reduction groupings
        train = df.iloc[train_idx].reset_index(drop=True)
        sub_models = [m for _, m in sorted(est.fitMultiple(train, grid))]
        sub_combined = sub_models[0]._combine(sub_models)
        subset[f] = sub_combined._transform_evaluate_arrays(
            feats_full[valid_idx], labels[valid_idx], eva
        )
    np.testing.assert_array_equal(engine_avg, baseline.mean(axis=0))
    np.testing.assert_allclose(engine_avg, subset.mean(axis=0), rtol=1e-6, atol=1e-9)


def test_sparse_cv_converts_and_places_ell_once(tele, rng):
    # the sparse half of the one-placement contract: a CV grid over CSR data
    # converts CSR->ELL and places the ELL tensors ONCE (FitInputs.ell_rows
    # is memoized across fold masks and solves), not once per solve
    df = _cls_df(rng, n=120, sparse=True)
    lr = LogisticRegression(
        maxIter=10, float32_inputs=False, enable_sparse_data_optim=True
    ).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 0.1]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=2, seed=2,
    )
    cv.fit(df)
    snap = telemetry.snapshot()
    assert snap["counters"]["ingest.datasets"] == 1
    assert snap["counters"]["sparse.csr_to_ell_calls"] == 1


def test_cv_masked_fold_respects_train_classes(rng):
    # a fold whose TRAIN rows miss a class must behave like the physical
    # split (class discovery honors the mask, not the full dataset)
    n = 30
    x = rng.normal(size=(n, 3))
    y = np.zeros(n)
    y[-3:] = 1.0  # the rare class sits in 3 rows
    df = pd.DataFrame({"features": list(x), "label": y, "fold": [0] * (n - 3) + [1] * 3})
    lr = LogisticRegression(maxIter=10, float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=2, foldCol="fold",
    )
    m = cv.fit(df)  # fold 1 trains on class-0 rows only: degenerate fit path
    assert np.isfinite(m.avgMetrics[0])


# ----------------------------------------------------------- batched sweeps --


def test_batched_sweep_matches_sequential_logistic(rng):
    df = _cls_df(rng, n=150)
    lr = LogisticRegression(maxIter=40, float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(
        lr.getParam("regParam"), [1e-4, 1e-2, 1.0]
    ).build()
    swept = [m for _, m in sorted(lr.fitMultiple(df, grid))]  # batched dispatch
    for pm, m_b in zip(grid, swept):
        m_s = lr.copy(pm).fit(df)  # single fit: sequential solver
        np.testing.assert_allclose(m_b.coef_, m_s.coef_, rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(m_b.intercept_, m_s.intercept_, rtol=1e-9, atol=1e-12)
        assert m_b.n_iter_ == m_s.n_iter_  # frozen loops: same trajectory


def test_batched_sweep_groups_by_program_structure(tele, rng):
    # use_l1 is a STATIC of the traced program: a grid mixing L1-on/off
    # splits into one batched solve per side; a maxIter grid (program
    # structure) falls back to sequential solves entirely
    df = _cls_df(rng, n=120)
    lr = LogisticRegression(maxIter=30, float32_inputs=False).setFeaturesCol("features")
    grid = (
        ParamGridBuilder()
        .addGrid(lr.getParam("regParam"), [0.01, 0.1])
        .addGrid(lr.getParam("elasticNetParam"), [0.0, 0.5])
        .build()
    )
    swept = [m for _, m in sorted(lr.fitMultiple(df, grid))]
    snap = telemetry.snapshot()
    assert snap["counters"]["fit.solves_batched"] == 4  # 2 groups of 2
    assert "fit.solves_sequential" not in snap["counters"]
    for pm, m_b in zip(grid, swept):
        m_s = lr.copy(pm).fit(df)
        np.testing.assert_allclose(m_b.coef_, m_s.coef_, rtol=1e-8, atol=1e-10)

    telemetry.registry().reset()
    grid_iter = ParamGridBuilder().addGrid(lr.getParam("maxIter"), [5, 10]).build()
    list(lr.fitMultiple(df, grid_iter))
    snap = telemetry.snapshot()
    assert snap["counters"]["fit.solves_sequential"] == 2
    assert "fit.solves_batched" not in snap["counters"]


def test_batched_sweep_matches_sequential_linear_cd(rng):
    df = _reg_df(rng, n=150)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = (
        ParamGridBuilder()
        .addGrid(lr.getParam("regParam"), [0.01, 0.1, 1.0])
        .addGrid(lr.getParam("elasticNetParam"), [0.5])
        .build()
    )
    swept = [m for _, m in sorted(lr.fitMultiple(df, grid))]
    for pm, m_b in zip(grid, swept):
        m_s = lr.copy(pm).fit(df)
        np.testing.assert_allclose(m_b.coef_, m_s.coef_, rtol=1e-10, atol=1e-13)
        assert m_b.n_iter_ == m_s.n_iter_


# --------------------------------------------------------- bucketed serving --


def test_transform_bucket_ladder_compiles_per_bucket(tele, rng):
    from spark_rapids_ml_tpu.ops.linear import linear_predict

    df = _reg_df(rng, n=64, d=5)
    model = LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    old_min = core.config["transform_bucket_min_rows"]
    core.config["transform_bucket_min_rows"] = 8
    try:
        cache_before = (
            linear_predict._cache_size() if hasattr(linear_predict, "_cache_size") else None
        )
        programs_before = telemetry.snapshot()["counters"].get("transform.bucket_programs", 0)
        sizes = [1, 2, 3, 5, 7, 8, 9, 11, 13, 17, 19, 23, 29, 31, 33, 40, 47, 55, 63]
        for n in sizes:
            out = model._transform_arrays(rng.normal(size=(n, 5)))
            assert out.shape == (n,)  # outputs sliced back to the valid rows
        new_programs = (
            telemetry.snapshot()["counters"].get("transform.bucket_programs", 0)
            - programs_before
        )
        # 19 distinct batch sizes, ladder rungs 8/16/32/64 only
        assert new_programs <= 4, f"expected <=4 bucket programs, saw {new_programs}"
        if cache_before is not None:
            compiled = linear_predict._cache_size() - cache_before
            assert compiled <= 4, f"predict compiled {compiled} times for 19 shapes"
    finally:
        core.config["transform_bucket_min_rows"] = old_min


def test_transform_bucket_values_unchanged(rng):
    # bucket padding must not leak into valid rows' outputs
    df = _reg_df(rng, n=50, d=5)
    model = LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    x = rng.normal(size=(37, 5))
    expect = x @ model.coef_ + model.intercept_
    np.testing.assert_allclose(model._transform_arrays(x), expect, rtol=1e-12)


# --------------------------------------------------- zero-row transform fix --


def test_transform_zero_rows_multi_output(rng):
    # ISSUE satellite: a zero-row block through a MULTI-output predict must
    # yield one correctly-shaped empty array PER output, not one bare
    # np.zeros((0,)) that _split_output would mis-map across columns
    df = _cls_df(rng, n=80)
    model = LogisticRegression(maxIter=10, float32_inputs=False).setFeaturesCol("features").fit(df)
    out = model._transform_arrays(np.zeros((0, 4)))
    assert isinstance(out, tuple) and len(out) == 2
    raw, prob = out
    assert raw.shape == (0, 2) and prob.shape == (0, 2)
    # and through the full transform surface
    empty = model.transform({"features": np.zeros((0, 4)), "label": np.zeros(0)})
    assert len(empty) == 0
    for col in ("rawPrediction", "probability", "prediction"):
        assert col in empty.columns

    # single-output model: empty 1-D prediction block
    df_r = _reg_df(rng, n=60, d=5)
    lin = LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(df_r)
    out_r = lin._transform_arrays(np.zeros((0, 5)))
    assert out_r.shape == (0,)


# -------------------------------------------------- persistent compile cache --


def test_compile_cache_dir_and_first_solve_gauge(tele, rng, tmp_path):
    import jax

    old = core.config["compilation_cache_dir"]
    core.config["compilation_cache_dir"] = str(tmp_path / "xla_cache")
    try:
        df = _reg_df(rng, n=60)
        LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
        snap = telemetry.snapshot()
        # first-call wall time under the persistent cache is recorded for
        # cross-round cache-efficacy tracking (BENCH JSON)
        assert "fit.compile_cache_hit" in snap["gauges"]
        assert snap["gauges"]["fit.compile_cache_hit"] > 0
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "xla_cache")
    finally:
        core.config["compilation_cache_dir"] = old
        from spark_rapids_ml_tpu.parallel.mesh import ensure_compilation_cache

        ensure_compilation_cache()  # re-point jax at the restored config


def test_compile_probe_guarded_after_batching(tele, rng):
    # identical param maps batch into ONE solve — the compile-overhead probe
    # must not fire on a single solve time (nothing to difference against)
    df = _reg_df(rng, n=80)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.1, 0.1, 0.1]).build()
    list(lr.fitMultiple(df, grid))
    snap = telemetry.snapshot()
    assert snap["counters"]["fit.solves_batched"] == 3
    assert "fit.compile_overhead_s_est" not in snap["gauges"]


# --------------------------------------------- SPMD placement agreement -----
#
# Under multi-process SPMD the DeviceDataset cache-hit branch runs no
# collectives while the miss branch runs the layout allgather — so hit/miss
# must be SYMMETRIC across ranks. `_device_dataset` agrees placement
# fingerprints over ONE rendezvous round (every rank votes its have-bit;
# the cache is used only when ALL ranks hold the entry). These tests drive
# the agreement protocol directly with thread ranks + LocalRendezvous and
# stubbed ingest/layout (real cross-process XLA is exercised by
# tests/sweep_worker.py where the backend supports it).


def _dds_worker(rank, rendezvous, key, steps, counts, errors):
    """One thread-rank running the scripted `_device_dataset` sequence."""
    from types import SimpleNamespace

    from spark_rapids_ml_tpu.models.clustering import KMeans

    try:
        est = KMeans(k=2)
        est._pre_process_data = lambda dataset, **kw: (
            counts[rank].__setitem__("ingest", counts[rank]["ingest"] + 1),
            SimpleNamespace(n_rows=10),
        )[1]

        def _layout(extracted, ctx, stage_logger, force_stream=False,
                    key=None, source=None, attempt=0):
            counts[rank]["layout"] += 1
            return core.DeviceDataset(
                key=key, extracted=extracted, inputs=None, source=source
            )

        est._admit_and_layout = _layout
        est._device_dataset_key = lambda dataset, ctx: key
        ctx = SimpleNamespace(
            is_spmd=True, rank=rank, nranks=2, rendezvous=rendezvous
        )
        with core.device_dataset_scope():
            scope = core._DDS_SCOPE.get()
            for step in steps:
                if step == "fit":
                    est._device_dataset(object(), ctx, None)
                elif step == "evict-rank1":
                    # lockstep mutation: barrier, rank 1 drops its entry,
                    # barrier — so the next fit sees a split cache state
                    rendezvous.allgather("sync-a")
                    if rank == 1:
                        scope.cache.pop(key)
                    rendezvous.allgather("sync-b")
    except BaseException as e:  # surfaced by the parent; threads must not die silently
        errors[rank] = e


def test_spmd_placement_agreement_hits_only_when_all_ranks_have(tele):
    import threading

    from spark_rapids_ml_tpu.parallel import LocalRendezvous

    key = ("fp", ("features", None, None, None, None), ("float32", False), (0, 1))
    rvs = LocalRendezvous.create(2, timeout_s=20.0)
    counts = [
        {"ingest": 0, "layout": 0},
        {"ingest": 0, "layout": 0},
    ]
    errors = [None, None]
    steps = ["fit", "fit", "evict-rank1", "fit"]
    threads = [
        threading.Thread(
            target=_dds_worker, args=(r, rvs[r], key, steps, counts, errors)
        )
        for r in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    # symmetry is the whole point: an asymmetric hit/miss would deadlock one
    # rank in the layout allgather — both threads must come back
    assert not any(t.is_alive() for t in threads)
    assert errors == [None, None]

    # fit 1: both miss -> both ingest + layout and cache the entry
    # fit 2: both have -> pure cache hit, NO ingest/layout anywhere
    # fit 3: rank 1 evicted -> the vote fails, BOTH ranks rebuild together:
    #        rank 0 still holds the exact entry, so it takes the
    #        host-retained path (ingest skipped, layout re-run); rank 1
    #        re-ingests + lays out
    assert counts[0] == {"ingest": 1, "layout": 2}
    assert counts[1] == {"ingest": 2, "layout": 2}
    snap = tele.snapshot()["counters"]
    assert snap["fit.device_dataset_spmd_rounds"] == 6  # 3 fits x 2 ranks
    assert snap["fit.device_dataset_reuses"] == 2  # fit 2 only
    assert snap["fit.device_dataset_builds"] == 3  # fit 1 (x2) + fit 3 rank 1
    assert snap["recovery.replacements"] == 1  # fit 3 rank 0 host-retained


def test_spmd_agreement_skipped_off_spmd(tele, rng):
    # single-process fits must not pay (or count) any rendezvous round
    df = _reg_df(rng)
    lr = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    with core.device_dataset_scope():
        lr.fit(df)
        lr.fit(df)
    snap = tele.snapshot()["counters"]
    assert "fit.device_dataset_spmd_rounds" not in snap
    assert snap["fit.device_dataset_reuses"] == 1
