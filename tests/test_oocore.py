#
# Out-of-core streaming fit tests: the memory-safety acceptance suite
# (docs/robustness.md "Memory safety"). Streaming fits must MATCH resident
# fits to rtol 1e-9 (dense + padded-ELL, all four out-of-core solvers), the
# double-buffer overlap must be telemetry-visible, demotion must be counted
# and stamped, and the whole OOM conversion ladder — injected budget, fake
# RESOURCE_EXHAUSTED at placement/solve, resume-from-checkpoint on the
# streaming path — must end in a completed fit or a typed HbmBudgetError,
# never a raw backend error.
#
import json
import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu import telemetry
from spark_rapids_ml_tpu.errors import HbmBudgetError, IngestValidationError
from spark_rapids_ml_tpu.linalg import SparseVector
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.clustering import KMeans
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.models.regression import LinearRegression
from spark_rapids_ml_tpu.parallel import chaos

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

_MEM_KEYS = (
    "hbm_budget_bytes", "hbm_headroom_fraction", "stream_chunk_rows",
    "checkpoint_every_iters", "validate_ingest",
)


@pytest.fixture
def tele():
    telemetry.enable()
    telemetry.registry().reset()
    saved = {k: core_mod.config[k] for k in _MEM_KEYS}
    yield telemetry
    core_mod.config.update(saved)
    chaos.clear_fault_plan()
    telemetry.disable()
    telemetry.registry().reset()


def _budget(budget, chunk=512):
    core_mod.config["hbm_budget_bytes"] = budget
    core_mod.config["stream_chunk_rows"] = chunk if budget else 0


def _reg_df(rng, n=2000, d=6):
    x = rng.normal(size=(n, d))
    y = x @ rng.normal(size=d) + 0.5 + 0.05 * rng.normal(size=n)
    return pd.DataFrame({"features": list(x), "label": y})


def _cls_df(rng, n=2000, d=6, k=2):
    x = rng.normal(size=(n, d))
    if k == 2:
        y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    else:
        y = rng.integers(0, k, size=n).astype(np.float64)
    return pd.DataFrame({"features": list(x), "label": y})


def _sparse_rows(rng, n=1500, d=20):
    x = rng.normal(size=(n, d))
    x = np.where(np.abs(x) > 1.0, x, 0.0)
    rows = [
        SparseVector(d, np.nonzero(r)[0].astype(np.int32), r[np.nonzero(r)[0]])
        for r in x
    ]
    return x, rows


def _assert_streamed(model, counters):
    adm = model._fit_metrics["admission"]
    assert adm["verdict"] == "stream"
    assert adm["chunk_rows"] >= 1 and adm["reason"]
    assert counters.get("fit.demotions") == 1
    return adm


# ----------------------------------------------------- parity: dense --------


def test_linear_streaming_matches_resident(tele, rng):
    df = _reg_df(rng)
    est = lambda: LinearRegression(regParam=0.001, float32_inputs=False).setFeaturesCol("features")  # noqa: E731
    _budget(None)
    res = est().fit(df)
    tele.registry().reset()
    _budget(12_000)
    stream = est().fit(df)
    snap = tele.snapshot()
    _assert_streamed(stream, snap["counters"])
    np.testing.assert_allclose(stream.coef_, res.coef_, rtol=1e-9)
    np.testing.assert_allclose(stream.intercept_, res.intercept_, rtol=1e-9)
    # the double-buffer overlap acceptance: 2000 rows / 512-row chunks = 4
    # chunks, 3 of which were dispatched during a predecessor's compute
    assert snap["gauges"]["ingest.overlap_fraction"] == pytest.approx(0.75)
    assert snap["counters"]["ingest.stream_chunks"] >= 4


@pytest.mark.parametrize("family_k", [2, 3], ids=["binomial", "multinomial"])
def test_logistic_streaming_matches_resident(tele, rng, family_k):
    df = _cls_df(rng, k=family_k)
    est = lambda: LogisticRegression(regParam=0.01, float32_inputs=False).setFeaturesCol("features")  # noqa: E731
    _budget(None)
    res = est().fit(df)
    tele.registry().reset()
    _budget(12_000)
    stream = est().fit(df)
    _assert_streamed(stream, tele.snapshot()["counters"])
    np.testing.assert_allclose(
        np.asarray(stream.coef_), np.asarray(res.coef_), rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(stream.intercept_), np.asarray(res.intercept_), rtol=1e-9
    )


def test_pca_streaming_matches_resident(tele, rng):
    df = pd.DataFrame({"features": list(rng.normal(size=(2000, 6)))})
    est = lambda: PCA(k=3, float32_inputs=False).setInputCol("features")  # noqa: E731
    _budget(None)
    res = est().fit(df)
    tele.registry().reset()
    _budget(12_000)
    stream = est().fit(df)
    _assert_streamed(stream, tele.snapshot()["counters"])
    np.testing.assert_allclose(
        np.asarray(stream.components_), np.asarray(res.components_), rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(stream.explained_variance_),
        np.asarray(res.explained_variance_),
        rtol=1e-9,
    )


def test_kmeans_streaming_matches_resident(tele, rng):
    df = pd.DataFrame({"features": list(rng.normal(size=(2000, 6)))})
    est = lambda: KMeans(k=4, seed=7, maxIter=15, float32_inputs=False).setFeaturesCol("features")  # noqa: E731
    _budget(None)
    res = est().fit(df)
    tele.registry().reset()
    _budget(16_000)
    stream = est().fit(df)
    _assert_streamed(stream, tele.snapshot()["counters"])
    np.testing.assert_allclose(stream.cluster_centers_, res.cluster_centers_, rtol=1e-9)


# ------------------------------------------------- parity: padded ELL -------


def test_linear_streaming_matches_resident_ell(tele, rng):
    x, rows = _sparse_rows(rng)
    y = x @ rng.normal(size=x.shape[1]) + 0.1 * rng.normal(size=len(x))
    df = pd.DataFrame({"features": rows, "label": y})
    est = lambda: LinearRegression(  # noqa: E731
        regParam=0.001, float32_inputs=False, enable_sparse_data_optim=True
    ).setFeaturesCol("features")
    _budget(None)
    res = est().fit(df)
    tele.registry().reset()
    _budget(30_000)
    stream = est().fit(df)
    _assert_streamed(stream, tele.snapshot()["counters"])
    np.testing.assert_allclose(stream.coef_, res.coef_, rtol=1e-9)
    np.testing.assert_allclose(stream.intercept_, res.intercept_, rtol=1e-9)


def test_logistic_streaming_matches_resident_ell(tele, rng):
    x, rows = _sparse_rows(rng)
    y = (x @ rng.normal(size=x.shape[1]) > 0).astype(np.float64)
    df = pd.DataFrame({"features": rows, "label": y})
    est = lambda: LogisticRegression(  # noqa: E731
        regParam=0.01, float32_inputs=False, enable_sparse_data_optim=True
    ).setFeaturesCol("features")
    _budget(None)
    res = est().fit(df)
    tele.registry().reset()
    _budget(30_000)
    stream = est().fit(df)
    _assert_streamed(stream, tele.snapshot()["counters"])
    np.testing.assert_allclose(
        np.asarray(stream.coef_), np.asarray(res.coef_), rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(stream.intercept_), np.asarray(res.intercept_), rtol=1e-9
    )


# ----------------------------------------------------- typed failures -------


def test_overbudget_even_streaming_raises_typed_error(tele, rng):
    _budget(1_000)
    with pytest.raises(HbmBudgetError) as ei:
        LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(
            _reg_df(rng)
        )
    # the failure names WHAT doesn't fit — never a raw XLA error
    assert ei.value.largest_term == "stream.chunk_buffers"
    assert "stream.chunk_buffers" in str(ei.value)
    assert ei.value.estimate_bytes > ei.value.capacity_bytes


def test_l1_logistic_demotion_refuses_typed(tele, rng):
    # OWL-QN has no out-of-core form: a demoted L1 fit fails TYPED at the
    # solver gate, not with a shape/attribute error from a half-built path
    _budget(12_000)
    with pytest.raises(HbmBudgetError, match="OWL-QN"):
        LogisticRegression(
            regParam=0.01, elasticNetParam=1.0, float32_inputs=False
        ).setFeaturesCol("features").fit(_cls_df(rng))


# ------------------------------------------------------- OOM ladder ---------


def test_oom_at_placement_converts_and_streams(tele, rng):
    df = _reg_df(rng)
    base = LinearRegression(regParam=0.001, float32_inputs=False).setFeaturesCol(
        "features"
    ).fit(df)
    tele.registry().reset()
    core_mod.config["stream_chunk_rows"] = 512
    chaos.set_fault_plan("oom:stage=placement")
    model = LinearRegression(regParam=0.001, float32_inputs=False).setFeaturesCol(
        "features"
    ).fit(df)
    snap = tele.snapshot()
    assert model._fit_metrics["admission"]["verdict"] == "stream"
    assert model._fit_metrics["admission"]["reason"].startswith("backend OOM")
    assert snap["counters"]["memory.oom_caught"] == 1
    np.testing.assert_allclose(model.coef_, base.coef_, rtol=1e-9)


def test_oom_mid_solve_resumes_on_streaming_path(tele, rng):
    # a RESOURCE_EXHAUSTED at a solver checkpoint boundary: the conversion
    # ladder must finish the fit on the streaming path FROM THE CHECKPOINT
    # (restores == 1), matching an uninterrupted fit to rtol 1e-9
    df = pd.DataFrame({"features": list(rng.normal(size=(2000, 6)))})
    est = lambda: KMeans(  # noqa: E731
        k=4, seed=7, maxIter=12, tol=1e-12, float32_inputs=False
    ).setFeaturesCol("features")
    base = est().fit(df)
    tele.registry().reset()
    core_mod.config["stream_chunk_rows"] = 512
    core_mod.config["checkpoint_every_iters"] = 3
    chaos.set_fault_plan("oom:stage=solve:round=6")
    model = est().fit(df)
    snap = tele.snapshot()
    assert model._fit_metrics["admission"]["verdict"] == "stream"
    assert snap["counters"]["memory.oom_caught"] == 1
    assert snap["counters"]["checkpoint.restores"] == 1
    np.testing.assert_allclose(model.cluster_centers_, base.cluster_centers_, rtol=1e-9)


def test_unstreamable_estimator_oom_raises_typed(tele, rng):
    # an estimator with no out-of-core path: the caught backend OOM becomes
    # the typed permanent error (no silent second resident attempt)
    df = _reg_df(rng)
    chaos.set_fault_plan("oom:stage=placement")
    est = LinearRegression(float32_inputs=False).setFeaturesCol("features")
    est._supports_streaming_fit = False
    with pytest.raises(HbmBudgetError, match="backend out-of-memory"):
        est.fit(df)


# ------------------------------------------------ streaming semantics -------


def test_streamed_dataset_not_cached_in_scope(tele, rng):
    # a demoted fit has no HBM placement to reuse: the DeviceDataset cache
    # must not retain it, and a later fit re-budgets from scratch
    df = _reg_df(rng)
    _budget(12_000)
    with core_mod.device_dataset_scope():
        LinearRegression(regParam=0.001, float32_inputs=False).setFeaturesCol(
            "features"
        ).fit(df)
        snap = tele.snapshot()["counters"]
        assert snap.get("fit.device_dataset_builds") is None
        LinearRegression(regParam=0.002, float32_inputs=False).setFeaturesCol(
            "features"
        ).fit(df)
        snap = tele.snapshot()["counters"]
        assert snap.get("fit.device_dataset_reuses") is None
        assert snap.get("fit.demotions") == 2


def test_streaming_validation_names_column_and_row(tele, rng):
    # the per-row-block NaN scan: the bad row is named with its ABSOLUTE
    # index even though validation ran chunk by chunk inside the pipeline
    df = _reg_df(rng)
    feats = np.stack(df["features"].to_numpy())
    feats[1400, 2] = np.nan
    df = pd.DataFrame({"features": list(feats), "label": df["label"]})
    _budget(12_000)
    core_mod.config["validate_ingest"] = True
    with pytest.raises(IngestValidationError) as ei:
        LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    assert "features" in str(ei.value)
    assert "1400" in str(ei.value)


def test_resident_validation_still_eager(tele, rng):
    # the resident path keeps the fit-entry full scan (deferral is an
    # implementation detail of the driver, not a behavior change)
    df = _reg_df(rng, n=300)
    feats = np.stack(df["features"].to_numpy())
    feats[42, 0] = np.inf
    df = pd.DataFrame({"features": list(feats), "label": df["label"]})
    core_mod.config["validate_ingest"] = True
    with pytest.raises(IngestValidationError, match="42"):
        LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(df)


def test_memory_watermark_sampled_at_chunk_boundaries(tele, rng):
    # stream_place_blocks samples record_device_memory() once per chunk
    # boundary; on CPU there are no stats, so the pinned contract here is
    # the counter pair every streamed pass must leave behind
    df = _reg_df(rng)
    _budget(12_000)
    LinearRegression(regParam=0.001, float32_inputs=False).setFeaturesCol(
        "features"
    ).fit(df)
    counters = tele.snapshot()["counters"]
    assert counters["ingest.stream_chunks"] == 4
    assert counters["ingest.stream_rows"] == 2000


# ------------------------------------------- subprocess harness (env) -------


def _run_worker(mode, tmp_path, plan):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["SRML_FAULT_PLAN"] = plan
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = str(tmp_path / f"{mode}.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "oom_worker.py"), mode, out],
        env=env, capture_output=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stdout.decode() + proc.stderr.decode()
    with open(out) as f:
        return json.load(f)


def test_subprocess_oom_injection_demotes_at_fit_entry(tmp_path):
    # THE acceptance scenario: a chaos `oom` budget injection at fit entry
    # completes the fit via demotion with fit.demotions == 1, and the model
    # matches the clean resident fit the same process runs once the plan is
    # spent
    result = _run_worker("demote", tmp_path, "oom:budget=16000")
    assert result["error"] is None, result
    assert result["admission_faulted"]["verdict"] == "stream"
    assert result["admission_clean"]["verdict"] == "resident"
    assert result["counters"]["fit.demotions"] == 1
    assert result["max_rel_center_diff"] < 1e-9
    assert result["gauges"]["ingest.overlap_fraction"] > 0


def test_subprocess_oom_mid_recovery_resumes_streaming(tmp_path):
    # THE mid-recovery acceptance scenario: attempt 0 checkpoints and dies on
    # a transient; the recovery attempt's RE-placement OOMs (round=1 = the
    # retry attempt index) — the fit must still complete, resumed from the
    # attempt-0 checkpoint ON THE STREAMING PATH, matching an uninterrupted
    # fit
    result = _run_worker(
        "midrecovery", tmp_path, "fail:stage=solve;oom:stage=placement:round=1"
    )
    assert result["error"] is None, result
    assert result["admission_faulted"]["verdict"] == "stream"
    assert result["admission_faulted"]["reason"].startswith("backend OOM")
    c = result["counters"]
    assert c["fit.retries"] == 1
    assert c["memory.oom_caught"] == 1
    assert c["checkpoint.restores"] >= 1
    assert c["fit.demotions"] == 1
    assert result["max_rel_center_diff"] < 1e-9
