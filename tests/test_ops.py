#
# Direct unit tests for solver-layer primitives not covered transitively.
#
import numpy as np

import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.linalg import sign_flip, topk_eigh_desc, weighted_cov, weighted_moments


def test_weighted_moments(rng):
    x = rng.normal(size=(100, 4))
    w = rng.uniform(0.5, 2.0, size=100)
    total, mean, var = weighted_moments(jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(float(total), w.sum(), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(mean), np.average(x, axis=0, weights=w), rtol=1e-10)
    expected_var = np.average((x - np.average(x, axis=0, weights=w)) ** 2, axis=0, weights=w)
    np.testing.assert_allclose(np.asarray(var), expected_var, rtol=1e-8)


def test_weighted_cov_matches_numpy(rng):
    x = rng.normal(size=(50, 3))
    w = np.ones(50)
    _, mean, cov = weighted_cov(jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(cov), np.cov(x.T), rtol=1e-10)


def test_sign_flip():
    comps = jnp.asarray([[0.1, -0.9, 0.2], [0.5, 0.4, 0.3]])
    flipped = np.asarray(sign_flip(comps))
    np.testing.assert_allclose(flipped[0], [-0.1, 0.9, -0.2])
    np.testing.assert_allclose(flipped[1], [0.5, 0.4, 0.3])


def test_topk_eigh_desc(rng):
    a = rng.normal(size=(5, 5))
    sym = a @ a.T
    evals, evecs = topk_eigh_desc(jnp.asarray(sym), 3)
    evals = np.asarray(evals)
    assert evals[0] >= evals[1] >= evals[2]
    for i in range(3):
        np.testing.assert_allclose(sym @ np.asarray(evecs[i]), evals[i] * np.asarray(evecs[i]), atol=1e-8)


def test_owlqn_lam0_equals_lbfgs(rng):
    # with no L1 term OWL-QN must degrade to plain L-BFGS: same minimizer on a
    # strongly-convex quadratic-ish smooth objective
    import jax

    from spark_rapids_ml_tpu.ops.logistic import _lbfgs_minimize
    from spark_rapids_ml_tpu.ops.owlqn import owlqn_minimize

    A = jnp.asarray(rng.normal(size=(20, 6)))
    b = jnp.asarray(rng.normal(size=20))

    def smooth(x):
        r = A @ x - b
        return jnp.sum(jax.nn.softplus(r)) / 20.0 + 0.05 * jnp.sum(x * x)

    x0 = jnp.zeros(6)
    x_owl, f_owl, _ = jax.jit(
        lambda: owlqn_minimize(smooth, x0, jnp.ones(6), 0.0, max_iter=200, tol=1e-14)
    )()
    x_lb, f_lb, _ = jax.jit(
        lambda: _lbfgs_minimize(smooth, x0, max_iter=200, tol=1e-14)
    )()
    np.testing.assert_allclose(float(f_owl), float(f_lb), rtol=1e-8)
    np.testing.assert_allclose(np.asarray(x_owl), np.asarray(x_lb), atol=1e-4)


def test_owlqn_lasso_zeros(rng):
    # L1-regularized least squares with a known sparse solution: OWL-QN must
    # drive truly-inactive coordinates to EXACT zero (orthant projection)
    import jax

    from spark_rapids_ml_tpu.ops.owlqn import owlqn_minimize

    n, d = 120, 10
    A = jnp.asarray(rng.normal(size=(n, d)))
    x_true = np.zeros(d)
    x_true[:3] = [2.0, -1.5, 1.0]
    b = A @ jnp.asarray(x_true) + 0.01 * jnp.asarray(rng.normal(size=n))

    def smooth(x):
        r = A @ x - b
        return 0.5 * jnp.sum(r * r) / n

    lam = 0.08
    x, _, _ = jax.jit(
        lambda: owlqn_minimize(smooth, jnp.zeros(d), jnp.ones(d), lam, max_iter=300, tol=1e-14)
    )()
    x = np.asarray(x)
    # compare against sklearn Lasso (identical objective: 1/(2n)·‖Ax−b‖² + λ‖x‖₁
    # in sklearn is alpha=λ ... sklearn uses 1/(2n) too)
    from sklearn.linear_model import Lasso

    sk = Lasso(alpha=lam, fit_intercept=False, tol=1e-14, max_iter=100000).fit(
        np.asarray(A), np.asarray(b)
    )
    np.testing.assert_allclose(x, sk.coef_, atol=2e-4)
    np.testing.assert_array_equal(x == 0.0, sk.coef_ == 0.0)
