#
# Direct unit tests for solver-layer primitives not covered transitively.
#
import numpy as np

import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.linalg import sign_flip, topk_eigh_desc, weighted_cov, weighted_moments


def test_weighted_moments(rng):
    x = rng.normal(size=(100, 4))
    w = rng.uniform(0.5, 2.0, size=100)
    total, mean, var = weighted_moments(jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(float(total), w.sum(), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(mean), np.average(x, axis=0, weights=w), rtol=1e-10)
    expected_var = np.average((x - np.average(x, axis=0, weights=w)) ** 2, axis=0, weights=w)
    np.testing.assert_allclose(np.asarray(var), expected_var, rtol=1e-8)


def test_weighted_cov_matches_numpy(rng):
    x = rng.normal(size=(50, 3))
    w = np.ones(50)
    _, mean, cov = weighted_cov(jnp.asarray(x), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(cov), np.cov(x.T), rtol=1e-10)


def test_sign_flip():
    comps = jnp.asarray([[0.1, -0.9, 0.2], [0.5, 0.4, 0.3]])
    flipped = np.asarray(sign_flip(comps))
    np.testing.assert_allclose(flipped[0], [-0.1, 0.9, -0.2])
    np.testing.assert_allclose(flipped[1], [0.5, 0.4, 0.3])


def test_topk_eigh_desc(rng):
    a = rng.normal(size=(5, 5))
    sym = a @ a.T
    evals, evecs = topk_eigh_desc(jnp.asarray(sym), 3)
    evals = np.asarray(evals)
    assert evals[0] >= evals[1] >= evals[2]
    for i in range(3):
        np.testing.assert_allclose(sym @ np.asarray(evecs[i]), evals[i] * np.asarray(evecs[i]), atol=1e-8)
