#
# PCA compat tests — parameterized over feature type and dtype, compared against
# sklearn (the reference compares against Spark CPU / single-GPU cuML the same
# way; reference tests/test_pca.py).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.linalg import Vectors
from spark_rapids_ml_tpu.models.feature import PCA, PCAModel


def _make_df(rng, n=200, d=8, feature_type="array", dtype=np.float32):
    x = rng.normal(size=(n, d)).astype(dtype)
    x[:, 0] *= 5  # give PCA something to find
    x[:, 1] *= 2
    if feature_type == "array":
        df = pd.DataFrame({"features": list(x)})
        cols = dict(inputCol="features")
    elif feature_type == "vector":
        df = pd.DataFrame({"features": [Vectors.dense(v) for v in x]})
        cols = dict(inputCol="features")
    else:  # multi_cols
        df = pd.DataFrame({f"c{i}": x[:, i] for i in range(d)})
        cols = dict(inputCols=[f"c{i}" for i in range(d)])
    return df, x, cols


@pytest.mark.parametrize("feature_type", ["array", "vector", "multi_cols"])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_pca_vs_sklearn(rng, feature_type, dtype):
    from sklearn.decomposition import PCA as SkPCA

    df, x, cols = _make_df(rng, feature_type=feature_type, dtype=dtype)
    k = 3
    est = PCA(k=k, num_workers=4, float32_inputs=(dtype == np.float32), **cols)
    assert est.solver_params["n_components"] == 3
    model = est.fit(df)

    sk = SkPCA(n_components=k, svd_solver="full").fit(x.astype(np.float64))
    tol = 1e-3 if dtype == np.float32 else 1e-8
    # components match up to sign; our sign convention = max-|v| positive
    for i in range(k):
        ours, theirs = model.components_[i], sk.components_[i]
        theirs = theirs * np.sign(theirs[np.argmax(np.abs(theirs))])
        np.testing.assert_allclose(ours, theirs, atol=tol)
    np.testing.assert_allclose(model.explained_variance_, sk.explained_variance_, rtol=1e-2 if dtype == np.float32 else 1e-8)
    np.testing.assert_allclose(
        model.explained_variance_ratio_, sk.explained_variance_ratio_, rtol=1e-2 if dtype == np.float32 else 1e-8
    )
    np.testing.assert_allclose(model.mean_, x.mean(axis=0), atol=tol)

    # transform parity: Spark semantics = X @ compsᵀ (no centering)
    out = model.transform(df)
    out_col = model._out_column_names()[0]
    got = np.stack([np.asarray(v.toArray() if hasattr(v, "toArray") else v) for v in out[out_col]])
    np.testing.assert_allclose(got, x @ model.components_.T, atol=tol * 10)


def test_pca_spark_surface(rng):
    df, x, cols = _make_df(rng)
    model = PCA(num_workers=2).setK(2).setInputCol("features").setOutputCol("pca_out").fit(df)
    assert model.pc.shape == (8, 2)
    assert len(model.mean) == 8
    assert model.explainedVariance.shape == (2,)
    out = model.transform(df)
    assert "pca_out" in out.columns
    assert model.getK() == 2


def test_pca_sign_flip_convention(rng):
    df, x, cols = _make_df(rng)
    model = PCA(k=4, inputCol="features").fit(df)
    for comp in model.components_:
        assert comp[np.argmax(np.abs(comp))] > 0


def test_pca_k_exceeds_cols_raises(rng):
    df, _, cols = _make_df(rng, d=4)
    with pytest.raises(ValueError, match="exceeds"):
        PCA(k=5, inputCol="features").fit(df)


def test_pca_persistence(tmp_path, rng):
    df, x, cols = _make_df(rng)
    model = PCA(k=3, inputCol="features", outputCol="o").fit(df)
    p = str(tmp_path / "pca_model")
    model.write().overwrite().save(p)
    loaded = PCAModel.load(p)
    np.testing.assert_array_equal(loaded.components_, model.components_)
    np.testing.assert_array_equal(loaded.mean_, model.mean_)
    out1 = model.transform(df)
    out2 = loaded.transform(df)
    a = np.stack([np.asarray(v) for v in out1["o"]])
    b = np.stack([np.asarray(v) for v in out2["o"]])
    np.testing.assert_allclose(a, b)


def test_pca_fit_multiple(rng):
    df, _, cols = _make_df(rng)
    est = PCA(inputCol="features")
    pmaps = [{est.getParam("k"): 1}, {est.getParam("k"): 3}]
    models = dict(est.fitMultiple(df, pmaps))
    assert models[0].components_.shape == (1, 8)
    assert models[1].components_.shape == (3, 8)


def test_pca_padding_invariance(rng):
    # results must not depend on how rows pad onto the mesh: compare a row count
    # divisible by 8 against one that forces 7 padding rows
    x = rng.normal(size=(160, 5)).astype(np.float64)
    m1 = PCA(k=2, inputCol="features", float32_inputs=False, num_workers=8).fit(
        pd.DataFrame({"features": list(x)})
    )
    m2 = PCA(k=2, inputCol="features", float32_inputs=False, num_workers=8).fit(
        pd.DataFrame({"features": list(x[:153])})
    )
    m1b = PCA(k=2, inputCol="features", float32_inputs=False, num_workers=1).fit(
        pd.DataFrame({"features": list(x[:153])})
    )
    # same data on 8 devices (with padding) vs 1 device (no padding) is identical
    np.testing.assert_allclose(m2.mean_, m1b.mean_, atol=1e-12)
    np.testing.assert_allclose(m2.components_, m1b.components_, atol=1e-10)
    assert not np.allclose(m1.mean_, m2.mean_)  # different data actually differs
