#
# Partition-parallel data generation tests (reference gen_data_distributed.py
# analog): per-partition seed determinism, bit-identical output for any
# process count, streaming ELL assembly equality, and the scaled-down
# 1e7x2200 sparse scale-shape lane (slow).
#
import os

import numpy as np
import pytest

from benchmark.gen_data_distributed import (
    GENERATORS,
    BlobsDataGen,
    ClassificationDataGen,
    RegressionDataGen,
    SparseRegressionDataGen,
    iter_sparse_npz_dataset,
    partitions_to_ell,
    read_sparse_npz_dataset,
)


def test_partition_content_is_pure_function_of_seed_and_index():
    # two independent instances, any order of partition generation: identical
    a = SparseRegressionDataGen(5_003, 64, seed=11, n_partitions=4, density=0.05)
    b = SparseRegressionDataGen(5_003, 64, seed=11, n_partitions=4, density=0.05)
    xb, yb = b.gen_partition(2)  # b generates ONLY partition 2
    for i in [0, 3, 2, 1]:
        a.gen_partition(i)
    xa, ya = a.gen_partition(2)
    assert (xa != xb).nnz == 0
    np.testing.assert_array_equal(ya, yb)
    # different seed / different partition => different bytes
    c = SparseRegressionDataGen(5_003, 64, seed=12, n_partitions=4, density=0.05)
    xc, _ = c.gen_partition(2)
    assert (xa != xc).nnz > 0


def test_partition_bounds_cover_rows_exactly():
    g = RegressionDataGen(1000, 8, seed=0, n_partitions=7)
    bounds = [g.partition_bounds(i) for i in range(7)]
    assert bounds[0][0] == 0 and bounds[-1][1] == 1000
    for (lo, hi), (lo2, _) in zip(bounds, bounds[1:]):
        assert hi == lo2 and hi > lo


@pytest.mark.parametrize("kind", sorted(GENERATORS))
def test_write_bit_identical_across_process_counts(kind, tmp_path):
    gen = GENERATORS[kind](2_001, 12, seed=5, n_partitions=5)
    d1, d3 = str(tmp_path / "p1"), str(tmp_path / "p3")
    assert gen.write(d1, n_processes=1) == 5
    assert gen.write(d3, n_processes=3) == 5
    files1 = sorted(os.listdir(d1))
    files3 = sorted(os.listdir(d3))
    assert files1 == files3 and len(files1) == 5
    for f in files1:
        with open(os.path.join(d1, f), "rb") as fa, open(os.path.join(d3, f), "rb") as fb:
            assert fa.read() == fb.read(), f"part file {f} differs across process counts"


def test_generate_matches_written_partitions(tmp_path):
    from benchmark.dataset_io import read_parquet_dataset

    g = ClassificationDataGen(1_234, 10, seed=2, n_partitions=3, n_classes=3)
    X, y = g.generate()
    assert X.shape == (1_234, 10) and set(np.unique(y)) <= {0, 1, 2}
    out = str(tmp_path / "ds")
    g.write(out, n_processes=2)
    X2, y2 = read_parquet_dataset(out)
    np.testing.assert_array_equal(X2, X)
    np.testing.assert_array_equal(y2.astype(np.int64), y)

    gs = SparseRegressionDataGen(999, 40, seed=3, n_partitions=4, density=0.05)
    Xs, ys = gs.generate()
    outs = str(tmp_path / "sp")
    gs.write(outs, n_processes=2)
    Xr, yr = read_sparse_npz_dataset(outs)
    assert (Xs != Xr).nnz == 0
    np.testing.assert_array_equal(ys, yr)
    # streaming reader yields partitions in order with the same total
    n_stream = sum(x.shape[0] for x, _ in iter_sparse_npz_dataset(outs))
    assert n_stream == 999


def test_partitions_to_ell_matches_whole_csr_conversion():
    from spark_rapids_ml_tpu.ops.sparse import csr_to_ell

    g = SparseRegressionDataGen(3_000, 80, seed=9, n_partitions=6, density=0.03)
    idx_s, val_s, k_s, y_s = partitions_to_ell(g)
    X, y = g.generate()
    idx_w, val_w, k_w = csr_to_ell(X, k_max=k_s, dtype=np.float32)
    np.testing.assert_array_equal(idx_s, idx_w)
    np.testing.assert_array_equal(val_s, val_w)
    np.testing.assert_array_equal(y_s, y)


def test_blobs_labels_match_centers():
    g = BlobsDataGen(800, 6, seed=1, n_partitions=2, centers=4)
    X, y = g.generate()
    C = g.shared["C"]
    d2 = ((X[:, None, :] - C[None, :, :]) ** 2).sum(-1)
    # cluster_std=1 around well-separated (10x) centers: labels = nearest center
    assert (np.argmin(d2, axis=1) == y).mean() > 0.99


def test_cli_writes_parts(tmp_path):
    from benchmark.gen_data_distributed import main as gen_main

    out = str(tmp_path / "cli")
    gen_main([
        "sparse_regression", "--num_rows", "400", "--num_cols", "30",
        "--density", "0.1", "--n_partitions", "3", "--n_processes", "2",
        "--output", out,
    ])
    X, y = read_sparse_npz_dataset(out)
    assert X.shape == (400, 30) and y.shape == (400,)
    assert 0.05 < X.nnz / (400 * 30) < 0.2


@pytest.mark.slow
def test_scale_shape_partition_parallel(tmp_path):
    # the 1e7 x 2200 sparse regression scale shape, scaled down 25x in rows
    # (same width/density => same per-row statistics): partition-parallel
    # write, per-partition seed determinism, and the streaming ELL budget
    n, d, density = 400_000, 2200, 0.001
    g = SparseRegressionDataGen(n, d, seed=0, density=density, n_partitions=8)
    out = str(tmp_path / "scale")
    g.write(out, n_processes=2)
    # an independent instance generating ONLY partition 5 reproduces the
    # written file's content bit-exactly
    solo = SparseRegressionDataGen(n, d, seed=0, density=density, n_partitions=8)
    x5, y5 = solo.gen_partition(5)
    parts = list(iter_sparse_npz_dataset(out))
    assert len(parts) == 8
    assert (parts[5][0] != x5).nnz == 0
    np.testing.assert_array_equal(parts[5][1], y5)
    # streaming ELL ingest: k_max stays in the padded-ELL design budget
    idx, val, k_max, y = partitions_to_ell(g)
    assert idx.shape[0] == n and k_max <= 64
    assert abs(val.astype(bool).sum() / (n * d) - density) / density < 0.05
