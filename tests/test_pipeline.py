#
# Pipeline / PipelineModel tests — the pyspark.ml.Pipeline contract driven
# without a Spark session (chained fit/transform, composite persistence).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.linalg import Vectors
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.pipeline import Pipeline, PipelineModel


def _data(rng, n=400, d=10):
    x = rng.normal(size=(n, d))
    # anisotropic: the label-carrying dimensions dominate the variance, so a
    # k=4 PCA stage keeps the signal (isotropic features would rotate it away)
    x[:, 0] *= 6.0
    x[:, 1] *= 4.0
    y = (x[:, 0] / 6.0 + 0.5 * x[:, 1] / 4.0 > 0).astype(float)
    return pd.DataFrame({"features": [Vectors.dense(r) for r in x], "label": y}), x, y


def test_pipeline_pca_then_logreg(rng, tmp_path):
    df, x, y = _data(rng)
    pca = PCA(k=4, inputCol="features", outputCol="pca_features", float32_inputs=False)
    lr = (
        LogisticRegression(maxIter=100, regParam=0.01, float32_inputs=False)
        .setFeaturesCol("pca_features")
    )
    model = Pipeline(stages=[pca, lr]).fit(df)
    assert isinstance(model, PipelineModel) and len(model.stages) == 2

    out = model.transform(df)
    assert {"pca_features", "prediction", "probability"} <= set(out.columns)
    acc = (out["prediction"].to_numpy() == y).mean()
    assert acc > 0.9, acc

    # manual chaining must match exactly
    pca_model = pca.fit(df)
    lr_model = lr.fit(pca_model.transform(df))
    manual = lr_model.transform(pca_model.transform(df))["prediction"].to_numpy()
    np.testing.assert_array_equal(out["prediction"].to_numpy(), manual)

    # persistence round-trip through the composite writer + class dispatch
    path = str(tmp_path / "pipe")
    model.save(path)
    with pytest.raises(FileExistsError):
        model.save(path)
    loaded = PipelineModel.load(path)
    np.testing.assert_array_equal(
        loaded.transform(df)["prediction"].to_numpy(), out["prediction"].to_numpy()
    )


def test_pipeline_transformer_stage_passthrough(rng):
    # a FITTED model mixed into the stage list acts as a transformer
    df, x, y = _data(rng, n=200)
    pca_model = PCA(k=3, inputCol="features", outputCol="p", float32_inputs=False).fit(df)
    lr = LogisticRegression(maxIter=50, float32_inputs=False).setFeaturesCol("p")
    model = Pipeline(stages=[pca_model, lr]).fit(df)
    out = model.transform(df)
    assert "prediction" in out.columns and len(out) == 200


def test_cross_validator_over_pipeline(rng):
    # the standard pyspark workflow: CV sweeping a stage param of a Pipeline
    # (takes the fallback fit-per-model path; Pipeline.copy routes the grid
    # entry to the stage that owns the param)
    from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
    from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

    df, x, y = _data(rng, n=240)
    lr = LogisticRegression(maxIter=60, float32_inputs=False).setFeaturesCol("pca_features")
    pipe = Pipeline(stages=[
        PCA(k=4, inputCol="features", outputCol="pca_features", float32_inputs=False),
        lr,
    ])
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.001, 1.0]).build()
    cv = CrossValidator(
        estimator=pipe, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=2, seed=1,
    )
    cv_model = cv.fit(df)
    assert len(cv_model.avgMetrics) == 2
    # tiny regularization must win on separable data
    assert int(np.argmax(cv_model.avgMetrics)) == 0
    out = cv_model.transform(df)
    assert (out["prediction"].to_numpy() == y).mean() > 0.9


def test_pipeline_copy_ambiguous_param_raises(rng):
    # Params are per-NAME singletons: a grid param carried by two stages
    # cannot identify its target — must raise, not silently re-tune both
    lr = LogisticRegression()
    pca = PCA(k=2)
    pipe = Pipeline(stages=[pca, lr])
    shared = lr.getParam("featuresCol")  # both stages carry featuresCol
    with pytest.raises(ValueError, match="ambiguous"):
        pipe.copy({shared: "x"})
    # unambiguous params route fine
    out = pipe.copy({lr.getParam("regParam"): 0.5})
    assert out.getStages()[1].getOrDefault("regParam") == 0.5
    assert out.getStages()[0].getOrDefault("k") == 2


def test_pipeline_copy_unmatched_param_raises():
    # a typo'd / wrong-estimator key owned by NO stage must be as loud as the
    # ambiguous case — silently dropping it would train identical models for
    # every point of a CV/TVS grid (ADVICE round 5)
    pipe = Pipeline(stages=[PCA(k=2), LogisticRegression()])
    with pytest.raises(ValueError, match="no stage"):
        pipe.copy({"regParamm": 0.5})  # typo'd name
    with pytest.raises(ValueError, match="no stage"):
        pipe.copy({"maxDepth": 3})  # wrong-estimator key (RF param)


def test_pipeline_validation():
    with pytest.raises(ValueError, match="stages"):
        Pipeline().fit(pd.DataFrame({"features": []}))
    with pytest.raises(TypeError, match="stage 0"):
        Pipeline(stages=[object()]).fit(pd.DataFrame({"features": []}))
