#
# Distributed-diagnostics tests: trace correlation (per-rank JSONL -> Chrome
# trace-event JSON, clock-skew aligned), the always-on flight recorder
# (ring bounds, SrmlError tails, dumps), cross-rank post-mortem assembly
# (incl. the 3-rank SIGKILL acceptance harness), and the perf-regression
# gate over the BENCH trajectory.
#
import json
import os
import signal
import subprocess
import sys
import threading
import uuid

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import diagnostics, telemetry
from spark_rapids_ml_tpu.errors import RankFailedError, RendezvousTimeoutError

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)


@pytest.fixture
def fresh_recorder():
    """Reset the process flight recorder around the test (it is always-on
    and global, so other suites leave events in it)."""
    rec = diagnostics.flight_recorder()
    rec.reset()
    yield rec
    rec.reset()


@pytest.fixture
def tele(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    telemetry.registry().reset()
    telemetry.enable(path)
    yield path
    telemetry.disable()
    telemetry._STATE.sink_path = None
    telemetry.registry().reset()


def _binary_df(rng, n=150, d=4):
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return pd.DataFrame({"features": list(x), "label": y})


# ------------------------------------------------------------ flight recorder


def test_flight_recorder_ring_bound_and_drop_counter(tele):
    rec = diagnostics.FlightRecorder(capacity=4, enabled=True)
    for i in range(10):
        rec.record("tick", i=i)
    evs = rec.events()
    assert len(evs) == 4
    assert [e["i"] for e in evs] == [6, 7, 8, 9]  # oldest overwritten first
    stats = rec.stats()
    assert stats["recorded"] == 10 and stats["dropped"] == 6
    # truncation is NEVER silent: the registry counter mirrors the drops
    assert telemetry.snapshot()["counters"]["flightrec.events_dropped"] == 6
    assert rec.tail(2) == evs[-2:]


def test_flight_recorder_dump_roundtrip(tmp_path, fresh_recorder):
    fresh_recorder.record("alpha", x=1)
    fresh_recorder.record("beta", x=2)
    path = str(tmp_path / "flightrec_rank_0.jsonl")
    assert fresh_recorder.dump(path, reason="unit test") == path
    lines = [json.loads(l) for l in open(path)]
    assert [l["kind"] for l in lines] == ["alpha", "beta", "flightrec_dump"]
    footer = lines[-1]
    assert footer["reason"] == "unit test" and footer["recorded"] == 2


def test_flight_recorder_disabled_records_nothing(monkeypatch):
    rec = diagnostics.FlightRecorder(capacity=8, enabled=False)
    rec.record("tick")
    assert rec.events() == []
    assert rec.dump("/nonexistent/should/not/matter") is None


def test_srml_error_attaches_tail_and_dumps(tmp_path, monkeypatch, fresh_recorder):
    monkeypatch.setenv("SRML_FLIGHTREC_DIR", str(tmp_path))
    diagnostics.record_event("marker", round=41)
    try:
        raise RankFailedError(2, "peer died", round_index=7)
    except RankFailedError as e:
        tail = e.flightrec_tail
    assert tail, "SrmlError must carry the flight-recorder tail"
    assert tail[-1]["kind"] == "error"
    assert tail[-1]["failed_rank"] == 2 and tail[-1]["round_index"] == 7
    assert any(ev["kind"] == "marker" for ev in tail)
    dump = tmp_path / "flightrec_rank_0.jsonl"
    assert dump.exists(), "SrmlError with a dump dir configured must dump the ring"
    kinds = [json.loads(l)["kind"] for l in open(dump)]
    assert "marker" in kinds and "error" in kinds
    # SRML_FLIGHTREC_TAIL=0 means NO tail, not the whole ring (evs[-0:] trap)
    monkeypatch.setenv("SRML_FLIGHTREC_TAIL", "0")
    try:
        raise RankFailedError(2, "no-tail case")
    except RankFailedError as e2:
        assert e2.flightrec_tail == []


def test_config_flightrec_dir_without_env(tmp_path, monkeypatch, fresh_recorder):
    # config["flightrec_dir"] works when core is loaded (the in-process
    # path); resolution must NOT import core itself — inside SrmlError
    # construction that import chain (~1s) would ride every survivor's
    # failure-detection latency in control-plane-only processes (pinned by
    # test_chaos.py::test_killed_rank_detected_within_heartbeat_budget)
    from spark_rapids_ml_tpu import core as core_mod

    monkeypatch.delenv("SRML_FLIGHTREC_DIR", raising=False)
    monkeypatch.setitem(core_mod.config, "flightrec_dir", str(tmp_path))
    try:
        raise RankFailedError(1, "via config dir")
    except RankFailedError:
        pass
    assert (tmp_path / "flightrec_rank_0.jsonl").exists()


def test_timeout_error_also_carries_round(fresh_recorder):
    # attributes are set BEFORE super().__init__ so the hook records them
    try:
        raise RendezvousTimeoutError("round 3 timed out", round_index=3, timeout_s=1.0)
    except RendezvousTimeoutError as e:
        assert e.flightrec_tail[-1]["round_index"] == 3


def test_summary_and_snapshot_expose_flightrec_health(tele, fresh_recorder):
    diagnostics.record_event("tick")
    s = telemetry.summary()
    assert "flightrec rank0:" in s and "recorded" in s and "dropped" in s
    snap = telemetry.snapshot()
    assert snap["flightrec"]["recorded"] >= 1
    assert snap["flightrec"]["enabled"] is True


# --------------------------------------------------------- trace correlation


def test_trace_scope_tags_span_and_fit_records(tele, fresh_recorder):
    with diagnostics.trace_scope("UnitTest"):
        tags = diagnostics.trace_tags()
        assert tags["trace_id"] and tags["fit_id"].startswith("fit-")
        with telemetry.span("stage_a"):
            pass
    assert diagnostics.trace_tags() == {}  # scope exited cleanly
    recs = [json.loads(l) for l in open(tele)]
    spans = [r for r in recs if r["kind"] == "span"]
    assert spans and all(r["trace_id"] == tags["trace_id"] for r in spans)
    assert all("t0" in r for r in spans)
    # the flight recorder saw the scope too, with the same identity
    kinds = {e["kind"] for e in diagnostics.flight_recorder().events()}
    assert {"trace_begin", "span_begin", "span_end", "trace_end"} <= kinds


def test_trace_scope_spmd_propagates_rank0_id():
    # rank 0 mints, every rank adopts — one extra allgather round, lockstep
    from spark_rapids_ml_tpu.parallel import LocalRendezvous

    class _Ctx:
        is_spmd = True

        def __init__(self, rank, rdv):
            self.rank = rank
            self.rendezvous = rdv

    rvs = LocalRendezvous.create(2, timeout_s=10.0)
    seen = [None, None]

    def run(r):
        with diagnostics.trace_scope("spmd", _Ctx(r, rvs[r])) as tags:
            seen[r] = tags["trace_id"]

    threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen[0] is not None and seen[0] == seen[1]


def test_trace_exchange_failure_is_nonfatal(fresh_recorder):
    # the trace-id round runs BEFORE the fit body enters retryable_stage:
    # a control-plane failure there must degrade correlation (local id),
    # never kill the fit — the next real round fails WITH retry protection
    class _Ctx:
        is_spmd = True
        rank = 1

        class rendezvous:  # noqa: N801 - stub namespace
            @staticmethod
            def allgather(payload):
                raise RendezvousTimeoutError("peer slow entering fit", round_index=0)

    with diagnostics.trace_scope("degraded", _Ctx()) as tags:
        assert tags["trace_id"]  # locally-minted fallback
    kinds = [e["kind"] for e in diagnostics.flight_recorder().events()]
    assert "trace_exchange_failed" in kinds


def test_malformed_flightrec_capacity_env_does_not_crash(monkeypatch):
    monkeypatch.setenv("SRML_FLIGHTREC_EVENTS", "2k")  # operator typo
    rec = diagnostics.FlightRecorder()
    assert rec.capacity == 2048  # default, not a ValueError at import


def test_fits_get_distinct_trace_ids_and_sequenced_fit_ids(tele, rng):
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    df = _binary_df(rng)
    LogisticRegression(maxIter=5).setFeaturesCol("features").fit(df)
    LogisticRegression(maxIter=5).setFeaturesCol("features").fit(df)
    fit_recs = [json.loads(l) for l in open(tele)]
    fit_recs = [r for r in fit_recs if r["kind"] == "fit"]
    assert len(fit_recs) == 2
    assert fit_recs[0]["trace_id"] != fit_recs[1]["trace_id"]
    n0 = int(fit_recs[0]["fit_id"].split("-")[1])
    n1 = int(fit_recs[1]["fit_id"].split("-")[1])
    assert n1 == n0 + 1


def test_env_trace_id_tags_records_without_a_scope(monkeypatch, fresh_recorder):
    monkeypatch.setenv("SRML_TRACE_ID", "launcher-minted")
    diagnostics.record_event("tick")
    assert diagnostics.flight_recorder().events()[-1]["trace_id"] == "launcher-minted"


# ---------------------------------------------------------------- trace merge


def _mk_span(rank, name, path, t0, wall, trace_id="t1", **extra):
    return {"kind": "span", "name": name, "path": path, "wall_s": wall,
            "rank": rank, "trace_id": trace_id, "fit_id": "fit-1", "t0": t0,
            **extra}


def _synthetic_rank_records(skew_rank1=5.0):
    """Three lockstep rendezvous rounds on 2 ranks + per-rank work spans.
    rank 1's clock runs `skew_rank1` seconds FAST (its recorded t0s are
    shifted); rank 1 is also RAGGED (missing the last work span)."""
    base = 1000.0
    r0, r1 = [], []
    for rnd in range(3):
        t = base + rnd * 2.0
        r0.append(_mk_span(0, "rendezvous.allgather", "rendezvous.allgather",
                           t, 0.5, round=rnd, nranks=2))
        # rank1 entered a touch later but (physically) exited in lockstep;
        # its CLOCK shifts every timestamp by skew_rank1
        r1.append(_mk_span(1, "rendezvous.allgather", "rendezvous.allgather",
                           t + 0.2 + skew_rank1, 0.3, round=rnd, nranks=2))
    r0.append(_mk_span(0, "solve", "fit/solve", base + 6.5, 1.0))
    r1_work_missing = True  # ragged: rank 1 never recorded its solve span
    assert r1_work_missing
    return {0: r0, 1: r1}


def _validate_chrome_trace(trace):
    """Chrome trace-event JSON-object-format schema invariants (what
    Perfetto/chrome://tracing require to load the file)."""
    assert isinstance(trace, dict)
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert isinstance(ev.get("ph"), str) and ev["ph"]
        assert isinstance(ev.get("name"), str)
        assert isinstance(ev.get("pid"), int)
        assert isinstance(ev.get("tid"), int)
        if ev["ph"] in ("X", "s", "f"):
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        if ev["ph"] == "M":
            assert "args" in ev
    json.dumps(trace)  # round-trippable


def test_merge_chrome_trace_schema_tracks_and_flows():
    trace = diagnostics.merge_chrome_trace(_synthetic_rank_records())
    _validate_chrome_trace(trace)
    events = trace["traceEvents"]
    thread_names = {e["tid"]: e["args"]["name"] for e in events
                    if e["ph"] == "M" and e["name"] == "thread_name"}
    assert thread_names == {0: "rank 0", 1: "rank 1"}  # one track per rank
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["tid"] for e in xs} == {0, 1}
    # rendezvous rounds render as flow arrows (one start + one finish each)
    starts = [e for e in events if e["ph"] == "s"]
    finishes = [e for e in events if e["ph"] == "f"]
    assert len(starts) == 3 and len(finishes) == 3
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}


def test_merge_aligns_clock_skew_on_barrier_rounds():
    trace = diagnostics.merge_chrome_trace(_synthetic_rank_records(skew_rank1=5.0))
    # the recovered offset is the barrier-exit delta: ~-5s for the rank whose
    # clock runs 5s fast (median over rounds; exact here — constant skew)
    off = trace["otherData"]["clock_offsets_s"]
    assert abs(off["1"] + 5.0) < 0.11 and off["0"] == 0.0
    # after alignment the two ranks' round-0 allgather exits coincide
    xs = [e for e in trace["traceEvents"]
          if e["ph"] == "X" and e["name"] == "rendezvous.allgather"]
    ends = {(e["tid"], e["args"]["round"]): e["ts"] + e["dur"] for e in xs}
    assert abs(ends[(0, 0)] - ends[(1, 0)]) < 0.11 * 1e6
    # unaligned, they are ~5s apart
    raw = diagnostics.merge_chrome_trace(
        _synthetic_rank_records(skew_rank1=5.0), align_clocks=False
    )
    raw_ends = {(e["tid"], e["args"]["round"]): e["ts"] + e["dur"]
                for e in raw["traceEvents"]
                if e["ph"] == "X" and e["name"] == "rendezvous.allgather"}
    assert abs(raw_ends[(0, 0)] - raw_ends[(1, 0)]) > 4.0 * 1e6


def test_load_telemetry_jsonl_tolerates_missing_and_garbage(tmp_path):
    base = str(tmp_path / "m.jsonl")
    with open(base, "w") as f:
        for rec in _synthetic_rank_records()[0]:
            f.write(json.dumps(rec) + "\n")
        f.write("NOT JSON\n")  # torn line — skipped, not fatal
    with open(base + ".rank1", "w") as f:
        for rec in _synthetic_rank_records()[1]:
            f.write(json.dumps(rec) + "\n")
    # rank 2's file simply does not exist (killed before its first flush)
    per_rank = diagnostics.load_telemetry_jsonl(base)
    assert sorted(per_rank) == [0, 1]
    trace = diagnostics.merge_chrome_trace(per_rank)
    _validate_chrome_trace(trace)
    assert trace["otherData"]["ranks"] == [0, 1]


def test_trace_merge_filters_by_trace_id():
    per_rank = {0: [_mk_span(0, "solve", "fit/solve", 1.0, 0.5, trace_id="a"),
                    _mk_span(0, "solve", "fit/solve", 2.0, 0.5, trace_id="b")]}
    trace = diagnostics.merge_chrome_trace(per_rank, trace_id="a")
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 1 and xs[0]["args"]["trace_id"] == "a"


def test_cv_fit_jsonl_merges_to_valid_chrome_trace(tele, rng):
    # THE acceptance path: a CrossValidator fit's telemetry JSONL -> valid
    # Chrome trace-event JSON, via the same entry point the CLI uses
    from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

    lr = LogisticRegression(maxIter=5, float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 0.1]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=2, seed=3,
    )
    cv.fit(_binary_df(rng, n=120))
    trace = diagnostics.chrome_trace_from_files(tele)
    _validate_chrome_trace(trace)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(xs) >= 3
    assert any(e["name"].endswith("solve") for e in xs)
    # every span slice carries its trace identity in args, and the WHOLE
    # cross-validation (fold fits, held-out scoring, refit) is ONE trace
    assert all("trace_id" in e["args"] for e in xs)
    assert len({e["args"]["trace_id"] for e in xs}) == 1
    # ...while the fold/refit fits keep their own fit_ids under it
    fit_ids = {e["args"].get("fit_id") for e in xs if e["name"] == "fit"}
    assert len(fit_ids) >= 2


def test_trace_merge_cli(tmp_path):
    base = str(tmp_path / "m.jsonl")
    with open(base, "w") as f:
        for rec in _synthetic_rank_records()[0]:
            f.write(json.dumps(rec) + "\n")
    out = str(tmp_path / "trace.json")
    from benchmark.trace_merge import main

    assert main([base, "-o", out]) == 0
    with open(out) as f:
        _validate_chrome_trace(json.load(f))


# ---------------------------------------------------------------- post-mortem


def _write_dump(tmp_path, rank, events):
    with open(tmp_path / f"flightrec_rank_{rank}.jsonl", "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _ev(rank, kind, t, **fields):
    return {"t": t, "kind": kind, "rank": rank, "trace_id": "tr1", **fields}


def test_postmortem_names_failed_rank_round_and_blockage(tmp_path):
    # ranks 0/1 survived long enough to dump; rank 2 was hard-killed (no
    # file). Both survivors recorded RankFailedError(2) at round 3 and were
    # still INSIDE round 3 when they noticed.
    for r in (0, 1):
        evs = []
        for rnd in range(3):
            evs.append(_ev(r, "rdv_enter", 10.0 + rnd, round=rnd, nranks=3))
            evs.append(_ev(r, "rdv_exit", 10.4 + rnd, round=rnd))
        evs.append(_ev(r, "rdv_enter", 13.0 + 0.01 * r, round=3, nranks=3))
        evs.append(_ev(r, "error", 14.0 + 0.01 * r, error="RankFailedError",
                       failed_rank=2, round_index=3, reason="heartbeat stale"))
        _write_dump(tmp_path, r, evs)
    pm = diagnostics.assemble_postmortem(str(tmp_path), nranks=3)
    assert pm["failed_rank"] == 2
    assert pm["failed_round"] == 3
    assert pm["missing_ranks"] == [2]
    assert pm["trace_id"] == "tr1"
    for r in (0, 1):
        assert pm["ranks"][r]["blocked_on"] == "rendezvous round 3"
        assert pm["ranks"][r]["error"] == "RankFailedError"
    # timeline is merged + time-sorted across ranks
    ts = [e["t"] for e in pm["timeline"]]
    assert ts == sorted(ts)
    text = diagnostics.render_postmortem(pm)
    assert "rank 2 failed at round 3" in text
    assert "heartbeat stale" in text
    assert "missing dumps" in text


def test_postmortem_ragged_and_empty(tmp_path):
    # one rank dumped, the rest never started: still assembles, blames the
    # missing rank only via absence (no error events to vote with)
    _write_dump(tmp_path, 0, [_ev(0, "rdv_enter", 1.0, round=0, nranks=2)])
    pm = diagnostics.assemble_postmortem(str(tmp_path), nranks=2)
    assert pm["failed_rank"] == 1  # absence as evidence
    assert pm["ranks"][0]["blocked_on"] == "rendezvous round 0"
    empty = diagnostics.assemble_postmortem(str(tmp_path / "nothing_here"), nranks=2)
    assert empty["failed_rank"] is None and empty["missing_ranks"] == [0, 1]


def test_postmortem_timeout_failure_names_missing_rank_and_round(tmp_path):
    # timeout-shaped failure: nobody published an abort (the hung rank is
    # alive but wedged), so survivors raise RendezvousTimeoutError carrying
    # round_index + missing_ranks — the post-mortem must still name both
    for r in (0, 1):
        evs = [_ev(r, "rdv_enter", 10.0, round=5, nranks=3),
               _ev(r, "error", 70.0, error="RendezvousTimeoutError",
                   round_index=5, missing_ranks=[2],
                   message="rendezvous round 5: ranks [2] missing after 60s")]
        _write_dump(tmp_path, r, evs)
    _write_dump(tmp_path, 2, [_ev(2, "rdv_enter", 9.0, round=4, nranks=3)])  # wedged
    pm = diagnostics.assemble_postmortem(str(tmp_path), nranks=3)
    assert pm["failed_rank"] == 2
    assert pm["failed_round"] == 5
    assert "missing after 60s" in pm["failure_reason"]


def test_postmortem_selects_latest_trace(tmp_path):
    old = [_ev(0, "error", 5.0, error="RankFailedError", failed_rank=1,
               round_index=0) | {"trace_id": "old"}]
    new = [_ev(0, "error", 50.0, error="RankFailedError", failed_rank=2,
               round_index=4) | {"trace_id": "new"}]
    _write_dump(tmp_path, 0, old + new)
    pm = diagnostics.assemble_postmortem(str(tmp_path))
    assert pm["trace_id"] == "new" and pm["failed_rank"] == 2


# -------------------------------------------- 3-rank SIGKILL e2e acceptance --


def _launch_diag_chaos_workers(nranks, tmp_path, plan, *, rounds, heartbeat_s,
                               timeout_s, trace_id):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["SRML_FAULT_PLAN"] = plan
    env["SRML_FLIGHTREC_DIR"] = str(tmp_path / "flightrec")
    env["SRML_TRACE_ID"] = trace_id
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rdv_dir = str(tmp_path / "rdv")
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir, exist_ok=True)
    os.makedirs(env["SRML_FLIGHTREC_DIR"], exist_ok=True)
    run_id = uuid.uuid4().hex
    procs = [
        subprocess.Popen(
            [
                sys.executable, os.path.join(HERE, "chaos_worker.py"),
                str(r), str(nranks), rdv_dir, out_dir, run_id,
                str(rounds), str(heartbeat_s), str(timeout_s),
            ],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(nranks)
    ]
    outputs = [p.communicate(timeout=180)[0].decode() for p in procs]
    return env["SRML_FLIGHTREC_DIR"], procs, outputs


def test_sigkilled_rank_yields_postmortem_naming_rank_and_round(tmp_path):
    # THE acceptance scenario: a 3-rank FileRendezvous run, rank 2 SIGKILLed
    # entering round 3 (no abort file, no atexit, no dump — hard death).
    # Survivors' SrmlErrors dump their flight-recorder rings; the assembled
    # post-mortem must name the dead rank AND the round, and show what each
    # survivor was blocked on, all correlated by the launcher's trace id.
    kill_round = 3
    trace_id = f"chaos-{uuid.uuid4().hex[:8]}"
    dump_dir, procs, outputs = _launch_diag_chaos_workers(
        3, tmp_path, f"kill:rank=2:round={kill_round}",
        rounds=6, heartbeat_s=0.75, timeout_s=60.0, trace_id=trace_id,
    )
    assert procs[2].returncode == -signal.SIGKILL
    dumps = sorted(os.listdir(dump_dir))
    assert dumps == ["flightrec_rank_0.jsonl", "flightrec_rank_1.jsonl"], (
        f"survivors must dump, the SIGKILLed rank must not: {dumps}\n"
        f"{outputs[0]}\n{outputs[1]}"
    )
    pm = diagnostics.assemble_postmortem(dump_dir, nranks=3, trace_id=trace_id)
    assert pm["failed_rank"] == 2
    assert pm["failed_round"] == kill_round
    assert pm["missing_ranks"] == [2]
    for r in (0, 1):
        info = pm["ranks"][r]
        assert info["blocked_on"] == f"rendezvous round {kill_round}"
        assert info["error"] == "RankFailedError"
        assert info["last_events"], "last-K events from every survivor"
        assert all(
            ev.get("trace_id") == trace_id for ev in info["last_events"]
        ), "all dump events correlated by the launcher trace id"
    text = diagnostics.render_postmortem(pm)
    assert f"rank 2 failed at round {kill_round}" in text
    # the CLI agrees (exit 0 = verdict reached)
    from benchmark.postmortem import main

    assert main([dump_dir, "--nranks", "3", "--trace-id", trace_id]) == 0


# ------------------------------------------------------------ regression gate


def _bench_record(value, counters=None, incomplete=False):
    unit = "rows/sec/chip (geomean of ..." + ("; INCOMPLETE, missing pca)" if incomplete else ")")
    rec = {"metric": "classical_ml_fit_throughput_geomean", "value": value,
           "unit": unit, "vs_baseline": 1.0}
    if counters is not None:
        rec["telemetry"] = {"counters": counters}
    return rec


HIST = [
    _bench_record(100_000.0, {"ingest.rows": 1e6, "ingest.datasets": 2,
                              "placement.device_put_calls": 10}),
    _bench_record(110_000.0, {"ingest.rows": 1e6, "ingest.datasets": 2,
                              "placement.device_put_calls": 10}),
    _bench_record(105_000.0),
]


def test_regression_gate_passes_on_steady_trajectory():
    from benchmark.regression import run_gate

    verdict = run_gate(_bench_record(102_000.0, {"ingest.rows": 1e6,
                                                 "ingest.datasets": 2}), HIST)
    assert verdict["verdict"] == "pass", verdict
    lanes = {ln["lane"]: ln for ln in verdict["lanes"]}
    assert lanes["throughput_geomean"]["status"] == "pass"
    assert lanes["ingest.rows"]["status"] == "pass"
    assert lanes["placement.device_put_calls"]["status"] == "skipped"  # absent current-side


def test_regression_gate_fails_on_2x_slowdown():
    from benchmark.regression import run_gate

    verdict = run_gate(_bench_record(52_500.0), HIST)  # half the median
    assert verdict["verdict"] == "fail"
    assert "throughput_geomean" in verdict["failed_lanes"]


def test_regression_gate_fails_on_counter_blowup_despite_wall_time():
    # the cache-regression class: wall time fine, ingest work DOUBLED
    from benchmark.regression import run_gate

    verdict = run_gate(
        _bench_record(106_000.0, {"ingest.rows": 2e6, "ingest.datasets": 4}), HIST
    )
    assert verdict["verdict"] == "fail"
    assert set(verdict["failed_lanes"]) == {"ingest.rows", "ingest.datasets"}
    lanes = {ln["lane"]: ln for ln in verdict["lanes"]}
    assert lanes["throughput_geomean"]["status"] == "pass"


def test_regression_counter_reference_is_one_coherent_snapshot():
    # a counter that stopped being emitted rounds ago must NOT gate the
    # current run against that stale reference: the reference set is the
    # newest counter-bearing complete run, taken whole
    from benchmark.regression import run_gate

    hist = [
        _bench_record(100_000.0, {"ingest.rows": 1e6, "sparse.csr_to_ell_calls": 1}),
        _bench_record(101_000.0, {"ingest.rows": 1e6}),  # newest counter-bearing
    ]
    verdict = run_gate(
        _bench_record(100_500.0, {"ingest.rows": 1e6, "sparse.csr_to_ell_calls": 5}),
        hist,
    )
    lanes = {ln["lane"]: ln for ln in verdict["lanes"]}
    assert lanes["sparse.csr_to_ell_calls"]["status"] == "skipped"
    assert verdict["verdict"] == "pass"


def test_regression_latency_lanes_gate_lower_better():
    # serving p50/p99 gate as LOWER-is-better lanes (the counter machinery,
    # generalized): within tolerance passes, a p99 blowup fails even though
    # every throughput lane is fine
    from benchmark.regression import run_gate

    def lat_rec(value, p50, p99):
        rec = _bench_record(value)
        rec["latency_lanes"] = {"serving_p50_ms": p50, "serving_p99_ms": p99}
        return rec

    hist = [lat_rec(100_000.0, 1.0, 5.0), lat_rec(102_000.0, 1.2, 5.5)]
    ok = run_gate(lat_rec(101_000.0, 1.1, 6.0), hist)
    lanes = {ln["lane"]: ln for ln in ok["lanes"]}
    assert lanes["latency:serving_p99_ms"]["status"] == "pass"
    assert lanes["latency:serving_p99_ms"]["direction"] == "lower-better"
    assert ok["verdict"] == "pass"

    bad = run_gate(lat_rec(103_000.0, 1.1, 12.0), hist)  # p99 blowup only
    assert bad["verdict"] == "fail"
    assert bad["failed_lanes"] == ["latency:serving_p99_ms"]
    lanes = {ln["lane"]: ln for ln in bad["lanes"]}
    assert lanes["throughput_geomean"]["status"] == "pass"
    assert lanes["latency:serving_p50_ms"]["status"] == "pass"


def test_regression_latency_lane_trajectory_start_is_skipped():
    # the first artifact carrying latency_lanes must not false-fail against
    # history that predates the serving lane
    from benchmark.regression import run_gate

    cur = _bench_record(101_000.0)
    cur["latency_lanes"] = {"serving_p99_ms": 4.0}
    verdict = run_gate(cur, HIST)
    lanes = {ln["lane"]: ln for ln in verdict["lanes"]}
    assert lanes["latency:serving_p99_ms"]["status"] == "skipped"
    assert "trajectory start" in lanes["latency:serving_p99_ms"]["note"]
    assert verdict["verdict"] == "pass"


def test_regression_latency_ratio_is_configurable():
    from benchmark.regression import run_gate

    def lat_rec(value, p99):
        rec = _bench_record(value)
        rec["latency_lanes"] = {"serving_p99_ms": p99}
        return rec

    hist = [lat_rec(100_000.0, 5.0)]
    strict = run_gate(lat_rec(100_000.0, 6.0), hist, max_latency_ratio=1.1)
    assert strict["verdict"] == "fail"
    loose = run_gate(lat_rec(100_000.0, 6.0), hist, max_latency_ratio=2.0)
    assert loose["verdict"] == "pass"


def test_regression_new_lanes_start_their_own_trajectory():
    # the first artifact carrying per-lane values (kmeans_scale/knn joining
    # the geomean) must NOT false-fail against history that lacks them: the
    # geomean lane is skipped (different composition), the per-lane gates
    # are skipped (trajectory start), and the counter lanes still run
    from benchmark.regression import run_gate

    cur = _bench_record(80_000.0, {"ingest.rows": 1e6, "ingest.datasets": 2})
    cur["lanes"] = {"pca": 1e6, "kmeans": 1e5, "kmeans_scale": 3e6, "knn": 5e4}
    verdict = run_gate(cur, HIST)
    lanes = {ln["lane"]: ln for ln in verdict["lanes"]}
    assert lanes["throughput_geomean"]["status"] == "skipped"
    assert "new" in lanes["throughput_geomean"]["note"]
    for name in ("pca", "kmeans", "kmeans_scale", "knn"):
        assert lanes[f"lane:{name}"]["status"] == "skipped"
        assert "trajectory start" in lanes[f"lane:{name}"]["note"]
    assert lanes["ingest.rows"]["status"] == "pass"
    assert verdict["verdict"] == "pass"


def test_regression_per_lane_gate_catches_single_lane_slowdown():
    # once two runs share the lane composition: a 2x slowdown in ONE lane
    # fails its per-lane gate even when the other lanes lift the geomean
    from benchmark.regression import run_gate

    def lane_rec(value, lanes):
        rec = _bench_record(value)
        rec["lanes"] = dict(lanes)
        return rec

    hist = [
        lane_rec(100_000.0, {"kmeans_scale": 3e6, "knn": 5e4}),
        lane_rec(101_000.0, {"kmeans_scale": 3e6, "knn": 5e4}),
    ]
    cur = lane_rec(102_000.0, {"kmeans_scale": 6e6, "knn": 2e4})  # knn halved
    verdict = run_gate(cur, hist)
    lanes = {ln["lane"]: ln for ln in verdict["lanes"]}
    assert lanes["throughput_geomean"]["status"] == "pass"  # same composition
    assert lanes["lane:kmeans_scale"]["status"] == "pass"
    assert lanes["lane:knn"]["status"] == "fail"
    assert verdict["verdict"] == "fail"
    assert "lane:knn" in verdict["failed_lanes"]


def test_regression_optional_extra_lane_does_not_skip_geomean_gate():
    # BENCH_OOCORE toggled on for one round adds an EXTRA embedded lane but
    # the geomean composition (geomean_lanes) is unchanged — the headline
    # gate must still run (and fail here: 2x slowdown), while the extra
    # lane just starts its own trajectory
    from benchmark.regression import run_gate

    def rec(value, extras=None):
        r = _bench_record(value)
        r["lanes"] = {"pca": 1e6, "kmeans": 1e5}
        r["lanes"].update(extras or {})
        r["geomean_lanes"] = ["kmeans", "pca"]
        return r

    hist = [rec(100_000.0), rec(101_000.0)]
    verdict = run_gate(rec(50_000.0, extras={"oocore_stream": 7e4}), hist)
    lanes = {ln["lane"]: ln for ln in verdict["lanes"]}
    assert lanes["throughput_geomean"]["status"] == "fail"
    assert lanes["lane:oocore_stream"]["status"] == "skipped"
    assert "trajectory start" in lanes["lane:oocore_stream"]["note"]
    assert verdict["verdict"] == "fail"


def test_regression_gate_incomplete_run_is_no_data_not_failure():
    from benchmark.regression import run_gate

    verdict = run_gate(_bench_record(0.0, incomplete=True), HIST)
    assert verdict["verdict"] == "no-data"
    # and incomplete runs never poison the reference either
    verdict2 = run_gate(
        _bench_record(102_000.0), HIST + [_bench_record(0.0, incomplete=True)]
    )
    assert verdict2["verdict"] == "pass"
    assert verdict2["reference_runs"] == 3


def test_regression_gate_cli_and_exit_codes(tmp_path):
    from benchmark.regression import main

    # wrap like the round driver does ({"parsed": <record>}) + one bare file
    for i, rec in enumerate(HIST, start=1):
        with open(tmp_path / f"BENCH_r{i:02d}.json", "w") as f:
            json.dump({"n": i, "rc": 0, "parsed": rec}, f)
    with open(tmp_path / "BENCH_r04.json", "w") as f:
        json.dump(_bench_record(50_000.0), f)  # bare record, 2x slowdown
    assert main(["--root", str(tmp_path), "--report-only"]) == 0  # reports, never gates
    assert main(["--root", str(tmp_path)]) == 1  # strict mode fails
    out = tmp_path / "verdict.json"
    assert main(["--root", str(tmp_path), "--report-only", "--out", str(out)]) == 0
    verdict = json.loads(out.read_text())
    assert verdict["verdict"] == "fail" and verdict["current_artifact"] == "BENCH_r04.json"
    # numeric round ordering: r10 sorts after r04, not between r01/r02
    with open(tmp_path / "BENCH_r10.json", "w") as f:
        json.dump(_bench_record(104_000.0), f)
    assert main(["--root", str(tmp_path)]) == 0


def test_regression_gate_no_artifacts_is_no_data(tmp_path):
    from benchmark.regression import main

    assert main(["--root", str(tmp_path)]) == 0


def test_checked_in_trajectory_passes_report_lane():
    # the ci/test.sh lane must hold on the real repo artifacts
    from benchmark.regression import main

    assert main(["--root", REPO, "--report-only"]) == 0


# ------------------------------------------------------------ bench satellite


def test_bench_emit_embeds_attempt_phase_history(capsys):
    import bench

    attempts = [{"attempt": 1, "rc": -1, "elapsed_s": 240.0,
                 "ran": ["pca"], "phases": [{"phase": "backend-init", "t_s": 0.1}]}]
    bench.emit({}, None, attempts)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["attempts"] == attempts
    assert rec["value"] == 0.0  # degraded emission still explains itself
