#
# LinearRegression compat tests vs sklearn across OLS / Ridge / Lasso / EN
# (reference tests/test_linear_regression.py pattern).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.models.regression import LinearRegression, LinearRegressionModel


def _data(rng, n=300, d=8, noise=0.1, dtype=np.float64):
    x = rng.normal(size=(n, d)).astype(dtype)
    true_coef = rng.normal(size=d)
    y = (x @ true_coef + 1.5 + noise * rng.normal(size=n)).astype(dtype)
    df = pd.DataFrame({"features": list(x), "label": y})
    return df, x, y, true_coef


def test_ols_vs_sklearn(rng):
    from sklearn.linear_model import LinearRegression as SkLR

    df, x, y, _ = _data(rng)
    model = LinearRegression(regParam=0.0, float32_inputs=False, num_workers=4).setFeaturesCol("features").fit(df)
    sk = SkLR().fit(x, y)
    np.testing.assert_allclose(model.coef_, sk.coef_, rtol=1e-6)
    np.testing.assert_allclose(model.intercept_, sk.intercept_, rtol=1e-6)
    out = model.transform(df)
    np.testing.assert_allclose(np.asarray(out["prediction"]), sk.predict(x), rtol=1e-6)


def test_ridge_spark_alpha_scaling(rng):
    # Spark objective 1/(2n)RSS + λ/2‖b‖² == sklearn Ridge(alpha=λ·n)
    from sklearn.linear_model import Ridge

    df, x, y, _ = _data(rng)
    lam = 1e-3
    model = (
        LinearRegression(regParam=lam, elasticNetParam=0.0, standardization=False, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(df)
    )
    sk = Ridge(alpha=lam * len(y)).fit(x, y)
    np.testing.assert_allclose(model.coef_, sk.coef_, rtol=1e-5)
    np.testing.assert_allclose(model.intercept_, sk.intercept_, rtol=1e-5)


def test_lasso_vs_sklearn(rng):
    from sklearn.linear_model import Lasso

    df, x, y, _ = _data(rng, n=500, d=10)
    lam = 0.05
    model = (
        LinearRegression(
            regParam=lam, elasticNetParam=1.0, standardization=False,
            maxIter=2000, tol=1e-10, float32_inputs=False,
        )
        .setFeaturesCol("features")
        .fit(df)
    )
    sk = Lasso(alpha=lam, max_iter=10000, tol=1e-12).fit(x, y)
    np.testing.assert_allclose(model.coef_, sk.coef_, atol=1e-5)
    np.testing.assert_allclose(model.intercept_, sk.intercept_, atol=1e-5)
    # sparsity induced
    assert np.sum(np.abs(model.coef_) < 1e-9) == np.sum(np.abs(sk.coef_) < 1e-9)


def test_elastic_net_vs_sklearn(rng):
    from sklearn.linear_model import ElasticNet

    df, x, y, _ = _data(rng, n=400, d=6)
    lam, l1r = 0.03, 0.5
    model = (
        LinearRegression(
            regParam=lam, elasticNetParam=l1r, standardization=False,
            maxIter=3000, tol=1e-10, float32_inputs=False,
        )
        .setFeaturesCol("features")
        .fit(df)
    )
    sk = ElasticNet(alpha=lam, l1_ratio=l1r, max_iter=10000, tol=1e-12).fit(x, y)
    np.testing.assert_allclose(model.coef_, sk.coef_, atol=1e-5)
    np.testing.assert_allclose(model.intercept_, sk.intercept_, atol=1e-5)


def test_no_intercept(rng):
    from sklearn.linear_model import LinearRegression as SkLR

    df, x, y, _ = _data(rng)
    model = (
        LinearRegression(fitIntercept=False, float32_inputs=False).setFeaturesCol("features").fit(df)
    )
    sk = SkLR(fit_intercept=False).fit(x, y)
    np.testing.assert_allclose(model.coef_, sk.coef_, rtol=1e-6)
    assert model.intercept_ == 0.0


def test_weighted_equals_duplication(rng):
    from sklearn.linear_model import LinearRegression as SkLR

    df, x, y, _ = _data(rng, n=60, d=4)
    w = rng.integers(1, 4, size=60).astype(np.float64)
    df["w"] = w
    model = (
        LinearRegression(float32_inputs=False).setFeaturesCol("features").setWeightCol("w").fit(df)
    )
    x_dup = np.repeat(x, w.astype(int), axis=0)
    y_dup = np.repeat(y, w.astype(int))
    sk = SkLR().fit(x_dup, y_dup)
    np.testing.assert_allclose(model.coef_, sk.coef_, rtol=1e-6)
    np.testing.assert_allclose(model.intercept_, sk.intercept_, rtol=1e-6)


def test_standardization_ridge_differs_but_predicts(rng):
    df, x, y, _ = _data(rng)
    m_std = LinearRegression(regParam=0.1, standardization=True, float32_inputs=False).setFeaturesCol("features").fit(df)
    m_raw = LinearRegression(regParam=0.1, standardization=False, float32_inputs=False).setFeaturesCol("features").fit(df)
    assert not np.allclose(m_std.coef_, m_raw.coef_)
    # both still predict reasonably
    for m in (m_std, m_raw):
        p = np.asarray(m.transform(df)["prediction"])
        assert np.corrcoef(p, y)[0, 1] > 0.95


def test_spark_params_surface(rng):
    lr = (
        LinearRegression()
        .setMaxIter(42)
        .setRegParam(0.2)
        .setElasticNetParam(0.3)
        .setTol(1e-9)
        .setStandardization(False)
        .setLabelCol("label")
        .setPredictionCol("pred_out")
        .setFeaturesCol("features")
    )
    assert lr.solver_params["max_iter"] == 42
    assert lr.solver_params["alpha"] == 0.2
    assert lr.solver_params["l1_ratio"] == 0.3
    assert lr.getOrDefault("predictionCol") == "pred_out"
    with pytest.raises(ValueError):
        lr._set_params(loss="huber")  # unsupported loss value

    df, x, y, _ = _data(rng, n=50, d=3)
    model = lr.fit(df)
    out = model.transform(df)
    assert "pred_out" in out.columns
    assert model.coefficients.size == 3
    assert isinstance(model.intercept, float)
    assert model.numFeatures == 3
    assert abs(model.predict(x[0]) - np.asarray(out["pred_out"])[0]) < 1e-5


def test_persistence(tmp_path, rng):
    df, x, y, _ = _data(rng, n=50, d=3)
    model = LinearRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    p = str(tmp_path / "lr")
    model.write().overwrite().save(p)
    loaded = LinearRegressionModel.load(p)
    np.testing.assert_array_equal(loaded.coef_, model.coef_)
    assert loaded.intercept_ == model.intercept_
    np.testing.assert_allclose(
        np.asarray(loaded.transform(df)["prediction"]),
        np.asarray(model.transform(df)["prediction"]),
    )


def test_fit_multiple_reg_paths(rng):
    df, x, y, _ = _data(rng)
    est = LinearRegression(standardization=False, float32_inputs=False).setFeaturesCol("features")
    pmaps = [
        {est.getParam("regParam"): 0.0},
        {est.getParam("regParam"): 0.1},
        {est.getParam("regParam"): 0.1, est.getParam("elasticNetParam"): 1.0},
    ]
    models = dict(est.fitMultiple(df, pmaps))
    assert len(models) == 3
    # more regularization shrinks coefficients
    assert np.linalg.norm(models[1].coef_) < np.linalg.norm(models[0].coef_)
    assert np.linalg.norm(models[2].coef_) < np.linalg.norm(models[0].coef_)


def _sparse_reg_df(rng, n=300, d=20, density=0.15):
    import scipy.sparse as sp

    from spark_rapids_ml_tpu.linalg import Vectors

    x = sp.random(n, d, density=density, random_state=np.random.RandomState(11), format="csr")
    xd = np.asarray(x.todense())
    coef = rng.normal(size=d)
    y = xd @ coef + 0.5 + 0.01 * rng.normal(size=n)
    rows = [Vectors.sparse(d, x[i].indices.tolist(), x[i].data.tolist()) for i in range(n)]
    return (
        pd.DataFrame({"features": rows, "label": y}),
        pd.DataFrame({"features": list(xd), "label": y}),
    )


@pytest.mark.parametrize(
    "kw",
    [
        dict(regParam=0.0),                                     # OLS
        dict(regParam=0.01),                                    # ridge
        dict(regParam=0.01, elasticNetParam=0.5, maxIter=2000), # CD elastic net
        dict(regParam=0.01, standardization=False),
        dict(regParam=0.0, fitIntercept=False),
    ],
)
def test_sparse_linear_matches_dense(rng, kw):
    # identical sufficient statistics -> identical solve: sparse == dense exactly
    df_sp, df_dn = _sparse_reg_df(rng)
    base = dict(float32_inputs=False, tol=1e-12)
    m_sp = LinearRegression(**base, **kw).setFeaturesCol("features").fit(df_sp)
    m_dn = LinearRegression(**base, **kw).setFeaturesCol("features").fit(df_dn)
    np.testing.assert_allclose(m_sp.coef_, m_dn.coef_, rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(m_sp.intercept_, m_dn.intercept_, rtol=1e-8, atol=1e-10)


@pytest.mark.slow
def test_sparse_linear_large_scale(rng):
    # the reference's headline sparse scale pattern (tests_large): 1e6 x 2000 at
    # ~0.1% density fits without densifying
    import scipy.sparse as sp

    n, d = 1_000_000, 2000
    x = sp.random(n, d, density=0.001, random_state=np.random.RandomState(3), format="csr", dtype=np.float32)
    coef = np.zeros(d, dtype=np.float32)
    coef[:50] = rng.normal(size=50)
    y = np.asarray(x @ coef) + 0.01 * rng.normal(size=n).astype(np.float32)
    # dict dataset with a whole CSR block: the at-scale ingest fast path
    m = (
        LinearRegression(regParam=0.001, maxIter=100)
        .setFeaturesCol("features")
        .fit({"features": x, "label": y})
    )
    err = np.abs(np.asarray(m.coef_[:50]) - coef[:50]).max()
    assert err < 0.05
