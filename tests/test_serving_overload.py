#
# Overload-resilient serving tests (docs/serving.md "Overload &
# backpressure"): server-side deadlines (expired requests NEVER dispatch),
# deadline-aware admission with its typed evidence-carrying refusals, the
# hysteresis-guarded backpressure ladder (no flapping), the degraded bf16
# rung's parity, adaptive batching's zero-window escape hatch, and the
# end-to-end burst scenario: healthy -> refusals -> recovery, every ladder
# verdict audited and zero over-deadline dispatches.
#
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu import core, telemetry
from spark_rapids_ml_tpu.errors import (
    RequestTimeoutError,
    ServeOverloadError,
    ServingStoppedError,
)
from spark_rapids_ml_tpu.models.clustering import KMeansModel
from spark_rapids_ml_tpu.ops_plane import audit as ops_audit
from spark_rapids_ml_tpu.ops_plane import slo as ops_slo
from spark_rapids_ml_tpu.parallel import chaos
from spark_rapids_ml_tpu.serving import ModelRegistry, ScoringEngine
from spark_rapids_ml_tpu.serving.overload import (
    LEVEL_DEGRADE,
    LEVEL_HEALTHY,
    LEVEL_SHED,
    LEVEL_THROTTLE,
    LEVELS,
    OverloadController,
    plan_target_rows,
    plan_window,
)


@pytest.fixture
def tele():
    """Enable telemetry with a fresh registry; restore after."""
    telemetry.registry().reset()
    telemetry.enable()
    yield telemetry.registry()
    telemetry.disable()
    telemetry.registry().reset()


@pytest.fixture
def overload_cfg():
    """Small ladder + overload knobs saved/restored around each test."""
    keys = (
        "transform_bucket_min_rows",
        "serve_prewarm_rows",
        "serve_max_batch_rows",
        "serve_coalesce_window_ms",
        "serve_default_deadline_ms",
        "serve_max_queue_rows",
        "serve_adaptive_batching",
        "serve_overload_hold_s",
        "serve_throttle_rows_per_s",
        "serve_degraded_dtype",
        "slo",
        "metrics_bucket_seconds",
        "metrics_bucket_count",
    )
    saved = {k: core.config[k] for k in keys}
    core.config["transform_bucket_min_rows"] = 8
    core.config["serve_prewarm_rows"] = 64
    core.config["serve_max_batch_rows"] = 256
    core.config["serve_coalesce_window_ms"] = 5.0
    core.config["slo"] = []
    yield
    core.config.update(saved)
    ops_slo.reset()


@pytest.fixture(autouse=True)
def _clean_chaos():
    yield
    chaos.clear_fault_plan()


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _kmeans_model(rng, k=4, d=8, scale=10.0):
    centers = (rng.standard_normal((k, d)) * scale).astype(np.float32)
    return KMeansModel(cluster_centers_=centers, n_cols=d, dtype="float32")


def _feats(rng, n, d=8):
    return rng.standard_normal((n, d)).astype(np.float32)


# ------------------------------------------------- the batching planners ----


def test_plan_window_zero_base_disables_coalescing():
    # an explicit zero window means NO coalescing, adaptive or not
    assert plan_window(
        0.0, floor_s=0.001, ceiling_s=0.02, arrival_rows_per_s=1e6,
        queue_rows=10_000, queue_wait_p99_s=10.0, max_rows=256,
    ) == 0.0


def test_plan_window_uncongested_is_exactly_static():
    # static values are overrides, not hints: no congestion evidence (p99
    # absent, or at/under the static window) returns base EXACTLY
    for p99 in (None, 0.0, 0.002):
        assert plan_window(
            0.002, floor_s=0.0005, ceiling_s=0.02, arrival_rows_per_s=500.0,
            queue_rows=10, queue_wait_p99_s=p99, max_rows=256,
        ) == 0.002


def test_plan_window_congested_full_queue_hits_floor():
    # a queue already holding a full batch gains nothing from waiting
    assert plan_window(
        0.002, floor_s=0.0005, ceiling_s=0.02, arrival_rows_per_s=500.0,
        queue_rows=256, queue_wait_p99_s=1.0, max_rows=256,
    ) == 0.0005


def test_plan_window_congested_grows_to_fill_time_clamped():
    # congested, queue half full: window = time to fill the batch at the
    # observed arrival rate, clamped to [base, ceiling]
    w = plan_window(
        0.002, floor_s=0.0005, ceiling_s=0.02, arrival_rows_per_s=12_800.0,
        queue_rows=128, queue_wait_p99_s=1.0, max_rows=256,
    )
    assert w == pytest.approx(128 / 12_800.0)  # 10ms, inside [2ms, 20ms]
    # slow arrivals clamp at the ceiling
    assert plan_window(
        0.002, floor_s=0.0005, ceiling_s=0.02, arrival_rows_per_s=100.0,
        queue_rows=0, queue_wait_p99_s=1.0, max_rows=256,
    ) == 0.02


def test_plan_target_rows_rungs():
    # uncongested: the window, not the target, bounds the batch
    assert plan_target_rows(
        min_rows=8, max_rows=256, queue_rows=10, arrival_rows_per_s=None,
        window_s=0.002, congested=False,
    ) == 256
    # congested: the geometric rung covering backlog + one window's arrivals
    assert plan_target_rows(
        min_rows=8, max_rows=256, queue_rows=20, arrival_rows_per_s=1000.0,
        window_s=0.01, congested=True,
    ) == 32  # 20 + 10 = 30 -> rung 32
    assert plan_target_rows(
        min_rows=8, max_rows=256, queue_rows=10_000, arrival_rows_per_s=None,
        window_s=0.01, congested=True,
    ) == 256


# ----------------------------------------------------- deadline semantics ---


def test_expired_deadline_fails_fast_and_never_dispatches(tele, overload_cfg, rng):
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    # window 0: no coalescing, so the delayed first request cannot absorb
    # the short-deadline second one
    chaos.set_fault_plan("delay:stage=serve:seconds=0.25:times=1")
    with ScoringEngine(registry, coalesce_window_s=0.0) as engine:
        a = engine.submit("km", _feats(rng, 4))
        b = engine.submit("km", _feats(rng, 4), deadline_ms=100.0)
        assert a.result(timeout=10.0) is not None
        with pytest.raises(RequestTimeoutError) as ei:
            b.result(timeout=10.0)
    err = ei.value
    assert err.model == "km"
    assert err.deadline_ms == pytest.approx(100.0, rel=0.05)
    assert err.waited_ms >= err.deadline_ms
    snap = tele.snapshot()["counters"]
    assert snap["serve.expired_requests"] == 1
    # only the healthy request dispatched, and the tripwire stayed silent
    assert snap["serve.batches"] == 1
    assert snap.get("serve.overdeadline_dispatches", 0) == 0


def test_deadline_defaults_and_zero_disables(tele, overload_cfg, rng):
    core.config["serve_default_deadline_ms"] = 5000.0
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    with ScoringEngine(registry) as engine:
        t0 = time.monotonic()
        fut = engine.submit("km", _feats(rng, 2))
        assert fut.deadline is not None
        assert fut.deadline - t0 == pytest.approx(5.0, abs=0.5)
        # deadline_ms <= 0 disables the server-side deadline entirely
        assert engine.submit("km", _feats(rng, 2), deadline_ms=0).deadline is None


def test_admission_rejects_infeasible_deadline_with_evidence(tele, overload_cfg, rng):
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    # seed the windowed queue-wait p99 far above the request's deadline:
    # admission must refuse synchronously, with the prediction as evidence
    for _ in range(8):
        tele.observe("serve.queue_wait_s", 5.0)
    with ScoringEngine(registry) as engine:
        with pytest.raises(ServeOverloadError) as ei:
            engine.submit("km", _feats(rng, 4), deadline_ms=100.0)
    err = ei.value
    assert err.model == "km"
    assert err.level == "healthy"  # refused by prediction, not the ladder
    assert err.predicted_wait_ms is not None and err.predicted_wait_ms > 100.0
    assert err.deadline_ms == pytest.approx(100.0, rel=0.05)
    assert tele.snapshot()["counters"]["serve.rejected_requests"] == 1


def test_admission_bounded_queue_refuses(tele, overload_cfg, rng):
    core.config["serve_max_queue_rows"] = 4
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    with ScoringEngine(registry) as engine:
        with pytest.raises(ServeOverloadError) as ei:
            engine.submit("km", _feats(rng, 8))
    assert "queue is full" in str(ei.value)
    assert ei.value.queue_rows == 0
    assert tele.snapshot()["counters"]["serve.rejected_requests"] == 1


# ------------------------------------------------------------- the ladder ---


def _spec(**over):
    spec = {
        "name": "serving_p99", "kind": "latency", "histogram": "serve.e2e_s",
        "threshold_s": 0.1, "objective": 0.5, "fast_window_s": 1.0,
        "fast_burn": 1.0,
    }
    spec.update(over)
    return spec


def test_ladder_hysteresis_one_rung_per_hold_no_flap(overload_cfg):
    core.config["serve_overload_hold_s"] = 10.0
    ops_slo.reset()
    ctl = OverloadController()
    # create the tenant through the public admission path
    ctl.admit(
        model="m", tenant="acme", rows=1, deadline_s=None, now=0.0,
        queue_depth=0, queue_rows=0,
    )
    burn = {"v": 5.0}
    ctl._tenant_burn = lambda tenant, spec: burn["v"]  # the scripting seam
    audited_before = len(ops_audit.decisions(kind="backpressure", tenant="acme"))
    spec = _spec()

    def level():
        return ctl.level("acme")

    ctl.evaluate(spec, now=0.0)
    assert level() == LEVEL_THROTTLE  # healthy escalates without dwell
    ctl.evaluate(spec, now=5.0)
    assert level() == LEVEL_THROTTLE  # still burning, but inside the hold
    ctl.evaluate(spec, now=11.0)
    assert level() == LEVEL_DEGRADE  # one rung per dwell
    burn["v"] = 0.0
    ctl.evaluate(spec, now=15.0)
    assert level() == LEVEL_DEGRADE  # clear, but inside the hold: no flap
    ctl.evaluate(spec, now=22.0)
    assert level() == LEVEL_THROTTLE  # restore one rung per dwell
    ctl.evaluate(spec, now=23.0)
    assert level() == LEVEL_THROTTLE  # no flap on the way down either
    ctl.evaluate(spec, now=33.0)
    assert level() == LEVEL_HEALTHY
    # every transition audited, in order, with the restore verdicts
    events = ops_audit.decisions(kind="backpressure", tenant="acme")
    new = events[audited_before:]
    assert [e["verdict"] for e in new] == [
        "throttle", "degrade", "restore", "restore",
    ]
    assert ctl.stats()["acme"]["transitions"] == 4


def test_ladder_empty_burn_window_is_not_burning(overload_cfg):
    # no traffic in the fast window -> burn None -> never escalates (an
    # idle tenant is not an overloaded tenant)
    core.config["serve_overload_hold_s"] = 0.0
    ops_slo.reset()
    ctl = OverloadController()
    ctl.admit(
        model="m", tenant="idle", rows=1, deadline_s=None, now=0.0,
        queue_depth=0, queue_rows=0,
    )
    ctl._tenant_burn = lambda tenant, spec: None
    ctl.evaluate(_spec(), now=1.0)
    assert ctl.level("idle") == LEVEL_HEALTHY


def test_throttle_token_bucket_meters_and_refills(overload_cfg):
    core.config["serve_throttle_rows_per_s"] = 100.0
    ctl = OverloadController()
    ctl.admit(
        model="m", tenant="t", rows=1, deadline_s=None, now=0.0,
        queue_depth=0, queue_rows=0,
    )
    ctl.force_level("t", LEVEL_THROTTLE)

    def admit(rows, now):
        return ctl.admit(
            model="m", tenant="t", rows=rows, deadline_s=None, now=now,
            queue_depth=0, queue_rows=0,
        )

    # first fill is one second of rate (100 rows): two 40-row takes pass,
    # the third finds 20 tokens and is refused with the typed evidence
    admit(40, 1.0)
    admit(40, 1.0)
    with pytest.raises(ServeOverloadError) as ei:
        admit(40, 1.0)
    assert ei.value.level == "throttle"
    assert ei.value.tenant == "t"
    # half a second refills 50 tokens: the same request now passes
    admit(40, 1.5)
    assert ctl.stats()["t"]["throttled_requests"] == 1


def test_degraded_rung_routes_to_bf16_with_parity(tele, overload_cfg, rng):
    # well-separated centers: bf16 rounding cannot flip assignments, so the
    # degraded rung's output must MATCH a reference engine serving bf16 as
    # its primary dtype
    core.config["serve_degraded_dtype"] = "bf16"
    # degrade sits ABOVE throttle on the ladder, so its admissions are
    # still token-metered; a generous rate keeps this a pure parity test
    core.config["serve_throttle_rows_per_s"] = 1e9
    model = _kmeans_model(rng, scale=50.0)
    feats = _feats(rng, 32)
    registry = ModelRegistry()
    entry = registry.load("km", model)
    assert entry.degraded_program is not None
    ref_registry = ModelRegistry()
    ref_registry.load("km16", model, serve_dtype="bf16")
    with ScoringEngine(ref_registry) as ref_engine:
        expect = ref_engine.score("km16", feats)
    with ScoringEngine(registry) as engine:
        engine._overload.force_level("default", LEVEL_DEGRADE)
        got = engine.score("km", feats)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expect))
    snap = tele.snapshot()["counters"]
    assert snap["serve.degraded_requests"] >= 1
    assert snap["serve.degraded_rows"] >= 32


def test_shed_refuses_outright(tele, overload_cfg, rng):
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    with ScoringEngine(registry) as engine:
        engine._overload.force_level("default", LEVEL_SHED)
        with pytest.raises(ServeOverloadError) as ei:
            engine.submit("km", _feats(rng, 4))
    assert ei.value.level == "shed"
    assert tele.snapshot()["counters"]["serve.shed_requests"] == 1


# ------------------------------------------------------- adaptive batching --


def test_zero_window_disables_coalescing_under_adaptive(tele, overload_cfg, rng):
    core.config["serve_coalesce_window_ms"] = 0.0
    core.config["serve_adaptive_batching"] = True
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    # a per-dispatch delay queues the later requests behind the first:
    # WITH coalescing they would merge; the zero window must dispatch solo
    chaos.set_fault_plan("delay:stage=serve:seconds=0.05:times=4")
    with ScoringEngine(registry) as engine:
        futs = [engine.submit("km", _feats(rng, 4)) for _ in range(4)]
        for f in futs:
            f.result(timeout=10.0)
    snap = tele.snapshot()["counters"]
    assert snap["serve.batches"] == 4
    assert snap.get("serve.coalesced_batches", 0) == 0


# ----------------------------------------------- stop() + stats + report ----


def test_stop_fails_pending_futures_typed(overload_cfg, rng):
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    # every dispatch sleeps 0.5s; window 0 so the queued requests cannot
    # merge into the in-flight batch
    chaos.set_fault_plan("delay:stage=serve:seconds=0.5:times=1000")
    engine = ScoringEngine(registry, coalesce_window_s=0.0).start()
    worker = engine._thread
    try:
        engine.submit("km", _feats(rng, 4))
        b = engine.submit("km", _feats(rng, 4))
        c = engine.submit("km", _feats(rng, 4))
        engine.stop(timeout=0.05)  # drain deadline elapses mid-dispatch
        for pos, fut in ((0, b), (1, c)):
            with pytest.raises(ServingStoppedError) as ei:
                fut.result(timeout=1.0)
            assert ei.value.model == "km"
            assert ei.value.queue_position == pos
    finally:
        chaos.clear_fault_plan()
        if worker is not None:
            worker.join(5.0)  # let the in-flight dispatch finish


def test_stats_and_ops_report_surface_tenants(tele, overload_cfg, rng):
    from spark_rapids_ml_tpu import ops_plane
    from benchmark import opsreport

    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    with ScoringEngine(registry) as engine:
        for _ in range(3):
            engine.submit("km", _feats(rng, 8), tenant="acme").result(timeout=10.0)
        stats = engine.stats()
    assert stats["queue_depth"] == 0 and stats["queue_rows"] == 0
    acme = stats["tenants"]["acme"]
    assert acme["level"] == "healthy"
    assert acme["queue_wait_p99_s"] is not None
    assert acme["e2e_p50_s"] is not None
    for key in ("shed_requests", "throttled_requests", "degraded_requests"):
        assert acme[key] == 0
    report = ops_plane.report()
    assert "acme" in report["serving"]["tenants"]
    assert report["serving"]["tenants"]["acme"]["level"] == "healthy"
    rendered = opsreport.render(report)
    assert "backpressure ladder" in rendered
    assert "acme" in rendered


# ------------------------------------------------------------ e2e burst -----


def test_burst_escalates_audits_and_recovers(tele, overload_cfg, rng):
    """The saturation story end to end, at test scale: a chaos-planned
    burst drives a healthy tenant into refusals, every ladder verdict lands
    in the audit log, no expired request ever dispatches, and clearing the
    load restores the tenant to healthy through the submit-path hook (a
    fully-refused tenant generates no dispatches)."""
    core.config["metrics_bucket_seconds"] = 0.2
    core.config["metrics_bucket_count"] = 20
    telemetry.registry().reset()  # window params rebind at first record
    core.config["serve_max_batch_rows"] = 16
    core.config["serve_coalesce_window_ms"] = 2.0
    core.config["serve_overload_hold_s"] = 0.25
    core.config["serve_default_deadline_ms"] = 600.0
    core.config["slo"] = [_spec(threshold_s=0.25, fast_window_s=0.6)]
    model = _kmeans_model(rng)
    registry = ModelRegistry()
    registry.load("km", model)
    audited_before = len(ops_audit.decisions(kind="backpressure"))
    # service pinned at 20ms/dispatch (capacity ~800 rows/s at 16-row
    # batches); the chaos plan declares the burst's load shape
    chaos.set_fault_plan(
        "delay:stage=serve:seconds=0.02:times=100000;"
        "burst:stage=serve:rows=2000:seconds=1"
    )
    fault = chaos.maybe_burst_stage("serve")
    assert fault is not None
    refusals = []
    futs = []
    with ScoringEngine(registry) as engine:
        req_rows = 16
        t_next = time.monotonic()
        t_end = t_next + fault.seconds
        while time.monotonic() < t_end:
            try:
                futs.append(engine.submit("km", _feats(rng, req_rows)))
            except ServeOverloadError as e:
                refusals.append(e)
            t_next += req_rows / fault.rows
            delay = t_next - time.monotonic()
            if delay > 0:
                time.sleep(delay)
        outcomes = {"ok": 0, "expired": 0}
        for f in futs:
            try:
                f.result(timeout=10.0)
                outcomes["ok"] += 1
            except RequestTimeoutError:
                outcomes["expired"] += 1
        # recovery: lift the injected service delay and offer light load;
        # even a fully-shed tenant must walk back down (admission refusals
        # still advance the ladder via the submit-path hook)
        chaos.clear_fault_plan()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            try:
                engine.submit(
                    "km", _feats(rng, 4), deadline_ms=5000.0
                ).result(timeout=10.0)
            except ServeOverloadError as e:
                refusals.append(e)
            if engine.stats()["tenants"]["default"]["level"] == "healthy":
                break
            time.sleep(0.05)
        final = engine.stats()
    snap = tele.snapshot()["counters"]
    # the ladder engaged: transitions happened and at least one request was
    # refused or expired while the burst ran
    transitions = int(snap["serve.backpressure_transitions"])
    assert transitions >= 2  # at least one escalation and one restore
    pressure = (
        len(refusals)
        + outcomes["expired"]
        + int(snap.get("serve.rejected_requests", 0))
    )
    assert pressure > 0
    assert outcomes["ok"] > 0  # the burst did not collapse service entirely
    for e in refusals:
        assert e.level in LEVELS
    # the deadline contract held under saturation
    assert snap.get("serve.overdeadline_dispatches", 0) == 0
    # every verdict audited: the decision log grew by exactly the
    # transition count
    audited = ops_audit.decisions(kind="backpressure")[audited_before:]
    assert len(audited) == transitions
    assert {a["verdict"] for a in audited} <= set(LEVELS[1:]) | {"restore"}
    # ...and the tenant walked back to healthy
    assert final["tenants"]["default"]["level"] == "healthy"
