#
# Mixed-precision solver contract (docs/performance.md "Mixed-precision
# solvers"): per-solver bf16==f32 parity at the documented tolerances
# (dense + padded-ELL, resident + streaming), the `solver_precision`
# resolution ladder (estimator param > config > "f32" default, invalid
# values raise, choices are counted), warm starts across precisions,
# ":bf16" checkpoint keying-apart, and the numcheck acceptance: bf16 fits
# sweep clean under SRML_NUMCHECK=1 and no solver-STATE stage ever
# watermarks a bfloat16 — only the dot/einsum INPUTS narrow.
#
import numpy as np
import pandas as pd
import pytest

import jax.numpy as jnp

from spark_rapids_ml_tpu import checkpoint as ckpt
from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu import diagnostics, telemetry
from spark_rapids_ml_tpu.core import resolve_solver_precision
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.clustering import KMeans
from spark_rapids_ml_tpu.models.feature import PCA
from spark_rapids_ml_tpu.models.regression import LinearRegression
from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit
from spark_rapids_ml_tpu.ops.linear import linear_fit, linear_fit_ell
from spark_rapids_ml_tpu.ops.logistic import logistic_fit, logistic_fit_ell
from spark_rapids_ml_tpu.ops.pca import pca_fit, pca_fit_checkpointed
from spark_rapids_ml_tpu.parallel.mesh import get_mesh
from spark_rapids_ml_tpu.utils import numcheck

_KEYS = (
    "solver_precision", "hbm_budget_bytes", "hbm_headroom_fraction",
    "stream_chunk_rows", "checkpoint_every_iters",
)


@pytest.fixture
def prec():
    """Config + telemetry isolation for precision tests (the test_oocore
    fixture discipline): solver_precision and the streaming-budget knobs are
    restored exactly, counters start from zero."""
    saved = {k: core_mod.config[k] for k in _KEYS}
    telemetry.enable()
    telemetry.registry().reset()
    yield core_mod.config
    core_mod.config.update(saved)
    telemetry.disable()
    telemetry.registry().reset()


def _budget(budget, chunk=512):
    core_mod.config["hbm_budget_bytes"] = budget
    core_mod.config["stream_chunk_rows"] = chunk if budget else 0


def _counters():
    return telemetry.registry().snapshot()["counters"]


def _full_ell(x):
    """A dense matrix in padded-ELL clothing: every row stores all d values."""
    n, d = x.shape
    values = jnp.asarray(x)
    indices = jnp.asarray(np.tile(np.arange(d, dtype=np.int32), (n, 1)))
    return values, indices


def _blobs(rng, n=1200, d=6, k=4, dtype=np.float64):
    centers = rng.normal(scale=10.0, size=(k, d))
    x = centers[rng.integers(0, k, size=n)] + rng.normal(size=(n, d))
    return x.astype(dtype), centers.astype(dtype)


# ------------------------------------------------ resolution ladder ---------


def test_resolve_default_is_f32(prec):
    prec["solver_precision"] = "f32"
    assert resolve_solver_precision() == "f32"
    assert resolve_solver_precision({}) == "f32"
    assert resolve_solver_precision({"solver_precision": None}) == "f32"


def test_resolve_config_then_param_override(prec):
    prec["solver_precision"] = "bf16"
    assert resolve_solver_precision() == "bf16"
    # the per-estimator override beats the config-wide default, both ways
    assert resolve_solver_precision({"solver_precision": "f32"}) == "f32"
    prec["solver_precision"] = "f32"
    assert resolve_solver_precision({"solver_precision": "bf16"}) == "bf16"
    # case-normalized
    assert resolve_solver_precision({"solver_precision": "BF16"}) == "bf16"


def test_resolve_invalid_raises(prec):
    with pytest.raises(ValueError, match="solver_precision"):
        resolve_solver_precision({"solver_precision": "fp16"})
    prec["solver_precision"] = "float64"
    with pytest.raises(ValueError, match="solver_precision"):
        resolve_solver_precision()


def test_resolve_counts_choices(prec):
    prec["solver_precision"] = "f32"
    resolve_solver_precision()
    resolve_solver_precision({"solver_precision": "bf16"})
    resolve_solver_precision({"solver_precision": "bf16"})
    snap = _counters()
    assert snap["fit.precision_f32"] == 1
    assert snap["fit.precision_bf16"] == 2


# ------------------------------------------- ops-level parity: GLMs ---------


def test_linear_dense_bf16_parity(rng):
    x = rng.normal(size=(500, 8))
    y = x @ rng.normal(size=8) + 0.5 + 0.01 * rng.normal(size=500)
    w = np.ones(500)
    kw = dict(alpha=1e-3, l1_ratio=0.0)
    full = linear_fit(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w), **kw)
    fast = linear_fit(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w), fast=True, **kw)
    # the cast actually happened: bf16 statistics cannot be bitwise f64 ones
    assert not np.array_equal(np.asarray(fast["coef_"]), np.asarray(full["coef_"]))
    np.testing.assert_allclose(
        np.asarray(fast["coef_"]), np.asarray(full["coef_"]), rtol=5e-3, atol=5e-4
    )
    np.testing.assert_allclose(
        float(fast["intercept_"]), float(full["intercept_"]), atol=5e-3
    )


def test_linear_ell_bf16_parity(rng):
    x = rng.normal(size=(400, 6))
    x = np.where(np.abs(x) > 0.6, x, 0.0)  # sparse-ish but stored full-ELL
    y = x @ rng.normal(size=6) - 0.25 + 0.01 * rng.normal(size=400)
    w = np.ones(400)
    values, indices = _full_ell(x)
    kw = dict(d=6, alpha=1e-3, l1_ratio=0.0)
    full = linear_fit_ell(values, indices, jnp.asarray(y), jnp.asarray(w), **kw)
    fast = linear_fit_ell(values, indices, jnp.asarray(y), jnp.asarray(w), fast=True, **kw)
    np.testing.assert_allclose(
        np.asarray(fast["coef_"]), np.asarray(full["coef_"]), rtol=5e-3, atol=5e-4
    )


@pytest.mark.parametrize("family_k", [2, 3], ids=["binomial", "multinomial"])
def test_logistic_dense_bf16_parity(rng, family_k):
    x = rng.normal(size=(600, 6))
    if family_k == 2:
        y = (x @ rng.normal(size=6) > 0).astype(np.int32)
    else:
        y = rng.integers(0, family_k, size=600).astype(np.int32)
    w = np.ones(600)
    kw = dict(k=family_k, multinomial=family_k > 2, lam_l2=0.01, max_iter=80, tol=1e-9)
    full = logistic_fit(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w), **kw)
    fast = logistic_fit(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w), fast=True, **kw)
    np.testing.assert_allclose(
        np.asarray(fast["coef_"]), np.asarray(full["coef_"]), rtol=5e-2, atol=5e-3
    )
    np.testing.assert_allclose(
        float(fast["objective_"]), float(full["objective_"]), rtol=1e-3
    )


def test_logistic_ell_bf16_parity(rng):
    x = rng.normal(size=(500, 6))
    x = np.where(np.abs(x) > 0.6, x, 0.0)
    y = (x @ rng.normal(size=6) > 0).astype(np.int32)
    w = np.ones(500)
    values, indices = _full_ell(x)
    kw = dict(d=6, k=2, multinomial=False, lam_l2=0.01, max_iter=80, tol=1e-9)
    full = logistic_fit_ell(values, indices, jnp.asarray(y), jnp.asarray(w), **kw)
    fast = logistic_fit_ell(values, indices, jnp.asarray(y), jnp.asarray(w), fast=True, **kw)
    np.testing.assert_allclose(
        np.asarray(fast["coef_"]), np.asarray(full["coef_"]), rtol=5e-2, atol=5e-3
    )


# ----------------------------------------- ops-level parity: PCA/kmeans -----


def test_pca_bf16_parity(rng):
    x = rng.normal(size=(800, 6)) @ np.diag([5.0, 4.0, 3.0, 0.5, 0.2, 0.1])
    w = np.ones(800)
    full = pca_fit(jnp.asarray(x), jnp.asarray(w), k=3)
    fast = pca_fit(jnp.asarray(x), jnp.asarray(w), k=3, fast=True)
    np.testing.assert_allclose(
        np.asarray(fast["explained_variance_"]),
        np.asarray(full["explained_variance_"]),
        rtol=2e-3,
    )
    # sign-tolerant component parity (sign_flip picks the max-abs element's
    # sign; a bf16-perturbed near-tie may legitimately flip a row)
    np.testing.assert_allclose(
        np.abs(np.asarray(fast["components_"])),
        np.abs(np.asarray(full["components_"])),
        atol=5e-3,
    )


def test_kmeans_fast_vs_high_parity(rng):
    x, _ = _blobs(rng, dtype=np.float32)
    w = np.ones(len(x), dtype=np.float32)
    init = x[:4].copy()
    kw = dict(mesh=get_mesh(), max_iter=20, tol=1e-6)
    full = kmeans_fit(jnp.asarray(x), jnp.asarray(w), jnp.asarray(init),
                      precision_mode="high", **kw)
    fast = kmeans_fit(jnp.asarray(x), jnp.asarray(w), jnp.asarray(init),
                      precision_mode="fast", **kw)
    np.testing.assert_allclose(
        np.asarray(fast["cluster_centers_"]),
        np.asarray(full["cluster_centers_"]),
        rtol=1e-3, atol=5e-3,
    )
    # final inertia always reruns at full precision — close AND finite
    assert np.isfinite(float(fast["inertia_"]))
    np.testing.assert_allclose(
        float(fast["inertia_"]), float(full["inertia_"]), rtol=1e-3
    )


def test_kmeans_fast_gated_to_f32(rng):
    # f64 inputs disable the bf16 path entirely: "fast" must be bitwise "high"
    x, _ = _blobs(rng, n=600, dtype=np.float64)
    w = np.ones(len(x))
    init = x[:4].copy()
    kw = dict(mesh=get_mesh(), max_iter=10, tol=1e-6)
    full = kmeans_fit(jnp.asarray(x), jnp.asarray(w), jnp.asarray(init),
                      precision_mode="high", **kw)
    fast = kmeans_fit(jnp.asarray(x), jnp.asarray(w), jnp.asarray(init),
                      precision_mode="fast", **kw)
    np.testing.assert_array_equal(
        np.asarray(fast["cluster_centers_"]), np.asarray(full["cluster_centers_"])
    )


# ------------------------------------------- estimator-level contract -------


def test_estimator_param_beats_config(prec, rng):
    x = rng.normal(size=(400, 5))
    y = x @ rng.normal(size=5) + 0.1
    df = pd.DataFrame({"features": list(x), "label": y})
    prec["solver_precision"] = "bf16"
    LinearRegression(regParam=1e-3).setFeaturesCol("features").fit(df)
    assert _counters()["fit.precision_bf16"] == 1
    # per-estimator f32 override under a bf16 config-wide default
    LinearRegression(regParam=1e-3, solver_precision="f32").setFeaturesCol("features").fit(df)
    assert _counters()["fit.precision_f32"] == 1


def _assert_streamed(model):
    adm = model._fit_metrics["admission"]
    assert adm["verdict"] == "stream"


def test_linear_streaming_bf16_matches_resident_bf16(prec, rng):
    x = rng.normal(size=(2000, 6))
    y = x @ rng.normal(size=6) + 0.5 + 0.05 * rng.normal(size=2000)
    df = pd.DataFrame({"features": list(x), "label": y})
    est = lambda: LinearRegression(  # noqa: E731
        regParam=1e-3, solver_precision="bf16", float32_inputs=False
    ).setFeaturesCol("features")
    _budget(None)
    res = est().fit(df)
    _budget(12_000)
    stream = est().fit(df)
    _assert_streamed(stream)
    # both sides round the SAME elements through bf16; only the f64
    # accumulation order differs between chunked and resident statistics
    np.testing.assert_allclose(stream.coef_, res.coef_, rtol=1e-6)
    np.testing.assert_allclose(stream.intercept_, res.intercept_, rtol=1e-6)


def test_logistic_streaming_bf16_matches_resident_bf16(prec, rng):
    x = rng.normal(size=(2000, 6))
    y = (x @ rng.normal(size=6) > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(x), "label": y})
    est = lambda: LogisticRegression(  # noqa: E731
        regParam=0.01, solver_precision="bf16", float32_inputs=False
    ).setFeaturesCol("features")
    _budget(None)
    res = est().fit(df)
    _budget(12_000)
    stream = est().fit(df)
    _assert_streamed(stream)
    np.testing.assert_allclose(
        np.asarray(stream.coef_), np.asarray(res.coef_), rtol=1e-5, atol=1e-8
    )


def test_pca_streaming_bf16_matches_resident_bf16(prec, rng):
    df = pd.DataFrame({"features": list(rng.normal(size=(2000, 6)))})
    est = lambda: PCA(  # noqa: E731
        k=3, solver_precision="bf16", float32_inputs=False
    ).setInputCol("features")
    _budget(None)
    res = est().fit(df)
    _budget(12_000)
    stream = est().fit(df)
    _assert_streamed(stream)
    np.testing.assert_allclose(
        np.asarray(stream.components_), np.asarray(res.components_),
        rtol=1e-5, atol=1e-8,
    )


def test_kmeans_streaming_bf16_matches_resident_bf16(prec, rng):
    x, _ = _blobs(rng, n=2000, dtype=np.float64)  # f32 ingest is the default
    df = pd.DataFrame({"features": list(x)})
    est = lambda: KMeans(  # noqa: E731
        k=4, seed=7, maxIter=15, solver_precision="bf16"
    ).setFeaturesCol("features")
    _budget(None)
    res = est().fit(df)
    _budget(16_000)
    stream = est().fit(df)
    _assert_streamed(stream)
    np.testing.assert_allclose(
        stream.cluster_centers_, res.cluster_centers_, rtol=1e-5, atol=1e-5
    )


def test_logistic_warm_start_f32_donor_bf16_resume(prec, rng):
    # a bf16 fit warm-started from an f32 model: the seed crosses precisions
    # through ORIGINAL coefficient space (never checkpoint state — those are
    # keyed apart), converges, and lands on the same model
    x = rng.normal(size=(1500, 6))
    y = (x @ rng.normal(size=6) > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(x), "label": y})
    cold = LogisticRegression(maxIter=60, regParam=1e-3).setFeaturesCol("features").fit(df)
    warm = LogisticRegression(
        maxIter=60, regParam=1e-3, solver_precision="bf16"
    ).setFeaturesCol("features").fit(df, warm_start_from=cold)
    assert warm.n_iter_ < cold.n_iter_
    np.testing.assert_allclose(
        np.asarray(warm.coef_), np.asarray(cold.coef_), rtol=5e-2, atol=5e-3
    )


# -------------------------------------------------- checkpoint keying -------


def test_bf16_checkpoints_key_apart(rng):
    x = jnp.asarray(rng.normal(size=(500, 6)))
    w = jnp.ones(500)
    with ckpt.checkpoint_scope() as store:
        full = pca_fit_checkpointed(x, w, k=3)
        fast = pca_fit_checkpointed(x, w, k=3, fast=True)
        # distinct entries: a bf16 pass can never serve (or be resumed from)
        # a full-precision statistics checkpoint
        assert store.peek("pca_stats") is not None
        assert store.peek("pca_stats:bf16") is not None
        full_cov = store.peek("pca_stats").state["cov"]
        fast_cov = store.peek("pca_stats:bf16").state["cov"]
        assert not np.array_equal(full_cov, fast_cov)
    assert not np.array_equal(
        np.asarray(full["explained_variance_"]), np.asarray(fast["explained_variance_"])
    )


# ------------------------------------------------- numcheck acceptance ------


@pytest.fixture
def sanitizer(monkeypatch):
    monkeypatch.setenv("SRML_NUMCHECK", "1")
    state = numcheck.snapshot()
    numcheck.reset()
    diagnostics.flight_recorder().reset()
    yield numcheck
    numcheck.restore(state)


def _assert_no_bf16_watermark(nc):
    assert nc.trips() == []
    assert nc.checks() > 0
    for stage, marks in nc.watermarks().items():
        assert "bfloat16" not in marks, (
            f"solver state narrowed to bf16 at boundary {stage!r}: {marks}"
        )


def test_numcheck_bf16_resident_fits_sweep_clean(sanitizer, rng):
    # every bf16 family under the sanitizer: zero trips, and every staged
    # boundary value — iterates, statistics, chunk partials — watermarks at
    # full precision (the bf16 narrowing lives INSIDE the dots, never in
    # state that crosses a check boundary)
    x, _ = _blobs(rng, n=800, dtype=np.float32)
    w32 = jnp.ones(len(x), dtype=jnp.float32)
    kmeans_fit(jnp.asarray(x), w32, jnp.asarray(x[:4].copy()),
               mesh=get_mesh(), max_iter=8, precision_mode="fast")
    xd = rng.normal(size=(500, 6))
    yd = (xd @ rng.normal(size=6) > 0).astype(np.int32)
    logistic_fit(jnp.asarray(xd), jnp.asarray(yd), jnp.ones(500),
                 k=2, multinomial=False, lam_l2=0.01, max_iter=30, fast=True)
    pca_fit(jnp.asarray(xd), jnp.ones(500), k=3, fast=True)
    _assert_no_bf16_watermark(sanitizer)


def test_numcheck_bf16_streaming_sweeps_clean(sanitizer, prec, rng):
    x, _ = _blobs(rng, n=2000, dtype=np.float64)
    df = pd.DataFrame({"features": list(x)})
    _budget(16_000)
    model = KMeans(
        k=4, seed=7, maxIter=10, solver_precision="bf16"
    ).setFeaturesCol("features").fit(df)
    _assert_streamed(model)
    _assert_no_bf16_watermark(sanitizer)
    assert any(s.startswith("kmeans_stream") for s in sanitizer.watermarks())
