#
# Native C++ component tests (the reference's PCASuite.scala / JNI analog):
# covariance gemm, Jacobi eigh, signflip, and the end-to-end native PCA vs
# numpy/sklearn. Skipped when no C++ toolchain is available.
#
import numpy as np
import pytest

native = pytest.importorskip("spark_rapids_ml_tpu.native")

if not native.available():  # no cmake/g++ in this environment
    pytest.skip("native library could not be built", allow_module_level=True)


def test_cov_accumulate_matches_numpy(rng):
    x = rng.normal(size=(500, 12))
    c = native.cov_accumulate(x)
    np.testing.assert_allclose(c, x.T @ x, rtol=1e-12)
    # accumulation across blocks
    c2 = native.cov_accumulate(x[:250])
    c2 = native.cov_accumulate(x[250:], c2)
    np.testing.assert_allclose(c2, c, rtol=1e-12)


def test_weighted_mean(rng):
    x = rng.normal(size=(200, 5))
    w = rng.uniform(0.1, 2.0, 200)
    np.testing.assert_allclose(
        native.weighted_mean(x, w), np.average(x, axis=0, weights=w), rtol=1e-12
    )
    np.testing.assert_allclose(native.weighted_mean(x), x.mean(axis=0), rtol=1e-12)


def test_eigh_jacobi_matches_numpy(rng):
    a = rng.normal(size=(24, 24))
    sym = a + a.T
    evals, evecs = native.eigh(sym)
    ref_vals, _ = np.linalg.eigh(sym)
    np.testing.assert_allclose(evals, ref_vals, rtol=1e-10, atol=1e-10)
    # each eigenpair satisfies A v = λ v; vectors orthonormal
    for i in range(24):
        np.testing.assert_allclose(sym @ evecs[:, i], evals[i] * evecs[:, i], atol=1e-8)
    np.testing.assert_allclose(evecs.T @ evecs, np.eye(24), atol=1e-10)


def test_signflip_semantics():
    comps = np.array([[0.1, -0.9, 0.2], [0.5, 0.4, 0.3], [-0.2, 0.1, -0.7]])
    out = native.signflip(comps.copy())
    # row 0: max-|.| is -0.9 -> flipped; row 1 untouched; row 2: -0.7 -> flipped
    np.testing.assert_allclose(out[0], [-0.1, 0.9, -0.2])
    np.testing.assert_allclose(out[1], comps[1])
    np.testing.assert_allclose(out[2], [0.2, -0.1, 0.7])


def test_native_pca_matches_sklearn(rng):
    from sklearn.decomposition import PCA as SkPCA

    x = rng.normal(size=(300, 10)) @ rng.normal(size=(10, 10))
    comps, var, mean = native.pca_from_cov(x, k=3)
    sk = SkPCA(n_components=3).fit(x)
    np.testing.assert_allclose(mean, sk.mean_, rtol=1e-10)
    np.testing.assert_allclose(var, sk.explained_variance_, rtol=1e-8)
    # components equal up to sign; after signflip both are canonicalized the
    # same way (sklearn uses svd_flip on U — compare absolute values, then
    # verify OUR canonicalization is deterministic)
    np.testing.assert_allclose(np.abs(comps), np.abs(sk.components_), atol=1e-8)
    comps2, _, _ = native.pca_from_cov(x, k=3)
    np.testing.assert_allclose(comps, comps2, rtol=1e-12)


def test_native_pca_matches_jax_path(rng):
    # the native stack and the TPU (JAX) estimator agree on the same data
    import pandas as pd

    from spark_rapids_ml_tpu.models.feature import PCA

    x = rng.normal(size=(400, 8))
    comps, var, mean = native.pca_from_cov(x, k=3)
    model = PCA(k=3, inputCol="features", float32_inputs=False).fit(
        pd.DataFrame({"features": list(x)})
    )
    np.testing.assert_allclose(np.abs(np.asarray(model.components_)), np.abs(comps), atol=1e-6)
    np.testing.assert_allclose(np.asarray(model.mean_), mean, atol=1e-10)
