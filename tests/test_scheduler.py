#
# Multi-tenant fit scheduler tests (spark_rapids_ml_tpu/scheduler/,
# docs/scheduling.md): the shared HBM ledger's accounting, bin-packed
# co-admission, the cooperative preemption -> checkpoint -> resume ladder
# (bit-identity pinned for kmeans + logistic, dense + ELL), streaming
# demotion after repeated displacement, typed saturation refusals, and
# dead-job reservation reclamation.
#
# Every estimator here runs single-device (num_workers=1): co-admitted jobs
# genuinely overlap on worker threads, and single-device programs carry no
# collectives to deadlock on the shared CPU mesh.
#
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import checkpoint as ckpt
from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu import memory, telemetry
from spark_rapids_ml_tpu.errors import PreemptedError, SchedulerSaturatedError
from spark_rapids_ml_tpu.linalg import SparseVector
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.clustering import KMeans
from spark_rapids_ml_tpu.parallel import chaos
from spark_rapids_ml_tpu.scheduler import (
    FitScheduler,
    HbmLedger,
    global_ledger,
    job_scope,
)
from spark_rapids_ml_tpu.scheduler.queue import FitJob


@pytest.fixture(autouse=True)
def _clean_state():
    chaos.clear_fault_plan()
    keys = (
        "hbm_budget_bytes", "checkpoint_every_iters", "sched_max_preemptions",
        "sched_max_concurrent", "fit_max_retries", "fit_retry_backoff_s",
        "stream_chunk_rows",
    )
    saved = {k: core_mod.config[k] for k in keys}
    core_mod.config["fit_retry_backoff_s"] = 0.01
    telemetry.enable()
    telemetry.registry().reset()
    yield
    chaos.clear_fault_plan()
    core_mod.config.update(saved)
    telemetry.disable()


def _counters():
    return telemetry.registry().snapshot()["counters"]


def _blob_df(rng, n=600, d=5):
    x = rng.normal(size=(n, d)).astype(np.float32)
    return pd.DataFrame({"features": list(x)})


def _cls_df(rng, n=800, d=6):
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return pd.DataFrame({"features": list(x), "label": y})


def _mk_kmeans(**kw):
    est = KMeans(**{"k": 4, "maxIter": 6, "seed": 3, **kw})
    est.num_workers = 1
    return est


def _need_bytes(est, df):
    ex = est._pre_process_data(df, for_fit=True, defer_validation=True)
    return memory.resident_estimate(est, ex, 1).total()


def _set_budget(raw_bytes):
    """hbm_budget_bytes such that the post-headroom budget is `raw_bytes`."""
    core_mod.config["hbm_budget_bytes"] = int(raw_bytes / 0.9) + 16


# ---------------------------------------------------------------- ledger ----


def test_ledger_reserve_release_and_watermark():
    led = HbmLedger()
    a = led.reserve("a", "fit", 100)
    b = led.reserve("b", "serve", 50)
    assert led.reserved_bytes() == 150
    assert led.reserved_bytes(kind="serve") == 50
    assert led.reserved_bytes(exclude=a) == 50
    assert led.high_watermark == 150
    led.release(a)
    assert led.reserved_bytes() == 50
    led.release(a)  # idempotent: never a double credit
    assert led.reserved_bytes() == 50
    led.release(None)  # None-safe for finally blocks
    led.release(b)
    assert led.reserved_bytes() == 0
    assert led.high_watermark == 150  # the watermark survives the drain


def test_ledger_try_reserve_enforces_budget_atomically():
    led = HbmLedger()
    r1 = led.try_reserve("a", "job", 60, budget=100)
    assert r1 is not None
    assert led.try_reserve("b", "job", 50, budget=100) is None  # would overshoot
    r3 = led.try_reserve("c", "job", 40, budget=100)  # exact fit admits
    assert r3 is not None and led.reserved_bytes() == 100
    # exclusion: re-truing one's own claim must not double-count itself
    led.release(r3)
    assert led.try_reserve("d", "job", 90, budget=100, exclude=r1) is not None
    # a None budget is bookkeeping-only (no capacity info = no budgeting)
    assert led.try_reserve("e", "job", 10**12, budget=None) is not None


def test_ledger_resize_and_utilization():
    led = HbmLedger()
    r = led.reserve("job:1", "job", 100)
    led.resize(r, 400)
    assert led.reserved_bytes() == 400
    assert led.high_watermark == 400
    led.note_admission(800)
    assert led.utilization() == 0.5
    seen = []
    led.admission_hooks.append(lambda reserved, budget: seen.append((reserved, budget)))
    led.note_admission(800)
    assert seen == [(400, 800)]


# ------------------------------------------------------------ basic queue ---


def test_single_job_completes_and_drains_ledger(rng):
    df = _blob_df(rng)
    sched = FitScheduler()
    try:
        job = sched.submit(_mk_kmeans(), df, tenant="a", priority=1)
        model = job.result(timeout=120)
        assert job.state == "completed" and job.done()
        # per-tenant scheduler telemetry rides the job result
        st = model._fit_metrics["scheduler"]
        assert st["tenant"] == "a" and st["priority"] == 1
        assert st["preemptions"] == 0 and st["queue_wait_s"] >= 0.0
        snap = _counters()
        assert snap["scheduler.jobs_submitted"] == 1
        assert snap["scheduler.jobs_admitted"] == 1
        assert snap["scheduler.jobs_completed"] == 1
    finally:
        sched.shutdown()
    assert global_ledger().reserved_bytes() == 0


def test_co_admission_bin_packs_within_budget(rng):
    df = _blob_df(rng)
    need = _need_bytes(_mk_kmeans(), df)
    _set_budget(int(2.2 * need))  # two jobs co-admit, the third queues
    violations = []
    global_ledger().admission_hooks.append(
        lambda reserved, budget: violations.append(reserved)
        if budget is not None and reserved > budget
        else None
    )
    sched = FitScheduler()
    try:
        jobs = [
            sched.submit(_mk_kmeans(maxIter=12, tol=0.0), df, tenant=f"t{i}")
            for i in range(3)
        ]
        for j in jobs:
            j.result(timeout=120)
    finally:
        sched.shutdown()
    snap = _counters()
    assert snap["scheduler.jobs_admitted"] == 3
    assert snap["scheduler.jobs_completed"] == 3
    assert snap.get("scheduler.jobs_queued", 0) >= 1  # the third deferred
    assert violations == []  # never over budget, at ANY admission
    hwm = global_ledger().high_watermark
    assert need <= hwm <= int(2.2 * need) + 16


def test_respects_max_concurrent_cap(rng):
    df = _blob_df(rng)
    core_mod.config["sched_max_concurrent"] = 1
    peak = [0]
    sched = FitScheduler()
    try:
        jobs = [sched.submit(_mk_kmeans(), df, tenant=f"t{i}") for i in range(3)]
        while not all(j.done() for j in jobs):
            with sched._lock:
                peak[0] = max(peak[0], len(sched._running))
            time.sleep(0.005)
        for j in jobs:
            j.result(timeout=120)
    finally:
        sched.shutdown()
    assert peak[0] <= 1


def test_shutdown_fails_queued_jobs(rng):
    df = _blob_df(rng)
    need = _need_bytes(_mk_kmeans(), df)
    _set_budget(int(1.2 * need))  # one at a time: later submissions queue
    sched = FitScheduler()
    jobs = [
        sched.submit(_mk_kmeans(maxIter=30, tol=0.0), df, tenant=f"t{i}")
        for i in range(4)
    ]
    sched.shutdown(wait=True, timeout=120)
    states = {j.state for j in jobs}
    assert "failed" in states  # drained queue entries fail typed
    for j in jobs:
        if j.state == "failed":
            with pytest.raises(RuntimeError, match="shut down"):
                j.result(timeout=1)
    with pytest.raises(RuntimeError, match="shut down"):
        sched.submit(_mk_kmeans(), df)
    assert global_ledger().reserved_bytes() == 0


# ------------------------------------------------- preemption bit-identity --
# Deterministic unit-level preemption: the job's preempt flag is armed BEFORE
# the fit, so the solver yields at its FIRST checkpoint boundary; the resume
# re-enters with the same job-owned store. No scheduler timing involved.


def _preempt_then_resume(make_est, df):
    job = FitJob(99, make_est(), df, "t", 0)
    job.request_preempt("test preemption")
    with job_scope(job), ckpt.checkpoint_scope(store=job.store):
        with pytest.raises(PreemptedError) as ei:
            job.estimator.fit(df)
    assert ei.value.job_id == 99 and ei.value.iteration >= 1
    assert len(job.store) >= 1  # the boundary checkpoint survived the unwind
    job._preempt.clear()
    with job_scope(job), ckpt.checkpoint_scope(store=job.store):
        resumed = make_est().fit(df)
    return resumed


def test_preempted_kmeans_resumes_bit_identical(rng):
    df = _blob_df(rng)
    core_mod.config["checkpoint_every_iters"] = 3

    def make():
        return _mk_kmeans(k=8, maxIter=10, tol=0.0, seed=7)

    clean = make().fit(df)  # uninterrupted checkpointed fit
    telemetry.registry().reset()
    resumed = _preempt_then_resume(make, df)
    np.testing.assert_array_equal(resumed.cluster_centers_, clean.cluster_centers_)
    assert resumed.n_iter_ == clean.n_iter_
    assert _counters()["checkpoint.restores"] >= 1  # resumed, not restarted


def test_preempted_logistic_resumes_bit_identical(rng):
    df = _cls_df(rng)
    core_mod.config["checkpoint_every_iters"] = 4

    def make():
        est = LogisticRegression(maxIter=20)
        est.num_workers = 1
        return est

    clean = make().fit(df)
    telemetry.registry().reset()
    resumed = _preempt_then_resume(make, df)
    np.testing.assert_array_equal(resumed.coef_, clean.coef_)
    np.testing.assert_array_equal(resumed.intercept_, clean.intercept_)
    assert resumed.n_iter_ == clean.n_iter_
    assert _counters()["checkpoint.restores"] >= 1


def test_preempted_logistic_ell_resumes_bit_identical(rng):
    # the sparse (padded-ELL) solver path yields at the same segmented
    # boundary — preemption is layout-independent
    d = 20
    x = rng.normal(size=(1200, d))
    x = np.where(np.abs(x) > 1.0, x, 0.0)
    rows = [
        SparseVector(d, np.nonzero(r)[0].astype(np.int32), r[np.nonzero(r)[0]])
        for r in x
    ]
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    df = pd.DataFrame({"features": rows, "label": y})
    core_mod.config["checkpoint_every_iters"] = 4

    def make():
        est = LogisticRegression(
            maxIter=20, regParam=0.01, enable_sparse_data_optim=True,
            float32_inputs=False,
        )
        est.num_workers = 1
        return est

    clean = make().fit(df)
    telemetry.registry().reset()
    resumed = _preempt_then_resume(make, df)
    np.testing.assert_array_equal(
        np.asarray(resumed.coef_), np.asarray(clean.coef_)
    )
    assert _counters()["checkpoint.restores"] >= 1


# ------------------------------------------------------ 3-tenant scenario ---


def test_three_tenants_preempt_resume_acceptance(rng):
    # THE acceptance scenario (ISSUE 12): a low-priority big fit running; a
    # high-priority small fit preempts it; a third tenant queues in between;
    # all complete. Pins: the preempted fit's final model is BIT-identical
    # to an uninterrupted checkpointed run, the ledger never exceeds the
    # budget AT ANY admission, and per-tenant scheduler.* telemetry rides
    # every job result.
    xb = rng.normal(size=(20_000, 32)).astype(np.float32)
    df_big = pd.DataFrame({"features": list(xb)})
    df_small = _blob_df(rng, n=500, d=32)
    core_mod.config["checkpoint_every_iters"] = 2

    def mk_big():
        return _mk_kmeans(k=16, maxIter=200, tol=0.0, seed=7)

    def mk_small():
        return _mk_kmeans(k=4, maxIter=5, seed=3)

    need_b = _need_bytes(mk_big(), df_big)
    need_s = _need_bytes(mk_small(), df_small)
    # the big fit fits ALONE; big + small does NOT — the high-priority small
    # job can only run by preempting
    _set_budget(int(need_b + 0.5 * need_s))

    ref = mk_big().fit(df_big)  # uninterrupted checkpointed reference

    violations = []
    budgets = []
    global_ledger().admission_hooks.append(
        lambda reserved, budget: (
            budgets.append(budget),
            violations.append(reserved) if budget is not None and reserved > budget else None,
        )
    )
    telemetry.registry().reset()
    sched = FitScheduler()
    try:
        mark = telemetry.registry().mark()
        job_big = sched.submit(mk_big(), df_big, tenant="batch", priority=0)
        # wait until the big fit is genuinely mid-solve (its OWN checkpoints)
        deadline = time.monotonic() + 120
        while (
            telemetry.registry().delta(mark)["counters"].get("checkpoint.saves", 0) < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        job_hi = sched.submit(mk_small(), df_small, tenant="interactive", priority=10)
        job_mid = sched.submit(mk_small(), df_small, tenant="reporting", priority=5)
        m_hi = job_hi.result(timeout=180)
        m_mid = job_mid.result(timeout=180)
        m_big = job_big.result(timeout=300)
    finally:
        sched.shutdown()

    # every tenant completed; the big fit was preempted and resumed
    snap = _counters()
    assert snap["scheduler.jobs_preempted"] >= 1
    assert snap["scheduler.jobs_resumed"] >= 1
    assert snap["checkpoint.restores"] >= 1
    assert job_big.preemptions >= 1 and job_big.state == "completed"
    # bit-identical to the uninterrupted checkpointed fit — zero lost work
    np.testing.assert_array_equal(
        np.asarray(m_big.cluster_centers_), np.asarray(ref.cluster_centers_)
    )
    assert m_big.n_iter_ == ref.n_iter_
    # the ledger never exceeded the budget, checked at EVERY admission
    assert violations == [] and len(budgets) >= 3
    assert global_ledger().reserved_bytes() == 0
    # per-tenant scheduler telemetry present in each job result
    for model, tenant in ((m_big, "batch"), (m_hi, "interactive"), (m_mid, "reporting")):
        st = model._fit_metrics["scheduler"]
        assert st["tenant"] == tenant
        assert st["queue_wait_s"] >= 0.0 and "hbm_share" in st
    assert m_big._fit_metrics["scheduler"]["preemptions"] >= 1
    # the high-priority tenant never waited for the whole big fit
    assert m_hi._fit_metrics["scheduler"]["queue_wait_s"] < job_big.run_s + 60


# ------------------------------------------------------------- demotion -----


def test_preempted_too_often_job_demotes_to_streaming(rng):
    # sched_max_preemptions=1: the FIRST preemption demotes the job — its
    # re-admission runs the out-of-core streaming path (floor footprint,
    # always packable) and the model carries the stream verdict. The
    # preemption is requested directly on the job handle so the test is
    # deterministic regardless of solver speed (the scheduler-initiated
    # request path is pinned by the 3-tenant acceptance test above).
    x = rng.normal(size=(60_000, 32))
    y = (x @ rng.normal(size=32) > 0).astype(np.float64)
    df_big = pd.DataFrame({"features": list(x), "label": y})
    core_mod.config["checkpoint_every_iters"] = 2
    core_mod.config["sched_max_preemptions"] = 1

    def mk_big():
        est = LogisticRegression(maxIter=40, tol=0.0, regParam=1e-4)
        est.num_workers = 1
        return est

    _set_budget(int(1.5 * _need_bytes(mk_big(), df_big)))

    sched = FitScheduler()
    try:
        mark = telemetry.registry().mark()
        job_big = sched.submit(mk_big(), df_big, tenant="batch", priority=0)
        deadline = time.monotonic() + 120
        while (
            telemetry.registry().delta(mark)["counters"].get("checkpoint.saves", 0) < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        job_big.request_preempt("higher-priority tenant needs the reservation")
        m_big = job_big.result(timeout=600)
    finally:
        sched.shutdown()
    snap = _counters()
    assert snap["scheduler.jobs_preempted"] >= 1
    assert snap["scheduler.jobs_demoted"] == 1
    assert job_big.demoted and job_big.state == "completed"
    st = m_big._fit_metrics["scheduler"]
    assert st["demoted"] is True
    # the demoted re-admission really streamed (degraded-mode service)
    adm = m_big._fit_metrics["admission"]
    assert adm["verdict"] == "stream"
    assert "sched_max_preemptions" in adm["reason"]
    assert global_ledger().reserved_bytes() == 0


# ------------------------------------------------------------- refusals -----


def test_submit_refuses_never_fitting_job_typed(rng):
    df = _blob_df(rng, n=2000, d=16)
    core_mod.config["hbm_budget_bytes"] = 2000  # smaller than any floor
    sched = FitScheduler()
    try:
        with pytest.raises(SchedulerSaturatedError) as ei:
            sched.submit(_mk_kmeans(), df, tenant="hopeless")
        e = ei.value
        assert e.tenant == "hopeless"
        assert e.estimate_bytes and e.budget_bytes and e.largest_term
        assert e.largest_term in str(e)
        assert isinstance(e, MemoryError)  # mirrors HbmBudgetError's IS-A
        assert _counters()["scheduler.jobs_refused"] == 1
    finally:
        sched.shutdown()
    assert global_ledger().reserved_bytes() == 0


# -------------------------------------------------------- dead-job chaos ----


def test_dead_tenant_job_reclaims_reservation_and_queue_drains(rng):
    # chaos-killed tenant (the chaos_worker pattern: an injected stage fault
    # with the retry budget at zero = the fit dies abruptly): the scheduler
    # must reclaim the dead job's reservation and keep scheduling — a dead
    # tenant cannot wedge the queue
    df = _blob_df(rng)
    need = _need_bytes(_mk_kmeans(), df)
    _set_budget(int(1.2 * need))  # one job at a time: the second queues
    core_mod.config["fit_max_retries"] = 0
    chaos.set_fault_plan("fail:stage=fit:times=1")
    sched = FitScheduler()
    try:
        doomed = sched.submit(_mk_kmeans(), df, tenant="dead")
        survivor = sched.submit(_mk_kmeans(), df, tenant="alive")
        model = survivor.result(timeout=120)
        assert model is not None and survivor.state == "completed"
        with pytest.raises(Exception):
            doomed.result(timeout=60)
        assert doomed.state == "failed"
    finally:
        sched.shutdown()
    snap = _counters()
    assert snap["scheduler.jobs_failed"] == 1
    assert snap["scheduler.jobs_completed"] == 1
    assert global_ledger().reserved_bytes() == 0  # the dead job's claim reclaimed


# ------------------------------------------------------------- telemetry ----


def test_ledger_gauges_flow_through_registry(rng):
    df = _blob_df(rng)
    _set_budget(int(3 * _need_bytes(_mk_kmeans(), df)))
    sched = FitScheduler()
    try:
        sched.submit(_mk_kmeans(), df, tenant="a").result(timeout=120)
    finally:
        sched.shutdown()
    snap = telemetry.registry().snapshot()
    assert "scheduler.ledger_reserved_bytes" in snap["gauges"]
    assert "scheduler.ledger_utilization" in snap["gauges"]
    assert snap["histograms"].get("scheduler.queue_wait_s", {}).get("count", 0) >= 1
    assert snap["histograms"].get("scheduler.hbm_share", {}).get("count", 0) >= 1
    stats = sched.stats()
    assert stats["tenants"]["a"]["completed"] == 1
    assert stats["ledger_reserved_bytes"] == 0


# --------------------------------------------------- review regressions -----


def test_transient_retry_readmits_without_double_count(rng):
    # a retry re-enters admission while the failed attempt's reservation is
    # still held; the re-admission must hand that claim back first — a
    # resident fit at ~0.9x budget must NOT spuriously demote on retry (and
    # the retried model stays bit-identical, the PR-3 contract)
    df = _blob_df(rng)
    est_probe = _mk_kmeans(k=8, maxIter=10, tol=0.0, seed=7)
    need = _need_bytes(est_probe, df)
    _set_budget(int(1.1 * need))  # resident fits, but not twice over
    core_mod.config["checkpoint_every_iters"] = 3

    clean = _mk_kmeans(k=8, maxIter=10, tol=0.0, seed=7).fit(df)
    assert clean._fit_metrics["admission"]["verdict"] == "resident"

    chaos.set_fault_plan("fail:stage=solve:times=1")
    telemetry.registry().reset()
    retried = _mk_kmeans(k=8, maxIter=10, tol=0.0, seed=7).fit(df)
    snap = _counters()
    assert snap["fit.retries"] == 1
    assert snap.get("fit.demotions", 0) == 0  # NOT demoted by its own ghost
    assert retried._fit_metrics["admission"]["verdict"] == "resident"
    np.testing.assert_array_equal(retried.cluster_centers_, clean.cluster_centers_)
    assert global_ledger().reserved_bytes() == 0


def test_no_preemption_request_without_checkpoint_cadence(rng):
    # cadence 0: solvers never reach a yield point, so requesting preemption
    # would only freeze backfill — the blocked high-priority job waits for
    # completion instead, and the victim's flag is never set
    df = _blob_df(rng)
    need = _need_bytes(_mk_kmeans(), df)
    _set_budget(int(1.2 * need))
    core_mod.config["checkpoint_every_iters"] = 0
    sched = FitScheduler()
    try:
        low = sched.submit(_mk_kmeans(maxIter=30, tol=0.0), df, tenant="low", priority=0)
        hi = sched.submit(_mk_kmeans(), df, tenant="hi", priority=10)
        hi.result(timeout=120)
        low.result(timeout=120)
    finally:
        sched.shutdown()
    assert low.preemptions == 0 and not low.preempt_requested()
    assert _counters().get("scheduler.jobs_preempted", 0) == 0


def test_refused_jobs_appear_in_stats(rng):
    df = _blob_df(rng, n=2000, d=16)
    core_mod.config["hbm_budget_bytes"] = 2000
    sched = FitScheduler()
    try:
        with pytest.raises(SchedulerSaturatedError):
            sched.submit(_mk_kmeans(), df, tenant="hopeless")
        t = sched.stats()["tenants"]["hopeless"]
        assert t["jobs"] == 1 and t["failed"] == 1
    finally:
        sched.shutdown()


def test_package_level_fitscheduler_is_the_real_class():
    import spark_rapids_ml_tpu as pkg
    from spark_rapids_ml_tpu.scheduler import FitScheduler as real

    assert pkg.FitScheduler is real  # PEP 562 lazy export, not a wrapper
    sched = pkg.FitScheduler(ledger=HbmLedger())  # kwargs AND the class API
    assert isinstance(sched, pkg.FitScheduler)
    sched.shutdown()


# ---------------------------------------------------------- 2-D placement ---
#
# The chip-occupancy half of the ledger (docs/scheduling.md "2-D placement"):
# chip-scoped claims own WHICH chips exclusively, legacy claims keep the
# bytes-only contract, and FitScheduler(chip_placement=True) first-fits
# contiguous runs so equal-width jobs co-admit onto disjoint halves.


def test_ledger_2d_coadmit_disjoint_refuse_overlap():
    led = HbmLedger()
    led.note_chip_pool(8)
    a = led.try_reserve("a", "job", 40, budget=100, chip_ids=[0, 1, 2, 3])
    b = led.try_reserve("b", "job", 40, budget=100, chip_ids=[4, 5, 6, 7])
    assert a is not None and b is not None  # disjoint sets co-admit
    assert led.occupied_chips() == set(range(8))
    # overlap refused even with byte headroom on every chip: occupancy is
    # exclusive (two SPMD programs cannot time-share a chip)
    assert led.try_reserve("c", "job", 1, budget=100, chip_ids=[3, 4]) is None
    led.release(b)
    assert led.occupied_chips() == {0, 1, 2, 3}
    assert led.try_reserve("c", "job", 1, budget=100, chip_ids=[3, 4]) is None
    c = led.try_reserve("c", "job", 1, budget=100, chip_ids=[4, 5])
    assert c is not None  # freed chips return to the pool


def test_ledger_legacy_claims_budget_every_chip_but_do_not_occupy():
    led = HbmLedger()
    led.note_chip_pool(4)
    led.reserve("resident", "serve", 70, chips=4)  # legacy: no chip_ids
    # an unplaced claim does not occupy — placement stays possible...
    assert led.occupied_chips() == set()
    # ...but its bytes count on EVERY chip (it may live anywhere), so a
    # chip-scoped claim sees them in its per-chip budget check
    assert led.try_reserve("j", "job", 40, budget=100, chip_ids=[0, 1]) is None
    r = led.try_reserve("j", "job", 25, budget=100, chip_ids=[0, 1])
    assert r is not None
    assert led.reserved_bytes_on(0) == 95  # legacy 70 + placed 25
    assert led.reserved_bytes_on(3) == 70  # legacy only off the placed set


def test_ledger_rebind_moves_occupancy_bytes_and_utilization():
    # the sub-mesh resize move: a recovered sweep (or resumed job) re-points
    # its claim at a different-width chip set; both dimensions must follow
    led = HbmLedger()
    led.note_chip_pool(8)
    r = led.try_reserve("j", "job", 60, budget=100, chip_ids=[0, 1, 2, 3])
    assert r is not None and led.occupied_chips() == {0, 1, 2, 3}
    led.note_admission(100)
    assert led.utilization() == pytest.approx(60 * 4 / (100 * 8))
    led.rebind(r, [4, 5])
    assert led.occupied_chips() == {4, 5}
    assert r.chips == 2  # chips multiplier follows the set
    assert led.reserved_bytes_on(0) == 0 and led.reserved_bytes_on(4) == 60
    assert led.utilization() == pytest.approx(60 * 2 / (100 * 8))
    # accounting: the released claim's chip-seconds accrued at each width
    led.release(r)
    u = led.tenant_usage()["default"]
    assert u["chip_seconds"] >= 0.0 and u["reservations"] == 1.0


def test_pool_gauges_flow_through_ops_plane_report():
    from spark_rapids_ml_tpu import ops_plane

    led = global_ledger()
    led.note_chip_pool(8)
    r = led.reserve("j", "job", 10, tenant="acme", chip_ids=[0, 1, 2])
    try:
        tenants = ops_plane.report()["tenants"]
        assert tenants["_pool"]["chips_busy"] == 3.0
        assert tenants["_pool"]["chips_total"] == 8.0
        assert tenants["_pool"]["chips_idle"] == 5.0
        assert tenants["acme"]["chips_busy"] == 3.0
    finally:
        led.release(r)
    tenants = ops_plane.report()["tenants"]
    assert tenants["_pool"]["chips_busy"] == 0.0
    assert tenants["_pool"]["chips_idle"] == 8.0


def _mk_wide_kmeans(**kw):
    """A width-4 (half-mesh) estimator — the 2-D scheduler's placement unit."""
    est = KMeans(**{"k": 8, "maxIter": 12, "seed": 7, "tol": 0.0, **kw})
    est.num_workers = 4
    return est


def _occupancy_trace(samples):
    """Step-integral of occupied chips over the busy window -> (avg, peak)."""
    busy = [(t, occ) for t, occ in samples if occ > 0]
    if len(busy) < 2:
        return 0.0, max((occ for _, occ in samples), default=0)
    integral = sum(
        occ * (t1 - t0)
        for (t0, occ), (t1, _) in zip(busy, busy[1:])
    )
    span = busy[-1][0] - busy[0][0]
    peak = max(occ for _, occ in busy)
    return (integral / span if span > 0 else 0.0), peak


def _sample_occupancy(stop, samples):
    while not stop.is_set():
        samples.append(
            (time.monotonic(), len(global_ledger().occupied_chips()))
        )
        time.sleep(0.002)  # blocking-ok: test poll cadence


def test_coadmission_occupies_both_halves_and_stays_bit_identical(rng):
    """The co-admission acceptance pin (ISSUE 19): two half-mesh fits
    co-admitted onto disjoint chip sets keep BOTH halves of the pool busy —
    the chip-occupancy integral is >= 1.5x the time-sliced schedule's — and
    every model is bit-identical to the same fit run alone on the whole
    pool. (Wall-clock rows/sec is the report-only benchmark lane: on the
    virtual CPU mesh all 8 "chips" share the same host cores, so occupancy
    — what a real multi-chip part turns into throughput — is the pinned
    metric.)"""
    import threading

    df = _blob_df(rng, n=20000, d=16)
    ref = _mk_wide_kmeans().fit(df)  # whole-pool sequential reference

    # concurrent: both width-4 jobs co-admit onto disjoint halves; a third
    # width-4 job must QUEUE on chip overlap alone (no byte budget is set,
    # so bytes can never be the refusal here)
    sched = FitScheduler(chip_placement=True)
    samples, stop = [], threading.Event()
    poller = threading.Thread(target=_sample_occupancy, args=(stop, samples))
    try:
        poller.start()
        ja = sched.submit(_mk_wide_kmeans(), df, tenant="a")
        jb = sched.submit(_mk_wide_kmeans(), df, tenant="b")
        jc = sched.submit(_mk_wide_kmeans(), df, tenant="c")
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            st = sched.stats()
            if st["running"] == 2 and st["queued"] == 1:
                break
            time.sleep(0.002)  # blocking-ok: bounded test poll
        st = sched.stats()
        assert st["running"] == 2 and st["queued"] == 1
        assert sorted(st["ledger_occupied_chips"]) == list(range(8))
        ma = ja.result(timeout=120)
        mb = jb.result(timeout=120)
        mc = jc.result(timeout=120)
    finally:
        stop.set()
        poller.join(timeout=5)
        sched.shutdown()
    _, peak_conc = _occupancy_trace(samples)
    assert peak_conc == 8  # both halves genuinely claimed at once

    chips_a = ma._fit_metrics["scheduler"]["chip_ids"]
    chips_b = mb._fit_metrics["scheduler"]["chip_ids"]
    chips_c = mc._fit_metrics["scheduler"]["chip_ids"]
    assert len(chips_a) == len(chips_b) == len(chips_c) == 4
    assert not set(chips_a) & set(chips_b)  # disjoint co-admission
    assert set(chips_a) | set(chips_b) == set(range(8))

    # occupancy integral, measured on a CLEAN two-job phase: the 3-job phase
    # above ends with the queued job running alone (a solo width-4 tail that
    # dilutes the average when warm compile caches make fits fast), so the
    # >= 1.5x pin compares exactly the schedules the benchmark lane compares
    # — the same two jobs co-admitted vs time-sliced
    sched1 = FitScheduler(chip_placement=True)
    samples1, stop1 = [], threading.Event()
    poller1 = threading.Thread(target=_sample_occupancy, args=(stop1, samples1))
    try:
        poller1.start()
        ca = sched1.submit(_mk_wide_kmeans(), df, tenant="a")
        cb = sched1.submit(_mk_wide_kmeans(), df, tenant="b")
        mca = ca.result(timeout=120)
        mcb = cb.result(timeout=120)
    finally:
        stop1.set()
        poller1.join(timeout=5)
        sched1.shutdown()
    avg_conc, peak_conc2 = _occupancy_trace(samples1)
    assert peak_conc2 == 8

    # time-sliced: same jobs, one at a time — half the pool busy at best
    sched2 = FitScheduler(chip_placement=True, max_concurrent=1)
    samples2, stop2 = [], threading.Event()
    poller2 = threading.Thread(target=_sample_occupancy, args=(stop2, samples2))
    try:
        poller2.start()
        sa = sched2.submit(_mk_wide_kmeans(), df, tenant="a")
        sb = sched2.submit(_mk_wide_kmeans(), df, tenant="b")
        msa = sa.result(timeout=120)
        msb = sb.result(timeout=120)
    finally:
        stop2.set()
        poller2.join(timeout=5)
        sched2.shutdown()
    avg_sliced, peak_sliced = _occupancy_trace(samples2)
    assert peak_sliced == 4  # one width-4 claim at a time

    assert avg_sliced > 0
    ratio = avg_conc / avg_sliced
    assert ratio >= 1.5, (
        f"co-admission occupancy {avg_conc:.2f} vs time-sliced "
        f"{avg_sliced:.2f} (ratio {ratio:.2f} < 1.5)"
    )

    # placement must not perturb math: every schedule, every chip set,
    # bit-identical to the whole-pool sequential fit
    for m in (ma, mb, mc, mca, mcb, msa, msb):
        np.testing.assert_array_equal(
            np.asarray(m.cluster_centers_), np.asarray(ref.cluster_centers_)
        )


def test_preempted_job_resumes_on_different_chip_set_bit_identically(rng):
    """Satellite (c3): a width-4 job preempted off [4..7] resumes on [0..3]
    once those chips free up — a DIFFERENT equal-width run — and its model
    stays bit-identical to an uninterrupted fit (checkpoints are chip-set
    agnostic: host-side solver state, re-placed at restore)."""
    df = _blob_df(rng, n=6000, d=8)
    core_mod.config["checkpoint_every_iters"] = 2
    est_a = _mk_wide_kmeans(maxIter=40)
    extracted = est_a._pre_process_data(df, for_fit=True, defer_validation=True)
    need = memory.resident_estimate(est_a, extracted, 4).total()
    _set_budget(3 * need + 4096)
    clean = _mk_wide_kmeans(maxIter=40).fit(df)

    # a resident serving claim pins the LEFT half: the job can only land on
    # [4..7] first
    serve = global_ledger().reserve(
        "serve:pin", "serve", 1024, tenant="svc", chip_ids=[0, 1, 2, 3]
    )
    sched = FitScheduler(chip_placement=True)
    try:
        mark = telemetry.registry().mark()
        ja = sched.submit(_mk_wide_kmeans(maxIter=40), df, tenant="low")
        deadline = time.monotonic() + 30.0
        first_chips = None
        while time.monotonic() < deadline:
            if ja.chip_ids is not None:
                first_chips = tuple(ja.chip_ids)
                break
            time.sleep(0.002)  # blocking-ok: bounded test poll
        assert first_chips == (4, 5, 6, 7)
        # let it make checkpointed progress before displacing it
        while time.monotonic() < deadline:
            d = telemetry.registry().delta(mark)["counters"]
            if d.get("checkpoint.saves", 0) >= 1:
                break
            time.sleep(0.002)  # blocking-ok: bounded test poll
        jb = sched.submit(
            _mk_wide_kmeans(maxIter=4), df, tenant="high", priority=10
        )
        # the preemptor takes the only free-able run — the one A held
        while time.monotonic() < deadline:
            if jb.chip_ids is not None:
                break
            time.sleep(0.002)  # blocking-ok: bounded test poll
        assert tuple(jb.chip_ids or ()) == (4, 5, 6, 7)
        # the serving replica drains: the left half opens up for A's resume
        global_ledger().release(serve)
        serve = None
        jb.result(timeout=120)
        # nudge a pass in case B finished before the release (releases do
        # not reschedule); width-1 filler, lower in FIFO order than A
        sched.submit(_mk_kmeans(), df, tenant="filler").result(timeout=120)
        resumed = ja.result(timeout=120)
    finally:
        global_ledger().release(serve)
        sched.shutdown()

    st = resumed._fit_metrics["scheduler"]
    assert st["preemptions"] == 1 and st["resumes"] == 1
    assert tuple(st["chip_ids"]) == (0, 1, 2, 3)  # a different run
    assert tuple(st["chip_ids"]) != first_chips
    np.testing.assert_array_equal(
        np.asarray(resumed.cluster_centers_), np.asarray(clean.cluster_centers_)
    )
