#
# Multi-tenant fit scheduler tests (spark_rapids_ml_tpu/scheduler/,
# docs/scheduling.md): the shared HBM ledger's accounting, bin-packed
# co-admission, the cooperative preemption -> checkpoint -> resume ladder
# (bit-identity pinned for kmeans + logistic, dense + ELL), streaming
# demotion after repeated displacement, typed saturation refusals, and
# dead-job reservation reclamation.
#
# Every estimator here runs single-device (num_workers=1): co-admitted jobs
# genuinely overlap on worker threads, and single-device programs carry no
# collectives to deadlock on the shared CPU mesh.
#
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import checkpoint as ckpt
from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu import memory, telemetry
from spark_rapids_ml_tpu.errors import PreemptedError, SchedulerSaturatedError
from spark_rapids_ml_tpu.linalg import SparseVector
from spark_rapids_ml_tpu.models.classification import LogisticRegression
from spark_rapids_ml_tpu.models.clustering import KMeans
from spark_rapids_ml_tpu.parallel import chaos
from spark_rapids_ml_tpu.scheduler import (
    FitScheduler,
    HbmLedger,
    global_ledger,
    job_scope,
)
from spark_rapids_ml_tpu.scheduler.queue import FitJob


@pytest.fixture(autouse=True)
def _clean_state():
    chaos.clear_fault_plan()
    keys = (
        "hbm_budget_bytes", "checkpoint_every_iters", "sched_max_preemptions",
        "sched_max_concurrent", "fit_max_retries", "fit_retry_backoff_s",
        "stream_chunk_rows",
    )
    saved = {k: core_mod.config[k] for k in keys}
    core_mod.config["fit_retry_backoff_s"] = 0.01
    telemetry.enable()
    telemetry.registry().reset()
    yield
    chaos.clear_fault_plan()
    core_mod.config.update(saved)
    telemetry.disable()


def _counters():
    return telemetry.registry().snapshot()["counters"]


def _blob_df(rng, n=600, d=5):
    x = rng.normal(size=(n, d)).astype(np.float32)
    return pd.DataFrame({"features": list(x)})


def _cls_df(rng, n=800, d=6):
    x = rng.normal(size=(n, d))
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    return pd.DataFrame({"features": list(x), "label": y})


def _mk_kmeans(**kw):
    est = KMeans(**{"k": 4, "maxIter": 6, "seed": 3, **kw})
    est.num_workers = 1
    return est


def _need_bytes(est, df):
    ex = est._pre_process_data(df, for_fit=True, defer_validation=True)
    return memory.resident_estimate(est, ex, 1).total()


def _set_budget(raw_bytes):
    """hbm_budget_bytes such that the post-headroom budget is `raw_bytes`."""
    core_mod.config["hbm_budget_bytes"] = int(raw_bytes / 0.9) + 16


# ---------------------------------------------------------------- ledger ----


def test_ledger_reserve_release_and_watermark():
    led = HbmLedger()
    a = led.reserve("a", "fit", 100)
    b = led.reserve("b", "serve", 50)
    assert led.reserved_bytes() == 150
    assert led.reserved_bytes(kind="serve") == 50
    assert led.reserved_bytes(exclude=a) == 50
    assert led.high_watermark == 150
    led.release(a)
    assert led.reserved_bytes() == 50
    led.release(a)  # idempotent: never a double credit
    assert led.reserved_bytes() == 50
    led.release(None)  # None-safe for finally blocks
    led.release(b)
    assert led.reserved_bytes() == 0
    assert led.high_watermark == 150  # the watermark survives the drain


def test_ledger_try_reserve_enforces_budget_atomically():
    led = HbmLedger()
    r1 = led.try_reserve("a", "job", 60, budget=100)
    assert r1 is not None
    assert led.try_reserve("b", "job", 50, budget=100) is None  # would overshoot
    r3 = led.try_reserve("c", "job", 40, budget=100)  # exact fit admits
    assert r3 is not None and led.reserved_bytes() == 100
    # exclusion: re-truing one's own claim must not double-count itself
    led.release(r3)
    assert led.try_reserve("d", "job", 90, budget=100, exclude=r1) is not None
    # a None budget is bookkeeping-only (no capacity info = no budgeting)
    assert led.try_reserve("e", "job", 10**12, budget=None) is not None


def test_ledger_resize_and_utilization():
    led = HbmLedger()
    r = led.reserve("job:1", "job", 100)
    led.resize(r, 400)
    assert led.reserved_bytes() == 400
    assert led.high_watermark == 400
    led.note_admission(800)
    assert led.utilization() == 0.5
    seen = []
    led.admission_hooks.append(lambda reserved, budget: seen.append((reserved, budget)))
    led.note_admission(800)
    assert seen == [(400, 800)]


# ------------------------------------------------------------ basic queue ---


def test_single_job_completes_and_drains_ledger(rng):
    df = _blob_df(rng)
    sched = FitScheduler()
    try:
        job = sched.submit(_mk_kmeans(), df, tenant="a", priority=1)
        model = job.result(timeout=120)
        assert job.state == "completed" and job.done()
        # per-tenant scheduler telemetry rides the job result
        st = model._fit_metrics["scheduler"]
        assert st["tenant"] == "a" and st["priority"] == 1
        assert st["preemptions"] == 0 and st["queue_wait_s"] >= 0.0
        snap = _counters()
        assert snap["scheduler.jobs_submitted"] == 1
        assert snap["scheduler.jobs_admitted"] == 1
        assert snap["scheduler.jobs_completed"] == 1
    finally:
        sched.shutdown()
    assert global_ledger().reserved_bytes() == 0


def test_co_admission_bin_packs_within_budget(rng):
    df = _blob_df(rng)
    need = _need_bytes(_mk_kmeans(), df)
    _set_budget(int(2.2 * need))  # two jobs co-admit, the third queues
    violations = []
    global_ledger().admission_hooks.append(
        lambda reserved, budget: violations.append(reserved)
        if budget is not None and reserved > budget
        else None
    )
    sched = FitScheduler()
    try:
        jobs = [
            sched.submit(_mk_kmeans(maxIter=12, tol=0.0), df, tenant=f"t{i}")
            for i in range(3)
        ]
        for j in jobs:
            j.result(timeout=120)
    finally:
        sched.shutdown()
    snap = _counters()
    assert snap["scheduler.jobs_admitted"] == 3
    assert snap["scheduler.jobs_completed"] == 3
    assert snap.get("scheduler.jobs_queued", 0) >= 1  # the third deferred
    assert violations == []  # never over budget, at ANY admission
    hwm = global_ledger().high_watermark
    assert need <= hwm <= int(2.2 * need) + 16


def test_respects_max_concurrent_cap(rng):
    df = _blob_df(rng)
    core_mod.config["sched_max_concurrent"] = 1
    peak = [0]
    sched = FitScheduler()
    try:
        jobs = [sched.submit(_mk_kmeans(), df, tenant=f"t{i}") for i in range(3)]
        while not all(j.done() for j in jobs):
            with sched._lock:
                peak[0] = max(peak[0], len(sched._running))
            time.sleep(0.005)
        for j in jobs:
            j.result(timeout=120)
    finally:
        sched.shutdown()
    assert peak[0] <= 1


def test_shutdown_fails_queued_jobs(rng):
    df = _blob_df(rng)
    need = _need_bytes(_mk_kmeans(), df)
    _set_budget(int(1.2 * need))  # one at a time: later submissions queue
    sched = FitScheduler()
    jobs = [
        sched.submit(_mk_kmeans(maxIter=30, tol=0.0), df, tenant=f"t{i}")
        for i in range(4)
    ]
    sched.shutdown(wait=True, timeout=120)
    states = {j.state for j in jobs}
    assert "failed" in states  # drained queue entries fail typed
    for j in jobs:
        if j.state == "failed":
            with pytest.raises(RuntimeError, match="shut down"):
                j.result(timeout=1)
    with pytest.raises(RuntimeError, match="shut down"):
        sched.submit(_mk_kmeans(), df)
    assert global_ledger().reserved_bytes() == 0


# ------------------------------------------------- preemption bit-identity --
# Deterministic unit-level preemption: the job's preempt flag is armed BEFORE
# the fit, so the solver yields at its FIRST checkpoint boundary; the resume
# re-enters with the same job-owned store. No scheduler timing involved.


def _preempt_then_resume(make_est, df):
    job = FitJob(99, make_est(), df, "t", 0)
    job.request_preempt("test preemption")
    with job_scope(job), ckpt.checkpoint_scope(store=job.store):
        with pytest.raises(PreemptedError) as ei:
            job.estimator.fit(df)
    assert ei.value.job_id == 99 and ei.value.iteration >= 1
    assert len(job.store) >= 1  # the boundary checkpoint survived the unwind
    job._preempt.clear()
    with job_scope(job), ckpt.checkpoint_scope(store=job.store):
        resumed = make_est().fit(df)
    return resumed


def test_preempted_kmeans_resumes_bit_identical(rng):
    df = _blob_df(rng)
    core_mod.config["checkpoint_every_iters"] = 3

    def make():
        return _mk_kmeans(k=8, maxIter=10, tol=0.0, seed=7)

    clean = make().fit(df)  # uninterrupted checkpointed fit
    telemetry.registry().reset()
    resumed = _preempt_then_resume(make, df)
    np.testing.assert_array_equal(resumed.cluster_centers_, clean.cluster_centers_)
    assert resumed.n_iter_ == clean.n_iter_
    assert _counters()["checkpoint.restores"] >= 1  # resumed, not restarted


def test_preempted_logistic_resumes_bit_identical(rng):
    df = _cls_df(rng)
    core_mod.config["checkpoint_every_iters"] = 4

    def make():
        est = LogisticRegression(maxIter=20)
        est.num_workers = 1
        return est

    clean = make().fit(df)
    telemetry.registry().reset()
    resumed = _preempt_then_resume(make, df)
    np.testing.assert_array_equal(resumed.coef_, clean.coef_)
    np.testing.assert_array_equal(resumed.intercept_, clean.intercept_)
    assert resumed.n_iter_ == clean.n_iter_
    assert _counters()["checkpoint.restores"] >= 1


def test_preempted_logistic_ell_resumes_bit_identical(rng):
    # the sparse (padded-ELL) solver path yields at the same segmented
    # boundary — preemption is layout-independent
    d = 20
    x = rng.normal(size=(1200, d))
    x = np.where(np.abs(x) > 1.0, x, 0.0)
    rows = [
        SparseVector(d, np.nonzero(r)[0].astype(np.int32), r[np.nonzero(r)[0]])
        for r in x
    ]
    y = (x @ rng.normal(size=d) > 0).astype(np.float64)
    df = pd.DataFrame({"features": rows, "label": y})
    core_mod.config["checkpoint_every_iters"] = 4

    def make():
        est = LogisticRegression(
            maxIter=20, regParam=0.01, enable_sparse_data_optim=True,
            float32_inputs=False,
        )
        est.num_workers = 1
        return est

    clean = make().fit(df)
    telemetry.registry().reset()
    resumed = _preempt_then_resume(make, df)
    np.testing.assert_array_equal(
        np.asarray(resumed.coef_), np.asarray(clean.coef_)
    )
    assert _counters()["checkpoint.restores"] >= 1


# ------------------------------------------------------ 3-tenant scenario ---


def test_three_tenants_preempt_resume_acceptance(rng):
    # THE acceptance scenario (ISSUE 12): a low-priority big fit running; a
    # high-priority small fit preempts it; a third tenant queues in between;
    # all complete. Pins: the preempted fit's final model is BIT-identical
    # to an uninterrupted checkpointed run, the ledger never exceeds the
    # budget AT ANY admission, and per-tenant scheduler.* telemetry rides
    # every job result.
    xb = rng.normal(size=(20_000, 32)).astype(np.float32)
    df_big = pd.DataFrame({"features": list(xb)})
    df_small = _blob_df(rng, n=500, d=32)
    core_mod.config["checkpoint_every_iters"] = 2

    def mk_big():
        return _mk_kmeans(k=16, maxIter=200, tol=0.0, seed=7)

    def mk_small():
        return _mk_kmeans(k=4, maxIter=5, seed=3)

    need_b = _need_bytes(mk_big(), df_big)
    need_s = _need_bytes(mk_small(), df_small)
    # the big fit fits ALONE; big + small does NOT — the high-priority small
    # job can only run by preempting
    _set_budget(int(need_b + 0.5 * need_s))

    ref = mk_big().fit(df_big)  # uninterrupted checkpointed reference

    violations = []
    budgets = []
    global_ledger().admission_hooks.append(
        lambda reserved, budget: (
            budgets.append(budget),
            violations.append(reserved) if budget is not None and reserved > budget else None,
        )
    )
    telemetry.registry().reset()
    sched = FitScheduler()
    try:
        mark = telemetry.registry().mark()
        job_big = sched.submit(mk_big(), df_big, tenant="batch", priority=0)
        # wait until the big fit is genuinely mid-solve (its OWN checkpoints)
        deadline = time.monotonic() + 120
        while (
            telemetry.registry().delta(mark)["counters"].get("checkpoint.saves", 0) < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        job_hi = sched.submit(mk_small(), df_small, tenant="interactive", priority=10)
        job_mid = sched.submit(mk_small(), df_small, tenant="reporting", priority=5)
        m_hi = job_hi.result(timeout=180)
        m_mid = job_mid.result(timeout=180)
        m_big = job_big.result(timeout=300)
    finally:
        sched.shutdown()

    # every tenant completed; the big fit was preempted and resumed
    snap = _counters()
    assert snap["scheduler.jobs_preempted"] >= 1
    assert snap["scheduler.jobs_resumed"] >= 1
    assert snap["checkpoint.restores"] >= 1
    assert job_big.preemptions >= 1 and job_big.state == "completed"
    # bit-identical to the uninterrupted checkpointed fit — zero lost work
    np.testing.assert_array_equal(
        np.asarray(m_big.cluster_centers_), np.asarray(ref.cluster_centers_)
    )
    assert m_big.n_iter_ == ref.n_iter_
    # the ledger never exceeded the budget, checked at EVERY admission
    assert violations == [] and len(budgets) >= 3
    assert global_ledger().reserved_bytes() == 0
    # per-tenant scheduler telemetry present in each job result
    for model, tenant in ((m_big, "batch"), (m_hi, "interactive"), (m_mid, "reporting")):
        st = model._fit_metrics["scheduler"]
        assert st["tenant"] == tenant
        assert st["queue_wait_s"] >= 0.0 and "hbm_share" in st
    assert m_big._fit_metrics["scheduler"]["preemptions"] >= 1
    # the high-priority tenant never waited for the whole big fit
    assert m_hi._fit_metrics["scheduler"]["queue_wait_s"] < job_big.run_s + 60


# ------------------------------------------------------------- demotion -----


def test_preempted_too_often_job_demotes_to_streaming(rng):
    # sched_max_preemptions=1: the FIRST preemption demotes the job — its
    # re-admission runs the out-of-core streaming path (floor footprint,
    # always packable) and the model carries the stream verdict. The
    # preemption is requested directly on the job handle so the test is
    # deterministic regardless of solver speed (the scheduler-initiated
    # request path is pinned by the 3-tenant acceptance test above).
    x = rng.normal(size=(60_000, 32))
    y = (x @ rng.normal(size=32) > 0).astype(np.float64)
    df_big = pd.DataFrame({"features": list(x), "label": y})
    core_mod.config["checkpoint_every_iters"] = 2
    core_mod.config["sched_max_preemptions"] = 1

    def mk_big():
        est = LogisticRegression(maxIter=40, tol=0.0, regParam=1e-4)
        est.num_workers = 1
        return est

    _set_budget(int(1.5 * _need_bytes(mk_big(), df_big)))

    sched = FitScheduler()
    try:
        mark = telemetry.registry().mark()
        job_big = sched.submit(mk_big(), df_big, tenant="batch", priority=0)
        deadline = time.monotonic() + 120
        while (
            telemetry.registry().delta(mark)["counters"].get("checkpoint.saves", 0) < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        job_big.request_preempt("higher-priority tenant needs the reservation")
        m_big = job_big.result(timeout=600)
    finally:
        sched.shutdown()
    snap = _counters()
    assert snap["scheduler.jobs_preempted"] >= 1
    assert snap["scheduler.jobs_demoted"] == 1
    assert job_big.demoted and job_big.state == "completed"
    st = m_big._fit_metrics["scheduler"]
    assert st["demoted"] is True
    # the demoted re-admission really streamed (degraded-mode service)
    adm = m_big._fit_metrics["admission"]
    assert adm["verdict"] == "stream"
    assert "sched_max_preemptions" in adm["reason"]
    assert global_ledger().reserved_bytes() == 0


# ------------------------------------------------------------- refusals -----


def test_submit_refuses_never_fitting_job_typed(rng):
    df = _blob_df(rng, n=2000, d=16)
    core_mod.config["hbm_budget_bytes"] = 2000  # smaller than any floor
    sched = FitScheduler()
    try:
        with pytest.raises(SchedulerSaturatedError) as ei:
            sched.submit(_mk_kmeans(), df, tenant="hopeless")
        e = ei.value
        assert e.tenant == "hopeless"
        assert e.estimate_bytes and e.budget_bytes and e.largest_term
        assert e.largest_term in str(e)
        assert isinstance(e, MemoryError)  # mirrors HbmBudgetError's IS-A
        assert _counters()["scheduler.jobs_refused"] == 1
    finally:
        sched.shutdown()
    assert global_ledger().reserved_bytes() == 0


# -------------------------------------------------------- dead-job chaos ----


def test_dead_tenant_job_reclaims_reservation_and_queue_drains(rng):
    # chaos-killed tenant (the chaos_worker pattern: an injected stage fault
    # with the retry budget at zero = the fit dies abruptly): the scheduler
    # must reclaim the dead job's reservation and keep scheduling — a dead
    # tenant cannot wedge the queue
    df = _blob_df(rng)
    need = _need_bytes(_mk_kmeans(), df)
    _set_budget(int(1.2 * need))  # one job at a time: the second queues
    core_mod.config["fit_max_retries"] = 0
    chaos.set_fault_plan("fail:stage=fit:times=1")
    sched = FitScheduler()
    try:
        doomed = sched.submit(_mk_kmeans(), df, tenant="dead")
        survivor = sched.submit(_mk_kmeans(), df, tenant="alive")
        model = survivor.result(timeout=120)
        assert model is not None and survivor.state == "completed"
        with pytest.raises(Exception):
            doomed.result(timeout=60)
        assert doomed.state == "failed"
    finally:
        sched.shutdown()
    snap = _counters()
    assert snap["scheduler.jobs_failed"] == 1
    assert snap["scheduler.jobs_completed"] == 1
    assert global_ledger().reserved_bytes() == 0  # the dead job's claim reclaimed


# ------------------------------------------------------------- telemetry ----


def test_ledger_gauges_flow_through_registry(rng):
    df = _blob_df(rng)
    _set_budget(int(3 * _need_bytes(_mk_kmeans(), df)))
    sched = FitScheduler()
    try:
        sched.submit(_mk_kmeans(), df, tenant="a").result(timeout=120)
    finally:
        sched.shutdown()
    snap = telemetry.registry().snapshot()
    assert "scheduler.ledger_reserved_bytes" in snap["gauges"]
    assert "scheduler.ledger_utilization" in snap["gauges"]
    assert snap["histograms"].get("scheduler.queue_wait_s", {}).get("count", 0) >= 1
    assert snap["histograms"].get("scheduler.hbm_share", {}).get("count", 0) >= 1
    stats = sched.stats()
    assert stats["tenants"]["a"]["completed"] == 1
    assert stats["ledger_reserved_bytes"] == 0


# --------------------------------------------------- review regressions -----


def test_transient_retry_readmits_without_double_count(rng):
    # a retry re-enters admission while the failed attempt's reservation is
    # still held; the re-admission must hand that claim back first — a
    # resident fit at ~0.9x budget must NOT spuriously demote on retry (and
    # the retried model stays bit-identical, the PR-3 contract)
    df = _blob_df(rng)
    est_probe = _mk_kmeans(k=8, maxIter=10, tol=0.0, seed=7)
    need = _need_bytes(est_probe, df)
    _set_budget(int(1.1 * need))  # resident fits, but not twice over
    core_mod.config["checkpoint_every_iters"] = 3

    clean = _mk_kmeans(k=8, maxIter=10, tol=0.0, seed=7).fit(df)
    assert clean._fit_metrics["admission"]["verdict"] == "resident"

    chaos.set_fault_plan("fail:stage=solve:times=1")
    telemetry.registry().reset()
    retried = _mk_kmeans(k=8, maxIter=10, tol=0.0, seed=7).fit(df)
    snap = _counters()
    assert snap["fit.retries"] == 1
    assert snap.get("fit.demotions", 0) == 0  # NOT demoted by its own ghost
    assert retried._fit_metrics["admission"]["verdict"] == "resident"
    np.testing.assert_array_equal(retried.cluster_centers_, clean.cluster_centers_)
    assert global_ledger().reserved_bytes() == 0


def test_no_preemption_request_without_checkpoint_cadence(rng):
    # cadence 0: solvers never reach a yield point, so requesting preemption
    # would only freeze backfill — the blocked high-priority job waits for
    # completion instead, and the victim's flag is never set
    df = _blob_df(rng)
    need = _need_bytes(_mk_kmeans(), df)
    _set_budget(int(1.2 * need))
    core_mod.config["checkpoint_every_iters"] = 0
    sched = FitScheduler()
    try:
        low = sched.submit(_mk_kmeans(maxIter=30, tol=0.0), df, tenant="low", priority=0)
        hi = sched.submit(_mk_kmeans(), df, tenant="hi", priority=10)
        hi.result(timeout=120)
        low.result(timeout=120)
    finally:
        sched.shutdown()
    assert low.preemptions == 0 and not low.preempt_requested()
    assert _counters().get("scheduler.jobs_preempted", 0) == 0


def test_refused_jobs_appear_in_stats(rng):
    df = _blob_df(rng, n=2000, d=16)
    core_mod.config["hbm_budget_bytes"] = 2000
    sched = FitScheduler()
    try:
        with pytest.raises(SchedulerSaturatedError):
            sched.submit(_mk_kmeans(), df, tenant="hopeless")
        t = sched.stats()["tenants"]["hopeless"]
        assert t["jobs"] == 1 and t["failed"] == 1
    finally:
        sched.shutdown()


def test_package_level_fitscheduler_is_the_real_class():
    import spark_rapids_ml_tpu as pkg
    from spark_rapids_ml_tpu.scheduler import FitScheduler as real

    assert pkg.FitScheduler is real  # PEP 562 lazy export, not a wrapper
    sched = pkg.FitScheduler(ledger=HbmLedger())  # kwargs AND the class API
    assert isinstance(sched, pkg.FitScheduler)
    sched.shutdown()
