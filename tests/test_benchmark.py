#
# Benchmark suite smoke tests (reference tests/test_benchmark.py pattern):
# every per-algo benchmark runs end-to-end at tiny scale and reports sane
# timings/quality; gen_data generators produce the advertised statistics.
#
import os

import numpy as np
import pytest

from benchmark.benchmark_runner import ALGORITHMS, PROTOCOL


SMOKE = {
    "serving": ["--num_cols", "24", "--k", "16", "--n_requests", "32",
                "--concurrency", "4"],
    "ingest": ["--num_rows", "4000", "--num_cols", "64"],
    "pca": ["--num_rows", "2000", "--num_cols", "32"],
    "kmeans": ["--num_rows", "2000", "--num_cols", "16", "--k", "8", "--maxIter", "3"],
    "linear_regression": ["--num_rows", "2000", "--num_cols", "16"],
    "logistic_regression": ["--num_rows", "2000", "--num_cols", "16", "--maxIter", "10"],
    "random_forest": ["--num_rows", "1000", "--num_cols", "8", "--numTrees", "4",
                      "--maxDepth", "3", "--maxBins", "16"],
    "nearest_neighbors": ["--num_rows", "1000", "--num_cols", "8", "--k", "4",
                          "--num_queries", "64"],
    "approximate_nearest_neighbors": ["--num_rows", "1000", "--num_cols", "16", "--k", "4",
                                      "--num_queries", "64", "--nlist", "16", "--nprobe", "4"],
    "oocore": ["--num_rows", "4000", "--num_cols", "16", "--chunk_rows", "1024",
               "--maxIter", "3"],
    "scheduler": ["--num_rows", "4000", "--num_cols", "16", "--tenants", "2",
                  "--small_rows", "400", "--maxIter", "30",
                  "--checkpoint_every", "2"],
    "dbscan": ["--num_rows", "500", "--num_cols", "8", "--eps", "3.0"],
    "umap": ["--num_rows", "400", "--num_cols", "8", "--n_epochs", "30"],
}


@pytest.mark.parametrize("algo", sorted(SMOKE))
def test_benchmark_smoke(algo, tmp_path):
    report = str(tmp_path / "report.csv")
    row = ALGORITHMS[algo]().run(SMOKE[algo] + ["--report", report])
    assert row.get("fit_sec", row.get("kneighbors_sec", 0)) > 0
    assert os.path.exists(report)
    with open(report) as f:
        assert algo in f.read()


def test_benchmark_smoke_quality_scores(tmp_path):
    row = ALGORITHMS["pca"]().run(SMOKE["pca"])
    assert row["orthonormality_err"] < 1e-3
    row = ALGORITHMS["logistic_regression"]().run(SMOKE["logistic_regression"])
    assert row["accuracy"] > 0.8
    row = ALGORITHMS["linear_regression"]().run(SMOKE["linear_regression"])
    assert row["rmse_ols"] < 0.5


def test_benchmark_ivfpq_smoke(tmp_path):
    row = ALGORITHMS["approximate_nearest_neighbors"]().run(
        SMOKE["approximate_nearest_neighbors"] + ["--algorithm", "ivfpq"]
    )
    assert row["recall"] > 0.3


def test_protocol_covers_all_reference_configs():
    # the protocol list must carry every BASELINE.md config: both RF tasks,
    # all three linear configs, the kNN/ANN/DBSCAN/UMAP rows
    names = [n for n, _ in PROTOCOL]
    assert names.count("random_forest") == 2
    for required in ("pca", "kmeans", "linear_regression", "logistic_regression",
                     "nearest_neighbors", "approximate_nearest_neighbors", "dbscan", "umap"):
        assert required in names


def test_gen_data_cli(tmp_path):
    from benchmark.gen_data import main as gen_main

    out = str(tmp_path / "d.npz")
    gen_main(["regression", "--num_rows", "200", "--num_cols", "8", "--output", out])
    with np.load(out) as z:
        assert z["X"].shape == (200, 8)
        assert z["y"].shape == (200,)

    out2 = str(tmp_path / "s.npz")
    gen_main(["sparse_regression", "--num_rows", "300", "--num_cols", "50",
              "--density", "0.1", "--output", out2])
    with np.load(out2) as z:
        import scipy.sparse as sp

        x = sp.csr_matrix((z["data"], z["indices"], z["indptr"]), shape=tuple(z["shape"]))
        assert x.shape == (300, 50)
        assert 0.05 < x.nnz / (300 * 50) < 0.2


def test_gen_device_matches_spec(mesh8):
    from benchmark.gen_data import gen_classification_device, gen_low_rank_device

    X, w = gen_low_rank_device(1000, 24, mesh=mesh8, tile=256)
    assert X.shape == (1000, 24)
    xs = np.asarray(X)
    assert np.isfinite(xs).all()
    # low-rank + small noise: top singular values dominate
    s = np.linalg.svd(xs, compute_uv=False)
    assert s[15] > 5 * s[17]

    X2, y, _ = gen_classification_device(800, 16, n_classes=3, mesh=mesh8, tile=256)
    assert set(np.unique(np.asarray(y))) <= {0, 1, 2}
    assert len(np.unique(np.asarray(y))) == 3


def test_parquet_dataset_roundtrip(tmp_path):
    # the reference protocol's multi-file parquet layout: write N part files,
    # read them back bit-exact (benchmark/dataset_io.py)
    from benchmark.dataset_io import read_parquet_dataset, write_parquet_dataset

    rng = np.random.default_rng(0)
    X = rng.normal(size=(257, 9)).astype(np.float32)
    y = rng.normal(size=257)
    path = str(tmp_path / "ds")
    n_files = write_parquet_dataset(path, X, y, n_files=7)
    assert n_files == 7
    assert len(os.listdir(path)) == 7
    X2, y2 = read_parquet_dataset(path)
    np.testing.assert_array_equal(X2, X)
    np.testing.assert_allclose(y2, y)
    # no label
    path2 = str(tmp_path / "ds2")
    write_parquet_dataset(path2, X, None, n_files=3)
    X3, y3 = read_parquet_dataset(path2)
    np.testing.assert_array_equal(X3, X)
    assert y3 is None


def test_benchmark_dataset_path_lane(tmp_path):
    # benches consume --dataset_path (shared parquet) instead of generating
    from benchmark.dataset_io import write_parquet_dataset
    from benchmark.gen_data import gen_classification_host

    X, y = gen_classification_host(1500, 12, 2, 0)
    path = str(tmp_path / "clf")
    write_parquet_dataset(path, X, y, n_files=4)
    row = ALGORITHMS["logistic_regression"]().run(
        ["--dataset_path", path, "--maxIter", "10"]
    )
    assert row["num_rows"] == 1500 and row["num_cols"] == 12
    assert row["accuracy"] > 0.8


def test_benchmark_cpu_comparison_arm(tmp_path):
    # the accelerated-vs-CPU arm (reference base.py:50-61): sklearn fit runs
    # on the SAME host rows and the report carries cpu_fit_sec + speedup
    row = ALGORITHMS["pca"]().run(SMOKE["pca"] + ["--cpu_comparison"])
    assert row["cpu_fit_sec"] > 0
    assert "speedup_vs_cpu" in row
    row = ALGORITHMS["kmeans"]().run(SMOKE["kmeans"] + ["--cpu_comparison"])
    assert row["cpu_fit_sec"] > 0


def test_gen_data_cli_parquet(tmp_path):
    from benchmark.gen_data import main as gen_main

    out = str(tmp_path / "pq")
    gen_main(["regression", "--num_rows", "300", "--num_cols", "6",
              "--output", out, "--fmt", "parquet", "--n_files", "5"])
    from benchmark.dataset_io import read_parquet_dataset

    X, y = read_parquet_dataset(out)
    assert X.shape == (300, 6) and y is not None and len(y) == 300


def test_benchmark_cagra_smoke(tmp_path):
    row = ALGORITHMS["approximate_nearest_neighbors"]().run(
        ["--num_rows", "1200", "--num_cols", "16", "--k", "8",
         "--num_queries", "64", "--algorithm", "cagra",
         "--graph_degree", "24", "--intermediate_graph_degree", "32"]
    )
    assert row["recall"] >= 0.8
    assert row["build_sec"] > 0 and row["search_sec"] > 0


def test_benchmark_sparse_logistic_lane(tmp_path):
    # --density > 0: the padded-ELL lane over the partition-parallel generator
    # (benchmark/gen_data_distributed.py), streamed into ELL without full-CSR
    # materialization; quality = accuracy of the binarized-target fit
    report = str(tmp_path / "report.csv")
    row = ALGORITHMS["logistic_regression"]().run(
        ["--num_rows", "4000", "--num_cols", "100", "--density", "0.02",
         "--maxIter", "25", "--report", report]
    )
    assert row["fit_sec"] > 0
    assert row["accuracy"] > 0.75
    assert os.path.exists(report)


def test_benchmark_serving_lane(tmp_path):
    # the serving lane's acceptance numbers (docs/serving.md): p50 <= p99,
    # QPS > 0, prewarm happened, and — the bit-identity criterion — every
    # coalesced response equal to the same request served solo
    from benchmark.bench_serving import run_serving_bench

    out = run_serving_bench(
        n_cols=24, k=16, n_requests=32, concurrency=4,
        coalesce_window_ms=10.0, seed=3,
    )
    assert out["qps"] > 0 and out["rows_per_sec"] > 0
    assert 0 < out["p50_ms"] <= out["p99_ms"]
    assert out["prewarmed_programs"] > 0
    assert out["max_abs_diff"] == 0.0  # coalesced == solo, bitwise
    assert out["coalesced_batches"] >= 1  # micro-batching actually engaged


def test_bench_emit_embeds_latency_lanes(capsys):
    # bench.py's record carries the serving lane's p50/p99 under
    # latency_lanes — what benchmark/regression.py's latency gates read
    import json

    import bench

    bench.emit(
        {"pca": 1e5, "serving": 2e5},
        latency_lanes={"serving_p50_ms": 1.25, "serving_p99_ms": 4.5},
    )
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["latency_lanes"] == {"serving_p50_ms": 1.25, "serving_p99_ms": 4.5}
    assert rec["lanes"]["serving"] == 2e5
    assert "serving" in rec["geomean_lanes"]


def test_benchmark_ingest_records_chunked_vs_monolithic(tmp_path):
    # tentpole acceptance: the suite records chunked vs monolithic ingest wall
    # time side by side
    row = ALGORITHMS["ingest"]().run(["--num_rows", "20000", "--num_cols", "128"])
    assert row["fit_sec"] > 0  # chunked placement
    assert row["monolithic_place_sec"] > 0
    assert row["extract_sec"] > 0
