#
# Fault-tolerant control-plane tests: the fault-injection suite that PROVES
# docs/robustness.md. A rank that dies mid-fit must become a prompt, TYPED,
# correctly-attributed error on every survivor — never a hang, never a raw
# threading.BrokenBarrierError — and a transient fault must retry to a
# bit-identical model.
#
import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid

import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu.errors import (
    RankFailedError,
    RendezvousTimeoutError,
    SolverDivergedError,
    SrmlError,
)
from spark_rapids_ml_tpu.parallel import (
    ChaosRendezvous,
    FileRendezvous,
    LocalRendezvous,
    Rendezvous,
    TpuContext,
)
from spark_rapids_ml_tpu.parallel import chaos

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clean_chaos_plan():
    chaos.clear_fault_plan()
    yield
    chaos.clear_fault_plan()


@pytest.fixture
def fast_backoff():
    saved = core_mod.config["fit_retry_backoff_s"]
    core_mod.config["fit_retry_backoff_s"] = 0.01
    yield
    core_mod.config["fit_retry_backoff_s"] = saved


# ---------------------------------------------------------------- plan spec --


def test_fault_plan_parsing():
    plan = chaos.parse_fault_plan(
        "kill:rank=1:round=3; delay:rank=0:round=2:seconds=0.5;"
        "abort:rank=2:round=1:reason=boom; drop:rank=1:round=4:times=2;"
        "fail:stage=fit:times=1"
    )
    kinds = [f.kind for f in plan]
    assert kinds == ["kill", "delay", "abort", "drop", "fail"]
    assert plan[0].rank == 1 and plan[0].round == 3 and plan[0].times == 1
    assert plan[1].seconds == 0.5
    assert plan[2].reason == "boom"
    assert plan[3].times == 2
    assert plan[4].stage == "fit"


@pytest.mark.parametrize(
    "bad",
    [
        "explode:rank=1:round=0",  # unknown kind
        "kill:rank=1",  # missing round
        "fail:times=1",  # missing stage
        "kill:rank1:round=0",  # malformed field
        "kill:rank=1:round=0:color=red",  # unknown field
    ],
)
def test_fault_plan_rejects_malformed(bad):
    with pytest.raises(ValueError):
        chaos.parse_fault_plan(bad)


def test_fault_plan_parses_burst():
    plan = chaos.parse_fault_plan("burst:stage=serve:rows=4096:seconds=2")
    assert [f.kind for f in plan] == ["burst"]
    assert plan[0].stage == "serve"
    assert plan[0].rows == 4096
    assert plan[0].seconds == 2.0
    assert plan[0].times == 1


@pytest.mark.parametrize(
    "bad",
    [
        "burst:rows=4096:seconds=2",  # missing stage
        "burst:stage=serve:seconds=2",  # missing rows
        "burst:stage=serve:rows=4096",  # missing seconds
        "burst:stage=serve:rows=0:seconds=2",  # zero load is a typo
        "burst:stage=serve:rows=4096:seconds=0",  # zero duration is a typo
    ],
)
def test_fault_plan_rejects_malformed_burst(bad):
    with pytest.raises(ValueError):
        chaos.parse_fault_plan(bad)


def test_maybe_burst_stage_consumes_one_firing():
    chaos.set_fault_plan("burst:stage=serve:rows=128:seconds=1")
    try:
        # wrong stage leaves the entry un-spent
        assert chaos.maybe_burst_stage("fit") is None
        fault = chaos.maybe_burst_stage("serve")
        assert fault is not None
        assert fault.rows == 128 and fault.seconds == 1.0
        # the firing was consumed: the same entry never fires twice
        assert chaos.maybe_burst_stage("serve") is None
    finally:
        chaos.clear_fault_plan()


# ------------------------------------------------------- LocalRendezvous ----


def test_local_rendezvous_round_deadline_is_typed():
    # a peer that never arrives must surface as RendezvousTimeoutError (a
    # TimeoutError subclass), not threading.BrokenBarrierError
    rdv = LocalRendezvous.create(2, timeout_s=0.25)[0]
    t0 = time.monotonic()
    with pytest.raises(RendezvousTimeoutError) as ei:
        rdv.allgather("hello")
    assert time.monotonic() - t0 < 5.0
    assert isinstance(ei.value, TimeoutError) and isinstance(ei.value, SrmlError)
    assert ei.value.round_index == 0


def test_local_rendezvous_abort_wakes_peers_promptly():
    # rank 1 publishes ABORT while rank 0 is blocked in a round with a LONG
    # deadline: rank 0 must raise RankFailedError naming rank 1 well before
    # the deadline (no test relies on the round timeout elapsing)
    rvs = LocalRendezvous.create(2, timeout_s=60.0)
    err: list = [None]
    started = threading.Event()

    def work():
        started.set()
        try:
            rvs[0].allgather("payload")
        except Exception as e:  # noqa: BLE001 - capturing for assertion
            err[0] = e

    t = threading.Thread(target=work)
    t.start()
    started.wait()
    time.sleep(0.05)  # let rank 0 reach the barrier
    t0 = time.monotonic()
    rvs[1].abort("injected failure")
    t.join(timeout=10)
    assert not t.is_alive()
    assert time.monotonic() - t0 < 2.0
    assert isinstance(err[0], RankFailedError)
    assert err[0].failed_rank == 1
    assert "injected failure" in err[0].reason
    # the sentinel rode the extra slot write
    assert rvs[1]._shared.slots[1].startswith("ABORT:1:")
    # later rounds fail FAST (no waiting at all) while the abort stands
    t0 = time.monotonic()
    with pytest.raises(RankFailedError):
        rvs[0].allgather("again")
    assert time.monotonic() - t0 < 0.5


def test_local_rendezvous_begin_epoch_clears_abort():
    rvs = LocalRendezvous.create(2, timeout_s=5.0)
    rvs[1].abort("transient blip")
    with pytest.raises(RankFailedError):
        rvs[0].allgather("x")
    for r in rvs:
        r.begin_epoch(1)
    results = [None, None]

    def work(r):
        results[r] = rvs[r].allgather(f"rank{r}")

    threads = [threading.Thread(target=work, args=(r,)) for r in range(2)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert results[0] == results[1] == ["rank0", "rank1"]


# -------------------------------------------------------- FileRendezvous ----


def test_file_rendezvous_round_deadline_is_typed(tmp_path):
    rdv = FileRendezvous(
        0, 2, str(tmp_path), timeout_s=0.3, run_id="t", heartbeat_interval_s=60.0
    )
    try:
        with pytest.raises(RendezvousTimeoutError) as ei:
            rdv.allgather("x")
    finally:
        rdv.close()
    assert isinstance(ei.value, TimeoutError)  # back-compat with the old raise
    assert ei.value.missing_ranks == [1]
    assert ei.value.round_index == 0


def test_file_rendezvous_abort_file_detection(tmp_path):
    # rank 0 blocks in a round with a long deadline; rank 1 publishes its
    # abort file — rank 0 must raise RankFailedError within a poll tick
    r0 = FileRendezvous(
        0, 2, str(tmp_path), timeout_s=60.0, run_id="t", heartbeat_interval_s=60.0
    )
    r1 = FileRendezvous(
        1, 2, str(tmp_path), timeout_s=60.0, run_id="t", heartbeat_interval_s=60.0
    )
    err: list = [None]

    def work():
        try:
            r0.allgather("payload")
        except Exception as e:  # noqa: BLE001
            err[0] = e

    t = threading.Thread(target=work)
    t.start()
    time.sleep(0.1)
    t0 = time.monotonic()
    r1.abort("worker exception")
    t.join(timeout=10)
    r0.close()
    r1.close()
    assert not t.is_alive()
    assert time.monotonic() - t0 < 2.0
    assert isinstance(err[0], RankFailedError)
    assert err[0].failed_rank == 1 and "worker exception" in err[0].reason


def test_file_rendezvous_rejoin_marker_outranks_heartbeat(tmp_path):
    # a respawned incarnation of a dead rank resumes touching the SAME
    # heartbeat file from construction — so the corpse looks alive to a
    # survivor blocked in a round. The rejoin_wait marker (written at
    # rejoin() entry) is positive death evidence and must fire within a
    # failure-scan tick even while the heartbeat keeps progressing.
    r0 = FileRendezvous(
        0, 2, str(tmp_path), timeout_s=60.0, run_id="t", heartbeat_interval_s=0.1
    )
    # the respawn: same rank/root, heartbeating from construction (this is
    # exactly what masks the death), but stuck ahead of its reform vote
    r1_respawn = FileRendezvous(
        1, 2, str(tmp_path), timeout_s=60.0, run_id="t", heartbeat_interval_s=0.1
    )
    err: list = [None]

    def work():
        try:
            r0.allgather("payload")
        except Exception as e:  # noqa: BLE001
            err[0] = e

    t = threading.Thread(target=work)
    t.start()
    time.sleep(0.3)  # several heartbeat touches land: rank 1 "looks alive"
    t0 = time.monotonic()
    # what rejoin() publishes first
    with open(r1_respawn._rejoin_wait_path(1), "w") as f:
        f.write("{}")
    t.join(timeout=10)
    r0.close()
    r1_respawn.close()
    assert not t.is_alive()
    assert time.monotonic() - t0 < 2.0
    assert isinstance(err[0], RankFailedError)
    assert err[0].failed_rank == 1 and "rejoin" in err[0].reason


def test_file_rendezvous_stale_heartbeat_detection(tmp_path):
    # a rank that HEARTBEAT then died silently (no abort file) must be
    # declared failed once its heartbeat goes stale — well before the round
    # deadline
    interval = 0.2
    r0 = FileRendezvous(
        0, 2, str(tmp_path), timeout_s=60.0, heartbeat_interval_s=interval
    )
    # simulate rank 1: one heartbeat touch, then death (no round payload ever)
    hb1 = r0._heartbeat_path(1)
    with open(hb1, "w"):
        pass
    t0 = time.monotonic()
    try:
        with pytest.raises(RankFailedError) as ei:
            r0.allgather("x")
    finally:
        r0.close()
    elapsed = time.monotonic() - t0
    assert ei.value.failed_rank == 1
    assert "heartbeat" in ei.value.reason
    assert elapsed < 2 * interval + 1.0  # stale threshold 1.5x + poll slack


def test_file_rendezvous_epoch_namespacing(tmp_path):
    # an abort published in epoch 0 must NOT poison a retry in epoch 1
    r0 = FileRendezvous(
        0, 1, str(tmp_path), timeout_s=5.0, run_id="t", heartbeat_interval_s=60.0
    )
    r0.abort("attempt 0 failure")
    r0.begin_epoch(1)
    try:
        assert r0.allgather("fresh") == ["fresh"]
        assert r0._round == 1
    finally:
        r0.close()
    # the epoch-0 abort file exists with the documented name, untouched
    assert os.path.exists(os.path.join(r0.root, "abort_rank_0"))


# -------------------------------------------------------- ChaosRendezvous ---


def _run_ranks(rvs, rounds=3):
    """Drive all ranks through `rounds` allgathers; returns per-rank outcome
    (the exception instance or the last gather)."""
    out = [None] * len(rvs)

    def work(r):
        try:
            for i in range(rounds):
                out[r] = rvs[r].allgather(f"{r}:{i}")
        except Exception as e:  # noqa: BLE001
            out[r] = e

    threads = [threading.Thread(target=work, args=(r,)) for r in range(len(rvs))]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert not any(t.is_alive() for t in threads)
    return out


def test_chaos_delay_is_benign():
    inner = LocalRendezvous.create(2, timeout_s=30.0)
    plan = chaos.parse_fault_plan("delay:rank=0:round=1:seconds=0.05")
    rvs = [ChaosRendezvous(inner[0], plan), ChaosRendezvous(inner[1], [])]
    out = _run_ranks(rvs, rounds=3)
    assert out[0] == out[1] == ["0:2", "1:2"]
    assert plan[0].spent()


def test_chaos_abort_fault_blames_the_injected_rank():
    inner = LocalRendezvous.create(2, timeout_s=30.0)
    plan = chaos.parse_fault_plan("abort:rank=1:round=1:reason=injected")
    rvs = [ChaosRendezvous(inner[0], []), ChaosRendezvous(inner[1], plan)]
    out = _run_ranks(rvs, rounds=3)
    # the survivor gets the typed, attributed error
    assert isinstance(out[0], RankFailedError) and out[0].failed_rank == 1
    # the injected rank raised its own (chaos) error after publishing
    assert isinstance(out[1], RuntimeError) and "chaos" in str(out[1])


# ---------------------------------------------- subprocess kill-at-round ----


def _launch_chaos_workers(nranks, tmp_path, plan, *, rounds, heartbeat_s, timeout_s):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["SRML_FAULT_PLAN"] = plan
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rdv_dir = str(tmp_path / "rdv")
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir, exist_ok=True)
    run_id = uuid.uuid4().hex
    procs = [
        subprocess.Popen(
            [
                sys.executable, os.path.join(HERE, "chaos_worker.py"),
                str(r), str(nranks), rdv_dir, out_dir, run_id,
                str(rounds), str(heartbeat_s), str(timeout_s),
            ],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for r in range(nranks)
    ]
    outputs = [p.communicate(timeout=180)[0].decode() for p in procs]
    return out_dir, procs, outputs


def _read_json(path):
    with open(path) as f:
        return json.load(f)


def test_killed_rank_detected_within_heartbeat_budget(tmp_path):
    # THE acceptance scenario: SIGKILL a rank entering an arbitrary round
    # (no abort file, no atexit — heartbeats are the only evidence) and
    # require every survivor to raise RankFailedError blaming that rank
    # within 2x the heartbeat interval — NOT after the 60s round deadline.
    heartbeat_s = 0.75
    kill_round = 3
    out_dir, procs, outputs = _launch_chaos_workers(
        3, tmp_path, f"kill:rank=2:round={kill_round}",
        rounds=6, heartbeat_s=heartbeat_s, timeout_s=60.0,
    )
    assert procs[2].returncode == -signal.SIGKILL
    marks = _read_json(os.path.join(out_dir, "marks_rank2.json"))
    assert marks[-1]["round"] == kill_round  # died entering the planned round
    kill_t = marks[-1]["t"]
    for r in (0, 1):
        assert procs[r].returncode == 0, f"rank {r}:\n{outputs[r]}"
        res = _read_json(os.path.join(out_dir, f"result_rank{r}.json"))
        assert res["error"] == "RankFailedError", res
        assert res["failed_rank"] == 2
        assert res["rounds_done"] == kill_round
        detect_lag = res["detected_at"] - kill_t
        assert detect_lag < 2 * heartbeat_s, (
            f"rank {r} took {detect_lag:.2f}s to detect the kill "
            f"(budget {2 * heartbeat_s}s)"
        )


def test_aborting_rank_detected_within_poll_tick(tmp_path):
    # graceful failure: the failing rank PUBLISHES, so survivors don't even
    # need a heartbeat miss — detection is one poll tick
    out_dir, procs, outputs = _launch_chaos_workers(
        3, tmp_path, "abort:rank=1:round=2:reason=synthetic",
        rounds=5, heartbeat_s=5.0, timeout_s=60.0,
    )
    aborter = _read_json(os.path.join(out_dir, "result_rank1.json"))
    assert aborter["error"] == "RuntimeError"  # its own chaos raise
    for r in (0, 2):
        assert procs[r].returncode == 0, f"rank {r}:\n{outputs[r]}"
        res = _read_json(os.path.join(out_dir, f"result_rank{r}.json"))
        assert res["error"] == "RankFailedError", res
        assert res["failed_rank"] == 1
        assert "synthetic" in str(res)
        assert res["detected_at"] - aborter["detected_at"] < 2.0


# ---------------------------------------------------------- TpuContext ------


class _SpyRendezvous(Rendezvous):
    def __init__(self, nranks=2):
        self.rank = 0
        self.nranks = nranks
        self.aborted = []
        self.gathers = []

    def _allgather_impl(self, payload):
        self.gathers.append(payload)
        return [payload] * self.nranks

    def abort(self, reason):
        self.aborted.append(reason)


def test_tpu_context_exit_propagates_abort():
    spy = _SpyRendezvous()
    ctx = TpuContext(0, 2, spy)
    ctx.__exit__(RuntimeError, RuntimeError("solver blew up"), None)
    assert spy.aborted == ["RuntimeError: solver blew up"]
    assert spy.gathers == []  # no success barrier on the failure path


def test_tpu_context_exit_does_not_cascade_rank_failures():
    # relaying a PEER's failure must not publish a fresh abort: a cascade of
    # abort files would let later scanners blame a healthy survivor
    spy = _SpyRendezvous(nranks=3)
    ctx = TpuContext(0, 3, spy)
    err = RankFailedError(2, "root cause")
    ctx.__exit__(RankFailedError, err, None)
    assert spy.aborted == []


def test_tpu_context_teardown_swallows_peer_failure():
    # a peer that died AFTER our work completed surfaces at the teardown
    # barrier; our results are whole, so this is a warning, not a raise
    class _PeerDiedAtTeardown(_SpyRendezvous):
        def _allgather_impl(self, payload):
            raise RankFailedError(1, "died between solve and teardown")

    ctx = TpuContext(0, 2, _PeerDiedAtTeardown())
    ctx.__exit__(None, None, None)  # must not raise


def test_local_rendezvous_round_desync_is_typed_not_silent():
    # a straggler exchanging a DIFFERENT round's payload on the same barrier
    # must surface as the transient desync error on both sides — never as a
    # silent mixed-round gather
    rvs = LocalRendezvous.create(2, timeout_s=10.0)
    rvs[1]._round = 5  # straggler believes it is 5 rounds ahead
    out = _run_ranks(rvs, rounds=1)
    assert isinstance(out[0], RendezvousTimeoutError) and "desync" in str(out[0])
    assert isinstance(out[1], RendezvousTimeoutError) and "desync" in str(out[1])


def test_tpu_context_exit_success_barrier_runs():
    spy = _SpyRendezvous()
    ctx = TpuContext(0, 2, spy)
    ctx.__exit__(None, None, None)
    assert spy.gathers == [""]


def test_tpu_context_teardown_barrier_is_bounded():
    # peer already exited: the success-path barrier must time out after
    # config["teardown_timeout_s"] with a warning, NOT hang for the full
    # rendezvous deadline (satellite: bounded teardown)
    rdv = LocalRendezvous.create(2)[0]  # rank 1 will never arrive
    ctx = TpuContext(0, 2, rdv)
    saved = core_mod.config["teardown_timeout_s"]
    core_mod.config["teardown_timeout_s"] = 0.3
    t0 = time.monotonic()
    try:
        ctx.__exit__(None, None, None)  # must swallow the timeout
    finally:
        core_mod.config["teardown_timeout_s"] = saved
    assert time.monotonic() - t0 < 5.0


# ------------------------------------------------------- retryable_stage ----


def test_retryable_stage_retries_transient_and_resyncs_epochs(fast_backoff):
    calls, epochs = [], []

    class _R:
        def begin_epoch(self, e):
            epochs.append(e)

    def fn(attempt):
        calls.append(attempt)
        if attempt < 2:
            raise RendezvousTimeoutError("flaky round")
        return "ok"

    assert core_mod.retryable_stage(fn, stage="t", rendezvous=_R(), max_retries=3) == "ok"
    assert calls == [0, 1, 2]
    assert epochs == [1, 2]


def test_retryable_stage_permanent_errors_propagate_immediately(fast_backoff):
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise RankFailedError(1, "dead peer")

    with pytest.raises(RankFailedError):
        core_mod.retryable_stage(fn, stage="t", max_retries=3)
    assert calls == [0]  # permanent: no second attempt


def test_retryable_stage_bounded_exhaustion(fast_backoff):
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise RendezvousTimeoutError("always down")

    with pytest.raises(RendezvousTimeoutError):
        core_mod.retryable_stage(fn, stage="t", max_retries=2)
    assert calls == [0, 1, 2]  # initial try + 2 retries, then gives up


def test_retryable_stage_chaos_injection(fast_backoff):
    chaos.set_fault_plan("fail:stage=probe:times=1")
    calls = []
    result = core_mod.retryable_stage(
        lambda attempt: calls.append(attempt) or attempt, stage="probe", max_retries=2
    )
    assert result == 1 and calls == [1]  # attempt 0 was injected away


def test_fit_retry_is_bit_identical_and_counted(rng, fast_backoff):
    # acceptance: a fit interrupted by an injected transient rendezvous fault
    # retries and produces a BIT-IDENTICAL model; the retry counter reaches
    # model._fit_metrics and the telemetry snapshot (the bench JSON source)
    from spark_rapids_ml_tpu import telemetry
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    n, d = 400, 4
    x = rng.normal(size=(n, d))
    y = (x[:, 0] + 0.2 * rng.normal(size=n) > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(x), "label": y})

    def make():
        return LogisticRegression(maxIter=25, float32_inputs=False).setFeaturesCol(
            "features"
        )

    clean = make().fit(df)
    chaos.set_fault_plan("fail:stage=fit:times=1")
    telemetry.enable()
    try:
        retried = make().fit(df)
    finally:
        telemetry.disable()
    np.testing.assert_array_equal(np.asarray(retried.coef_), np.asarray(clean.coef_))
    np.testing.assert_array_equal(
        np.asarray(retried.intercept_), np.asarray(clean.intercept_)
    )
    assert retried.n_iter_ == clean.n_iter_
    assert retried._fit_metrics["counters"]["fit.retries"] == 1
    assert telemetry.snapshot()["counters"]["fit.retries"] >= 1


# ------------------------------------------------------ solver divergence ---


def test_kmeans_divergence_guard_carries_last_good(mesh8, rng):
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit
    from spark_rapids_ml_tpu.parallel import make_global_rows

    x = rng.normal(size=(64, 3)).astype(np.float64)
    x[5] = np.inf  # poisons sums -> centers -> the fetched shift scalar
    X, w, _ = make_global_rows(mesh8, x)
    centers0 = jnp.asarray(rng.normal(size=(4, 3)).astype(np.float64))
    with pytest.raises(SolverDivergedError) as ei:
        kmeans_fit(X, w, centers0, mesh=mesh8, max_iter=5, tol=0.0)
    e = ei.value
    assert e.solver == "kmeans"
    assert e.iteration >= 1
    assert np.isfinite(e.last_good["cluster_centers_"]).all()
    assert e.last_good["cluster_centers_"].shape == (4, 3)


def test_check_glm_result_guard():
    from spark_rapids_ml_tpu.ops.logistic import check_glm_result

    ok = {
        "coef_": np.ones((1, 2)), "intercept_": np.zeros(1),
        "objective_": 0.5, "n_iter_": 3,
    }
    assert check_glm_result(ok) is ok
    bad = {
        "coef_": np.array([[1.0, np.nan]]), "intercept_": np.zeros(1),
        "objective_": np.array(np.inf), "n_iter_": np.array(7),
    }
    with pytest.raises(SolverDivergedError) as ei:
        check_glm_result(bad)
    assert ei.value.solver == "logistic"
    assert ei.value.iteration == 7
    assert "intercept_" in ei.value.last_good  # the finite remainder survives
    assert "coef_" not in ei.value.last_good


def test_check_pca_state_guard():
    from spark_rapids_ml_tpu.ops.pca import check_pca_state

    ok = {
        "components_": np.eye(2), "explained_variance_": np.ones(2),
        "mean_": np.zeros(2), "explained_variance_ratio_": np.ones(2),
        "singular_values_": np.ones(2),
    }
    assert check_pca_state(ok, k=2) is ok
    bad = dict(ok, components_=np.full((2, 2), np.nan))
    with pytest.raises(SolverDivergedError) as ei:
        check_pca_state(bad, k=2)
    assert ei.value.solver == "pca" and ei.value.iteration == 0
    assert "mean_" in ei.value.last_good


# ------------------------------------- elastic recovery (subprocess) --------
# The chaos_worker `recover` mode: a small distributed Lloyd fit (numpy +
# rendezvous collectives — the control-plane shape of a real SPMD fit) under
# `core.recoverable_stage` with solver checkpoints on. SIGKILLs here are real
# process deaths on a real FileRendezvous plane.


def _lloyd_reference(iters):
    """Single-process reference of the harness fit: same dataset, same math,
    one shard. The distributed result re-associates the per-shard float64
    sums, so agreement is to reduction-order tolerance, not bitwise — the
    documented degraded-mesh contract (docs/robustness.md)."""
    from tests.chaos_worker import _lloyd_local_sums, _recover_dataset

    X, centers = _recover_dataset()
    for _ in range(iters):
        sums, counts = _lloyd_local_sums(X, centers)
        centers = np.where(
            counts[:, None] > 0,
            sums / np.maximum(counts[:, None], 1.0),
            centers,
        )
    return centers


def _launch_recover_workers(
    nranks, tmp_path, plan, *, iters, heartbeat_s, timeout_s,
    rejoin_grace_s=0.0, trace_id=None,
):
    """Launch `recover`-mode workers; returns (procs, spawn, out_dir,
    flightrec_dir). `spawn(rank, mode)` launches one more worker in the same
    run (the kill+rejoin harness respawns the victim with mode='rejoin')."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["SRML_FAULT_PLAN"] = plan
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SRML_TEST_REJOIN_GRACE"] = str(rejoin_grace_s)
    flightrec = str(tmp_path / "flightrec")
    env["SRML_FLIGHTREC_DIR"] = flightrec
    if trace_id:
        env["SRML_TRACE_ID"] = trace_id
    rdv_dir = str(tmp_path / "rdv")
    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir, exist_ok=True)
    run_id = uuid.uuid4().hex

    def spawn(rank, mode, **env_overrides):
        # a RESPAWNED victim must not inherit the plan that killed it: the
        # Fault `times` ledger is per-process, so the fresh incarnation would
        # re-fire the same kill at the same round and SIGKILL itself again —
        # exhausting the recovery budget (found the hard way)
        child_env = dict(env, **env_overrides)
        return subprocess.Popen(
            [
                sys.executable, os.path.join(HERE, "chaos_worker.py"),
                str(rank), str(nranks), rdv_dir, out_dir, run_id,
                str(iters), str(heartbeat_s), str(timeout_s), mode,
            ],
            env=child_env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )

    procs = [spawn(r, "recover") for r in range(nranks)]
    return procs, spawn, out_dir, flightrec


def test_sigkill_mid_solve_recovers_on_survivor_mesh(tmp_path):
    # THE elastic-recovery acceptance scenario: a 3-process FileRendezvous
    # fit, one rank SIGKILLed mid-solve. Survivors must reform to a 2-rank
    # group, RESUME from the collective-consistent checkpoint, and complete —
    # centers within the documented tolerance of the uninterrupted fit,
    # fit.recoveries == 1, and the post-mortem timeline naming the epoch.
    from spark_rapids_ml_tpu import diagnostics

    # Round arithmetic: allgather_ndarray is TWO control-plane rounds per
    # call (chunk-count agreement + data), so with the resume-consensus
    # gather first, iteration k occupies rounds (2k+2, 2k+3). Round 8 is
    # iteration 3 — AFTER the iteration-2 checkpoint landed, so survivors
    # must RESUME (restores >= 1), not restart. Heartbeat 2.0s: the 1.5x
    # staleness threshold must comfortably exceed scheduler pauses with
    # several worker processes sharing few cores (a 2-core CI box starved a
    # live rank's heartbeat thread past a 1.5s threshold — falsely killing
    # it mid-recovery), at the cost of slower detection (unasserted here).
    iters = 6
    trace_id = f"recover-{uuid.uuid4().hex[:8]}"
    procs, _, out_dir, flightrec = _launch_recover_workers(
        3, tmp_path, "kill:rank=2:round=8", iters=iters,
        heartbeat_s=2.0, timeout_s=45.0, trace_id=trace_id,
    )
    outputs = [p.communicate(timeout=180)[0].decode() for p in procs]
    assert procs[2].returncode == -signal.SIGKILL
    ref = _lloyd_reference(iters)
    for r in (0, 1):
        assert procs[r].returncode == 0, f"rank {r}:\n{outputs[r]}"
        res = _read_json(os.path.join(out_dir, f"result_rank{r}.json"))
        assert res["error"] is None, res
        assert res["live_final"] == [0, 1]
        assert res["generation"] == 1
        assert res["orig_rank"] == r
        np.testing.assert_allclose(res["centers"], ref, rtol=1e-9)
        c = res["counters"]
        assert c["fit.recoveries"] == 1
        assert c["recovery.epochs"] == 1
        assert c["recovery.rank_losses"] == 1
        assert c["rendezvous.reforms"] == 1
        # resumed from the checkpoint, not from scratch
        assert c["checkpoint.saves"] >= 1
        assert c["checkpoint.restores"] >= 1
    # survivors dumped their rings after the reform; the assembled
    # post-mortem names the failure AND the recovery epoch
    pm = diagnostics.assemble_postmortem(flightrec, nranks=3, trace_id=trace_id)
    assert pm["failed_rank"] == 2
    assert pm["recovery_epochs"] == [
        {"generation": 1, "survivors": [0, 1], "dead": [2]}
    ]
    text = diagnostics.render_postmortem(pm)
    assert "recovery epoch g1" in text and "survivors [0, 1]" in text


@pytest.mark.slow
def test_sigkill_then_rejoin_restores_full_strength(tmp_path):
    # kill+rejoin recovery injection: the victim is respawned after death and
    # rejoins at the epoch boundary — the reform window stays open
    # `recovery_rejoin_grace_s` — so the fit completes at FULL strength, the
    # fresh rank catching up from the resume-consensus round (it has no local
    # checkpoint; it adopts the most advanced member's).
    #
    # Slow lane: 4 python processes (one respawned mid-run) on a small CI box
    # stretch heartbeat/vote timing far past the nominal path — the fast lane
    # keeps the single-kill recovery acceptance test; heartbeat 3.0s buys the
    # respawn import + vote extra starvation headroom at the cost of slower
    # detection (unasserted here).
    iters = 6
    procs, spawn, out_dir, _ = _launch_recover_workers(
        3, tmp_path, "kill:rank=2:round=8:respawn=1", iters=iters,
        heartbeat_s=3.0, timeout_s=90.0, rejoin_grace_s=60.0,
    )
    assert procs[2].wait(timeout=120) == -signal.SIGKILL
    respawned = spawn(2, "rejoin", SRML_FAULT_PLAN="")
    outputs = [p.communicate(timeout=180)[0].decode() for p in procs[:2]]
    out2 = respawned.communicate(timeout=180)[0].decode()
    ref = _lloyd_reference(iters)
    for r, (rc, out) in enumerate(
        [(procs[0].returncode, outputs[0]), (procs[1].returncode, outputs[1]),
         (respawned.returncode, out2)]
    ):
        assert rc == 0, f"rank {r}:\n{out}"
        res = _read_json(os.path.join(out_dir, f"result_rank{r}.json"))
        assert res["error"] is None, res
        assert res["live_final"] == [0, 1, 2], res
        assert res["orig_rank"] == r
        np.testing.assert_allclose(res["centers"], ref, rtol=1e-9)


@pytest.mark.parametrize(
    "kill_round",
    [
        # kill-at-every-round sweep: wherever the SIGKILL lands — the resume-
        # consensus agreement round (0), its data round (1), the first solve
        # round (2), a post-checkpoint solve round (7), or the very last
        # round (11) — every kill point must end in CLEAN RECOVERY (here:
        # recovery budget 1 covers the single loss) or a typed error, within
        # the deadline budget. Never a hang: the communicate() timeout is the
        # hang detector. The fast lane keeps the two qualitatively distinct
        # extremes (death before first contact: no heartbeat file ever, only
        # the timeout path can surface it; and a post-checkpoint solve round:
        # the resume-not-restart proof lives in the acceptance test above,
        # which kills at a post-checkpoint solve round and asserts
        # checkpoint.restores) — the other points ride the nightly --runslow
        # lane, each test being 3 subprocesses (~9 s nominal, several× under
        # CI load).
        0,
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(7, marks=pytest.mark.slow),
        pytest.param(11, marks=pytest.mark.slow),
    ],
)
def test_kill_at_every_round_recovers_or_types(tmp_path, kill_round):
    iters = 5  # rounds per attempt: 2 consensus + 2 per Lloyd iteration
    procs, _, out_dir, _ = _launch_recover_workers(
        3, tmp_path, f"kill:rank=1:round={kill_round}", iters=iters,
        heartbeat_s=2.0, timeout_s=45.0,
    )
    outputs = [p.communicate(timeout=120)[0].decode() for p in procs]
    assert procs[1].returncode == -signal.SIGKILL
    ref = _lloyd_reference(iters)
    for r in (0, 2):
        assert procs[r].returncode == 0, f"rank {r}:\n{outputs[r]}"
        res = _read_json(os.path.join(out_dir, f"result_rank{r}.json"))
        assert res["error"] is None, res
        assert res["live_final"] == [0, 2]
        assert res["counters"]["fit.recoveries"] == 1
        np.testing.assert_allclose(res["centers"], ref, rtol=1e-9)
