#
# Large-scale sparse LogisticRegression (the reference's tests_large lane:
# tests_large/test_large_logistic_regression.py:16-23 fits 1e7 x 2200 sparse
# vectors at ~0.1% density). Nightly-gated with --runslow; run via
# `ci/test.sh --nightly`.
#
# Exercises the padded-ELL design (ops/sparse.py) at its design point: at
# 0.1% density the per-row nnz is Poisson(2.2), so k_max lands in the tens —
# the ELL tensor is ~n * k_max * 8 bytes (~1-2 GB at 1e7 rows), orders of
# magnitude below the 88 GB dense equivalent. Checked against sklearn fit on
# a row subsample: holdout accuracy must match and the coefficient supports
# must correlate.
#
import numpy as np
import pytest

pytestmark = pytest.mark.slow

N_ROWS = 10_000_000
N_COLS = 2200
DENSITY = 0.001


def _gen_sparse_classification(n, d, density, seed=0):
    """Labeled sparse dataset over the shared O(nnz) generator
    (tests/sparse_gen.py — see there for why scipy.sparse.random cannot be
    used at this shape)."""
    from tests.sparse_gen import random_csr

    rng = np.random.default_rng(seed)
    x = random_csr(rng, n, d, density)
    # DENSE coefficient support: at ~2.2 nnz/row, a sparse (d/10) support
    # leaves ~80% of rows with zero signal (label = coin flip) and caps
    # attainable accuracy near 0.6 — no solver could meet the bar below.
    # With full support, every nonzero row carries |signal| >> noise and the
    # ~11% all-zero rows are the only coin flips (accuracy ceiling ~0.94).
    coef = rng.normal(scale=4.0, size=d)
    logits = np.asarray(x @ coef) + 0.25 * rng.normal(size=n)
    y = (logits > 0).astype(np.float32)
    return x, y, coef


def test_large_sparse_logistic_regression():
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.logistic import logistic_fit_ell
    from spark_rapids_ml_tpu.ops.sparse import csr_to_ell, ell_matmul

    x, y, coef_true = _gen_sparse_classification(N_ROWS, N_COLS, DENSITY)

    indices, values, k_max = csr_to_ell(x, dtype=np.float32)
    # the ELL design point this test certifies: ~0.1% density => k_max in the
    # tens, memory ~ n*k_max*8 bytes (documented in ops/sparse.py:20-24)
    assert k_max <= 64, f"k_max {k_max} blows the padded-ELL budget"
    ell_bytes = values.nbytes + indices.nbytes
    assert ell_bytes < 6e9, f"ELL tensor {ell_bytes/1e9:.1f} GB"

    state = logistic_fit_ell(
        jax.device_put(values), jax.device_put(indices),
        jax.device_put(y.astype(np.int32)),
        jnp.ones((N_ROWS,), jnp.float32),
        d=N_COLS, k=2, multinomial=False,
        # standardize = the sparse SCALE-ONLY standardization (never centered)
        # — the reference's sparse path always fits this way
        # (classification.py:975-1098) and it is what keeps the badly-scaled
        # 0.1%-density problem conditioned for the quasi-Newton solver
        lam_l2=1e-6, fit_intercept=True, standardize=True,
        max_iter=60, tol=1e-12,
    )
    coef = np.asarray(state["coef_"], dtype=np.float64).ravel()
    intercept = float(np.asarray(state["intercept_"]).ravel()[0])

    # holdout scoring through the same ELL matmul (first 200k rows)
    n_h = 200_000
    zh = np.asarray(
        ell_matmul(
            jax.device_put(values[:n_h]),
            jax.device_put(indices[:n_h]),
            jax.device_put(coef.astype(np.float32)[:, None]),
        )
    ).ravel() + intercept  # ell_matmul takes (values, indices, B)
    acc_ours = float(((zh > 0) == (y[:n_h] > 0)).mean())

    # sklearn arm on a 500k-row subsample (the reference checks its large fit
    # against smaller-scale reference results the same way)
    from sklearn.linear_model import LogisticRegression as SkLR

    n_sub = 500_000
    sk = SkLR(C=1.0 / (n_sub * 1e-6), max_iter=200, tol=1e-10)
    sk.fit(x[:n_sub], y[:n_sub])
    zs = np.asarray(x[:n_h] @ sk.coef_.ravel()) + float(sk.intercept_[0])
    acc_sk = float(((zs > 0) == (y[:n_h] > 0)).mean())

    assert acc_ours >= 0.9, acc_ours
    assert acc_ours >= acc_sk - 0.01, (acc_ours, acc_sk)
    # coefficient agreement in direction (full-data fit vs subsample fit)
    cos = float(
        coef @ sk.coef_.ravel()
        / max(np.linalg.norm(coef) * np.linalg.norm(sk.coef_), 1e-30)
    )
    assert cos >= 0.97, cos
    # the true support should carry the signal
    cos_true = float(
        coef @ coef_true / max(np.linalg.norm(coef) * np.linalg.norm(coef_true), 1e-30)
    )
    assert cos_true >= 0.9, cos_true
