#
# Worker for the fault-injection harness (launched by tests/test_chaos.py;
# the non-test prefix keeps pytest from collecting it).
#
# Each rank drives a fixed number of control-plane rounds through a
# ChaosRendezvous(FileRendezvous) — pure rendezvous traffic, no fit, no XLA
# backend — with the fault plan inherited from SRML_FAULT_PLAN. Before each
# round it writes a timestamp mark (so the parent can date a SIGKILL to the
# round that triggered it), and on exit it writes a JSON result: rounds
# completed, the typed error class observed, which rank it blamed, and when.
#
# argv: rank nranks rdv_dir out_dir run_id rounds heartbeat_interval_s timeout_s
#
import json
import os
import sys
import time


def _write_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def main() -> None:
    rank = int(sys.argv[1])
    nranks = int(sys.argv[2])
    rdv_dir = sys.argv[3]
    out_dir = sys.argv[4]
    run_id = sys.argv[5]
    rounds = int(sys.argv[6])
    heartbeat_interval_s = float(sys.argv[7])
    timeout_s = float(sys.argv[8])

    from spark_rapids_ml_tpu import diagnostics
    from spark_rapids_ml_tpu.errors import RankFailedError, RendezvousTimeoutError
    from spark_rapids_ml_tpu.parallel.chaos import ChaosRendezvous
    from spark_rapids_ml_tpu.parallel.context import FileRendezvous

    # no TpuContext in this harness: pin the rank so flight-recorder events
    # and dumps (flightrec_rank_<r>.jsonl, written on the typed errors below
    # when SRML_FLIGHTREC_DIR is set) are attributed per rank, not all rank 0
    diagnostics.set_process_rank(rank)
    rdv = ChaosRendezvous(
        FileRendezvous(
            rank,
            nranks,
            rdv_dir,
            timeout_s=timeout_s,
            run_id=run_id,
            heartbeat_interval_s=heartbeat_interval_s,
        )
    )
    result = {
        "rank": rank,
        "rounds_done": 0,
        "error": None,
        "failed_rank": None,
        "round_index": None,
        "detected_at": None,
    }
    marks = []
    try:
        for i in range(rounds):
            # mark BEFORE joining the round: a kill fault fires on entry, so
            # the victim's last mark timestamps the kill to within the write
            marks.append({"round": i, "t": time.time()})
            _write_json(os.path.join(out_dir, f"marks_rank{rank}.json"), marks)
            out = rdv.allgather(f"r{rank}:{i}")
            assert out == [f"r{r}:{i}" for r in range(nranks)], out
            result["rounds_done"] = i + 1
    except RankFailedError as e:
        result["error"] = "RankFailedError"
        result["failed_rank"] = e.failed_rank
        result["reason"] = e.reason
        result["round_index"] = e.round_index
        result["detected_at"] = time.time()
    except RendezvousTimeoutError as e:
        result["error"] = "RendezvousTimeoutError"
        result["round_index"] = e.round_index
        result["detected_at"] = time.time()
    except Exception as e:  # noqa: BLE001 - e.g. the chaos abort fault's own raise
        result["error"] = type(e).__name__
        result["detail"] = str(e)
        result["detected_at"] = time.time()
    finally:
        rdv.close()
    _write_json(os.path.join(out_dir, f"result_rank{rank}.json"), result)


if __name__ == "__main__":
    main()
