#
# Worker for the fault-injection harness (launched by tests/test_chaos.py;
# the non-test prefix keeps pytest from collecting it).
#
# Modes (argv[9], default "rounds"):
#
#   rounds    Each rank drives a fixed number of control-plane rounds through
#             a ChaosRendezvous(FileRendezvous) — pure rendezvous traffic, no
#             fit, no XLA backend — with the fault plan inherited from
#             SRML_FAULT_PLAN. Before each round it writes a timestamp mark
#             (so the parent can date a SIGKILL to the round that triggered
#             it), and on exit a JSON result: rounds completed, the typed
#             error class observed, which rank it blamed, and when.
#
#   recover   The ELASTIC-RECOVERY harness: each rank runs a small
#             distributed Lloyd fit (numpy + rendezvous collectives — the
#             control-plane shape of a real SPMD fit without needing
#             cross-process XLA) under `core.recoverable_stage` with solver
#             checkpoints on. The dataset derives from a fixed seed (the
#             host-retained-ingest analog: every survivor can re-derive the
#             full row set), sharded over the CURRENT live rank set. A
#             SIGKILLed peer surfaces as RankFailedError; survivors reform,
#             re-shard, and RESUME from the collective-consistent checkpoint
#             — the per-attempt resume-consensus round adopts the most
#             advanced member checkpoint, which also lets a rejoining rank
#             catch up. `rounds` argv = Lloyd iterations.
#
# argv: rank nranks rdv_dir out_dir run_id rounds heartbeat_interval_s timeout_s [mode]
#
import json
import os
import sys
import time


def _write_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _recover_dataset(n_rows: int = 240, d: int = 4, k: int = 3):
    """Deterministic dataset + init — derivable by every rank (and any
    respawned incarnation) from the seed alone."""
    import numpy as np

    rng = np.random.default_rng(1234)
    offsets = rng.normal(scale=6.0, size=(k, d))
    X = np.concatenate(
        [rng.normal(size=(n_rows // k, d)) + offsets[c] for c in range(k)]
    ).astype(np.float64)
    init = X[rng.choice(len(X), size=k, replace=False)].copy()
    return X, init


def _lloyd_local_sums(X_shard, centers):
    import numpy as np

    d2 = (
        np.sum(centers * centers, axis=1)[None, :]
        - 2.0 * (X_shard @ centers.T)
    )
    assign = np.argmin(d2, axis=1)
    k, d = centers.shape
    sums = np.zeros((k, d))
    counts = np.zeros(k)
    for c in range(k):
        m = assign == c
        counts[c] = m.sum()
        sums[c] = X_shard[m].sum(axis=0)
    return sums, counts


def recover_main(
    rank: int, nranks: int, rdv_dir: str, out_dir: str, run_id: str,
    iters: int, heartbeat_interval_s: float, timeout_s: float, *, rejoin: bool,
) -> None:
    import numpy as np

    from spark_rapids_ml_tpu import checkpoint as ckpt
    from spark_rapids_ml_tpu import core, diagnostics, telemetry
    from spark_rapids_ml_tpu.errors import SrmlError
    from spark_rapids_ml_tpu.parallel.chaos import ChaosRendezvous
    from spark_rapids_ml_tpu.parallel.context import FileRendezvous, allgather_ndarray

    diagnostics.set_process_rank(rank)
    telemetry.enable()
    core.config["checkpoint_every_iters"] = 2
    core.config["heartbeat_interval_s"] = heartbeat_interval_s
    # kill+rejoin runs: the launcher keeps the reform window open long enough
    # for the respawned incarnation to import + vote
    core.config["recovery_rejoin_grace_s"] = float(
        os.environ.get("SRML_TEST_REJOIN_GRACE", "0")
    )

    base = FileRendezvous(
        rank, nranks, rdv_dir, timeout_s=timeout_s, run_id=run_id,
        heartbeat_interval_s=heartbeat_interval_s,
    )
    if rejoin:
        # respawned incarnation: vote in the open reform window and join the
        # reformed group at the epoch boundary
        base = base.rejoin()
    rdv = ChaosRendezvous(base)
    holder = {"rdv": rdv}

    X, init = _recover_dataset()
    k = init.shape[0]

    def fit(attempt: int):
        r = holder["rdv"]
        store = ckpt.active_store()
        live = r.live_ranks
        # survivor re-sharding: the FULL row set re-partitions over the
        # CURRENT membership (host-retained: re-derived from the seed)
        bounds = np.linspace(0, len(X), r.nranks + 1).astype(int)
        shard = X[bounds[r.rank]: bounds[r.rank + 1]]
        # resume consensus: adopt the most advanced member checkpoint, so
        # survivors resume together and a rejoined (fresh) rank catches up
        saved = store.load("centers") if store is not None else None
        it0 = 0 if saved is None else int(saved.iteration)
        centers = init.copy() if saved is None else saved.state["centers"]
        packed = np.concatenate([[float(it0)], centers.ravel()])
        gathered = allgather_ndarray(r, packed)
        best = max(range(len(gathered)), key=lambda i: (gathered[i][0], -i))
        it0 = int(gathered[best][0])
        centers = gathered[best][1:].reshape(centers.shape)
        for it in range(it0, iters):
            sums, counts = _lloyd_local_sums(shard, centers)
            packed = np.concatenate([sums, counts[:, None]], axis=1)
            total = np.sum(allgather_ndarray(r, packed[None, ...]), axis=0)[0]
            g_sums, g_counts = total[:, :-1], total[:, -1]
            centers = np.where(
                g_counts[:, None] > 0,
                g_sums / np.maximum(g_counts[:, None], 1.0),
                centers,
            )
            if store is not None and (it + 1) % 2 == 0:
                store.save("centers", ckpt.SolverCheckpoint(
                    solver="harness_kmeans", iteration=it + 1,
                    state={"centers": centers.copy()},
                ))
        return centers

    result = {"rank": rank, "error": None}
    try:
        centers = core.recoverable_stage(
            fit, stage="fit", rendezvous=rdv,
            on_recover=lambda new_rdv, gen, dead: holder.update(rdv=new_rdv),
        )
        final = holder["rdv"]
        result.update(
            centers=np.asarray(centers).tolist(),
            live_final=list(final.live_ranks),
            generation=int(getattr(final, "reform_generation", 0)),
            orig_rank=int(final.orig_rank),
        )
    except SrmlError as e:
        result["error"] = type(e).__name__
        result["detail"] = str(e)
    except Exception as e:  # noqa: BLE001 - typed classification is the point
        result["error"] = type(e).__name__
        result["detail"] = str(e)
    finally:
        holder["rdv"].close()
    counters = telemetry.registry().snapshot().get("counters", {})
    result["counters"] = {
        key: counters.get(key)
        for key in (
            "fit.recoveries", "recovery.epochs", "recovery.rank_losses",
            "rendezvous.reforms", "checkpoint.saves", "checkpoint.restores",
            "fit.retries",
        )
    }
    _write_json(os.path.join(out_dir, f"result_rank{rank}.json"), result)


def main() -> None:
    rank = int(sys.argv[1])
    nranks = int(sys.argv[2])
    rdv_dir = sys.argv[3]
    out_dir = sys.argv[4]
    run_id = sys.argv[5]
    rounds = int(sys.argv[6])
    heartbeat_interval_s = float(sys.argv[7])
    timeout_s = float(sys.argv[8])
    mode = sys.argv[9] if len(sys.argv) > 9 else "rounds"

    if mode in ("recover", "rejoin"):
        recover_main(
            rank, nranks, rdv_dir, out_dir, run_id, rounds,
            heartbeat_interval_s, timeout_s, rejoin=(mode == "rejoin"),
        )
        return

    from spark_rapids_ml_tpu import diagnostics
    from spark_rapids_ml_tpu.errors import RankFailedError, RendezvousTimeoutError
    from spark_rapids_ml_tpu.parallel.chaos import ChaosRendezvous
    from spark_rapids_ml_tpu.parallel.context import FileRendezvous

    # no TpuContext in this harness: pin the rank so flight-recorder events
    # and dumps (flightrec_rank_<r>.jsonl, written on the typed errors below
    # when SRML_FLIGHTREC_DIR is set) are attributed per rank, not all rank 0
    diagnostics.set_process_rank(rank)
    rdv = ChaosRendezvous(
        FileRendezvous(
            rank,
            nranks,
            rdv_dir,
            timeout_s=timeout_s,
            run_id=run_id,
            heartbeat_interval_s=heartbeat_interval_s,
        )
    )
    result = {
        "rank": rank,
        "rounds_done": 0,
        "error": None,
        "failed_rank": None,
        "round_index": None,
        "detected_at": None,
    }
    marks = []
    try:
        for i in range(rounds):
            # mark BEFORE joining the round: a kill fault fires on entry, so
            # the victim's last mark timestamps the kill to within the write
            marks.append({"round": i, "t": time.time()})
            _write_json(os.path.join(out_dir, f"marks_rank{rank}.json"), marks)
            out = rdv.allgather(f"r{rank}:{i}")
            assert out == [f"r{r}:{i}" for r in range(nranks)], out
            result["rounds_done"] = i + 1
    except RankFailedError as e:
        result["error"] = "RankFailedError"
        result["failed_rank"] = e.failed_rank
        result["reason"] = e.reason
        result["round_index"] = e.round_index
        result["detected_at"] = time.time()
    except RendezvousTimeoutError as e:
        result["error"] = "RendezvousTimeoutError"
        result["round_index"] = e.round_index
        result["detected_at"] = time.time()
    except Exception as e:  # noqa: BLE001 - e.g. the chaos abort fault's own raise
        result["error"] = type(e).__name__
        result["detail"] = str(e)
        result["detected_at"] = time.time()
    finally:
        rdv.close()
    _write_json(os.path.join(out_dir, f"result_rank{rank}.json"), result)


if __name__ == "__main__":
    main()
