#
# Fixture corpus for the whole-program concurrency rules (ci/analysis
# rules/concurrency.py over the program.py pass-1 model): per rule at least
# one true positive and one false-positive guard, including the cross-file
# lock-order cycle that PER-FILE analysis provably cannot see, the
# re-entrant RLock non-finding, and `with a, b` ordering. Plus the
# content-hash cache (unchanged files skip re-parsing, edits invalidate)
# and `--explain`.
#
import json
import pathlib
import sys
import textwrap
import threading
import time

import pytest

ROOT = pathlib.Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from ci.analysis import analyze_source, analyze_sources  # noqa: E402
from ci.analysis.cli import main as cli_main  # noqa: E402
from ci.analysis.rules import (  # noqa: E402
    BlockingUnderLockRule,
    GuardDisciplineRule,
    LockOrderRule,
)


def run(src, rule_factory, relpath="spark_rapids_ml_tpu/snippet.py"):
    return analyze_source(textwrap.dedent(src), relpath=relpath, rules=[rule_factory()])


def run_files(files, rule_factory):
    return analyze_sources(
        {rel: textwrap.dedent(src) for rel, src in files.items()},
        rules=[rule_factory()],
    )


def rule_ids(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------------------
# lock-order
# --------------------------------------------------------------------------


def test_lock_order_same_file_inversion_fires():
    src = """
    import threading
    _A = threading.Lock()
    _B = threading.Lock()
    def forward():
        with _A:
            with _B:
                pass
    def backward():
        with _B:
            with _A:
                pass
    """
    fs = run(src, LockOrderRule)
    assert rule_ids(fs) == ["lock-order"]
    assert "snippet._A" in fs[0].message and "snippet._B" in fs[0].message


def test_lock_order_consistent_global_order_passes():
    src = """
    import threading
    _A = threading.Lock()
    _B = threading.Lock()
    def one():
        with _A:
            with _B:
                pass
    def two():
        with _A:
            with _B:
                pass
    """
    assert run(src, LockOrderRule) == []


def test_lock_order_with_tuple_item_ordering():
    # `with a, b` acquires in item order — an inverted pair elsewhere cycles
    src = """
    import threading
    _A = threading.Lock()
    _B = threading.Lock()
    def one():
        with _A, _B:
            pass
    def two():
        with _B, _A:
            pass
    """
    fs = run(src, LockOrderRule)
    assert rule_ids(fs) == ["lock-order"]
    consistent = """
    import threading
    _A = threading.Lock()
    _B = threading.Lock()
    def one():
        with _A, _B:
            pass
    def two():
        with _A, _B:
            pass
    """
    assert run(consistent, LockOrderRule) == []


def test_lock_order_reentrant_rlock_is_not_a_finding():
    src = """
    import threading
    class R:
        def __init__(self):
            self._lock = threading.RLock()
        def outer(self):
            with self._lock:
                self.inner()
        def inner(self):
            with self._lock:
                pass
    """
    assert run(src, LockOrderRule) == []


def test_lock_order_plain_lock_self_reacquire_is_self_deadlock():
    src = """
    import threading
    _L = threading.Lock()
    def f():
        with _L:
            with _L:
                pass
    """
    fs = run(src, LockOrderRule)
    assert rule_ids(fs) == ["lock-order"]
    assert "self-deadlock" in fs[0].message


_CYCLE_FILE_A = """
import threading
class FixLedger:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
    def forward(self):
        with self._alock:
            self.inner()
    def inner(self):
        with self._block:
            pass
    def callback(self, sched):
        with self._block:
            sched.poke()
"""

_CYCLE_FILE_B = """
import threading
from .fix_ledger import FixLedger
class FixSched:
    def __init__(self):
        self._slock = threading.Lock()
        self._ledger = FixLedger()
    def schedule(self):
        with self._slock:
            self._ledger.forward()
    def poke(self):
        with self._slock:
            pass
"""


def test_lock_order_cross_file_cycle_via_call_graph():
    # the acceptance fixture: the inversion is SPLIT across two files —
    # schedule() holds slock and (through forward()) acquires block, while
    # callback() holds block and (through poke()) acquires slock
    fs = run_files(
        {
            "spark_rapids_ml_tpu/fix_ledger.py": _CYCLE_FILE_A,
            "spark_rapids_ml_tpu/fix_sched.py": _CYCLE_FILE_B,
        },
        LockOrderRule,
    )
    assert "lock-order" in rule_ids(fs)
    assert any("fix_sched.FixSched._slock" in f.message for f in fs)


def test_lock_order_cross_file_cycle_invisible_per_file():
    # each HALF alone is clean: per-file analysis cannot see this bug
    assert (
        run(_CYCLE_FILE_A, LockOrderRule, relpath="spark_rapids_ml_tpu/fix_ledger.py")
        == []
    )
    assert (
        run(_CYCLE_FILE_B, LockOrderRule, relpath="spark_rapids_ml_tpu/fix_sched.py")
        == []
    )


def test_lock_order_waiver_breaks_the_edge():
    src = """
    import threading
    _A = threading.Lock()
    _B = threading.Lock()
    def forward():
        with _A:
            with _B:
                pass
    def backward():
        with _B:
            with _A:  # lock-order-ok: fixture rationale — B->A path cannot run concurrently with forward()
                pass
    """
    assert run(src, LockOrderRule) == []


def test_lock_order_multi_cycle_scc_does_not_crash():
    # regression: a greedy cycle walk could dead-end in an SCC with
    # branching (A->B, B->C, B->D, C->B, D->A) and fabricate a closing
    # edge that was never recorded — KeyError out of finalize, crashing
    # the gate exactly when a complex deadlock exists
    src = """
    import threading
    _A = threading.Lock(); _B = threading.Lock(); _C = threading.Lock(); _D = threading.Lock()
    def e1():
        with _A:
            with _B: pass
    def e2():
        with _B:
            with _C: pass
    def e3():
        with _B:
            with _D: pass
    def e4():
        with _C:
            with _B: pass
    def e5():
        with _D:
            with _A: pass
    """
    fs = run(src, LockOrderRule)
    assert fs and all(f.rule == "lock-order" for f in fs)


def test_lock_order_through_lock_returning_helper():
    # `with self.admission():` — acquisition through a lock-returning helper
    src = """
    import threading
    class L:
        def __init__(self):
            self._lock = threading.Lock()
            self._admission = threading.Lock()
        def admission(self):
            return self._admission
        def forward(self):
            with self.admission():
                with self._lock:
                    pass
        def backward(self):
            with self._lock:
                with self.admission():
                    pass
    """
    fs = run(src, LockOrderRule)
    assert rule_ids(fs) == ["lock-order"]
    assert "_admission" in fs[0].message


# --------------------------------------------------------------------------
# blocking-under-lock
# --------------------------------------------------------------------------


def test_blocking_sleep_under_lock_fires():
    src = """
    import threading
    import time
    _L = threading.Lock()
    def f():
        with _L:
            time.sleep(0.5)
    """
    fs = run(src, BlockingUnderLockRule)
    assert rule_ids(fs) == ["blocking-under-lock"]
    assert "time.sleep" in fs[0].message


def test_blocking_sleep_outside_lock_passes():
    src = """
    import threading
    import time
    _L = threading.Lock()
    def f():
        with _L:
            pass
        time.sleep(0.5)
    """
    assert run(src, BlockingUnderLockRule) == []


def test_blocking_reached_through_cross_file_call_chain():
    files = {
        "spark_rapids_ml_tpu/fix_io.py": """
            def fetch_all(url):
                import urllib.request
                return urllib.request.urlopen(url)
            """,
        "spark_rapids_ml_tpu/fix_holder.py": """
            import threading
            from .fix_io import fetch_all
            _L = threading.Lock()
            def refresh(url):
                with _L:
                    return fetch_all(url)
            """,
    }
    fs = run_files(files, BlockingUnderLockRule)
    assert rule_ids(fs) == ["blocking-under-lock"]
    assert fs[0].path == "spark_rapids_ml_tpu/fix_holder.py"
    assert "urlopen" in fs[0].message and "fetch_all" in fs[0].message


def test_blocking_condition_wait_on_held_condition_is_sanctioned():
    src = """
    import threading
    class E:
        def __init__(self):
            self._cond = threading.Condition()
        def loop(self):
            with self._cond:
                self._cond.wait(0.05)
    """
    assert run(src, BlockingUnderLockRule) == []


def test_blocking_foreign_wait_under_lock_fires():
    src = """
    import threading
    class E:
        def __init__(self):
            self._cond = threading.Condition()
            self._done = threading.Event()
        def bad(self):
            with self._cond:
                self._done.wait(5.0)
    """
    fs = run(src, BlockingUnderLockRule)
    assert rule_ids(fs) == ["blocking-under-lock"]


def test_blocking_device_sync_under_lock_fires():
    src = """
    import threading
    import jax
    _L = threading.Lock()
    def f(x):
        with _L:
            jax.block_until_ready(x)
    """
    fs = run(src, BlockingUnderLockRule)
    assert rule_ids(fs) == ["blocking-under-lock"]


def test_blocking_waiver_on_the_with_header_covers_the_section():
    src = """
    import threading
    _L = threading.Lock()
    def f(path, line):
        with _L:  # held-ok: fixture rationale — the lock exists to serialize this append
            with open(path, "a") as fh:
                fh.write(line)
    """
    assert run(src, BlockingUnderLockRule) == []


def test_blocking_waiver_on_the_op_line_also_suppresses():
    src = """
    import threading
    import time
    _L = threading.Lock()
    def f():
        with _L:
            time.sleep(0.01)  # held-ok: fixture rationale — bounded poll tick
    """
    assert run(src, BlockingUnderLockRule) == []


# --------------------------------------------------------------------------
# guard-discipline
# --------------------------------------------------------------------------


def test_guard_unlocked_read_fires_and_locked_access_passes():
    src = """
    import threading
    class G:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}  # guarded-by: _lock
        def ok(self):
            with self._lock:
                return len(self._items)
        def bad(self):
            return self._items
    """
    fs = run(src, GuardDisciplineRule)
    assert rule_ids(fs) == ["guard-discipline"]
    assert "_items" in fs[0].message and "bad" in fs[0].message


def test_guard_locked_helper_proven_by_call_sites():
    # _drop_locked has no `with` of its own; every resolved call site holds
    # the lock, so the entry-held fixpoint proves it safe
    src = """
    import threading
    class G:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}  # guarded-by: _lock
        def outer(self):
            with self._lock:
                self._drop_locked()
        def _drop_locked(self):
            self._items.clear()
    """
    assert run(src, GuardDisciplineRule) == []


def test_guard_helper_with_one_unlocked_call_site_fires():
    src = """
    import threading
    class G:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}  # guarded-by: _lock
        def outer(self):
            with self._lock:
                self._drop_locked()
        def sloppy(self):
            self._drop_locked()
        def _drop_locked(self):
            self._items.clear()
    """
    fs = run(src, GuardDisciplineRule)
    assert rule_ids(fs) == ["guard-discipline"]


def test_guard_init_writes_are_exempt():
    src = """
    import threading
    class G:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}  # guarded-by: _lock
            self._items["seed"] = 1
    """
    assert run(src, GuardDisciplineRule) == []


def test_guard_module_global_state():
    src = """
    import threading
    _L = threading.Lock()
    _STATE = {}  # guarded-by: _L
    def good():
        with _L:
            _STATE["x"] = 1
    def bad():
        _STATE.clear()
    """
    fs = run(src, GuardDisciplineRule)
    assert rule_ids(fs) == ["guard-discipline"]
    assert fs[0].message.find("bad") != -1


def test_guard_unknown_lock_name_is_itself_a_finding():
    src = """
    import threading
    class G:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}  # guarded-by: _nope
    """
    fs = run(src, GuardDisciplineRule)
    assert rule_ids(fs) == ["guard-discipline"]
    assert "_nope" in fs[0].message


def test_guard_waiver_suppresses():
    src = """
    import threading
    class G:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}  # guarded-by: _lock
        def snapshot(self):
            return dict(self._items)  # guard-ok: fixture rationale — benign racy read
    """
    assert run(src, GuardDisciplineRule) == []


# --------------------------------------------------------------------------
# content-hash cache + --explain
# --------------------------------------------------------------------------


def _seed_repo(root: pathlib.Path, body: str) -> None:
    pkg = root / "spark_rapids_ml_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "mod.py").write_text(body)
    (root / "ci" / "analysis").mkdir(parents=True, exist_ok=True)


def test_cache_skips_unchanged_files_and_invalidates_on_edit(tmp_path, capsys):
    _seed_repo(tmp_path, "import time\n\n\ndef f():\n    time.sleep(1)  # sleep-ok: fixture rationale\n")
    args = ["spark_rapids_ml_tpu", "--root", str(tmp_path), "--no-imports", "--json",
            "--baseline", str(tmp_path / "baseline.json")]
    assert cli_main(args) == 0
    cold = json.loads(capsys.readouterr().out)
    assert cold["files_cached"] == 0 and cold["files_scanned"] == 1
    assert (tmp_path / "ci" / "analysis" / "cache.json").exists()

    assert cli_main(args) == 0
    warm = json.loads(capsys.readouterr().out)
    assert warm["files_cached"] == 1
    assert warm["findings"] == cold["findings"]

    # an edit invalidates exactly that file — and its NEW finding surfaces
    (tmp_path / "spark_rapids_ml_tpu" / "mod.py").write_text(
        "import time\n\n\ndef f():\n    time.sleep(1)\n"
    )
    assert cli_main(args) == 1
    edited = json.loads(capsys.readouterr().out)
    assert edited["files_cached"] == 0
    assert any(f["rule"] == "bare-sleep" for f in edited["findings"])


def test_cache_replays_collector_state_for_registry_rules(tmp_path, capsys):
    # a config-key usage in a CACHED file must still be checked in finalize
    _seed_repo(
        tmp_path,
        "from .core import config\n\n\ndef f():\n    return config.get('no_such_key')\n",
    )
    args = ["spark_rapids_ml_tpu", "--root", str(tmp_path), "--no-imports", "--json",
            "--baseline", str(tmp_path / "baseline.json")]
    assert cli_main(args) == 1
    cold = json.loads(capsys.readouterr().out)
    assert cli_main(args) == 1
    warm = json.loads(capsys.readouterr().out)
    cold_keys = [f for f in cold["findings"] if f["rule"] == "config-key"]
    warm_keys = [f for f in warm["findings"] if f["rule"] == "config-key"]
    assert cold_keys and warm_keys == cold_keys
    assert warm["files_cached"] == 1


def test_explain_prints_rule_doc(capsys):
    assert cli_main(["--explain", "lock-order"]) == 0
    out = capsys.readouterr().out
    assert "lock-order" in out
    assert "# lock-order-ok: <reason>" in out
    assert cli_main(["--explain", "no-such-rule"]) == 1


# --------------------------------------------------------------------------
# regression pins for the real findings this pass fixed
# --------------------------------------------------------------------------


def test_fit_multiple_iterator_lock_not_held_during_fit():
    """blocking-under-lock regression: the single fit pass used to run INSIDE
    the iterator lock (rendezvous rounds + sink I/O under a mutex); now the
    lock covers only index claiming."""
    from spark_rapids_ml_tpu.core import _FitMultipleIterator

    in_fit = threading.Event()
    release_fit = threading.Event()

    def slow_fit():
        in_fit.set()
        assert release_fit.wait(10.0)
        return ["m0", "m1"]

    it = _FitMultipleIterator(slow_fit, 2)
    results = {}

    def consume():
        idx, model = next(it)
        results[idx] = model

    t0 = threading.Thread(target=consume, daemon=True)
    t0.start()
    assert in_fit.wait(10.0)
    # the fit is in flight: the iterator lock must be FREE
    assert it.lock.acquire(timeout=1.0), "iterator lock held across the fit pass"
    it.lock.release()
    release_fit.set()
    t1 = threading.Thread(target=consume, daemon=True)
    t1.start()
    t0.join(10.0)
    t1.join(10.0)
    assert results == {0: "m0", 1: "m1"}


def test_fit_multiple_iterator_fit_failure_propagates_to_waiters():
    from spark_rapids_ml_tpu.core import _FitMultipleIterator

    def broken_fit():
        raise ValueError("boom")

    it = _FitMultipleIterator(broken_fit, 2)
    first_err = {}

    def first():
        try:
            next(it)
        except BaseException as e:  # noqa: BLE001 - recording for assertion
            first_err["e"] = e

    t = threading.Thread(target=first, daemon=True)
    t.start()
    t.join(10.0)
    assert isinstance(first_err.get("e"), ValueError)
    with pytest.raises(RuntimeError, match="fit pass"):
        next(it)


def test_metrics_delta_gauges_copy_is_race_free():
    """guard-discipline regression: delta() used to copy the gauges dict
    AFTER releasing the registry lock — a concurrent gauge() could resize it
    mid-iteration."""
    from spark_rapids_ml_tpu import telemetry

    telemetry.enable()
    try:
        reg = telemetry.registry()
        mark = reg.mark()
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                reg.gauge(f"fixture.g{i % 257}", float(i))  # metric-ok: synthetic churn names for the race regression test
                i += 1

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        deadline = time.monotonic() + 1.0
        try:
            while time.monotonic() < deadline:
                reg.delta(mark)  # pre-fix: RuntimeError(dict changed size)
        finally:
            stop.set()
            t.join(5.0)
    finally:
        telemetry.disable()
