#
# LogisticRegression compat tests vs sklearn: binomial/multinomial,
# standardization, regularization, thresholds, CV integration
# (reference tests/test_logistic_regression.py is the largest compat suite).
#
import numpy as np
import pandas as pd
import pytest

from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
from spark_rapids_ml_tpu.linalg import Vectors
from spark_rapids_ml_tpu.models.classification import (
    LogisticRegression,
    LogisticRegressionModel,
)
from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder


def _binary_data(rng, n=500, d=6):
    x = rng.normal(size=(n, d))
    true_coef = rng.normal(size=d)
    logits = x @ true_coef - 0.3
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return pd.DataFrame({"features": list(x), "label": y}), x, y


def _multi_data(rng, n=600, d=5, k=3):
    from sklearn.datasets import make_classification

    x, y = make_classification(
        n_samples=n, n_features=d, n_informative=d - 1, n_redundant=0,
        n_classes=k, random_state=5,
    )
    return pd.DataFrame({"features": list(x.astype(np.float64)), "label": y.astype(np.float64)}), x, y


def test_multinomial_many_classes_vs_sklearn(rng):
    # 20-class softmax: intercept centering, per-class coef recovery and
    # accuracy parity must hold well beyond the small-k tests
    from sklearn.linear_model import LogisticRegression as SkLR

    df, x, y = _multi_data(rng, n=3000, d=12, k=20)
    model = (
        LogisticRegression(maxIter=300, regParam=0.01, tol=1e-10, float32_inputs=False)
        .setFeaturesCol("features")
        .fit(df)
    )
    assert model.numClasses == 20
    assert np.asarray(model.coefficientMatrix).shape == (20, 12)
    # softmax shift invariance: intercepts are centered (Spark parity)
    np.testing.assert_allclose(np.mean(np.asarray(model.interceptVector)), 0.0, atol=1e-8)

    sk = SkLR(C=1.0 / (3000 * 0.01), max_iter=2000, tol=1e-10).fit(x, y)
    ours = model.transform(df)["prediction"].to_numpy()
    acc_ours = (ours == y).mean()
    acc_sk = (sk.predict(x) == y).mean()
    assert acc_ours >= acc_sk - 0.01, (acc_ours, acc_sk)
    # probabilities agree in aggregate (same regularized optimum)
    probs = np.stack(model.transform(df)["probability"].to_list())
    np.testing.assert_allclose(
        probs.mean(axis=0), sk.predict_proba(x).mean(axis=0), atol=5e-3
    )


def test_binomial_vs_sklearn(rng):
    from sklearn.linear_model import LogisticRegression as SkLR

    df, x, y = _binary_data(rng)
    model = (
        LogisticRegression(regParam=0.01, standardization=False, float32_inputs=False,
                           maxIter=200, tol=1e-10)
        .setFeaturesCol("features")
        .fit(df)
    )
    # Spark objective mean-logloss + λ‖b‖²/2  ==  sklearn C = 1/(n·λ)
    sk = SkLR(C=1.0 / (len(y) * 0.01), max_iter=2000, tol=1e-12).fit(x, y)
    np.testing.assert_allclose(model.coef_[0], sk.coef_[0], rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(model.intercept_[0], sk.intercept_[0], rtol=2e-3, atol=2e-4)

    out = model.transform(df)
    skp = sk.predict_proba(x)
    got = np.stack([v.toArray() if hasattr(v, "toArray") else np.asarray(v) for v in out["probability"]])
    np.testing.assert_allclose(got, skp, atol=1e-3)
    assert (np.asarray(out["prediction"]) == sk.predict(x)).mean() > 0.999


def test_multinomial_vs_sklearn(rng):
    from sklearn.linear_model import LogisticRegression as SkLR

    df, x, y = _multi_data(rng)
    model = (
        LogisticRegression(regParam=0.01, standardization=False, float32_inputs=False,
                           maxIter=300, tol=1e-10)
        .setFeaturesCol("features")
        .fit(df)
    )
    assert model.numClasses == 3
    assert model.coefficientMatrix.shape == (3, 5)
    sk = SkLR(C=1.0 / (len(y) * 0.01), max_iter=3000, tol=1e-12).fit(x, y)
    out = model.transform(df)
    agree = (np.asarray(out["prediction"]) == sk.predict(x)).mean()
    assert agree > 0.99
    got = np.stack([v.toArray() if hasattr(v, "toArray") else np.asarray(v) for v in out["probability"]])
    np.testing.assert_allclose(got, sk.predict_proba(x), atol=2e-3)


def test_standardization_in_graph(rng):
    # badly-scaled features: standardization must rescue convergence quality
    df, x, y = _binary_data(rng, n=400, d=4)
    x_bad = x * np.array([1e3, 1e-3, 1.0, 10.0])
    df_bad = pd.DataFrame({"features": list(x_bad), "label": y})
    m = (
        LogisticRegression(regParam=0.001, standardization=True, float32_inputs=False, maxIter=200)
        .setFeaturesCol("features")
        .fit(df_bad)
    )
    out = m.transform(df_bad)
    acc = (np.asarray(out["prediction"]) == y).mean()
    # matches what sklearn achieves on this noisy data (0.795 on the bad scaling)
    assert acc >= 0.79
    # coefficients are in ORIGINAL space: scale-inverse pattern
    assert abs(m.coef_[0][0]) < abs(m.coef_[0][1])


def test_multinomial_intercept_centering(rng):
    df, _, _ = _multi_data(rng)
    m = LogisticRegression(regParam=0.01, float32_inputs=False).setFeaturesCol("features").fit(df)
    np.testing.assert_allclose(np.mean(m.intercept_), 0.0, atol=1e-6)


def test_binary_threshold(rng):
    df, x, y = _binary_data(rng)
    m = LogisticRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    out_hi = m.setThreshold(0.9).transform(df)
    out_lo = m.setThreshold(0.1).transform(df)
    assert np.asarray(out_hi["prediction"]).sum() < np.asarray(out_lo["prediction"]).sum()


def test_single_class_degenerate(rng):
    x = rng.normal(size=(30, 3))
    df = pd.DataFrame({"features": list(x), "label": np.ones(30)})
    m = LogisticRegression().setFeaturesCol("features").fit(df)
    assert m.numClasses == 1
    out = m.transform(df)
    assert (np.asarray(out["prediction"]) == 1.0).all()


def test_noninteger_class_labels(rng):
    # arbitrary float labels map through classes_
    df, x, y = _binary_data(rng, n=200)
    df["label"] = np.where(y > 0, 7.0, 3.0)
    m = LogisticRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    np.testing.assert_array_equal(m.classes_, [3.0, 7.0])
    preds = set(np.unique(np.asarray(m.transform(df)["prediction"])))
    assert preds <= {3.0, 7.0}


def test_spark_model_surface(rng):
    df, x, y = _binary_data(rng, n=100, d=4)
    m = LogisticRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    assert m.coefficients.size == 4
    assert isinstance(m.intercept, float)
    assert m.numFeatures == 4
    p0 = m.predict(x[0])
    assert p0 in (0.0, 1.0)
    pp = m.predictProbability(x[0])
    np.testing.assert_allclose(np.sum(pp.toArray()), 1.0, atol=1e-9)
    raw = m.predictRaw(x[0]).toArray()
    assert raw.shape == pp.toArray().shape and np.isfinite(raw).all()
    with pytest.raises(RuntimeError, match="summary"):
        m.summary

    dfm, xm, ym = _multi_data(rng, n=150)
    mm = LogisticRegression(float32_inputs=False).setFeaturesCol("features").fit(dfm)
    with pytest.raises(Exception, match="coefficientMatrix"):
        mm.coefficients
    with pytest.raises(Exception, match="interceptVector"):
        mm.intercept


def test_elasticnet_binomial_vs_sklearn(rng):
    # Spark objective mean-logloss + λ[(1−α)/2‖b‖² + α‖b‖₁]  ==  sklearn saga
    # with penalty='elasticnet', C = 1/(n·λ), l1_ratio = α (standardization
    # off → same space).
    # TRIAGE (was one of 3 long-standing "parity failures"): the test passed
    # l1_ratio WITHOUT penalty='elasticnet', so sklearn silently fit pure L2
    # (it warns "l1_ratio parameter is only used when penalty is
    # 'elasticnet'") — a reference-side solver-param bug, not an OWL-QN
    # divergence. With the penalty set, the telemetry convergence traces show
    # both optimizers reach the SAME objective (ours 0.4914280807792140 vs
    # sklearn's coefs 0.4914280807792129 on this data) and coefficients agree
    # to ~7e-8.
    from sklearn.linear_model import LogisticRegression as SkLR

    df, x, y = _binary_data(rng, n=400, d=6)
    lam, a = 0.02, 0.5
    model = (
        LogisticRegression(
            regParam=lam, elasticNetParam=a, standardization=False,
            float32_inputs=False, maxIter=500, tol=1e-12,
        )
        .setFeaturesCol("features")
        .fit(df)
    )
    sk = SkLR(
        solver="saga", penalty="elasticnet", C=1.0 / (len(y) * lam), l1_ratio=a,
        max_iter=20000, tol=1e-12,
    ).fit(x, y)
    np.testing.assert_allclose(model.coef_[0], sk.coef_[0], rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(model.intercept_[0], sk.intercept_[0], rtol=5e-3, atol=5e-3)


def test_l1_sparsity_vs_sklearn(rng):
    # pure L1 (elasticNetParam=1): strong penalty must zero exactly the
    # coordinates sklearn's saga zeroes
    from sklearn.linear_model import LogisticRegression as SkLR

    df, x, y = _binary_data(rng, n=300, d=8)
    lam = 0.05
    model = (
        LogisticRegression(
            regParam=lam, elasticNetParam=1.0, standardization=False,
            float32_inputs=False, maxIter=500, tol=1e-12,
        )
        .setFeaturesCol("features")
        .fit(df)
    )
    # penalty='elasticnet' is required for l1_ratio to take effect (see the
    # triage note in test_elasticnet_binomial_vs_sklearn); l1_ratio=1 == pure L1
    sk = SkLR(
        solver="saga", penalty="elasticnet", C=1.0 / (len(y) * lam), l1_ratio=1.0,
        max_iter=20000, tol=1e-12,
    ).fit(x, y)
    got_zero = np.abs(model.coef_[0]) < 1e-6
    sk_zero = np.abs(sk.coef_[0]) < 1e-6
    assert sk_zero.any(), "test data should produce some zeroed coords"
    np.testing.assert_array_equal(got_zero, sk_zero)
    np.testing.assert_allclose(model.coef_[0], sk.coef_[0], atol=6e-3)


def test_elasticnet_multinomial_vs_sklearn(rng):
    from sklearn.linear_model import LogisticRegression as SkLR

    df, x, y = _multi_data(rng, n=500, d=6, k=3)
    lam, a = 0.01, 0.3
    model = (
        LogisticRegression(
            regParam=lam, elasticNetParam=a, standardization=False,
            float32_inputs=False, maxIter=500, tol=1e-12,
        )
        .setFeaturesCol("features")
        .fit(df)
    )
    # penalty='elasticnet' is required for l1_ratio to take effect (see the
    # triage note in test_elasticnet_binomial_vs_sklearn)
    sk = SkLR(
        solver="saga", penalty="elasticnet", C=1.0 / (len(y) * lam), l1_ratio=a,
        max_iter=20000, tol=1e-12,
    ).fit(x, y)
    out = model.transform(df)
    agree = (np.asarray(out["prediction"]) == sk.predict(x)).mean()
    assert agree > 0.98
    got = np.stack([v.toArray() if hasattr(v, "toArray") else np.asarray(v) for v in out["probability"]])
    np.testing.assert_allclose(got, sk.predict_proba(x), atol=2e-2)


def test_elasticnet_with_standardization(rng):
    # penalty lives in standardized space; on pre-standardized data the
    # standardization=True fit must agree with the standardization=False fit
    df, x, y = _binary_data(rng, n=300, d=5)
    xs = (x - x.mean(axis=0)) / x.std(axis=0, ddof=1)
    dfs = pd.DataFrame({"features": list(xs), "label": y})
    kw = dict(
        regParam=0.02, elasticNetParam=0.5, float32_inputs=False, maxIter=500, tol=1e-12
    )
    m_std = LogisticRegression(standardization=True, **kw).setFeaturesCol("features").fit(dfs)
    m_raw = LogisticRegression(standardization=False, **kw).setFeaturesCol("features").fit(dfs)
    np.testing.assert_allclose(m_std.coef_[0], m_raw.coef_[0], rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(m_std.intercept_[0], m_raw.intercept_[0], rtol=1e-3, atol=1e-4)


def test_persistence(tmp_path, rng):
    df, x, _ = _binary_data(rng, n=100)
    m = LogisticRegression(float32_inputs=False).setFeaturesCol("features").fit(df)
    p = str(tmp_path / "lr")
    m.write().overwrite().save(p)
    loaded = LogisticRegressionModel.load(p)
    np.testing.assert_array_equal(loaded.coef_, m.coef_)
    np.testing.assert_array_equal(loaded.classes_, m.classes_)
    np.testing.assert_array_equal(
        np.asarray(loaded.transform(df)["prediction"]), np.asarray(m.transform(df)["prediction"])
    )


def test_cv_integration_fused(rng):
    df, x, y = _binary_data(rng, n=300)
    lr = LogisticRegression(standardization=False, float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.001, 10.0]).build()
    ev = MulticlassClassificationEvaluator(metricName="accuracy")
    assert lr._supportsTransformEvaluate(ev)
    cv = CrossValidator(estimator=lr, estimatorParamMaps=grid, evaluator=ev, numFolds=3, seed=1)
    cv_model = cv.fit(df)
    assert len(cv_model.avgMetrics) == 2
    assert cv_model.avgMetrics[0] > cv_model.avgMetrics[1]  # tiny reg beats huge reg


def test_family_validation():
    with pytest.raises(ValueError, match="family"):
        LogisticRegression(family="Multinomial")
    with pytest.raises(ValueError, match="family"):
        LogisticRegression().setFamily("bogus")


def test_cv_logloss_with_rare_class(rng):
    # a fold can miss the rare class entirely; logLoss must not crash
    df, x, y = _binary_data(rng, n=120)
    lab = np.asarray(df["label"]).copy()
    lab[:3] = 2.0  # rare third class
    df["label"] = lab
    lr = LogisticRegression(float32_inputs=False).setFeaturesCol("features")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0]).build()
    ev = MulticlassClassificationEvaluator(metricName="logLoss")
    cv = CrossValidator(estimator=lr, estimatorParamMaps=grid, evaluator=ev, numFolds=4, seed=3)
    m = cv.fit(df)
    assert np.isfinite(m.avgMetrics[0])


def _sparse_df(rng, n=300, d=20, density=0.15, k=2):
    # CSR data with known structure, returned both as SparseVector rows and a
    # dense ndarray for the parity fit
    import scipy.sparse as sp

    x = sp.random(n, d, density=density, random_state=np.random.RandomState(7), format="csr")
    xd = np.asarray(x.todense())
    coef = rng.normal(size=d)
    logits = xd @ coef - 0.1
    if k == 2:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    else:
        y = rng.integers(0, k, size=n).astype(np.float64)
    rows = [
        Vectors.sparse(d, x[i].indices.tolist(), x[i].data.tolist()) for i in range(n)
    ]
    df_sp = pd.DataFrame({"features": rows, "label": y})
    df_dn = pd.DataFrame({"features": list(xd), "label": y})
    return df_sp, df_dn, xd, y


def test_sparse_fit_matches_dense(rng):
    # same objective, different data layout: ELL fit must equal the dense fit
    df_sp, df_dn, _, _ = _sparse_df(rng)
    kw = dict(regParam=0.01, standardization=False, float32_inputs=False, maxIter=300, tol=1e-12)
    m_sp = LogisticRegression(**kw).setFeaturesCol("features").fit(df_sp)
    m_dn = LogisticRegression(**kw).setFeaturesCol("features").fit(df_dn)
    np.testing.assert_allclose(m_sp.coef_, m_dn.coef_, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(m_sp.intercept_, m_dn.intercept_, rtol=1e-6, atol=1e-8)


def test_sparse_fit_multinomial_and_l1(rng):
    df_sp, df_dn, xd, y = _sparse_df(rng, n=400, d=15, k=3)
    kw = dict(
        regParam=0.02, elasticNetParam=0.6, standardization=False,
        float32_inputs=False, maxIter=300, tol=1e-12,
    )
    m_sp = LogisticRegression(**kw).setFeaturesCol("features").fit(df_sp)
    m_dn = LogisticRegression(**kw).setFeaturesCol("features").fit(df_dn)
    assert m_sp.numClasses == 3
    np.testing.assert_allclose(m_sp.coef_, m_dn.coef_, atol=1e-6)
    # L1 zeros agree between layouts
    np.testing.assert_array_equal(np.abs(m_sp.coef_) < 1e-8, np.abs(m_dn.coef_) < 1e-8)


def test_sparse_standardization_scale_only(rng):
    # sparse standardization never centers (reference's sparsity-preserving
    # trick): equivalent to dense fit on scale-only-standardized data
    df_sp, df_dn, xd, y = _sparse_df(rng, n=300, d=12)
    m_sp = (
        LogisticRegression(regParam=0.01, standardization=True, float32_inputs=False,
                           maxIter=300, tol=1e-12)
        .setFeaturesCol("features")
        .fit(df_sp)
    )
    # manual scale-only: divide by unbiased std, fit unstandardized, unfold
    sigma = xd.std(axis=0, ddof=1)
    x_scaled = xd / np.where(sigma > 0, sigma, 1.0)
    df_scaled = pd.DataFrame({"features": list(x_scaled), "label": y})
    m_ref = (
        LogisticRegression(regParam=0.01, standardization=False, float32_inputs=False,
                           maxIter=300, tol=1e-12)
        .setFeaturesCol("features")
        .fit(df_scaled)
    )
    np.testing.assert_allclose(
        m_sp.coef_[0] * np.where(sigma > 0, sigma, 1.0), m_ref.coef_[0], rtol=1e-5, atol=1e-7
    )
    np.testing.assert_allclose(m_sp.intercept_, m_ref.intercept_, rtol=1e-5, atol=1e-7)


def test_sparse_transform_and_predict(rng):
    df_sp, df_dn, xd, y = _sparse_df(rng)
    m = (
        LogisticRegression(regParam=0.01, float32_inputs=False, maxIter=200)
        .setFeaturesCol("features")
        .fit(df_sp)
    )
    out_sp = m.transform(df_sp)
    out_dn = m.transform(df_dn)
    np.testing.assert_allclose(
        np.asarray(out_sp["prediction"]), np.asarray(out_dn["prediction"])
    )


def test_sparse_optim_flag_validation(rng):
    df_sp, df_dn, _, _ = _sparse_df(rng, n=50)
    # True on dense input raises (reference params.py:44-65 semantics)
    with pytest.raises(ValueError, match="sparse"):
        LogisticRegression(enable_sparse_data_optim=True).setFeaturesCol("features").fit(df_dn)
    # False on sparse input densifies (fit still works)
    m = LogisticRegression(enable_sparse_data_optim=False, maxIter=50).setFeaturesCol("features").fit(df_sp)
    assert m.numClasses == 2


@pytest.mark.slow
def test_sparse_logistic_large_scale(rng):
    # the reference's headline sparse logistic scale pattern
    # (tests_large/test_large_logistic_regression.py: 1e7x2200 sparse): here
    # 1e6 x 2000 at ~0.1% density, fit without densifying
    import scipy.sparse as sp

    n, d = 1_000_000, 2000
    x = sp.random(n, d, density=0.001, random_state=np.random.RandomState(5), format="csr", dtype=np.float32)
    coef = np.zeros(d, dtype=np.float32)
    coef[:50] = rng.normal(size=50) * 3
    logits = np.asarray(x @ coef)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    m = (
        LogisticRegression(regParam=1e-5, maxIter=50, tol=1e-8)
        .setFeaturesCol("features")
        .fit({"features": x, "label": y})
    )
    assert m.numClasses == 2
    # recover sign pattern of the strong coordinates
    strong = np.abs(coef[:50]) > 1
    agree = (np.sign(m.coef_[0][:50]) == np.sign(coef[:50]))[strong].mean()
    assert agree > 0.9


def test_early_stall_warning_on_unstandardized_fit(rng):
    # ADVICE round 5: when the Armijo stall check ends an UNSTANDARDIZED fit
    # well before maxIter/tol, the user gets a warning pointing at
    # standardization=True instead of a silently under-converged model.
    # (The framework logger sets propagate=False, so capture with a handler.)
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    n, d = 4000, 6
    x = rng.normal(size=(n, d)) * 1e4  # badly scaled: minimizer |coef| >> 1
    y = (x[:, 0] > 0).astype(np.float64)
    df = pd.DataFrame({"features": list(x), "label": y})
    handler = _Capture(level=logging.WARNING)
    logger = logging.getLogger("spark_rapids_ml_tpu.LogisticRegression")
    logger.addHandler(handler)
    try:
        m = LogisticRegression(maxIter=200, standardization=False).setFeaturesCol(
            "features"
        ).fit(df)
        assert m.n_iter_ < 200
        assert any("stalled" in r for r in records)

        # the standardized fit must NOT warn
        records.clear()
        LogisticRegression(maxIter=50, standardization=True).setFeaturesCol(
            "features"
        ).fit(df)
        assert not any("stalled" in r for r in records)
    finally:
        logger.removeHandler(handler)


def test_warn_if_early_stall_helper():
    # host-side decision table of the warning helper (ops/logistic.py)
    import logging

    from spark_rapids_ml_tpu.ops.logistic import warn_if_early_stall

    logger = logging.getLogger("srml-test-stall")
    stalled_early = {"stalled_": np.asarray(True), "n_iter_": np.asarray(3)}
    assert warn_if_early_stall(stalled_early, standardize=False, max_iter=100, logger=logger)
    # standardized fits never warn (the stall limit is an unstandardized-
    # conditioning failure mode)
    assert not warn_if_early_stall(stalled_early, standardize=True, max_iter=100, logger=logger)
    # running to maxIter is not a stall termination
    at_max = {"stalled_": np.asarray(True), "n_iter_": np.asarray(100)}
    assert not warn_if_early_stall(at_max, standardize=False, max_iter=100, logger=logger)
    clean = {"stalled_": np.asarray(False), "n_iter_": np.asarray(40)}
    assert not warn_if_early_stall(clean, standardize=False, max_iter=100, logger=logger)
