#
# Streaming-ingest tests: per-shard placement equivalence against the old
# monolithic pad+device_put path, chunked column->block extraction equality,
# chunked CSR->ELL equality, and the peak-host-memory regression contract
# (chunked ingest+placement stays ~1x dataset bytes of extra host memory
# where the monolithic path held ~2x extra / ~3x total).
#
import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import jax

from spark_rapids_ml_tpu import core as core_mod
from spark_rapids_ml_tpu.parallel import (
    get_mesh,
    make_global_rows,
    pad_rows,
    place_row_shards,
    row_sharding,
    shard_row_slices,
)

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)


@pytest.fixture
def tiny_chunks():
    """Run the body under a pathologically small ingest_chunk_bytes so every
    chunk boundary is exercised, restoring the default afterwards."""
    saved = core_mod.config["ingest_chunk_bytes"]
    core_mod.config["ingest_chunk_bytes"] = 256
    yield
    core_mod.config["ingest_chunk_bytes"] = saved


# ---------------------------------------------------------------------------
# placement equivalence (tentpole acceptance: every dtype/sharding)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32])
@pytest.mark.parametrize("shape", [(13, 3), (16, 4), (3, 2), (29,), (8,)])
def test_place_row_shards_matches_monolithic(mesh8, dtype, shape):
    # the chunked per-shard path must produce arrays numerically identical to
    # the old monolithic pad+device_put placement, same sharding included
    x = (np.arange(int(np.prod(shape))) % 17).reshape(shape).astype(dtype)
    X = place_row_shards(mesh8, x)
    xp, _ = pad_rows(x, 8)
    ref = jax.device_put(xp, row_sharding(mesh8, x.ndim))
    assert X.sharding == ref.sharding
    assert X.dtype == ref.dtype
    np.testing.assert_array_equal(np.asarray(X), np.asarray(ref))


def test_shard_row_slices_views_and_tail_pad():
    x = np.arange(26, dtype=np.float32).reshape(13, 2)
    pieces, n_pad = shard_row_slices(x, 4)
    assert n_pad == 16 and len(pieces) == 4
    # all but the tail shard are zero-copy views of x
    for p in pieces[:3]:
        assert np.shares_memory(p, x)
    assert not np.shares_memory(pieces[3], x)  # tail is the one padded copy
    np.testing.assert_array_equal(np.concatenate(pieces)[:13], x)
    np.testing.assert_array_equal(np.concatenate(pieces)[13:], 0)


def test_make_global_rows_matches_monolithic_f64(mesh8):
    x = np.linspace(0, 1, 21 * 5, dtype=np.float64).reshape(21, 5)
    w_in = np.arange(21, dtype=np.float64) + 1
    X, w, n_valid = make_global_rows(mesh8, x, weights=w_in)
    xp, _ = pad_rows(x, 8)
    wp, _ = pad_rows(w_in, 8)
    np.testing.assert_array_equal(np.asarray(X), xp)
    np.testing.assert_array_equal(np.asarray(w), wp)
    assert n_valid == 21


def test_single_device_mesh_placement_unchanged():
    mesh1 = get_mesh(1)
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    X, w, n_valid = make_global_rows(mesh1, x)
    assert n_valid == 6 and X.shape == (6, 2)
    np.testing.assert_array_equal(np.asarray(X), x)
    # 1-device placement stays UNCOMMITTED-sharding (plain device_put): a
    # committed NamedSharding would re-stage X in consumer programs
    assert len(X.sharding.device_set) == 1


def test_sparse_fit_invariant_to_chunk_size(rng):
    # end-to-end: CSR input through chunked CSR->ELL and per-shard placement
    # must produce bit-identical coefficients at any chunk size
    from benchmark.gen_data import random_csr
    from spark_rapids_ml_tpu.models.classification import LogisticRegression

    x = random_csr(rng, 600, 24, 0.15)
    s = np.asarray(x.sum(axis=1)).ravel()  # plain ndarray (scipy sum yields np.matrix)
    y = (s > np.median(s)).astype(np.float64)
    df = {"features": x, "label": y}

    def fit_coef():
        m = (
            LogisticRegression(maxIter=25, regParam=0.01, standardization=True)
            .setFeaturesCol("features")
            .fit(df)
        )
        return np.asarray(m.coef_)

    c_default = fit_coef()
    saved = core_mod.config["ingest_chunk_bytes"]
    try:
        core_mod.config["ingest_chunk_bytes"] = 512
        c_chunked = fit_coef()
    finally:
        core_mod.config["ingest_chunk_bytes"] = saved
    np.testing.assert_array_equal(c_default, c_chunked)


# ---------------------------------------------------------------------------
# chunked extraction equality
# ---------------------------------------------------------------------------


def test_chunked_extraction_bit_identical(rng, tiny_chunks):
    from spark_rapids_ml_tpu.data import extract_dataset
    from spark_rapids_ml_tpu.linalg import DenseVector, SparseVector

    n, d = 257, 6
    X = rng.normal(size=(n, d))
    saved = core_mod.config["ingest_chunk_bytes"]
    core_mod.config["ingest_chunk_bytes"] = 1 << 30
    try:
        ref_arr = extract_dataset({"f": list(X)}, input_col="f").features
        ref_vec = extract_dataset(
            pd.DataFrame({"f": [DenseVector(r) for r in X]}), input_col="f"
        ).features
        ref_cols = extract_dataset(
            pd.DataFrame({f"c{i}": X[:, i] for i in range(d)}),
            input_cols=[f"c{i}" for i in range(d)],
        ).features
        sv = [
            SparseVector(d, np.sort(rng.choice(d, 2, replace=False)).astype(np.int32),
                         rng.normal(size=2))
            for _ in range(n)
        ]
        ref_sp = extract_dataset(
            pd.DataFrame({"f": sv}), input_col="f", enable_sparse_data_optim=True
        ).features
    finally:
        core_mod.config["ingest_chunk_bytes"] = saved  # fixture value (tiny)

    got_arr = extract_dataset({"f": list(X)}, input_col="f").features
    got_vec = extract_dataset(
        pd.DataFrame({"f": [DenseVector(r) for r in X]}), input_col="f"
    ).features
    got_cols = extract_dataset(
        pd.DataFrame({f"c{i}": X[:, i] for i in range(d)}),
        input_cols=[f"c{i}" for i in range(d)],
    ).features
    got_sp = extract_dataset(
        pd.DataFrame({"f": sv}), input_col="f", enable_sparse_data_optim=True
    ).features
    np.testing.assert_array_equal(got_arr, ref_arr)
    np.testing.assert_array_equal(got_vec, ref_vec)
    np.testing.assert_array_equal(got_cols, ref_cols)
    assert (got_sp != ref_sp).nnz == 0
    np.testing.assert_array_equal(got_sp.indptr, ref_sp.indptr)


def test_csr_to_ell_chunked_bit_identical(rng, tiny_chunks):
    from benchmark.gen_data import random_csr
    from spark_rapids_ml_tpu.ops.sparse import csr_to_ell

    x = random_csr(rng, 311, 40, 0.12)
    saved = core_mod.config["ingest_chunk_bytes"]
    core_mod.config["ingest_chunk_bytes"] = 1 << 30
    try:
        i_ref, v_ref, k_ref = csr_to_ell(x, dtype=np.float32)
    finally:
        core_mod.config["ingest_chunk_bytes"] = saved
    i_got, v_got, k_got = csr_to_ell(x, dtype=np.float32)
    assert k_got == k_ref
    np.testing.assert_array_equal(i_got, i_ref)
    np.testing.assert_array_equal(v_got, v_ref)


# ---------------------------------------------------------------------------
# the unit_rows zero-row convention (satellite; ADVICE round 5)
# ---------------------------------------------------------------------------


def test_unit_rows_zero_row_convention():
    from spark_rapids_ml_tpu.utils import unit_rows

    x = np.array([[3.0, 4.0], [0.0, 0.0], [0.0, 2.0]], np.float32)
    u = unit_rows(x)
    np.testing.assert_allclose(np.linalg.norm(u[[0, 2]], axis=1), 1.0, rtol=1e-6)
    np.testing.assert_array_equal(u[1], 0.0)  # zero rows stay zero
    # through the cosine kernels' d²/2 conversion (models/knn.py) a zero row
    # is at distance 0.5 from EVERY unit vector — equidistant (ranking-
    # neutral) but not sklearn's 1.0 convention; this pins the documented value
    d2 = ((u[1] - u[0]) ** 2).sum()
    assert d2 / 2.0 == pytest.approx(0.5, abs=1e-6)
    d2b = ((u[1] - u[2]) ** 2).sum()
    assert d2b / 2.0 == pytest.approx(0.5, abs=1e-6)


# ---------------------------------------------------------------------------
# peak-host-memory regression (tentpole acceptance)
# ---------------------------------------------------------------------------

_MEM_PROBE = r"""
import os, sys, threading, time
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
from spark_rapids_ml_tpu.parallel import get_mesh, make_global_rows, set_devices
from spark_rapids_ml_tpu.parallel.mesh import pad_rows, row_sharding
set_devices("cpu")

mode, n, d = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
mesh = get_mesh(8)
# warm the CPU PJRT client + placement machinery before the baseline
_ = np.asarray(jax.device_put(np.ones((16, d), np.float32), row_sharding(mesh, 2)))

x = np.full((n, d), 0.5, np.float32)  # touched pages: truly resident
page = os.sysconf("SC_PAGE_SIZE")

def rss():
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * page

peak = [0]
stop = threading.Event()

def sampler():
    while not stop.is_set():
        r = rss()
        if r > peak[0]:
            peak[0] = r
        time.sleep(0.001)

base = rss()
t = threading.Thread(target=sampler, daemon=True)
t.start()
if mode == "chunked":
    X, w, _ = make_global_rows(mesh, x)
else:  # the old monolithic path: whole-block pad copy + one giant device_put
    xp, _ = pad_rows(x, 8)
    X = jax.device_put(xp, row_sharding(mesh, 2))
    w = jax.device_put(np.ones(xp.shape[0], np.float32), row_sharding(mesh, 1))
jax.block_until_ready(X)
final = rss()
stop.set(); t.join()
print(max(peak[0], final) - base)
"""


def _measure_extra_bytes(mode: str, n: int, d: int) -> int:
    """Peak RSS growth of ingest+placement of an [n, d] f32 block, measured in
    a fresh subprocess (clean allocator high-water mark per measurement)."""
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c", _MEM_PROBE, mode, str(n), str(d)],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return int(out.stdout.strip().splitlines()[-1])


def test_ingest_peak_host_memory_small():
    # 128 MiB block, n NOT divisible by the mesh so the old path really pads:
    # chunked placement must stay ~1x extra (device shard buffers only);
    # the monolithic path holds pad copy + device buffers (~2x extra)
    n, d = 8 * 4096 + 5, 1024
    dataset_bytes = n * d * 4
    chunked = _measure_extra_bytes("chunked", n, d)
    mono = _measure_extra_bytes("monolithic", n, d)
    assert chunked <= 1.3 * dataset_bytes, (
        f"chunked ingest used {chunked / dataset_bytes:.2f}x dataset bytes"
    )
    assert mono >= chunked + 0.5 * dataset_bytes, (
        f"expected the monolithic path to hold a full pad copy: "
        f"mono={mono / dataset_bytes:.2f}x chunked={chunked / dataset_bytes:.2f}x"
    )


@pytest.mark.slow
def test_ingest_peak_host_memory_1gib():
    # the tentpole acceptance shape: >= 1 GiB dense block, <= ~1.3x extra
    n, d = 8 * 8192 * 4 + 3, 1024  # 262147 x 1024 f32 = 1.00 GiB
    dataset_bytes = n * d * 4
    assert dataset_bytes >= 1 << 30
    chunked = _measure_extra_bytes("chunked", n, d)
    assert chunked <= 1.3 * dataset_bytes, (
        f"chunked ingest used {chunked / dataset_bytes:.2f}x dataset bytes"
    )


# ------------------------------------------------- opt-in ingest validation --


@pytest.fixture
def validate_on():
    saved = core_mod.config["validate_ingest"]
    core_mod.config["validate_ingest"] = True
    yield
    core_mod.config["validate_ingest"] = saved


def test_validate_ingest_names_the_feature_column(validate_on, tiny_chunks):
    from spark_rapids_ml_tpu.data import extract_dataset
    from spark_rapids_ml_tpu.errors import IngestValidationError

    x = np.arange(400, dtype=np.float64).reshape(100, 4)
    x[37, 2] = np.nan  # lands several 256-byte chunks in
    with pytest.raises(IngestValidationError, match=r"'feat'.*row 37") as ei:
        extract_dataset({"feat": x}, input_col="feat")
    assert isinstance(ei.value, ValueError)  # satellite contract: a clear ValueError
    assert ei.value.column == "feat" and ei.value.row == 37


def test_validate_ingest_names_the_exact_multi_col(validate_on):
    from spark_rapids_ml_tpu.data import extract_dataset
    from spark_rapids_ml_tpu.errors import IngestValidationError

    df = pd.DataFrame(
        {"a": np.ones(50), "b": np.ones(50), "c": np.ones(50), "label": np.zeros(50)}
    )
    df.loc[11, "b"] = np.inf
    with pytest.raises(IngestValidationError) as ei:
        extract_dataset(df, input_cols=["a", "b", "c"], label_col="label")
    assert ei.value.column == "b" and ei.value.row == 11


def test_validate_ingest_checks_label_and_weight(validate_on):
    from spark_rapids_ml_tpu.data import extract_dataset
    from spark_rapids_ml_tpu.errors import IngestValidationError

    x = np.ones((20, 3))
    lab = np.zeros(20)
    lab[4] = np.nan
    with pytest.raises(IngestValidationError) as ei:
        extract_dataset(
            {"f": x, "y": lab}, input_col="f", label_col="y"
        )
    assert ei.value.column == "y" and ei.value.row == 4
    w = np.ones(20)
    w[9] = -np.inf
    with pytest.raises(IngestValidationError) as ei:
        extract_dataset(
            {"f": x, "y": np.zeros(20), "w": w},
            input_col="f", label_col="y", weight_col="w",
        )
    assert ei.value.column == "w" and ei.value.row == 9


def test_validate_ingest_sparse_maps_back_to_the_row(validate_on):
    import scipy.sparse as sp

    from spark_rapids_ml_tpu.data import extract_dataset
    from spark_rapids_ml_tpu.errors import IngestValidationError

    m = sp.random(60, 10, density=0.2, random_state=0, format="csr")
    bad_row = 23
    m[bad_row, m[bad_row].indices[0] if m[bad_row].nnz else 0] = np.nan
    m = m.tocsr()
    with pytest.raises(IngestValidationError) as ei:
        extract_dataset({"f": m}, input_col="f")
    assert ei.value.column == "f" and ei.value.row == bad_row


def test_validate_ingest_off_by_default_and_clean_data_passes(validate_on):
    from spark_rapids_ml_tpu.data import extract_dataset

    x = np.ones((10, 2))
    out = extract_dataset({"f": x}, input_col="f")
    assert out.n_rows == 10  # clean data passes with validation ON
    core_mod.config["validate_ingest"] = False
    x_bad = x.copy()
    x_bad[0, 0] = np.nan
    out = extract_dataset({"f": x_bad}, input_col="f")  # default: no scan, no raise
    assert np.isnan(out.features[0, 0])
