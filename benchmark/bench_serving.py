#
# Serving-plane benchmark: p50/p99 request latency + QPS through the resident
# scoring service (docs/serving.md) — the FIRST lane that measures serve, not
# fit. Joins bench.py's gated geomean (per-lane trajectory gating from
# benchmark/regression.py; the p99 latency additionally gates as a
# lower-is-better lane).
#
# Shape: a KMeans model (constructed directly from synthetic centers — the
# lane measures the serving plane, not a fit) is loaded into a ModelRegistry
# (admission + ladder prewarm), then `concurrency` client threads fire
# `n_requests` mixed-size predict requests through one ScoringEngine. Per
# request we record end-to-end latency; the lane value is rows scored per
# second (the serve-side analog of the fit lanes' rows/sec normalization).
#
# The lane doubles as a LIVE correctness canary: every coalesced response is
# compared against the same request served solo (`_transform_arrays`) and the
# max abs difference is reported — 0.0 is the bit-identity acceptance
# criterion (assignments are integers, so any drift is a real bug, not
# rounding).
#
from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

from .base import BenchmarkBase


def run_serving_bench(
    n_cols: int = 256,
    k: int = 256,
    *,
    n_requests: int = 256,
    concurrency: int = 8,
    request_rows: tuple = (1, 16, 128, 512),
    coalesce_window_ms: float = 2.0,
    serve_dtype: str = "",
    seed: int = 0,
) -> Dict[str, Any]:
    """One serving-lane run; returns QPS, rows/sec, p50/p99 latency (ms),
    coalescing counters, and the solo-vs-coalesced max abs diff. Shared by
    the BenchmarkBase lane below and bench.py's `serving` geomean lane."""
    from concurrent.futures import ThreadPoolExecutor

    from spark_rapids_ml_tpu import core, telemetry
    from spark_rapids_ml_tpu.models.clustering import KMeansModel
    from spark_rapids_ml_tpu.ops_plane import slo as ops_slo
    from spark_rapids_ml_tpu.scheduler.ledger import global_ledger
    from spark_rapids_ml_tpu.serving import ModelRegistry, ScoringEngine

    rng = np.random.default_rng(seed)
    centers = (rng.standard_normal((k, n_cols)) * 4.0).astype(np.float32)
    model = KMeansModel(cluster_centers_=centers, n_cols=n_cols, dtype="float32")

    telemetry.enable()
    saved = core.config["serve_coalesce_window_ms"]
    saved_slo = core.config["slo"]
    core.config["serve_coalesce_window_ms"] = float(coalesce_window_ms)
    if not saved_slo:
        # report-only SLO verdict embedded in the BENCH record (outside the
        # gated geomean): lenient lab objectives, the point is that the
        # burn-rate machinery ran over THIS run's traffic
        core.config["slo"] = [
            {"name": "serve_e2e_p99", "kind": "latency",
             "histogram": "serve.e2e_s", "threshold_s": 0.5,
             "objective": 0.99},
            {"name": "serve_errors", "kind": "error_rate",
             "errors": "serve.errors", "total": "serve.requests",
             "threshold": 0.01},
        ]
    mark = telemetry.registry().mark()
    try:
        registry = ModelRegistry()
        t0 = time.perf_counter()
        entry = registry.load(
            "bench", model, serve_dtype=serve_dtype or None
        )
        load_s = time.perf_counter() - t0

        requests: List[np.ndarray] = [
            rng.standard_normal(
                (int(request_rows[i % len(request_rows)]), n_cols)
            ).astype(np.float32)
            for i in range(n_requests)
        ]
        # solo reference OUTSIDE the timed window (the bit-identity canary)
        solo = [np.asarray(model._transform_arrays(q)) for q in requests]

        latencies = np.zeros(n_requests)
        responses: List[Any] = [None] * n_requests

        with ScoringEngine(registry) as engine:
            # warm the dispatch path (programs are already prewarmed at load)
            engine.score("bench", requests[0])

            def one(i: int) -> None:
                t = time.perf_counter()
                responses[i] = engine.score("bench", requests[i], timeout=120)
                latencies[i] = time.perf_counter() - t

            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=concurrency) as pool:
                list(pool.map(one, range(n_requests)))
            wall = time.perf_counter() - t0

        max_abs_diff = max(
            float(np.max(np.abs(np.asarray(r) - s))) if s.size else 0.0
            for r, s in zip(responses, solo)
        )
        # end-of-run ops verdicts, evaluated while the SLO config is live
        slo_health = ops_slo.health(fresh=True)
        tenant_usage = global_ledger().tenant_usage()
    finally:
        core.config["serve_coalesce_window_ms"] = saved
        core.config["slo"] = saved_slo
        ops_slo.reset()

    delta = telemetry.registry().delta(mark)
    counters = delta.get("counters", {})
    total_rows = int(sum(q.shape[0] for q in requests))
    return {
        "fit": wall,  # BenchmarkBase's timing key
        "load_s": load_s,
        "qps": n_requests / wall,
        "rows_per_sec": total_rows / wall,
        "p50_ms": float(np.percentile(latencies, 50) * 1e3),
        "p99_ms": float(np.percentile(latencies, 99) * 1e3),
        "max_abs_diff": max_abs_diff,
        "requests": float(n_requests),
        "total_rows": float(total_rows),
        "coalesced_batches": float(counters.get("serve.coalesced_batches", 0.0)),
        "batches": float(counters.get("serve.batches", 0.0)),
        "bucket_hits": float(counters.get("serve.bucket_hits", 0.0)),
        "prewarmed_programs": float(entry.prewarmed_rungs),
        # report-only ops embeds (non-scalar; ride the BENCH record under
        # "ops", never the gated geomean)
        "slo": {
            "healthy": slo_health["healthy"],
            "failing": slo_health["failing"],
            "verdicts": slo_health["verdicts"],
        },
        "tenant_byte_seconds": {
            t: round(u.get("byte_seconds", 0.0), 3)
            for t, u in tenant_usage.items()
        },
    }


class BenchmarkServing(BenchmarkBase):
    name = "serving"
    extra_args = {
        "k": (int, 256, "resident KMeans model's center count"),
        "n_requests": (int, 256, "scoring requests fired through the engine"),
        "concurrency": (int, 8, "client threads"),
        "coalesce_window_ms": (float, 2.0, "engine coalesce window"),
        "serve_dtype": (str, "", "per-model serving dtype ('' = fit dtype, 'bf16' = distance-core fast path)"),
    }

    def gen_dataset(self, args, mesh) -> Dict[str, Any]:
        # the model and requests are generated inside run_serving_bench: the
        # lane measures load+score through the serving plane end to end
        return {}

    def run_once(self, args, data, mesh) -> Dict[str, float]:
        out = run_serving_bench(
            n_cols=args.num_cols,
            k=args.k,
            n_requests=args.n_requests,
            concurrency=args.concurrency,
            coalesce_window_ms=args.coalesce_window_ms,
            serve_dtype=args.serve_dtype,
            seed=args.seed,
        )
        data["counters"] = {
            key: v for key, v in out.items()
            if key not in ("fit", "slo", "tenant_byte_seconds")
        }
        data["ops"] = {
            "slo": out["slo"], "tenant_byte_seconds": out["tenant_byte_seconds"]
        }
        return {"fit": out["fit"]}

    def quality(self, args, data) -> Dict[str, float]:
        # qps/p50/p99/max_abs_diff: the lane's acceptance numbers
        # (max_abs_diff == 0 is the coalesce bit-identity criterion)
        return data.get("counters", {})


if __name__ == "__main__":
    BenchmarkServing().run()
