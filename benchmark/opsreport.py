#
# opsreport: render an ops-plane report — live from this process, or from a
# snapshot file written by `ops_plane.export.write_snapshot()` (the rotating
# `ops_snapshot.json` a headless run leaves behind, or the per-rank
# `ops_snapshot_rank_<r>.json` a flight-recorder dump rides with).
#
#   python -m benchmark.opsreport /path/ops_snapshot.json
#   python -m benchmark.opsreport snap.json --tenant tenant3
#   python -m benchmark.opsreport snap.json --trace-id ab12... --json
#   python -m benchmark.opsreport --write /tmp/ops_snapshot.json  # archive
#
# The human rendering answers the on-call question directly: which SLO is
# violated (burn rates and windows), which tenants are holding/holding-up
# HBM (byte-seconds, chip-seconds), and the decision-log entries — tenant,
# verdict, reason — for the filtered tenant/trace
# (docs/observability.md "Ops plane").
#
# Cluster mode (docs/observability.md "Fleet plane"):
#
#   python -m benchmark.opsreport --cluster /path/snapshot_dir --nranks 3
#   python -m benchmark.opsreport --cluster            # live merged view
#
# merges the per-rank `ops_snapshot*.json` files (dropping stale dead-rank
# data by their `meta` headers) and renders the cluster verdict, straggler
# lags, and the fleet tenant rollup — NAMING missing/stale ranks.
#
# Exit codes: 0 = healthy (or no SLOs configured), 1 = at least one SLO
# failing, 2 = snapshot unreadable, 3 = PARTIAL cluster (healthy so far as
# visible, but some rank snapshots missing or stale — a half-dead fleet is
# not a healthy one, and not an unreadable one either).
#
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

EXIT_HEALTHY = 0
EXIT_FAILING = 1
EXIT_UNREADABLE = 2
EXIT_PARTIAL = 3


def _fmt_burn(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:.2f}"


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024.0:
            return f"{v:,.1f}{unit}"
        v /= 1024.0
    return f"{v:,.1f}TiB"


def render(
    report: Dict[str, Any],
    *,
    tenant: Optional[str] = None,
    trace_id: Optional[str] = None,
    decision_limit: int = 20,
) -> str:
    lines: List[str] = []
    health = report.get("health") or {}
    verdicts = report.get("slo") or []
    ok = bool(health.get("healthy", True))
    lines.append(
        f"health: {'OK' if ok else 'FAILING'} "
        f"({health.get('specs', 0)} SLO spec(s))"
    )
    for v in verdicts:
        mark = "FAIL" if v.get("failing") else "ok"
        extra = ""
        if v.get("kind") == "latency":
            extra = f" threshold={v.get('threshold_s')}s objective={v.get('objective')}"
        elif v.get("kind") == "error_rate":
            extra = f" threshold={v.get('threshold')}"
        elif v.get("kind") == "gauge_ceiling":
            extra = f" value={v.get('value')} ceiling={v.get('ceiling')}"
        lines.append(
            f"  [{mark:>4}] {v.get('name')} ({v.get('kind')}): "
            f"burn fast={_fmt_burn(v.get('fast_burn'))}"
            f"/{v.get('fast_burn_threshold')} "
            f"({v.get('fast_window_s'):g}s), "
            f"slow={_fmt_burn(v.get('slow_burn'))}"
            f"/{v.get('slow_burn_threshold')} "
            f"({v.get('slow_window_s'):g}s){extra}"
        )
    tenants = report.get("tenants") or {}
    if tenants:
        lines.append("tenant HBM accounting:")
        for name in sorted(tenants):
            if tenant is not None and name != tenant:
                continue
            u = tenants[name]
            live = (
                f", live {_fmt_bytes(u['live_bytes'])} "
                f"across {int(u.get('live_reservations', 0))} claim(s)"
                if u.get("live_bytes")
                else ""
            )
            lines.append(
                f"  {name}: {_fmt_bytes(u.get('byte_seconds', 0.0))}·s, "
                f"{u.get('chip_seconds', 0.0):.3f} chip·s over "
                f"{int(u.get('reservations', 0))} reservation(s){live}"
            )
            dt = u.get("device_time")
            if dt:
                lines.append(
                    f"    device time: execute={dt.get('execute_s', 0.0):.3f}s "
                    f"compile={dt.get('compile_s', 0.0):.3f}s "
                    f"host={dt.get('host_s', 0.0):.3f}s "
                    f"idle={dt.get('idle_s', 0.0):.3f}s"
                )
    decisions = report.get("decisions") or []
    if tenant is not None:
        decisions = [d for d in decisions if d.get("tenant") == tenant]
    if trace_id is not None:
        decisions = [d for d in decisions if d.get("trace_id") == trace_id]
    scope = ""
    if tenant is not None:
        scope += f" tenant={tenant}"
    if trace_id is not None:
        scope += f" trace={trace_id}"
    lines.append(f"decision log{scope}: {len(decisions)} entr(ies)")
    for d in decisions[-max(0, decision_limit):]:
        reason = f" — {d['reason']}" if d.get("reason") else ""
        tid = f" trace={d['trace_id']}" if d.get("trace_id") else ""
        lines.append(
            f"  [{d.get('kind')}/{d.get('subsystem')}] "
            f"tenant={d.get('tenant')} {d.get('subject')}: "
            f"{d.get('verdict')}{reason}{tid}"
        )
    serving = (report.get("serving") or {}).get("tenants") or {}
    if serving:
        lines.append("serving overload (backpressure ladder):")
        for name in sorted(serving):
            if tenant is not None and name != tenant:
                continue
            s = serving[name]
            burn = s.get("burn")
            burn_s = f", burn={burn:.2f}" if burn is not None else ""
            p99 = s.get("e2e_p99_s")
            p99_s = f", e2e p99={p99 * 1e3:.1f}ms" if p99 is not None else ""
            lines.append(
                f"  {name}: level={s.get('level')}{burn_s}{p99_s} — "
                f"shed={int(s.get('shed_requests', 0))} "
                f"throttled={int(s.get('throttled_requests', 0))} "
                f"degraded={int(s.get('degraded_requests', 0))} over "
                f"{int(s.get('transitions', 0))} transition(s)"
            )
    drift = report.get("drift")
    if drift:
        psi = (
            f", psi_max={drift['psi_max']:.4f}" if "psi_max" in drift else ""
        )
        lines.append(
            f"ingest drift: {drift.get('rows', 0)} row(s) over "
            f"{len(drift.get('columns', []))} column(s){psi}"
        )
    eff = report.get("efficiency") or {}
    eff_tenants = eff.get("tenants") or {}
    if eff_tenants:
        lines.append("efficiency (attributed device time):")
        for name in sorted(eff_tenants):
            if tenant is not None and name != tenant:
                continue
            t = eff_tenants[name]
            wall = t.get("wall_s", 0.0)
            mfu = f", mfu={t['mfu']:.3f}" if t.get("mfu") is not None else ""
            top = t.get("top_idle_stage")
            top_s = f", top idle stage: {top}" if top else ""
            lines.append(
                f"  {name}: wall={wall:.3f}s "
                f"execute={t.get('execute_s', 0.0):.3f}s "
                f"compile={t.get('compile_s', 0.0):.3f}s "
                f"host={t.get('host_s', 0.0):.3f}s "
                f"idle={t.get('idle_s', 0.0):.3f}s{mfu}{top_s}"
            )
    comp = eff.get("compile") or {}
    if comp.get("programs"):
        lines.append(
            f"compile ledger: {comp.get('programs', 0)} program/shape "
            f"entr(ies), {comp.get('misses', 0)} miss(es) totalling "
            f"{comp.get('wall_s', 0.0):.3f}s, {comp.get('hits', 0)} hit(s)"
        )
    tune = report.get("autotune") or {}
    if tune.get("measurements") or tune.get("hits") or tune.get("misses"):
        path = tune.get("table_path") or "in-memory"
        lines.append(
            f"autotune: {tune.get('hits', 0)} hit(s) / "
            f"{tune.get('misses', 0)} miss(es), "
            f"{tune.get('measurements', 0)} measurement(s), "
            f"{tune.get('table_errors', 0)} table error(s), "
            f"{tune.get('entries', 0)} table entr(ies) @ {path}"
        )
    return "\n".join(lines)


def render_cluster(view: Dict[str, Any], issues: Dict[str, Any]) -> str:
    lines: List[str] = []
    n = view.get("nranks") or issues.get("nranks") or 0
    lines.append(
        f"cluster: {view.get('ranks_reporting', 0)}/{n} rank(s) reporting"
    )
    for key, label in (("missing", "missing"), ("stale", "stale"), ("unreadable", "unreadable")):
        bad = issues.get(key) or []
        if bad:
            lines.append(f"  {label} rank(s): {', '.join(str(r) for r in bad)}")
    for r in sorted(view.get("ranks") or {}):
        meta = view["ranks"][r]
        host = meta.get("host") or "?"
        lines.append(f"  rank {r}: host={host} pid={meta.get('pid')}")
    health = view.get("health") or {}
    ok = bool(health.get("healthy", True))
    lines.append(
        f"cluster health: {'OK' if ok else 'FAILING'} "
        f"({health.get('specs', 0)} SLO spec(s) over the merged window)"
    )
    for v in health.get("verdicts") or []:
        mark = "FAIL" if v.get("failing") else "ok"
        lines.append(
            f"  [{mark:>4}] {v.get('name')} ({v.get('kind')}): "
            f"burn fast={_fmt_burn(v.get('fast_burn'))}"
            f"/{v.get('fast_burn_threshold')}, "
            f"slow={_fmt_burn(v.get('slow_burn'))}"
            f"/{v.get('slow_burn_threshold')}"
        )
    strag = view.get("straggler") or {}
    lags = strag.get("lags_s") or {}
    if lags:
        lag_s = ", ".join(
            f"rank {r}={lags[r]*1e3:.1f}ms" for r in sorted(lags, key=lambda x: int(x))
        )
        slowest = strag.get("slowest")
        tail = f" (slowest: rank {slowest})" if slowest is not None else ""
        lines.append(f"straggler lags: {lag_s}{tail}")
    tenants = view.get("tenants") or {}
    pool = tenants.get("_pool") or {}
    if pool:
        lines.append(
            f"fleet chips: busy={pool.get('chips_busy', 0.0):g} "
            f"idle={pool.get('chips_idle', 0.0):g} "
            f"total={pool.get('chips_total', 0.0):g}"
        )
    named = {t: u for t, u in tenants.items() if t != "_pool"}
    if named:
        lines.append("fleet tenant rollup:")
        for name in sorted(named):
            u = named[name]
            lines.append(
                f"  {name}: {_fmt_bytes(u.get('byte_seconds', 0.0))}·s, "
                f"{u.get('chip_seconds', 0.0):.3f} chip·s, "
                f"chips_busy={u.get('chips_busy', 0.0):g}"
            )
            dt = u.get("device_time")
            if dt:
                lines.append(
                    f"    device time: execute={dt.get('execute_s', 0.0):.3f}s "
                    f"compile={dt.get('compile_s', 0.0):.3f}s "
                    f"host={dt.get('host_s', 0.0):.3f}s "
                    f"idle={dt.get('idle_s', 0.0):.3f}s"
                )
    if view.get("windows_error"):
        lines.append(f"window merge degraded: {view['windows_error']}")
    return "\n".join(lines)


def _cluster_main(args: Any) -> int:
    from spark_rapids_ml_tpu.ops_plane import fleet

    if args.snapshot is None:
        live = fleet.cluster_report()
        if not live.get("available"):
            print(
                "opsreport: no live cluster view (no ops round has merged yet)",
                file=sys.stderr,
            )
            return EXIT_UNREADABLE
        view = live
        issues: Dict[str, Any] = {
            "missing": view.get("missing") or [],
            "stale": [],
            "unreadable": [],
            "nranks": view.get("nranks"),
        }
    else:
        reports, issues = fleet.read_rank_snapshots(args.snapshot, nranks=args.nranks)
        if not reports:
            named = issues.get("stale") or issues.get("unreadable") or "none found"
            print(
                f"opsreport: no usable rank snapshots in {args.snapshot} "
                f"(stale/unreadable: {named})",
                file=sys.stderr,
            )
            return EXIT_UNREADABLE
        view = fleet.merge_reports(
            reports, expected=issues.get("nranks") or args.nranks
        )
    if args.write:
        with open(args.write, "w") as f:
            json.dump({"cluster": view, "issues": issues}, f, indent=2, default=str)
    if args.json:
        print(json.dumps({"cluster": view, "issues": issues}, default=str))
    else:
        print(render_cluster(view, issues))
    if not (view.get("health") or {}).get("healthy", True):
        return EXIT_FAILING
    partial = (
        (issues.get("missing") or [])
        or (issues.get("stale") or [])
        or (issues.get("unreadable") or [])
        or (view.get("missing") or [])
    )
    return EXIT_PARTIAL if partial else EXIT_HEALTHY


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="opsreport",
        description="render an ops-plane report (live, or from a snapshot file)",
    )
    p.add_argument("snapshot", nargs="?", default=None,
                   help="ops_snapshot.json path (omitted = this process's live state)")
    p.add_argument("--tenant", default=None, help="filter decisions/accounting to one tenant")
    p.add_argument("--trace-id", default=None, help="filter decisions to one trace")
    p.add_argument("--json", action="store_true", help="emit the raw report dict")
    p.add_argument("--decisions", type=int, default=20, help="decision-log entries rendered")
    p.add_argument("--write", default=None, metavar="PATH",
                   help="also archive the report as a rotating snapshot at PATH")
    p.add_argument("--write-efficiency", default=None, metavar="PATH",
                   help="archive just the efficiency section (attribution "
                        "splits + compile ledger) as JSON at PATH")
    p.add_argument("--cluster", action="store_true",
                   help="fleet mode: treat SNAPSHOT as a DIRECTORY of per-rank "
                        "ops_snapshot*.json files and render the merged "
                        "cluster view (omitted = this process's live merged "
                        "view); exit 3 names a partial cluster")
    p.add_argument("--nranks", type=int, default=None,
                   help="expected rank count for --cluster (missing ranks "
                        "are named; default: inferred from the snapshots)")
    args = p.parse_args(argv)

    if args.cluster:
        return _cluster_main(args)
    if args.snapshot is not None:
        try:
            with open(args.snapshot) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            print(f"opsreport: cannot read {args.snapshot}: {e}", file=sys.stderr)
            return 2
    else:
        from spark_rapids_ml_tpu import ops_plane

        report = ops_plane.report(tenant=args.tenant, trace_id=args.trace_id)
        if args.write:
            from spark_rapids_ml_tpu.ops_plane import export

            export.write_snapshot(args.write)
    if args.write_efficiency:
        eff_doc = {
            "t": report.get("t"),
            "efficiency": report.get("efficiency") or {},
            "autotune": report.get("autotune") or {},
        }
        with open(args.write_efficiency, "w") as f:
            json.dump(eff_doc, f, indent=2, default=str)
    if args.json:
        print(json.dumps(report, default=str))
    else:
        print(render(report, tenant=args.tenant, trace_id=args.trace_id,
                     decision_limit=args.decisions))
    return 0 if (report.get("health") or {}).get("healthy", True) else 1


if __name__ == "__main__":
    sys.exit(main())
