#
# Merge per-rank telemetry JSONL into Chrome trace-event JSON.
#
#   python -m benchmark.trace_merge /tmp/metrics.jsonl -o /tmp/trace.json
#   # then open /tmp/trace.json in https://ui.perfetto.dev or chrome://tracing
#
# Input is the telemetry sink family (`SRML_METRICS_PATH`): rank 0 owns the
# base path, rank r writes `<base>.rank<r>`. Output is one track per rank,
# every span as a complete ("X") event, rendezvous rounds as flow arrows,
# and per-rank clock skew corrected using barrier rounds as sync points —
# see spark_rapids_ml_tpu/diagnostics.py (merge_chrome_trace) and
# docs/observability.md "Trace correlation".
#
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("metrics", help="telemetry JSONL base path (SRML_METRICS_PATH)")
    ap.add_argument("-o", "--out", default=None,
                    help="output trace file (default: <metrics>.trace.json)")
    ap.add_argument("--trace-id", default=None,
                    help="merge only records of this trace id (default: all)")
    ap.add_argument("--no-align", action="store_true",
                    help="skip barrier-based clock-skew alignment")
    args = ap.parse_args(argv)

    from spark_rapids_ml_tpu.diagnostics import chrome_trace_from_files

    trace = chrome_trace_from_files(
        args.metrics, trace_id=args.trace_id, align_clocks=not args.no_align
    )
    out_path = args.out or f"{args.metrics}.trace.json"
    with open(out_path, "w") as f:
        json.dump(trace, f)
    n_spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    n_flows = sum(1 for e in trace["traceEvents"] if e.get("ph") == "s")
    ranks = trace["otherData"]["ranks"]
    print(
        f"wrote {out_path}: {n_spans} spans across {len(ranks)} rank track(s), "
        f"{n_flows} rendezvous flow arrow(s)",
        file=sys.stderr,
    )
    if not n_spans:
        print(
            "note: no span records found — was the fit run with "
            "SRML_METRICS_PATH set?", file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
