#
# KMeans benchmark — protocol config k=1000, maxIter=30, tol=1e-20,
# initMode=random on the 1M x 3k dataset (reference
# databricks/run_benchmark.sh:50-60; quality = inertia, bench_kmeans.py).
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase, fetch
from .gen_data import gen_low_rank_device
from .utils import with_benchmark


class BenchmarkKMeans(BenchmarkBase):
    name = "kmeans"
    extra_args = {
        "k": (int, 1000, "number of clusters (protocol: 1000)"),
        "maxIter": (int, 30, "Lloyd iterations (protocol: 30)"),
        "batch_rows": (int, 16384, "rows per assignment tile (HBM knob)"),
    }

    def gen_dataset(self, args, mesh):
        import jax

        n_dev = int(mesh.devices.size)
        X, w = gen_low_rank_device(
            args.num_rows, args.num_cols, seed=args.seed,
            mesh=mesh if n_dev > 1 else None,  # plain on 1 device (no Shardy copy)
        )
        # random-row init (initMode=random protocol config), pulled one
        # dynamic_slice at a time — a fancy-index gather program on the full X
        # materializes a second copy of it (OOM at the 1M x 3k protocol shape)
        rng = np.random.default_rng(args.seed + 1)
        idx = np.sort(rng.choice(args.num_rows, args.k, replace=False))
        slice_row = jax.jit(lambda X, i: jax.lax.dynamic_slice_in_dim(X, i, 1, 0))
        centers0 = jax.device_put(
            np.concatenate([np.asarray(slice_row(X, np.int32(i))) for i in idx], axis=0)
        )
        fetch(w[:1])
        return {"X": X, "w": w, "centers0": centers0}

    def run_once(self, args, data, mesh):
        from jax import default_matmul_precision

        from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit

        def run():
            # KMeans precision policy: 3-pass bf16 MXU (see parallel/mesh.py)
            with default_matmul_precision("BF16_BF16_F32_X3"):
                return kmeans_fit(
                    data["X"], data["w"], data["centers0"], mesh=mesh,
                    max_iter=args.maxIter, tol=1e-20, batch_rows=args.batch_rows,
                )

        fetch(run()["cluster_centers_"])  # compile outside timing
        state = {}

        def timed():
            s = run()
            fetch(s["cluster_centers_"])
            state.update(s)
            return s

        _, sec = with_benchmark("kmeans fit", timed)
        self._inertia = float(np.asarray(state["inertia_"]))
        return {"fit": sec}

    def quality(self, args, data):
        return {"inertia": self._inertia}


if __name__ == "__main__":
    BenchmarkKMeans().run()
