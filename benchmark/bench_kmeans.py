#
# KMeans benchmark — protocol config k=1000, maxIter=30, tol=1e-20,
# initMode=random on the 1M x 3k dataset (reference
# databricks/run_benchmark.sh:50-60; quality = inertia, bench_kmeans.py).
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase, fetch
from .gen_data import gen_low_rank_device
from .utils import with_benchmark


class BenchmarkKMeans(BenchmarkBase):
    name = "kmeans"
    extra_args = {
        "k": (int, 1000, "number of clusters (protocol: 1000)"),
        "maxIter": (int, 30, "Lloyd iterations (protocol: 30)"),
        "batch_rows": (
            int, 16384,
            "rows per assignment tile (HBM knob); the per-tile assignment + "
            "accumulation runs on the shared tiled distance core "
            "(ops/distance.py, docs/performance.md 'Tiled distance core')",
        ),
    }

    def gen_dataset(self, args, mesh):
        import jax

        if args.cpu_comparison:
            from .gen_data import gen_low_rank_host

            Xh = gen_low_rank_host(args.num_rows, args.num_cols, seed=args.seed)
            return self.dataset_from_arrays(Xh, None, args, mesh)
        n_dev = int(mesh.devices.size)
        X, w = gen_low_rank_device(
            args.num_rows, args.num_cols, seed=args.seed,
            mesh=mesh if n_dev > 1 else None,  # plain on 1 device (no Shardy copy)
        )
        # random-row init (initMode=random protocol config). The dataset rows
        # are iid, so ONE contiguous k-row block at a random offset is an
        # equally random sample — one dynamic_slice program, no per-row
        # device round trips (1000 of them cost ~145 s through the tunnel),
        # and no fancy-index gather on X (which materializes a second copy of
        # it — OOM at the 1M x 3k protocol shape).
        rng = np.random.default_rng(args.seed + 1)
        r0 = int(rng.integers(0, max(1, args.num_rows - args.k + 1)))
        centers0 = jax.jit(
            lambda X: jax.lax.dynamic_slice_in_dim(X, r0, args.k, 0)
        )(X)
        fetch(centers0[:1])
        fetch(w[:1])
        return {"X": X, "w": w, "centers0": centers0}

    def dataset_from_arrays(self, X, y, args, mesh):
        import jax

        from spark_rapids_ml_tpu.parallel import make_global_rows

        Xh = np.asarray(X, dtype=np.float32)
        rng = np.random.default_rng(args.seed + 1)
        # TRUE random-row init here: external datasets may be ordered (e.g.
        # written grouped by label), so a contiguous block is NOT a random
        # sample — and the rows are on host, so host fancy-indexing is free
        # (the contiguous-block trick in gen_dataset exists only for
        # device-resident iid generated data)
        idx = np.sort(rng.choice(len(Xh), min(args.k, len(Xh)), replace=False))
        c0 = np.ascontiguousarray(Xh[idx])
        Xd, w, _ = make_global_rows(mesh, Xh)  # pad + row-shard like the gens
        return {
            "X": Xd,
            "w": w,
            "centers0": jax.device_put(c0),
            "X_host": Xh,
            "centers0_host": c0,
        }

    def run_cpu(self, args, data):
        import time

        from sklearn.cluster import KMeans as SkKMeans

        t0 = time.perf_counter()
        SkKMeans(
            n_clusters=args.k, init=data["centers0_host"], n_init=1,
            max_iter=args.maxIter, tol=1e-20, algorithm="lloyd",
        ).fit(data["X_host"])
        return {"cpu_fit": time.perf_counter() - t0}

    def run_once(self, args, data, mesh):
        from jax import default_matmul_precision

        from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit
        from spark_rapids_ml_tpu.parallel.mesh import effective_matmul_precision

        def run():
            # KMeans precision policy: 3-pass bf16 MXU (see parallel/mesh.py)
            with default_matmul_precision(effective_matmul_precision("BF16_BF16_F32_X3")):
                return kmeans_fit(
                    data["X"], data["w"], data["centers0"], mesh=mesh,
                    max_iter=args.maxIter, tol=1e-20, batch_rows=args.batch_rows,
                )

        fetch(run()["cluster_centers_"])  # compile outside timing
        state = {}

        def timed():
            s = run()
            fetch(s["cluster_centers_"])
            state.update(s)
            return s

        _, sec = with_benchmark("kmeans fit", timed)
        self._inertia = float(np.asarray(state["inertia_"]))
        return {"fit": sec}

    def quality(self, args, data):
        return {"inertia": self._inertia}


if __name__ == "__main__":
    BenchmarkKMeans().run()
