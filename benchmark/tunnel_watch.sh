#!/usr/bin/env bash
#
# Watch the TPU tunnel; when it comes back, capture the round's on-chip
# evidence automatically: bench.py (headline JSON), then the full protocol
# sweep + RF ladder (capture_protocol.sh). Probe log: /tmp/tunnel_watch.log.
#
set -uo pipefail
cd "$(dirname "$0")/.."
TAG="${1:-r05}"
for i in $(seq 1 "${2:-140}"); do
  if timeout 120 python -c "import jax; print(jax.devices())" > /tmp/tunnel_watch.log 2>&1; then
    echo "TUNNEL UP at probe $i ($(date -u +%H:%M:%S)): $(tail -1 /tmp/tunnel_watch.log)"
    echo "== capturing bench.py"
    BENCH_ATTEMPTS=3 python bench.py > "/tmp/bench_${TAG}_live.json" 2> "/tmp/bench_${TAG}_live.log"
    echo "bench done: $(cat /tmp/bench_${TAG}_live.json)"
    echo "== capturing protocol"
    bash benchmark/capture_protocol.sh "${TAG}" > "/tmp/protocol_${TAG}.log" 2>&1
    echo "protocol done; rows:"
    cat "PROTOCOL_${TAG}.csv" 2>/dev/null
    exit 0
  fi
  echo "probe $i down ($(date -u +%H:%M:%S))" >> /tmp/tunnel_watch_history.log
  sleep 180
done
echo "TUNNEL STILL DOWN after all probes ($(date -u +%H:%M:%S))"
exit 1
