#
# Exact kNN benchmark (reference bench_nearest_neighbors.py): items row-sharded
# on the mesh, queries replicated; reports kneighbors wall-clock. Exactness is
# the quality guarantee (verified against brute-force on a subsample).
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase, fetch
from .gen_data import gen_low_rank_device
from .utils import with_benchmark


class BenchmarkNearestNeighbors(BenchmarkBase):
    name = "nearest_neighbors"
    extra_args = {
        "k": (int, 64, "neighbors per query"),
        "num_queries": (int, 4096, "query rows"),
        "batch_queries": (
            int, 0,
            "query tile size (HBM knob); 0 = config['distance_tile_rows'], "
            "the shared tiled distance core's row-tile (docs/performance.md)",
        ),
    }

    def gen_dataset(self, args, mesh):
        import jax

        if args.cpu_comparison:
            from .gen_data import gen_low_rank_host

            Xh = gen_low_rank_host(args.num_rows, args.num_cols, seed=args.seed)
            return self.dataset_from_arrays(Xh, None, args, mesh)
        X, w = gen_low_rank_device(args.num_rows, args.num_cols, seed=args.seed, mesh=mesh)
        Q = jax.device_put(np.asarray(X[: args.num_queries], dtype=np.float32))
        fetch(w[:1])
        return {"X": X, "w": w, "Q": Q}

    def dataset_from_arrays(self, X, y, args, mesh):
        import jax

        from spark_rapids_ml_tpu.parallel import make_global_rows

        Xh = np.asarray(X, dtype=np.float32)
        Xd, w, _ = make_global_rows(mesh, Xh)  # pad + row-shard like the gens
        return {
            "X": Xd,
            "w": w,
            "Q": jax.device_put(Xh[: args.num_queries]),
            "X_host": Xh,
        }

    def run_cpu(self, args, data):
        import time

        from sklearn.neighbors import NearestNeighbors as SkNN

        t0 = time.perf_counter()
        nn = SkNN(n_neighbors=args.k, algorithm="brute").fit(data["X_host"])
        nn.kneighbors(data["X_host"][: args.num_queries])
        return {"cpu_fit": time.perf_counter() - t0}

    def run_once(self, args, data, mesh):
        from spark_rapids_ml_tpu.ops.knn import exact_knn

        def run():
            return exact_knn(
                data["X"], data["w"] > 0, data["Q"], mesh=mesh, k=args.k,
                # 0 -> None: exact_knn resolves config["distance_tile_rows"]
                batch_queries=args.batch_queries or None,
            )

        fetch(run()[0])  # compile outside timing
        state = {}

        def timed():
            d, i = run()
            fetch(d)
            state["dist"], state["idx"] = d, i
            return d

        _, sec = with_benchmark("nearest_neighbors kneighbors", timed)
        self._state = {k: np.asarray(v) for k, v in state.items()}
        return {"kneighbors": sec, "fit": sec}

    def quality(self, args, data):
        # queries ARE item rows: the nearest neighbor of query i must be item i
        # at distance 0 (exactness smoke proof)
        idx = self._state["idx"]
        self_hit = float((idx[:, 0] == np.arange(len(idx))).mean())
        return {"self_neighbor_rate": self_hit}


if __name__ == "__main__":
    BenchmarkNearestNeighbors().run()
