#
# LogisticRegression benchmark — protocol config maxIter=200, tol=1e-30,
# regParam=1e-5 on 1M x 3k classification (reference
# databricks/run_benchmark.sh:131-140; quality = training accuracy).
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase, fetch
from .gen_data import gen_classification_device
from .utils import with_benchmark


class BenchmarkLogisticRegression(BenchmarkBase):
    name = "logistic_regression"
    extra_args = {
        "maxIter": (int, 200, "L-BFGS iterations (protocol: 200)"),
        "reg": (float, 1e-5, "regParam (protocol: 1e-5)"),
        "elasticNetParam": (float, 0.0, "L1 ratio (OWL-QN path when > 0)"),
        "n_classes": (int, 2, "label cardinality"),
        "density": (float, 0.0,
                    "feature density; > 0 runs the sparse padded-ELL lane over"
                    " the partition-parallel generator (reference tests_large"
                    " shape: 1e7 x 2200 at 0.001)"),
    }

    def gen_dataset(self, args, mesh):
        if args.density > 0:
            return self._gen_sparse(args, mesh)
        if args.cpu_comparison:
            from .gen_data import gen_classification_host

            Xh, yh = gen_classification_host(
                args.num_rows, args.num_cols, args.n_classes, args.seed
            )
            return self.dataset_from_arrays(Xh, yh, args, mesh)
        X, y, w = gen_classification_device(
            args.num_rows, args.num_cols, n_classes=args.n_classes, seed=args.seed, mesh=mesh
        )
        fetch(w[:1])
        return {"X": X, "y": y, "w": w}

    def _gen_sparse(self, args, mesh):
        """Sparse lane: stream partition-parallel CSR partitions into padded
        ELL (never materializing the full CSR driver-side), binarize the
        regression target at 0, and row-shard the ELL tensors on the mesh —
        the one certified recipe shared with bench.py."""
        # fail fast on flag combinations the lane cannot honor, BEFORE the
        # (potentially minutes-long) scale-shape generation
        if args.cpu_comparison:
            raise SystemExit(
                "--cpu_comparison is not supported with --density (the sparse "
                "lane streams partitions and keeps no host CSR copy)"
            )
        if args.n_classes != 2:
            raise SystemExit(
                "--density runs the binarized-target sparse lane; only "
                "--n_classes 2 is supported"
            )
        from .gen_data_distributed import sparse_classification_ell

        data = sparse_classification_ell(
            args.num_rows, args.num_cols, args.density, args.seed, mesh
        )
        fetch(data["w"][:1])
        return data

    def dataset_from_arrays(self, X, y, args, mesh):
        from spark_rapids_ml_tpu.parallel import make_global_rows

        if args.density > 0:
            raise SystemExit(
                "--dataset_path loads a dense block; it cannot be combined "
                "with the --density sparse-ELL lane"
            )
        if y is None:
            raise ValueError("logistic_regression dataset needs a label column")
        Xh = np.asarray(X, dtype=np.float32)
        yh = np.asarray(y, dtype=np.float32)
        Xd, w, _ = make_global_rows(mesh, Xh)  # pad + row-shard like the gens
        yd, _, _ = make_global_rows(mesh, yh.astype(np.int32))
        return {
            "X": Xd,
            "y": yd,
            "w": w,
            "X_host": Xh,
            "y_host": yh,
        }

    def run_cpu(self, args, data):
        import time

        from sklearn.linear_model import LogisticRegression as SkLR

        # Spark regParam -> sklearn C = 1 / (n * regParam)
        C = 1.0 / max(len(data["X_host"]) * args.reg, 1e-30)
        t0 = time.perf_counter()
        SkLR(C=C, max_iter=args.maxIter, tol=1e-30, solver="lbfgs").fit(
            data["X_host"], data["y_host"]
        )
        return {"cpu_fit": time.perf_counter() - t0}

    def run_once(self, args, data, mesh):
        from spark_rapids_ml_tpu.ops.logistic import logistic_fit, logistic_fit_ell

        l1 = args.reg * args.elasticNetParam

        if args.density > 0:
            def run():
                return logistic_fit_ell(
                    data["values"], data["indices"], data["y"], data["w"],
                    d=args.num_cols, k=2, multinomial=False,
                    lam_l2=args.reg * (1.0 - args.elasticNetParam), lam_l1=l1,
                    use_l1=l1 > 0, fit_intercept=True, standardize=True,
                    max_iter=args.maxIter, tol=1e-30,
                )
        else:
            def run():
                return logistic_fit(
                    data["X"], data["y"], data["w"],
                    k=args.n_classes, multinomial=args.n_classes > 2,
                    lam_l2=args.reg * (1.0 - args.elasticNetParam), lam_l1=l1,
                    use_l1=l1 > 0, fit_intercept=True, standardize=True,
                    max_iter=args.maxIter, tol=1e-30,
                )

        fetch(run()["coef_"])  # compile outside timing
        state = {}

        def timed():
            s = run()
            fetch(s["coef_"])
            state.update(s)
            return s

        _, sec = with_benchmark("logistic_regression fit", timed)
        self._state = {k: np.asarray(v) for k, v in state.items()}
        self._data = data
        return {"fit": sec}

    def quality(self, args, data):
        import jax
        import jax.numpy as jnp

        coef = self._state["coef_"]
        intercept = self._state["intercept_"]

        if args.density > 0:
            from spark_rapids_ml_tpu.ops.sparse import ell_matmul

            @jax.jit
            def acc_ell(values, indices, y, w):
                z = ell_matmul(values, indices, jnp.asarray(coef[0])[:, None])[:, 0]
                pred = (z + intercept[0] > 0).astype(jnp.int32)
                # padding rows carry w == 0: mask them out of the mean
                return jnp.sum(w * (pred == y).astype(jnp.float32)) / jnp.sum(w)

            return {
                "accuracy": float(np.asarray(
                    acc_ell(data["values"], data["indices"], data["y"], data["w"])
                )),
                "n_iter": float(self._state["n_iter_"]),
            }

        @jax.jit
        def acc(X, y):
            if coef.shape[0] == 1:
                pred = (X @ coef[0] + intercept[0] > 0).astype(jnp.int32)
            else:
                pred = jnp.argmax(X @ coef.T + intercept[None, :], axis=1).astype(jnp.int32)
            return jnp.mean((pred == y).astype(jnp.float32))

        return {
            "accuracy": float(np.asarray(acc(data["X"], data["y"]))),
            "n_iter": float(self._state["n_iter_"]),
        }


if __name__ == "__main__":
    BenchmarkLogisticRegression().run()
