#
# Multi-host scaling lane for the fleet observability plane
# (docs/observability.md "Fleet plane").
#
# Two scenarios, both on the CPU SPMD harness (LocalRendezvous threads —
# the same substrate tests/test_parallel.py certifies against the real
# multi-host control plane):
#
#   * scaling — N ranks each stream numpy work slices through lockstep
#     rendezvous rounds WITH periodic forced ops rounds riding the same
#     control plane. The lane value is aggregate rows/sec at the widest
#     rank count; per-count values ride `fleet_scale_<n>` sub-lanes so the
#     PR-10 per-lane trajectory gate sees the scaling CURVE, not one point
#     (a fleet-plane overhead regression shows up as the wide counts
#     flattening while n=1 stays put);
#
#   * utilization — per-tenant chip-window reservations against a fresh
#     2-D ledger, rolled up through the fleet merge (`chips_busy` /
#     `chips_idle` and per-tenant device-time splits) — utilization vs
#     tenant count is the number the capacity dashboard plots.
#
# `--smoke --write <path>` is the CI transcript (ci/test.sh): a 3-rank
# aggregation round with crafted distinct per-rank counters, asserting the
# merged counters equal the per-rank sum, then archiving the merged cluster
# snapshot next to the verdict JSONs.
#
# Excluded from the gated geomean until the lane history stabilizes
# (bench.py BASELINES carries no entry; trajectory-start gating in
# benchmark/regression.py makes later promotion cheap).
#
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def run_fleet_scaling_bench(
    nranks_list: Sequence[int] = (1, 2, 3),
    rows_per_rank: int = 50_000,
    n_cols: int = 64,
    *,
    n_rounds: int = 8,
    ops_every: int = 2,
    seed: int = 0,
) -> Dict[str, Any]:
    """One scaling sweep: for each rank count, N threads each run
    `n_rounds` lockstep iterations of (numpy work slice -> allgather),
    forcing a fleet ops round every `ops_every` iterations — the
    aggregation cost rides the measured wall like it does in production.
    Returns the per-count aggregate rows/sec, the widest count's value as
    the lane metric, and the last merged cluster view's vitals."""
    from spark_rapids_ml_tpu import telemetry
    from spark_rapids_ml_tpu.ops_plane import fleet
    from spark_rapids_ml_tpu.parallel import LocalRendezvous

    telemetry.enable()
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((rows_per_rank, n_cols), dtype=np.float32)
    w = rng.standard_normal((n_cols,), dtype=np.float32)

    scale: Dict[int, float] = {}
    last_view: Optional[Dict[str, Any]] = None
    for n in nranks_list:
        n = int(n)
        fleet.reset()
        rdvs = LocalRendezvous.create(n, timeout_s=60.0)
        views: List[Optional[Dict[str, Any]]] = [None] * n
        errors: List[BaseException] = []

        def work(rank: int) -> None:
            rdv = rdvs[rank]
            try:
                for i in range(n_rounds):
                    # the work slice: one pass over this rank's rows
                    float((x @ w).sum())
                    rdv.allgather(f"step:{i}")
                    if (i + 1) % ops_every == 0:
                        v = fleet.ops_round(rdv, force=True)
                        if v is not None:
                            views[rank] = v
            except BaseException as e:  # surfaced after join — a hung
                errors.append(e)  # thread must not wedge the lane
                rdv.abort(f"bench rank {rank}: {type(e).__name__}")

        threads = [
            threading.Thread(target=work, args=(r,), daemon=True) for r in range(n)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        wall = time.perf_counter() - t0
        if errors:
            raise RuntimeError(
                f"fleet scaling lane: rank thread died at n={n}: "
                f"{type(errors[0]).__name__}: {errors[0]}"
            )
        scale[n] = (n * rows_per_rank * n_rounds) / wall if wall else 0.0
        merged = [v for v in views if v is not None]
        if merged:
            last_view = merged[-1]

    widest = int(max(nranks_list))
    counters = telemetry.registry().snapshot()["counters"]
    out: Dict[str, Any] = {
        "rows_per_sec": scale[widest],
        "nranks": float(widest),
        "scale": {str(k): round(v, 1) for k, v in sorted(scale.items())},
        "ops_rounds": float(counters.get("fleet.ops_rounds", 0.0)),
        "ops_rounds_failed": float(counters.get("fleet.ops_rounds_failed", 0.0)),
    }
    if last_view is not None:
        out["ranks_reporting"] = float(last_view.get("ranks_reporting", 0))
        out["cluster_healthy"] = bool(
            (last_view.get("health") or {}).get("healthy", True)
        )
    return out


def run_fleet_utilization_bench(
    tenant_counts: Sequence[int] = (1, 2, 4),
    pool_chips: int = 8,
    *,
    bytes_per_tenant: int = 1 << 20,
    hold_s: float = 0.05,
) -> Dict[str, Any]:
    """Utilization-vs-tenants sweep over a fresh 2-D ledger: each tenant
    claims a disjoint chip window, the fleet rollup reports the pool's
    chips_busy/chips_idle, and the lane value is the widest sweep's pool
    utilization (busy / total). Per-tenant device-time splits ride the
    merged tenants view the same way `opsreport --cluster` renders them."""
    from spark_rapids_ml_tpu.scheduler import reset_global_ledger
    from spark_rapids_ml_tpu.scheduler.ledger import merge_tenant_usage

    sweep: Dict[int, Dict[str, float]] = {}
    for n in tenant_counts:
        n = int(n)
        ledger = reset_global_ledger()
        ledger.note_chip_pool(pool_chips)
        width = max(1, pool_chips // max(1, n))
        held = [
            ledger.reserve(
                f"bench_fleet:{t}", "fit", bytes_per_tenant,
                tenant=f"tenant{t}",
                chip_ids=range(t * width, min(pool_chips, (t + 1) * width)),
            )
            for t in range(n)
        ]
        time.sleep(hold_s)  # integrate some chip-seconds before the read
        usage = merge_tenant_usage([ledger.tenant_usage()])
        for r in held:
            ledger.release(r)
        pool = usage.get("_pool") or {}
        busy = float(pool.get("chips_busy", 0.0))
        total = float(pool.get("chips_total", pool_chips)) or 1.0
        sweep[n] = {
            "utilization": busy / total,
            "chips_busy": busy,
            "chips_idle": float(pool.get("chips_idle", 0.0)),
            "chip_seconds": sum(
                float(u.get("chip_seconds", 0.0))
                for t, u in usage.items()
                if t != "_pool"
            ),
        }
    widest = int(max(tenant_counts))
    return {
        "utilization": sweep[widest]["utilization"],
        "pool_chips": float(pool_chips),
        "tenants": float(widest),
        "sweep": {str(k): v for k, v in sorted(sweep.items())},
    }


def run_fleet_smoke(nranks: int = 3) -> Dict[str, Any]:
    """The CI aggregation smoke: one forced ops round over `nranks`
    LocalRendezvous threads with crafted DISTINCT per-rank counters (the
    threaded harness shares one registry, so the payload hook is what makes
    the sum assertion meaningful). Raises when the merged counters differ
    from the per-rank sum; returns the merged cluster view for archival."""
    from spark_rapids_ml_tpu import core, telemetry
    from spark_rapids_ml_tpu.ops_plane import fleet
    from spark_rapids_ml_tpu.parallel import LocalRendezvous

    saved = {
        k: core.config[k]
        for k in ("metrics_bucket_seconds", "metrics_bucket_count")
    }
    core.config["metrics_bucket_seconds"] = 0.25
    core.config["metrics_bucket_count"] = 8
    was_enabled = telemetry.enabled()
    telemetry.registry().reset()
    telemetry.enable()
    fleet.reset()
    try:
        rdvs = LocalRendezvous.create(nranks, timeout_s=60.0)
        views: List[Optional[Dict[str, Any]]] = [None] * nranks
        errors: List[BaseException] = []

        def work(rank: int) -> None:
            try:
                payload = dict(
                    fleet.local_payload(rank),
                    rank=rank,
                    counters={"fleet_smoke.work": float(rank + 1)},
                )
                views[rank] = fleet.ops_round(
                    rdvs[rank], force=True, payload=payload
                )
            except BaseException as e:
                errors.append(e)
                rdvs[rank].abort(f"smoke rank {rank}: {type(e).__name__}")

        threads = [
            threading.Thread(target=work, args=(r,), daemon=True)
            for r in range(nranks)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        if errors:
            raise RuntimeError(
                f"fleet smoke: rank thread died: "
                f"{type(errors[0]).__name__}: {errors[0]}"
            )
        view = next((v for v in views if v is not None), None)
        if view is None:
            raise RuntimeError("fleet smoke: no rank received a merged view")
        got = view["counters"].get("fleet_smoke.work")
        want = float(sum(range(1, nranks + 1)))
        if got != want:
            raise RuntimeError(
                f"fleet smoke: merged counter {got!r} != per-rank sum {want!r}"
            )
        if view["ranks_reporting"] != nranks or view["missing"]:
            raise RuntimeError(
                f"fleet smoke: {view['ranks_reporting']}/{nranks} ranks "
                f"reporting, missing {view['missing']}"
            )
        return view
    finally:
        if not was_enabled:
            telemetry.disable()
        core.config.update(saved)
        fleet.reset()


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="run the 3-rank CI aggregation smoke and exit")
    p.add_argument("--nranks", type=int, default=3,
                   help="rank count for --smoke (default 3)")
    p.add_argument("--write", metavar="PATH",
                   help="archive the merged cluster snapshot JSON here")
    args = p.parse_args(argv)
    if args.smoke:
        view = run_fleet_smoke(args.nranks)
        if args.write:
            with open(args.write, "w") as f:
                json.dump({"cluster": view}, f, indent=2, default=str)
        print(
            f"fleet smoke OK: {int(view['ranks_reporting'])}/{args.nranks} "
            f"ranks merged, cluster healthy="
            f"{(view.get('health') or {}).get('healthy', True)}",
            file=sys.stderr,
        )
        return 0
    out = run_fleet_scaling_bench()
    util = run_fleet_utilization_bench()
    print(json.dumps({"scaling": out, "utilization": util}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
