#
# PCA benchmark — protocol config k=3 on the 1M x 3k low-rank matrix
# (reference bench_pca.py; quality score = orthonormality max|I − PPᵀ| +
# Σ explained variance, bench_pca.py:86-110).
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase, fetch
from .gen_data import gen_low_rank_device
from .utils import with_benchmark


class BenchmarkPCA(BenchmarkBase):
    name = "pca"
    extra_args = {
        "k": (int, 3, "number of components (protocol: 3)"),
    }

    def gen_dataset(self, args, mesh):
        if args.cpu_comparison:
            # host-generated so the sklearn arm sees the same rows (fetching a
            # device-generated matrix back is off the table: ~4 MB/s tunnel)
            from .gen_data import gen_low_rank_host

            Xh = gen_low_rank_host(args.num_rows, args.num_cols, seed=args.seed)
            return self.dataset_from_arrays(Xh, None, args, mesh)
        X, w = gen_low_rank_device(args.num_rows, args.num_cols, seed=args.seed, mesh=mesh)
        fetch(w[:1])
        return {"X": X, "w": w}

    def dataset_from_arrays(self, X, y, args, mesh):
        from spark_rapids_ml_tpu.parallel import make_global_rows

        Xh = np.asarray(X, dtype=np.float32)
        # mesh-aware layout (pad + row-shard), exactly like the generator path
        Xd, w, _ = make_global_rows(mesh, Xh)
        return {"X": Xd, "w": w, "X_host": Xh}

    def run_cpu(self, args, data):
        import time

        from sklearn.decomposition import PCA as SkPCA

        t0 = time.perf_counter()
        SkPCA(n_components=args.k, svd_solver="randomized", random_state=0).fit(
            data["X_host"]
        )
        return {"cpu_fit": time.perf_counter() - t0}

    def run_once(self, args, data, mesh):
        import jax

        from spark_rapids_ml_tpu.ops.pca import pca_fit

        fit = jax.jit(lambda X, w: pca_fit(X, w, k=args.k))
        fetch(fit(data["X"], data["w"])["components_"])  # compile outside timing
        state, sec = with_benchmark(
            "pca fit", lambda: fetch(fit(data["X"], data["w"])["components_"])
        )
        self._components = state
        return {"fit": sec}

    def quality(self, args, data):
        P = np.asarray(self._components, dtype=np.float64)
        ortho = float(np.abs(np.eye(P.shape[0]) - P @ P.T).max())
        return {"orthonormality_err": ortho}


if __name__ == "__main__":
    BenchmarkPCA().run()
