#
# Assemble a cross-rank post-mortem from flight-recorder dumps.
#
#   python -m benchmark.postmortem /path/to/flightrec_dir --nranks 3
#
# Reads every `flightrec_rank_<r>.jsonl` the failed run dumped (ranks write
# them on any SrmlError / abort publication; a hard-killed rank writes
# NOTHING — its absence is evidence), correlates them by trace id, and
# prints one timeline naming the failed rank, the round it died in, and what
# every survivor was blocked on. `--json` emits the machine-readable form.
# See docs/robustness.md "Post-mortems" / docs/observability.md.
#
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump_dir", help="directory holding flightrec_rank_<r>.jsonl dumps")
    ap.add_argument("--nranks", type=int, default=None,
                    help="expected rank count (absent dumps become missing-rank evidence)")
    ap.add_argument("--trace-id", default=None,
                    help="assemble this trace (default: newest seen in the dumps)")
    ap.add_argument("--last-k", type=int, default=25,
                    help="events of per-rank tail to include")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable post-mortem dict instead of text")
    ap.add_argument("-o", "--out", default=None,
                    help="also write the machine-readable JSON here")
    args = ap.parse_args(argv)

    from spark_rapids_ml_tpu.diagnostics import assemble_postmortem, render_postmortem

    pm = assemble_postmortem(
        args.dump_dir, nranks=args.nranks, trace_id=args.trace_id, last_k=args.last_k
    )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(pm, f, indent=2, default=str)
    print(json.dumps(pm, indent=2, default=str) if args.as_json else render_postmortem(pm))
    # exit 0 when the assembler reached a verdict, 2 when it found no failure
    # evidence (so harnesses can tell "clean run" from "named a culprit")
    return 0 if pm.get("failed_rank") is not None else 2


if __name__ == "__main__":
    sys.exit(main())
