#
# CrossValidator grid-sweep benchmark — the multi-fit engine's acceptance
# lane (docs/performance.md "Multi-fit engine"). A numFolds x paramMaps CV
# fit is the dominant production fit workload: this bench measures what the
# engine claims to eliminate — per-fold ingest/layout and per-param-map
# dispatch — by reporting solves/sec and the INGEST COUNT per CV fit
# (1 under the engine, numFolds+1 without it) straight from the telemetry
# registry, alongside the usual wall-clock row.
#
from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from .base import BenchmarkBase


def run_cv_fit(
    n_rows: int,
    n_cols: int,
    *,
    num_folds: int = 3,
    grid_size: int = 4,
    algo: str = "logistic",
    max_iter: int = 30,
    seed: int = 0,
) -> Dict[str, float]:
    """One telemetry-instrumented CV grid fit over a host dataset (the dict
    fast-ingest path); returns wall time plus the engine counters. Shared by
    the BenchmarkBase lane below and bench.py's BENCH_CV lane."""
    from spark_rapids_ml_tpu import telemetry
    from spark_rapids_ml_tpu.evaluation import (
        MulticlassClassificationEvaluator,
        RegressionEvaluator,
    )
    from spark_rapids_ml_tpu.models.classification import LogisticRegression
    from spark_rapids_ml_tpu.models.regression import LinearRegression
    from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n_rows, n_cols), dtype=np.float32)
    coef = rng.standard_normal(n_cols).astype(np.float32)
    margin = x @ coef
    if algo == "logistic":
        est = LogisticRegression(maxIter=max_iter, tol=1e-12)
        eva = MulticlassClassificationEvaluator(metricName="accuracy")
        data = {"features": x, "label": (margin > 0).astype(np.float64)}
    else:
        est = LinearRegression()
        eva = RegressionEvaluator(metricName="rmse")
        data = {
            "features": x,
            "label": (margin + 0.1 * rng.standard_normal(n_rows)).astype(np.float64),
        }
    est.setFeaturesCol("features")
    grid = (
        ParamGridBuilder()
        .addGrid(est.getParam("regParam"), list(np.logspace(-6, -3, grid_size)))
        .build()
    )
    cv = CrossValidator(
        estimator=est, estimatorParamMaps=grid, evaluator=eva,
        numFolds=num_folds, seed=seed,
    )

    telemetry.enable()
    mark = telemetry.registry().mark()
    t0 = time.perf_counter()
    cv.fit(data)
    wall_s = time.perf_counter() - t0
    counters = telemetry.registry().delta(mark)["counters"]

    n_solves = num_folds * grid_size + 1  # + the best-model refit
    return {
        "fit": wall_s,
        "solves": float(n_solves),
        "solves_per_sec": n_solves / wall_s,
        "ingests": counters.get("ingest.datasets", 0.0),
        "placement_reuses": counters.get("fit.device_dataset_reuses", 0.0),
        "solves_batched": counters.get("fit.solves_batched", 0.0),
        "solves_sequential": counters.get("fit.solves_sequential", 0.0),
    }


class BenchmarkCV(BenchmarkBase):
    name = "cv"
    extra_args = {
        "num_folds": (int, 3, "CV folds"),
        "grid_size": (int, 4, "regParam grid points"),
        "algo": (str, "logistic", "logistic | linear"),
        "maxIter": (int, 30, "solver iterations (logistic)"),
    }

    def gen_dataset(self, args, mesh) -> Dict[str, Any]:
        # data is generated inside run_cv_fit (host-side: CV ingests from the
        # host exactly because ingest cost is what this lane measures)
        return {}

    def run_once(self, args, data, mesh) -> Dict[str, float]:
        out = run_cv_fit(
            args.num_rows, args.num_cols,
            num_folds=args.num_folds, grid_size=args.grid_size,
            algo=args.algo, max_iter=args.maxIter, seed=args.seed,
        )
        data["counters"] = {k: v for k, v in out.items() if k != "fit"}
        return {"fit": out["fit"]}

    def quality(self, args, data) -> Dict[str, float]:
        # solves/sec + ingest-count-per-CV-fit: the engine's acceptance
        # numbers (1 ingest under the engine vs numFolds+1 without it)
        return data.get("counters", {})


if __name__ == "__main__":
    BenchmarkCV().run()
