#
# Multi-tenant scheduler contention lane (docs/scheduling.md "Benchmark").
#
# N tenants with ADVERSARIAL job sizes — one big low-priority fit per pair of
# tenants, interleaved with bursts of small high-priority fits — submitted
# through one `FitScheduler` against a budget sized so the big jobs cannot
# co-reside with each other. What the lane measures is the scheduling plane
# itself:
#
#   * utilization — byte-seconds reserved in the shared ledger over
#     budget × wall (bin-packing efficiency: idle HBM is the waste this
#     subsystem exists to reclaim);
#   * per-tenant queue-wait p50/p99 — the fairness numbers (high-priority
#     tenants should wait ~one checkpoint segment, never a whole big fit);
#   * preemption/resume/demotion counts — the ladder actually exercising;
#   * total fit throughput (rows/sec across every completed job) — the
#     headline `@RESULT` value.
#
# Excluded from the gated geomean until the lane history stabilizes
# (bench.py BASELINES carries no entry; trajectory-start gating in
# benchmark/regression.py makes later promotion cheap).
#
from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

from .base import BenchmarkBase


def _quantile(values: List[float], q: float) -> float:
    """telemetry.quantile_of with the lane's 0.0-on-empty convention — the
    one shared nearest-rank extraction (docs/observability.md)."""
    from spark_rapids_ml_tpu.telemetry import quantile_of

    v = quantile_of(values, q)
    return 0.0 if v is None else v


def run_scheduler_bench(
    n_tenants: int = 4,
    big_rows: int = 60_000,
    n_cols: int = 32,
    *,
    small_rows: int = 2_000,
    small_jobs_per_tenant: int = 3,
    max_iter_big: int = 120,
    max_iter_small: int = 10,
    checkpoint_every: int = 3,
    seed: int = 0,
) -> Dict[str, float]:
    """One contention scenario (module docstring): even tenants submit one
    big priority-0 fit each; odd tenants burst `small_jobs_per_tenant`
    priority-10 fits that must bin-pack beside — or preempt — the big ones.
    Returns utilization, per-tenant queue-wait quantiles, preemption counts,
    and total rows/sec. Shared by the BenchmarkBase lane below and bench.py's
    BENCH_SCHED lane."""
    from spark_rapids_ml_tpu import core, memory, telemetry
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.ops_plane import slo as ops_slo
    from spark_rapids_ml_tpu.scheduler import FitScheduler, reset_global_ledger

    telemetry.enable()
    rng = np.random.default_rng(seed)
    x_big = rng.standard_normal((big_rows, n_cols), dtype=np.float32)
    x_small = rng.standard_normal((small_rows, n_cols), dtype=np.float32)
    df_big = {"features": x_big}
    df_small = {"features": x_small}

    def mk_big():
        est = KMeans(k=16, maxIter=max_iter_big, tol=0.0, seed=7)
        est.num_workers = 1
        return est

    def mk_small():
        est = KMeans(k=4, maxIter=max_iter_small, seed=3)
        est.num_workers = 1
        return est

    # budget: a big job fits ALONE but not beside even one small job — the
    # adversarial shape: a high-priority small burst must preempt the running
    # big fit (which resumes from its boundary checkpoint), and big jobs
    # serialize against each other
    ext_b = mk_big()._pre_process_data(df_big, for_fit=True, defer_validation=True)
    need_b = memory.resident_estimate(mk_big(), ext_b, 1).total()
    ext_s = mk_small()._pre_process_data(df_small, for_fit=True, defer_validation=True)
    need_s = memory.resident_estimate(mk_small(), ext_s, 1).total()
    saved = {
        k: core.config[k]
        for k in ("hbm_budget_bytes", "checkpoint_every_iters",
                  "sched_max_preemptions", "slo")
    }
    core.config["hbm_budget_bytes"] = int((need_b + 0.5 * need_s) / 0.9)
    core.config["checkpoint_every_iters"] = int(checkpoint_every)
    core.config["sched_max_preemptions"] = 2
    if not saved["slo"]:
        # report-only SLO verdict embedded in the BENCH record (outside the
        # gated geomean): queue-wait latency + ledger-utilization ceiling
        core.config["slo"] = [
            {"name": "queue_wait_p99", "kind": "latency",
             "histogram": "scheduler.queue_wait_s", "threshold_s": 60.0,
             "objective": 0.95},
            {"name": "ledger_util", "kind": "gauge_ceiling",
             "gauge": "scheduler.ledger_utilization", "ceiling": 1.0},
        ]

    ledger = reset_global_ledger()
    # budget-conformance samples: (reserved, budget) at EVERY admission
    over = [0]

    def _check(reserved: int, budget: Any) -> None:
        if budget is not None and reserved > budget:
            over[0] += 1

    ledger.admission_hooks.append(_check)

    sched = FitScheduler()
    jobs = []
    t0 = time.perf_counter()
    try:
        for t in range(n_tenants):
            tenant = f"tenant{t}"
            if t % 2 == 0:
                jobs.append(
                    (sched.submit(mk_big(), df_big, tenant=tenant, priority=0), big_rows)
                )
            else:
                for _ in range(small_jobs_per_tenant):
                    jobs.append(
                        (
                            sched.submit(
                                mk_small(), df_small, tenant=tenant, priority=10
                            ),
                            small_rows,
                        )
                    )
        for job, _ in jobs:
            job.result(timeout=900)
        wall = time.perf_counter() - t0
        stats = sched.stats()
        budget = core.config["hbm_budget_bytes"] * 0.9
        # time-integrated utilization: byte-seconds each job held its
        # reservation while running, over budget x wall
        byte_seconds = sum(j.admitted_bytes * j.run_s for j, _ in jobs)
        utilization = byte_seconds / (budget * wall) if budget and wall else 0.0
        waits = [j.queue_wait_s for j, _ in jobs]
        hi_waits = [j.queue_wait_s for j, _ in jobs if j.priority > 0]
        per_tenant = {
            name: {
                "queue_wait_p50_s": _quantile(t_stats["queue_wait_s"], 0.50),
                "queue_wait_p99_s": _quantile(t_stats["queue_wait_s"], 0.99),
                "preemptions": t_stats["preemptions"],
                "demotions": t_stats["demotions"],
            }
            for name, t_stats in stats["tenants"].items()
        }
        counters = telemetry.registry().snapshot()["counters"]
        # end-of-run ops verdicts (report-only BENCH embeds): the SLO health
        # over THIS run's queue waits, and the ledger's per-tenant
        # byte-second integration
        slo_health = ops_slo.health(fresh=True)
        tenant_usage = ledger.tenant_usage()
        total_rows = float(sum(rows for _, rows in jobs))
        out: Dict[str, float] = {
            "fit": wall,
            "wall_s": wall,
            "jobs": float(len(jobs)),
            "rows_per_sec": total_rows / wall,
            "utilization": utilization,
            "ledger_high_watermark": float(ledger.high_watermark),
            "ledger_over_budget_admissions": float(over[0]),
            "queue_wait_p50_s": _quantile(waits, 0.50),
            "queue_wait_p99_s": _quantile(waits, 0.99),
            "hi_priority_wait_p99_s": _quantile(hi_waits, 0.99),
            "preemptions": float(counters.get("scheduler.jobs_preempted", 0.0)),
            "resumes": float(counters.get("scheduler.jobs_resumed", 0.0)),
            "demotions": float(counters.get("scheduler.jobs_demoted", 0.0)),
        }
        out["per_tenant"] = per_tenant  # type: ignore[assignment]
        out["slo"] = {  # type: ignore[assignment]
            "healthy": slo_health["healthy"],
            "failing": slo_health["failing"],
            "verdicts": slo_health["verdicts"],
        }
        out["tenant_byte_seconds"] = {  # type: ignore[assignment]
            t: round(u.get("byte_seconds", 0.0), 3)
            for t, u in tenant_usage.items()
        }
        return out
    finally:
        sched.shutdown(wait=True, timeout=60)
        ledger.admission_hooks.remove(_check)
        core.config.update(saved)
        ops_slo.reset()


def _occupancy_integral(samples: List, t_end: float) -> Dict[str, float]:
    """Step-integral of a (timestamp, chips-held) poll trace: chip-seconds,
    time-averaged chips, and peak — the occupancy dimension of the 2-D book
    (docs/scheduling.md "2-D placement")."""
    if not samples:
        return {"chip_seconds": 0.0, "avg_chips": 0.0, "peak_chips": 0.0}
    area, peak = 0.0, 0.0
    closed = samples + [(t_end, samples[-1][1])]
    for (t0, v), (t1, _) in zip(closed, closed[1:]):
        area += v * max(0.0, t1 - t0)
        peak = max(peak, float(v))
    span = max(1e-9, t_end - samples[0][0])
    return {
        "chip_seconds": area,
        "avg_chips": area / span,
        "peak_chips": peak,
    }


def run_coadmission_bench(
    n_rows: int = 40_000,
    n_cols: int = 32,
    *,
    k: int = 8,
    max_iter: int = 12,
    seed: int = 0,
    poll_interval_s: float = 0.002,
) -> Dict[str, float]:
    """Co-admission utilization lane (docs/scheduling.md "2-D placement"):
    the SAME two half-mesh-wide KMeans fits run (a) co-admitted by the 2-D
    ledger onto disjoint contiguous chip windows and (b) time-sliced
    (`max_concurrent=1`) — the only difference is placement. Reports the
    aggregate rows/sec ratio and the chip-occupancy integral of both phases
    (concurrent should hold ~the whole pool, sliced ~half), plus the
    placement bit-identity check (max |Δcenters| across phases must be 0 —
    WHERE a fit runs must not bend its math). Report-only `@RESULT` lane in
    bench.py until its trajectory starts (PR-10 per-lane gating)."""
    import threading

    from spark_rapids_ml_tpu import telemetry
    from spark_rapids_ml_tpu.models.clustering import KMeans
    from spark_rapids_ml_tpu.parallel import get_mesh
    from spark_rapids_ml_tpu.scheduler import FitScheduler, reset_global_ledger
    from spark_rapids_ml_tpu.scheduler.ledger import global_ledger

    telemetry.enable()
    rng = np.random.default_rng(seed)
    df = {"features": rng.standard_normal((n_rows, n_cols), dtype=np.float32)}
    pool = int(get_mesh().devices.size)
    width = max(1, pool // 2)

    def mk():
        est = KMeans(k=k, maxIter=max_iter, tol=0.0, seed=7)
        est.num_workers = width
        return est

    def phase(max_concurrent: int):
        reset_global_ledger()
        sched = FitScheduler(chip_placement=True, max_concurrent=max_concurrent)
        samples: List = []
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                samples.append(
                    (time.perf_counter(), len(global_ledger().occupied_chips()))
                )
                stop.wait(poll_interval_s)

        sampler = threading.Thread(target=poll, daemon=True)
        t0 = time.perf_counter()
        sampler.start()
        try:
            jobs = [sched.submit(mk(), df, tenant=f"t{i}") for i in range(2)]
            models = [j.result(timeout=600) for j in jobs]
        finally:
            stop.set()
            sampler.join(5.0)
            sched.shutdown(wait=True, timeout=60)
        wall = time.perf_counter() - t0
        occ = _occupancy_integral(samples, t0 + wall)
        return wall, occ, models

    # warm the compile cache outside the timed phases: both placements run
    # the same `width`-device program shapes, so neither phase should pay
    # compilation (whichever runs first otherwise eats the whole compile)
    mk().fit(df)

    wall_c, occ_c, models_c = phase(max_concurrent=2)
    wall_s, occ_s, models_s = phase(max_concurrent=1)

    # placement bit-identity: disjoint-window concurrent fits vs the
    # time-sliced whole-queue fits of the same estimator/data/seed
    ref = models_s[0].cluster_centers_
    max_abs_diff = max(
        float(np.max(np.abs(np.asarray(m.cluster_centers_) - np.asarray(ref))))
        for m in (models_c + models_s)
    )
    rows_total = float(2 * n_rows)
    rps_c = rows_total / wall_c if wall_c else 0.0
    rps_s = rows_total / wall_s if wall_s else 0.0
    return {
        "pool_chips": float(pool),
        "job_width": float(width),
        "wall_concurrent_s": wall_c,
        "wall_sliced_s": wall_s,
        "rows_per_sec_concurrent": rps_c,
        "rows_per_sec_sliced": rps_s,
        "rows_per_sec_ratio": rps_c / rps_s if rps_s else 0.0,
        "avg_chips_concurrent": occ_c["avg_chips"],
        "avg_chips_sliced": occ_s["avg_chips"],
        "peak_chips_concurrent": occ_c["peak_chips"],
        "peak_chips_sliced": occ_s["peak_chips"],
        "chip_seconds_concurrent": occ_c["chip_seconds"],
        "chip_seconds_sliced": occ_s["chip_seconds"],
        "occupancy_ratio": (
            occ_c["avg_chips"] / occ_s["avg_chips"] if occ_s["avg_chips"] else 0.0
        ),
        "max_abs_diff": max_abs_diff,
    }


class BenchmarkScheduler(BenchmarkBase):
    name = "scheduler"
    extra_args = {
        "tenants": (int, 4, "tenant count (even: big batch jobs; odd: small bursts)"),
        "small_rows": (int, 2000, "rows per small high-priority job"),
        "maxIter": (int, 120, "big-job solver iterations"),
        "checkpoint_every": (int, 3, "preemption granularity (checkpoint cadence)"),
    }

    def gen_dataset(self, args, mesh) -> Dict[str, Any]:
        # data is generated inside run_scheduler_bench: each tenant's jobs
        # ingest independently — ingest contention is part of what the lane
        # measures
        return {}

    def run_once(self, args, data, mesh) -> Dict[str, float]:
        out = run_scheduler_bench(
            args.tenants, args.num_rows, args.num_cols,
            small_rows=args.small_rows, max_iter_big=args.maxIter,
            checkpoint_every=args.checkpoint_every, seed=args.seed,
        )
        data["counters"] = {
            k: v for k, v in out.items()
            if k not in ("fit", "per_tenant", "slo", "tenant_byte_seconds")
        }
        data["per_tenant"] = out.get("per_tenant", {})
        data["ops"] = {
            "slo": out.get("slo", {}),
            "tenant_byte_seconds": out.get("tenant_byte_seconds", {}),
        }
        return {"fit": out["fit"]}

    def quality(self, args, data) -> Dict[str, float]:
        # utilization + fairness + budget conformance: the lane's acceptance
        # numbers (over_budget_admissions must stay 0)
        return data.get("counters", {})


if __name__ == "__main__":
    BenchmarkScheduler().run()
