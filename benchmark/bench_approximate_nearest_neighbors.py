#
# Approximate kNN benchmark (reference bench_approximate_nearest_neighbors.py):
# IVF index build + probe search; quality = recall vs the exact result on the
# same queries (the reference reports the same recall curve).
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase, fetch
from .gen_data import gen_low_rank_device
from .utils import with_benchmark


class BenchmarkApproximateNearestNeighbors(BenchmarkBase):
    name = "approximate_nearest_neighbors"
    extra_args = {
        "k": (int, 64, "neighbors per query"),
        "num_queries": (int, 4096, "query rows"),
        "nlist": (int, 256, "IVF coarse lists"),
        "nprobe": (int, 16, "lists probed per query"),
        "algorithm": (str, "ivfflat", "ivfflat | ivfpq | cagra"),
        "graph_degree": (int, 64, "cagra: final graph degree"),
        "intermediate_graph_degree": (int, 128, "cagra: build-time degree"),
        "build_algo": (str, "ivf_pq", "cagra: ivf_pq | nn_descent"),
        "itopk": (int, 64, "cagra: retained search candidates"),
    }

    def gen_dataset(self, args, mesh):
        # device-resident datagen: the index builds consume x straight from
        # HBM (a 1 GB host array costs minutes of h2d through a slow tunnel);
        # only the small query block is fetched
        x, w = gen_low_rank_device(args.num_rows, args.num_cols, seed=args.seed)
        q = np.asarray(x[: args.num_queries])
        return {"x": x, "q": q, "w": w}

    def run_once(self, args, data, mesh):
        import jax

        from spark_rapids_ml_tpu.ops.knn import build_ivfflat, ivfflat_search

        build = lambda: build_ivfflat(data["x"], args.nlist, seed=args.seed)  # noqa: E731
        if args.algorithm == "ivfpq":
            from spark_rapids_ml_tpu.ops.knn import build_ivfpq, ivfpq_search

            build = lambda: build_ivfpq(data["x"], args.nlist, seed=args.seed)  # noqa: E731
        elif args.algorithm == "cagra":
            from spark_rapids_ml_tpu.ops.cagra import build_cagra

            build = lambda: build_cagra(  # noqa: E731
                data["x"], graph_degree=args.graph_degree,
                intermediate_graph_degree=args.intermediate_graph_degree,
                build_algo=args.build_algo, seed=args.seed,
            )

        build()  # warm the XLA programs outside the timers (like every bench)
        index, build_sec = with_benchmark(f"ann[{args.algorithm}] build", build)
        if args.algorithm != "cagra":  # cagra_search takes host queries
            Q = jax.device_put(data["q"])

        if args.algorithm == "ivfpq":
            from spark_rapids_ml_tpu.ops.knn import ivfpq_search

            def run():
                return ivfpq_search(
                    Q, index, k=args.k, n_probes=args.nprobe,
                )
        elif args.algorithm == "cagra":
            from spark_rapids_ml_tpu.ops.cagra import cagra_search

            # build_cagra returns a device-resident index, so nothing needs
            # hoisting: the timed search transfers only the query tiles
            def run():
                return cagra_search(
                    data["q"], index, k=args.k, itopk_size=args.itopk
                )[::-1]  # (idx, d2) -> (d2, idx) like the ivf searches
        else:
            cent = jax.device_put(index["centroids"].astype(np.float32))
            buck = jax.device_put(index["buckets"])
            bids = jax.device_put(index["bucket_ids"])

            def run():
                return ivfflat_search(
                    Q, cent, buck, bids, k=args.k, n_probes=args.nprobe,
                )

        fetch(run()[0])  # compile outside timing
        state = {}

        def timed():
            d, i = run()
            fetch(d)
            state["idx"] = np.asarray(i)
            return d

        _, sec = with_benchmark(f"ann[{args.algorithm}] search", timed)
        self._idx = state["idx"]
        self._search_sec = sec
        return {"build": build_sec, "search": sec, "fit": build_sec + sec}

    def quality(self, args, data):
        # recall@k vs brute-force exact on a query subsample
        import jax

        from spark_rapids_ml_tpu.ops.knn import exact_knn
        from spark_rapids_ml_tpu.parallel import get_mesh

        n_check = min(512, len(data["q"]))
        mesh1 = get_mesh(1)
        # x is already a device array (gen_dataset); never round-trip it
        _, exact_idx = exact_knn(
            data["x"], data["w"] > 0, jax.device_put(data["q"][:n_check]),
            mesh=mesh1, k=args.k,
        )
        exact_idx = np.asarray(exact_idx)
        hits = 0
        for i in range(n_check):
            hits += len(set(exact_idx[i]) & set(self._idx[i][self._idx[i] >= 0]))
        return {
            "recall": hits / (n_check * args.k),
            "qps": float(len(data["q"])) / max(self._search_sec, 1e-9),
        }


if __name__ == "__main__":
    BenchmarkApproximateNearestNeighbors().run()
