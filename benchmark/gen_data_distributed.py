#
# Partition-parallel dataset generation — the TPU-native rebuild of the
# reference's `gen_data_distributed.py` (1177 LoC: DataGenBase subclasses that
# generate each Spark partition independently inside `mapInPandas`, incl.
# `SparseRegressionDataGen`:581). No Spark here: a partition is a row range
# whose content is a PURE FUNCTION of (seed, kind, partition index), so any
# process — or any number of processes — can generate any partition and the
# bytes are identical. The multi-process driver is a plain multiprocessing
# pool over partition blocks (each worker writes its own part files, the
# reference's one-task-per-partition write), and the streaming consumers
# (`iter_partitions`, `partitions_to_ell`) hand partitions to ingest one at a
# time so the full dataset is never materialized driver-side.
#
# Determinism contract (tested in tests/test_gen_distributed.py):
#   gen.gen_partition(i) depends ONLY on the generator's params + i
#   => generate()/write() output is bit-identical for any n_processes.
#
from __future__ import annotations

import argparse
import glob
import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from .gen_data import random_csr

# Stable per-kind seed tags: keep each generator's RNG streams disjoint even
# for the same (seed, partition) pair.
_KIND_TAGS = {
    "blobs": 1,
    "low_rank": 2,
    "regression": 3,
    "classification": 4,
    "sparse_regression": 5,
}
_SHARED_STREAM = 0  # per-run shared state (coef/centers/V)
_PARTITION_STREAM = 1  # per-partition row content


class DataGenBase:
    """One dataset kind, generated partition-by-partition.

    Subclasses define `kind`, optional extra params (captured in `self.params`),
    `_shared(rng)` (per-run state every partition needs: coefficient vectors,
    cluster centers, the low-rank factor) and `gen_partition(i)`.
    """

    kind: str = ""
    sparse: bool = False

    def __init__(
        self,
        n_rows: int,
        n_cols: int,
        *,
        seed: int = 0,
        n_partitions: Optional[int] = None,
        **params,
    ) -> None:
        if n_rows <= 0 or n_cols <= 0:
            raise ValueError(f"invalid shape {n_rows}x{n_cols}")
        self.n_rows = int(n_rows)
        self.n_cols = int(n_cols)
        self.seed = int(seed)
        if n_partitions is None:
            # ~1M rows per partition by default (the reference's Spark default
            # parallelism analog), at least one per generator
            n_partitions = max(1, -(-self.n_rows // 1_000_000))
        self.n_partitions = max(1, min(int(n_partitions), self.n_rows))
        self.params = params
        self._shared_cache = None

    # -- determinism plumbing ---------------------------------------------
    def _rng(self, stream: int, part_idx: int = 0) -> np.random.Generator:
        return np.random.default_rng(
            np.random.SeedSequence(
                [self.seed, _KIND_TAGS[self.kind], int(stream), int(part_idx)]
            )
        )

    def partition_bounds(self, i: int) -> Tuple[int, int]:
        """Row range [lo, hi) of partition `i`: even split, remainder spread
        over the first partitions (PartitionDescriptor convention)."""
        base, rem = divmod(self.n_rows, self.n_partitions)
        lo = i * base + min(i, rem)
        return lo, lo + base + (1 if i < rem else 0)

    @property
    def shared(self):
        """Per-run state derived from the seed alone — recomputed identically
        in every worker process (no pickling/broadcast needed)."""
        if self._shared_cache is None:
            self._shared_cache = self._shared(self._rng(_SHARED_STREAM))
        return self._shared_cache

    def _shared(self, rng) -> Dict[str, np.ndarray]:
        return {}

    # -- subclass surface --------------------------------------------------
    def gen_partition(self, i: int):
        """Generate partition `i`: (X [rows, d] f32 | CSR, y [rows] | None)."""
        raise NotImplementedError

    # -- drivers -----------------------------------------------------------
    def iter_partitions(self) -> Iterator[Tuple[int, Tuple]]:
        """Stream (i, (X, y)) one partition at a time — the ingest-facing API:
        consumers see one partition of host memory, never the whole set."""
        for i in range(self.n_partitions):
            yield i, self.gen_partition(i)

    def generate(self) -> Tuple:
        """Materialize the full dataset (small shapes / tests). Bit-identical
        to concatenating any multi-process run's partition outputs."""
        xs, ys = [], []
        for _, (x, y) in self.iter_partitions():
            xs.append(x)
            ys.append(y)
        if self.sparse:
            import scipy.sparse as sp

            X = sp.vstack(xs, format="csr") if len(xs) > 1 else xs[0]
        else:
            X = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
        y = None if ys[0] is None else np.concatenate(ys)
        return X, y

    def write_partition(self, i: int, out_dir: str) -> str:
        """Write partition `i` as its own part file (parquet for dense, npz
        CSR triple for sparse) — the per-task write of the reference's
        partition-parallel generators."""
        x, y = self.gen_partition(i)
        if self.sparse:
            path = os.path.join(out_dir, f"part-{i:05d}.npz")
            np.savez(
                path, data=x.data, indices=x.indices, indptr=x.indptr,
                shape=np.asarray(x.shape), **({} if y is None else {"y": y}),
            )
        else:
            from .dataset_io import write_parquet_part

            path = os.path.join(out_dir, f"part-{i:05d}.parquet")
            write_parquet_part(path, x, y)
        return path

    def write(self, out_dir: str, n_processes: int = 1) -> int:
        """Write every partition under `out_dir`, `n_processes`-parallel.

        Output is bit-identical for any `n_processes` (each part file is a
        pure function of params + partition index). Returns files written.
        """
        os.makedirs(out_dir, exist_ok=True)
        n_processes = max(1, min(int(n_processes), self.n_partitions))
        if n_processes == 1:
            for i in range(self.n_partitions):
                self.write_partition(i, out_dir)
            return self.n_partitions
        import multiprocessing as mp

        spec = self.to_spec()
        blocks = [
            list(range(r, self.n_partitions, n_processes)) for r in range(n_processes)
        ]
        # spawn, not fork: the calling process usually has a live multithreaded
        # JAX runtime, and forking it is a documented deadlock hazard. Workers
        # only import numpy/pyarrow (every jax import in this module is lazy),
        # so spawn startup is cheap.
        ctx = mp.get_context("spawn")
        with ctx.Pool(n_processes) as pool:
            pool.map(
                _write_partition_block,
                [(spec, block, out_dir) for block in blocks if block],
            )
        return self.n_partitions

    # -- multiprocessing (re)construction ---------------------------------
    def to_spec(self) -> Dict:
        return {
            "kind": self.kind,
            "n_rows": self.n_rows,
            "n_cols": self.n_cols,
            "seed": self.seed,
            "n_partitions": self.n_partitions,
            "params": dict(self.params),
        }

    @staticmethod
    def from_spec(spec: Dict) -> "DataGenBase":
        cls = GENERATORS[spec["kind"]]
        return cls(
            spec["n_rows"], spec["n_cols"], seed=spec["seed"],
            n_partitions=spec["n_partitions"], **spec["params"],
        )


def _write_partition_block(args) -> None:
    """Pool worker: rebuild the generator from its spec and write a block of
    partitions (module-level for picklability)."""
    spec, part_ids, out_dir = args
    gen = DataGenBase.from_spec(spec)
    for i in part_ids:
        gen.write_partition(i, out_dir)


class LowRankMatrixDataGen(DataGenBase):
    """Low-rank + noise features (reference LowRankMatrixDataGen analog):
    shared factor V [rank, d]; each partition draws its own U rows."""

    kind = "low_rank"

    def _shared(self, rng):
        rank = int(self.params.get("rank", 16))
        return {"V": rng.normal(size=(rank, self.n_cols)).astype(np.float32)}

    def gen_partition(self, i: int):
        lo, hi = self.partition_bounds(i)
        rng = self._rng(_PARTITION_STREAM, i)
        V = self.shared["V"]
        noise = float(self.params.get("noise", 0.1))
        U = rng.normal(size=(hi - lo, V.shape[0])).astype(np.float32)
        X = U @ V + noise * rng.normal(size=(hi - lo, self.n_cols)).astype(np.float32)
        return X, None


class RegressionDataGen(LowRankMatrixDataGen):
    """Low-rank features + shared linear target (reference RegressionDataGen)."""

    kind = "regression"

    def _shared(self, rng):
        state = super()._shared(rng)
        state["coef"] = (
            rng.normal(size=self.n_cols) / np.sqrt(self.n_cols)
        ).astype(np.float32)
        return state

    def gen_partition(self, i: int):
        X, _ = super().gen_partition(i)
        rng = self._rng(_PARTITION_STREAM + 1, i)  # label noise stream
        noise = float(self.params.get("noise", 0.1))
        y = X @ self.shared["coef"] + noise * rng.normal(size=len(X)).astype(np.float32)
        return X, y.astype(np.float32)


class ClassificationDataGen(LowRankMatrixDataGen):
    """Low-rank features + linear-margin labels (reference ClassificationDataGen)."""

    kind = "classification"

    def _shared(self, rng):
        state = super()._shared(rng)
        n_classes = int(self.params.get("n_classes", 2))
        state["coef"] = (
            rng.normal(size=(self.n_cols, max(1, n_classes - 1))) / np.sqrt(self.n_cols)
        ).astype(np.float32)
        return state

    def gen_partition(self, i: int):
        X, _ = super().gen_partition(i)
        rng = self._rng(_PARTITION_STREAM + 1, i)
        margins = X @ self.shared["coef"]
        z = np.concatenate(
            [np.zeros((len(X), 1), np.float32),
             margins + 0.5 * rng.normal(size=margins.shape).astype(np.float32)],
            axis=1,
        )
        return X, np.argmax(z, axis=1).astype(np.int64)


class BlobsDataGen(DataGenBase):
    """Gaussian blobs around shared centers (reference BlobsDataGen)."""

    kind = "blobs"

    def _shared(self, rng):
        centers = int(self.params.get("centers", 10))
        return {"C": 10.0 * rng.normal(size=(centers, self.n_cols)).astype(np.float32)}

    def gen_partition(self, i: int):
        lo, hi = self.partition_bounds(i)
        rng = self._rng(_PARTITION_STREAM, i)
        C = self.shared["C"]
        std = float(self.params.get("cluster_std", 1.0))
        assign = rng.integers(0, len(C), size=hi - lo)
        X = C[assign] + std * rng.normal(size=(hi - lo, self.n_cols)).astype(np.float32)
        return X.astype(np.float32), assign.astype(np.int64)


class SparseRegressionDataGen(DataGenBase):
    """Sparse CSR regression partitions (reference SparseRegressionDataGen:581):
    O(nnz) per-partition CSR via the shared `random_csr` generator, shared
    sparse-support coefficient, per-partition label noise. The 1e7 x 2200
    scale shape generates partition-parallel with ~nnz/partition peak memory.
    """

    kind = "sparse_regression"
    sparse = True

    def _shared(self, rng):
        # coef_support: fraction of columns carrying signal. The default
        # (1/40, gen_data.gen_sparse_regression_host parity) leaves most
        # ultra-sparse rows signal-free; classification consumers that score
        # accuracy want coef_support=1.0 (the tests/test_large_sparse.py
        # design: dense support, every nonzero row carries signal).
        coef = np.zeros(self.n_cols, dtype=np.float32)
        support = float(self.params.get("coef_support", 1.0 / 40.0))
        scale = float(self.params.get("coef_scale", 1.0))
        k = max(1, int(self.n_cols * support))
        coef[:k] = scale * rng.normal(size=k)
        return {"coef": coef}

    def gen_partition(self, i: int):
        lo, hi = self.partition_bounds(i)
        rng = self._rng(_PARTITION_STREAM, i)
        density = float(self.params.get("density", 0.001))
        noise = float(self.params.get("noise", 0.01))
        x = random_csr(rng, hi - lo, self.n_cols, density)
        y = np.asarray(x @ self.shared["coef"]).ravel()
        y = y + noise * rng.normal(size=hi - lo).astype(np.float32)
        return x, y.astype(np.float32)


GENERATORS = {
    "blobs": BlobsDataGen,
    "low_rank": LowRankMatrixDataGen,
    "regression": RegressionDataGen,
    "classification": ClassificationDataGen,
    "sparse_regression": SparseRegressionDataGen,
}


# ---------------------------------------------------------------------------
# streaming consumers
# ---------------------------------------------------------------------------


def partitions_to_ell(gen: DataGenBase, dtype=np.float32):
    """Stream a sparse generator's partitions straight into padded-ELL arrays.

    Two passes over the (pure, replayable) partition stream: pass 1 counts
    rows and finds the global widest-row k_max without keeping anything;
    pass 2 converts each partition and writes it into the preallocated
    output. Peak host memory is the ELL output + ONE partition of CSR+ELL —
    the full-dataset CSR is never materialized, and no second full-ELL
    accumulation exists (regenerating a partition costs seconds at the
    1e7x2200 scale shape; holding a second ELL copy costs a gigabyte).
    Returns ``(indices [n, k_max] int32, values [n, k_max], k_max, y)``.
    """
    from spark_rapids_ml_tpu.ops.sparse import csr_to_ell

    n, k_max, have_y = 0, 1, False
    for _, (x, y) in gen.iter_partitions():
        n += x.shape[0]
        if x.nnz:
            k_max = max(k_max, int(np.diff(x.indptr).max()))
        have_y = y is not None
    indices = np.zeros((n, k_max), np.int32)
    values = np.zeros((n, k_max), dtype)
    y_out = np.empty((n,), np.float32) if have_y else None
    lo = 0
    for _, (x, y) in gen.iter_partitions():
        idx, val, _ = csr_to_ell(x, k_max=k_max, dtype=dtype)
        hi = lo + idx.shape[0]
        indices[lo:hi] = idx
        values[lo:hi] = val
        if y_out is not None:
            y_out[lo:hi] = y
        lo = hi
    return indices, values, k_max, y_out


def sparse_classification_ell(n_rows: int, n_cols: int, density: float, seed: int, mesh):
    """The certified sparse classification lane shared by `bench.py` and
    `bench_logistic_regression`: dense-support scale-4 coefficient (the
    tests/test_large_sparse.py design — every nonzero row carries signal,
    accuracy ceiling ~0.94 at 0.1% density), streamed partition-by-partition
    into padded ELL, target binarized at 0, ELL tensors + labels row-sharded
    on `mesh` with ONE shared weight vector (ELL zero-padding rows carry
    w == 0 and index 0 / value 0, both neutral).

    Returns {"values", "indices", "y", "w", "k_max"} device-resident.
    """
    from spark_rapids_ml_tpu.parallel import make_global_rows, place_rows

    gen = SparseRegressionDataGen(
        n_rows, n_cols, seed=seed, density=density,
        coef_support=1.0, coef_scale=4.0, noise=0.25,
    )
    indices, values, k_max, y = partitions_to_ell(gen)
    y_idx = (y > 0).astype(np.int32)
    Xv, w, _ = make_global_rows(mesh, values)
    Xi = place_rows(mesh, indices)
    yd = place_rows(mesh, y_idx)
    return {"values": Xv, "indices": Xi, "y": yd, "w": w, "k_max": k_max}


def read_sparse_npz_dataset(path: str):
    """Load a sparse part-*.npz directory back into one CSR (+ y). Streaming
    consumers should prefer `iter_sparse_npz_dataset`."""
    import scipy.sparse as sp

    xs, ys = [], []
    for x, y in iter_sparse_npz_dataset(path):
        xs.append(x)
        ys.append(y)
    X = sp.vstack(xs, format="csr") if len(xs) > 1 else xs[0]
    y = None if ys[0] is None else np.concatenate(ys)
    return X, y


def iter_sparse_npz_dataset(path: str):
    """Yield (CSR, y|None) per part file, in partition order."""
    import scipy.sparse as sp

    files = sorted(glob.glob(os.path.join(path, "part-*.npz")))
    if not files:
        raise FileNotFoundError(f"no part-*.npz files under {path}")
    for fp in files:
        with np.load(fp) as z:
            x = sp.csr_matrix(
                (z["data"], z["indices"], z["indptr"]), shape=tuple(z["shape"])
            )
            yield x, (z["y"] if "y" in z.files else None)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="partition-parallel dataset generator (reference "
        "gen_data_distributed.py analog)"
    )
    p.add_argument("kind", choices=sorted(GENERATORS))
    p.add_argument("--num_rows", type=int, default=1_000_000)
    p.add_argument("--num_cols", type=int, default=300)
    p.add_argument("--n_classes", type=int, default=2)
    p.add_argument("--centers", type=int, default=10)
    p.add_argument("--density", type=float, default=0.001)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--n_partitions", type=int, default=0, help="0 = auto (~1M rows each)")
    p.add_argument("--n_processes", type=int, default=1, help="parallel writer processes")
    p.add_argument("--output", required=True, help="output directory")
    args = p.parse_args(argv)

    extra: Dict = {}
    if args.kind == "classification":
        extra["n_classes"] = args.n_classes
    elif args.kind == "blobs":
        extra["centers"] = args.centers
    elif args.kind == "sparse_regression":
        extra["density"] = args.density
    gen = GENERATORS[args.kind](
        args.num_rows, args.num_cols, seed=args.seed,
        n_partitions=args.n_partitions or None, **extra,
    )
    n = gen.write(args.output, n_processes=args.n_processes)
    print(f"wrote {n} part files under {args.output}")


if __name__ == "__main__":
    main()
