#
# Perf-regression gate over the BENCH trajectory (docs/observability.md
# "Regression gate").
#
# Every round ships a BENCH_r<NN>.json artifact (bench.py's one-line JSON,
# wrapped by the round driver under a "parsed" key). The trajectory was
# collected but never CHECKED — a slowdown ships silently, and a cache
# regression that doubles ingest work can hide entirely inside unchanged
# wall time. This gate closes both holes:
#
#   * WALL-TIME LANE — the headline throughput geomean of the newest complete
#     run must stay within `--min-ratio` (default 0.8) of the trajectory
#     reference (median of prior complete runs WITH THE SAME lane
#     composition — a round that adds lanes to the geomean starts a new
#     geomean trajectory instead of being gated on the mix).
#   * PER-ALGO WALL LANES — records embedding per-lane values ("lanes",
#     added when kmeans_scale/knn joined the geomean) are also gated lane by
#     lane against each lane's OWN history; the first artifact carrying a
#     lane is that lane's trajectory start (skipped, never a false fail
#     against rounds that predate it).
#   * COUNTER LANES — telemetry counters embedded in the BENCH snapshot
#     (ingest/layout/placement/solve counts) are lower-is-better efficiency
#     invariants: the newest run failing `current <= tolerance * reference`
#     fails the lane even when wall time looks fine.
#   * LATENCY LANES — records embedding `latency_lanes` (serving p50/p99 ms,
#     added with the persistent serving plane) gate each value as a
#     LOWER-IS-BETTER lane against the median of its own trajectory at
#     `--max-latency-ratio` (default 1.5): a p99 blowup fails even when the
#     throughput lanes hide it. Same trajectory-start rule as the per-algo
#     wall lanes — the first artifact carrying a latency lane is skipped.
#
# Infra honesty: a run the tunnel killed (value 0.0 / INCOMPLETE) carries no
# perf signal — those runs are excluded from the reference and, when the
# NEWEST run is incomplete, the verdict is "no-data" (exit 0): an outage is
# the watchdog's problem, not a perf regression. A lane with no reference
# data reports "skipped".
#
# Output: one machine-readable JSON verdict on stdout
#   {"verdict": "pass"|"fail"|"no-data", "lanes": [...], ...}
# Exit code: 1 on "fail" unless --report-only (the ci/ lane runs report-only
# until the trajectory carries enough telemetry-bearing rounds to be strict).
#
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys
from typing import Any, Dict, List, Optional, Tuple

# Counter lanes: (counter name, lower-is-better tolerance ratio). Chosen for
# work-amount invariants the multi-fit engine and ingest cache guarantee —
# the "cache regression doubles ingests" class. Tolerances are loose enough
# to absorb lane additions (a new bench lane adds real work) but a 2x blowup
# always fails.
DEFAULT_COUNTER_LANES: List[Tuple[str, float]] = [
    ("ingest.rows", 1.5),
    ("ingest.datasets", 1.5),
    ("ingest.chunks", 1.5),
    ("placement.device_put_calls", 1.5),
    ("sparse.csr_to_ell_calls", 1.5),
    ("fit.solves_sequential", 1.5),
    ("rendezvous.rounds", 1.5),
]


def load_bench_record(path: str) -> Dict[str, Any]:
    """A BENCH artifact's inner record: the round driver wraps bench.py's
    stdout line under "parsed"; accept the bare record (or a JSONL file whose
    last parseable line is the record) too, so fixtures and ad-hoc runs work."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
        for line in reversed(text.splitlines()):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
                break
            except ValueError:
                continue
        if doc is None:
            return {}
    if not isinstance(doc, dict):
        return {}
    inner = doc.get("parsed")
    if isinstance(inner, dict) and "value" in inner:
        return inner
    return doc if "value" in doc else {}


def is_complete(rec: Dict[str, Any]) -> bool:
    """A run carries perf signal only when it finished: positive value and
    not flagged INCOMPLETE (a tunnel outage's degraded emission)."""
    try:
        value = float(rec.get("value") or 0.0)
    except (TypeError, ValueError):
        return False
    return value > 0.0 and "INCOMPLETE" not in str(rec.get("unit", ""))


def _counters(rec: Dict[str, Any]) -> Dict[str, float]:
    tel = rec.get("telemetry")
    if isinstance(tel, dict) and isinstance(tel.get("counters"), dict):
        return {k: float(v) for k, v in tel["counters"].items()
                if isinstance(v, (int, float))}
    return {}


def _lanes(rec: Dict[str, Any]) -> Dict[str, float]:
    """Per-algo throughput values embedded in the record ("lanes", added
    when kmeans_scale/knn entered the geomean). Empty for older artifacts —
    which is exactly how the gate knows a lane's trajectory starts here."""
    lanes = rec.get("lanes")
    if isinstance(lanes, dict):
        return {k: float(v) for k, v in lanes.items()
                if isinstance(v, (int, float)) and float(v) > 0.0}
    return {}


def _latency_lanes(rec: Dict[str, Any]) -> Dict[str, float]:
    """Lower-is-better latency values embedded in the record
    ("latency_lanes", added when the serving lane joined — p50/p99 ms).
    Empty for older artifacts, which is how the gate knows a latency lane's
    trajectory starts here."""
    lanes = rec.get("latency_lanes")
    if isinstance(lanes, dict):
        return {k: float(v) for k, v in lanes.items()
                if isinstance(v, (int, float)) and float(v) > 0.0}
    return {}


def _lower_better_lane(
    name: str, kind: str, cur: float, ref: Optional[float], tolerance: float,
    skip_note: str = "counter absent on one side",
) -> Dict[str, Any]:
    """One lower-is-better lane verdict — the counter-lane machinery,
    generalized so latency lanes gate through the exact same rule
    (`current <= tolerance * reference`)."""
    if cur is None or ref is None or ref <= 0:
        return {
            "lane": name, "kind": kind, "status": "skipped",
            "current": cur, "reference": ref, "note": skip_note,
        }
    ratio = cur / ref
    return {
        "lane": name,
        "kind": kind,
        "direction": "lower-better",
        "current": cur,
        "reference": ref,
        "ratio": round(ratio, 4),
        "threshold": tolerance,
        "status": "pass" if ratio <= tolerance else "fail",
    }


def _geomean_lanes(rec: Dict[str, Any]) -> frozenset:
    """The lane names whose values entered the record's headline geomean —
    the COMPARABILITY key for the wall lane. bench.py embeds it explicitly
    ("geomean_lanes"); records without it (incl. the pre-lanes era) fall
    back to every embedded lane, and lane-less legacy records compare as
    the empty set (i.e. with each other), preserving pre-lane behavior.
    Keying on the embedded lane dict alone would let an OPTIONAL extra lane
    (BENCH_SPARSE/BENCH_OOCORE toggled on for one round) silently skip the
    headline gate even though the geomean composition never changed."""
    gl = rec.get("geomean_lanes")
    if isinstance(gl, (list, tuple)):
        return frozenset(str(x) for x in gl)
    return frozenset(_lanes(rec).keys())


def discover_trajectory(root: str, pattern: str = "BENCH_r*.json") -> List[str]:
    """BENCH artifacts in round order (numeric suffix sort, not lexical —
    r2 < r10)."""
    def round_key(p: str):
        m = re.search(r"_r(\d+)", os.path.basename(p))
        return (int(m.group(1)) if m else -1, p)

    return sorted(glob.glob(os.path.join(root, pattern)), key=round_key)


def run_gate(
    current: Dict[str, Any],
    history: List[Dict[str, Any]],
    *,
    min_ratio: float = 0.8,
    counter_lanes: Optional[List[Tuple[str, float]]] = None,
    max_latency_ratio: float = 1.5,
) -> Dict[str, Any]:
    """Compare `current` against the completed runs in `history`. Pure
    function of its inputs (the CLI wires files in); returns the verdict
    dict described in the module header."""
    if counter_lanes is None:
        counter_lanes = DEFAULT_COUNTER_LANES
    lanes: List[Dict[str, Any]] = []
    complete_hist = [r for r in history if is_complete(r)]

    if not is_complete(current):
        return {
            "verdict": "no-data",
            "reason": "newest run is incomplete (infra outage, not a perf signal)",
            "current_value": current.get("value"),
            "reference_runs": len(complete_hist),
            "lanes": [],
        }

    # -- wall-time lane: throughput geomean, higher is better --------------
    # The geomean is only comparable between runs with the SAME lane
    # composition: when a round ADDS lanes to the headline (kmeans_scale/knn
    # joining with the tiled distance core), its geomean is a different
    # statistic, and gating it against the old composition's median would
    # false-fail (or false-pass) on the mix, not on performance. Runs that
    # predate the "lanes" embed have no composition info — treated as
    # matching only other lane-less runs.
    cur_value = float(current["value"])
    cur_lanes = _lanes(current)
    comparable = [
        r for r in complete_hist
        if _geomean_lanes(r) == _geomean_lanes(current)
    ]
    if comparable:
        ref_value = statistics.median(float(r["value"]) for r in comparable)
        ratio = cur_value / ref_value if ref_value > 0 else float("inf")
        lanes.append({
            "lane": "throughput_geomean",
            "kind": "wall",
            "direction": "higher-better",
            "current": cur_value,
            "reference": ref_value,
            "ratio": round(ratio, 4),
            "threshold": min_ratio,
            "status": "pass" if ratio >= min_ratio else "fail",
        })
    elif complete_hist:
        lanes.append({
            "lane": "throughput_geomean",
            "kind": "wall",
            "current": cur_value,
            "reference": None,
            "status": "skipped",
            "note": "lane composition changed — this artifact starts the new "
                    "geomean trajectory; the per-lane gates carry the signal",
        })
    else:
        lanes.append({
            "lane": "throughput_geomean",
            "kind": "wall",
            "current": cur_value,
            "reference": None,
            "status": "skipped",
            "note": "no complete historical run to compare against",
        })

    # -- per-algo wall lanes: each lane gates against ITS OWN trajectory ---
    # A lane absent from every historical run starts its trajectory at the
    # current artifact (status "skipped", never a false fail against rounds
    # that predate the lane — e.g. kmeans_scale/knn joining at round N).
    for lane_name in sorted(cur_lanes):
        refs = [
            _lanes(r)[lane_name] for r in complete_hist if lane_name in _lanes(r)
        ]
        if not refs:
            lanes.append({
                "lane": f"lane:{lane_name}",
                "kind": "wall",
                "current": cur_lanes[lane_name],
                "reference": None,
                "status": "skipped",
                "note": "trajectory start: no historical run carries this lane",
            })
            continue
        ref_value = statistics.median(refs)
        ratio = cur_lanes[lane_name] / ref_value if ref_value > 0 else float("inf")
        lanes.append({
            "lane": f"lane:{lane_name}",
            "kind": "wall",
            "direction": "higher-better",
            "current": cur_lanes[lane_name],
            "reference": ref_value,
            "ratio": round(ratio, 4),
            "threshold": min_ratio,
            "status": "pass" if ratio >= min_ratio else "fail",
        })

    # -- latency lanes: p50/p99 upper bounds, lower is better --------------
    # Same machinery as the counter lanes (`current <= tolerance * ref`),
    # with the per-algo wall lanes' trajectory rule: each latency value
    # gates against the median of ITS OWN history, and the first artifact
    # carrying a lane starts that lane's trajectory (skipped).
    cur_lat = _latency_lanes(current)
    for lane_name in sorted(cur_lat):
        refs = [
            _latency_lanes(r)[lane_name]
            for r in complete_hist
            if lane_name in _latency_lanes(r)
        ]
        lanes.append(_lower_better_lane(
            f"latency:{lane_name}", "latency", cur_lat[lane_name],
            statistics.median(refs) if refs else None, max_latency_ratio,
            skip_note="trajectory start: no historical run carries this lane",
        ))

    # -- counter lanes: work-amount invariants, lower is better ------------
    # Reference = the NEWEST complete run that embedded a telemetry
    # snapshot, taken as one coherent set. Never assembled per-key across
    # runs: a counter that stopped being emitted two rounds ago would then
    # gate today's run against a stale reference while the wall lane
    # compares against the current median.
    cur_counters = _counters(current)
    ref_counters: Dict[str, float] = {}
    for r in reversed(complete_hist):
        if _counters(r):
            ref_counters = _counters(r)
            break
    for name, tolerance in counter_lanes:
        lanes.append(_lower_better_lane(
            name, "counter", cur_counters.get(name), ref_counters.get(name),
            tolerance,
        ))

    checked = [ln for ln in lanes if ln["status"] in ("pass", "fail")]
    failed = [ln for ln in lanes if ln["status"] == "fail"]
    verdict = "fail" if failed else ("pass" if checked else "no-data")
    return {
        "verdict": verdict,
        "current_value": cur_value,
        "reference_runs": len(complete_hist),
        "failed_lanes": [ln["lane"] for ln in failed],
        "lanes": lanes,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=None,
                    help="repo root holding BENCH_r*.json (default: this repo)")
    ap.add_argument("--pattern", default="BENCH_r*.json",
                    help="glob for trajectory artifacts under --root")
    ap.add_argument("--current", default=None,
                    help="explicit newest artifact (default: highest round in the trajectory)")
    ap.add_argument("--min-ratio", type=float, default=0.8,
                    help="wall lane: fail when current/reference drops below this")
    ap.add_argument("--counter-tolerance", type=float, default=None,
                    help="override every counter lane's tolerance ratio")
    ap.add_argument("--max-latency-ratio", type=float, default=1.5,
                    help="latency lanes: fail when current/reference exceeds this")
    ap.add_argument("--report-only", action="store_true",
                    help="always exit 0 (CI report lane); the verdict JSON still says fail")
    ap.add_argument("--out", default=None, help="also write the verdict JSON here")
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = discover_trajectory(root, args.pattern)
    if args.current:
        current_path = args.current
        history_paths = [p for p in paths if os.path.abspath(p) != os.path.abspath(current_path)]
    elif paths:
        current_path, history_paths = paths[-1], paths[:-1]
    else:
        verdict = {"verdict": "no-data", "reason": f"no artifacts match {args.pattern} under {root}",
                   "lanes": []}
        print(json.dumps(verdict, indent=2))
        return 0

    lanes = DEFAULT_COUNTER_LANES
    if args.counter_tolerance is not None:
        lanes = [(name, args.counter_tolerance) for name, _ in lanes]
    verdict = run_gate(
        load_bench_record(current_path),
        [load_bench_record(p) for p in history_paths],
        min_ratio=args.min_ratio,
        counter_lanes=lanes,
        max_latency_ratio=args.max_latency_ratio,
    )
    verdict["current_artifact"] = os.path.basename(current_path)
    verdict["history_artifacts"] = [os.path.basename(p) for p in history_paths]
    out = json.dumps(verdict, indent=2)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if verdict["verdict"] == "fail" and not args.report_only:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
