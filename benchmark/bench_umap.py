#
# UMAP benchmark (reference bench_umap.py): fit + transform timing; quality =
# trustworthiness of the embedding on a subsample (the reference's score).
#
from __future__ import annotations

import numpy as np
import pandas as pd

from .base import BenchmarkBase
from .gen_data import gen_blobs_host
from .utils import with_benchmark


class BenchmarkUMAP(BenchmarkBase):
    name = "umap"
    extra_args = {
        "n_neighbors": (int, 15, "kNN graph degree"),
        "n_epochs": (int, 200, "SGD layout epochs"),
        "centers": (int, 10, "generating blob count"),
    }

    def gen_dataset(self, args, mesh):
        x, y = gen_blobs_host(args.num_rows, args.num_cols, centers=args.centers, seed=args.seed)
        return {"x": x, "df": pd.DataFrame({"features": list(x.astype(np.float64))})}

    def run_once(self, args, data, mesh):
        from spark_rapids_ml_tpu.models.umap import UMAP

        est = UMAP(
            n_neighbors=args.n_neighbors, n_epochs=args.n_epochs, random_state=42
        ).setFeaturesCol("features")
        # warm the XLA programs outside the timers, like every other bench
        # (fit at this shape cold-compiles ~2 min of kNN/SGD programs)
        warm = est.fit(data["df"])
        warm.transform(data["df"])
        model, fit_sec = with_benchmark("umap fit", lambda: est.fit(data["df"]))
        _, tr_sec = with_benchmark("umap transform", lambda: model.transform(data["df"]))
        self._model = model
        return {"fit": fit_sec, "transform": tr_sec}

    def quality(self, args, data):
        from sklearn.manifold import trustworthiness

        n = min(2000, len(data["x"]))
        emb = np.asarray(self._model.embedding_)[:n]
        return {
            "trustworthiness": float(
                trustworthiness(data["x"][:n], emb, n_neighbors=args.n_neighbors)
            )
        }


if __name__ == "__main__":
    BenchmarkUMAP().run()
