# Benchmark suite for spark_rapids_ml_tpu — the TPU-native re-build of the
# reference's python/benchmark tree (runner + per-algo benches + data gen;
# reference benchmark_runner.py:38-50, benchmark/base.py:241-270).
