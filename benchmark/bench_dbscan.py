#
# DBSCAN benchmark (reference bench_dbscan.py): replicated-data rank-sliced N²
# clustering; quality = adjusted Rand index vs the generating blob labels.
# The N² memory profile caps practical row counts well below the dense-solver
# protocol scale — same in the reference (its DBSCAN bench uses smaller sets).
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase
from .gen_data import gen_blobs_host
from .utils import with_benchmark


class BenchmarkDBSCAN(BenchmarkBase):
    name = "dbscan"
    extra_args = {
        "eps": (float, 0.0, "neighborhood radius (0 = auto 1.5*sqrt(num_cols), "
                            "matching the blob generator's unit-variance noise)"),
        "min_samples": (int, 5, "core-point threshold"),
        "centers": (int, 20, "generating blob count"),
        "max_mbytes_per_batch": (int, 512, "distance-tile memory budget"),
    }

    def gen_dataset(self, args, mesh):
        if not args.eps:
            # in d dims the typical in-cluster pair distance is ~sqrt(2d)·std;
            # a fixed low-dim default marks everything noise at d=64
            args.eps = 1.5 * float(np.sqrt(args.num_cols))
        x, y = gen_blobs_host(args.num_rows, args.num_cols, centers=args.centers, seed=args.seed)
        return {"x": x, "y": y}

    def run_once(self, args, data, mesh):
        from spark_rapids_ml_tpu.ops.dbscan import dbscan_fit

        def run():
            labels, _ = dbscan_fit(
                data["x"].astype(np.float32), mesh=mesh, eps=args.eps,
                min_samples=args.min_samples,
                max_mbytes_per_batch=args.max_mbytes_per_batch,
                calc_core_sample_indices=False,
            )
            return np.asarray(labels)

        labels, sec = with_benchmark("dbscan fit_predict", run)
        self._labels = labels
        return {"fit": sec}

    def quality(self, args, data):
        from sklearn.metrics import adjusted_rand_score

        return {"ari_vs_generator": float(adjusted_rand_score(data["y"], self._labels))}


if __name__ == "__main__":
    BenchmarkDBSCAN().run()
