#
# RandomForest benchmark — the protocol's two configs (reference
# databricks/run_benchmark.sh:107-129): classifier 50 trees / depth 13 /
# 128 bins, regressor 30 trees / depth 6 / 128 bins, both on 1M x 3k.
# Quality = training accuracy (clf) / R² (reg) on a row subsample.
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase, fetch
from .gen_data import gen_classification_device, gen_regression_device
from .utils import with_benchmark


class BenchmarkRandomForest(BenchmarkBase):
    name = "random_forest"
    extra_args = {
        "task": (str, "classification", "classification (50xd13) | regression (30xd6)"),
        "numTrees": (int, 0, "override protocol tree count"),
        "maxDepth": (int, 0, "override protocol depth"),
        "maxBins": (int, 128, "histogram bins (protocol: 128)"),
        "node_chunk": (int, 256, "nodes processed per histogram pass (HBM knob)"),
    }

    def gen_dataset(self, args, mesh):
        if args.cpu_comparison:
            from .gen_data import gen_classification_host, gen_regression_host

            if args.task == "classification":
                Xh, yh = gen_classification_host(
                    args.num_rows, args.num_cols, 2, args.seed
                )
            else:
                Xh, yh, _ = gen_regression_host(
                    args.num_rows, args.num_cols, seed=args.seed
                )
            return self.dataset_from_arrays(Xh, yh, args, mesh)
        if args.task == "classification":
            X, y, w = gen_classification_device(
                args.num_rows, args.num_cols, n_classes=2, seed=args.seed, mesh=mesh
            )
            data = {"X": X, "y": y, "w": w}
        else:
            X, y, w, _ = gen_regression_device(
                args.num_rows, args.num_cols, seed=args.seed, mesh=mesh
            )
            data = {"X": X, "y": y, "w": w}
        fetch(w[:1])
        return data

    def dataset_from_arrays(self, X, y, args, mesh):
        from spark_rapids_ml_tpu.parallel import make_global_rows

        if y is None:
            raise ValueError("random_forest dataset needs a label column")
        Xh = np.asarray(X, dtype=np.float32)
        yh = np.asarray(y, dtype=np.float32)
        Xd, w, _ = make_global_rows(mesh, Xh)  # pad + row-shard like the gens
        yd, _, _ = make_global_rows(mesh, yh)
        return {
            "X": Xd,
            "y": yd,
            "w": w,
            "X_host": Xh,
            "y_host": yh,
        }

    def run_cpu(self, args, data):
        import time

        from sklearn.ensemble import RandomForestClassifier as SkRFC
        from sklearn.ensemble import RandomForestRegressor as SkRFR

        clf = args.task == "classification"
        n_trees = args.numTrees or (50 if clf else 30)
        depth = args.maxDepth or (13 if clf else 6)
        est = (SkRFC if clf else SkRFR)(
            n_estimators=n_trees, max_depth=depth, n_jobs=-1, random_state=0
        )
        t0 = time.perf_counter()
        est.fit(data["X_host"], data["y_host"])
        return {"cpu_fit": time.perf_counter() - t0}

    def run_once(self, args, data, mesh):
        import jax

        from spark_rapids_ml_tpu.ops.trees import bin_features, forest_fit, quantile_bins

        clf = args.task == "classification"
        n_trees = args.numTrees or (50 if clf else 30)
        depth = args.maxDepth or (13 if clf else 6)

        if data.get("X") is None:
            # a previous run released the raw matrix (see below); the device
            # generators are deterministic in the seed, so regenerate
            # identically (datagen, not fit — outside the timer)
            if data.get("X_host") is not None:
                data["X"] = jax.device_put(data["X_host"])
            elif clf:
                data["X"], _, _ = gen_classification_device(
                    args.num_rows, args.num_cols, n_classes=2, seed=args.seed, mesh=mesh
                )
            else:
                data["X"], _, _, _ = gen_regression_device(
                    args.num_rows, args.num_cols, seed=args.seed, mesh=mesh
                )
        # raw row sample fetched ONCE: quantile edges (fit) + quality eval
        n_sample = min(args.num_rows, 65536)
        if "X_sample" not in data:
            data["X_sample"] = np.asarray(data["X"][:n_sample], dtype=np.float32)
        xs = data["X_sample"]
        release_raw = args.num_rows * args.num_cols >= 500_000_000

        def run():
            # quantile sketch from the row subsample (binning is part of the
            # fit, like cuRF's quantile computation)
            edges = quantile_bins(xs, args.maxBins, seed=args.seed).astype(np.float32)
            Xb = bin_features(data["X"], edges)
            if release_raw:
                # the forest consumes ONLY the binned matrix; at protocol
                # scale the idle raw X (11.2 GB) plus Xb plus histogram
                # buffers exceed one chip's HBM — release X for the growth
                # phase (regenerated above if another run follows). The tiny
                # fetch is the reliable completion fence on this platform.
                np.asarray(Xb[:1, :1])
                data["X"].delete()
                data["X"] = None
            y_host = np.asarray(data["y"])
            if clf:
                stats = np.zeros((len(y_host), 2), np.float32)
                stats[np.arange(len(y_host)), y_host.astype(int)] = 1.0
            else:
                stats = np.stack(
                    [np.ones_like(y_host), y_host, y_host * y_host], axis=1
                ).astype(np.float32)
            from spark_rapids_ml_tpu.parallel.mesh import row_sharding

            stats_dev = jax.device_put(stats, row_sharding(mesh, 2))
            w = data["w"]
            return forest_fit(
                Xb, stats_dev * w[:, None], w, args.seed, mesh=mesh,
                n_trees=n_trees, max_depth=depth, max_bins=args.maxBins,
                max_features=max(1, int(np.sqrt(args.num_cols))) if clf else max(1, args.num_cols // 3),
                impurity="gini" if clf else "variance",
                node_chunk=args.node_chunk, bootstrap=True, subsample_rate=1.0,
                min_instances=1.0, min_info_gain=0.0, n_stats=2 if clf else 3,
            )

        state = {}

        def timed():
            s = run()
            fetch(s["feature"])
            state.update(s)
            return s

        _, sec = with_benchmark(f"random_forest[{args.task}] fit", timed)
        self._state = {k: np.asarray(v) for k, v in state.items()}
        self._clf = clf
        self._depth = depth
        return {"fit": sec}

    def quality(self, args, data):
        from spark_rapids_ml_tpu.ops.trees import forest_raw_predict, split_bins_to_thresholds
        from spark_rapids_ml_tpu.models.tree import _fill_empty_nodes

        n_eval = min(args.num_rows, 32768)
        # the raw matrix may have been released during the fit (HBM budget);
        # the stashed host sample covers both eval rows and the edge sketch
        X = data["X_sample"][:n_eval]
        y = np.asarray(data["y"][:n_eval])
        feature = self._state["feature"]
        node_stats = _fill_empty_nodes(feature, self._state["node_stats"].astype(np.float64))
        from spark_rapids_ml_tpu.ops.trees import quantile_bins

        edges = quantile_bins(data["X_sample"], args.maxBins, seed=args.seed)
        threshold = split_bins_to_thresholds(feature, self._state["split_bin"], edges)
        if self._clf:
            leaves = node_stats / np.maximum(node_stats.sum(axis=2, keepdims=True), 1e-30)
            dist = np.asarray(
                forest_raw_predict(
                    X, feature, threshold.astype(np.float32), leaves.astype(np.float32),
                    max_depth=self._depth,
                )
            )
            pred = np.argmax(dist, axis=1)
            return {"accuracy": float((pred == y).mean())}
        w = node_stats[..., 0]
        leaves = (node_stats[..., 1] / np.maximum(w, 1e-30))[..., None]
        pred = np.asarray(
            forest_raw_predict(
                X, feature, threshold.astype(np.float32), leaves.astype(np.float32),
                max_depth=self._depth,
            )
        )[:, 0]
        ss_res = float(((pred - y) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        return {"r2": 1.0 - ss_res / max(ss_tot, 1e-30)}


if __name__ == "__main__":
    BenchmarkRandomForest().run()
