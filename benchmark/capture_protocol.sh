#!/usr/bin/env bash
#
# One-command protocol capture on the real chip (PROTOCOL_r{N} artifacts).
# Usage: benchmark/capture_protocol.sh [round_tag]   (e.g. r05)
#
# Runs the full 10-config protocol with per-config process isolation and a
# time limit (benchmark_runner --isolate), then walks the RandomForest
# fallback ladder: the protocol config (50 trees / depth 13 / 128 bins at
# 1M x 3k) first, then decreasing depths until one completes — recording the
# deepest completing config (VERDICT r04 task 2; the axon TPU worker has
# historically kernel-faulted on deep RF fits).
#
set -uo pipefail
cd "$(dirname "$0")/.."

TAG="${1:-r05}"
CSV="PROTOCOL_${TAG}.csv"
export BENCH_TIME_LIMIT="${BENCH_TIME_LIMIT:-2400}"

probe_chip() {
  # a dead tunnel HANGS at backend init: bound the probe so a mid-capture
  # outage aborts the run in minutes, not BENCH_TIME_LIMIT per config
  timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

probe_chip || { echo "== chip unreachable before sweep; aborting"; exit 1; }

echo "== protocol sweep -> ${CSV}"
python -m benchmark.benchmark_runner protocol --isolate --report "${CSV}"

probe_chip || { echo "== chip lost after sweep; skipping RF ladder"; exit 1; }

echo "== RF protocol ladder (classification 50 trees, 128 bins, 1M x 3k)"
for depth in 13 12 11 10; do
  echo "== RF depth ${depth}"
  if timeout "${BENCH_TIME_LIMIT}" python -m benchmark.benchmark_runner \
      random_forest --task classification --num_rows 1000000 --num_cols 3000 \
      --numTrees 50 --maxDepth "${depth}" --maxBins 128 --report "${CSV}"; then
    echo "== RF depth ${depth} COMPLETED"
    break
  fi
  echo "== RF depth ${depth} failed/faulted; stepping down"
  probe_chip || { echo "== chip lost during RF ladder; stopping"; break; }
done

echo "== done; rows:"
cat "${CSV}"
