#
# LinearRegression benchmark — the protocol's THREE configs (reference
# databricks/run_benchmark.sh:71-105): {reg=0} OLS, {reg=1e-5, EN=0.5,
# tol=1e-30, maxIter=10} elastic net, {reg=1e-5} ridge. Quality = training
# RMSE. `--config all` runs the three in sequence (one dataset).
#
from __future__ import annotations

import numpy as np

from .base import BenchmarkBase, fetch
from .gen_data import gen_regression_device
from .utils import log, with_benchmark

CONFIGS = {
    "ols": dict(alpha=0.0, l1_ratio=0.0, max_iter=100, use_cd=False),
    "elasticnet": dict(alpha=1e-5, l1_ratio=0.5, max_iter=10, use_cd=True),
    "ridge": dict(alpha=1e-5, l1_ratio=0.0, max_iter=100, use_cd=False),
}


class BenchmarkLinearRegression(BenchmarkBase):
    name = "linear_regression"
    extra_args = {
        "config": (str, "all", "ols | elasticnet | ridge | all (protocol: all three)"),
    }

    def gen_dataset(self, args, mesh):
        if args.cpu_comparison:
            from .gen_data import gen_regression_host

            Xh, yh, coef = gen_regression_host(args.num_rows, args.num_cols, seed=args.seed)
            data = self.dataset_from_arrays(Xh, yh, args, mesh)
            data["coef_true"] = coef
            return data
        X, y, w, coef = gen_regression_device(
            args.num_rows, args.num_cols, seed=args.seed, mesh=mesh
        )
        fetch(w[:1])
        return {"X": X, "y": y, "w": w, "coef_true": coef}

    def dataset_from_arrays(self, X, y, args, mesh):
        from spark_rapids_ml_tpu.parallel import make_global_rows

        if y is None:
            raise ValueError("linear_regression dataset needs a label column")
        Xh = np.asarray(X, dtype=np.float32)
        yh = np.asarray(y, dtype=np.float32)
        Xd, w, _ = make_global_rows(mesh, Xh)  # pad + row-shard like the gens
        yd, _, _ = make_global_rows(mesh, yh)
        return {
            "X": Xd,
            "y": yd,
            "w": w,
            "coef_true": None,
            "X_host": Xh,
            "y_host": yh,
        }

    def run_cpu(self, args, data):
        import time

        from sklearn.linear_model import ElasticNet, LinearRegression, Ridge

        names = list(CONFIGS) if args.config == "all" else [args.config]
        out = {}
        total = 0.0
        for cname in names:
            cfg = CONFIGS[cname]
            if cfg["alpha"] == 0.0:
                est = LinearRegression()
            elif cfg["l1_ratio"] > 0.0:
                est = ElasticNet(
                    alpha=cfg["alpha"], l1_ratio=cfg["l1_ratio"],
                    max_iter=cfg["max_iter"],
                )
            else:
                est = Ridge(alpha=cfg["alpha"] * len(data["X_host"]))
            t0 = time.perf_counter()
            est.fit(data["X_host"], data["y_host"])
            dt = time.perf_counter() - t0
            out[f"cpu_fit_{cname}"] = dt
            total += dt
        out["cpu_fit"] = total
        return out

    def run_once(self, args, data, mesh):
        from spark_rapids_ml_tpu.ops.linear import linear_fit

        names = list(CONFIGS) if args.config == "all" else [args.config]
        timings = {}
        self._states = {}
        for cname in names:
            cfg = CONFIGS[cname]

            def run():
                return linear_fit(
                    data["X"], data["y"], data["w"],
                    alpha=cfg["alpha"], l1_ratio=cfg["l1_ratio"],
                    fit_intercept=True, standardize=True, use_cd=cfg["use_cd"],
                    max_iter=cfg["max_iter"], tol=1e-30,
                )

            fetch(run()["coef_"])  # compile outside timing
            state = {}

            def timed():
                s = run()
                fetch(s["coef_"])
                state.update(s)
                return s

            _, sec = with_benchmark(f"linear_regression[{cname}] fit", timed)
            timings[f"fit_{cname}"] = sec
            self._states[cname] = {k: np.asarray(v) for k, v in state.items()}
        timings["fit"] = sum(timings.values())
        return timings

    def quality(self, args, data):
        import jax
        import jax.numpy as jnp

        out = {}
        for cname, st in self._states.items():
            coef, b = st["coef_"], st["intercept_"]

            @jax.jit
            def rmse(X, y):
                r = X @ coef + b - y
                return jnp.sqrt(jnp.mean(r * r))

            out[f"rmse_{cname}"] = float(np.asarray(rmse(data["X"], data["y"])))
        log(f"[linear_regression] quality {out}")
        return out


if __name__ == "__main__":
    BenchmarkLinearRegression().run()
