#
# Shared parquet dataset layout (the reference's benchmark datasets are
# multi-file parquet directories written by gen_data.py:248-453 /
# gen_data_distributed.py and read by every benchmark through
# spark.read.parquet; databricks/README.md documents the shared-bucket
# layout). TPU analog: a directory of part-*.parquet files with a "features"
# list<float> column (+ optional "label"), written/read with pyarrow — no
# Spark needed, but the layout matches what a Spark reader/writer produces so
# datasets can be exchanged with the reference pipeline.
#
from __future__ import annotations

import glob
import os
from typing import Optional, Tuple

import numpy as np


def write_parquet_part(
    file_path: str,
    X: np.ndarray,
    y: Optional[np.ndarray] = None,
    *,
    features_col: str = "features",
    label_col: str = "label",
) -> None:
    """Write one part-*.parquet file (list<float> features + optional label) —
    the per-partition unit shared by `write_parquet_dataset` and the
    partition-parallel generators (gen_data_distributed)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    cols = {features_col: pa.array(list(np.asarray(X).astype(np.float32)))}
    if y is not None:
        cols[label_col] = pa.array(np.asarray(y).astype(np.float64))
    pq.write_table(pa.table(cols), file_path)


def write_parquet_dataset(
    path: str,
    X: np.ndarray,
    y: Optional[np.ndarray] = None,
    *,
    n_files: int = 50,
    features_col: str = "features",
    label_col: str = "label",
) -> int:
    """Write [n, d] features (+ labels) as `n_files` part-*.parquet files under
    `path` (the reference protocol's 50-file layout). Returns files written."""
    os.makedirs(path, exist_ok=True)
    n = len(X)
    n_files = max(1, min(n_files, n))
    bounds = np.linspace(0, n, n_files + 1).astype(np.int64)
    for f in range(n_files):
        lo, hi = int(bounds[f]), int(bounds[f + 1])
        write_parquet_part(
            os.path.join(path, f"part-{f:05d}.parquet"),
            X[lo:hi],
            None if y is None else y[lo:hi],
            features_col=features_col,
            label_col=label_col,
        )
    return n_files


def read_parquet_dataset(
    path: str,
    *,
    features_col: str = "features",
    label_col: str = "label",
) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Read a parquet dataset directory (or single file) into (X [n, d] f32,
    y or None). Accepts both the list<float> "features" column this module
    writes and a multi-column numeric layout (feature_0..feature_k, the
    reference's alternative schema)."""
    import pyarrow.parquet as pq

    files = (
        sorted(glob.glob(os.path.join(path, "*.parquet")))
        if os.path.isdir(path)
        else [path]
    )
    if not files:
        raise FileNotFoundError(f"no parquet files under {path}")
    xs, ys = [], []
    for fp in files:
        t = pq.read_table(fp)
        names = t.column_names
        if features_col in names:
            feats = t.column(features_col).to_pylist()
            xs.append(np.asarray(feats, dtype=np.float32))
        else:
            fcols = [c for c in names if c != label_col]
            xs.append(
                np.column_stack(
                    [np.asarray(t.column(c), dtype=np.float32) for c in fcols]
                )
            )
        if label_col in names:
            ys.append(np.asarray(t.column(label_col), dtype=np.float64))
    X = np.concatenate(xs, axis=0)
    y = np.concatenate(ys, axis=0) if ys else None
    return X, y
