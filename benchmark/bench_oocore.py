#
# Out-of-core streaming benchmark — the memory-safety plane's perf lane
# (docs/robustness.md "Memory safety", docs/performance.md "Out-of-core
# streaming"). Fits the SAME dataset twice with the same estimator: once
# resident (the baseline every other lane measures) and once demoted to the
# streaming path via a `hbm_budget_bytes` override, reporting rows/sec for
# both, the streaming/resident throughput ratio, and the measured
# `ingest.overlap_fraction` — the double-buffer pipeline's acceptance gauge
# ((n-1)/n when every chunk's transfer overlapped its predecessor's compute).
#
# Excluded from the gated geomean until the lane history stabilizes
# (bench.py BASELINES carries no entry for it; regression.py only gates
# lanes present in BASELINES).
#
from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from .base import BenchmarkBase


def run_oocore_fit(
    n_rows: int,
    n_cols: int,
    *,
    algo: str = "linear",
    chunk_rows: int = 65536,
    max_iter: int = 20,
    seed: int = 0,
) -> Dict[str, float]:
    """One resident + one streaming fit over the same host dataset; returns
    wall times, throughputs, the overlap gauge, and the max relative
    coefficient/center difference — the lane doubles as a live parity canary
    at the lane's working dtype (~1e-5 in the default float32; the pinned
    1e-9 contract is asserted in float64 by tests/test_oocore.py). Shared by
    the BenchmarkBase lane below and bench.py's BENCH_OOCORE lane."""
    from spark_rapids_ml_tpu import core, telemetry

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n_rows, n_cols), dtype=np.float32)
    coef = rng.standard_normal(n_cols).astype(np.float32)
    if algo == "kmeans":
        from spark_rapids_ml_tpu.models.clustering import KMeans

        est = lambda: KMeans(  # noqa: E731
            k=8, seed=seed, maxIter=max_iter, tol=1e-12
        ).setFeaturesCol("features")
        data = {"features": x}
        result = lambda m: np.asarray(m.cluster_centers_)  # noqa: E731
    elif algo == "logistic":
        from spark_rapids_ml_tpu.models.classification import LogisticRegression

        est = lambda: LogisticRegression(  # noqa: E731
            regParam=1e-4, maxIter=max_iter, tol=1e-12
        ).setFeaturesCol("features")
        data = {"features": x, "label": (x @ coef > 0).astype(np.float64)}
        result = lambda m: np.asarray(m.coef_)  # noqa: E731
    else:
        from spark_rapids_ml_tpu.models.regression import LinearRegression

        est = lambda: LinearRegression(regParam=1e-4).setFeaturesCol("features")  # noqa: E731
        data = {
            "features": x,
            "label": (x @ coef + 0.1 * rng.standard_normal(n_rows)).astype(np.float64),
        }
        result = lambda m: np.asarray(m.coef_)  # noqa: E731

    telemetry.enable()
    saved = {
        k: core.config[k] for k in ("hbm_budget_bytes", "stream_chunk_rows")
    }
    try:
        core.config["hbm_budget_bytes"] = None
        core.config["stream_chunk_rows"] = 0
        t0 = time.perf_counter()
        m_res = est().fit(data)
        resident_s = time.perf_counter() - t0

        # demote by budget: half the estimated resident need refuses the
        # resident verdict while still admitting the streaming working set
        # (two chunk buffers + workspace), with the chunk size pinned
        import jax

        from spark_rapids_ml_tpu import memory

        extracted_like = type(
            "E", (), {
                "n_rows": n_rows, "n_cols": n_cols, "is_sparse": False,
                "label": data.get("label"), "features": x,
            },
        )()
        n_dev = max(1, jax.local_device_count())
        need = memory.resident_estimate(est(), extracted_like, n_dev).total()
        core.config["hbm_budget_bytes"] = max(1024, int(need * 0.5))
        core.config["stream_chunk_rows"] = int(chunk_rows)
        mark = telemetry.registry().mark()
        t0 = time.perf_counter()
        m_str = est().fit(data)
        stream_s = time.perf_counter() - t0
        delta = telemetry.registry().delta(mark)
        gauges = delta.get("gauges", {})
        counters = delta.get("counters", {})
    finally:
        core.config.update(saved)

    a, b = result(m_res), result(m_str)
    denom = np.maximum(np.abs(a), 1e-30)
    return {
        "fit": stream_s,
        "resident_s": resident_s,
        "stream_s": stream_s,
        "resident_rows_per_sec": n_rows / resident_s,
        "stream_rows_per_sec": n_rows / stream_s,
        "stream_vs_resident": resident_s / stream_s,
        "overlap_fraction": float(gauges.get("ingest.overlap_fraction", 0.0)),
        "stream_chunks": float(counters.get("ingest.stream_chunks", 0.0)),
        "demotions": float(counters.get("fit.demotions", 0.0)),
        "max_rel_diff": float(np.max(np.abs(a - b) / denom)),
    }


class BenchmarkOOCore(BenchmarkBase):
    name = "oocore"
    extra_args = {
        "algo": (str, "linear", "linear | logistic | kmeans"),
        "chunk_rows": (int, 65536, "streaming chunk rows"),
        "maxIter": (int, 20, "solver iterations (logistic/kmeans)"),
    }

    def gen_dataset(self, args, mesh) -> Dict[str, Any]:
        # data is generated inside run_oocore_fit: the resident-vs-streaming
        # comparison must ingest from the host both times (ingest cost is
        # part of what the lane measures)
        return {}

    def run_once(self, args, data, mesh) -> Dict[str, float]:
        out = run_oocore_fit(
            args.num_rows, args.num_cols,
            algo=args.algo, chunk_rows=args.chunk_rows,
            max_iter=args.maxIter, seed=args.seed,
        )
        data["counters"] = {k: v for k, v in out.items() if k != "fit"}
        return {"fit": out["fit"]}

    def quality(self, args, data) -> Dict[str, float]:
        # throughput ratio + overlap fraction + live parity: the lane's
        # acceptance numbers (overlap > 0 on any multi-chunk fit;
        # max_rel_diff at working-dtype rounding)
        return data.get("counters", {})


if __name__ == "__main__":
    BenchmarkOOCore().run()
