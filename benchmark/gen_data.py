#
# Dataset generation for the benchmark suite — the TPU-native rebuild of the
# reference's `gen_data.py` (sklearn make_blobs/low_rank_matrix/regression/
# classification -> parquet, reference gen_data.py:248-453) and the sparse
# generator from `gen_data_distributed.py` (SparseRegressionDataGen :581).
#
# Two modes:
#  * DEVICE mode (the default inside benches): the matrix is generated directly
#    into HBM, row-sharded over the mesh, in row TILES via a fori_loop of
#    dynamic_update_slice — peak memory = X + one tile, so the true 1M x 3k
#    protocol shape fits one v5e chip (11.2 GiB of f32 + tile workspace).
#    No host->device transfer happens at all.
#  * HOST mode (gen_*_host / the CLI): numpy arrays (optionally saved .npz) for
#    tests, small runs, and CPU-side consumers.
#
from __future__ import annotations

import argparse
from functools import partial
from typing import Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# device-side generators
# ---------------------------------------------------------------------------


def _tiled_fill(n_rows: int, n_cols: int, tile: int, make_tile, key):
    """Generate [n_rows, n_cols] on device in `tile`-row blocks.

    The buffer is allocated at EXACTLY [n_rows, n_cols] (peak memory = X + one
    tile — a padded buffer plus final slice would double the footprint at the
    1M x 3k protocol shape). The last partial tile relies on
    `dynamic_update_slice` start-index clipping: its start shifts back so the
    block fits, overwriting some already-written rows with fresh random values
    — distributionally identical for iid generators."""
    import jax
    import jax.numpy as jnp

    tile = min(tile, n_rows)
    n_tiles = -(-n_rows // tile)

    def body(i, carry):
        X, key = carry
        key, sub = jax.random.split(key)
        block = make_tile(sub, i * tile)
        X = jax.lax.dynamic_update_slice(X, block, (i * tile, 0))
        return X, key

    X0 = jnp.zeros((n_rows, n_cols), jnp.float32)
    X, _ = jax.lax.fori_loop(0, n_tiles, body, (X0, key))
    return X


def gen_low_rank_device(
    n_rows: int, n_cols: int, *, rank: int = 16, noise: float = 0.1,
    seed: int = 0, tile: int = 65536, mesh=None,
):
    """Low-rank + noise matrix (the reference's PCA/linear dataset shape,
    gen_data.py low_rank_matrix analog), generated tile-wise into a row-sharded
    buffer. Returns (X [n,d] f32, w ones [n])."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.parallel.mesh import row_sharding

    tile = min(tile, n_rows)  # make_tile blocks must fit the buffer
    key = jax.random.PRNGKey(seed)
    kV, key = jax.random.split(key)
    V = jax.random.normal(kV, (rank, n_cols), jnp.float32)

    def make_tile(k, row0):
        k1, k2 = jax.random.split(k)
        U = jax.random.normal(k1, (tile, rank), jnp.float32)
        return U @ V + noise * jax.random.normal(k2, (tile, n_cols), jnp.float32)

    fn = lambda key: _tiled_fill(n_rows, n_cols, tile, make_tile, key)  # noqa: E731
    if mesh is not None:
        fn = jax.jit(fn, out_shardings=row_sharding(mesh, 2))
    else:
        fn = jax.jit(fn)
    X = fn(key)
    w = jnp.ones((n_rows,), jnp.float32)
    if mesh is not None:
        w = jax.device_put(w, row_sharding(mesh, 1))
    return X, w


def gen_classification_device(
    n_rows: int, n_cols: int, *, n_classes: int = 2, seed: int = 0,
    rank: int = 16, tile: int = 65536, mesh=None,
):
    """Low-rank features + linear-margin labels (the reference's
    make_classification analog at protocol scale). Returns (X, y int32, w)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.parallel.mesh import row_sharding

    X, w = gen_low_rank_device(
        n_rows, n_cols, rank=rank, seed=seed, tile=tile, mesh=mesh
    )
    key = jax.random.PRNGKey(seed + 1)
    k1, k2 = jax.random.split(key)
    coef = jax.random.normal(k1, (n_cols, max(1, n_classes - 1)), jnp.float32) / np.float32(np.sqrt(n_cols))

    def label_fn(X, key):
        margins = X @ coef  # [n, n_classes-1]
        noise = 0.5 * jax.random.normal(key, margins.shape, jnp.float32)
        z = jnp.concatenate([jnp.zeros((X.shape[0], 1), jnp.float32), margins + noise], axis=1)
        return jnp.argmax(z, axis=1).astype(jnp.int32)

    out_sh = row_sharding(mesh, 1) if mesh is not None else None
    y = jax.jit(label_fn, out_shardings=out_sh)(X, k2)
    return X, y, w


def gen_regression_device(
    n_rows: int, n_cols: int, *, seed: int = 0, rank: int = 16,
    noise: float = 0.1, tile: int = 65536, mesh=None,
):
    """Features + linear target (reference make_regression analog).
    Returns (X, y f32, w, coef)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.parallel.mesh import row_sharding

    X, w = gen_low_rank_device(
        n_rows, n_cols, rank=rank, seed=seed, tile=tile, mesh=mesh
    )
    key = jax.random.PRNGKey(seed + 2)
    k1, k2 = jax.random.split(key)
    coef = jax.random.normal(k1, (n_cols,), jnp.float32) / np.float32(np.sqrt(n_cols))

    def target_fn(X, key):
        return X @ coef + noise * jax.random.normal(key, (X.shape[0],), jnp.float32)

    out_sh = row_sharding(mesh, 1) if mesh is not None else None
    y = jax.jit(target_fn, out_shardings=out_sh)(X, k2)
    return X, y, w, coef


def gen_blobs_device(
    n_rows: int, n_cols: int, *, centers: int = 10, cluster_std: float = 1.0,
    seed: int = 0, tile: int = 65536, mesh=None,
):
    """Gaussian blobs (reference make_blobs analog) generated tile-wise.
    Returns (X, y int32 true labels, w)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.parallel.mesh import row_sharding

    tile = min(tile, n_rows)  # make_tile blocks must fit the buffer
    key = jax.random.PRNGKey(seed)
    kc, key = jax.random.split(key)
    C = 10.0 * jax.random.normal(kc, (centers, n_cols), jnp.float32)

    def make_tile(k, row0):
        k1, k2 = jax.random.split(k)
        assign = jax.random.randint(k1, (tile,), 0, centers)
        return C[assign] + cluster_std * jax.random.normal(k2, (tile, n_cols), jnp.float32)

    fn = lambda key: _tiled_fill(n_rows, n_cols, tile, make_tile, key)  # noqa: E731
    fn = jax.jit(fn, out_shardings=row_sharding(mesh, 2) if mesh is not None else None)
    X = fn(key)
    w = jnp.ones((n_rows,), jnp.float32)
    if mesh is not None:
        w = jax.device_put(w, row_sharding(mesh, 1))

    def label_fn(X):
        d2 = jnp.sum(C * C, 1)[None, :] - 2.0 * X @ C.T
        return jnp.argmin(d2, axis=1).astype(jnp.int32)

    y = jax.jit(label_fn)(X)
    return X, y, w


# ---------------------------------------------------------------------------
# host-side generators (tests / CLI / sparse)
# ---------------------------------------------------------------------------


def gen_blobs_host(n_rows: int, n_cols: int, centers: int = 10, seed: int = 0):
    from sklearn.datasets import make_blobs

    x, y = make_blobs(
        n_samples=n_rows, n_features=n_cols, centers=centers, random_state=seed
    )
    return x.astype(np.float32), y.astype(np.int64)


def gen_low_rank_host(n_rows: int, n_cols: int, rank: int = 16, seed: int = 0):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_rows, rank)).astype(np.float32)
    V = rng.normal(size=(rank, n_cols)).astype(np.float32)
    return U @ V + 0.1 * rng.normal(size=(n_rows, n_cols)).astype(np.float32)


def gen_regression_host(n_rows: int, n_cols: int, seed: int = 0, noise: float = 0.1):
    rng = np.random.default_rng(seed)
    x = gen_low_rank_host(n_rows, n_cols, seed=seed)
    coef = (rng.normal(size=n_cols) / np.sqrt(n_cols)).astype(np.float32)
    y = x @ coef + noise * rng.normal(size=n_rows).astype(np.float32)
    return x, y.astype(np.float32), coef


def gen_classification_host(n_rows: int, n_cols: int, n_classes: int = 2, seed: int = 0):
    rng = np.random.default_rng(seed)
    x = gen_low_rank_host(n_rows, n_cols, seed=seed)
    coef = rng.normal(size=(n_cols, max(1, n_classes - 1))) / np.sqrt(n_cols)
    z = np.concatenate(
        [np.zeros((n_rows, 1)), x @ coef + 0.5 * rng.normal(size=(n_rows, n_classes - 1))],
        axis=1,
    )
    return x, np.argmax(z, axis=1).astype(np.int64)


def random_csr(rng, n_rows: int, n_cols: int, density: float, dtype=np.float32,
               values: str = "uniform"):
    """O(nnz)-memory CSR generator. `scipy.sparse.random` is unusable at
    protocol scale: sampling its n*d cell space without replacement
    materializes index arrays orders of magnitude larger than the matrix
    (observed host MemoryError at 1e7 x 2200 on a 125 GB box). Per-row
    Binomial(d, density) nnz with with-replacement column draws matches the
    density; rare in-row duplicate columns sum — harmless for every consumer
    here. `values` = "uniform" [0,1) or "normal"."""
    import scipy.sparse as sp

    nnz_row = rng.binomial(n_cols, density, size=n_rows).astype(np.int64)
    indptr = np.zeros(n_rows + 1, np.int64)
    np.cumsum(nnz_row, out=indptr[1:])
    total = int(indptr[-1])
    indices = rng.integers(0, n_cols, size=total).astype(np.int32)
    if values == "normal":
        data = rng.normal(size=total).astype(dtype)
    else:
        data = rng.random(total, dtype=np.float32).astype(dtype)
    return sp.csr_matrix((data, indices, indptr), shape=(n_rows, n_cols))


def gen_sparse_regression_host(
    n_rows: int, n_cols: int, density: float = 0.001, seed: int = 0, noise: float = 0.01
):
    """Sparse CSR regression set (reference gen_data_distributed.py
    SparseRegressionDataGen:581 analog)."""
    rng = np.random.default_rng(seed)
    x = random_csr(rng, n_rows, n_cols, density)
    coef = np.zeros(n_cols, dtype=np.float32)
    k = max(1, n_cols // 40)
    coef[:k] = rng.normal(size=k)
    y = np.asarray(x @ coef) + noise * rng.normal(size=n_rows).astype(np.float32)
    return x, y.astype(np.float32), coef


def main(argv=None) -> None:
    """CLI: generate a dataset to .npz (dense) / .npz CSR triple (sparse), or
    to the reference protocol's multi-file parquet layout
    (`--fmt parquet --n_files 50`, ref gen_data.py:248-453 +
    databricks/README.md shared-bucket datasets)."""
    p = argparse.ArgumentParser(description="benchmark dataset generator")
    p.add_argument("kind", choices=["blobs", "low_rank", "regression", "classification", "sparse_regression"])
    p.add_argument("--num_rows", type=int, default=100_000)
    p.add_argument("--num_cols", type=int, default=300)
    p.add_argument("--n_classes", type=int, default=2)
    p.add_argument("--centers", type=int, default=10)
    p.add_argument("--density", type=float, default=0.001)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--output", required=True, help="output .npz path / parquet dir")
    p.add_argument("--fmt", choices=["npz", "parquet"], default="npz")
    p.add_argument("--n_files", type=int, default=50,
                   help="parquet part files (reference protocol: 50)")
    args = p.parse_args(argv)

    y = coef = None
    if args.kind == "blobs":
        x, y = gen_blobs_host(args.num_rows, args.num_cols, args.centers, args.seed)
    elif args.kind == "low_rank":
        x = gen_low_rank_host(args.num_rows, args.num_cols, seed=args.seed)
    elif args.kind == "regression":
        x, y, coef = gen_regression_host(args.num_rows, args.num_cols, seed=args.seed)
    elif args.kind == "classification":
        x, y = gen_classification_host(args.num_rows, args.num_cols, args.n_classes, args.seed)
    else:
        if args.fmt == "parquet":
            raise SystemExit(
                "sparse_regression writes an npz CSR triple; --fmt parquet is"
                " only for dense datasets"
            )
        x, y, coef = gen_sparse_regression_host(
            args.num_rows, args.num_cols, args.density, args.seed
        )
        # sparse stays npz (CSR triple); parquet layout is for dense protocol sets
        np.savez_compressed(
            args.output, data=x.data, indices=x.indices, indptr=x.indptr,
            shape=np.asarray(x.shape), y=y, coef=coef,
        )
        print(f"wrote {args.output}")
        return

    if args.fmt == "parquet":
        from .dataset_io import write_parquet_dataset

        n_files = write_parquet_dataset(args.output, x, y, n_files=args.n_files)
        print(f"wrote {n_files} parquet part files under {args.output}")
    else:
        arrays = {"X": x}
        if y is not None:
            arrays["y"] = y
        if coef is not None:
            arrays["coef"] = coef
        np.savez_compressed(args.output, **arrays)
        print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
