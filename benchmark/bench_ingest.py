#
# Ingest micro-benchmark: host->HBM placement, chunked per-shard vs the old
# monolithic pad+device_put path (tentpole acceptance for the streaming
# ingest rework). `fit` is the CHUNKED placement (so fit_rows_per_sec is the
# ingest throughput the framework actually ships); `monolithic_place`
# records the old path's wall time on the same block for comparison, and
# `extract` times the chunked column->block conversion of a per-row column.
#
from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from .base import BenchmarkBase


class BenchmarkIngest(BenchmarkBase):
    name = "ingest"
    extra_args = {
        "skip_extract": (int, 0, "1 = skip the column->block extraction timing"),
    }

    def gen_dataset(self, args, mesh) -> Dict[str, Any]:
        rng = np.random.default_rng(args.seed)
        # +1 row: force the tail-pad/monolithic-pad path both benches exercise
        n = args.num_rows + 1
        return {"X_host": rng.standard_normal((n, args.num_cols), dtype=np.float32)}

    def run_once(self, args, data, mesh):
        import jax

        from spark_rapids_ml_tpu.parallel import make_global_rows
        from spark_rapids_ml_tpu.parallel.mesh import pad_rows, row_sharding

        x = data["X_host"]

        t0 = time.perf_counter()
        X, w, _ = make_global_rows(mesh, x)
        jax.block_until_ready(X)
        chunked_s = time.perf_counter() - t0
        del X, w

        t0 = time.perf_counter()
        xp, _ = pad_rows(x, int(mesh.devices.size))
        Xm = jax.device_put(xp, row_sharding(mesh, 2))
        wm = jax.device_put(np.ones(xp.shape[0], np.float32), row_sharding(mesh, 1))
        jax.block_until_ready(Xm)
        mono_s = time.perf_counter() - t0
        del Xm, wm, xp

        out = {"fit": chunked_s, "monolithic_place": mono_s}
        if not args.skip_extract:
            from spark_rapids_ml_tpu.data import extract_dataset

            rows = list(x)  # per-row object column (the pandas-ingest shape)
            t0 = time.perf_counter()
            extracted = extract_dataset({"features": rows}, input_col="features")
            out["extract"] = time.perf_counter() - t0
            assert extracted.n_rows == x.shape[0]
        return out


if __name__ == "__main__":
    BenchmarkIngest().run()
