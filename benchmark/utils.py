#
# Timing + report helpers (reference benchmark/utils.py `with_benchmark` and
# base.py:241-270 csv report).
#
from __future__ import annotations

import csv
import os
import sys
import time
from typing import Any, Callable, Dict, Tuple


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def with_benchmark(name: str, fn: Callable[[], Any]) -> Tuple[Any, float]:
    """Run fn, log '<name> took N sec', return (result, seconds).

    The caller's fn must force device->host materialization of its outputs
    (np.asarray of a result leaf) — on the experimental axon PJRT platform
    `block_until_ready` is unreliable, so fetching is the honest fence.
    """
    t0 = time.perf_counter()
    out = fn()
    sec = time.perf_counter() - t0
    log(f"{name} took: {sec:.4g} sec")
    return out, sec


# Schema-stable shared columns; algorithm-specific keys (quality scores,
# per-config timings) go into one JSON `extra` column so rows from different
# algorithms never land under mismatched headers.
_REPORT_COLUMNS = [
    "algo", "num_rows", "num_cols", "num_devices",
    "gen_sec", "fit_sec", "fit_rows_per_sec", "extra",
]


def append_report(
    path: str,
    algo: str,
    rows: Dict[str, Any],
) -> None:
    """Append one result row to a CSV report (header written on first use) —
    the reference's report_row shape (base.py:269-270)."""
    import json

    if not path:
        return
    exists = os.path.exists(path)
    shared = {k: rows[k] for k in _REPORT_COLUMNS if k in rows}
    extra = {k: v for k, v in rows.items() if k not in _REPORT_COLUMNS}
    with open(path, "a", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=_REPORT_COLUMNS, restval="")
        if not exists:
            writer.writeheader()
        writer.writerow({"algo": algo, **shared, "extra": json.dumps(extra, sort_keys=True)})


def pretty_dict(d: Dict[str, Any]) -> str:
    return ", ".join(f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}" for k, v in d.items())
