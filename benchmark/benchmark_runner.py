#
# Benchmark runner — dispatch to the per-algorithm benchmarks (the reference's
# benchmark_runner.py:38-50 registry shape).
#
#   python -m benchmark.benchmark_runner <algo> [--num_rows N --num_cols D ...]
#   python -m benchmark.benchmark_runner protocol --report out.csv
#
# `protocol` runs every algorithm at its reference-protocol config (BASELINE.md)
# scaled by --num_rows/--num_cols (defaults to the full 1M x 3k for the dense
# solvers; DBSCAN/UMAP/kNN run their own protocol sizes).
#
from __future__ import annotations

import sys

from .bench_approximate_nearest_neighbors import BenchmarkApproximateNearestNeighbors
from .bench_cv import BenchmarkCV
from .bench_dbscan import BenchmarkDBSCAN
from .bench_ingest import BenchmarkIngest
from .bench_kmeans import BenchmarkKMeans
from .bench_linear_regression import BenchmarkLinearRegression
from .bench_logistic_regression import BenchmarkLogisticRegression
from .bench_nearest_neighbors import BenchmarkNearestNeighbors
from .bench_oocore import BenchmarkOOCore
from .bench_pca import BenchmarkPCA
from .bench_random_forest import BenchmarkRandomForest
from .bench_scheduler import BenchmarkScheduler
from .bench_serving import BenchmarkServing
from .bench_umap import BenchmarkUMAP
from .utils import log

ALGORITHMS = {
    "cv": BenchmarkCV,
    "ingest": BenchmarkIngest,
    "oocore": BenchmarkOOCore,
    "scheduler": BenchmarkScheduler,
    "serving": BenchmarkServing,
    "pca": BenchmarkPCA,
    "kmeans": BenchmarkKMeans,
    "linear_regression": BenchmarkLinearRegression,
    "logistic_regression": BenchmarkLogisticRegression,
    "random_forest": BenchmarkRandomForest,
    "random_forest_classifier": BenchmarkRandomForest,
    "random_forest_regressor": BenchmarkRandomForest,
    "knn": BenchmarkNearestNeighbors,
    "nearest_neighbors": BenchmarkNearestNeighbors,
    "approximate_nearest_neighbors": BenchmarkApproximateNearestNeighbors,
    "dbscan": BenchmarkDBSCAN,
    "umap": BenchmarkUMAP,
}

# The full reference protocol (BASELINE.md): (algo, extra argv). Sizes come
# from --num_rows/--num_cols so the same list runs scaled-down smoke tests.
PROTOCOL = [
    ("pca", ["--k", "3"]),
    ("kmeans", ["--k", "1000", "--maxIter", "30"]),
    ("linear_regression", ["--config", "all"]),
    ("logistic_regression", ["--maxIter", "200", "--reg", "1e-5"]),
    ("random_forest", ["--task", "classification"]),
    ("random_forest", ["--task", "regression"]),
    ("nearest_neighbors", []),
    ("approximate_nearest_neighbors", []),
    ("approximate_nearest_neighbors", ["--algorithm", "cagra"]),
    ("dbscan", ["--num_rows", "40000", "--num_cols", "64"]),
    ("umap", ["--num_rows", "20000", "--num_cols", "64"]),
    ("umap", ["--num_rows", "100000", "--num_cols", "64"]),
]


def _run_protocol(rest) -> None:
    """One process per config when --isolate is passed: a config that faults
    the accelerator worker (observed: deep RF fits kill the axon TPU worker,
    PROTOCOL_r03.md) or hangs cannot take the remaining configs down — the
    same resilience contract as the reference's time-limited per-algo loop
    (databricks/run_benchmark.sh:33-47) and the repo's bench.py."""
    import os
    import subprocess
    import time

    isolate = "--isolate" in rest
    rest = [a for a in rest if a != "--isolate"]
    time_limit = float(os.environ.get("BENCH_TIME_LIMIT", 3600))
    for name, extra in PROTOCOL:
        log(f"=== protocol: {name} {' '.join(extra)}")
        # later flags win in argparse, so per-algo sizes in `extra` override
        # the shared scale flags passed on the command line
        if not isolate:
            ALGORITHMS[name]().run(rest + extra)
            continue
        t0 = time.monotonic()
        try:
            rc = subprocess.run(
                [sys.executable, "-m", "benchmark.benchmark_runner", name, *rest, *extra],
                timeout=time_limit,
            ).returncode
        except subprocess.TimeoutExpired:
            log(f"=== protocol: {name} TIMED OUT after {time_limit:.0f}s")
            continue
        if rc != 0:
            log(f"=== protocol: {name} FAILED rc={rc} after {time.monotonic() - t0:.0f}s")


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(f"usage: benchmark_runner <{'|'.join(sorted(set(ALGORITHMS)))}|protocol> [args]")
        return
    algo, rest = argv[0], argv[1:]
    if algo == "protocol":
        _run_protocol(rest)
        return
    if algo not in ALGORITHMS:
        raise SystemExit(f"unknown algorithm {algo!r}; one of {sorted(set(ALGORITHMS))}")
    if algo == "random_forest_classifier":
        rest = ["--task", "classification"] + rest
    elif algo == "random_forest_regressor":
        rest = ["--task", "regression"] + rest
    ALGORITHMS[algo]().run(rest)


if __name__ == "__main__":
    main()
