#
# Benchmark base — the reference's `benchmark/base.py` (283 LoC: argparse from
# the estimator's supported params, fit/transform timing, csv report) rebuilt
# for the TPU framework. No Spark cluster: datasets are generated on device
# (gen_data) and the estimators run on the local chip/mesh.
#
from __future__ import annotations

import argparse
from abc import ABC, abstractmethod
from typing import Any, Dict, Optional

import numpy as np

from .utils import append_report, log, pretty_dict, with_benchmark


class BenchmarkBase(ABC):
    """One algorithm benchmark: parse args -> gen data -> time fit (+transform)
    -> quality score -> report row."""

    name: str = ""
    # argparse spec: {flag: (type, default, help)}
    extra_args: Dict[str, tuple] = {}

    def __init__(self) -> None:
        self.parser = argparse.ArgumentParser(prog=f"benchmark {self.name}")
        self.parser.add_argument("--num_rows", type=int, default=100_000)
        self.parser.add_argument("--num_cols", type=int, default=300)
        self.parser.add_argument("--num_runs", type=int, default=1,
                                 help="timed runs; the best is reported (3 in the reference protocol)")
        self.parser.add_argument("--report", type=str, default="",
                                 help="CSV file to append the result row to")
        self.parser.add_argument("--num_workers", type=int, default=0,
                                 help="devices in the mesh (0 = all visible)")
        self.parser.add_argument("--seed", type=int, default=0)
        for flag, (typ, default, help_) in self.extra_args.items():
            self.parser.add_argument(f"--{flag}", type=typ, default=default, help=help_)

    # -- subclass surface --------------------------------------------------
    @abstractmethod
    def gen_dataset(self, args, mesh) -> Dict[str, Any]:
        """Generate the dataset (device-resident where possible)."""

    @abstractmethod
    def run_once(self, args, data: Dict[str, Any], mesh) -> Dict[str, float]:
        """One timed fit(+transform); returns {'fit': sec, ...} timings."""

    def quality(self, args, data: Dict[str, Any]) -> Dict[str, float]:
        """Post-run quality scores (uses state stashed by run_once)."""
        return {}

    # -- driver ------------------------------------------------------------
    def run(self, argv=None) -> Dict[str, Any]:
        import jax

        from spark_rapids_ml_tpu.parallel import get_mesh

        args = self.parser.parse_args(argv)
        n_dev = args.num_workers or len(jax.devices())
        mesh = get_mesh(min(n_dev, len(jax.devices())))
        log(f"[{self.name}] {args.num_rows}x{args.num_cols} on {mesh.devices.size} device(s)")

        data, gen_s = with_benchmark(f"{self.name} gen_dataset", lambda: self.gen_dataset(args, mesh))

        timings: Dict[str, float] = {}
        for i in range(max(1, args.num_runs)):
            t = self.run_once(args, data, mesh)
            for k, v in t.items():
                timings[k] = min(timings.get(k, float("inf")), v)
            log(f"[{self.name}] run {i}: {pretty_dict(t)}")

        q = self.quality(args, data)
        row = {
            "num_rows": args.num_rows,
            "num_cols": args.num_cols,
            "num_devices": int(mesh.devices.size),
            "gen_sec": round(gen_s, 4),
            **{f"{k}_sec": round(v, 4) for k, v in timings.items()},
            **{k: round(float(v), 6) for k, v in q.items()},
        }
        if "fit" in timings:
            row["fit_rows_per_sec"] = round(args.num_rows / timings["fit"], 1)
        log(f"[{self.name}] RESULT {pretty_dict(row)}")
        append_report(args.report, self.name, row)
        return row


def fetch(x) -> np.ndarray:
    """Force device->host materialization (the honest timing fence on the
    experimental axon PJRT platform where block_until_ready is unreliable)."""
    return np.asarray(x)
