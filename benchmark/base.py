#
# Benchmark base — the reference's `benchmark/base.py` (283 LoC: argparse from
# the estimator's supported params, fit/transform timing, csv report) rebuilt
# for the TPU framework. No Spark cluster: datasets are generated on device
# (gen_data) and the estimators run on the local chip/mesh.
#
from __future__ import annotations

import argparse
from abc import ABC, abstractmethod
from typing import Any, Dict, Optional

import numpy as np

from .utils import append_report, log, pretty_dict, with_benchmark


class BenchmarkBase(ABC):
    """One algorithm benchmark: parse args -> gen data -> time fit (+transform)
    -> quality score -> report row."""

    name: str = ""
    # argparse spec: {flag: (type, default, help)}
    extra_args: Dict[str, tuple] = {}

    def __init__(self) -> None:
        self.parser = argparse.ArgumentParser(prog=f"benchmark {self.name}")
        self.parser.add_argument("--num_rows", type=int, default=100_000)
        self.parser.add_argument("--num_cols", type=int, default=300)
        self.parser.add_argument("--num_runs", type=int, default=1,
                                 help="timed runs; the best is reported (3 in the reference protocol)")
        self.parser.add_argument("--report", type=str, default="",
                                 help="CSV file to append the result row to")
        self.parser.add_argument("--num_workers", type=int, default=0,
                                 help="devices in the mesh (0 = all visible)")
        self.parser.add_argument("--seed", type=int, default=0)
        self.parser.add_argument("--dataset_path", type=str, default="",
                                 help="read the dataset from this parquet directory/file"
                                      " (the reference's shared multi-file parquet"
                                      " layout) instead of generating it")
        self.parser.add_argument("--cpu_comparison", action="store_true",
                                 help="also run the sklearn CPU equivalent and report"
                                      " cpu_fit_sec + speedup_vs_cpu (the reference"
                                      " protocol's accelerated-vs-CPU arm,"
                                      " ref base.py:50-61)")
        for flag, (typ, default, help_) in self.extra_args.items():
            self.parser.add_argument(f"--{flag}", type=typ, default=default, help=help_)

    # -- subclass surface --------------------------------------------------
    @abstractmethod
    def gen_dataset(self, args, mesh) -> Dict[str, Any]:
        """Generate the dataset (device-resident where possible)."""

    def dataset_from_arrays(self, X, y, args, mesh) -> Dict[str, Any]:
        """Build the run_once data dict from host arrays loaded off parquet
        (--dataset_path). Benches that support external datasets override."""
        raise NotImplementedError(
            f"{self.name} does not support --dataset_path yet"
        )

    def run_cpu(self, args, data: Dict[str, Any]) -> Dict[str, float]:
        """One CPU (sklearn) fit on the host copy of the dataset; returns
        {'cpu_fit': sec, ...}. Benches that support --cpu_comparison override.
        Host arrays are stashed by gen_dataset when args.cpu_comparison (or
        provided by dataset_from_arrays)."""
        raise NotImplementedError(
            f"{self.name} does not support --cpu_comparison yet"
        )

    @abstractmethod
    def run_once(self, args, data: Dict[str, Any], mesh) -> Dict[str, float]:
        """One timed fit(+transform); returns {'fit': sec, ...} timings."""

    def quality(self, args, data: Dict[str, Any]) -> Dict[str, float]:
        """Post-run quality scores (uses state stashed by run_once)."""
        return {}

    # -- driver ------------------------------------------------------------
    def run(self, argv=None) -> Dict[str, Any]:
        import jax

        from spark_rapids_ml_tpu.parallel import get_mesh

        args = self.parser.parse_args(argv)
        n_dev = args.num_workers or len(jax.devices())
        mesh = get_mesh(min(n_dev, len(jax.devices())))

        if args.cpu_comparison and type(self).run_cpu is BenchmarkBase.run_cpu:
            # fail BEFORE datagen/timed runs, not after minutes of work
            raise SystemExit(
                f"{self.name} does not support --cpu_comparison"
            )

        if args.dataset_path:
            from .dataset_io import read_parquet_dataset

            def load():
                X, y = read_parquet_dataset(args.dataset_path)
                args.num_rows, args.num_cols = X.shape
                return self.dataset_from_arrays(X, y, args, mesh)

            log(f"[{self.name}] dataset from {args.dataset_path}"
                f" on {mesh.devices.size} device(s)")
            data, gen_s = with_benchmark(f"{self.name} load_dataset", load)
        else:
            log(f"[{self.name}] {args.num_rows}x{args.num_cols}"
                f" on {mesh.devices.size} device(s)")
            data, gen_s = with_benchmark(
                f"{self.name} gen_dataset", lambda: self.gen_dataset(args, mesh)
            )

        timings: Dict[str, float] = {}
        for i in range(max(1, args.num_runs)):
            t = self.run_once(args, data, mesh)
            for k, v in t.items():
                timings[k] = min(timings.get(k, float("inf")), v)
            log(f"[{self.name}] run {i}: {pretty_dict(t)}")

        cpu_t: Dict[str, float] = {}
        if args.cpu_comparison:
            cpu_t, cpu_s = with_benchmark(
                f"{self.name} cpu arm", lambda: self.run_cpu(args, data)
            )
            log(f"[{self.name}] cpu arm: {pretty_dict(cpu_t)} ({cpu_s:.1f}s total)")

        q = self.quality(args, data)
        row = {
            "num_rows": args.num_rows,
            "num_cols": args.num_cols,
            "num_devices": int(mesh.devices.size),
            "gen_sec": round(gen_s, 4),
            **{f"{k}_sec": round(v, 4) for k, v in timings.items()},
            **{k: round(float(v), 6) for k, v in q.items()},
        }
        for k, v in cpu_t.items():
            row[f"{k}_sec"] = round(v, 4)
        if "fit" in timings:
            row["fit_rows_per_sec"] = round(args.num_rows / timings["fit"], 1)
            if cpu_t.get("cpu_fit"):
                row["speedup_vs_cpu"] = round(cpu_t["cpu_fit"] / timings["fit"], 2)
        log(f"[{self.name}] RESULT {pretty_dict(row)}")
        append_report(args.report, self.name, row)
        return row


def fetch(x) -> np.ndarray:
    """Force device->host materialization (the honest timing fence on the
    experimental axon PJRT platform where block_until_ready is unreliable)."""
    return np.asarray(x)
