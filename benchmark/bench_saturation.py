#
# Serving saturation lane: offered load past capacity, gated on graceful
# degradation (docs/serving.md "Overload & backpressure") — the acceptance
# harness for ROADMAP item 4's "graceful at overload" as a *gated* property.
#
# Three phases against one resident model, with a chaos `delay:stage=serve`
# fault pinning the per-dispatch service time so "capacity" is deterministic
# on CPU CI (the same trick the SLO burn-rate acceptance test uses):
#
#   1. PLATEAU — closed-loop clients measure sustainable goodput and p99;
#      these numbers calibrate the run (SLO threshold, deadline, queue bound).
#   2. BURST — a chaos `burst:stage=serve:rows=<rows/s>:seconds=<s>` fault
#      (parallel/chaos.py) declares the overload shape: an open-loop
#      generator ramps offered load to `overload_factor` x the measured
#      plateau. The closed loop must hold: bounded queue, deadline-aware
#      admission, and the per-tenant backpressure ladder
#      (throttle -> degrade -> shed), every verdict audited.
#   3. RECOVER — closed-loop clients again; the ladder must walk back to
#      healthy and goodput must return to the plateau.
#
# HARD GATES (the lane raises instead of reporting a slow number):
#   * zero over-deadline dispatches (`serve.overdeadline_dispatches` == 0);
#   * served-request e2e p99 bounded by the deadline contract — NOT by the
#     burst length (open loop without admission would queue ~overload_factor
#     x burst_s seconds of work);
#   * goodput under burst and after recovery stays within a factor of the
#     pre-burst plateau;
#   * the ladder engaged (>= 1 transition) and every transition appears in
#     the `ops_plane.audit` decision log (kind "backpressure").
#
from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List

import numpy as np

from .base import BenchmarkBase

_TENANT = "default"


def _closed_loop(
    engine: Any,
    name: str,
    make_request,
    duration_s: float,
    *,
    concurrency: int,
    deadline_ms: float,
) -> Dict[str, Any]:
    """Closed-loop clients: each thread submits, waits, repeats. Refusals
    (`ServeOverloadError`) back off briefly and retry — the well-behaved
    client the ladder is shaped for. Returns served rows, wall, latencies."""
    from spark_rapids_ml_tpu.errors import ServeOverloadError, SrmlError

    latencies: List[float] = []
    refused = [0]
    rows = [0]
    lock = threading.Lock()
    t_end = time.perf_counter() + duration_s

    def client() -> None:
        while time.perf_counter() < t_end:
            feats = make_request()
            t0 = time.perf_counter()
            try:
                engine.submit(
                    name, feats, deadline_ms=deadline_ms, tenant=_TENANT
                ).result(timeout=30)
            except ServeOverloadError:
                with lock:
                    refused[0] += 1
                time.sleep(0.01)
                continue
            except SrmlError:
                continue  # expiries under churn: counted by the engine
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)
                rows[0] += feats.shape[0]

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat = np.asarray(latencies) if latencies else np.zeros(1)
    return {
        "rows": rows[0],
        "wall_s": wall,
        "rows_per_sec": rows[0] / wall if wall > 0 else 0.0,
        "p50_s": float(np.percentile(lat, 50)),
        "p99_s": float(np.percentile(lat, 99)),
        "served": len(latencies),
        "refused": refused[0],
    }


def run_saturation_bench(
    n_cols: int = 64,
    k: int = 64,
    *,
    request_rows: int = 32,
    max_batch_rows: int = 128,
    plateau_s: float = 2.0,
    burst_s: float = 4.0,
    recover_s: float = 1.5,
    recover_timeout_s: float = 15.0,
    concurrency: int = 4,
    service_delay_s: float = 0.02,
    overload_factor: float = 2.5,
    burst_goodput_frac: float = 0.3,
    recover_goodput_frac: float = 0.6,
    seed: int = 0,
) -> Dict[str, Any]:
    """One saturation run; returns phase goodputs/latencies, refusal
    counters, ladder/audit evidence, and the `gates` verdict dict the lane
    turns into a hard failure."""
    from spark_rapids_ml_tpu import core, telemetry
    from spark_rapids_ml_tpu.errors import ServeOverloadError, SrmlError
    from spark_rapids_ml_tpu.models.clustering import KMeansModel
    from spark_rapids_ml_tpu.ops_plane import audit as ops_audit
    from spark_rapids_ml_tpu.ops_plane import slo as ops_slo
    from spark_rapids_ml_tpu.parallel import chaos
    from spark_rapids_ml_tpu.serving import ModelRegistry, ScoringEngine

    rng = np.random.default_rng(seed)
    centers = (rng.standard_normal((k, n_cols)) * 4.0).astype(np.float32)
    model = KMeansModel(cluster_centers_=centers, n_cols=n_cols, dtype="float32")

    # pre-generated request pool: client threads share it through an atomic
    # counter (the Generator itself is not thread-safe)
    pool = [
        rng.standard_normal((request_rows, n_cols)).astype(np.float32)
        for _ in range(32)
    ]
    counter = itertools.count()

    def make_request() -> np.ndarray:
        return pool[next(counter) % len(pool)]

    saved = {
        key: core.config[key]
        for key in (
            "metrics_bucket_seconds", "metrics_bucket_count", "slo",
            "serve_coalesce_window_ms", "serve_overload_hold_s",
            "serve_max_queue_rows", "serve_degraded_dtype",
            "serve_adaptive_batching",
        )
    }
    # fast windows so the closed loop reacts at bench timescale (window
    # params bind at first record after reset)
    core.config["metrics_bucket_seconds"] = 0.25
    core.config["metrics_bucket_count"] = 24  # 6s horizon
    core.config["serve_coalesce_window_ms"] = 2.0
    core.config["serve_adaptive_batching"] = True
    core.config["serve_overload_hold_s"] = 0.4
    core.config["serve_degraded_dtype"] = "bf16"
    core.config["slo"] = []
    telemetry.registry().reset()
    telemetry.enable()
    audited_before = len(ops_audit.decisions(kind="backpressure"))
    mark = telemetry.registry().mark()
    try:
        # the pinned service time: every dispatch sleeps `service_delay_s`,
        # so capacity = max_batch_rows / service_delay_s regardless of host
        delay_entry = f"delay:stage=serve:seconds={service_delay_s}:times=1000000"
        chaos.set_fault_plan(delay_entry)

        registry = ModelRegistry()
        registry.load("satbench", model)
        with ScoringEngine(registry, max_batch_rows=max_batch_rows) as engine:
            engine.score("satbench", make_request())  # warm the dispatch path

            # ---- phase 1: plateau (calibration) -------------------------
            plateau = _closed_loop(
                engine, "satbench", make_request, plateau_s,
                concurrency=concurrency, deadline_ms=10_000.0,
            )
            capacity = max(plateau["rows_per_sec"], 1.0)
            # the run's SLO: threshold comfortably above the plateau p99 (so
            # healthy traffic never burns), deadline a small multiple of it
            threshold_s = max(0.08, 4.0 * plateau["p99_s"])
            deadline_s = 2.5 * threshold_s
            core.config["serve_max_queue_rows"] = max(512, int(capacity))
            core.config["slo"] = [{
                "name": "saturation_p99", "kind": "latency",
                "histogram": "serve.e2e_s", "threshold_s": threshold_s,
                "objective": 0.5, "fast_window_s": 1.0, "fast_burn": 1.0,
            }]

            # ---- phase 2: burst (the chaos plan declares the load shape) --
            burst_rate = int(overload_factor * capacity)
            chaos.set_fault_plan(
                delay_entry
                + f";burst:stage=serve:rows={burst_rate}:seconds={burst_s}"
            )
            fault = chaos.maybe_burst_stage("serve")
            assert fault is not None and fault.rows == burst_rate
            futures: List[Any] = []
            refusals = {"shed": 0, "throttle": 0, "other": 0}
            t_burst0 = time.perf_counter()
            t_next = t_burst0
            while time.perf_counter() - t_burst0 < fault.seconds:
                try:
                    futures.append(engine.submit(
                        "satbench", make_request(),
                        deadline_ms=deadline_s * 1e3, tenant=_TENANT,
                    ))
                except ServeOverloadError as e:
                    level = getattr(e, "level", None)
                    refusals[level if level in refusals else "other"] += 1
                t_next += request_rows / fault.rows
                lag = t_next - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
            served_rows = 0
            burst_lat: List[float] = []
            expired = 0
            for fut in futures:  # drain: the tail still resolves typed
                try:
                    fut.result(timeout=60)
                except SrmlError:
                    expired += 1
                    continue
                served_rows += fut.rows
                burst_lat.append(fut.t_done - fut.t_submit)
            burst_wall = time.perf_counter() - t_burst0
            lat = np.asarray(burst_lat) if burst_lat else np.zeros(1)
            burst = {
                "offered_rows_per_sec": float(burst_rate),
                "rows_per_sec": served_rows / burst_wall,
                "p99_s": float(np.percentile(lat, 99)),
                "served": len(burst_lat),
                "expired": expired,
                "refused_shed": refusals["shed"],
                "refused_throttle": refusals["throttle"],
                "refused_other": refusals["other"],
            }

            # ---- phase 3: recover ---------------------------------------
            t0 = time.perf_counter()
            level = "unknown"
            while time.perf_counter() - t0 < recover_timeout_s:
                try:
                    engine.submit(
                        "satbench", make_request(), deadline_ms=10_000.0,
                        tenant=_TENANT,
                    ).result(timeout=30)
                except SrmlError:
                    time.sleep(0.02)
                tenants = engine.stats()["tenants"]
                level = tenants.get(_TENANT, {}).get("level", "unknown")
                if level == "healthy":
                    break
            recover_wait_s = time.perf_counter() - t0
            recover = _closed_loop(
                engine, "satbench", make_request, recover_s,
                concurrency=concurrency, deadline_ms=10_000.0,
            )
            stats = engine.stats()
        registry.evict("satbench")
        transitions = sum(
            t.get("transitions", 0) for t in stats["tenants"].values()
        )
        audited = [
            d for d in ops_audit.decisions(kind="backpressure")
        ][audited_before:]
        verdicts = sorted({d.get("verdict", "") for d in audited})
        # extract counters BEFORE the registry reset below wipes them
        counters = telemetry.registry().delta(mark).get("counters", {})
    finally:
        chaos.clear_fault_plan()
        core.config.update(saved)
        ops_slo.reset()
        telemetry.registry().reset()  # later lanes bind default windows

    gates = {
        "zero_overdeadline_dispatches": {
            "ok": counters.get("serve.overdeadline_dispatches", 0.0) == 0.0,
            "detail": f"{counters.get('serve.overdeadline_dispatches', 0.0):g} "
                      "request(s) dispatched past their deadline",
        },
        "bounded_p99": {
            # the deadline contract bounds every served wait; threshold_s of
            # slack covers the in-flight batch's service time
            "ok": burst["p99_s"] <= deadline_s + threshold_s,
            "detail": f"served p99 {burst['p99_s']*1e3:.0f}ms vs bound "
                      f"{(deadline_s + threshold_s)*1e3:.0f}ms "
                      f"(open loop would queue ~{burst_s:.0f}s)",
        },
        "burst_goodput": {
            # the hysteresis ladder is bang-bang: shed dwells drain the
            # queue to restore latency, so sustained-overload goodput runs
            # at a ~0.4-0.5 duty cycle of capacity BY DESIGN. The gate
            # guards against COLLAPSE — the expiry-cascade failure mode
            # (admit everything, dispatch nothing) measures < 0.1 here
            "ok": burst["rows_per_sec"] >= burst_goodput_frac * capacity,
            "detail": f"{burst['rows_per_sec']:,.0f} rows/s under burst vs "
                      f"{burst_goodput_frac:.2f} x plateau {capacity:,.0f}",
        },
        "recover_goodput": {
            "ok": recover["rows_per_sec"] >= recover_goodput_frac * capacity,
            "detail": f"{recover['rows_per_sec']:,.0f} rows/s after recovery "
                      f"(level {level!r} after {recover_wait_s:.1f}s) vs "
                      f"{recover_goodput_frac:g} x plateau {capacity:,.0f}",
        },
        "ladder_engaged_and_audited": {
            "ok": transitions > 0 and len(audited) == transitions,
            "detail": f"{transitions} transition(s), {len(audited)} audited "
                      f"(verdicts: {', '.join(verdicts) or 'none'})",
        },
    }
    return {
        "fit": burst_wall,  # BenchmarkBase's timing key: the burst phase
        "plateau_rows_per_sec": plateau["rows_per_sec"],
        "plateau_p99_ms": plateau["p99_s"] * 1e3,
        "burst_offered_rows_per_sec": burst["offered_rows_per_sec"],
        "burst_rows_per_sec": burst["rows_per_sec"],
        "burst_p99_ms": burst["p99_s"] * 1e3,
        "recover_rows_per_sec": recover["rows_per_sec"],
        "recover_p99_ms": recover["p99_s"] * 1e3,
        "recover_wait_s": recover_wait_s,
        "final_level": level,
        "threshold_ms": threshold_s * 1e3,
        "deadline_ms": deadline_s * 1e3,
        "served": float(burst["served"]),
        "expired_requests": float(counters.get("serve.expired_requests", 0.0)),
        "rejected_requests": float(counters.get("serve.rejected_requests", 0.0)),
        "shed_requests": float(counters.get("serve.shed_requests", 0.0)),
        "throttled_requests": float(counters.get("serve.throttled_requests", 0.0)),
        "degraded_requests": float(counters.get("serve.degraded_requests", 0.0)),
        "overdeadline_dispatches": float(
            counters.get("serve.overdeadline_dispatches", 0.0)
        ),
        "transitions": float(transitions),
        "audited_verdicts": verdicts,
        "gates": gates,
    }


class BenchmarkServingSaturation(BenchmarkBase):
    name = "serving_saturation"
    extra_args = {
        "k": (int, 64, "resident KMeans model's center count"),
        "request_rows": (int, 32, "rows per scoring request"),
        "plateau_s": (float, 2.0, "calibration phase length"),
        "burst_s": (float, 4.0, "overload phase length"),
        "overload_factor": (float, 2.5, "offered load vs measured plateau"),
    }

    def gen_dataset(self, args, mesh) -> Dict[str, Any]:
        return {}  # the model and requests are generated inside the runner

    def run_once(self, args, data, mesh) -> Dict[str, float]:
        out = run_saturation_bench(
            n_cols=min(args.num_cols, 256), k=args.k,
            request_rows=args.request_rows, plateau_s=args.plateau_s,
            burst_s=args.burst_s, overload_factor=args.overload_factor,
            seed=args.seed,
        )
        data["counters"] = {
            key: v for key, v in out.items()
            if isinstance(v, (int, float)) and key != "fit"
        }
        data["gates"] = out["gates"]
        failed = [n for n, g in out["gates"].items() if not g["ok"]]
        if failed:
            raise RuntimeError(
                "saturation gates failed: "
                + "; ".join(f"{n}: {out['gates'][n]['detail']}" for n in failed)
            )
        return {"fit": out["fit"]}

    def quality(self, args, data) -> Dict[str, float]:
        return data.get("counters", {})


if __name__ == "__main__":
    BenchmarkServingSaturation().run()
