//
// TpuPCA test (the reference's PCASuite analog, jvm/src/test/scala/.../
// PCASuite.scala pattern: fit on a small local dataset, check component
// orthonormality and variance ordering).
//
package com.srmltpu.feature

import org.apache.spark.sql.SparkSession
import org.scalatest.funsuite.AnyFunSuite

class TpuPCASuite extends AnyFunSuite {

  test("fit recovers an orthonormal top-k basis with descending variance") {
    val spark = SparkSession.builder().master("local[2]").appName("TpuPCASuite").getOrCreate()
    try {
      val rng = new scala.util.Random(7)
      val d = 6
      // anisotropic Gaussian: leading direction has much larger variance
      val rows = Seq.fill(500) {
        val base = Array.fill(d)(rng.nextGaussian())
        base(0) *= 10.0; base(1) *= 3.0
        base
      }
      val rdd = spark.sparkContext.parallelize(rows, 3)
      val model = new TpuPCA(3).fit(rdd)

      assert(model.pc.length == 3 && model.pc.head.length == d)
      // descending explained variance
      assert(model.explainedVariance.sliding(2).forall(p => p(0) >= p(1) - 1e-12))
      // orthonormal components
      for (a <- 0 until 3; b <- 0 until 3) {
        val dot = (0 until d).map(j => model.pc(a)(j) * model.pc(b)(j)).sum
        val expect = if (a == b) 1.0 else 0.0
        assert(math.abs(dot - expect) < 1e-8, s"pc($a) . pc($b) = $dot")
      }
      // the leading component aligns with axis 0 (variance 100 vs <= 9)
      assert(math.abs(model.pc(0)(0)) > 0.99)
      // sign canonicalization: max-|.| element of every component positive
      model.pc.foreach { row =>
        assert(row(row.map(math.abs).zipWithIndex.maxBy(_._1)._2) >= 0.0)
      }
    } finally {
      spark.stop()
    }
  }
}
