//
// Typed Scala facade over the srml native kernels — the counterpart of the
// reference's RAPIDSML.scala BLAS facade (reference jvm/src/main/scala/org/
// apache/spark/ml/linalg/RAPIDSML.scala:38-155: typed cov/gemm/calSVD
// wrappers over its JNI CUDA library). Callers (TpuRowMatrix, TpuPCA) use
// these instead of raw SrmlNative entry points, so the JNI surface has one
// owner and argument/layout contracts live in one place.
//
package com.srmltpu.linalg

object SrmlBlas {

  /** Eigendecomposition result: ascending eigenvalues, eigenvectors as
    * COLUMNS of the row-major `evecs` [d, d] matrix. */
  case class EighResult(evals: Array[Double], evecs: Array[Double], sweeps: Int)

  /** Accumulate X^T X of a row-major block `x` [n, d] into `c` [d, d]
    * (row-major, symmetric on completion of all blocks). One JNI call per
    * multi-row block — never call per row (72 MB accumulator copy per call
    * at d=3000). */
  def covAccumulate(x: Array[Double], n: Long, d: Long, c: Array[Double]): Unit = {
    SrmlNative.ensureLoaded()
    require(x.length >= n * d, s"block too short: ${x.length} < ${n * d}")
    require(c.length == d * d, s"accumulator must be d*d, got ${c.length}")
    SrmlNative.covAccumulate(x, n, d, c)
  }

  /** Weighted column means of row-major `x` [n, d]; `w` may be null for
    * unit weights. */
  def weightedMean(x: Array[Double], w: Array[Double], n: Long, d: Long): Array[Double] = {
    SrmlNative.ensureLoaded()
    val mean = new Array[Double](d.toInt)
    SrmlNative.weightedMean(x, w, n, d, mean)
    mean
  }

  /** Cyclic-Jacobi symmetric eigendecomposition of row-major `a` [d, d].
    * Throws if the sweep budget is exhausted before convergence. */
  def eigh(a: Array[Double], d: Long, maxSweeps: Int = 100, tol: Double = 1e-12): EighResult = {
    SrmlNative.ensureLoaded()
    require(a.length == d * d, s"matrix must be d*d, got ${a.length}")
    val evals = new Array[Double](d.toInt)
    val evecs = new Array[Double]((d * d).toInt)
    val sweeps = SrmlNative.eighJacobi(a, d, evals, evecs, maxSweeps, tol)
    require(sweeps >= 0, s"eigensolver did not converge in $maxSweeps sweeps")
    EighResult(evals, evecs, sweeps)
  }

  /** In-place sign canonicalization of `comps` [k, d] row-major component
    * rows: the max-|.| element of each row is made positive (the
    * deterministic-output convention shared with the Python layer and the
    * reference's signFlip kernel). */
  def signFlip(comps: Array[Double], k: Long, d: Long): Unit = {
    SrmlNative.ensureLoaded()
    require(comps.length == k * d, s"components must be k*d, got ${comps.length}")
    SrmlNative.signFlip(comps, k, d)
  }
}
