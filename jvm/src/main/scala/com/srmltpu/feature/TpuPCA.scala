//
// Scala PCA estimator over the srml native kernels — the JVM API analog of
// the reference's accelerated Spark-ML PCA (reference jvm/src/main/scala/org/
// apache/spark/ml/feature/RapidsPCA.scala:72-166, which replaces the
// covariance gemm + SVD with its JNI CUDA library). Design here: executors
// reduce the covariance sufficient statistics with `treeAggregate` (each
// partition accumulates X^T X and the weighted sum through SrmlNative), the
// driver runs the native Jacobi eigensolver + sign canonicalization, and the
// result is exposed with the same (pc, explainedVariance) model surface.
//
package com.srmltpu.feature

import com.srmltpu.linalg.SrmlNative

import org.apache.spark.rdd.RDD

/** Fitted PCA model: `pc` is row-major [k, d] (rows = components, descending
  * eigenvalue order, sign-canonicalized), `explainedVariance` the matching
  * variance ratios, `mean` the column means removed before projection. */
case class TpuPCAModel(
    k: Int,
    mean: Array[Double],
    pc: Array[Array[Double]],
    explainedVariance: Array[Double]
) {
  /** Project one row: (x - mean) dot pc_r for each component r. */
  def transform(x: Array[Double]): Array[Double] = {
    val out = new Array[Double](k)
    var r = 0
    while (r < k) {
      var acc = 0.0
      var j = 0
      val row = pc(r)
      while (j < row.length) { acc += (x(j) - mean(j)) * row(j); j += 1 }
      out(r) = acc
      r += 1
    }
    out
  }
}

class TpuPCA(val k: Int) extends Serializable {
  require(k > 0, s"k must be positive, got $k")

  /** Fit over an RDD of dense feature rows (all the same length d). */
  def fit(rows: RDD[Array[Double]]): TpuPCAModel = {
    val d = rows.first().length
    val n = rows.count()
    require(k <= d, s"k ($k) must be <= feature dimension ($d)")

    // sufficient statistics per partition: (sum x, X^T X flattened, count).
    // Rows are buffered into multi-row blocks and handed to the native gram
    // kernel ONE JNI call per block — a per-row seqOp would copy the full
    // d*d accumulator (72 MB at d=3000) across the JNI boundary for every
    // row, turning the fit into O(n*d^2) copy traffic.
    val chunkRows = math.max(1, math.min(4096, (4 << 20) / d)) // ~32 MB block
    val partStats = rows.mapPartitions { it =>
      SrmlNative.ensureLoaded()
      val s = new Array[Double](d)
      val c = new Array[Double](d * d)
      val buf = new Array[Double](chunkRows * d)
      var cnt = 0L
      var filled = 0
      while (it.hasNext) {
        val row = it.next()
        System.arraycopy(row, 0, buf, filled * d, d)
        var j = 0
        while (j < d) { s(j) += row(j); j += 1 }
        filled += 1
        cnt += 1
        if (filled == chunkRows) {
          SrmlNative.covAccumulate(buf, filled.toLong, d.toLong, c)
          filled = 0
        }
      }
      if (filled > 0) SrmlNative.covAccumulate(buf, filled.toLong, d.toLong, c)
      Iterator.single((s, c, cnt))
    }
    val (sumX, xtx, total) = partStats.treeReduce { case ((s1, c1, n1), (s2, c2, n2)) =>
      var j = 0
      while (j < d) { s1(j) += s2(j); j += 1 }
      j = 0
      while (j < d * d) { c1(j) += c2(j); j += 1 }
      (s1, c1, n1 + n2)
    }
    require(total == n && total > 1, s"degenerate dataset: $total rows")

    // covariance = (X^T X - n * mean mean^T) / (n - 1)
    val mean = sumX.map(_ / total)
    val cov = new Array[Double](d * d)
    var i = 0
    while (i < d) {
      var j = 0
      while (j < d) {
        cov(i * d + j) = (xtx(i * d + j) - total * mean(i) * mean(j)) / (total - 1.0)
        j += 1
      }
      i += 1
    }

    SrmlNative.ensureLoaded()
    val evals = new Array[Double](d)
    val evecs = new Array[Double](d * d)
    val sweeps = SrmlNative.eighJacobi(cov, d.toLong, evals, evecs, 100, 1e-12)
    require(sweeps >= 0, "eigensolver did not converge")

    // top-k columns, descending eigenvalue; rows of `pc` are components
    val pcFlat = new Array[Double](k * d)
    val ev = new Array[Double](k)
    var r = 0
    while (r < k) {
      val col = d - 1 - r // ascending -> take from the back
      ev(r) = math.max(evals(col), 0.0)
      var row = 0
      while (row < d) { pcFlat(r * d + row) = evecs(row * d + col); row += 1 }
      r += 1
    }
    SrmlNative.signFlip(pcFlat, k.toLong, d.toLong)

    val totVar = evals.map(math.max(_, 0.0)).sum
    val ratio = ev.map(v => if (totVar > 0) v / totVar else 0.0)
    val pc = Array.tabulate(k)(r => pcFlat.slice(r * d, (r + 1) * d))
    TpuPCAModel(k, mean, pc, ratio)
  }
}
