//
// Scala PCA estimator over the srml native kernels — the JVM API analog of
// the reference's accelerated Spark-ML PCA (reference jvm/src/main/scala/org/
// apache/spark/ml/feature/RapidsPCA.scala:72-166, which delegates the
// covariance + SVD to RapidsRowMatrix over its JNI CUDA library). The same
// structure here: TpuPCA.fit delegates to
// TpuRowMatrix.computePrincipalComponentsAndExplainedVariance (distributed
// sufficient stats through SrmlBlas, driver-side native eigensolve) and
// exposes the (pc, explainedVariance) model surface.
//
package com.srmltpu.feature

import com.srmltpu.distributed.TpuRowMatrix

import org.apache.spark.rdd.RDD

/** Fitted PCA model: `pc` is row-major [k, d] (rows = components, descending
  * eigenvalue order, sign-canonicalized), `explainedVariance` the matching
  * variance ratios, `mean` the column means removed before projection. */
case class TpuPCAModel(
    k: Int,
    mean: Array[Double],
    pc: Array[Array[Double]],
    explainedVariance: Array[Double]
) {
  /** Project one row: (x - mean) dot pc_r for each component r. */
  def transform(x: Array[Double]): Array[Double] = {
    val out = new Array[Double](k)
    var r = 0
    while (r < k) {
      var acc = 0.0
      var j = 0
      val row = pc(r)
      while (j < row.length) { acc += (x(j) - mean(j)) * row(j); j += 1 }
      out(r) = acc
      r += 1
    }
    out
  }
}

class TpuPCA(val k: Int) extends Serializable {
  require(k > 0, s"k must be positive, got $k")

  /** Fit over an RDD of dense feature rows (all the same length d). */
  def fit(rows: RDD[Array[Double]]): TpuPCAModel = {
    val d = rows.first().length
    require(k <= d, s"k ($k) must be <= feature dimension ($d)")
    val matrix = new TpuRowMatrix(rows, d)
    val (pc, ratio, mean) = matrix.computePrincipalComponentsAndExplainedVariance(k)
    TpuPCAModel(k, mean, pc, ratio)
  }
}
