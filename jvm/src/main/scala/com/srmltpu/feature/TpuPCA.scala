//
// Scala PCA estimator over the srml native kernels — the JVM API analog of
// the reference's accelerated Spark-ML PCA (reference jvm/src/main/scala/org/
// apache/spark/ml/feature/RapidsPCA.scala:72-166, which replaces the
// covariance gemm + SVD with its JNI CUDA library). Design here: executors
// reduce the covariance sufficient statistics with `treeAggregate` (each
// partition accumulates X^T X and the weighted sum through SrmlNative), the
// driver runs the native Jacobi eigensolver + sign canonicalization, and the
// result is exposed with the same (pc, explainedVariance) model surface.
//
package com.srmltpu.feature

import com.srmltpu.linalg.SrmlNative

import org.apache.spark.rdd.RDD

/** Fitted PCA model: `pc` is row-major [k, d] (rows = components, descending
  * eigenvalue order, sign-canonicalized), `explainedVariance` the matching
  * variance ratios, `mean` the column means removed before projection. */
case class TpuPCAModel(
    k: Int,
    mean: Array[Double],
    pc: Array[Array[Double]],
    explainedVariance: Array[Double]
) {
  /** Project one row: (x - mean) dot pc_r for each component r. */
  def transform(x: Array[Double]): Array[Double] = {
    val out = new Array[Double](k)
    var r = 0
    while (r < k) {
      var acc = 0.0
      var j = 0
      val row = pc(r)
      while (j < row.length) { acc += (x(j) - mean(j)) * row(j); j += 1 }
      out(r) = acc
      r += 1
    }
    out
  }
}

class TpuPCA(val k: Int) extends Serializable {
  require(k > 0, s"k must be positive, got $k")

  /** Fit over an RDD of dense feature rows (all the same length d). */
  def fit(rows: RDD[Array[Double]]): TpuPCAModel = {
    val d = rows.first().length
    val n = rows.count()
    require(k <= d, s"k ($k) must be <= feature dimension ($d)")

    // sufficient statistics per partition: (sum x, X^T X flattened, count)
    val zero = (new Array[Double](d), new Array[Double](d * d), 0L)
    val (sumX, xtx, total) = rows.treeAggregate(zero)(
      seqOp = { case ((s, c, cnt), row) =>
        SrmlNative.ensureLoaded()
        // accumulate one row into the gram through the blocked native kernel
        SrmlNative.covAccumulate(row, 1L, d.toLong, c)
        var j = 0
        while (j < d) { s(j) += row(j); j += 1 }
        (s, c, cnt + 1L)
      },
      combOp = { case ((s1, c1, n1), (s2, c2, n2)) =>
        var j = 0
        while (j < d) { s1(j) += s2(j); j += 1 }
        j = 0
        while (j < d * d) { c1(j) += c2(j); j += 1 }
        (s1, c1, n1 + n2)
      }
    )
    require(total == n && total > 1, s"degenerate dataset: $total rows")

    // covariance = (X^T X - n * mean mean^T) / (n - 1)
    val mean = sumX.map(_ / total)
    val cov = new Array[Double](d * d)
    var i = 0
    while (i < d) {
      var j = 0
      while (j < d) {
        cov(i * d + j) = (xtx(i * d + j) - total * mean(i) * mean(j)) / (total - 1.0)
        j += 1
      }
      i += 1
    }

    SrmlNative.ensureLoaded()
    val evals = new Array[Double](d)
    val evecs = new Array[Double](d * d)
    val sweeps = SrmlNative.eighJacobi(cov, d.toLong, evals, evecs, 100, 1e-12)
    require(sweeps >= 0, "eigensolver did not converge")

    // top-k columns, descending eigenvalue; rows of `pc` are components
    val pcFlat = new Array[Double](k * d)
    val ev = new Array[Double](k)
    var r = 0
    while (r < k) {
      val col = d - 1 - r // ascending -> take from the back
      ev(r) = math.max(evals(col), 0.0)
      var row = 0
      while (row < d) { pcFlat(r * d + row) = evecs(row * d + col); row += 1 }
      r += 1
    }
    SrmlNative.signFlip(pcFlat, k.toLong, d.toLong)

    val totVar = evals.map(math.max(_, 0.0)).sum
    val ratio = ev.map(v => if (totVar > 0) v / totVar else 0.0)
    val pc = Array.tabulate(k)(r => pcFlat.slice(r * d, (r + 1) * d))
    TpuPCAModel(k, mean, pc, ratio)
  }
}
