//
// Distributed row matrix over the srml native kernels — the counterpart of
// the reference's RapidsRowMatrix (reference jvm/src/main/scala/org/apache/
// spark/ml/linalg/distributed/RapidsRowMatrix.scala:59-141: per-partition
// GPU covariance gemm + driver-side eigendecomposition). Here each partition
// accumulates its sufficient statistics through the native blocked gram
// kernel (SrmlBlas.covAccumulate, one JNI call per row block), the
// (sumX, X^T X, count) triples treeReduce to the driver, and the driver runs
// the native Jacobi eigensolver.
//
package com.srmltpu.distributed

import com.srmltpu.linalg.SrmlBlas

import org.apache.spark.rdd.RDD

/** Sufficient statistics of a row matrix: column sums, gram (X^T X, row-major
  * [d, d]) and row count. */
case class RowMatrixStats(sumX: Array[Double], gram: Array[Double], count: Long)

class TpuRowMatrix(val rows: RDD[Array[Double]], val numCols: Int) extends Serializable {

  /** Rows buffered per partition into ~32 MB blocks: ONE native gram call per
    * block (a per-row call would copy the d*d accumulator across JNI per row
    * — O(n*d^2) copy traffic at the protocol d=3000). */
  private def chunkRows: Int = math.max(1, math.min(4096, (4 << 20) / numCols))

  /** Distributed (sum, gram, count) — the single data-touching pass every
    * spectral routine here builds on. */
  def computeStats(): RowMatrixStats = {
    val d = numCols
    val chunk = chunkRows
    val partStats = rows.mapPartitions { it =>
      val s = new Array[Double](d)
      val c = new Array[Double](d * d)
      val buf = new Array[Double](chunk * d)
      var cnt = 0L
      var filled = 0
      while (it.hasNext) {
        val row = it.next()
        System.arraycopy(row, 0, buf, filled * d, d)
        var j = 0
        while (j < d) { s(j) += row(j); j += 1 }
        filled += 1
        cnt += 1
        if (filled == chunk) {
          SrmlBlas.covAccumulate(buf, filled.toLong, d.toLong, c)
          filled = 0
        }
      }
      if (filled > 0) SrmlBlas.covAccumulate(buf, filled.toLong, d.toLong, c)
      Iterator.single(RowMatrixStats(s, c, cnt))
    }
    partStats.treeReduce { (a, b) =>
      var j = 0
      while (j < d) { a.sumX(j) += b.sumX(j); j += 1 }
      j = 0
      while (j < d * d) { a.gram(j) += b.gram(j); j += 1 }
      RowMatrixStats(a.sumX, a.gram, a.count + b.count)
    }
  }

  /** Sample covariance (row-major [d, d]) and the column means. */
  def computeCovariance(): (Array[Double], Array[Double], Long) = {
    val d = numCols
    val stats = computeStats()
    require(stats.count > 1, s"degenerate dataset: ${stats.count} rows")
    val n = stats.count
    val mean = stats.sumX.map(_ / n)
    val cov = new Array[Double](d * d)
    var i = 0
    while (i < d) {
      var j = 0
      while (j < d) {
        cov(i * d + j) = (stats.gram(i * d + j) - n * mean(i) * mean(j)) / (n - 1.0)
        j += 1
      }
      i += 1
    }
    (cov, mean, n)
  }

  /** Top-k principal components (rows of the returned [k, d] matrix,
    * descending eigenvalue, sign-canonicalized) with explained-variance
    * ratios and the column means — the reference's
    * computePrincipalComponentsAndExplainedVariance surface. */
  def computePrincipalComponentsAndExplainedVariance(
      k: Int
  ): (Array[Array[Double]], Array[Double], Array[Double]) = {
    val d = numCols
    require(k > 0 && k <= d, s"k ($k) must be in [1, $d]")
    val (cov, mean, _) = computeCovariance()
    val eig = SrmlBlas.eigh(cov, d.toLong)

    val pcFlat = new Array[Double](k * d)
    val ev = new Array[Double](k)
    var r = 0
    while (r < k) {
      val col = d - 1 - r // ascending eigenvalues -> take from the back
      ev(r) = math.max(eig.evals(col), 0.0)
      var row = 0
      while (row < d) { pcFlat(r * d + row) = eig.evecs(row * d + col); row += 1 }
      r += 1
    }
    SrmlBlas.signFlip(pcFlat, k.toLong, d.toLong)

    val totVar = eig.evals.map(math.max(_, 0.0)).sum
    val ratio = ev.map(v => if (totVar > 0) v / totVar else 0.0)
    val pc = Array.tabulate(k)(r => pcFlat.slice(r * d, (r + 1) * d))
    (pc, ratio, mean)
  }
}
