//
// JNI loader for the srml native kernels — the counterpart of the
// reference's JNI entry class (jvm/src/main/java/com/nvidia/spark/ml/linalg/
// JniRAPIDSML.java:64-77 declares dgemm/calSVD natives over rapidsml_jni.cu).
// Implementations live in native/src/srml_jni.cpp over the same C kernels
// the Python ctypes path uses (native/src/srml_native.cpp).
//
package com.srmltpu.linalg;

public final class SrmlNative {
  private static volatile boolean loaded = false;

  private SrmlNative() {}

  /**
   * Load libsrml_jni.so. Resolution order: the `srml.native.path` system
   * property, then java.library.path. Call once before any native method.
   */
  public static synchronized void ensureLoaded() {
    if (loaded) {
      return;
    }
    String explicit = System.getProperty("srml.native.path");
    if (explicit != null) {
      System.load(explicit);
    } else {
      System.loadLibrary("srml_jni");
    }
    loaded = true;
  }

  /** c += x^T x for row-major x [n, d]; c row-major [d, d], accumulated. */
  public static native void covAccumulate(double[] x, long n, long d, double[] c);

  /** mean = sum_i w_i x_i / sum_i w_i (w may be null for unit weights). */
  public static native void weightedMean(double[] x, double[] w, long n, long d, double[] mean);

  /**
   * Cyclic-Jacobi symmetric eigendecomposition of row-major a [d, d]:
   * eigenvalues ascending into evals [d], eigenvectors as columns of
   * row-major evecs [d, d]. Returns sweeps used, or -1 if not converged.
   */
  public static native int eighJacobi(
      double[] a, long d, double[] evals, double[] evecs, int maxSweeps, double tol);

  /** Per row of comps [k, d]: negate the row if its max-|.| element is negative. */
  public static native void signFlip(double[] comps, long k, long d);
}
