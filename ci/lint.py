#
# Thin shim over the AST analysis gate (ci/analysis/) so existing
# `python ci/lint.py` invocations keep working. The regex-era rules this
# file used to implement are now AST rules with exact call/attribute
# matching — `.wait()` in a comment or string no longer trips, and every
# waiver must carry a `: <reason>` suffix. Rule catalog, waiver policy, and
# the baseline ratchet: docs/development.md.
#
from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))  # the script lives in ci/, the package resolves from the repo root

from ci.analysis import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
