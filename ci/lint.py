#
# Minimal lint gate (the reference runs mypy+black+isort via ci/lint_python.py;
# none of those are baked into this image, so the gate checks what the
# toolchain supports everywhere: every source file compiles, has no tabs, no
# trailing whitespace, and the package + benchmark roots import cleanly).
#
from __future__ import annotations

import pathlib
import py_compile
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
TARGETS = ["spark_rapids_ml_tpu", "benchmark", "tests"]

# Stage timing inside the framework goes through telemetry spans
# (spark_rapids_ml_tpu/telemetry.py), not hand-rolled perf_counter deltas —
# ad-hoc timing is invisible to the registry/JSONL sinks and drifts from the
# span taxonomy. perf_counter is allowed in telemetry.py itself (the one
# clock owner) and on lines carrying an explicit `# telemetry-ok` waiver
# (none needed today; the allowlist mechanism exists for genuinely
# non-telemetry uses, e.g. a future jitter probe).
_PERF_COUNTER_TREE = "spark_rapids_ml_tpu"
_PERF_COUNTER_EXEMPT_FILES = {"telemetry.py"}

# Unbounded blocking waits (`while True` poll loops, bare `Barrier.wait()` /
# `Event.wait()` with no timeout) are how a dead peer becomes a HUNG process
# instead of a typed RankFailedError/RendezvousTimeoutError (docs/
# robustness.md). All bounded waiting lives in parallel/context.py — the one
# deadline owner; anywhere else in the framework a blocking wait must carry a
# `# blocking-ok` waiver explaining its bound.
_BLOCKING_TREE = "spark_rapids_ml_tpu"
_BLOCKING_EXEMPT_FILES = {"context.py"}
_BLOCKING_RE = re.compile(r"while\s+True\b|\.wait\(\s*\)")

# Framework JSONL emission goes through the telemetry/diagnostics sinks
# (telemetry._sink_write, diagnostics.FlightRecorder.dump) — the two owners
# that tag records with rank + trace ids and keep per-rank files from
# interleaving. A hand-rolled `f.write(json.dumps(...) + "\n")` elsewhere
# produces records the trace merge and post-mortem assemblers cannot
# correlate. Non-JSONL json uses (model save metadata via json.dump,
# control-plane payloads via bare json.dumps) don't match; a genuinely
# non-telemetry JSONL writer carries a `# sink-ok` waiver.
_JSONL_TREE = "spark_rapids_ml_tpu"
_JSONL_EXEMPT_FILES = {"telemetry.py", "diagnostics.py"}
_JSONL_RE = re.compile(
    r"""\.write\(\s*json\.dumps|json\.dumps\([^)]*\)\s*\+\s*(['"])\\n\1"""
)

# Bare `time.sleep` in the framework is either a poll loop that should be
# event/deadline-driven or an ad-hoc delay that stretches failure detection
# past its documented budget. Sleeping is legal only for the retry/backoff,
# heartbeat-pacing, and rendezvous-poll owners (core.retryable_stage's capped
# backoff, parallel/context.py's poll ticks + heartbeat Event.wait,
# parallel/chaos.py's injected delays) — every such line carries `# sleep-ok`
# naming its bound, as must any future waiver.
_SLEEP_TREE = "spark_rapids_ml_tpu"
_SLEEP_EXEMPT_FILES: set = set()
_SLEEP_RE = re.compile(r"\btime\.sleep\s*\(")

# HBM accounting goes through the admission budgeter (memory.py — capacity
# resolution, chaos-injected budgets, config override order) and the
# telemetry watermark sampler (telemetry.record_device_memory). A direct
# `Device.memory_stats()` call elsewhere bypasses the `hbm_budget_bytes`
# override and the chaos `oom:budget=` injection, so the code under test
# budgets against a DIFFERENT capacity than the admission controller —
# exactly the split-brain the memory-safety plane exists to prevent (docs/
# robustness.md "Memory safety"). A genuinely read-only probe carries a
# `# hbm-ok` waiver naming why it must not flow through memory.py.
_MEMSTATS_TREE = "spark_rapids_ml_tpu"
_MEMSTATS_EXEMPT_FILES = {"memory.py", "telemetry.py"}
_MEMSTATS_RE = re.compile(r"\.memory_stats\s*\(")

# Transform/serving code pads batches through the bucket ladder
# (parallel/mesh.py bucket_rows), never raw pad_rows: an exact-shape pad
# mints one compiled `predict` program per distinct tail shape — tens of
# seconds each on a TPU backend — where the ladder compiles once per bucket
# (docs/performance.md "Multi-fit engine"). pad_rows stays legal inside
# mesh.py itself (the ladder is built on it) and on lines carrying an
# explicit `# bucket-ok` waiver (fit-side layout code, where every fit pads
# to ONE shape anyway).
_PAD_ROWS_TREE = "spark_rapids_ml_tpu"
_PAD_ROWS_EXEMPT_FILES = {"mesh.py"}
_PAD_ROWS_RE = re.compile(r"\bpad_rows\s*\(")

failures: list[str] = []
for target in TARGETS:
    for path in sorted((ROOT / target).rglob("*.py")):
        try:
            py_compile.compile(str(path), doraise=True)
        except py_compile.PyCompileError as e:
            failures.append(f"{path}: {e.msg}")
            continue
        text = path.read_text()
        check_timing = target == _PERF_COUNTER_TREE and path.name not in _PERF_COUNTER_EXEMPT_FILES
        for lineno, line in enumerate(text.splitlines(), 1):
            if "\t" in line:
                failures.append(f"{path}:{lineno}: tab character")
            if line != line.rstrip():
                failures.append(f"{path}:{lineno}: trailing whitespace")
            if check_timing and "perf_counter" in line and "# telemetry-ok" not in line:
                failures.append(
                    f"{path}:{lineno}: bare perf_counter timing in the framework — "
                    "use telemetry.span()/registry (or mark `# telemetry-ok`)"
                )
            if (
                target == _BLOCKING_TREE
                and path.name not in _BLOCKING_EXEMPT_FILES
                and _BLOCKING_RE.search(line)
                and "# blocking-ok" not in line
            ):
                failures.append(
                    f"{path}:{lineno}: unbounded blocking wait in the framework — "
                    "a dead peer must raise a typed error, not hang; bound it with "
                    "a deadline (see parallel/context.py) or mark `# blocking-ok`"
                )
            if (
                target == _JSONL_TREE
                and path.name not in _JSONL_EXEMPT_FILES
                and _JSONL_RE.search(line)
                and "# sink-ok" not in line
            ):
                failures.append(
                    f"{path}:{lineno}: hand-rolled JSONL emission in the framework — "
                    "records must flow through the telemetry sink or flight recorder "
                    "(rank + trace-id tagging, per-rank files) or mark `# sink-ok`"
                )
            if (
                target == _SLEEP_TREE
                and path.name not in _SLEEP_EXEMPT_FILES
                and _SLEEP_RE.search(line)
                and "# sleep-ok" not in line
            ):
                failures.append(
                    f"{path}:{lineno}: bare time.sleep in the framework — "
                    "sleeping belongs to the retry-backoff/heartbeat/poll "
                    "owners; bound it and mark `# sleep-ok: <why>`"
                )
            if (
                target == _MEMSTATS_TREE
                and path.name not in _MEMSTATS_EXEMPT_FILES
                and _MEMSTATS_RE.search(line)
                and "# hbm-ok" not in line
            ):
                failures.append(
                    f"{path}:{lineno}: direct memory_stats() in the framework — "
                    "HBM capacity flows through the admission budgeter "
                    "(memory.device_capacity_bytes: honors hbm_budget_bytes + "
                    "chaos budgets) or the telemetry watermark sampler; use "
                    "them or mark `# hbm-ok: <why>`"
                )
            if (
                target == _PAD_ROWS_TREE
                and path.name not in _PAD_ROWS_EXEMPT_FILES
                and _PAD_ROWS_RE.search(line)
                and "# bucket-ok" not in line
            ):
                failures.append(
                    f"{path}:{lineno}: raw pad_rows in the framework — serving "
                    "batches pad through the bucket ladder (mesh.bucket_rows: one "
                    "compile per bucket, not per tail shape); use it or mark "
                    "`# bucket-ok`"
                )

import importlib

sys.path.insert(0, str(ROOT))  # the script lives in ci/, imports resolve from the repo root
for mod in ("spark_rapids_ml_tpu", "benchmark.benchmark_runner"):
    try:
        importlib.import_module(mod)
    except Exception as e:  # import-time breakage must fail the gate
        failures.append(f"import {mod}: {e!r}")

if failures:
    print("\n".join(failures))
    print(f"lint: {len(failures)} issue(s)")
    sys.exit(1)
print(f"lint: OK ({len(TARGETS)} trees + imports)")
